"""Epoch-fenced canary deployments for the sharded serving tier.

``--role serve-ctl`` is the serving tier's control plane: learner epochs
(with their param-version minor key — :mod:`apex_tpu.serving.fence`)
become model VERSIONS, and every new version walks a canary lifecycle
before the whole tier serves it::

    IDLE --new version--> CANARY --healthy soak_s--> PROMOTED
                             |
                             +--SLO/eval breach--> ROLLED_BACK

* **CANARY**: a configured fraction of infer shards (the lowest shard
  indices — stable, so the canary band is the same worker population
  every deployment) is told to track the live param stream; every other
  shard PINS the incumbent via the server's epoch-fenced param gate
  (:meth:`apex_tpu.infer_service.service.InferServer.apply_ctl`).
* **PROMOTED**: the canary band's eval-ladder score and the round-trip
  SLO held for ``soak_s`` — judged from the SAME
  :class:`~apex_tpu.obs.slo.SloEngine` objective states and heartbeat
  gauges PR 11 ships (one status round-trip to the learner per tick; no
  second judgment machine).  All shards unpin; the candidate becomes the
  incumbent.
* **ROLLED_BACK**: a gate objective BREACHED mid-canary.  Canary shards
  revert BY EPOCH/VERSION to the retained incumbent (bit-identical
  params — the server stashed them at canary start) and the whole tier
  pins the incumbent; the candidate is remembered as rejected and never
  re-canaried.

The controller RECONCILES rather than fire-and-forgets: every tick it
re-asserts each shard's desired gate state, so a supervised shard
respawn (which comes up unpinned, knowing nothing) is re-pinned within
one tick instead of silently serving the rejected candidate.

Decisions and evidence ride the existing planes: the controller
heartbeats like any role (registry membership, ``--role status`` row)
and ships its bounded deployment timeline to the learner as a
:class:`ServingStat` on the stat channel, so ``fleet_summary.json``, the
status table, and the ``apex_serving_*`` Prometheus rows all show the
same machine — and the timeline survives the controller's death the
same way the registry survives an actor's.

Pure stdlib at module level (zmq/transport import lazily inside the
socket wrapper), so the learner can import :class:`ServingStat` and the
exposition builders without touching the comms extra.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field

from apex_tpu.serving import fence

IDLE, CANARY, PROMOTED, ROLLED_BACK = ("IDLE", "CANARY", "PROMOTED",
                                       "ROLLED_BACK")

#: state -> numeric code for gauges/exposition (the slo_state pattern)
STATE_CODE = {IDLE: 0, CANARY: 1, PROMOTED: 2, ROLLED_BACK: 3}

#: SLO objectives whose BREACHED state fails a canary (an unknown state
#: holds — promoting on a half-clear signal is how canaries lie)
GATE_OBJECTIVES = ("eval_score", "infer_rt_p99_ms")


@dataclass
class ServingStat:
    """The controller's state shipped to the learner on the stat channel
    (wire-allowlisted): ``snapshot`` is :meth:`DeployController.snapshot`
    — plain builtins only, so the restricted unpickler carries it."""

    identity: str
    snapshot: dict = field(default_factory=dict)


class DeployController:
    """The canary state machine, socket-free and fake-clock testable.

    :meth:`tick` consumes one observation — the learner's newest
    published model fence plus the SLO objective states — and returns
    the ``(shard, ctl command)`` list to send this tick (the reconcile
    set, plus rollback edges).  Everything time-like runs off the
    injected clock, so tests/test_serving.py pins every transition
    deterministically.
    """

    def __init__(self, n_shards: int, canary_frac: float = 0.5,
                 soak_s: float = 60.0, version_every: int = 0,
                 gate: tuple = GATE_OBJECTIVES, gate_open_s: float = 10.0,
                 clock=time.monotonic, wall=time.time,
                 timeline_cap: int = 128):
        self.n_shards = max(1, int(n_shards))
        self.canary_frac = float(canary_frac)
        self.soak_s = float(soak_s)
        # minimum param-version spacing between deployments within one
        # learner epoch (0 = epoch changes only: a restarted learner's
        # params are always a new model, a long-lived learner's stream
        # is one); CI drills compress the cycle with small values
        self.version_every = int(version_every)
        self.gate = tuple(gate)
        # how long the param gate stays OPEN after a promotion before
        # the tier re-freezes — long enough for every shard to install
        # the newly judged version off the conflate stream (a couple of
        # publish periods), short enough that unjudged successors don't
        # ride in behind it
        self.gate_open_s = float(gate_open_s)
        # the canary band: the LOWEST shard indices, at least one, and
        # never the whole tier unless the tier is one shard (an
        # incumbent must keep serving somewhere for the rollback to
        # mean anything)
        k = max(1, int(math.ceil(self.canary_frac * self.n_shards)))
        if self.n_shards > 1:
            k = min(k, self.n_shards - 1)
        self.canary_shards = tuple(range(k))
        self._clock = clock
        self._wall = wall
        self.state = IDLE
        self.incumbent: tuple | None = None     # trusted model fence
        self.candidate: tuple | None = None     # fence under canary
        self.rejected: tuple | None = None      # newest rolled-back fence
        self.deployments = 0
        self.promotions = 0
        self.rollbacks = 0
        self.shard_view: dict[int, dict] = {}   # shard -> last ctl state
        self.timeline: deque = deque(maxlen=timeline_cap)
        self._t0: float | None = None
        self._healthy_since: float | None = None
        self._promoted_at: float | None = None  # gate-open window anchor

    # -- the machine -------------------------------------------------------

    def _event(self, now: float, frm: str, to: str, reason: str,
               version: tuple | None) -> dict:
        e = {"t_s": round(now - self._t0, 3),
             "wall": round(self._wall(), 3),
             "version": fence.fmt(version),
             "from": frm, "to": to, "reason": reason}
        self.timeline.append(e)
        return e

    def _deployable(self, f: tuple) -> bool:
        """Is ``f`` a NEW model version worth a deployment?  Anything at
        or behind the incumbent/rejected watermark is old news; a new
        learner epoch always deploys (restart = new model by
        definition); within an epoch, ``version_every`` spaces
        deployments (0 = never — epochs only)."""
        base = self.incumbent
        if self.rejected is not None and self.rejected > base:
            base = self.rejected        # a rejected fence is never re-run
        if not fence.beyond(f[0], f[1], base):
            return False
        if f[0] > base[0]:
            return True
        return self.version_every > 0 and f[1] >= base[1] + self.version_every

    def _health(self, slo_states: dict | None) -> bool | None:
        """True = every gate objective readable and un-breached, False =
        any BREACHED, None = unreadable (hold: neither soak credit nor
        rollback — the autoscaler's half-clear-signal discipline)."""
        if not slo_states:
            return None
        states = [slo_states.get(name) for name in self.gate]
        if any(s == "BREACHED" for s in states):
            return False
        if any(s is None for s in states):
            return None
        return True

    def _desired(self, now: float) -> dict[int, dict]:
        """Each shard's gate state for the CURRENT machine state — the
        per-tick reconcile (idempotent server-side), so a respawned
        shard re-converges within one tick.

        The tier serves FROZEN models: outside a deployment every shard
        is frozen at its own judged fence (``freeze`` — stash + pin at
        current), the gate opening only for ``gate_open_s`` after a
        promotion so shards take the newly judged version off the
        conflate stream, then re-freezing.  Without the freeze, the
        latest-wins stream would drift "incumbent" shards past the
        fence between deployments and a later rollback would have
        nothing judged to restore.
        """
        out: dict[int, dict] = {}
        inc = self.incumbent or (0, 0)
        for s in range(self.n_shards):
            if self.state == CANARY:
                out[s] = ({"cmd": "canary"} if s in self.canary_shards
                          else {"cmd": "freeze"})
            elif self.state == ROLLED_BACK:
                # rollback is the reconcile verb here: each shard
                # restores ITS OWN stashed incumbent (idempotent — a
                # restored/frozen shard no-ops), and a respawn that
                # picked up the candidate with no stash drops to dry
                out[s] = {"cmd": "rollback", "epoch": inc[0],
                          "version": inc[1]}
            elif self.state == PROMOTED and self._promoted_at is not None \
                    and now - self._promoted_at >= self.gate_open_s:
                out[s] = {"cmd": "freeze"}      # gate closed: re-freeze
            else:                       # IDLE bootstrap / open gate
                out[s] = {"cmd": "promote"}
        return out

    def tick(self, learner: dict | None,
             slo_states: dict | None) -> list[tuple[int, dict]]:
        """One control round.  ``learner`` is the newest published model
        (``{"epoch": E, "version": V}``) or None while the learner is
        unreachable; ``slo_states`` maps objective name -> alert state.
        Returns the ``(shard, command)`` sends for this tick."""
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        if learner is not None:
            f = fence.fence_key(learner.get("epoch"),
                                learner.get("version"))
            if self.incumbent is None:
                # bootstrap: the first model observed IS the incumbent —
                # there is nothing older to fall back to, so canarying
                # it would be theater
                self.incumbent = f
                self._event(now, IDLE, IDLE, "incumbent adopted", f)
            elif self.state != CANARY and self._deployable(f):
                self.candidate = f
                self.deployments += 1
                self._event(now, self.state, CANARY,
                            "new model version", f)
                self.state = CANARY
                self._healthy_since = None
            elif self.state == CANARY and f > self.candidate:
                # the canary band tracks the LIVE stream: the fence under
                # judgment advances with it (one deployment covers the
                # stream until verdict, not one frozen publish)
                self.candidate = f
        if self.state == CANARY:
            health = self._health(slo_states)
            if health is False:
                self.rollbacks += 1
                self.rejected = self.candidate
                bad = [n for n in self.gate
                       if (slo_states or {}).get(n) == "BREACHED"]
                self._event(now, CANARY, ROLLED_BACK,
                            f"breached: {','.join(bad)}", self.candidate)
                self.state = ROLLED_BACK
                self.candidate = None
                self._healthy_since = None
            elif health is True:
                if self._healthy_since is None:
                    self._healthy_since = now
                elif now - self._healthy_since >= self.soak_s:
                    self.promotions += 1
                    self.incumbent = self.candidate
                    self._event(now, CANARY, PROMOTED,
                                f"healthy for {self.soak_s:g}s",
                                self.candidate)
                    self.state = PROMOTED
                    self.candidate = None
                    self._promoted_at = now
            else:
                self._healthy_since = None      # unreadable: no credit
        return sorted(self._desired(now).items())

    # -- read surface ------------------------------------------------------

    def snapshot(self) -> dict:
        """Serializable controller view (ServingStat payload, the
        ``serving`` section of fleet_summary.json): plain builtins only.
        tests/test_serving.py pins this schema."""
        def _fence_dict(f):
            if f is None:
                return None
            return {"epoch": f[0], "version": f[1], "id": fence.fmt(f)}

        return {
            "kind": "apex_serving",
            "version": 1,
            "state": self.state,
            "n_shards": self.n_shards,
            "canary_frac": self.canary_frac,
            "canary_shards": list(self.canary_shards),
            "soak_s": self.soak_s,
            "version_every": self.version_every,
            "incumbent": _fence_dict(self.incumbent),
            "candidate": _fence_dict(self.candidate),
            "rejected": _fence_dict(self.rejected),
            "deployments": self.deployments,
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "shards": {str(s): dict(v)
                       for s, v in sorted(self.shard_view.items())},
            "timeline": list(self.timeline),
        }


# -- operator/exposition surfaces --------------------------------------------


def prometheus_sections(serving: dict) -> tuple[dict, dict]:
    """(gauges, labeled) — the ``apex_serving_*`` row family the
    learner's scrape surface serves next to the slo/fleet rows."""
    inc = serving.get("incumbent") or {}
    gauges = {
        "serving_deployments": serving.get("deployments", 0),
        "serving_promotions": serving.get("promotions", 0),
        "serving_rollbacks": serving.get("rollbacks", 0),
        "serving_canary_shards": len(serving.get("canary_shards", ())),
        "serving_incumbent_epoch": inc.get("epoch"),
        "serving_incumbent_version": inc.get("version"),
    }
    labeled = {
        "serving_state": [({"state": serving.get("state", IDLE)},
                           STATE_CODE.get(serving.get("state"), 0))],
        "serving_shard_pinned": [({"shard": s},
                                  1.0 if v.get("pinned") else 0.0)
                                 for s, v in sorted(
                                     (serving.get("shards") or {}).items())],
        "serving_shard_version": [({"shard": s}, v.get("version"))
                                  for s, v in sorted(
                                      (serving.get("shards") or {}).items())
                                  if v.get("version") is not None],
    }
    return gauges, labeled


def format_serving_lines(serving: dict) -> list[str]:
    """Human serving-tier lines for the ``--role status`` table."""
    inc = serving.get("incumbent") or {}
    cand = serving.get("candidate") or {}
    lines = [
        f"serving: {serving.get('state')} "
        f"incumbent={inc.get('id', '-')} "
        f"candidate={cand.get('id') or '-'} "
        f"canary_shards={serving.get('canary_shards')} "
        f"deployments={serving.get('deployments', 0)} "
        f"promotions={serving.get('promotions', 0)} "
        f"rollbacks={serving.get('rollbacks', 0)}"]
    for s, v in sorted((serving.get("shards") or {}).items()):
        lines.append(
            f"serving shard {s}: "
            f"{'PINNED' if v.get('pinned') else 'tracking'} "
            f"model={v.get('epoch')}:{v.get('version')} "
            f"held={v.get('held', 0)} rollbacks={v.get('rollbacks', 0)}")
    for e in (serving.get("timeline") or [])[-4:]:
        lines.append(f"serving t={e['t_s']}s {e['from']} -> {e['to']} "
                     f"({e['version']}; {e['reason']})")
    return lines


# -- the socket role ---------------------------------------------------------


class ServeCtl:
    """Socket wrapper around :class:`DeployController` — the
    ``--role serve-ctl`` process body.

    One thread owns everything (the J013 affinity contract): the status
    REQ round-trip to the learner, one ctl DEALER per shard (commands
    out, ``("ctl_ok", state)`` replies drained non-blocking into the
    controller's shard view), and the learner-channel ChunkSender
    carrying heartbeats + :class:`ServingStat` snapshots.
    """

    def __init__(self, cfg, learner_ip: str | None = None,
                 canary_frac: float = 0.5, soak_s: float = 60.0,
                 version_every: int = 0, interval_s: float = 5.0):
        import zmq

        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        from apex_tpu.runtime import transport
        from apex_tpu.serving.shard import shard_port

        self._zmq = zmq
        self.comms = cfg.comms
        self.learner_ip = learner_ip or cfg.comms.learner_ip
        self.interval_s = float(interval_s)
        n = max(1, getattr(cfg.comms, "infer_shards", 1))
        # gate stays open two reconcile rounds after promotion: enough
        # for every shard to see a publish, bounded drift behind it
        self.ctrl = DeployController(n, canary_frac=canary_frac,
                                     soak_s=soak_s,
                                     version_every=version_every,
                                     gate_open_s=max(2.0 * interval_s,
                                                     5.0))
        ip = cfg.comms.infer_ip
        self.ctl_socks = []
        for s in range(n):
            sock = zmq.Context.instance().socket(zmq.DEALER)
            sock.setsockopt(zmq.IDENTITY, f"serve-ctl-{s}".encode())
            sock.setsockopt(zmq.SNDHWM, 8)   # a dead shard must not
            sock.connect(f"tcp://{ip}:{shard_port(cfg.comms, s)}")  # wedge us
            self.ctl_socks.append(sock)
        self.sender = transport.ChunkSender(cfg.comms, "serve-ctl",
                                            learner_ip=self.learner_ip)
        self.beat = HeartbeatEmitter(
            "serve-ctl", role="serve-ctl",
            interval_s=cfg.comms.heartbeat_interval_s,
            gauges_fn=self._gauges)
        self.ticks = 0
        self._rid = 0
        self._events_seen = 0

    def _gauges(self) -> dict:
        c = self.ctrl
        return {"serve_state_code": STATE_CODE.get(c.state, 0),
                "serve_deployments": c.deployments,
                "serve_promotions": c.promotions,
                "serve_rollbacks": c.rollbacks}

    def _probe(self) -> tuple[dict | None, dict | None]:
        """One learner status round-trip -> (newest model fence, SLO
        objective states); (None, None) while nothing answers."""
        from apex_tpu.fleet.registry import status_request

        try:
            snap = status_request(self.comms, learner_ip=self.learner_ip,
                                  timeout_s=min(2.0, self.interval_s))
        except Exception:
            return None, None
        if not snap:
            return None, None
        m = snap.get("metrics") or {}
        learner = None
        if m.get("param_version") is not None:
            learner = {"epoch": m.get("learner_epoch", 0),
                       "version": m.get("param_version", 0)}
        slo = {o["name"]: o["state"]
               for o in (snap.get("slo") or {}).get("objectives", [])}
        return learner, (slo or None)

    def _drain_ctl_replies(self) -> None:
        from apex_tpu.runtime import wire

        for sock in self.ctl_socks:
            while sock.poll(0, self._zmq.POLLIN):
                try:
                    got = wire.restricted_loads(sock.recv())
                except wire.WireRejected:
                    continue
                if (isinstance(got, tuple) and len(got) == 2
                        and got[0] == "ctl_ok" and isinstance(got[1], dict)):
                    body = got[1]
                    self.ctrl.shard_view[int(body.get("shard", 0))] = body

    def step(self) -> None:
        """One control round: probe -> decide -> reconcile -> report."""
        from apex_tpu.runtime import wire

        learner, slo = self._probe()
        before = len(self.ctrl.timeline)
        cmds = self.ctrl.tick(learner, slo)
        for e in list(self.ctrl.timeline)[before:]:
            print(f"serve-ctl: {e['from']} -> {e['to']} "
                  f"({e['version']}; {e['reason']})", flush=True)
        for s, cmd in cmds:
            self._rid += 1
            try:
                self.ctl_socks[s].send(
                    wire.dumps(("ctl", dict(cmd, rid=self._rid))),
                    self._zmq.DONTWAIT)
            except self._zmq.Again:
                pass            # dead shard: re-asserted next tick anyway
        self._drain_ctl_replies()
        self.ticks += 1
        # evidence out: the timeline must land in fleet_summary.json /
        # the status table / apex_serving_* rows, so every tick ships
        # the snapshot (small, bounded) — not just transitions
        self.sender.send_stat(ServingStat("serve-ctl",
                                          self.ctrl.snapshot()))
        hb = self.beat.maybe_beat()
        if hb is not None:
            self.sender.send_stat(hb)

    def run(self, stop_event=None, max_seconds: float | None = None):
        deadline = (None if max_seconds is None
                    else time.monotonic() + max_seconds)
        try:
            while True:
                if stop_event is not None and stop_event.is_set():
                    break
                if deadline is not None and time.monotonic() > deadline:
                    break
                t0 = time.monotonic()
                self.step()
                rest = self.interval_s - (time.monotonic() - t0)
                if rest > 0:
                    if stop_event is not None:
                        stop_event.wait(rest)
                    else:
                        time.sleep(rest)
        finally:
            self.close()
        return self.ctrl.snapshot()

    def close(self) -> None:
        for sock in self.ctl_socks:
            sock.close(linger=0)
        self.sender.close(drain_s=0.0)


def run_serve_ctl(cfg, identity=None, canary_frac: float = 0.5,
                  soak_s: float = 60.0, version_every: int = 0,
                  interval_s: float = 5.0, stop_event=None,
                  max_seconds: float | None = None) -> dict:
    """The ``--role serve-ctl`` entry point.  Skips the startup barrier
    like the replay/infer roles — the controller is useful the moment
    the learner's status port answers, and holds (no deployments, no
    pins) until then.  Returns the final controller snapshot."""
    from apex_tpu.obs.trace import get_ring, set_process_label

    set_process_label("serve-ctl")
    get_ring()
    # the caller folds any explicit role-identity IPs into cfg.comms
    # (runtime.roles._with_ips) before handing the config over
    ctl = ServeCtl(cfg, canary_frac=canary_frac,
                   soak_s=soak_s, version_every=version_every,
                   interval_s=interval_s)
    print(f"serve-ctl: {ctl.ctrl.n_shards} shard(s), canary band "
          f"{list(ctl.ctrl.canary_shards)} (frac={canary_frac}), "
          f"soak={soak_s:g}s, version_every={version_every}, "
          f"tick={interval_s:g}s", flush=True)
    return ctl.run(stop_event=stop_event, max_seconds=max_seconds)
