"""Shard fabric for the serving tier: identity-hashed infer-shard routing.

PR 9's inference plane is ONE server on ``comms.infer_port``; the
serving tier runs ``comms.infer_shards`` of them, shard ``s`` binding
``infer_port + s`` (the replay service's port-base discipline).  Each
remote-policy worker routes ALL of its half-group requests to one home
shard by a stable hash of its worker identity — deterministic, uniform,
and computable anywhere (the tests pin the mapping), so "which shard
serves actor-3" is a function, not a lookup.

Identity-hash (not per-request) routing is deliberate: a worker's two
half-groups must land in the SAME server's coalesce window to batch
together, and the per-worker :class:`~apex_tpu.infer_service.client.
InferClient` machinery — down-marker, bit-identical local fallback,
re-probe — then gives every shard PR 9's exact single-server semantics
for free: a dead shard degrades precisely the worker band hashed to it,
and degrades it to local acting, never to a stall.

The hash keys on ``identity#n_shards`` so a re-shard remaps the whole
fleet uniformly instead of stranding the old mapping's tail.
"""

from __future__ import annotations

from apex_tpu.config import CommsConfig
from apex_tpu.tenancy import namespace as tenancy_ns


def infer_shard(identity: str, n_shards: int) -> int:
    """Stable worker-identity -> home-shard index (crc32, like the chunk
    plane's :func:`~apex_tpu.replay_service.sender.chunk_shard`):
    identical across processes, platforms, and runs.  Routed through the
    tenancy band helper (apexlint J021) with the full tier as the band —
    bit-identical to the historical raw ``crc32 % n``, so the pinned
    mapping tests hold."""
    n = max(1, int(n_shards))
    return tenancy_ns.shard_in_band(f"{identity}#{n}", range(n))


def shard_port(comms: CommsConfig, shard: int) -> int:
    """Shard ``s`` binds ``infer_port + s`` (shard 0 IS the PR 9 single
    server — an unsharded config is the 1-shard tier)."""
    return comms.infer_port + int(shard)


def make_infer_client(comms: CommsConfig, identity: str, **kw):
    """The worker-side constructor for the sharded tier: one
    :class:`~apex_tpu.infer_service.client.InferClient` pointed at this
    identity's home shard, with the shard index stamped on the client so
    its heartbeat gauges attribute fallbacks/stale-epoch discards to the
    shard that caused them (a mis-pinned shard shows up in
    ``--role status``, not only in local counters)."""
    from apex_tpu.infer_service.client import InferClient
    from apex_tpu.tenancy import namespace as tenancy_ns

    # tenant-qualified home-shard hash (PR 13): two tenants' "actor-0"
    # workers are different identities, so their bands spread
    # independently; the default tenant qualifies to the bare id and
    # the pinned single-tenant mapping is untouched
    identity = tenancy_ns.qualify(tenancy_ns.current_tenant(), identity)
    s = infer_shard(identity, getattr(comms, "infer_shards", 1))
    client = InferClient(comms, identity, port=shard_port(comms, s), **kw)
    client.shard = s
    return client
