"""Model-version fencing: THE ordering helpers for epochs and versions.

A model VERSION in the serving tier is the pair ``(learner_epoch,
param_version)``: the epoch is the major key (a restarted learner's
params are a NEW model no matter what its version counter says — PR 8's
life fencing), the param version the minor key (within one life the
publish counter orders models totally).  Every ordering decision the
serving tier makes — the server-side param gate, the canary
promotion/rollback fences, the replay shards' stale-write-back
rejection — routes through the helpers below, so "is this model newer"
has exactly one spelling in the codebase.

apexlint J016 (``raw-epoch-comparison``) enforces the routing: an
ordering comparison on a ``learner_epoch``/``param_version`` attribute
anywhere outside this module is a finding.  The hazard is concrete: a
scattered ``>=`` on a raw epoch is how a rollback path keeps serving a
dead life's params, or rejects a legitimately restored incumbent as
"stale" — the lexicographic pair below is the only comparison that
survives both restarts and rollbacks.

Pure stdlib, no imports at all: the replay shards, the infer servers,
and the deployment controller all call in from their hot paths.
"""

from __future__ import annotations


def fence_key(epoch, version) -> tuple[int, int]:
    """The total order on models: ``(learner_epoch, param_version)``,
    epoch-major.  ``None``/absent components clamp to 0 (the pre-fencing
    wire format's unstamped messages sort before everything real)."""
    return (int(epoch or 0), int(version or 0))


def beyond(epoch, version, fence: tuple) -> bool:
    """True when ``(epoch, version)`` is strictly newer than ``fence`` —
    the server-side param gate's hold condition and the rollback
    trigger."""
    return fence_key(epoch, version) > fence_key(*fence)


def at_or_before(epoch, version, fence: tuple) -> bool:
    """The gate's install condition (complement of :func:`beyond`)."""
    return not beyond(epoch, version, fence)


def newer_epoch(epoch, current) -> bool:
    """Epoch-only ordering: ``epoch`` proves a LATER learner life than
    ``current`` (the replay shards' restart detection)."""
    return int(epoch or 0) > int(current or 0)


def stale_epoch(epoch, current) -> bool:
    """``epoch`` belongs to an EARLIER life than ``current`` — the
    write-back/reply rejection condition (a dead life's stragglers)."""
    return int(epoch or 0) < int(current or 0)


def fmt(fence) -> str | None:
    """Human/JSON spelling of a fence: ``"epoch:version"``."""
    if fence is None:
        return None
    e, v = fence_key(*fence)
    return f"{e}:{v}"
