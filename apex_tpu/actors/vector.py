"""Vectorized actor workers: B envs per process, one batched policy call.

The reference runs exactly one env per actor process (``batchrecorder.py:79``,
``origin_repo/actor.py:52-115``), so its "192 actors" cost 192 processes on
48 nodes (``terraform.tfvars:4-5``).  On the TPU topology the policy is a
jitted pure function that is *already batched* (``make_policy_fn`` vectorizes
over the leading axis), so one process can drive B envs with a single
forward per step — B actor slots for one interpreter, one model copy, and
1/B-th the per-call dispatch overhead.  The 256-actor north star
(BASELINE.json) becomes 8 processes x 32 envs instead of 256 processes.

Semantics per env slot are IDENTICAL to the scalar worker
(:mod:`apex_tpu.actors.pool`):

* each slot has its own env, seed, :class:`FrameChunkBuilder`, and its own
  epsilon from the global Ape-X ladder — the ladder spans ALL
  ``n_actors * n_envs`` slots, so exploration diversity matches a fleet of
  scalar actors (``batchrecorder.py:121``);
* n-step windows, truncation bootstrapping, and acting-time TD priorities
  are per-slot (one builder each);
* param refresh stays CONFLATE latest-wins, polled every
  ``update_interval`` *env* steps — i.e. every ``update_interval / B``
  vector steps, so policy staleness measured in env frames is unchanged
  (``actor.py:97-103``);
* episode stats carry the global slot id, so the learner's logs can still
  attribute rewards to an exploration level.

Chunks from all slots ship on the same bounded queue; backpressure applies
to the whole process (a full queue blocks all B slots — strictly stronger
than the scalar fleet's per-process blocking, preserving the end-to-end
flow control).

The vector hot loop is ALTERNATING DOUBLE-BUFFERED (Stooke & Abbeel,
*Accelerated Methods for Deep RL*): the B slots split into two half-groups
A/B, the per-step key derives one subkey per group via
``fold_in(step_key, group)``, and with ``ActorConfig.double_buffer`` on the
jitted policy for BOTH groups dispatches asynchronously before any result
is materialized — group A's env stepping then runs on the host while the
device still computes group B's inference.  The serial interleave
(``double_buffer=False``) dispatches, materializes, and steps one group at
a time with the SAME group split and the SAME per-group keys, so the two
modes are bit-identical per slot (actions, chunks, priorities — pinned in
``tests/test_vector.py``); the knob is a pure scheduling A/B.  Acting
stacks are assembled IN PLACE: one preallocated contiguous
``[B, *stacked]`` buffer whose rows the per-slot
:class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder`\\ s maintain
through bound views — the policy consumes buffer slices directly, no
per-step ``np.stack`` of B copied stacks.  Each step's wall time is split
into policy-wait / env-step / drain phases
(:class:`~apex_tpu.utils.profiling.PhaseTimer`) and shipped periodically
as :class:`~apex_tpu.actors.pool.ActorTimingStat`.
"""

from __future__ import annotations

import math
import queue as queue_lib

import numpy as np

from apex_tpu.config import ApexConfig
from apex_tpu.actors.pool import EpisodeStat


class VectorFamilyBase:
    """Shared scaffolding for B-env worker families: slot bookkeeping, the
    per-slot epsilon anneal, and episode accounting with auto-reset.  One
    implementation for every family — the reference maintains near-copy
    recorders per algorithm (``batchrecorder.py`` vs
    ``batchrecoder_AQL.py``), the defect this hierarchy exists to avoid.

    Subclasses provide ``_make_env(seed)``, ``_on_reset(i, obs)``, and the
    per-group hooks ``_policy_group``/``_step_group`` consumed by the
    shared double-buffered :meth:`step_all` template (module docstring);
    ``_step_group`` calls :meth:`_finish_step` per slot to get uniform
    accounting/reset behavior.
    """

    #: remote-policy client (apex_tpu/infer_service) — None = local
    #: acting; families that ship their half-groups to the infer server
    #: set ``supports_remote`` and route through it in ``_policy_group``
    infer = None
    supports_remote = False

    def __init__(self, cfg: ApexConfig, seeds, slot_ids, epsilons):
        from apex_tpu.utils.profiling import DispatchGapTimer, PhaseTimer

        self.cfg = cfg
        self.seeds = list(seeds)
        self.slot_ids = list(slot_ids)
        self.epsilons = np.asarray(epsilons, np.float32)
        self.n_envs = len(self.seeds)
        if not (self.n_envs == len(self.slot_ids) == len(self.epsilons)):
            # survives `python -O`, unlike the assert it replaces: a
            # mis-derived slot band would run the wrong exploration
            # spectrum for the whole process
            raise ValueError(
                f"vector worker slot arity mismatch: {len(self.seeds)} "
                f"seeds, {len(self.slot_ids)} slot_ids, "
                f"{len(self.epsilons)} epsilons — all three derive from "
                f"ActorConfig.n_envs_per_actor x ActorConfig.n_actors "
                f"(see worker_slots); check those knobs")
        self.envs = [self._make_env(s) for s in self.seeds]
        self.ep_reward = np.zeros(self.n_envs, np.float64)
        self.ep_len = np.zeros(self.n_envs, np.int64)
        self.slot_steps = np.zeros(self.n_envs, np.int64)
        # alternating double-buffer state: two half-groups (first takes
        # the odd slot), serial fallback when there is nothing to overlap
        half = (self.n_envs + 1) // 2
        self.groups = [sl for sl in (slice(0, half),
                                     slice(half, self.n_envs))
                       if sl.stop > sl.start]
        self.double_buffer = (
            bool(getattr(cfg.actor, "double_buffer", True))
            and self.n_envs >= 2)
        # per-group device epsilon cache (anneal off => the ladder is a
        # constant; re-uploading it every dispatch costs a host->device
        # conversion per group per step)
        self._eps_cache: list | None = None
        # actor-plane observability: per-phase wall fractions + the host
        # gap between policy dispatches (both pure host timing)
        self.phase = PhaseTimer()
        self.gap = DispatchGapTimer()

    # -- lifecycle ---------------------------------------------------------

    def reset_all(self) -> None:
        for i, (env, seed) in enumerate(zip(self.envs, self.seeds)):
            obs, _ = env.reset(seed=seed)
            self._on_reset(i, obs)

    def attach_infer(self, client) -> None:
        """Route this family's half-group policy calls through the
        inference plane (``ActorConfig.remote_policy``).  The local
        policy stays jitted as the fallback — remote and local are
        bit-identical for the same params + key chain, so attaching the
        client changes scheduling, never trajectories."""
        if not self.supports_remote:
            raise NotImplementedError(
                f"{type(self).__name__} has no remote-policy path — "
                f"ActorConfig.remote_policy currently serves the DQN "
                f"vector family only (see ROADMAP.md)")
        self.infer = client

    def close(self) -> None:
        for env in self.envs:
            env.close()
        if self.infer is not None:
            self.infer.close()

    # -- the double-buffered vector step -----------------------------------

    def step_all(self, params, key) -> list:
        """One vector step over all B slots.  Both modes derive one subkey
        per half-group (``fold_in(key, group)`` — folded INSIDE the jitted
        group call, so the derivation costs no extra dispatch) and run the
        policy per group; double-buffered, every group's inference
        dispatches BEFORE any result is materialized, so group A's env
        stepping overlaps group B's device compute.  Returns stats for
        slots whose episodes ended (those are auto-reset)."""
        stats: list = []
        eps = self._group_eps()
        if self.double_buffer:
            outs = []
            for g, sl in enumerate(self.groups):
                self.gap.about_to_dispatch()
                # apexlint: disable=J004 -- each group call folds key with its group id inside the jit: distinct subkeys, no reuse
                outs.append(self._policy_group(params, sl, eps[g], key, g))
                self.gap.dispatch_returned()
            for sl, out in zip(self.groups, outs):
                with self.phase.phase("policy_wait"):
                    host = self._materialize(out)
                with self.phase.phase("env_step"):
                    self._step_group(sl, host, stats)
        else:
            for g, sl in enumerate(self.groups):
                self.gap.about_to_dispatch()
                # apexlint: disable=J004 -- each group call folds key with its group id inside the jit: distinct subkeys, no reuse
                out = self._policy_group(params, sl, eps[g], key, g)
                self.gap.dispatch_returned()
                with self.phase.phase("policy_wait"):
                    host = self._materialize(out)
                with self.phase.phase("env_step"):
                    self._step_group(sl, host, stats)
        return stats

    def _group_eps(self) -> list:
        """Per-group epsilon arrays for this step — device-cached while
        the anneal is off (the ladder is constant), recomputed per step
        otherwise."""
        if not self.cfg.actor.eps_anneal_steps:
            if self._eps_cache is None:
                import jax.numpy as jnp
                self._eps_cache = [jnp.asarray(self.epsilons[sl])
                                   for sl in self.groups]
            return self._eps_cache
        eps = self._current_eps()
        return [eps[sl] for sl in self.groups]

    @staticmethod
    def _grouped_policy(policy_fn):
        """Jit ``policy_fn`` with the per-group key derivation fused in:
        the call receives the RAW per-step key plus its group id and folds
        inside the compiled program — bit-identical to a host-side
        ``fold_in`` at zero extra dispatches."""
        import jax

        def grouped(params, obs, eps, key, group):
            return policy_fn(params, obs, eps,
                             jax.random.fold_in(key, group))

        # group is structural (which half), not data: static avoids a
        # per-call scalar transfer at the cost of one compile per group
        return jax.jit(grouped, static_argnums=(4,))

    def _policy_group(self, params, sl: slice, eps, key, group: int):
        """Dispatch the jitted policy for the slots in ``sl``; must return
        device arrays WITHOUT materializing them (the double-buffered
        interleave defers every blocking host copy to the consumption
        site)."""
        raise NotImplementedError

    @staticmethod
    def _materialize(out) -> tuple:
        """The one blocking device->host sync per group, immediately before
        the group's envs consume the results.  A remote-policy pending
        handle (:class:`~apex_tpu.infer_service.client.PendingInfer`)
        blocks here on the reply — or the local fallback after
        ``infer_wait_s`` — at exactly the site the local path pays its
        ``np.asarray``."""
        mat = getattr(out, "materialize", None)
        if mat is not None:
            return mat()
        return tuple(np.asarray(x) for x in out)

    def _step_group(self, sl: slice, host: tuple, stats: list) -> None:
        """Step the envs in ``sl`` with the group's materialized policy
        outputs and record per-slot transitions."""
        raise NotImplementedError

    # -- shared stepping helpers -------------------------------------------

    def _current_eps(self) -> np.ndarray:
        anneal = self.cfg.actor.eps_anneal_steps
        if not anneal:
            return self.epsilons
        decay = np.exp(-self.slot_steps / anneal)
        return (self.epsilons + (1.0 - self.epsilons) * decay).astype(
            np.float32)

    def _finish_step(self, i: int, reward: float, done: bool,
                     stats: list) -> None:
        """Per-slot accounting + auto-reset; appends an EpisodeStat with
        the GLOBAL slot id when the episode ended."""
        self.ep_reward[i] += reward
        self.ep_len[i] += 1
        self.slot_steps[i] += 1
        if done:
            stats.append(EpisodeStat(self.slot_ids[i],
                                     float(self.ep_reward[i]),
                                     int(self.ep_len[i])))
            self.ep_reward[i] = 0.0
            self.ep_len[i] = 0
            obs, _ = self.envs[i].reset()
            self._on_reset(i, obs)


class VectorChunkFamilyBase(VectorFamilyBase):
    """Base for B-env families that record through per-slot
    :class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder`\\ s: un-stacked
    envs, builder-managed acting stacks, and chunk-message draining live
    here ONCE (the DQN and pixel-AQL vector families share them)."""

    builders: list            # set by subclass __init__

    def _make_env(self, seed: int):
        from apex_tpu.envs.registry import make_env
        return make_env(self.cfg.env.env_id, self.cfg.env, seed=seed,
                        max_episode_steps=self.cfg.actor.max_episode_length,
                        stack_frames=False)

    def _on_reset(self, i: int, obs) -> None:
        self.builders[i].begin_episode(obs)

    def _bind_acting_buffer(self) -> None:
        """Preallocate ONE contiguous ``[B, *stacked]`` acting buffer and
        hand each builder a row view to maintain in place — the policy
        consumes ``self._acting[group]`` slices directly, eliminating the
        per-step ``np.stack`` of B copied stacks (and each builder's
        per-call concatenate).  Group slices are contiguous and disjoint,
        so mutating one group's rows while the other group's dispatched
        policy call is still in flight can never touch that call's input."""
        stacked = self.builders[0].stacked_shape()
        self._acting = np.zeros((self.n_envs,) + stacked,
                                self.builders[0].frame_dtype)
        for i, builder in enumerate(self.builders):
            builder.bind_acting_view(self._acting[i])

    def poll_msgs(self) -> list[dict]:
        from apex_tpu.actors.pool import drain_builder_chunks
        out = []
        for builder in self.builders:
            out.extend(drain_builder_chunks(builder))
        return out


class VectorDQNWorkerFamily(VectorChunkFamilyBase):
    """B-env DQN acting/recording: the vector counterpart of
    :class:`apex_tpu.actors.pool.DQNWorkerFamily`."""

    supports_remote = True      # half-groups can ship to the infer server

    def __init__(self, cfg: ApexConfig, model_spec: dict, seeds,
                 slot_ids, epsilons, chunk_transitions: int):
        from apex_tpu.envs.registry import unstacked_env_spec
        from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
        from apex_tpu.replay.frame_chunks import FrameChunkBuilder

        super().__init__(cfg, seeds, slot_ids, epsilons)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            self.envs[0], cfg.env)
        self.policy = self._grouped_policy(
            make_policy_fn(DuelingDQN(**model_spec)))
        self.builders = [
            FrameChunkBuilder(
                cfg.learner.n_steps, cfg.learner.gamma, frame_stack,
                frame_shape, chunk_transitions=chunk_transitions,
                frame_dtype=frame_dtype)
            for _ in range(self.n_envs)
        ]
        self._bind_acting_buffer()

    def _policy_group(self, params, sl: slice, eps, key, group: int):
        if self.infer is not None:
            # remote policy: ship this half-group's stacked obs + ladder
            # slice + RAW step key + group id; fold_in happens server-
            # side in the same program the local jit runs.  The fallback
            # closure reads the SAME acting-buffer rows it shipped —
            # those rows only mutate in _step_group, which runs strictly
            # after this group's materialize, so remote timeout or not,
            # the inputs (hence outputs) are bit-identical.
            return self.infer.submit(
                self._acting[sl], np.asarray(eps), key, group,
                # apexlint: disable=J004 -- remote and fallback run the SAME fold_in(key, group) program; exactly one result is consumed, so the draw is used once
                fallback=lambda: self.policy(params, self._acting[sl],
                                             eps, key, group))
        return self.policy(params, self._acting[sl], eps, key, group)

    def _step_group(self, sl: slice, host: tuple, stats: list) -> None:
        actions, q = host
        for j, i in enumerate(range(sl.start, sl.stop)):
            a = int(actions[j])
            next_obs, reward, term, trunc, _ = self.envs[i].step(a)
            self.builders[i].add_step(a, float(reward), q[j], next_obs,
                                      bool(term), bool(trunc))
            self._finish_step(i, float(reward), bool(term or trunc), stats)


def _timing_stat(actor_id: int, family, steps_window: int):
    """One :class:`~apex_tpu.actors.pool.ActorTimingStat` from the family's
    phase/gap timers, resetting the phase window (``dropped_stats`` is
    stamped by the put loop, like every stat)."""
    from apex_tpu.actors.pool import ActorTimingStat

    w = family.phase.window(reset=True)
    fr = w["fracs"]
    return ActorTimingStat(
        actor_id=actor_id,
        frames_per_sec=round(steps_window * family.n_envs / w["wall_s"], 1),
        policy_wait_frac=round(fr.get("policy_wait", 0.0), 4),
        env_step_frac=round(fr.get("env_step", 0.0), 4),
        drain_frac=round(fr.get("drain", 0.0), 4),
        dispatch_gap_ms_p50=family.gap.snapshot()["dispatch_gap_ms_p50"],
        vector_steps=steps_window,
        double_buffer=bool(getattr(family, "double_buffer", False)))


def vector_worker_loop(actor_id: int, cfg: ApexConfig, family, chunk_queue,
                       param_queue, stat_queue, stop_event) -> None:
    """Vector counterpart of :func:`apex_tpu.actors.pool.worker_loop`: the
    same lifecycle (interruptible first-publish wait, CONFLATE param polls,
    chunk backpressure, clean shutdown) over B env slots, plus the
    actor-plane observability cadence (drain-phase timing and the periodic
    :class:`~apex_tpu.actors.pool.ActorTimingStat`)."""
    import jax

    from apex_tpu.fleet.heartbeat import HeartbeatEmitter
    from apex_tpu.obs import spans as obs_spans
    from apex_tpu.obs.trace import get_ring, set_process_label

    from apex_tpu.tenancy import namespace as tenancy_ns

    # tenant-qualified identity (PR 13): the worker's beats must agree
    # with the role-level wire identity (park heartbeats, chunk-arrival
    # liveness) or a tenant's actor shows up TWICE in its registry;
    # the default tenant qualifies to the bare name
    identity = tenancy_ns.qualify(tenancy_ns.current_tenant(),
                                  f"actor-{actor_id}")
    set_process_label(identity)
    ring = get_ring()
    # attach the trace ring to the family's existing timers: every
    # policy-wait/env-step/drain phase and every dispatch gap becomes a
    # trace event on this role's track (sampled, bounded, host-only)
    family.phase.ring = ring
    family.phase.track = "actor-phases"
    family.gap.ring = ring
    family.gap.track = "actor-dispatch"

    key = jax.random.key(family.seeds[0])
    beat = HeartbeatEmitter(
        identity, role="actor",
        interval_s=cfg.comms.heartbeat_interval_s,
        counters_fn=getattr(chunk_queue, "wire_counters", None),
        park_fn=getattr(param_queue, "park_state", None),
        # remote-policy health (fallback count, round-trip percentiles)
        # rides the same beats the registry already consumes
        gauges_fn=(family.infer.gauges if family.infer is not None
                   else None))
    version, params = 0, None
    while True:                                  # block for first publish
        if stop_event.is_set():
            family.close()
            return
        hb = beat.maybe_beat(version)
        if hb is not None:
            try:
                stat_queue.put_nowait(hb)
            except queue_lib.Full:
                pass
        try:
            version, params = param_queue.get(timeout=0.5)
            break
        except queue_lib.Empty:
            continue

    # poll cadence in VECTOR steps so staleness in env frames matches the
    # scalar worker's update_interval
    poll_every = max(1, math.ceil(cfg.actor.update_interval / family.n_envs))
    timing_every = max(0, int(getattr(cfg.actor, "timing_interval", 0)))
    steps_since_poll = 0
    vec_steps = 0
    dropped = 0         # stats lost to a full queue, carried on the next
    #                     successful put (auditably lossy, not silently)
    family.reset_all()
    family.phase.window(reset=True)   # timing windows start at the loop,
    #                                   not at family construction

    while not stop_event.is_set():
        steps_since_poll += 1
        if steps_since_poll >= poll_every:
            steps_since_poll = 0
            try:
                while True:                      # keep only the newest
                    version, params = param_queue.get_nowait()
            except queue_lib.Empty:
                pass

        key, akey = jax.random.split(key)
        stats = list(family.step_all(params, akey))
        vec_steps += 1
        beat.tick(family.n_envs)
        hb = beat.maybe_beat(version)
        if hb is not None:
            stats.append(hb)      # rides the stat put loop like every stat
        if timing_every and vec_steps % timing_every == 0:
            stats.append(_timing_stat(actor_id, family, timing_every))
        for stat in stats:
            if hasattr(stat, "param_version"):
                stat.param_version = version
            stat.dropped_stats = dropped
            try:
                stat_queue.put_nowait(stat)
                dropped = 0
            except queue_lib.Full:
                dropped += 1

        with family.phase.phase("drain"):
            for msg in family.poll_msgs():
                beat.note_chunk()
                obs_spans.mark_send(msg, version)
                chunk_queue.put(("chunk", actor_id, msg))  # blocks when full

    family.close()


def worker_slots(cfg: ApexConfig, actor_id: int):
    """Pure slot derivation for one vector worker: ``(slot_ids, seeds,
    epsilons)``.  The ladder spans the WHOLE fleet
    (``n_actors * n_envs_per_actor`` slots) and worker ``i`` owns the
    contiguous band ``[i*B, (i+1)*B)`` — seeds match what a fleet of scalar
    workers with those global ids would use."""
    from apex_tpu.actors.pool import actor_epsilons

    b = cfg.actor.n_envs_per_actor
    total = cfg.actor.n_actors * b
    ladder = actor_epsilons(total, cfg.actor.eps_base, cfg.actor.eps_alpha)
    slot_ids = list(range(actor_id * b, (actor_id + 1) * b))
    seeds = [cfg.env.seed + 1000 * (s + 1) for s in slot_ids]
    return slot_ids, seeds, ladder[slot_ids]


def vector_worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                       chunk_queue, param_queue, stat_queue, stop_event,
                       epsilon: float, chunk_transitions: int) -> None:
    """Process body wired through :class:`~apex_tpu.actors.pool.ActorPool`'s
    scalar ``worker_fn`` signature: ``epsilon`` is ignored — the family
    re-derives its slots' epsilons from the GLOBAL ladder
    (:func:`worker_slots`) so the fleet's exploration spectrum is identical
    whether slots are processes or vector lanes."""
    slot_ids, seeds, epsilons = worker_slots(cfg, actor_id)
    family = VectorDQNWorkerFamily(
        cfg, model_spec, seeds=seeds, slot_ids=slot_ids, epsilons=epsilons,
        chunk_transitions=chunk_transitions)
    if getattr(cfg.actor, "remote_policy", False):
        # centralized inference: the half-group policy calls ship to this
        # worker's home infer shard (identity-hashed — serving/shard.py;
        # one shard IS the PR 9 single server); the family's local jit
        # stays as the fallback
        from apex_tpu.serving.shard import make_infer_client
        family.attach_infer(make_infer_client(cfg.comms,
                                              f"actor-{actor_id}"))
    vector_worker_loop(actor_id, cfg, family, chunk_queue, param_queue,
                       stat_queue, stop_event)


vector_worker_main.is_vector = True     # ActorPool guard marker
