"""Vectorized actor workers: B envs per process, one batched policy call.

The reference runs exactly one env per actor process (``batchrecorder.py:79``,
``origin_repo/actor.py:52-115``), so its "192 actors" cost 192 processes on
48 nodes (``terraform.tfvars:4-5``).  On the TPU topology the policy is a
jitted pure function that is *already batched* (``make_policy_fn`` vectorizes
over the leading axis), so one process can drive B envs with a single
forward per step — B actor slots for one interpreter, one model copy, and
1/B-th the per-call dispatch overhead.  The 256-actor north star
(BASELINE.json) becomes 8 processes x 32 envs instead of 256 processes.

Semantics per env slot are IDENTICAL to the scalar worker
(:mod:`apex_tpu.actors.pool`):

* each slot has its own env, seed, :class:`FrameChunkBuilder`, and its own
  epsilon from the global Ape-X ladder — the ladder spans ALL
  ``n_actors * n_envs`` slots, so exploration diversity matches a fleet of
  scalar actors (``batchrecorder.py:121``);
* n-step windows, truncation bootstrapping, and acting-time TD priorities
  are per-slot (one builder each);
* param refresh stays CONFLATE latest-wins, polled every
  ``update_interval`` *env* steps — i.e. every ``update_interval / B``
  vector steps, so policy staleness measured in env frames is unchanged
  (``actor.py:97-103``);
* episode stats carry the global slot id, so the learner's logs can still
  attribute rewards to an exploration level.

Chunks from all slots ship on the same bounded queue; backpressure applies
to the whole process (a full queue blocks all B slots — strictly stronger
than the scalar fleet's per-process blocking, preserving the end-to-end
flow control).
"""

from __future__ import annotations

import math
import queue as queue_lib

import numpy as np

from apex_tpu.config import ApexConfig
from apex_tpu.actors.pool import EpisodeStat


class VectorFamilyBase:
    """Shared scaffolding for B-env worker families: slot bookkeeping, the
    per-slot epsilon anneal, and episode accounting with auto-reset.  One
    implementation for every family — the reference maintains near-copy
    recorders per algorithm (``batchrecorder.py`` vs
    ``batchrecoder_AQL.py``), the defect this hierarchy exists to avoid.

    Subclasses provide ``_make_env(seed)``, ``_on_reset(i, obs)`` and
    ``step_all``; the latter calls :meth:`_finish_step` per slot to get
    uniform accounting/reset behavior.
    """

    def __init__(self, cfg: ApexConfig, seeds, slot_ids, epsilons):
        self.cfg = cfg
        self.seeds = list(seeds)
        self.slot_ids = list(slot_ids)
        self.epsilons = np.asarray(epsilons, np.float32)
        self.n_envs = len(self.seeds)
        assert self.n_envs == len(self.slot_ids) == len(self.epsilons)
        self.envs = [self._make_env(s) for s in self.seeds]
        self.ep_reward = np.zeros(self.n_envs, np.float64)
        self.ep_len = np.zeros(self.n_envs, np.int64)
        self.slot_steps = np.zeros(self.n_envs, np.int64)

    # -- lifecycle ---------------------------------------------------------

    def reset_all(self) -> None:
        for i, (env, seed) in enumerate(zip(self.envs, self.seeds)):
            obs, _ = env.reset(seed=seed)
            self._on_reset(i, obs)

    def close(self) -> None:
        for env in self.envs:
            env.close()

    # -- shared stepping helpers -------------------------------------------

    def _current_eps(self) -> np.ndarray:
        anneal = self.cfg.actor.eps_anneal_steps
        if not anneal:
            return self.epsilons
        decay = np.exp(-self.slot_steps / anneal)
        return (self.epsilons + (1.0 - self.epsilons) * decay).astype(
            np.float32)

    def _finish_step(self, i: int, reward: float, done: bool,
                     stats: list) -> None:
        """Per-slot accounting + auto-reset; appends an EpisodeStat with
        the GLOBAL slot id when the episode ended."""
        self.ep_reward[i] += reward
        self.ep_len[i] += 1
        self.slot_steps[i] += 1
        if done:
            stats.append(EpisodeStat(self.slot_ids[i],
                                     float(self.ep_reward[i]),
                                     int(self.ep_len[i])))
            self.ep_reward[i] = 0.0
            self.ep_len[i] = 0
            obs, _ = self.envs[i].reset()
            self._on_reset(i, obs)


class VectorChunkFamilyBase(VectorFamilyBase):
    """Base for B-env families that record through per-slot
    :class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder`\\ s: un-stacked
    envs, builder-managed acting stacks, and chunk-message draining live
    here ONCE (the DQN and pixel-AQL vector families share them)."""

    builders: list            # set by subclass __init__

    def _make_env(self, seed: int):
        from apex_tpu.envs.registry import make_env
        return make_env(self.cfg.env.env_id, self.cfg.env, seed=seed,
                        max_episode_steps=self.cfg.actor.max_episode_length,
                        stack_frames=False)

    def _on_reset(self, i: int, obs) -> None:
        self.builders[i].begin_episode(obs)

    def poll_msgs(self) -> list[dict]:
        from apex_tpu.actors.pool import drain_builder_chunks
        out = []
        for builder in self.builders:
            out.extend(drain_builder_chunks(builder))
        return out


class VectorDQNWorkerFamily(VectorChunkFamilyBase):
    """B-env DQN acting/recording: the vector counterpart of
    :class:`apex_tpu.actors.pool.DQNWorkerFamily`."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seeds,
                 slot_ids, epsilons, chunk_transitions: int):
        import jax

        from apex_tpu.envs.registry import unstacked_env_spec
        from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
        from apex_tpu.replay.frame_chunks import FrameChunkBuilder

        super().__init__(cfg, seeds, slot_ids, epsilons)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            self.envs[0], cfg.env)
        self.policy = jax.jit(make_policy_fn(DuelingDQN(**model_spec)))
        self.builders = [
            FrameChunkBuilder(
                cfg.learner.n_steps, cfg.learner.gamma, frame_stack,
                frame_shape, chunk_transitions=chunk_transitions,
                frame_dtype=frame_dtype)
            for _ in range(self.n_envs)
        ]

    def step_all(self, params, key) -> list[EpisodeStat]:
        """One batched policy call, then one env.step per slot.  Returns
        stats for slots whose episodes ended (those are auto-reset)."""
        import jax.numpy as jnp

        stacks = np.stack([b.current_stack() for b in self.builders])
        actions, q = self.policy(params, stacks,
                                 jnp.asarray(self._current_eps()), key)
        actions = np.asarray(actions)
        q = np.asarray(q)

        stats: list[EpisodeStat] = []
        for i, (env, builder) in enumerate(zip(self.envs, self.builders)):
            a = int(actions[i])
            next_obs, reward, term, trunc, _ = env.step(a)
            builder.add_step(a, float(reward), q[i], next_obs,
                             bool(term), bool(trunc))
            self._finish_step(i, float(reward), bool(term or trunc), stats)
        return stats


def vector_worker_loop(actor_id: int, cfg: ApexConfig,
                       family: VectorDQNWorkerFamily, chunk_queue,
                       param_queue, stat_queue, stop_event) -> None:
    """Vector counterpart of :func:`apex_tpu.actors.pool.worker_loop`: the
    same lifecycle (interruptible first-publish wait, CONFLATE param polls,
    chunk backpressure, clean shutdown) over B env slots."""
    import jax

    key = jax.random.key(family.seeds[0])
    version, params = 0, None
    while True:                                  # block for first publish
        if stop_event.is_set():
            family.close()
            return
        try:
            version, params = param_queue.get(timeout=0.5)
            break
        except queue_lib.Empty:
            continue

    # poll cadence in VECTOR steps so staleness in env frames matches the
    # scalar worker's update_interval
    poll_every = max(1, math.ceil(cfg.actor.update_interval / family.n_envs))
    steps_since_poll = 0
    family.reset_all()

    while not stop_event.is_set():
        steps_since_poll += 1
        if steps_since_poll >= poll_every:
            steps_since_poll = 0
            try:
                while True:                      # keep only the newest
                    version, params = param_queue.get_nowait()
            except queue_lib.Empty:
                pass

        key, akey = jax.random.split(key)
        for stat in family.step_all(params, akey):
            stat.param_version = version
            try:
                stat_queue.put_nowait(stat)
            except queue_lib.Full:
                pass

        for msg in family.poll_msgs():
            chunk_queue.put(("chunk", actor_id, msg))     # blocks when full

    family.close()


def worker_slots(cfg: ApexConfig, actor_id: int):
    """Pure slot derivation for one vector worker: ``(slot_ids, seeds,
    epsilons)``.  The ladder spans the WHOLE fleet
    (``n_actors * n_envs_per_actor`` slots) and worker ``i`` owns the
    contiguous band ``[i*B, (i+1)*B)`` — seeds match what a fleet of scalar
    workers with those global ids would use."""
    from apex_tpu.actors.pool import actor_epsilons

    b = cfg.actor.n_envs_per_actor
    total = cfg.actor.n_actors * b
    ladder = actor_epsilons(total, cfg.actor.eps_base, cfg.actor.eps_alpha)
    slot_ids = list(range(actor_id * b, (actor_id + 1) * b))
    seeds = [cfg.env.seed + 1000 * (s + 1) for s in slot_ids]
    return slot_ids, seeds, ladder[slot_ids]


def vector_worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                       chunk_queue, param_queue, stat_queue, stop_event,
                       epsilon: float, chunk_transitions: int) -> None:
    """Process body wired through :class:`~apex_tpu.actors.pool.ActorPool`'s
    scalar ``worker_fn`` signature: ``epsilon`` is ignored — the family
    re-derives its slots' epsilons from the GLOBAL ladder
    (:func:`worker_slots`) so the fleet's exploration spectrum is identical
    whether slots are processes or vector lanes."""
    slot_ids, seeds, epsilons = worker_slots(cfg, actor_id)
    family = VectorDQNWorkerFamily(
        cfg, model_spec, seeds=seeds, slot_ids=slot_ids, epsilons=epsilons,
        chunk_transitions=chunk_transitions)
    vector_worker_loop(actor_id, cfg, family, chunk_queue, param_queue,
                       stat_queue, stop_event)


vector_worker_main.is_vector = True     # ActorPool guard marker
