"""Recurrent (R2D2) actor worker family.

Plugs stateful acting into the family-agnostic
:func:`apex_tpu.actors.pool.worker_loop` — same continuous exploration,
conflating param queues, bounded chunk backpressure, and epsilon ladder as
the DQN/AQL families.  What's different here is WHAT ships: overlapping
fixed-length sequences with the policy's stored recurrent state at each
sequence start and acting-time insert priorities
(:class:`apex_tpu.training.r2d2.SequenceBuilder`), grouped ``group``
sequences per message so every message has one fixed shape (no learner
retrace).

The recurrent carry is worker-local state: it threads through the episode,
resets at boundaries, and only its stride-aligned snapshots cross to the
host (the builder's ``needs_carry`` gate).
"""

from __future__ import annotations

import numpy as np

from apex_tpu.config import ApexConfig


def sequence_message(seqs: list[dict]) -> dict:
    """Stack ``group`` drained sequences into one fixed-shape pool message.
    ``n_trans`` counts REAL steps (mask sum) so the learner's
    transition-denominated warmup/ratio gates stay meaningful."""
    prios = np.stack([s.pop("priority") for s in seqs])
    payload = {k: np.stack([s[k] for s in seqs]) for k in seqs[0]}
    return {"payload": payload, "priorities": prios,
            "n_trans": int(sum(int(s["mask"].sum()) for s in seqs))}


class R2D2WorkerFamily:
    """Recurrent acting/recording hooks for ``worker_loop``."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seed: int,
                 group: int):
        import jax

        from apex_tpu.envs.registry import make_env
        from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                               make_recurrent_policy_fn)
        from apex_tpu.training.r2d2 import SequenceBuilder

        self.seed = seed
        self.env = make_env(cfg.env.env_id, cfg.env, seed=seed,
                            max_episode_steps=cfg.actor.max_episode_length)
        self.model = RecurrentDuelingDQN(**model_spec)
        self.policy = jax.jit(make_recurrent_policy_fn(self.model))
        rc = cfg.r2d2
        self.builder = SequenceBuilder(rc.burn_in, rc.unroll,
                                       cfg.learner.n_steps,
                                       cfg.learner.gamma, stride=rc.stride)
        self.group = group
        self.carry = self.model.initial_state(1)
        self._ready: list[dict] = []

    def begin_episode(self, obs) -> None:
        self.carry = self.model.initial_state(1)

    def step(self, params, obs, epsilon: float, key):
        import jax.numpy as jnp
        obs_np = np.asarray(obs)
        if self.builder.needs_carry:
            cc = np.asarray(self.carry[0][0])
            ch = np.asarray(self.carry[1][0])
        else:
            cc = ch = None
        actions, q, self.carry = self.policy(params, obs_np[None],
                                             self.carry,
                                             jnp.float32(epsilon), key)
        action = int(actions[0])
        next_obs, reward, term, trunc, _ = self.env.step(action)
        self.builder.add_step(obs_np, action, float(reward), bool(term),
                              cc, ch, q_values=np.asarray(q[0]))
        if term or trunc:
            self.builder.end_episode(truncated=bool(trunc and not term))
            self._ready.extend(self.builder.drain())
        return next_obs, float(reward), bool(term), bool(trunc)

    def poll_msgs(self) -> list[dict]:
        out = []
        while len(self._ready) >= self.group:
            take = self._ready[:self.group]
            self._ready = self._ready[self.group:]
            out.append(sequence_message(take))
        return out


def r2d2_worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                     chunk_queue, param_queue, stat_queue, stop_event,
                     epsilon: float, chunk_transitions: int) -> None:
    """R2D2 worker process body; ``chunk_transitions`` is reused as the
    sequence GROUP per message (the pool passes it through verbatim)."""
    from apex_tpu.actors.pool import worker_loop

    family = R2D2WorkerFamily(cfg, model_spec,
                              seed=cfg.env.seed + 1000 * (actor_id + 1),
                              group=chunk_transitions)
    worker_loop(actor_id, cfg, family, chunk_queue, param_queue, stat_queue,
                stop_event, epsilon)
