"""Recurrent (R2D2) actor worker family.

Plugs stateful acting into the family-agnostic
:func:`apex_tpu.actors.pool.worker_loop` — same continuous exploration,
conflating param queues, bounded chunk backpressure, and epsilon ladder as
the DQN/AQL families.  What's different here is WHAT ships: overlapping
fixed-length sequences with the policy's stored recurrent state at each
sequence start and acting-time insert priorities
(:class:`apex_tpu.training.r2d2.SequenceBuilder`), grouped ``group``
sequences per message so every message has one fixed shape (no learner
retrace).

The recurrent carry is worker-local state: it threads through the episode,
resets at boundaries, and only its stride-aligned snapshots cross to the
host (the builder's ``needs_carry`` gate).
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import ApexConfig


def sequence_message(seqs: list[dict]) -> dict:
    """Stack ``group`` drained sequences into one fixed-shape pool message.
    ``n_trans`` sums the sequences' ``n_new`` (env steps NEW to each
    sequence vs its overlapping predecessors — every real step counts
    exactly once per episode), keeping the learner's
    transition-denominated warmup/ratio gates honest despite the stride
    overlap."""
    prios = np.stack([s.pop("priority") for s in seqs])
    n_new = sum(s.pop("n_new") for s in seqs)
    payload = {k: np.stack([s[k] for s in seqs]) for k in seqs[0]}
    return {"payload": payload, "priorities": prios, "n_trans": int(n_new)}


def pooled_sequence_message(seqs: list[dict]) -> dict:
    """Pack ``group`` drained POOLED sequences (SequenceBuilder with
    ``pooled=True``) into one fixed-shape message for
    :meth:`apex_tpu.replay.seq_pool.SequenceFramePoolReplay.add`.

    Frame economy: each referenced frame ships ONCE.  Windows over the
    same episode share that episode's frame array (``ep_frames``), so the
    packer ships the union coverage ``[min start, max end)`` per episode
    — the overlap between consecutive windows (R2D2's stride < t_total)
    costs nothing within a message; only window overlap ACROSS message
    boundaries is reshipped (t_total - stride rows per boundary,
    amortized by the group size).

    Fixed shapes, variable fill: ``frames`` is ``[G*T + 1, D]`` with
    ``n_frames`` real rows — row 0 is the all-zero frame every padded
    sequence position references, and rows past ``n_frames`` stay zero
    (the pool redirects them onto row 0's slot: identical duplicate
    writes).  ``n_trans`` sums ``n_new`` exactly as the stacked message
    does."""
    g = len(seqs)
    t_total = seqs[0]["action"].shape[0]
    frame_shape = seqs[0]["ep_frames"].shape[1:]
    d = int(np.prod(frame_shape))
    kf_max = g * t_total + 1
    prios = np.stack([s.pop("priority") for s in seqs])
    n_new = sum(s.pop("n_new") for s in seqs)

    # union coverage per distinct episode array (identity keyed: the
    # builder hands every window over one episode the SAME ndarray)
    episodes: dict[int, list] = {}
    for s in seqs:
        k = id(s["ep_frames"])
        e = episodes.get(k)
        if e is None:
            episodes[k] = [s["ep_frames"], s["start"], s["end"]]
        else:
            e[1] = min(e[1], s["start"])
            e[2] = max(e[2], s["end"])

    frames = np.zeros((kf_max, d), seqs[0]["ep_frames"].dtype)
    base: dict[int, int] = {}
    off = 1                          # row 0 = shared zero pad frame
    for k, (arr, lo, hi) in episodes.items():
        n = hi - lo
        frames[off:off + n] = arr[lo:hi].reshape(n, d)
        base[k] = off - lo           # message row of episode frame `lo`
        off += n
    # off <= kf_max here: coverage is <= t_total rows per sequence, which
    # SequenceBuilder guarantees at layout selection (stride <= t_total is
    # a ValueError for pooled builders — survives `python -O`, where a
    # pack-time assert would vanish)

    obs_ref = np.zeros((g, t_total), np.int32)
    for i, s in enumerate(seqs):
        ln = s["end"] - s["start"]
        b = base[id(s.pop("ep_frames"))]
        obs_ref[i, :ln] = b + s.pop("start") + np.arange(ln, dtype=np.int32)
        s.pop("end")                 # padded tail keeps ref 0 (zero row)

    payload = dict(
        frames=frames, n_frames=np.int32(off), n_seqs=np.int32(g),
        obs_ref=obs_ref,
        **{k: np.stack([s[k] for s in seqs]) for k in seqs[0]})
    return {"payload": payload, "priorities": prios, "n_trans": int(n_new)}


def drain_grouped(ready: list[dict], group: int,
                  message_fn=sequence_message) -> list[dict]:
    """THE one group-batching drain: pop full groups of ``group``
    sequences off ``ready`` (in place) as fixed-shape messages; partial
    groups stay buffered for the next drain.  Shared by the scalar and
    vector worker families and the single-process driver.
    ``message_fn`` picks the layout: :func:`sequence_message` (stacked)
    or :func:`pooled_sequence_message` (frame-dedup pool).

    Each message is born with its lineage span ("sealed" hop), exactly
    like the frame-chunk families' ``drain_builder_chunks`` — so the
    recurrent family is visible in the merged fleet timeline too.  The
    span rides message METADATA beside the payload, never inside it
    (the learner's sequence-batch shapes and the obs-plane bit-parity
    discipline both depend on that)."""
    from apex_tpu.obs import spans as obs_spans

    stamped = obs_spans.enabled()
    out = []
    while len(ready) >= group:
        take, ready[:] = ready[:group], ready[group:]
        msg = message_fn(take)
        if stamped:
            msg[obs_spans.SPAN_KEY] = [obs_spans.new_span(hop="sealed")]
        out.append(msg)
    return out


class R2D2WorkerFamily:
    """Recurrent acting/recording hooks for ``worker_loop``."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seed: int,
                 group: int):
        import jax

        from apex_tpu.envs.registry import make_env
        from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                               make_recurrent_policy_fn)
        from apex_tpu.training.r2d2 import (SequenceBuilder,
                                            r2d2_uses_frame_pool)

        self.seed = seed
        self.env = make_env(cfg.env.env_id, cfg.env, seed=seed,
                            max_episode_steps=cfg.actor.max_episode_length)
        self.model = RecurrentDuelingDQN(**model_spec)
        self.policy = jax.jit(make_recurrent_policy_fn(self.model))
        rc = cfg.r2d2
        pooled = r2d2_uses_frame_pool(cfg, self.env.observation_space.shape)
        self.message_fn = (pooled_sequence_message if pooled
                           else sequence_message)
        self.builder = SequenceBuilder(rc.burn_in, rc.unroll,
                                       cfg.learner.n_steps,
                                       cfg.learner.gamma, stride=rc.stride,
                                       pooled=pooled)
        self.group = group
        self.carry = self.model.initial_state(1)
        self._ready: list[dict] = []

    def begin_episode(self, obs) -> None:
        self.carry = self.model.initial_state(1)

    def step(self, params, obs, epsilon: float, key):
        import jax.numpy as jnp
        obs_np = np.asarray(obs)
        if self.builder.needs_carry:
            cc = np.asarray(self.carry[0][0])
            ch = np.asarray(self.carry[1][0])
        else:
            cc = ch = None
        actions, q, self.carry = self.policy(params, obs_np[None],
                                             self.carry,
                                             jnp.float32(epsilon), key)
        action = int(actions[0])
        next_obs, reward, term, trunc, _ = self.env.step(action)
        self.builder.add_step(obs_np, action, float(reward), bool(term),
                              cc, ch, q_values=np.asarray(q[0]))
        if term or trunc:
            self.builder.end_episode(truncated=bool(trunc and not term))
            self._ready.extend(self.builder.drain())
        return next_obs, float(reward), bool(term), bool(trunc)

    def poll_msgs(self) -> list[dict]:
        return drain_grouped(self._ready, self.group, self.message_fn)


def r2d2_worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                     chunk_queue, param_queue, stat_queue, stop_event,
                     epsilon: float, chunk_transitions: int) -> None:
    """R2D2 worker process body; ``chunk_transitions`` is reused as the
    sequence GROUP per message (the pool passes it through verbatim)."""
    from apex_tpu.actors.pool import worker_loop

    family = R2D2WorkerFamily(cfg, model_spec,
                              seed=cfg.env.seed + 1000 * (actor_id + 1),
                              group=chunk_transitions)
    worker_loop(actor_id, cfg, family, chunk_queue, param_queue, stat_queue,
                stop_event, epsilon)


class VectorR2D2WorkerFamily:
    """B-env recurrent acting: ONE batched policy call advances B carries
    ``[B, H]`` in lockstep; per-slot SequenceBuilders cut overlapping
    windows, and a slot's carry row zeroes on its episode reset.  Built on
    :class:`apex_tpu.actors.vector.VectorFamilyBase` for the slot ladder /
    accounting / auto-reset machinery every vector family shares."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seeds, slot_ids,
                 epsilons, group: int):
        import jax

        from apex_tpu.actors.vector import VectorFamilyBase
        from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                               make_recurrent_policy_fn)
        from apex_tpu.training.r2d2 import (SequenceBuilder,
                                            r2d2_uses_frame_pool)

        # composition over inheritance for the base: __init__ calls
        # _make_env before our model exists, so wire hooks explicitly
        class _Base(VectorFamilyBase):
            def _make_env(base, seed):
                from apex_tpu.envs.registry import make_env
                return make_env(cfg.env.env_id, cfg.env, seed=seed,
                                max_episode_steps=(
                                    cfg.actor.max_episode_length))

            def _on_reset(base, i, obs):
                self._obs[i] = np.asarray(obs)
                c, h = self.carry
                self.carry = (c.at[i].set(0.0), h.at[i].set(0.0))

        self._obs: list = [None] * len(list(seeds))
        self.base = _Base(cfg, seeds, slot_ids, epsilons)
        self.model = RecurrentDuelingDQN(**model_spec)
        self.policy = jax.jit(make_recurrent_policy_fn(self.model))
        self.carry = self.model.initial_state(self.base.n_envs)
        rc = cfg.r2d2
        pooled = r2d2_uses_frame_pool(
            cfg, self.base.envs[0].observation_space.shape)
        self.message_fn = (pooled_sequence_message if pooled
                           else sequence_message)
        self.builders = [
            SequenceBuilder(rc.burn_in, rc.unroll, cfg.learner.n_steps,
                            cfg.learner.gamma, stride=rc.stride,
                            pooled=pooled)
            for _ in range(self.base.n_envs)]
        self.group = group
        self._ready: list[dict] = []

    # the recurrent carry [B, H] advances in lockstep through ONE batched
    # call, so this family runs the serial interleave regardless of
    # ActorConfig.double_buffer (a group split would also split the carry)
    double_buffer = False

    # base delegation (vector_worker_loop drives these)
    @property
    def seeds(self):
        return self.base.seeds

    @property
    def n_envs(self):
        return self.base.n_envs

    @property
    def phase(self):
        return self.base.phase

    @property
    def gap(self):
        return self.base.gap

    def reset_all(self) -> None:
        self.base.reset_all()

    def close(self) -> None:
        self.base.close()

    def step_all(self, params, key) -> list:
        import jax.numpy as jnp

        obs = np.stack(self._obs)
        need = [b.needs_carry for b in self.builders]
        if any(need):           # ONE batched device->host carry transfer
            cc_all = np.asarray(self.carry[0])
            ch_all = np.asarray(self.carry[1])
        self.gap.about_to_dispatch()
        actions, q, self.carry = self.policy(
            params, obs, self.carry,
            jnp.asarray(self.base._current_eps()), key)
        self.gap.dispatch_returned()
        with self.phase.phase("policy_wait"):
            actions, q = np.asarray(actions), np.asarray(q)

        stats: list = []
        env_t0 = time.perf_counter()
        for i, env in enumerate(self.base.envs):
            a = int(actions[i])
            next_obs, reward, term, trunc, _ = env.step(a)
            self.builders[i].add_step(
                obs[i], a, float(reward), bool(term),
                cc_all[i] if need[i] else None,
                ch_all[i] if need[i] else None,
                q_values=q[i])
            if term or trunc:
                self.builders[i].end_episode(
                    truncated=bool(trunc and not term))
                self._ready.extend(self.builders[i].drain())
            else:
                self._obs[i] = np.asarray(next_obs)
            # on done: auto-reset calls _on_reset (obs + carry-row zero)
            self.base._finish_step(i, float(reward), bool(term or trunc),
                                   stats)
        self.phase.add("env_step", time.perf_counter() - env_t0)
        return stats

    def poll_msgs(self) -> list[dict]:
        return drain_grouped(self._ready, self.group, self.message_fn)


def vector_r2d2_worker_main(actor_id: int, cfg: ApexConfig,
                            model_spec: dict, chunk_queue, param_queue,
                            stat_queue, stop_event, epsilon: float,
                            chunk_transitions: int) -> None:
    """B-env recurrent worker body (``epsilon`` ignored: slots re-derive
    theirs from the global ladder, like every vector family)."""
    from apex_tpu.actors.vector import vector_worker_loop, worker_slots

    slot_ids, seeds, epsilons = worker_slots(cfg, actor_id)
    family = VectorR2D2WorkerFamily(cfg, model_spec, seeds=seeds,
                                    slot_ids=slot_ids, epsilons=epsilons,
                                    group=chunk_transitions)
    vector_worker_loop(actor_id, cfg, family, chunk_queue, param_queue,
                       stat_queue, stop_event)


vector_r2d2_worker_main.is_vector = True     # ActorPool guard marker
