"""AQL actor worker family (reference ``batchrecoder_AQL.py``, C9).

Plugs the proposal+Q acting step into the family-agnostic
:func:`apex_tpu.actors.pool.worker_loop` — same continuous exploration,
conflating param queues, bounded chunk backpressure, and epsilon ladder as
the DQN family — shipping 1-step transitions that carry the ``a_mu``
candidate set (``memory.py:364-391``) with acting-time TD priorities.

The reference's AQL recorder re-adds each transition ``len(state)`` times by
a loop quirk (``batchrecoder_AQL.py:121-123``); here every transition ships
exactly once.
"""

from __future__ import annotations

import numpy as np

from apex_tpu.actors.vector import VectorChunkFamilyBase, VectorFamilyBase
from apex_tpu.config import ApexConfig


class AQLWorkerFamily:
    """AQL acting/recording hooks for ``worker_loop``."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seed: int,
                 chunk_transitions: int):
        import jax

        from apex_tpu.envs.registry import make_env
        from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
        from apex_tpu.training.aql import AQLTransitionBuilder

        self.seed = seed
        self.env = make_env(cfg.env.env_id, cfg.env, seed=seed,
                            max_episode_steps=cfg.actor.max_episode_length)
        self.policy = jax.jit(make_aql_policy_fn(AQLNetwork(**model_spec)))
        self.builder = AQLTransitionBuilder(cfg.learner.gamma)
        self.chunk_transitions = chunk_transitions

    def begin_episode(self, obs) -> None:
        pass                        # 1-step transitions: no episode state

    def step(self, params, obs, epsilon: float, key):
        import jax.numpy as jnp
        obs_np = np.asarray(obs)
        actions, idx, a_mu, q = self.policy(params, obs_np[None],
                                            jnp.float32(epsilon), key)
        next_obs, reward, term, trunc, _ = self.env.step(
            np.asarray(actions[0]))
        self.builder.add_step(obs_np, int(idx[0]), float(reward),
                              np.asarray(next_obs), np.asarray(a_mu[0]),
                              np.asarray(q[0]), bool(term), bool(trunc))
        return next_obs, float(reward), bool(term), bool(trunc)

    def poll_msgs(self) -> list[dict]:
        out = []
        while len(self.builder) >= self.chunk_transitions:
            batch, prios = self.builder.drain(self.chunk_transitions)
            out.append({"payload": batch, "priorities": prios,
                        "n_trans": len(prios)})
        return out


class AQLPixelWorkerFamily:
    """Frame-pool AQL acting for image observations: un-stacked env +
    :class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder` shipping the
    ``a_mu`` candidate set as a per-transition sidecar (``extra_shapes``),
    so the learner's replay dedups frames instead of storing 2S stacked
    copies per transition (VERDICT r3 weak #4).  The recorded ``action`` is
    the candidate INDEX — exactly what the fused AQL loss indexes ``a_mu``
    with — and the acting-time priority reuses the chunk builder's
    ``|ret + disc*max q' - q[idx]|``, which is the same formula over
    candidate scores."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seed: int,
                 chunk_transitions: int):
        import jax

        from apex_tpu.envs.registry import make_env, unstacked_env_spec
        from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
        from apex_tpu.replay.frame_chunks import FrameChunkBuilder

        self.seed = seed
        self.env = make_env(cfg.env.env_id, cfg.env, seed=seed,
                            max_episode_steps=cfg.actor.max_episode_length,
                            stack_frames=False)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            self.env, cfg.env)
        model = AQLNetwork(**model_spec)
        self.policy = jax.jit(make_aql_policy_fn(model))
        a_dim = 1 if model.discrete else model.action_dim
        self.builder = FrameChunkBuilder(
            cfg.learner.n_steps, cfg.learner.gamma, frame_stack, frame_shape,
            chunk_transitions=chunk_transitions, frame_dtype=frame_dtype,
            extra_shapes={"a_mu": (model.total_sample, a_dim)})

    def begin_episode(self, obs) -> None:
        self.builder.begin_episode(obs)

    def step(self, params, obs, epsilon: float, key):
        import jax.numpy as jnp
        stack = self.builder.current_stack()
        actions, idx, a_mu, q = self.policy(params, stack[None],
                                            jnp.float32(epsilon), key)
        next_obs, reward, term, trunc, _ = self.env.step(
            np.asarray(actions[0]))
        self.builder.add_step(int(idx[0]), float(reward), np.asarray(q[0]),
                              next_obs, bool(term), bool(trunc),
                              extras={"a_mu": np.asarray(a_mu[0])})
        return next_obs, float(reward), bool(term), bool(trunc)

    def poll_msgs(self) -> list[dict]:
        from apex_tpu.actors.pool import drain_builder_chunks
        return drain_builder_chunks(self.builder)


def aql_worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                    chunk_queue, param_queue, stat_queue, stop_event,
                    epsilon: float, chunk_transitions: int) -> None:
    from apex_tpu.actors.pool import worker_loop

    cls = (AQLPixelWorkerFamily if model_spec.get("obs_is_image")
           else AQLWorkerFamily)
    family = cls(cfg, model_spec,
                 seed=cfg.env.seed + 1000 * (actor_id + 1),
                 chunk_transitions=chunk_transitions)
    worker_loop(actor_id, cfg, family, chunk_queue, param_queue, stat_queue,
                stop_event, epsilon)


class VectorAQLWorkerFamily(VectorFamilyBase):
    """B-env AQL acting: one batched propose+score per half-group under
    the base's double-buffered step, per-slot transition builders — the
    AQL counterpart of
    :class:`apex_tpu.actors.vector.VectorDQNWorkerFamily`, sharing its
    scaffolding through :class:`~apex_tpu.actors.vector.VectorFamilyBase`
    and driven by the same family-agnostic ``vector_worker_loop``."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seeds,
                 slot_ids, epsilons, chunk_transitions: int):
        from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
        from apex_tpu.training.aql import AQLTransitionBuilder

        super().__init__(cfg, seeds, slot_ids, epsilons)
        # in-place obs assembly: the policy consumes contiguous slices of
        # one preallocated [B, *obs] buffer instead of a per-step np.stack
        space = self.envs[0].observation_space
        self._acting = np.zeros((self.n_envs,) + tuple(space.shape),
                                space.dtype)
        self.policy = self._grouped_policy(
            make_aql_policy_fn(AQLNetwork(**model_spec)))
        self.builders = [AQLTransitionBuilder(cfg.learner.gamma)
                         for _ in range(self.n_envs)]
        self.chunk_transitions = chunk_transitions

    def _make_env(self, seed: int):
        from apex_tpu.envs.registry import make_env
        return make_env(self.cfg.env.env_id, self.cfg.env, seed=seed,
                        max_episode_steps=self.cfg.actor.max_episode_length)

    def _on_reset(self, i: int, obs) -> None:
        self._acting[i] = np.asarray(obs)

    def _policy_group(self, params, sl, eps, key, group: int):
        return self.policy(params, self._acting[sl], eps, key, group)

    def _step_group(self, sl, host, stats) -> None:
        actions, idx, a_mu, q = host
        for j, i in enumerate(range(sl.start, sl.stop)):
            # the builder keeps obs beyond this step; copy it out of the
            # in-place buffer before the row is overwritten
            obs = np.array(self._acting[i])
            next_obs, reward, term, trunc, _ = self.envs[i].step(actions[j])
            self.builders[i].add_step(obs, int(idx[j]), float(reward),
                                      np.asarray(next_obs), a_mu[j], q[j],
                                      bool(term), bool(trunc))
            self._acting[i] = np.asarray(next_obs)
            self._finish_step(i, float(reward), bool(term or trunc), stats)

    def poll_msgs(self) -> list[dict]:
        out = []
        for builder in self.builders:
            while len(builder) >= self.chunk_transitions:
                batch, prios = builder.drain(self.chunk_transitions)
                out.append({"payload": batch, "priorities": prios,
                            "n_trans": len(prios)})
        return out


class VectorAQLPixelWorkerFamily(VectorChunkFamilyBase):
    """B-env frame-pool AQL acting: the vector counterpart of
    :class:`AQLPixelWorkerFamily` — batched propose+score over each
    half-group's slice of the in-place acting buffer, per-slot chunk
    builders with ``a_mu`` sidecars.  Env construction, builder resets,
    acting-buffer binding, and chunk draining come from
    :class:`~apex_tpu.actors.vector.VectorChunkFamilyBase`."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seeds,
                 slot_ids, epsilons, chunk_transitions: int):
        from apex_tpu.envs.registry import unstacked_env_spec
        from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
        from apex_tpu.replay.frame_chunks import FrameChunkBuilder

        super().__init__(cfg, seeds, slot_ids, epsilons)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            self.envs[0], cfg.env)
        model = AQLNetwork(**model_spec)
        self.policy = self._grouped_policy(make_aql_policy_fn(model))
        a_dim = 1 if model.discrete else model.action_dim
        self.builders = [
            FrameChunkBuilder(
                cfg.learner.n_steps, cfg.learner.gamma, frame_stack,
                frame_shape, chunk_transitions=chunk_transitions,
                frame_dtype=frame_dtype,
                extra_shapes={"a_mu": (model.total_sample, a_dim)})
            for _ in range(self.n_envs)
        ]
        self._bind_acting_buffer()

    def _policy_group(self, params, sl, eps, key, group: int):
        return self.policy(params, self._acting[sl], eps, key, group)

    def _step_group(self, sl, host, stats) -> None:
        actions, idx, a_mu, q = host
        for j, i in enumerate(range(sl.start, sl.stop)):
            next_obs, reward, term, trunc, _ = self.envs[i].step(actions[j])
            self.builders[i].add_step(int(idx[j]), float(reward), q[j],
                                      next_obs, bool(term), bool(trunc),
                                      extras={"a_mu": a_mu[j]})
            self._finish_step(i, float(reward), bool(term or trunc), stats)


def vector_aql_worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                           chunk_queue, param_queue, stat_queue, stop_event,
                           epsilon: float, chunk_transitions: int) -> None:
    """Vector AQL process body (``epsilon`` ignored — slots re-derive
    theirs from the global ladder, like the DQN vector body)."""
    from apex_tpu.actors.vector import vector_worker_loop, worker_slots

    slot_ids, seeds, epsilons = worker_slots(cfg, actor_id)
    cls = (VectorAQLPixelWorkerFamily if model_spec.get("obs_is_image")
           else VectorAQLWorkerFamily)
    family = cls(
        cfg, model_spec, seeds=seeds, slot_ids=slot_ids, epsilons=epsilons,
        chunk_transitions=chunk_transitions)
    vector_worker_loop(actor_id, cfg, family, chunk_queue, param_queue,
                       stat_queue, stop_event)


vector_aql_worker_main.is_vector = True  # ActorPool guard marker
