"""In-host actor pool: worker processes feeding the learner's device replay.

Capability parity with the reference's ``BatchRecorder``/``Worker``
(``batchrecorder.py:79-152``) redesigned for the TPU topology:

* Each worker is an ``mp.Process`` with its own env, its own CPU-jitted
  policy, and a :class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder` —
  transitions ship as fixed-shape frame chunks ready for device ingest,
  priorities already computed from acting-time Q-values.
* Per-worker exploration ladder ``eps_base ** (1 + i/(N-1) * eps_alpha)``
  (``batchrecorder.py:121``, the Ape-X schedule).
* Unlike the reference's synchronous task rounds (``record_batch`` +
  ``queue.join`` — and the eager-call quirk at ``ApeX.py:94-97`` that made
  acting and learning fully sequential), workers run CONTINUOUSLY and the
  learner drains a bounded chunk queue — acting and the TPU step overlap.
* Param distribution is latest-wins, version-stamped: the learner puts
  ``(version, params)`` on per-worker depth-2 queues; workers drain and keep
  the newest (the reference's SUB+CONFLATE semantics, ``actor.py:40-49``),
  polling every ``update_interval`` env steps (``actor.py:97-103``).

Workers are forced onto the CPU JAX platform: the image's sitecustomize
would otherwise dial the single-client TPU tunnel from every spawned
process and deadlock.  The pool clears ``PALLAS_AXON_POOL_IPS`` and sets
``JAX_PLATFORMS=cpu`` in the parent's environment around ``Process.start``
so children inherit it before their interpreter boots.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue as queue_lib
import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from apex_tpu.config import ApexConfig


def actor_epsilons(n: int, eps_base: float = 0.4,
                   eps_alpha: float = 7.0) -> np.ndarray:
    """The Ape-X per-actor exploration ladder (``batchrecorder.py:121``)."""
    if n == 1:
        return np.asarray([eps_base], np.float64)
    i = np.arange(n, dtype=np.float64)
    return eps_base ** (1.0 + i / (n - 1) * eps_alpha)


@dataclass
class EpisodeStat:
    actor_id: int
    reward: float
    length: int
    param_version: int = 0          # staleness observability
    # stats the worker dropped on a full stat_queue since its LAST
    # successful put (the drop itself stays lossy — bounded queue — but
    # the loss is now counted, so reward/staleness accounting is
    # auditably incomplete rather than silently incomplete)
    dropped_stats: int = 0


@dataclass
class ActorTimingStat:
    """Periodic actor-plane observability message (one per worker every
    ``ActorConfig.timing_interval`` vector steps): where the worker's wall
    time went — policy-wait vs env-step vs drain — plus its frames/s and
    the host gap between policy dispatches.  Ships on the same stat queue
    as :class:`EpisodeStat`; the learner's stats drain dispatches on type
    (``training/apex.py``) and the e2e bench aggregates these into its
    ``actor_plane`` section."""

    actor_id: int                   # worker index (process), not env slot
    frames_per_sec: float           # env frames/s over the window
    policy_wait_frac: float         # blocking materialization of outputs
    env_step_frac: float            # env.step + builder recording
    drain_frac: float               # chunk poll + queue put (backpressure)
    dispatch_gap_ms_p50: float      # host gap between policy dispatches
    vector_steps: int               # window length in vector steps
    double_buffer: bool             # mode the worker is running
    dropped_stats: int = 0          # same carry semantics as EpisodeStat


def drain_builder_chunks(builder) -> list[dict]:
    """FrameChunkBuilder chunks -> pool messages.  THE one place the chunk
    message shape is defined — every builder-based family (DQN scalar and
    vector, pixel AQL scalar and vector) drains through here.  Each
    message is born with its lineage span ("sealed" hop — obs plane,
    :mod:`apex_tpu.obs.spans`); the timestamps ride message METADATA
    beside the payload, never inside it."""
    from apex_tpu.obs import spans as obs_spans

    stamped = obs_spans.enabled()
    out = []
    for chunk in builder.poll():
        msg = {"payload": chunk,
               "priorities": chunk.pop("priorities"),
               "n_trans": int(chunk["n_trans"])}
        if stamped:
            msg[obs_spans.SPAN_KEY] = [obs_spans.new_span(hop="sealed")]
        out.append(msg)
    return out


class DQNWorkerFamily:
    """DQN acting/recording hooks for :func:`worker_loop` (reference
    ``Worker.run``, ``batchrecorder.py:79-98``): epsilon-greedy over the
    builder's acting stack, frame-chunk emission."""

    def __init__(self, cfg: ApexConfig, model_spec: dict, seed: int,
                 chunk_transitions: int):
        import jax

        from apex_tpu.envs.registry import make_env, unstacked_env_spec
        from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
        from apex_tpu.replay.frame_chunks import FrameChunkBuilder

        self.seed = seed
        self.env = make_env(cfg.env.env_id, cfg.env, seed=seed,
                            max_episode_steps=cfg.actor.max_episode_length,
                            stack_frames=False)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            self.env, cfg.env)
        self.policy = jax.jit(make_policy_fn(DuelingDQN(**model_spec)))
        self.builder = FrameChunkBuilder(
            cfg.learner.n_steps, cfg.learner.gamma, frame_stack, frame_shape,
            chunk_transitions=chunk_transitions, frame_dtype=frame_dtype)

    def begin_episode(self, obs) -> None:
        self.builder.begin_episode(obs)

    def step(self, params, obs, epsilon: float, key):
        import jax.numpy as jnp
        stack = self.builder.current_stack()
        actions, q = self.policy(params, stack[None], jnp.float32(epsilon),
                                 key)
        action = int(actions[0])
        next_obs, reward, term, trunc, _ = self.env.step(action)
        self.builder.add_step(action, float(reward), np.asarray(q[0]),
                              next_obs, bool(term), bool(trunc))
        return next_obs, float(reward), bool(term), bool(trunc)

    def poll_msgs(self) -> list[dict]:
        return drain_builder_chunks(self.builder)


def worker_loop(actor_id: int, cfg: ApexConfig, family, chunk_queue,
                param_queue, stat_queue, stop_event, epsilon: float) -> None:
    """The family-agnostic worker lifecycle: interruptible wait for the
    first publish, CONFLATE param polls every ``update_interval`` steps
    (``actor.py:97-103``), exploration-epsilon anneal, chunk shipping with
    backpressure, episode stats, clean shutdown.  The acting/recording
    specifics live in ``family`` (:class:`DQNWorkerFamily`,
    ``apex_tpu.actors.aql.AQLWorkerFamily``) — one lifecycle, N families,
    where the reference maintains near-copies (``batchrecorder.py`` vs
    ``batchrecoder_AQL.py``)."""
    import math

    import jax

    from apex_tpu.fleet.heartbeat import HeartbeatEmitter
    from apex_tpu.obs import spans as obs_spans
    from apex_tpu.obs.trace import get_ring, set_process_label

    from apex_tpu.tenancy import namespace as tenancy_ns

    key = jax.random.key(family.seed)
    env = family.env
    # tenant-qualified identity (PR 13): the worker's beats must agree
    # with the role-level wire identity (park heartbeats, chunk-arrival
    # liveness) or a tenant's actor shows up TWICE in its registry;
    # the default tenant qualifies to the bare name
    identity = tenancy_ns.qualify(tenancy_ns.current_tenant(),
                                  f"actor-{actor_id}")
    set_process_label(identity)
    ring = get_ring()
    # fleet liveness: periodic Heartbeats on the stat channel — the
    # in-host trainer and the socket learner's registry consume the same
    # message (the socket adapters expose wire counters / park state)
    beat = HeartbeatEmitter(
        identity, role="actor",
        interval_s=cfg.comms.heartbeat_interval_s,
        counters_fn=getattr(chunk_queue, "wire_counters", None),
        park_fn=getattr(param_queue, "park_state", None),
        gauges_fn=getattr(chunk_queue, "wire_gauges", None))

    def _maybe_beat(version: int) -> None:
        hb = beat.maybe_beat(version)
        if hb is not None:
            try:
                stat_queue.put_nowait(hb)
            except queue_lib.Full:
                pass                # droppable telemetry, like every stat

    version = 0
    while True:                                  # block for first publish,
        if stop_event.is_set():                  # but stay interruptible
            env.close()
            return
        _maybe_beat(version)
        try:
            version, params = param_queue.get(timeout=0.5)
            break
        except queue_lib.Empty:
            continue

    anneal = cfg.actor.eps_anneal_steps
    total_steps = 0

    def current_eps() -> float:
        if not anneal:
            return epsilon
        return epsilon + (1.0 - epsilon) * math.exp(-total_steps / anneal)

    steps_since_poll = 0
    obs, _ = env.reset(seed=family.seed)
    family.begin_episode(obs)
    ep_reward, ep_len = 0.0, 0
    dropped = 0                     # stats lost to a full queue, carried
    #                                 on the next successful put

    while not stop_event.is_set():
        steps_since_poll += 1
        if steps_since_poll >= cfg.actor.update_interval:
            steps_since_poll = 0
            try:
                while True:                      # keep only the newest
                    version, params = param_queue.get_nowait()
            except queue_lib.Empty:
                pass

        key, akey = jax.random.split(key)
        obs, reward, terminated, truncated = family.step(
            params, obs, current_eps(), akey)
        total_steps += 1
        ep_reward += reward
        ep_len += 1
        beat.tick()
        _maybe_beat(version)

        for msg in family.poll_msgs():
            beat.note_chunk()
            obs_spans.mark_send(msg, version)
            t0 = time.perf_counter()
            chunk_queue.put(("chunk", actor_id, msg))     # blocks when full
            ring.complete("chunk_put", t0, time.perf_counter() - t0,
                          track="chunk-drain")
        if terminated or truncated:
            try:
                stat_queue.put_nowait(
                    EpisodeStat(actor_id, ep_reward, ep_len, version,
                                dropped_stats=dropped))
                dropped = 0
            except queue_lib.Full:
                dropped += 1
            ep_reward, ep_len = 0.0, 0
            obs, _ = env.reset()
            family.begin_episode(obs)

    env.close()


def _worker_main(actor_id: int, cfg: ApexConfig, model_spec: dict,
                 chunk_queue: mp.Queue, param_queue: mp.Queue,
                 stat_queue: mp.Queue, stop_event, epsilon: float,
                 chunk_transitions: int) -> None:
    """DQN worker process body.  Imports (and therefore jax platform
    selection) happen in the child, under the CPU env set by the parent."""
    family = DQNWorkerFamily(cfg, model_spec,
                             seed=cfg.env.seed + 1000 * (actor_id + 1),
                             chunk_transitions=chunk_transitions)
    worker_loop(actor_id, cfg, family, chunk_queue, param_queue, stat_queue,
                stop_event, epsilon)


class ActorPool:
    """Fan-out/fan-in around N continuously-running actor workers
    (reference ``BatchRecorder``, ``batchrecorder.py:100-152``).

    ``worker_fn`` is the process body — the queue/lifecycle machinery is
    family-agnostic; the DQN body is the default and the AQL family plugs
    in its own (reference ``batchrecoder_AQL.py`` is a near-copy of
    ``batchrecorder.py`` for the same reason, solved here by injection).
    """

    def __init__(self, cfg: ApexConfig, model_spec: dict,
                 chunk_transitions: int, chunk_queue_depth: int = 64,
                 worker_fn=None, shm_slot_bytes: int | None = None):
        self.cfg = cfg
        n = cfg.actor.n_actors
        ctx = mp.get_context("spawn")
        self.chunk_queue = self._make_chunk_queue(
            cfg, chunk_queue_depth, shm_slot_bytes, ctx)
        self.stat_queue: mp.Queue = ctx.Queue(maxsize=1024)
        self.param_queues = [ctx.Queue(maxsize=2) for _ in range(n)]
        self.stop_event = ctx.Event()
        if cfg.actor.n_envs_per_actor > 1 or getattr(
                cfg.actor, "remote_policy", False):
            if worker_fn is not None and not getattr(worker_fn, "is_vector",
                                                     False):
                # silently falling back to one env/process would run a
                # 1/B-rate fleet with the wrong exploration spectrum
                raise ValueError(
                    "n_envs_per_actor > 1 requires a vectorized worker "
                    "body (vector_worker_main / vector_aql_worker_main); "
                    "this pool was built with "
                    f"{getattr(worker_fn, '__name__', worker_fn)}")
            if worker_fn is None:
                from apex_tpu.actors.vector import vector_worker_main
                worker_fn = vector_worker_main  # B envs, batched policy
        eps = actor_epsilons(n, cfg.actor.eps_base, cfg.actor.eps_alpha)
        self._ctx = ctx
        self._worker_fn = worker_fn or _worker_main
        self._worker_args = [
            (i, cfg, model_spec, self.chunk_queue, self.param_queues[i],
             self.stat_queue, self.stop_event, float(eps[i]),
             chunk_transitions)
            for i in range(n)
        ]
        self.procs = [ctx.Process(target=self._worker_fn, args=a,
                                  daemon=True) for a in self._worker_args]
        self._started = False
        self._last_params: tuple | None = None
        self.worker_deaths = 0          # cumulative respawn count
        # a worker that keeps dying is a systemic failure (bad env, import
        # error in the child), not flakiness: respawns are RATE-LIMITED to
        # this many per slot per window (anchored at the slot's last
        # respawn).  Sporadic crashes over a long run never retire a
        # healthy slot, and even a persistently-broken slot retries at a
        # bounded rate — so a cause fixed mid-run (path restored, OOM
        # relieved) recovers without intervention.
        self.max_respawns_per_slot = 5
        self.respawn_window_s = 600.0
        self._slot_respawns = [0] * n
        self._slot_last_respawn = [0.0] * n

    @staticmethod
    def _make_chunk_queue(cfg: ApexConfig, depth: int,
                          shm_slot_bytes: int | None, ctx):
        """The chunk plane: native shared-memory ring when available
        (:mod:`apex_tpu.native`), else mp.Queue.  Same bounded-queue
        backpressure either way."""
        if cfg.actor.shm_data_plane:
            from apex_tpu.native import shm_available
            if shm_available():
                from apex_tpu.native.ring import ShmChunkQueue
                slot = (cfg.actor.shm_slot_bytes
                        or shm_slot_bytes or 4 * 1024 * 1024)
                name = f"apexshm-{os.getpid()}-{ShmChunkQueue.next_id()}"
                try:
                    return ShmChunkQueue(name, slot_bytes=slot, depth=depth)
                except Exception:
                    pass      # tmpfs full / permissions: degrade to mp.Queue
        return ctx.Queue(maxsize=depth)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Spawn workers with a CPU-pinned JAX environment (module docstring)."""
        self._spawn(self.procs)
        self._started = True

    def _spawn(self, procs) -> None:
        saved = {k: os.environ.get(k)
                 for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        try:
            for p in procs:
                p.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # -- failure detection (beyond the reference: its fleets have no
    # death-handling at all — an actor crash silently shrinks the fleet
    # forever, SURVEY.md §5.3) ---------------------------------------------

    def _refresh_budget(self, i: int) -> None:
        """A full window elapsed since the slot's LAST respawn restores its
        budget (rate limit, not a lifetime cap — see __init__ comment)."""
        if (self._slot_respawns[i]
                and time.monotonic() - self._slot_last_respawn[i]
                > self.respawn_window_s):
            self._slot_respawns[i] = 0

    def dead_workers(self) -> list[int]:
        """Indices of workers that exited while the pool is live and are
        still eligible for respawn (RAPID crashers age out, see
        ``max_respawns_per_slot`` / ``respawn_window_s``)."""
        if not self._started or self.stop_event.is_set():
            return []
        out = []
        for i, p in enumerate(self.procs):
            if p.is_alive():
                continue
            self._refresh_budget(i)
            if self._slot_respawns[i] < self.max_respawns_per_slot:
                out.append(i)
        return out

    def respawn_worker(self, i: int) -> bool:
        """Replace a dead worker with a fresh process on the same slot
        (same global actor id, epsilon, seed — the fleet's exploration
        spectrum is restored, not shifted).  The newest published params
        are re-queued so the newcomer doesn't idle until the next publish.
        Returns False while the slot's rate budget is exhausted — the
        fleet runs reduced, loudly, until the window rolls over."""
        old = self.procs[i]
        if old.is_alive():
            return True
        self._refresh_budget(i)
        if self._slot_respawns[i] >= self.max_respawns_per_slot:
            return False
        old.join(timeout=0)            # reap the zombie
        self.procs[i] = self._ctx.Process(target=self._worker_fn,
                                          args=self._worker_args[i],
                                          daemon=True)
        self._spawn([self.procs[i]])
        self.worker_deaths += 1
        self._slot_respawns[i] += 1
        self._slot_last_respawn[i] = time.monotonic()
        if self._slot_respawns[i] >= self.max_respawns_per_slot:
            print(f"apex_tpu: actor slot {i} died "
                  f"{self._slot_respawns[i]}x within "
                  f"{self.respawn_window_s:.0f}s; pausing its respawns — "
                  f"running with a reduced fleet", flush=True)
        if self._last_params is not None:
            version, params = self._last_params
            self._put_latest(self.param_queues[i], version, params)
        return True

    def cleanup(self, grace_seconds: float = 10.0) -> None:
        """Stop workers (reference ``BatchRecorder.cleanup``,
        ``batchrecorder.py:148-152``).

        The chunk queue is drained CONTINUOUSLY while joining — a single
        pre-join drain would race with workers refilling it (a worker can be
        mid-``put`` or produce one more chunk before seeing the stop event)
        and the subsequent ``terminate()`` could kill a process inside
        ``Queue.put``, corrupting the queue's shared pipe."""
        self.stop_event.set()
        deadline = time.monotonic() + grace_seconds
        pending = list(self.procs)
        while pending and time.monotonic() < deadline:
            try:                       # keep unblocking producers mid-put
                while True:
                    self.chunk_queue.get_nowait()
            except queue_lib.Empty:
                pass
            pending = [p for p in pending if (p.join(timeout=0.1), p)[1]
                       .is_alive()]
        for p in pending:              # unresponsive after the grace window
            p.terminate()
            p.join(timeout=5)
        # Detach queue feeder threads: a dead child never drains its pipe, and
        # the default atexit join would hang the parent forever.
        for q in [self.chunk_queue, self.stat_queue, *self.param_queues]:
            q.cancel_join_thread()
            q.close()

    # -- data/param planes -------------------------------------------------

    def publish_params(self, version: int, params: Any) -> None:
        """Latest-wins broadcast (reference ``set_worker_weights``,
        ``batchrecorder.py:140-146``, + PUB/CONFLATE semantics)."""
        self._last_params = (version, params)
        for q in self.param_queues:
            self._put_latest(q, version, params)

    @staticmethod
    def _put_latest(q, version: int, params: Any) -> None:
        while True:      # drop the stalest entry if the depth-2 queue is full
            try:
                q.put_nowait((version, params))
                break
            except queue_lib.Full:
                try:
                    q.get_nowait()
                except queue_lib.Empty:
                    pass

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        """Drain up to ``max_chunks`` transition chunks."""
        out = []
        for _ in range(max_chunks):
            try:
                msg = self.chunk_queue.get(timeout=timeout) if timeout \
                    else self.chunk_queue.get_nowait()
            except queue_lib.Empty:
                break
            out.append(msg[2])
        return out

    def poll_stats(self) -> list[EpisodeStat]:
        out = []
        try:
            while True:
                out.append(self.stat_queue.get_nowait())
        except queue_lib.Empty:
            pass
        return out
