"""The replay shard role: sockets, heartbeats, chaos, lifecycle.

The reference ran replay as a standalone process bridging actors and the
learner with three zmq proxies (``origin_repo/replay.py:48-74``).  This
role restores that topology for the TPU port, sharded: shard ``s`` binds
ONE ROUTER at ``replay_port_base + s`` and multiplexes the three message
kinds on it —

* ``("chunk", msg)``   from actors: restricted-decode, chaos gate,
  ingest into the shard's :class:`~apex_tpu.replay_service.shard.
  ReplayShardCore`, then ack (the ack IS the sender's next credit, same
  protocol as the learner's :class:`~apex_tpu.runtime.transport.
  ChunkReceiver`; a hostile payload is counted and dropped WITHOUT an
  ack, wedging only its sender's window);
* ``("pull",)``        from the learner: reply the next pre-sampled
  batch, or ``("dry", {...})`` so the learner's round-robin moves on;
* ``("prio", seq, idx, prios)`` from the learner: apply the write-back.

Strict-order deferral: while a write-back is outstanding the core
refuses ingest (:meth:`ReplayShardCore.can_ingest` — ingest and
write-back do not commute bitwise once the ring wraps), so arriving
chunks park in a host-side inbox WITHOUT acks — the actor credit windows
backpressure exactly like the learner's bounded queue does.  A learner
that dies mid-round-trip would wedge that gate forever, so write-back
silence past ``dead_after_s`` forgives the outstanding batches (counted).

Membership: the shard ships ordinary :class:`~apex_tpu.fleet.heartbeat.
Heartbeat`\\ s (role ``"replay"``) on a plain stat channel to the
learner's ROUTER — zero new control sockets, and the learner's
:class:`~apex_tpu.fleet.registry.FleetRegistry` runs its
JOINING→ALIVE→SUSPECT→DEAD machine over shards for free (a chaos-killed
shard shows up DEAD in ``fleet_summary.json``, pinned in tests).

Chaos: ``CHAOS_SEED``/``CHAOS_SPEC`` gate a per-shard plan under the
identity ``replay-<shard_id>`` — ``kill`` fires on the chunk-ingest
index (``os._exit(137)``), ``drop_frac`` drops ingested chunks (acked,
so the loss is silent data loss, exactly what a dying shard produces).
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import ApexConfig, CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.replay_service.shard import ReplayShardCore
from apex_tpu.runtime import wire


def shard_warmup(global_warmup: int, n_shards: int) -> int:
    """Per-shard warmup: the global gate split over shards (ceil — the
    fleet never trains EARLIER than the unsharded config would)."""
    return max(1, -(-int(global_warmup) // max(1, n_shards)))


def dqn_replay_spec(cfg: ApexConfig):
    """The FramePoolReplay spec the DQN learner builds — factored out so
    the shard role and :class:`~apex_tpu.training.apex.ApexTrainer`
    cannot drift (one spec, two owners would eventually disagree on
    frame shapes)."""
    from apex_tpu.replay.base import check_hbm_budget
    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.apex import dqn_env_specs

    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    replay = FramePoolReplay(
        capacity=cfg.replay.capacity, frame_shape=frame_shape,
        frame_stack=frame_stack, frame_dtype=np.dtype(frame_dtype).name,
        alpha=cfg.replay.alpha, eps=cfg.replay.eps)
    check_hbm_budget(replay.hbm_bytes(), cfg.replay.hbm_budget_gb,
                     "replay-shard frame pool", cfg.replay.capacity)
    return replay


def build_shard_core(cfg: ApexConfig, shard_id: int,
                     family: str = "dqn") -> ReplayShardCore:
    """One shard's core from the fleet config.  ``capacity``/``warmup``
    are per shard (capacity as configured — N shards hold N x capacity;
    warmup split so the global gate is preserved)."""
    import jax

    if family != "dqn":
        raise NotImplementedError(
            f"replay service shards currently serve the dqn family only "
            f"(got {family!r}); aql/r2d2 stay on in-learner replay — see "
            f"ROADMAP.md")
    replay = dqn_replay_spec(cfg)
    n = max(1, cfg.comms.replay_shards)
    key = jax.random.key(cfg.env.seed + 977_000 + shard_id)
    return ReplayShardCore(
        replay, key,
        batch_size=cfg.learner.batch_size,
        warmup=shard_warmup(cfg.replay.warmup, n),
        beta=cfg.replay.beta, beta_anneal=cfg.replay.beta_anneal,
        n_shards=n,
        strict_order=cfg.comms.replay_strict_order,
        presample_depth=cfg.comms.replay_presample)


class _ShardChaos:
    """The replay-shard fault gate: one RNG draw per ingested chunk off
    the seeded per-identity stream (:mod:`apex_tpu.fleet.chaos`), so a
    shard's kills and drops replay exactly, run after run."""

    def __init__(self, plan):
        self.plan = plan
        self._rng = plan.rng() if plan is not None else None
        self._n = 0
        self.dropped = 0

    def on_chunk(self) -> str:
        """"ok" | "drop"; a scheduled kill never returns."""
        if self.plan is None:
            return "ok"
        i = self._n
        self._n += 1
        if self.plan.kill_at is not None and i >= self.plan.kill_at:
            from apex_tpu.fleet.chaos import _die
            _die(self.plan.identity, i)
        if self._rng.random() < self.plan.drop_frac:
            self.dropped += 1
            return "drop"
        return "ok"


class ReplayShardServer:
    """Socket loop around one :class:`ReplayShardCore` (module
    docstring).  Single-threaded on purpose: one thread owns the ROUTER,
    the jit dispatches, and the deterministic op order the strict mode
    promises."""

    def __init__(self, comms: CommsConfig, shard_id: int,
                 core: ReplayShardCore, bind_ip: str = "*",
                 heartbeat=True, snapshot_path: str | None = None,
                 snapshot_s: float | None = None):
        import zmq

        from apex_tpu.fleet.chaos import chaos_from_env

        self._zmq = zmq
        self.comms = comms
        self.shard_id = int(shard_id)
        self.core = core
        self.identity = f"replay-{shard_id}"
        self.sock = zmq.Context.instance().socket(zmq.ROUTER)
        self.sock.bind(f"tcp://{bind_ip}:{comms.replay_port_base + shard_id}")
        self.rejected = 0
        self.batches_served = 0
        self._inbox: list = []          # strict-mode deferred (ident, msg)
        self._last_wb = time.monotonic()
        # shard durability: periodic whole-state snapshots (taken only at
        # quiescent points so a restore resumes the strict lockstep
        # bit-exactly); a supervised respawn restores the newest one
        self.snapshot_path = snapshot_path
        self.snapshot_s = (comms.replay_snapshot_s if snapshot_s is None
                           else snapshot_s)
        self._last_snapshot = time.monotonic()
        self.snapshots = 0
        self.snapshot_errors = 0
        chaos = chaos_from_env()
        plan = chaos.plan_for(self.identity) if chaos is not None else None
        self.chaos = _ShardChaos(plan)
        # directional link drop (shard->learner down while actor->shard
        # stays up): this shard's outgoing replies vanish — the learner's
        # pulls arrive, the sampled batches never make it back
        self._mute = bool(plan is not None and plan.mute_replies)
        self.chaos_muted = 0
        self._hb = None
        self._hb_sender = None
        if heartbeat:
            from apex_tpu.fleet.heartbeat import HeartbeatEmitter
            from apex_tpu.runtime.transport import ChunkSender
            self._hb_sender = ChunkSender(comms, self.identity)
            self._hb = HeartbeatEmitter(
                self.identity, role="replay",
                interval_s=comms.heartbeat_interval_s,
                counters_fn=lambda: {
                    "chunks_sent": self.batches_served,
                    "acks_received": self.core.wb_applied})

    # -- message handlers ----------------------------------------------------

    def _handle_chunk(self, ident: bytes, msg: dict) -> None:
        if self.chaos.on_chunk() == "drop":
            self.sock.send_multipart([ident, b"ack"])   # silent data loss
            return
        obs_spans.stamp(msg, "shard_recv")
        if not self.core.can_ingest():
            self._inbox.append((ident, msg))            # ack withheld:
            return                                      # credit paces sender
        self.core.ingest_msg(msg)
        if self._hb is not None:
            self._hb.tick(int(msg.get("n_trans", 0)))
        self.sock.send_multipart([ident, b"ack"])

    def _drain_inbox(self) -> None:
        while self._inbox and self.core.can_ingest():
            ident, msg = self._inbox.pop(0)
            self.core.ingest_msg(msg)
            if self._hb is not None:
                self._hb.tick(int(msg.get("n_trans", 0)))
            self.sock.send_multipart([ident, b"ack"])

    def _handle_pull(self, ident: bytes, epoch: int = 0) -> None:
        forgiven = self.core.note_epoch(int(epoch))
        if forgiven:
            # a restarted learner's first pull: its predecessor's
            # outstanding write-backs are gone with it — unwedge now
            # instead of waiting out the silence timeout
            print(f"{self.identity}: learner epoch -> "
                  f"{self.core.learner_epoch}, forgave {forgiven} "
                  f"outstanding write-back(s)", flush=True)
            self._last_wb = time.monotonic()
            self._drain_inbox()
        batch = self.core.next_batch()
        if batch is None:
            reply = ("dry", {"ingested": self.core.ingested,
                             "warm": self.core.warm,
                             "stale_wb": self.core.stale_wb,
                             "restored": self.core.restored})
        else:
            obs_spans.stamp(batch, "batch_send")
            self.batches_served += 1
            reply = ("batch", batch)
        if self._mute:
            self.chaos_muted += 1       # the reply dies on the down link
            return
        self.sock.send_multipart([ident, wire.dumps(reply)])

    def _handle_prio(self, seq: int, idx, prios, epoch: int = 0) -> None:
        stale_before = self.core.stale_wb
        self.core.write_back(int(seq), idx, prios, epoch=int(epoch))
        if self.core.stale_wb > stale_before:
            return      # a dead learner's ghost is not liveness
        self._last_wb = time.monotonic()
        self._drain_inbox()

    # -- lifecycle -----------------------------------------------------------

    def step(self, timeout_ms: int = 100) -> bool:
        """One poll/dispatch round; True when a message was handled."""
        if self._hb is not None:
            hb = self._hb.maybe_beat(0)
            if hb is not None:
                self._hb_sender.send_stat(hb)
        if (self.core.outstanding() > 0
                and time.monotonic() - self._last_wb
                > self.comms.dead_after_s):
            # the learner died between pull and write-back: forgive so
            # the strict gate (and the actor fleet behind it) unwedges
            n = self.core.forgive_outstanding()
            self._last_wb = time.monotonic()
            print(f"{self.identity}: forgave {n} outstanding "
                  f"write-back(s) after {self.comms.dead_after_s:.0f}s "
                  f"of learner silence", flush=True)
            self._drain_inbox()
        self._maybe_snapshot()
        if not self.sock.poll(timeout_ms, self._zmq.POLLIN):
            return False
        ident, payload = self.sock.recv_multipart()
        try:
            msg = wire.restricted_loads(payload)
        except wire.WireRejected:
            self.rejected += 1      # counted, dropped, and NOT acked
            return True
        kind = msg[0] if isinstance(msg, tuple) and msg else None
        if kind == "chunk":
            self._handle_chunk(ident, msg[1])
        elif kind == "pull":
            self._handle_pull(ident,
                              int(msg[1]) if len(msg) > 1 else 0)
        elif kind == "prio":
            self._handle_prio(msg[1], msg[2], msg[3],
                              int(msg[4]) if len(msg) > 4 else 0)
        else:
            self.rejected += 1      # well-pickled garbage is still garbage
        return True

    def _maybe_snapshot(self) -> None:
        """Periodic durability tick: persist the shard at most every
        ``snapshot_s`` seconds, and only at quiescent points (strict
        mode) so the on-disk state is the lockstep state a restore
        resumes.  A failed write is counted, never fatal — durability
        must not kill a serving shard."""
        if not self.snapshot_path or self.snapshot_s <= 0:
            return
        if time.monotonic() - self._last_snapshot < self.snapshot_s:
            return
        if not self.core.quiescent():
            return
        try:
            self.core.save_snapshot(self.snapshot_path)
            self.snapshots += 1
        except Exception as e:
            self.snapshot_errors += 1
            print(f"{self.identity}: snapshot failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        self._last_snapshot = time.monotonic()

    def run(self, stop_event=None, max_seconds: float | None = None) -> dict:
        deadline = (None if max_seconds is None
                    else time.monotonic() + max_seconds)
        while True:
            if stop_event is not None and stop_event.is_set():
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            self.step()
        return self.stats()

    def stats(self) -> dict:
        return {**self.core.stats(), "shard": self.shard_id,
                "batches_served": self.batches_served,
                "rejected": self.rejected,
                "chaos_dropped": self.chaos.dropped,
                "chaos_muted": self.chaos_muted,
                "snapshots": self.snapshots,
                "inbox_deferred": len(self._inbox)}

    def close(self) -> None:
        self.sock.close(linger=0)
        if self._hb_sender is not None:
            self._hb_sender.close(drain_s=0.0)


def snapshot_path_for(snapshot_dir: str, shard_id: int) -> str:
    """One canonical snapshot file per shard index — the respawned
    process finds its predecessor's state without coordination."""
    import os
    return os.path.join(snapshot_dir, f"replay_shard_{shard_id}.msgpack")


def run_replay_shard(cfg: ApexConfig, shard_id: int, family: str = "dqn",
                     stop_event=None, max_seconds: float | None = None,
                     bind_ip: str = "*",
                     snapshot_dir: str | None = None) -> dict:
    """The ``--role replay`` entry point: build the shard core from the
    fleet config, serve until stopped.  Returns the final stats dict.

    With ``snapshot_dir`` set the shard restores the newest snapshot on
    startup (a supervised respawn rejoins WARM instead of refilling from
    live streams) and keeps snapshotting at the config cadence."""
    import os

    from apex_tpu.obs.trace import get_ring, set_process_label

    set_process_label(f"replay-{shard_id}")
    get_ring()                      # arm the trace ring's dump triggers
    core = build_shard_core(cfg, shard_id, family=family)
    snap_path = None
    if snapshot_dir:
        os.makedirs(snapshot_dir, exist_ok=True)
        snap_path = snapshot_path_for(snapshot_dir, shard_id)
        if os.path.exists(snap_path):
            try:
                core.restore_snapshot(snap_path)
                print(f"replay-{shard_id}: warm restore "
                      f"({core.ingested} transitions, "
                      f"{core.sampled} batches sampled, learner epoch "
                      f"{core.learner_epoch}) from {snap_path}",
                      flush=True)
            except Exception as e:
                print(f"replay-{shard_id}: cold start — snapshot "
                      f"unusable ({type(e).__name__}: {e})", flush=True)
    server = ReplayShardServer(cfg.comms, shard_id, core,
                               snapshot_path=snap_path)
    print(f"replay-{shard_id}: serving on port "
          f"{cfg.comms.replay_port_base + shard_id} "
          f"(capacity={cfg.replay.capacity}, warmup={core.warmup}/shard, "
          f"strict={core.strict_order}, "
          f"snapshots={'on' if snap_path and server.snapshot_s > 0 else 'off'})",
          flush=True)
    try:
        return server.run(stop_event=stop_event, max_seconds=max_seconds)
    finally:
        server.close()
