"""The replay shard role: sockets, heartbeats, chaos, lifecycle.

The reference ran replay as a standalone process bridging actors and the
learner with three zmq proxies (``origin_repo/replay.py:48-74``).  This
role restores that topology for the TPU port, sharded: shard ``s`` binds
ONE ROUTER at ``replay_port_base + s`` and multiplexes the three message
kinds on it —

* ``("chunk", msg)``   from actors: restricted-decode, chaos gate,
  ingest into the shard's :class:`~apex_tpu.replay_service.shard.
  ReplayShardCore`, then ack (the ack IS the sender's next credit, same
  protocol as the learner's :class:`~apex_tpu.runtime.transport.
  ChunkReceiver`; a hostile payload is counted and dropped WITHOUT an
  ack, wedging only its sender's window);
* ``("pull",)``        from the learner: reply the next pre-sampled
  batch, or ``("dry", {...})`` so the learner's round-robin moves on;
* ``("prio", seq, idx, prios)`` from the learner: apply the write-back.

Strict-order deferral: while a write-back is outstanding the core
refuses ingest (:meth:`ReplayShardCore.can_ingest` — ingest and
write-back do not commute bitwise once the ring wraps), so arriving
chunks park in a host-side inbox WITHOUT acks — the actor credit windows
backpressure exactly like the learner's bounded queue does.  A learner
that dies mid-round-trip would wedge that gate forever, so write-back
silence past ``dead_after_s`` forgives the outstanding batches (counted).

Membership: the shard ships ordinary :class:`~apex_tpu.fleet.heartbeat.
Heartbeat`\\ s (role ``"replay"``) on a plain stat channel to the
learner's ROUTER — zero new control sockets, and the learner's
:class:`~apex_tpu.fleet.registry.FleetRegistry` runs its
JOINING→ALIVE→SUSPECT→DEAD machine over shards for free (a chaos-killed
shard shows up DEAD in ``fleet_summary.json``, pinned in tests).

Chaos: ``CHAOS_SEED``/``CHAOS_SPEC`` gate a per-shard plan under the
identity ``replay-<shard_id>`` — ``kill`` fires on the chunk-ingest
index (``os._exit(137)``), ``drop_frac`` drops ingested chunks (acked,
so the loss is silent data loss, exactly what a dying shard produces).
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import ApexConfig, CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.replay_service.shard import ReplayShardCore
from apex_tpu.runtime import codec
from apex_tpu.runtime import wire
from apex_tpu.tenancy import namespace as tenancy_ns


def shard_warmup(global_warmup: int, n_shards: int) -> int:
    """Per-shard warmup: the global gate split over shards (ceil — the
    fleet never trains EARLIER than the unsharded config would)."""
    return max(1, -(-int(global_warmup) // max(1, n_shards)))


def dqn_replay_spec(cfg: ApexConfig):
    """The FramePoolReplay spec the DQN learner builds — factored out so
    the shard role and :class:`~apex_tpu.training.apex.ApexTrainer`
    cannot drift (one spec, two owners would eventually disagree on
    frame shapes)."""
    from apex_tpu.replay.base import check_hbm_budget
    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.apex import dqn_env_specs

    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    replay = FramePoolReplay(
        capacity=cfg.replay.capacity, frame_shape=frame_shape,
        frame_stack=frame_stack, frame_dtype=np.dtype(frame_dtype).name,
        alpha=cfg.replay.alpha, eps=cfg.replay.eps)
    check_hbm_budget(replay.hbm_bytes(), cfg.replay.hbm_budget_gb,
                     "replay-shard frame pool", cfg.replay.capacity)
    return replay


def build_shard_core(cfg: ApexConfig, shard_id: int, family: str = "dqn",
                     tenant_spec=None) -> ReplayShardCore:
    """One shard's core from the fleet config.  ``capacity``/``warmup``
    are per shard (capacity as configured — N shards hold N x capacity;
    warmup split so the global gate is preserved).

    ``tenant_spec`` (PR 13) builds a TENANT PARTITION instead: its own
    FramePoolReplay sized from the tenant's env id, its own warmup/beta
    math over its own ingest count, its admission quota, and a PRNG
    chain folded by the tenant name — the default-tenant core (spec
    None) is constructed exactly as before, bit for bit."""
    import jax

    if family != "dqn":
        raise NotImplementedError(
            f"replay service shards currently serve the dqn family only "
            f"(got {family!r}); aql/r2d2 stay on in-learner replay — see "
            f"ROADMAP.md")
    quota = 0
    if tenant_spec is not None:
        if tenant_spec.family != "dqn":
            raise NotImplementedError(
                f"tenant {tenant_spec.name!r}: replay partitions serve "
                f"the dqn family only (got {tenant_spec.family!r})")
        import dataclasses
        cfg = cfg.replace(env=dataclasses.replace(
            cfg.env, env_id=tenant_spec.env_id))
        quota = tenant_spec.replay_quota
        from apex_tpu.population.lineage import LineageSpec, apply_lineage
        if isinstance(tenant_spec, LineageSpec):
            # a population lineage's partition honors ITS replay-shaping
            # hyperparameters (priority exponent alpha, IS beta) — the
            # vector the PBT controller mutates, applied where the trees
            # are built
            cfg = apply_lineage(cfg, tenant_spec)
    replay = dqn_replay_spec(cfg)
    n = max(1, cfg.comms.replay_shards)
    key = jax.random.key(cfg.env.seed + 977_000 + shard_id)
    if tenant_spec is not None and not tenancy_ns.is_default(
            tenant_spec.name):
        import zlib
        # a tenant-distinct chain: the default core's key untouched, the
        # partition's deterministically derived from the tenant name
        key = jax.random.fold_in(
            key, zlib.crc32(tenant_spec.name.encode()) % (2 ** 31))
    return ReplayShardCore(
        replay, key,
        batch_size=cfg.learner.batch_size,
        warmup=shard_warmup(cfg.replay.warmup, n),
        beta=cfg.replay.beta, beta_anneal=cfg.replay.beta_anneal,
        n_shards=n,
        strict_order=cfg.comms.replay_strict_order,
        presample_depth=cfg.comms.replay_presample,
        quota=quota)


class _ShardChaos:
    """The replay-shard fault gate: one RNG draw per ingested chunk off
    the seeded per-identity stream (:mod:`apex_tpu.fleet.chaos`), so a
    shard's kills and drops replay exactly, run after run."""

    def __init__(self, plan):
        self.plan = plan
        self._rng = plan.rng() if plan is not None else None
        self._n = 0
        self.dropped = 0

    def on_chunk(self) -> str:
        """"ok" | "drop"; a scheduled kill never returns."""
        if self.plan is None:
            return "ok"
        i = self._n
        self._n += 1
        if self.plan.kill_at is not None and i >= self.plan.kill_at:
            from apex_tpu.fleet.chaos import _die
            _die(self.plan.identity, i)
        if self._rng.random() < self.plan.drop_frac:
            self.dropped += 1
            return "drop"
        return "ok"


class ReplayShardServer:
    """Socket loop around one :class:`ReplayShardCore` (module
    docstring).  Single-threaded on purpose: one thread owns the ROUTER,
    the jit dispatches, and the deterministic op order the strict mode
    promises."""

    def __init__(self, comms: CommsConfig, shard_id: int,
                 core: ReplayShardCore, bind_ip: str = "*",
                 heartbeat=True, snapshot_path: str | None = None,
                 snapshot_s: float | None = None, tenant_factory=None,
                 snapshot_dir: str | None = None):
        import zmq

        from apex_tpu.fleet.chaos import chaos_from_env

        self._zmq = zmq
        self.comms = comms
        self.shard_id = int(shard_id)
        self.core = core
        # per-tenant partitions (PR 13): the default tenant's core IS
        # `core` (every single-tenant path bit-identical); roster
        # tenants' partitions build lazily via `tenant_factory(tenant)`
        # on their first chunk/pull, each its own FramePoolReplay +
        # PRNG chain + warmup/quota math.  Traffic from a tenant the
        # factory refuses is counted and refused (acked — a stranger's
        # credit window must not wedge on the shared plane).
        self.cores: dict[str, ReplayShardCore] = {
            tenancy_ns.DEFAULT_TENANT: core}
        self._tenant_factory = tenant_factory
        self.unknown_tenant = 0
        self.identity = f"replay-{shard_id}"
        self.sock = zmq.Context.instance().socket(zmq.ROUTER)
        self.sock.bind(f"tcp://{bind_ip}:{comms.replay_port_base + shard_id}")
        self.rejected = 0
        self.codec_chunks = 0      # compressed chunks decoded on ingest
        self.codec_rejected = 0    # garbage codec payloads dropped unacked
        self.batches_served = 0
        self._inbox: list = []   # strict-mode deferred (tenant, ident, msg)
        self._last_wb = {tenancy_ns.DEFAULT_TENANT: time.monotonic()}
        # shard durability: periodic whole-state snapshots (taken only at
        # quiescent points so a restore resumes the strict lockstep
        # bit-exactly); a supervised respawn restores the newest one.
        # With snapshot_dir set, TENANT partitions snapshot/restore too
        # (one file per (shard, tenant) — an exploited lineage's replay
        # state survives its learner's restart cycle, not just the
        # default tenant's); snapshot_path keeps naming the default
        # partition's file so pre-tenancy layouts stay readable.
        self.snapshot_path = snapshot_path
        self.snapshot_dir = snapshot_dir
        self.snapshot_s = (comms.replay_snapshot_s if snapshot_s is None
                           else snapshot_s)
        self._last_snapshot = time.monotonic()
        self.snapshots = 0
        self.snapshot_errors = 0
        self.tenant_snapshots: dict[str, int] = {}
        chaos = chaos_from_env()
        plan = chaos.plan_for(self.identity) if chaos is not None else None
        self.chaos = _ShardChaos(plan)
        # directional link drop (shard->learner down while actor->shard
        # stays up): this shard's outgoing replies vanish — the learner's
        # pulls arrive, the sampled batches never make it back
        self._mute = bool(plan is not None and plan.mute_replies)
        self.chaos_muted = 0
        self._hb = None
        self._hb_sender = None
        if heartbeat:
            from apex_tpu.fleet.heartbeat import HeartbeatEmitter
            from apex_tpu.runtime.transport import ChunkSender
            self._hb_sender = ChunkSender(comms, self.identity)
            self._hb = HeartbeatEmitter(
                self.identity, role="replay",
                interval_s=comms.heartbeat_interval_s,
                counters_fn=lambda: {
                    "chunks_sent": self.batches_served,
                    "acks_received": sum(c.wb_applied
                                         for c in self.cores.values())},
                gauges_fn=self._gauges)

    # -- message handlers ----------------------------------------------------

    def _core_for(self, tenant: str) -> ReplayShardCore | None:
        """This tenant's partition, built lazily from the factory on
        first sight (warm-restored from its own snapshot when one
        exists); None for tenants nobody admitted."""
        got = self.cores.get(tenant)
        if got is None and self._tenant_factory is not None:
            got = self._tenant_factory(tenant)
            if got is not None:
                path = self._tenant_snapshot_path(tenant)
                if path is not None:
                    import os
                    if os.path.exists(path):
                        try:
                            got.restore_snapshot(path)
                            print(f"{self.identity}: warm restore "
                                  f"({got.ingested} transitions, tenant "
                                  f"{tenant}) from {path}", flush=True)
                        except Exception as e:
                            print(f"{self.identity}: tenant {tenant!r} "
                                  f"cold start — snapshot unusable "
                                  f"({type(e).__name__}: {e})",
                                  flush=True)
                self.cores[tenant] = got
                self._last_wb[tenant] = time.monotonic()
                print(f"{self.identity}: tenant partition for "
                      f"{tenant!r} (warmup={got.warmup}, "
                      f"quota={got.quota or 'unlimited'})", flush=True)
        return got

    def _tenant_snapshot_path(self, tenant: str) -> str | None:
        """Where a tenant partition's snapshot lives: the default
        partition keeps the pre-tenancy ``snapshot_path`` name; roster
        tenants get their own per-(shard, tenant) file under
        ``snapshot_dir`` (None = durability off for that partition)."""
        if tenancy_ns.is_default(tenant):
            return self.snapshot_path
        if not self.snapshot_dir:
            return None
        return snapshot_path_for(self.snapshot_dir, self.shard_id,
                                 tenant=tenant)

    def _ingest(self, core: ReplayShardCore, ident: bytes,
                msg: dict) -> None:
        core.ingest_msg(msg)
        if self._hb is not None:
            self._hb.tick(int(msg.get("n_trans", 0)))
        self.sock.send_multipart([ident, b"ack"])

    def _handle_chunk(self, ident: bytes, msg: dict) -> None:
        if self.chaos.on_chunk() == "drop":
            self.sock.send_multipart([ident, b"ack"])   # silent data loss
            return
        obs_spans.stamp(msg, "shard_recv")
        tenant = tenancy_ns.tenant_of(str(msg.get("chunk_id") or ""))
        core = self._core_for(tenant)
        if core is None:
            self.unknown_tenant += 1    # unadmitted tenant: refused, but
            self.sock.send_multipart([ident, b"ack"])   # never wedged
            return
        if core.over_quota():
            core.quota_dropped += 1     # quota-bounded ingest: a full
            self.sock.send_multipart([ident, b"ack"])   # partition refuses
            return
        if not core.can_ingest():
            self._inbox.append((tenant, ident, msg))    # ack withheld:
            return                                      # credit paces sender
        self._ingest(core, ident, msg)

    def _drain_inbox(self) -> None:
        """Ingest deferred chunks whose tenant partition can take them
        now (per-entry gate: strict mode re-closes after one ingest, so
        later same-tenant entries stay parked — single-tenant behavior
        unchanged).  FIFO order preserved per tenant."""
        rest: list = []
        for tenant, ident, msg in self._inbox:
            core = self.cores.get(tenant)
            if core is not None and core.can_ingest():
                self._ingest(core, ident, msg)
            else:
                rest.append((tenant, ident, msg))
        self._inbox = rest

    def _handle_pull(self, ident: bytes, epoch: int = 0,
                     tenant: str = tenancy_ns.DEFAULT_TENANT) -> None:
        core = self._core_for(tenant)
        if core is None:
            self.unknown_tenant += 1
            reply = ("dry", {"ingested": 0, "warm": False,
                             "stale_wb": 0, "restored": 0})
            if not self._mute:
                self.sock.send_multipart([ident, wire.dumps(reply)])
            else:
                self.chaos_muted += 1
            return
        forgiven = core.note_epoch(int(epoch))
        if forgiven:
            # a restarted learner's first pull: its predecessor's
            # outstanding write-backs are gone with it — unwedge now
            # instead of waiting out the silence timeout
            print(f"{self.identity}: learner epoch -> "
                  f"{core.learner_epoch} (tenant {tenant}), forgave "
                  f"{forgiven} outstanding write-back(s)", flush=True)
            self._last_wb[tenant] = time.monotonic()
            self._drain_inbox()
        batch = core.next_batch()
        if batch is None:
            reply = ("dry", {"ingested": core.ingested,
                             "warm": core.warm,
                             "stale_wb": core.stale_wb,
                             "restored": core.restored})
        else:
            obs_spans.stamp(batch, "batch_send")
            self.batches_served += 1
            reply = ("batch", batch)
        if self._mute:
            self.chaos_muted += 1       # the reply dies on the down link
            return
        self.sock.send_multipart([ident, wire.dumps(reply)])

    def _handle_prio(self, seq: int, idx, prios, epoch: int = 0,
                     tenant: str = tenancy_ns.DEFAULT_TENANT) -> None:
        core = self.cores.get(tenant)
        if core is None:
            self.unknown_tenant += 1
            return
        stale_before = core.stale_wb
        core.write_back(int(seq), idx, prios, epoch=int(epoch))
        if core.stale_wb > stale_before:
            return      # a dead learner's ghost is not liveness
        self._last_wb[tenant] = time.monotonic()
        self._drain_inbox()

    # -- lifecycle -----------------------------------------------------------

    def step(self, timeout_ms: int = 100) -> bool:
        """One poll/dispatch round; True when a message was handled."""
        if self._hb is not None:
            hb = self._hb.maybe_beat(0)
            if hb is not None:
                self._hb_sender.send_stat(hb)
        for tenant, core in list(self.cores.items()):
            # per-tenant write-back liveness: each tenant's learner
            # lives and dies on its own — one tenant's death must only
            # ever unwedge (never wedge) another's partition
            if (core.outstanding() > 0
                    and time.monotonic() - self._last_wb[tenant]
                    > self.comms.dead_after_s):
                n = core.forgive_outstanding()
                self._last_wb[tenant] = time.monotonic()
                print(f"{self.identity}: forgave {n} outstanding "
                      f"write-back(s) (tenant {tenant}) after "
                      f"{self.comms.dead_after_s:.0f}s of learner "
                      f"silence", flush=True)
                self._drain_inbox()
        self._maybe_snapshot()
        if not self.sock.poll(timeout_ms, self._zmq.POLLIN):
            return False
        ident, payload = self.sock.recv_multipart()
        try:
            msg = wire.restricted_loads(payload)
        except wire.WireRejected:
            self.rejected += 1      # counted, dropped, and NOT acked
            return True
        kind = msg[0] if isinstance(msg, tuple) and msg else None
        if kind == "chunkc":
            # compressed chunk (runtime/codec.py): decode fused with the
            # ingest path, right here on the shard — the trainer hot
            # loop only ever sees ready batches.  Garbage gets the
            # RestrictedUnpickler treatment: counted, dropped, unacked.
            try:
                body = codec.decode_chunk(msg[1])
            except codec.CodecError:
                self.codec_rejected += 1
                return True
            self.codec_chunks += 1
            self._handle_chunk(ident, body)
        elif kind == "chunk":
            self._handle_chunk(ident, msg[1])
        elif kind == "pull":
            # legacy ("pull",) / ("pull", epoch) = the default tenant —
            # a pre-tenancy learner keeps working unmodified; tenant
            # learners append their name as the third element
            self._handle_pull(ident,
                              int(msg[1]) if len(msg) > 1 else 0,
                              str(msg[2]) if len(msg) > 2
                              else tenancy_ns.DEFAULT_TENANT)
        elif kind == "prio":
            self._handle_prio(msg[1], msg[2], msg[3],
                              int(msg[4]) if len(msg) > 4 else 0,
                              str(msg[5]) if len(msg) > 5
                              else tenancy_ns.DEFAULT_TENANT)
        else:
            self.rejected += 1      # well-pickled garbage is still garbage
        return True

    def _gauges(self) -> dict:
        """Heartbeat gauges: the tenancy scheduler's placement inputs —
        how many tenant partitions live here, and whether this host is
        accelerator-backed (the 2311.09445 heterogeneous-placement
        signal)."""
        import jax
        return {"tenants": len(self.cores),
                "backend_accel": float(jax.default_backend() != "cpu")}

    def _maybe_snapshot(self) -> None:
        """Periodic durability tick: persist EVERY partition (default +
        tenant) at most every ``snapshot_s`` seconds, each only at its
        own quiescent points (strict mode) so the on-disk state is the
        lockstep state a restore resumes.  A non-quiescent or pathless
        partition is skipped this round, not blocked on; a failed write
        is counted, never fatal — durability must not kill a serving
        shard."""
        if (not self.snapshot_path and not self.snapshot_dir) \
                or self.snapshot_s <= 0:
            return
        if time.monotonic() - self._last_snapshot < self.snapshot_s:
            return
        for tenant, core in sorted(self.cores.items()):
            path = self._tenant_snapshot_path(tenant)
            if path is None or not core.quiescent():
                continue
            try:
                core.save_snapshot(path)
                self.snapshots += 1
                if not tenancy_ns.is_default(tenant):
                    self.tenant_snapshots[tenant] = \
                        self.tenant_snapshots.get(tenant, 0) + 1
            except Exception as e:
                self.snapshot_errors += 1
                print(f"{self.identity}: snapshot failed (tenant "
                      f"{tenant}): {type(e).__name__}: {e}", flush=True)
        self._last_snapshot = time.monotonic()

    def run(self, stop_event=None, max_seconds: float | None = None) -> dict:
        deadline = (None if max_seconds is None
                    else time.monotonic() + max_seconds)
        while True:
            if stop_event is not None and stop_event.is_set():
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            self.step()
        return self.stats()

    def stats(self) -> dict:
        return {**self.core.stats(), "shard": self.shard_id,
                "batches_served": self.batches_served,
                "rejected": self.rejected,
                "codec_chunks": self.codec_chunks,
                "codec_rejected": self.codec_rejected,
                "chaos_dropped": self.chaos.dropped,
                "chaos_muted": self.chaos_muted,
                "snapshots": self.snapshots,
                "tenant_snapshots": dict(self.tenant_snapshots),
                "inbox_deferred": len(self._inbox),
                "unknown_tenant": self.unknown_tenant,
                # per-tenant partition counters (the default tenant's
                # duplicate the top-level keys above on purpose: old
                # readers keep working, new readers get the breakdown)
                "tenants": {t: c.stats()
                            for t, c in sorted(self.cores.items())}}

    def close(self) -> None:
        self.sock.close(linger=0)
        if self._hb_sender is not None:
            self._hb_sender.close(drain_s=0.0)


def snapshot_path_for(snapshot_dir: str, shard_id: int,
                      tenant: str = tenancy_ns.DEFAULT_TENANT) -> str:
    """One canonical snapshot file per (shard index, tenant) — the
    respawned process finds its predecessor's state without
    coordination.  The default tenant keeps the pre-tenancy name, so
    existing snapshot layouts restore unchanged."""
    import os
    if tenancy_ns.is_default(tenant):
        return os.path.join(snapshot_dir,
                            f"replay_shard_{shard_id}.msgpack")
    return os.path.join(snapshot_dir,
                        f"replay_shard_{shard_id}.{tenant}.msgpack")


def run_replay_shard(cfg: ApexConfig, shard_id: int, family: str = "dqn",
                     stop_event=None, max_seconds: float | None = None,
                     bind_ip: str = "*",
                     snapshot_dir: str | None = None) -> dict:
    """The ``--role replay`` entry point: build the shard core from the
    fleet config, serve until stopped.  Returns the final stats dict.

    With ``snapshot_dir`` set the shard restores the newest snapshot on
    startup (a supervised respawn rejoins WARM instead of refilling from
    live streams) and keeps snapshotting at the config cadence."""
    import os

    from apex_tpu.obs.trace import get_ring, set_process_label

    set_process_label(f"replay-{shard_id}")
    get_ring()                      # arm the trace ring's dump triggers
    core = build_shard_core(cfg, shard_id, family=family)
    # tenant partitions (PR 13): roster tenants' chunks/pulls build
    # their own partitions lazily; everyone else is refused (counted)
    roster = tenancy_ns.load_roster()

    def tenant_factory(tenant: str):
        spec = roster.get(tenant)
        if spec is None:
            return None
        try:
            return build_shard_core(cfg, shard_id, family=family,
                                    tenant_spec=spec)
        except Exception as e:      # a bad roster entry must not kill
            print(f"replay-{shard_id}: tenant {tenant!r} partition "
                  f"failed: {type(e).__name__}: {e}", flush=True)
            return None
    snap_path = None
    if snapshot_dir:
        os.makedirs(snapshot_dir, exist_ok=True)
        snap_path = snapshot_path_for(snapshot_dir, shard_id)
        if os.path.exists(snap_path):
            try:
                core.restore_snapshot(snap_path)
                print(f"replay-{shard_id}: warm restore "
                      f"({core.ingested} transitions, "
                      f"{core.sampled} batches sampled, learner epoch "
                      f"{core.learner_epoch}) from {snap_path}",
                      flush=True)
            except Exception as e:
                print(f"replay-{shard_id}: cold start — snapshot "
                      f"unusable ({type(e).__name__}: {e})", flush=True)
    server = ReplayShardServer(cfg.comms, shard_id, core,
                               snapshot_path=snap_path,
                               tenant_factory=(tenant_factory if roster
                                               else None),
                               snapshot_dir=(snapshot_dir or None))
    print(f"replay-{shard_id}: serving on port "
          f"{cfg.comms.replay_port_base + shard_id} "
          f"(capacity={cfg.replay.capacity}, warmup={core.warmup}/shard, "
          f"strict={core.strict_order}, "
          f"snapshots={'on' if snap_path and server.snapshot_s > 0 else 'off'})",
          flush=True)
    try:
        return server.run(stop_event=stop_event, max_seconds=max_seconds)
    finally:
        server.close()
