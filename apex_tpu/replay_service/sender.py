"""Actor-side chunk routing for the sharded replay service.

The reference's actors open one push socket to THE replay host
(``origin_repo/actor.py:105-115``); here the replay plane is N shard
processes, so the actor opens one credit-windowed
:class:`~apex_tpu.runtime.transport.ChunkSender` per shard and routes each
sealed chunk by a STABLE hash of its chunk id (``identity:seq``) —
deterministic, uniform, and independent of arrival timing, so a chunk's
owning shard can be recomputed anywhere (tests pin the mapping).

Fallback semantics: a shard whose credit window stays exhausted past
``shard_wait_s`` (dead shard, or one wedged behind a dead learner's
write-backs) does not strand the chunk — it reroutes to the LEARNER's
direct ingest socket, which still runs the pre-service fused path.  The
learner channel is also where park/rejoin liveness is probed
(``fleet/park.py``), so a dead learner parks the actor exactly as before.

Stats/heartbeats always ride the learner channel — membership lives in
the learner's :class:`~apex_tpu.fleet.registry.FleetRegistry`.
"""

from __future__ import annotations

from apex_tpu.config import CommsConfig
from apex_tpu.runtime import transport
from apex_tpu.tenancy import namespace as tenancy_ns


def chunk_shard(chunk_id: str, n_shards: int) -> int:
    """Stable chunk-id -> shard index (crc32: identical across processes,
    platforms, and runs — the routing IS the sharding function).  Routed
    through the tenancy band helper (apexlint J021) with the full tier as
    the band, which is bit-identical to the historical raw
    ``crc32 % n`` — the tests pin the mapping."""
    return tenancy_ns.shard_in_band(chunk_id, range(max(1, n_shards)))


class ShardedChunkSender:
    """N per-shard credit-windowed senders + the learner direct channel.

    Presents the single-sender interface the queue adapters, the park
    controller, and the chaos wrapper already speak (``send_chunk`` /
    ``send_stat`` / ``reset_credits`` / wire counters / ``close``), so
    the whole actor stack switches transports with one constructor.
    """

    def __init__(self, comms: CommsConfig, identity: str,
                 direct: transport.ChunkSender | None = None,
                 n_shards: int | None = None, replay_ip: str | None = None,
                 shard_wait_s: float = 2.0,
                 shard_reprobe_s: float | None = None):
        self.comms = comms
        self.identity = identity
        self.n_shards = n_shards or comms.replay_shards
        if self.n_shards <= 0:
            raise ValueError("ShardedChunkSender needs replay_shards > 0 "
                             "(use a plain ChunkSender for the in-learner "
                             "topology)")
        ip = replay_ip or comms.replay_ip
        self.shards = [
            transport.ChunkSender(comms, identity, ip=ip,
                                  port=comms.replay_port_base + s)
            for s in range(self.n_shards)]
        # the learner channel: stats/heartbeats, park liveness, and the
        # chunk fallback path — built here unless the caller already owns
        # one (run_actor constructs it first so ParkController sees it)
        self.direct = direct or transport.ChunkSender(comms, identity)
        self.shard_wait_s = float(shard_wait_s)
        # dead-shard re-probe (PR 8 fix): a dying shard takes the
        # in-flight acks with it, so its credit window stays exhausted
        # FOREVER and every later chunk falls back — a recovered
        # (respawned, registry-ALIVE) shard never got its traffic back
        # without an actor restart.  Every shard_reprobe_s of continuous
        # fallback the window resets and one real send probes the shard:
        # a live shard acks and the stream returns; a still-dead one
        # re-wedges after max_outstanding probes (bounded loss, same as
        # any chunk in a dead shard's socket buffer).
        self.shard_reprobe_s = (comms.shard_reprobe_s
                                if shard_reprobe_s is None
                                else float(shard_reprobe_s))
        self._down_since: list[float | None] = [None] * self.n_shards
        self._seq = 0
        self.rerouted = 0           # chunks that fell back to the learner
        self.reprobes = 0           # credit-reset probes of wedged shards

    # -- data plane ----------------------------------------------------------

    def send_chunk(self, msg: dict, stop_event=None,
                   max_wait_s: float | None = None) -> bool:
        """Hash-route one chunk to its shard; on a wedged shard window,
        reroute to the learner's direct ingest.  The final wait semantics
        (None = block, ``max_wait_s`` = bounded) apply to the fallback
        channel, so park-controller wedge detection keys off LEARNER
        liveness exactly as in the unsharded topology."""
        import time

        from apex_tpu.tenancy import namespace as tenancy_ns
        cid = msg.get("chunk_id")
        if cid is None:
            # canonical identity:seq grammar (tenancy/namespace.py): the
            # identity is already tenant-qualified by the role, so the
            # crc32 below partitions per tenant with no extra machinery
            cid = msg["chunk_id"] = tenancy_ns.chunk_id(self.identity,
                                                        self._seq)
        self._seq += 1
        s = chunk_shard(cid, self.n_shards)
        wait = self.shard_wait_s
        if max_wait_s is not None:
            wait = min(wait, max_wait_s)
        down = self._down_since[s]
        if (down is not None and self.shard_reprobe_s > 0
                and time.monotonic() - down >= self.shard_reprobe_s):
            # the shard has been wedged a full re-probe period: its old
            # acks are never coming (a respawned process has no memory
            # of them) — reset the window and give it one real send
            self.shards[s].reset_credits()
            self.reprobes += 1
            self._down_since[s] = time.monotonic()
        if self.shards[s].send_chunk(msg, stop_event, max_wait_s=wait):
            self._down_since[s] = None      # the shard is taking traffic
            return True
        if stop_event is not None and stop_event.is_set():
            return False
        if self._down_since[s] is None:
            self._down_since[s] = time.monotonic()
        self.rerouted += 1
        return self.direct.send_chunk(msg, stop_event,
                                      max_wait_s=max_wait_s)

    def send_stat(self, stat) -> None:
        self.direct.send_stat(stat)

    # -- park/heartbeat hooks ------------------------------------------------

    def reset_credits(self) -> None:
        """Rejoin after a learner death: every outstanding ack died with
        it — including shard acks wedged behind the dead learner's
        write-back gate (strict ordering)."""
        self.direct.reset_credits()
        for s in self.shards:
            s.reset_credits()

    def note_resend(self) -> None:
        """Adapter retry accounting rides the learner channel's counter
        (the bounded-wait fallback send is what the adapter retries)."""
        self.direct.note_resend()

    @property
    def chunks_sent(self) -> int:
        return (self.direct.chunks_sent
                + sum(s.chunks_sent for s in self.shards))

    @property
    def acks_received(self) -> int:
        return (self.direct.acks_received
                + sum(s.acks_received for s in self.shards))

    @property
    def resends(self) -> int:
        return (self.direct.resends
                + sum(s.resends for s in self.shards))

    @property
    def wire_bytes_out(self) -> int:
        return (self.direct.wire_bytes_out
                + sum(s.wire_bytes_out for s in self.shards))

    @property
    def wire_bytes_raw(self) -> int:
        return (self.direct.wire_bytes_raw
                + sum(s.wire_bytes_raw for s in self.shards))

    def wire_gauges(self) -> dict:
        """Fleet-wide codec byte gauges, aggregated exactly like the
        wire counters above (keys registered in obs.metrics)."""
        out = self.wire_bytes_out
        return {"wire_bytes_out": out,
                "wire_bytes_raw": self.wire_bytes_raw,
                "codec_ratio": (self.wire_bytes_raw / out) if out else 1.0}

    def close(self, drain_s: float = 2.0) -> None:
        for s in self.shards:
            s.close(drain_s=drain_s)
        self.direct.close(drain_s=drain_s)
