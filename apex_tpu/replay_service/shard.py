"""One replay shard's compute: ingest -> prioritized sample -> write-back.

This is the deterministic half of the replay service (no sockets — the
wire lives in :mod:`apex_tpu.replay_service.service`).  A shard owns ONE
:class:`~apex_tpu.replay.frame_pool.FramePoolReplay` segment tree and runs
the exact three programs the in-learner path runs, just as separate
dispatches instead of one fused one:

* ``add``      — the same ingest program ``LearnerCore.jit_ingest`` compiles
  (donated state, duplicate-pad-write invariant intact);
* ``sample``   — the same stratified PER sample the fused step embeds,
  driven by the shard's OWN PRNG key chain (``chain, k = split(chain)``
  per batch — the split sequence the trainer's ``self.key`` would have
  produced for the same dispatch count);
* ``update_priorities`` — the learner's TD priorities written back to the
  tree rows the batch was sampled from.

Bit-parity contract (the reason this class exists instead of an ad-hoc
loop in the server): with ``strict_order=True`` and one shard, the
sequence ``ingest(c1); b1=next_batch(); write_back(b1); ingest(c2); ...``
produces bit-identical replay state, sampled batches, and key-chain
position to the in-learner serial loop's ``fused_step(c1); fused_step(c2);
...`` — same tree, same beta schedule (beta is computed from the
PRE-ingest transition count, exactly like the trainer's ``_beta()`` call
before each fused dispatch), same keys.  tests/test_replay_service.py pins
params + every replay-state field + the key chain.

Ordering modes:

* ``strict_order=True`` (default): batch j+1 is sampled only after batch
  j's write-back has been applied, and the next ingest DEFERS behind an
  outstanding write-back (``can_ingest``) — because a wrapped ring can
  overwrite a just-sampled row, ingest and write-back do not commute
  bitwise.  The cost is one learner round-trip of latency per batch; the
  win is a replay plane that is deterministic and provably equivalent to
  the single-process path.
* ``strict_order=False``: the reference's semantics (``replay.py:104-146``
  applies priority updates whenever they arrive) — pre-sample up to
  ``presample_depth`` batches ahead, ingest never waits, write-backs land
  out of band.  Throughput mode for large fleets.

Families whose update consumes a PRNG key (``AQLCore.update_needs_key``)
get the trainer half of the split shipped WITH the batch: the shard
splits its per-batch key into (sample, update) halves like
``AQLCore.train_step`` does and sends the update half as raw key data —
one chain, two consumers, no fork.
"""

from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.obs import spans as obs_spans
from apex_tpu.serving import fence

#: most source-chunk lineage spans carried onto one sampled batch (the
#: batch mixes many chunks; the freshest few keep frame-age measurable)
MAX_BATCH_SPANS = 8


class ReplayShardCore:
    """State + jitted programs of one replay shard (module docstring).

    ``warmup`` is PER SHARD (drivers divide the global warmup by the
    shard count); ``beta_anneal``/``n_shards`` let the shard estimate the
    GLOBAL ingest count for the trainer's beta schedule (shard-local
    ingested x n_shards — exact at N=1, an unbiased estimate under the
    uniform chunk hash otherwise).
    """

    def __init__(self, replay, key, *, batch_size: int, warmup: int,
                 beta: float = 0.4, beta_anneal: int = 500_000,
                 n_shards: int = 1, strict_order: bool = True,
                 presample_depth: int = 2, update_needs_key: bool = False,
                 example_item=None, quota: int = 0):
        self.replay = replay
        self.state = replay.init(example_item)
        self.key = key
        self.batch_size = int(batch_size)
        self.warmup = int(warmup)
        self.beta0 = float(beta)
        self.beta_anneal = int(beta_anneal)
        self.n_shards = max(1, int(n_shards))
        self.strict_order = bool(strict_order)
        self.presample_depth = max(1, int(presample_depth))
        self.update_needs_key = bool(update_needs_key)
        # per-tenant replay quota (PR 13): max RESIDENT transitions this
        # partition may hold (0 = unlimited — the single-tenant default,
        # bit-identical behavior).  The server refuses ingest into a
        # full partition (acked + counted: quota_dropped) so one tenant
        # can never evict another's experience from the shared shard.
        self.quota = max(0, int(quota))
        self.quota_dropped = 0
        # the three programs the fused step decomposes into
        self._add = jax.jit(replay.add, donate_argnums=(0,))
        self._sample = jax.jit(replay.sample, static_argnums=(2,))
        self._wb = jax.jit(replay.update_priorities, donate_argnums=(0,))
        # counters
        self.ingested = 0               # transitions resident (cumulative)
        self.chunks = 0
        self.sampled = 0                # batches ever sampled (chain length)
        self.wb_applied = 0             # write-backs applied
        self.dup_wb = 0                 # duplicate/late write-backs dropped
        # learner-epoch fencing (PR 8): the highest epoch any pull or
        # write-back has carried (0 = unstamped legacy traffic, fencing
        # off).  Write-backs from an OLDER epoch are a restarted
        # learner's predecessor talking — rejected and counted, never
        # applied (they would corrupt priorities the new learner already
        # owns); a NEWER epoch's first pull forgives the old epoch's
        # outstanding batches (that learner is gone with its write-backs)
        self.learner_epoch = 0
        self.stale_wb = 0               # stale-epoch write-backs rejected
        self.epoch_forgiven = 0         # batches forgiven on epoch bumps
        self.restored = 0               # transitions resident at restore
        self._outbox: deque[dict] = deque()
        self._pending_spans: deque = deque(maxlen=MAX_BATCH_SPANS)

    # -- gating --------------------------------------------------------------

    @property
    def warm(self) -> bool:
        return self.ingested >= self.warmup

    def outstanding(self) -> int:
        """Batches sampled whose priorities have not come back yet."""
        return self.sampled - self.wb_applied

    def resident(self) -> int:
        """Transitions currently resident (the ring overwrites past
        capacity, so residency saturates there)."""
        return min(self.ingested, self.replay.capacity)

    def over_quota(self) -> bool:
        """True when the partition is at its tenant quota — the server
        drops (acks + counts) further ingest instead of letting this
        tenant grow past its admission record."""
        return self.quota > 0 and self.resident() >= self.quota

    def can_ingest(self) -> bool:
        """Strict mode defers ingest behind an outstanding write-back: a
        wrapped ring can overwrite a sampled row, so ingest and write-back
        do not commute bitwise (module docstring).  Loose mode never
        waits."""
        if not self.strict_order:
            return True
        return self.outstanding() == 0

    def _can_sample(self) -> bool:
        if not self.warm:
            return False
        if self.strict_order:
            return self.outstanding() == 0 and not self._outbox
        # outstanding() already counts outbox batches (sampled, priorities
        # not back) — it IS the batches-in-flight-beyond-this-tree measure
        # the depth bounds
        return self.outstanding() < self.presample_depth

    def beta(self, ingested: int | None = None) -> float:
        """The trainer's ``_beta`` schedule on the estimated GLOBAL
        ingest count (shard-local x n_shards; exact at N=1)."""
        n = (self.ingested if ingested is None else ingested) * self.n_shards
        frac = min(1.0, n / max(1, self.beta_anneal))
        return self.beta0 + (1.0 - self.beta0) * frac

    # -- ingest ----------------------------------------------------------------

    def ingest_msg(self, msg: dict) -> None:
        """Ingest one chunk message (``{"payload", "priorities",
        "n_trans"}``).  Pre-ingest warm/beta are captured FIRST — the
        in-learner loop computes both before the fused dispatch, and the
        lockstep sample after this ingest must see the same values."""
        warm_pre = self.warm
        beta_pre = self.beta()
        payload = msg["payload"]
        prios = jnp.asarray(np.asarray(msg["priorities"], np.float32))
        self.state = self._add(self.state, payload, prios)
        self.ingested += int(msg["n_trans"])
        self.chunks += 1
        spans = obs_spans.spans_of(msg)
        if spans:
            self._pending_spans.extend(spans)
        if warm_pre and self._can_sample():
            # lockstep pre-sample: one batch per warm ingest, with the
            # pre-ingest beta — exactly the fused step's sample half
            self._outbox.append(self._sample_batch(beta_pre))

    # -- sampling ----------------------------------------------------------------

    def _sample_batch(self, beta: float) -> dict:
        self.key, k = jax.random.split(self.key)
        if self.update_needs_key:
            # AQLCore.train_step splits the dispatch key into
            # (sample, update): ship the update half as raw key data so
            # the learner consumes the same chain without forking it
            k_sample, k_update = jax.random.split(k)
            update_key = np.asarray(jax.random.key_data(k_update))
        else:
            k_sample, update_key = k, None
        batch, weights, idx = self._sample(self.state, k_sample,
                                           self.batch_size,
                                           jnp.float32(beta))
        seq = self.sampled
        self.sampled += 1
        out = {
            "kind": "batch",
            "seq": seq,
            "batch": jax.device_get(batch),
            "weights": np.asarray(weights),
            "idx": np.asarray(idx),
            "ingested": self.ingested,
            "sampled": self.sampled,
        }
        if update_key is not None:
            out["update_key"] = update_key
        spans = list(self._pending_spans)
        self._pending_spans.clear()
        if spans:
            obs_spans.stamp_spans(spans, "shard_sample")
            out[obs_spans.SPAN_KEY] = spans
        return out

    def next_batch(self) -> dict | None:
        """The next pre-sampled batch, or an on-demand sample (the
        train-only-step equivalent: the learner is pulling faster than
        chunks arrive), or None when the shard cannot serve one yet
        (cold, or strict mode waiting on a write-back)."""
        if self._outbox:
            return self._outbox.popleft()
        if self._can_sample():
            return self._sample_batch(self.beta())
        return None

    # -- write-back --------------------------------------------------------------

    def note_epoch(self, epoch: int) -> int:
        """Pull-side half of the epoch fence: a pull stamped with a NEWER
        learner epoch proves a restart — the old learner's outstanding
        write-backs will never arrive, so they are forgiven immediately
        (counted) instead of wedging the strict gate until the silence
        timeout.  Returns the number forgiven."""
        if not fence.newer_epoch(epoch, self.learner_epoch):
            return 0
        forgiven = 0
        if self.learner_epoch and self.outstanding() > 0:
            forgiven = self.forgive_outstanding()
            self.epoch_forgiven += forgiven
        self.learner_epoch = epoch
        return forgiven

    def write_back(self, seq: int, idx, priorities, epoch: int = 0) -> bool:
        """Apply one batch's TD priorities to the tree rows it was
        sampled from.  Duplicates (a retried pull training the same data
        twice) are counted and dropped — the zmq DEALER preserves order,
        so ``seq`` regressions only mean retransmits.  A write-back
        stamped with a STALE learner epoch (a restarted learner's
        predecessor) is rejected and counted — applying it would corrupt
        priorities on rows the new learner's stream now owns."""
        if epoch and self.learner_epoch \
                and fence.stale_epoch(epoch, self.learner_epoch):
            self.stale_wb += 1
            return False
        if fence.newer_epoch(epoch, self.learner_epoch):
            self.learner_epoch = epoch
        if seq < self.wb_applied:
            self.dup_wb += 1
            return False
        self.state = self._wb(self.state, jnp.asarray(idx),
                              jnp.asarray(np.asarray(priorities,
                                                     np.float32)))
        self.wb_applied = seq + 1
        return True

    def forgive_outstanding(self) -> int:
        """Abandon write-backs that will never come (a learner that died
        between pull and write-back): the strict gate must not wedge the
        shard — and its actors' credit windows — forever.  The server
        calls this after ``dead_after_s`` of write-back silence; a late
        write-back for a forgiven batch lands as a counted duplicate.
        Returns the number forgiven."""
        n = self.outstanding()
        self.wb_applied = self.sampled
        return n

    # -- durability (PR 8: shard checkpoint/restore) -----------------------------

    #: spec fields a snapshot pins — a restore into a differently-shaped
    #: shard would corrupt silently, so mismatches start cold instead
    _SNAP_PINS = ("batch_size", "warmup", "n_shards", "strict_order",
                  "update_needs_key")

    def quiescent(self) -> bool:
        """True when a snapshot taken now is self-consistent: no batch in
        flight to the learner and none pre-sampled but unserved (their
        write-backs/serves would be lost with the process, breaking the
        strict lockstep a restore resumes).  Loose mode snapshots
        anywhere — restore forgives the in-flight tail."""
        if not self.strict_order:
            return True
        return self.outstanding() == 0 and not self._outbox

    def snapshot_meta(self) -> dict:
        meta = {p: getattr(self, p) for p in self._SNAP_PINS}
        meta.update(
            capacity=self.replay.capacity,
            ingested=self.ingested, chunks=self.chunks,
            sampled=self.sampled, wb_applied=self.wb_applied,
            dup_wb=self.dup_wb, stale_wb=self.stale_wb,
            epoch_forgiven=self.epoch_forgiven,
            learner_epoch=self.learner_epoch)
        return meta

    def save_snapshot(self, path: str) -> str:
        """Atomically persist the whole shard — segment trees + frame
        pool (one FramePoolState pytree), PRNG chain, counters — with the
        same tmp+rename discipline as ``fleet_summary.json``.  A reader
        never sees a torn file; a crash mid-save leaves the previous
        snapshot restorable."""
        from apex_tpu.training.checkpoint import save_bundle
        return save_bundle(
            path,
            {"state": self.state, "key": jax.random.key_data(self.key)},
            self.snapshot_meta())

    def restore_snapshot(self, path: str) -> dict:
        """Warm-rejoin from a snapshot: bit-exact replay state, key
        chain, and counters.  Batches sampled-but-unresolved at snapshot
        time (loose mode) are forgiven — their learner round-trips died
        with the old process.  Raises ValueError on a spec mismatch (the
        caller starts cold rather than corrupt)."""
        from apex_tpu.training.checkpoint import restore_bundle
        bundle, meta = restore_bundle(
            path,
            {"state": self.state, "key": jax.random.key_data(self.key)})
        for pin in self._SNAP_PINS + ("capacity",):
            want = (self.replay.capacity if pin == "capacity"
                    else getattr(self, pin))
            if meta.get(pin) != want:
                raise ValueError(
                    f"snapshot {pin}={meta.get(pin)!r} != live shard "
                    f"{pin}={want!r} — refusing a shape-shifting restore")
        self.state = bundle["state"]
        self.key = jax.random.wrap_key_data(bundle["key"])
        self.ingested = int(meta["ingested"])
        self.chunks = int(meta["chunks"])
        self.sampled = int(meta["sampled"])
        self.dup_wb = int(meta["dup_wb"])
        self.stale_wb = int(meta.get("stale_wb", 0))
        self.epoch_forgiven = int(meta.get("epoch_forgiven", 0))
        self.learner_epoch = int(meta.get("learner_epoch", 0))
        # in-flight tail forgiven: late write-backs land as counted dups
        self.wb_applied = self.sampled
        self._outbox.clear()
        self._pending_spans.clear()
        self.restored = self.ingested
        return meta

    # -- observability -----------------------------------------------------------

    def stats(self) -> dict:
        return {
            "ingested": self.ingested,
            "chunks": self.chunks,
            "sampled": self.sampled,
            "wb_applied": self.wb_applied,
            "dup_wb": self.dup_wb,
            "stale_wb": self.stale_wb,
            "epoch_forgiven": self.epoch_forgiven,
            "learner_epoch": self.learner_epoch,
            "restored": self.restored,
            "outbox": len(self._outbox),
            "warm": self.warm,
            "quota": self.quota,
            "quota_dropped": self.quota_dropped,
        }
