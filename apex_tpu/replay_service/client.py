"""Learner-side client of the sharded replay service.

One DEALER per shard, all driven by EXACTLY ONE thread — the ingest
pipeline's staging thread when the pipeline is on (the staging thread
already owns every ``poll_chunks``/``publish_params`` call, see
:class:`~apex_tpu.runtime.transport.RemotePool`'s thread-affinity
contract), else the trainer thread.  Construction happens on the caller
thread and the sockets migrate once: the migrate-then-use-single-threaded
pattern zmq tolerates.

Protocol per shard (DEALER <-> the shard's ROUTER,
:mod:`apex_tpu.replay_service.service`):

* ``("pull",)``                 -> ``("batch", msg)`` | ``("dry", info)``
* ``("prio", seq, idx, prios)`` -> (no reply — the write-back is the ack)

At most one pull is outstanding per shard (re-sent after ``retry_s`` so a
shard that died mid-request is probed, not trusted); replies are decoded
through the restricted wire unpickler, so a compromised shard costs
counted drops, never execution.  Round-robin starts at a rotating cursor
— no shard starves behind a chatty one — and a shard that stops
answering simply stops contributing batches: the learner keeps training
on whatever the surviving shards serve (the registry's DEAD transition,
fed by the shard's own heartbeats, is the operator-facing signal).
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.runtime import wire


class ReplayServiceClient:
    """Round-robin batch puller + priority write-back router."""

    def __init__(self, comms: CommsConfig, n_shards: int | None = None,
                 replay_ip: str | None = None, identity: str = "learner",
                 retry_s: float = 2.0):
        import zmq

        from apex_tpu.tenancy import namespace as tenancy_ns

        self._zmq = zmq
        self.comms = comms
        self.n_shards = n_shards or comms.replay_shards
        if self.n_shards <= 0:
            raise ValueError("ReplayServiceClient needs replay_shards > 0")
        # multi-tenant shards (PR 13): this learner's tenant rides every
        # pull/write-back so the shard routes to OUR partition; the
        # DEALER identities qualify too — two tenants' learners on one
        # shared shard ROUTER must never collide on "learner-0"
        self.tenant = tenancy_ns.current_tenant()
        identity = tenancy_ns.qualify(self.tenant, identity)
        ip = replay_ip or comms.replay_ip
        ctx = zmq.Context.instance()
        self.socks = []
        for s in range(self.n_shards):
            sock = ctx.socket(zmq.DEALER)
            sock.setsockopt(zmq.IDENTITY,
                            f"{identity}-{s}".encode())
            # bounded send queue: pulls/prios to a dead shard must pile
            # up in the counter below, not in an unbounded kernel buffer
            sock.setsockopt(zmq.SNDHWM, 64)
            sock.connect(f"tcp://{ip}:{comms.replay_port_base + s}")
            self.socks.append(sock)
        self.retry_s = float(retry_s)
        self._rr = 0
        self._outstanding = [False] * self.n_shards
        self._last_pull = [0.0] * self.n_shards
        self._ingested = [0] * self.n_shards
        self._stale_wb = [0] * self.n_shards    # shard-reported rejects
        self._restored = [0] * self.n_shards    # shard-reported warm state
        self.batches = 0
        self.rejected = 0           # replies outside the wire allowlist
        self.prio_sent = 0
        self.prio_dropped = 0       # write-backs a full send queue refused
        self.unanswered = [0] * self.n_shards   # consecutive pull retries
        # learner-epoch fencing: the trainer stamps this before training
        # starts; 0 = unstamped legacy traffic (shard fencing stays off).
        # The chaos harness can SKEW outgoing write-back epochs (identity
        # "learner") to drill the shards' stale-epoch rejection.
        self.learner_epoch = 0
        from apex_tpu.fleet.chaos import chaos_from_env
        chaos = chaos_from_env()
        self.epoch_skew = (chaos.plan_for(identity).epoch_skew
                           if chaos is not None else 0)

    # -- pulls ---------------------------------------------------------------

    def _ensure_pull(self, s: int, now: float) -> None:
        if self._outstanding[s] and now - self._last_pull[s] < self.retry_s:
            return
        if self._outstanding[s]:
            self.unanswered[s] += 1     # retry: the last pull went silent
        from apex_tpu.tenancy import namespace as tenancy_ns
        if not tenancy_ns.is_default(self.tenant):
            # tenant pulls always carry the name (epoch may be 0);
            # default-tenant pulls keep the legacy 1/2-tuple format
            msg = ("pull", self.learner_epoch, self.tenant)
        else:
            msg = (("pull", self.learner_epoch) if self.learner_epoch
                   else ("pull",))
        try:
            self.socks[s].send(wire.dumps(msg), self._zmq.DONTWAIT)
            self._outstanding[s] = True
            self._last_pull[s] = now
        except self._zmq.Again:
            pass

    def _recv(self, s: int):
        """Drain one reply off shard ``s``; a batch message or None."""
        while self.socks[s].poll(0, self._zmq.POLLIN):
            try:
                msg = wire.restricted_loads(self.socks[s].recv())
            except wire.WireRejected:
                self.rejected += 1
                self._outstanding[s] = False
                continue
            self._outstanding[s] = False
            self.unanswered[s] = 0
            kind = msg[0]
            if kind == "batch":
                body = msg[1]
                body["shard"] = s
                self._ingested[s] = max(self._ingested[s],
                                        int(body.get("ingested", 0)))
                obs_spans.stamp(body, "recv")
                self.batches += 1
                return body
            if kind == "dry":
                info = msg[1]
                self._ingested[s] = max(self._ingested[s],
                                        int(info.get("ingested", 0)))
                self._stale_wb[s] = max(self._stale_wb[s],
                                        int(info.get("stale_wb", 0)))
                self._restored[s] = max(self._restored[s],
                                        int(info.get("restored", 0)))
        return None

    def poll_batch(self, timeout: float = 0.0) -> dict | None:
        """Next pre-sampled batch, round-robin over shards; None when no
        shard served one within ``timeout``."""
        deadline = time.monotonic() + timeout
        while True:
            now = time.monotonic()
            for off in range(self.n_shards):
                s = (self._rr + off) % self.n_shards
                self._ensure_pull(s, now)
                got = self._recv(s)
                if got is not None:
                    self._rr = (s + 1) % self.n_shards
                    return got
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            # one poller pass over every shard socket instead of a sleep:
            # the first reply wakes us
            poller = self._zmq.Poller()
            for sock in self.socks:
                poller.register(sock, self._zmq.POLLIN)
            poller.poll(min(50.0, remaining * 1000.0))

    # -- write-backs ---------------------------------------------------------

    def push_priorities(self, shard: int, seq: int, idx,
                        priorities) -> bool:
        """Ship one batch's TD priorities to its owning shard.  Non-
        blocking: a dead shard's write-backs are counted and dropped (it
        forgives them server-side), never wedge the learner.  Each
        write-back carries the learner epoch (plus any chaos skew) so a
        restarted learner's shards can fence its predecessor's ghosts."""
        from apex_tpu.tenancy import namespace as tenancy_ns
        epoch = (max(0, self.learner_epoch + self.epoch_skew)
                 if self.learner_epoch else 0)
        msg = ("prio", int(seq), np.asarray(idx),
               np.asarray(priorities, np.float32), int(epoch))
        if not tenancy_ns.is_default(self.tenant):
            msg = msg + (self.tenant,)
        payload = wire.dumps(msg)
        try:
            self.socks[int(shard)].send(payload, self._zmq.DONTWAIT)
            self.prio_sent += 1
            return True
        except self._zmq.Again:
            self.prio_dropped += 1
            return False

    # -- observability -------------------------------------------------------

    def ingested_total(self) -> int:
        """Sum of the shards' last-reported resident transition counts —
        the service-mode input to the trainer's warmup/ratio math."""
        return sum(self._ingested)

    def shard_status(self) -> list[dict]:
        return [{"shard": s, "ingested": self._ingested[s],
                 "unanswered": self.unanswered[s],
                 "stale_wb": self._stale_wb[s],
                 "restored": self._restored[s]}
                for s in range(self.n_shards)]

    def close(self) -> None:
        for sock in self.socks:
            sock.close(linger=0)
