"""Sharded replay service: prioritized replay as its own fleet role.

The Ape-X reference ran replay as a standalone process between actors
and the learner (``origin_repo/replay.py``); the TPU port initially
dissolved it into the learner's HBM, making the learner host the single
ingest/sampling bottleneck.  This package restores the standalone role,
sharded N ways:

* :mod:`~apex_tpu.replay_service.shard`   — one shard's deterministic
  compute (ingest → PER sample → priority write-back over a
  ``FramePoolReplay`` segment tree; N=1 strict mode is bit-identical to
  in-learner replay).
* :mod:`~apex_tpu.replay_service.service` — the ``--role replay`` socket
  process (ROUTER, restricted unpickler, heartbeats, chaos gate).
* :mod:`~apex_tpu.replay_service.sender`  — actor-side chunk→shard hash
  routing with per-shard credit windows and learner-direct fallback.
* :mod:`~apex_tpu.replay_service.client`  — learner-side round-robin
  batch puller + write-back router (driven by the ingest pipeline's
  staging thread).
"""

from apex_tpu.replay_service.client import ReplayServiceClient
from apex_tpu.replay_service.sender import ShardedChunkSender, chunk_shard
from apex_tpu.replay_service.shard import ReplayShardCore
from apex_tpu.replay_service.service import (ReplayShardServer,
                                             build_shard_core,
                                             run_replay_shard,
                                             shard_warmup)

__all__ = [
    "ReplayServiceClient", "ReplayShardCore", "ReplayShardServer",
    "ShardedChunkSender", "build_shard_core", "chunk_shard",
    "run_replay_shard", "shard_warmup",
]
