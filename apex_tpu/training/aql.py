"""AQL training: fused learner core + single-process driver.

Capability parity with the reference's single-process ``AQL.py`` (C12) on the
TPU architecture: the candidate-set Q loss and the proposal loss run as ONE
compiled XLA program per update (sample -> both losses -> two-group Adam ->
target sync -> priority write-back), against the generic HBM
:class:`~apex_tpu.replay.device.DeviceReplay` whose item pytree carries the
``a_mu`` candidate set (reference ``CustomPrioritizedReplayBuffer_AQL``,
``memory.py:364-391``).

Structural deltas from the reference (deliberate):

* Two ``value_and_grad`` passes share one params tree and merge by label —
  the reference's zero_grad/step interleaving (``AQL_dis.py:87-101``)
  expressed functionally; the proposal loss cannot leak into Q parameters
  (merge takes non-proposal leaves from the Q grads alone) and vice versa.
* NoisyNet/proposal/epsilon randomness all ride explicit PRNG keys.
* Initial priorities are 1-step TD errors computed from acting-time Q-values
  (the DQN path's actor-priority principle, ``memory.py:451-464``, applied
  to AQL — the reference inserts AQL transitions at max priority).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.config import ApexConfig
from apex_tpu.envs.registry import make_env, make_eval_env
from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
from apex_tpu.ops.losses import (aql_proposal_loss, aql_q_loss,
                                 make_aql_optimizer)
from apex_tpu.replay.base import check_hbm_budget
from apex_tpu.replay.device import DeviceReplay, ReplayState
from apex_tpu.training.apex import ConcurrentTrainer
from apex_tpu.training.learner import scan_fused_steps
from apex_tpu.training.checkpoint import (CheckpointableTrainer,
                                          Checkpointer)
from apex_tpu.training.state import TrainState
from apex_tpu.utils.metrics import MetricLogger, RateCounter
from apex_tpu.utils.seeding import set_global_seeds


@dataclass(frozen=True)
class AQLCore:
    """Static wiring of the AQL model/replay/optimizer into jitted steps."""

    model: AQLNetwork
    replay: DeviceReplay
    optimizer: optax.GradientTransformation
    batch_size: int = 64
    target_update_interval: int = 500
    entropy_coef: float = 0.01
    # the update consumes a PRNG key (NoisyNet draws) — ShardedLearner
    # splits its per-chip key between sampling and the update
    update_needs_key = True

    # -- functional model hooks -------------------------------------------

    def _score(self, params, obs, a_mu, noise_key):
        return self.model.apply(params, obs, a_mu,
                                rngs={"noise": noise_key})

    def _log_prob(self, params, obs, actions):
        return self.model.apply(params, obs, actions,
                                method=AQLNetwork.proposal_log_prob)

    # -- update body -------------------------------------------------------

    def update_from_batch(self, ts: TrainState, batch, weights,
                          key: jax.Array, axis_name: str | None = None):
        k_online, k_target = jax.random.split(key)

        def q_loss_fn(params):
            return aql_q_loss(self._score, params, ts.target_params, batch,
                              weights, k_online, k_target)

        (loss_q, aux), q_grads = jax.value_and_grad(
            q_loss_fn, has_aux=True)(ts.params)
        # argmax-Q candidate under the same online noise draw, straight from
        # the loss pass — no second scoring of the candidate set
        best_idx = aux.best_idx

        def p_loss_fn(params):
            return aql_proposal_loss(self._log_prob, params, batch,
                                     best_idx, self.entropy_coef)

        loss_p, p_grads = jax.value_and_grad(p_loss_fn)(ts.params)

        # merge by label: proposal leaves from the proposal pass, the rest
        # from the Q pass — neither loss can touch the other group
        from apex_tpu.ops.losses import aql_param_labels
        labels = aql_param_labels(ts.params)
        grads = jax.tree.map(
            lambda lbl, qg, pg: pg if lbl == "proposal" else qg,
            labels, q_grads, p_grads)

        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
            loss_q = jax.lax.pmean(loss_q, axis_name)
            loss_p = jax.lax.pmean(loss_p, axis_name)

        updates, opt_state = self.optimizer.update(grads, ts.opt_state,
                                                   ts.params)
        params = optax.apply_updates(ts.params, updates)
        step = ts.step + 1
        target_params = jax.lax.cond(
            step % self.target_update_interval == 0,
            lambda: jax.tree.map(jnp.copy, params),
            lambda: ts.target_params)

        q_mean, td_mean = aux.q_taken.mean(), aux.td_abs.mean()
        if axis_name is not None:
            q_mean = jax.lax.pmean(q_mean, axis_name)
            td_mean = jax.lax.pmean(td_mean, axis_name)
        metrics = {"loss": loss_q, "loss_proposal": loss_p,
                   "grad_norm": optax.global_norm(grads),
                   "q_mean": q_mean,
                   "td_mean": td_mean}
        ts = TrainState(params=params, target_params=target_params,
                        opt_state=opt_state, step=step)
        return ts, aux.priorities, metrics

    def train_step(self, ts: TrainState, rs: ReplayState, key: jax.Array,
                   beta: jax.Array):
        k_sample, k_update = jax.random.split(key)
        batch, weights, idx = self.replay.sample(rs, k_sample,
                                                 self.batch_size, beta)
        ts, priorities, metrics = self.update_from_batch(ts, batch, weights,
                                                         k_update)
        rs = self.replay.update_priorities(rs, idx, priorities)
        return ts, rs, metrics

    def ingest(self, rs: ReplayState, batch, priorities) -> ReplayState:
        return self.replay.add(rs, batch, priorities)

    def fused_step(self, ts, rs, ingest_batch, ingest_prios, key, beta):
        rs = self.ingest(rs, ingest_batch, ingest_prios)
        return self.train_step(ts, rs, key, beta)

    def fused_multi_step(self, ts, rs, ingest_batches, ingest_prios, keys,
                         beta):
        """K fused steps in one dispatch (the two-loss AQL update scans
        exactly like the DQN one) — see
        :func:`apex_tpu.training.learner.scan_fused_steps`."""
        return scan_fused_steps(self, ts, rs, ingest_batches, ingest_prios,
                                keys, beta)

    def jit_train_step(self):
        return jax.jit(self.train_step, donate_argnums=(0, 1))

    def jit_ingest(self):
        return jax.jit(self.ingest, donate_argnums=(0,))

    def jit_fused_step(self):
        return jax.jit(self.fused_step, donate_argnums=(0, 1))

    def jit_fused_multi_step(self):
        return jax.jit(self.fused_multi_step, donate_argnums=(0, 1))


class AQLTransitionBuilder:
    """Host-side 1-step transition buffer with acting-time TD priorities.

    The reference's AQL recorder stores raw transitions with no n-step
    window (``batchrecoder_AQL.py:43-59``).  Emission is delayed one step so
    the priority can use the NEXT state's candidate scores:
    ``|r + gamma * max q' - q[idx]|`` — fresher than the reference's
    max-priority inserts, same principle as the DQN actors.
    """

    def __init__(self, gamma: float):
        self.gamma = gamma
        self._pending = None          # (obs, idx, reward, next_obs, a_mu, q)
        self._rows: list[dict] = []

    def add_step(self, obs, idx, reward, next_obs, a_mu, q,
                 terminated: bool, truncated: bool) -> None:
        q_next_max = float(np.max(q))  # q is the CURRENT state's scores
        if self._pending is not None:
            self._emit(self._pending, bootstrap=q_next_max)
        self._pending = (np.asarray(obs), int(idx), float(reward),
                         np.asarray(next_obs), np.asarray(a_mu),
                         float(q[int(idx)]))
        if terminated:
            self._emit(self._pending, bootstrap=None, discount=0.0)
            self._pending = None
        elif truncated:
            # the learner will bootstrap Q(next_obs) (discount=gamma); the
            # final next state was never scored, so the PRIORITY uses the
            # current state's max-Q as the bootstrap proxy — close for
            # slowly-mixing states, and corrected at first write-back
            self._emit(self._pending, bootstrap=q_next_max,
                       discount=self.gamma)
            self._pending = None

    def _emit(self, t, bootstrap, discount=None) -> None:
        obs, idx, reward, next_obs, a_mu, q_taken = t
        disc = self.gamma if discount is None else discount
        boot = 0.0 if bootstrap is None else bootstrap
        prio = abs(reward + disc * boot - q_taken) + 1e-6
        self._rows.append(dict(obs=obs, action=np.int32(idx),
                               reward=np.float32(reward), next_obs=next_obs,
                               discount=np.float32(disc), a_mu=a_mu,
                               priority=np.float32(prio)))

    def __len__(self) -> int:
        return len(self._rows)

    def drain(self, count: int) -> tuple[dict, np.ndarray]:
        rows, self._rows = self._rows[:count], self._rows[count:]
        batch = {k: np.stack([r[k] for r in rows])
                 for k in ("obs", "action", "reward", "next_obs",
                           "discount", "a_mu")}
        prios = np.asarray([r["priority"] for r in rows], np.float32)
        return batch, prios


def aql_model_spec(cfg: ApexConfig, env) -> dict:
    """AQLNetwork constructor kwargs from config + env spaces — picklable,
    shippable to worker processes (the pool's ``model_spec``).

    Box spaces get the Gaussian proposal; Discrete spaces the Categorical
    one with ``uniform_sample`` clamped to the action count (reference
    ``model.py:176-184``)."""
    space = env.action_space
    common = dict(
        propose_sample=cfg.aql.propose_sample,
        uniform_sample=cfg.aql.uniform_sample,
        action_var=cfg.aql.action_var,
        obs_is_image=len(env.observation_space.shape) == 3,
        compute_dtype=jnp.dtype(cfg.learner.compute_dtype),
        scale_uint8=env.observation_space.dtype == np.uint8)
    if hasattr(space, "high"):                         # Box
        return dict(
            action_dim=int(np.prod(space.shape)),
            action_low=float(np.min(space.low)),
            action_high=float(np.max(space.high)),
            **common)
    if not hasattr(space, "n"):
        raise ValueError(f"AQL drives Box or Discrete action spaces, "
                         f"got {type(space).__name__}")
    n = int(space.n)
    common["uniform_sample"] = min(cfg.aql.uniform_sample, n)
    return dict(action_dim=n, discrete=True, **common)


def build_aql(cfg: ApexConfig, model_spec: dict, obs_shape, obs_dtype,
              key: jax.Array, cosine_steps: int | None = None,
              frame_spec: tuple | None = None):
    """(model, train_state, replay, replay_state, core) for either driver.

    ``cosine_steps``: CosineAnnealingLR horizon for both Adam groups —
    the single-process driver passes ``cfg.aql.cosine_lr_steps``
    (``AQL.py:48-49``); the concurrent driver passes 0 (``AQL_dis``
    constructs no schedulers).

    ``frame_spec``: ``(frame_shape, frame_dtype, frame_stack)`` switches
    the replay to the frame-pool layout with the ``a_mu`` candidate set as
    a per-transition sidecar — pixel AQL with frame dedup instead of 2S
    stacked copies per transition (the concurrent driver passes this for
    image observations; ingest then expects FrameChunkBuilder chunks)."""
    model = AQLNetwork(**model_spec)
    t = model.total_sample
    # discrete candidates are index values on a singleton trailing axis
    a_dim = 1 if model.discrete else model.action_dim
    example_obs = jnp.zeros((1,) + tuple(obs_shape), obs_dtype)
    example_a_mu = jnp.zeros((1, t, a_dim), jnp.float32)
    init_key, noise_key, sample_key = jax.random.split(key, 3)
    optimizer = make_aql_optimizer(
        q_lr=cfg.aql.q_lr, proposal_lr=cfg.aql.proposal_lr,
        max_grad_norm=cfg.learner.max_grad_norm,
        cosine_steps=cosine_steps)
    params = model.init(
        {"params": init_key, "noise": noise_key, "sample": sample_key},
        example_obs, example_a_mu, method=AQLNetwork.full_init)
    train_state = TrainState(
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=optimizer.init(params),
        step=jnp.int32(0))

    if frame_spec is not None:
        from apex_tpu.replay.frame_pool import FramePoolReplay
        frame_shape, frame_dtype, frame_stack = frame_spec
        replay = FramePoolReplay(
            capacity=cfg.replay.capacity, frame_shape=tuple(frame_shape),
            frame_stack=frame_stack,
            frame_dtype=np.dtype(frame_dtype).name,
            alpha=cfg.replay.alpha, eps=cfg.replay.eps,
            extra_spec=(("a_mu", (t, a_dim)),))
        check_hbm_budget(replay.hbm_bytes(), cfg.replay.hbm_budget_gb,
                         "AQL frame-pool replay (frames + a_mu sidecars)",
                         cfg.replay.capacity)
        replay_state = replay.init()
    else:
        replay = DeviceReplay(capacity=cfg.replay.capacity,
                              alpha=cfg.replay.alpha, eps=cfg.replay.eps)
        example_item = dict(
            obs=jnp.zeros(tuple(obs_shape), obs_dtype),
            action=jnp.int32(0), reward=jnp.float32(0),
            next_obs=jnp.zeros(tuple(obs_shape), obs_dtype),
            discount=jnp.float32(0),
            a_mu=jnp.zeros((t, a_dim), jnp.float32))
        check_hbm_budget(replay.hbm_bytes(example_item),
                         cfg.replay.hbm_budget_gb,
                         "AQL replay (stacked obs + a_mu candidate sets)",
                         cfg.replay.capacity)
        replay_state = replay.init(example_item)

    core = AQLCore(model=model, replay=replay, optimizer=optimizer,
                   batch_size=cfg.learner.batch_size,
                   target_update_interval=cfg.learner.target_update_interval,
                   entropy_coef=cfg.aql.entropy_coef)
    return model, train_state, replay, replay_state, core


class AQLTrainer(CheckpointableTrainer):
    """Single-process AQL driver (reference ``AQL.py:17-109``)."""

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 train_every: int = 1, checkpoint_dir: str | None = None):
        self.cfg = cfg = config or ApexConfig()
        self.key = set_global_seeds(cfg.env.seed)
        self.env = make_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed)
        self.model_spec = aql_model_spec(cfg, self.env)
        self.key, build_key = jax.random.split(self.key)
        (self.model, self.train_state, self.replay, self.replay_state,
         self.core) = build_aql(cfg, self.model_spec,
                                self.env.observation_space.shape,
                                self.env.observation_space.dtype, build_key,
                                cosine_steps=cfg.aql.cosine_lr_steps)
        self._train_step = self.core.jit_train_step()
        self._ingest = self.core.jit_ingest()
        self._policy = jax.jit(make_aql_policy_fn(self.model))
        eval_model = self.model.clone(noisy_deterministic=True)
        self._eval_policy = jax.jit(make_aql_policy_fn(eval_model))

        from apex_tpu.training.dqn import BetaSchedule, EpsilonSchedule
        self.builder = AQLTransitionBuilder(cfg.learner.gamma)
        self.epsilon = EpsilonSchedule(decay=4000.0)
        self.beta = BetaSchedule(start=cfg.replay.beta)
        self.ingest_chunk = cfg.learner.ingest_chunk
        self.train_every = train_every
        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.steps_rate = RateCounter()
        self.frames_rate = RateCounter()
        self.ingested = 0
        self.checkpointer = (Checkpointer(checkpoint_dir)
                             if checkpoint_dir else None)

    # -- checkpointing (A4): format/IO in CheckpointableTrainer ------------

    def _counters(self) -> dict:
        return dict(ingested=self.ingested, frames=self.frames_rate.total,
                    steps=self.steps_rate.total)

    def _apply_counters(self, meta: dict) -> None:
        self.ingested = meta["ingested"]
        self.frames_rate.total = meta["frames"]
        self.steps_rate.total = meta["steps"]

    # -- main loop ---------------------------------------------------------

    def train(self, total_frames: int, log_every: int = 500):
        """Run ``total_frames`` MORE env frames (schedules continue from a
        restored checkpoint's frame counter)."""
        cfg = self.cfg
        obs, _ = self.env.reset(seed=cfg.env.seed)
        ep_reward, ep_idx = 0.0, 0
        start = self.frames_rate.total

        for frame in range(start + 1, start + total_frames + 1):
            self.key, k = jax.random.split(self.key)
            obs_np = np.asarray(obs)
            actions, idx, a_mu, q = self._policy(
                self.train_state.params, obs_np[None],
                jnp.float32(self.epsilon(frame)), k)
            next_obs, reward, term, trunc, _ = self.env.step(
                np.asarray(actions[0]))
            self.builder.add_step(obs_np, int(idx[0]), float(reward),
                                  np.asarray(next_obs), np.asarray(a_mu[0]),
                                  np.asarray(q[0]), bool(term), bool(trunc))
            ep_reward += float(reward)
            self.frames_rate.tick()

            if term or trunc:
                obs, _ = self.env.reset()
                self.log.scalars({"episode_reward": ep_reward}, ep_idx)
                ep_reward, ep_idx = 0.0, ep_idx + 1
            else:
                obs = next_obs

            while len(self.builder) >= self.ingest_chunk:
                batch, prios = self.builder.drain(self.ingest_chunk)
                self.replay_state = self._ingest(self.replay_state, batch,
                                                 jnp.asarray(prios))
                self.ingested += len(prios)

            warm = self.ingested >= cfg.replay.warmup
            if warm and frame % self.train_every == 0:
                self.key, sk = jax.random.split(self.key)
                self.train_state, self.replay_state, metrics = \
                    self._train_step(self.train_state, self.replay_state,
                                     sk, jnp.float32(self.beta(frame)))
                self.steps_rate.tick()
                if (self.checkpointer is not None and self.steps_rate.total
                        % cfg.learner.save_interval == 0):
                    self.save_checkpoint()
                if self.steps_rate.total % log_every == 0:
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate},
                        self.steps_rate.total)
        return self

    # -- evaluation --------------------------------------------------------

    def evaluate(self, episodes: int = 10, epsilon: float = 0.0,
                 max_steps: int = 1000) -> float:
        """Greedy eval with deterministic (mu-only) NoisyNet heads."""
        return _aql_evaluate(self, episodes, epsilon, max_steps)


def _aql_evaluate(trainer, episodes: int, epsilon: float,
                  max_steps: int) -> float:
    if not hasattr(trainer, "_eval_env"):
        trainer._eval_env = make_eval_env(
            trainer.cfg.env.env_id, trainer.cfg.env,
            seed=trainer.cfg.env.seed + 999)
    rewards = []
    for ep in range(episodes):
        obs, _ = trainer._eval_env.reset(
            seed=trainer.cfg.env.seed + 1000 + ep)
        total, done, steps = 0.0, False, 0
        while not done and steps < max_steps:
            trainer.key, k = jax.random.split(trainer.key)
            a, _, _, _ = trainer._eval_policy(
                trainer.train_state.params, np.asarray(obs)[None],
                jnp.float32(epsilon), k)
            obs, r, term, trunc, _ = trainer._eval_env.step(
                np.asarray(a[0]))
            total += float(r)
            done = term or trunc
            steps += 1
        rewards.append(total)
    return float(np.mean(rewards))


class AQLApexTrainer(ConcurrentTrainer):
    """Distributed AQL driver (reference ``AQL_dis.py:18-135``, C12): the
    shared concurrent loop over an AQL actor pool.

    Unlike the reference's SYNCHRONOUS rounds — push weights, every worker
    runs exactly one episode, drain, train ``total_ep//batch_size`` times
    (``AQL_dis.py:112-126``) — workers explore continuously and the learner
    overlaps with acting, same as the DQN family; the replay-ratio band
    supplies the coupling the synchronous rounds provided.
    """

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 publish_min_seconds: float = 0.2,
                 train_ratio: float | None = None,
                 min_train_ratio: float | None = None,
                 checkpoint_dir: str | None = None,
                 pool=None):
        from apex_tpu.actors.aql import aql_worker_main
        from apex_tpu.actors.pool import ActorPool

        self.cfg = cfg = config or ApexConfig()
        self.key = set_global_seeds(cfg.env.seed)
        self.publish_min_seconds = publish_min_seconds
        self.train_ratio = train_ratio
        self.min_train_ratio = min_train_ratio
        self.respawn_workers = True
        if (train_ratio is not None and min_train_ratio is not None
                and min_train_ratio > train_ratio):
            raise ValueError("min_train_ratio must be <= train_ratio")

        # ONE un-stacked probe covers every case (env construction can be
        # expensive — ALE ROM loads): model_spec reads spaces that stacking
        # doesn't change, and the stacked obs shape is FrameStack's own
        # formula (wrappers.py:198-200) applied analytically.
        from apex_tpu.envs.registry import unstacked_env_spec
        probe = make_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed,
                         stack_frames=False)
        self.model_spec = aql_model_spec(cfg, probe)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            probe, cfg.env)
        probe.close()
        frame_spec = None
        if self.model_spec["obs_is_image"]:
            # pixel AQL rides the frame-pool layout: actor workers switch
            # to the chunk builder family and replay dedups frames
            frame_spec = (frame_shape, frame_dtype, frame_stack)
            obs_shape = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
            obs_dtype = frame_dtype
        elif cfg.env.frame_stack > 1:
            # non-image envs are cheap (numpy toys): re-probe stacked so
            # declared spaces stay authoritative for the odd vector+stack
            # combination
            p2 = make_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed)
            obs_shape = p2.observation_space.shape
            obs_dtype = p2.observation_space.dtype
            p2.close()
        else:
            obs_shape, obs_dtype = frame_shape, frame_dtype

        self.key, build_key = jax.random.split(self.key)
        (self.model, self.train_state, self.replay, self.replay_state,
         self.core) = build_aql(cfg, self.model_spec, obs_shape, obs_dtype,
                                build_key, frame_spec=frame_spec)
        eval_model = self.model.clone(noisy_deterministic=True)
        self._eval_policy = jax.jit(make_aql_policy_fn(eval_model))

        if pool is not None:
            self.pool = pool
        else:
            # AQL chunks: K x (obs + next_obs + a_mu candidate set +
            # scalars) — size the ring slot from the actual spec
            k = cfg.actor.send_interval
            act_dim = (1 if self.model_spec.get("discrete")
                       else self.model_spec["action_dim"])
            t = (self.model_spec["propose_sample"]
                 + self.model_spec["uniform_sample"])
            if frame_spec is not None:
                # frame chunk (single frames + refs) + a_mu sidecar rows
                from apex_tpu.native.ring import chunk_slot_bytes
                from apex_tpu.replay.frame_chunks import FRAME_MARGIN
                frame_shape, frame_dtype, frame_stack = frame_spec
                slot = chunk_slot_bytes(
                    frame_dim=int(np.prod(frame_shape)),
                    frame_dtype_size=np.dtype(frame_dtype).itemsize,
                    kf=k + FRAME_MARGIN, k=k,
                    stack=frame_stack) + k * 4 * act_dim * t
            else:
                obs_bytes = (int(np.prod(obs_shape))
                             * np.dtype(obs_dtype).itemsize)
                slot = k * (2 * obs_bytes + 4 * act_dim * (t + 1) + 32) + 65536
            worker = aql_worker_main
            if cfg.actor.n_envs_per_actor > 1:
                from apex_tpu.actors.aql import vector_aql_worker_main
                worker = vector_aql_worker_main
            self.pool = ActorPool(
                cfg, self.model_spec,
                chunk_transitions=cfg.actor.send_interval,
                worker_fn=worker, shm_slot_bytes=slot)

        self.n_dp = int(np.prod(cfg.learner.mesh_shape))
        if self.n_dp > 1:
            self._init_sharded()
        else:
            self._fused = self.core.jit_fused_step()
            self._train = self.core.jit_train_step()
            self._ingest = self.core.jit_ingest()
            if cfg.learner.scan_steps > 1:
                self.scan_steps = cfg.learner.scan_steps
                self._multi = self.core.jit_fused_multi_step()
        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.steps_rate = RateCounter()
        self.frames_rate = RateCounter()
        self.ingested = 0
        self.param_version = 0
        self.checkpointer = (Checkpointer(checkpoint_dir)
                             if checkpoint_dir else None)

    # _init_sharded: ConcurrentTrainer (one multi-chip plan, both families)

    def evaluate(self, episodes: int = 10, epsilon: float = 0.0,
                 max_steps: int = 1000) -> float:
        return _aql_evaluate(self, episodes, epsilon, max_steps)
