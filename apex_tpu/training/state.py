"""Explicit train-state pytree.

The reference scatters learner state across a torch module, a target module,
an optimizer object, and loop-local counters (``ApeX.py:32-43``,
``DQN.py:100-115``).  Here everything the learner mutates is ONE pytree, so a
step is a pure function, checkpointing is whole-state by construction
(improving on the reference's weights-only saves, ``learner.py:166-168``), and
sharding annotations apply uniformly.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array               # i32 scalar — learner update count


def create_train_state(model, optimizer: optax.GradientTransformation,
                       key: jax.Array, example_obs: jax.Array) -> TrainState:
    params = model.init(key, example_obs)
    return TrainState(
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=optimizer.init(params),
        step=jnp.int32(0),
    )
