"""Recurrent DQN (R2D2-style) driver — the reference's unfinished TODO.

The reference lists "recurrent DQN" as future work (``README.md:5``) and
ships nothing; this module implements the family end to end, TPU-first:

* **Model**: :class:`apex_tpu.models.recurrent.RecurrentDuelingDQN` —
  same Nature trunk / dueling heads as the DQN family with an LSTM
  between them, unrolled by ``lax.scan`` inside one compiled step.
* **Replay**: sequences ARE replay items.  :class:`DeviceReplay` is
  generic over item pytrees, so a prioritized SEQUENCE buffer is just
  items with ``[T, ...]`` leaves (obs/action/reward/discount/mask per
  step + the stored recurrent state) — no new storage layout, and the
  fused ingest/sample/update machinery applies unchanged.
* **Actor side**: :class:`SequenceBuilder` splits episodes into
  overlapping fixed-length sequences (R2D2's stride = unroll/2) and
  records the policy's recurrent state at each sequence start (the
  "stored state" strategy).
* **Loss**: :func:`apex_tpu.ops.losses.r2d2_loss` — burn-in prefix
  warms the state gradient-free, then n-step double-DQN over the unroll
  with per-sequence mixed max/mean priorities.

The long-context story of this framework (SURVEY.md §5.7's n-step
windows + frame stacking) extends here to genuinely recurrent sequence
replay: the memory horizon is the LSTM's, not the frame stack's.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.actors.r2d2 import (drain_grouped, pooled_sequence_message,
                                  sequence_message)
from apex_tpu.config import ApexConfig
from apex_tpu.envs.registry import make_env, make_eval_env, num_actions
from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                       make_recurrent_policy_fn)
from apex_tpu.ops.losses import PRIORITY_ETA, make_optimizer, r2d2_loss
from apex_tpu.replay.base import check_hbm_budget
from apex_tpu.replay.device import DeviceReplay
from apex_tpu.training.apex import ConcurrentTrainer
from apex_tpu.training.checkpoint import (CheckpointableTrainer,
                                          Checkpointer)
from apex_tpu.training.dqn import BetaSchedule, EpsilonSchedule
from apex_tpu.training.learner import scan_fused_steps, td_update
from apex_tpu.training.state import TrainState
from apex_tpu.utils.metrics import MetricLogger, RateCounter
from apex_tpu.utils.seeding import set_global_seeds


class SequenceBuilder:
    """Host-side episode-to-sequence splitter (R2D2 overlapping windows).

    Per step the caller provides the observation, action, reward,
    termination flag, and the policy's recurrent state BEFORE acting (the
    carry that produced the action).  Episodes are cut into sequences of
    ``t_total = burn_in + unroll + n_steps`` steps starting every
    ``stride`` steps; short tails are zero-padded with ``mask=0`` (padded
    ``discount=0`` also truncates every n-step product crossing the
    boundary, see :func:`r2d2_loss`).  A sequence is emitted only if its
    loss region (positions ``burn_in..``) contains at least one real
    step.
    """

    def __init__(self, burn_in: int, unroll: int, n_steps: int,
                 gamma: float, stride: int | None = None,
                 pooled: bool = False):
        self.burn_in, self.unroll, self.n_steps = burn_in, unroll, n_steps
        self.t_total = burn_in + unroll + n_steps
        self.stride = stride or max(1, unroll // 2)
        if pooled and self.stride > self.t_total:
            # The pooled message packer ships each episode's union
            # coverage [min start, max end) as ONE contiguous block sized
            # for OVERLAPPING windows (<= t_total rows per sequence,
            # actors/r2d2.py:pooled_sequence_message); stride > t_total
            # leaves gaps inside that block and overflows the fixed
            # [G*T+1] frame buffer.  Raised HERE, where the pooled layout
            # is selected — a ValueError survives `python -O`, unlike the
            # bare assert that used to catch this at pack time.
            raise ValueError(
                f"pooled sequence layout requires stride <= t_total "
                f"(burn_in + unroll + n_steps = {self.t_total}), got "
                f"stride={self.stride}")
        self.gamma = gamma
        # pooled: emit frame REFERENCES for the dedup sequence frame-pool
        # layout (apex_tpu/replay/seq_pool.py) — sequences share one
        # episode frame array instead of each copying its padded window;
        # pooled_sequence_message packs the shared frames once per message
        self.pooled = pooled
        self._obs: list = []
        self._action: list = []
        self._reward: list = []
        self._discount: list = []
        self._carry: list = []
        self._q: list = []
        self._out: list[dict] = []

    @property
    def needs_carry(self) -> bool:
        """True when the NEXT ``add_step`` starts a sequence window (a
        stride boundary): only those carries are ever read back, so the
        caller can skip the device->host carry transfer everywhere else
        (two blocking syncs per frame otherwise)."""
        return len(self._obs) % self.stride == 0

    def add_step(self, obs, action: int, reward: float, terminated: bool,
                 carry_c: np.ndarray | None, carry_h: np.ndarray | None,
                 q_values: np.ndarray | None = None) -> None:
        """``carry_c``/``carry_h`` may be None except when
        :attr:`needs_carry` was True before this call.  ``q_values`` (the
        acting-time Q vector) feeds the insert-priority heuristic; omit it
        and sequences insert at priority 1."""
        if len(self._obs) % self.stride == 0 and carry_c is None:
            raise ValueError("sequence-start step needs its carry "
                             "(check builder.needs_carry before acting)")
        self._obs.append(np.asarray(obs))
        self._action.append(int(action))
        self._reward.append(float(reward))
        self._discount.append(0.0 if terminated else self.gamma)
        self._carry.append(
            None if carry_c is None
            else (np.asarray(carry_c), np.asarray(carry_h)))
        self._q.append(None if q_values is None
                       else np.asarray(q_values, np.float32))

    def end_episode(self, truncated: bool = False) -> None:
        """Cut the finished episode into sequences; clears step buffers.

        ``truncated``: the episode ended by time limit, not termination.
        Loss positions whose n-step window crosses a TRUNCATION boundary
        would bootstrap from padded all-zero observations at full weight
        ``gamma^n`` (a terminated boundary is safe: its ``discount=0``
        kills the product) — those positions get ``mask=0``, excluding
        them from the loss entirely.  The DQN family's analogue stores
        ``final_obs`` and bootstraps truncation-correctly
        (:mod:`apex_tpu.replay.nstep`); for sequences, dropping the last
        ``n_steps`` loss positions is the standard unbiased treatment.
        """
        n = len(self._obs)
        if n == 0:
            return
        mask_full = np.ones(n, np.float32)
        if truncated:
            mask_full[max(0, n - self.n_steps):] = 0.0
        td_full = self._acting_time_tds(n)
        obs = np.stack(self._obs)
        emitted: list[dict] = []
        starts: list[int] = []
        start = 0
        while start + self.burn_in < n:
            end = min(start + self.t_total, n)
            pad = self.t_total - (end - start)
            m = _pad(mask_full[start:end], pad)
            lm = m[self.burn_in:self.burn_in + self.unroll]
            if not lm.any():
                break            # loss region entirely padded/masked
            c, h = self._carry[start]
            seq = dict(
                action=_pad(np.asarray(self._action[start:end], np.int32),
                            pad),
                reward=_pad(np.asarray(self._reward[start:end], np.float32),
                            pad),
                discount=_pad(np.asarray(self._discount[start:end],
                                         np.float32), pad),
                mask=m,
                state_c=c.astype(np.float32),
                state_h=h.astype(np.float32),
            )
            if self.pooled:
                # the episode array is SHARED by every window over it —
                # the message packer ships each referenced frame once
                seq["ep_frames"], seq["start"], seq["end"] = obs, start, end
            else:
                seq["obs"] = _pad(obs[start:end], pad)
            if td_full is not None:
                td = _pad(td_full[start:end], pad)[
                    self.burn_in:self.burn_in + self.unroll] * lm
                nv = max(lm.sum(), 1.0)
                seq["priority"] = np.float32(
                    PRIORITY_ETA * td.max()
                    + (1.0 - PRIORITY_ETA) * td.sum() / nv + 1e-6)
            else:
                seq["priority"] = np.float32(1.0)
            emitted.append(seq)
            starts.append(start)
            start += self.stride
        # n_new: NEW env transitions this sequence contributes vs its
        # overlapping predecessors — step t counts exactly once across the
        # episode, so transition-denominated gates (warmup, replay ratio)
        # stay honest despite the stride overlap
        for i, (seq, s) in enumerate(zip(emitted, starts)):
            nxt = starts[i + 1] if i + 1 < len(starts) else n
            seq["n_new"] = int(min(nxt, n) - s)
        self._out.extend(emitted)
        self._obs, self._action, self._reward = [], [], []
        self._discount, self._carry, self._q = [], [], []

    def _acting_time_tds(self, n: int) -> np.ndarray | None:
        """Per-step 1-step |TD| from the acting-time Q vectors — the
        sequence analogue of the DQN actors' priorities-without-rerunning
        (``memory.py:451-464``): ``|r + disc * max q' - q[a]|``, bootstrap
        0 past the episode end.  The learner's unrolled n-step write-back
        replaces these after the first sample; they only order the replay
        until then.  None when any step lacked its Q vector."""
        if any(q is None for q in self._q):
            return None
        maxq = np.asarray([float(q.max()) for q in self._q] + [0.0],
                          np.float32)
        td = np.empty(n, np.float32)
        for t in range(n):
            td[t] = abs(self._reward[t]
                        + self._discount[t] * maxq[t + 1]
                        - float(self._q[t][self._action[t]]))
        return td

    def drain(self) -> list[dict]:
        out, self._out = self._out, []
        return out


def _pad(arr: np.ndarray, pad: int) -> np.ndarray:
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths)


@dataclass(frozen=True)
class R2D2Core:
    """Static wiring of the recurrent model/replay/optimizer into jitted
    steps — the recurrent sibling of :class:`LearnerCore`/:class:`AQLCore`
    (same ``ingest``/``train_step`` signature, so
    :func:`apex_tpu.training.learner.scan_fused_steps` applies)."""

    model: RecurrentDuelingDQN
    replay: object          # DeviceReplay | SequenceFramePoolReplay
    optimizer: optax.GradientTransformation
    batch_size: int = 64
    target_update_interval: int = 2500
    burn_in: int = 8
    n_steps: int = 3

    def update_from_batch(self, ts: TrainState, batch, weights,
                          axis_name: str | None = None):
        def loss_fn(params):
            return r2d2_loss(self.model.apply, params, ts.target_params,
                             batch, weights, burn_in=self.burn_in,
                             n_steps=self.n_steps)

        return td_update(self.optimizer, self.target_update_interval,
                         ts, loss_fn, axis_name)

    def train_step(self, ts, rs, key, beta):
        batch, weights, idx = self.replay.sample(rs, key, self.batch_size,
                                                 beta)
        ts, priorities, metrics = self.update_from_batch(ts, batch, weights)
        rs = self.replay.update_priorities(rs, idx, priorities)
        return ts, rs, metrics

    def ingest(self, rs, batch, priorities):
        return self.replay.add(rs, batch, priorities)

    def fused_step(self, ts, rs, ingest_batch, ingest_prios, key, beta):
        rs = self.ingest(rs, ingest_batch, ingest_prios)
        return self.train_step(ts, rs, key, beta)

    def fused_multi_step(self, ts, rs, ingest_batches, ingest_prios, keys,
                         beta):
        """K fused steps in one dispatch — see
        :func:`apex_tpu.training.learner.scan_fused_steps`."""
        return scan_fused_steps(self, ts, rs, ingest_batches, ingest_prios,
                                keys, beta)

    def jit_train_step(self):
        return jax.jit(self.train_step, donate_argnums=(0, 1))

    def jit_ingest(self):
        return jax.jit(self.ingest, donate_argnums=(0,))

    def jit_fused_step(self):
        return jax.jit(self.fused_step, donate_argnums=(0, 1))

    def jit_fused_multi_step(self):
        return jax.jit(self.fused_multi_step, donate_argnums=(0, 1))


def r2d2_env_specs(cfg: ApexConfig):
    """(model_spec, obs_shape, obs_dtype) for the recurrent family —
    single-frame observations (the LSTM is the memory).  Shared by the
    drivers and the socket roles."""
    import dataclasses as _dc

    cfg1 = cfg.replace(env=_dc.replace(cfg.env, frame_stack=1))
    probe = make_env(cfg1.env.env_id, cfg1.env, seed=cfg1.env.seed)
    obs_shape = probe.observation_space.shape
    obs_dtype = probe.observation_space.dtype
    spec = dict(
        num_actions=num_actions(probe),
        obs_is_image=len(obs_shape) == 3,
        compute_dtype=jnp.dtype(cfg.learner.compute_dtype),
        scale_uint8=obs_dtype == np.dtype(np.uint8),
        lstm_features=cfg.r2d2.lstm_features)
    probe.close()
    return spec, obs_shape, obs_dtype


def r2d2_model_spec(cfg: ApexConfig) -> dict:
    return r2d2_env_specs(cfg)[0]


def r2d2_uses_frame_pool(cfg: ApexConfig, obs_shape) -> bool:
    """THE one predicate deciding the recurrent family's storage layout —
    shared by :func:`build_r2d2` and the worker families so the learner's
    replay spec and the actors' message format cannot diverge.  Pooled
    storage dedups pixel frames; vector observations stay on the stacked
    layout (rows too small for the ring economics to matter)."""
    return bool(cfg.replay.frame_pool) and len(obs_shape) == 3


def r2d2_frame_capacity(cfg: ApexConfig) -> int:
    """Frame-ring rows for the pooled sequence layout.  Each live
    sequence references ~``stride`` frames new to it plus its share of
    the cross-message window reshipping (``(t_total - stride)/group``
    rows, :func:`apex_tpu.actors.r2d2.pooled_sequence_message`); 1.5x
    headroom keeps the staleness redirect a measure-zero event under
    episode-boundary jitter."""
    rc, lc = cfg.r2d2, cfg.learner
    t_total = rc.burn_in + rc.unroll + lc.n_steps
    stride = rc.stride or max(1, rc.unroll // 2)
    per_seq = stride + -(-(t_total - stride + 1) // rc.sequence_group)
    return max(2 * t_total, int(1.5 * cfg.replay.capacity * per_seq))


def build_r2d2(cfg: ApexConfig, key: jax.Array):
    """(model_spec, obs_shape, obs_dtype, model, replay, replay_state,
    train_state, core) — THE one definition of the family's replay item
    schema and core wiring, shared by the single-process and concurrent
    drivers (two hand-kept copies would let checkpoint bundles and replay
    layouts silently diverge between them)."""
    rc, lc = cfg.r2d2, cfg.learner
    model_spec, obs_shape, obs_dtype = r2d2_env_specs(cfg)
    model = RecurrentDuelingDQN(**model_spec)

    t_total = rc.burn_in + rc.unroll + lc.n_steps
    if r2d2_uses_frame_pool(cfg, obs_shape):
        from apex_tpu.replay.seq_pool import SequenceFramePoolReplay
        replay = SequenceFramePoolReplay(
            capacity=cfg.replay.capacity, t_total=t_total,
            lstm_features=rc.lstm_features, frame_shape=tuple(obs_shape),
            frame_capacity=r2d2_frame_capacity(cfg),
            frame_dtype=str(np.dtype(obs_dtype)),
            alpha=cfg.replay.alpha, eps=cfg.replay.eps)
        check_hbm_budget(replay.hbm_bytes(), cfg.replay.hbm_budget_gb,
                         "R2D2 replay (pooled sequence storage)",
                         cfg.replay.capacity)
        replay_state = replay.init()
    else:
        replay = DeviceReplay(capacity=cfg.replay.capacity,
                              alpha=cfg.replay.alpha, eps=cfg.replay.eps)
        example_item = dict(
            obs=jnp.zeros((t_total,) + obs_shape, obs_dtype),
            action=jnp.zeros(t_total, jnp.int32),
            reward=jnp.zeros(t_total, jnp.float32),
            discount=jnp.zeros(t_total, jnp.float32),
            mask=jnp.zeros(t_total, jnp.float32),
            state_c=jnp.zeros(rc.lstm_features, jnp.float32),
            state_h=jnp.zeros(rc.lstm_features, jnp.float32))
        check_hbm_budget(replay.hbm_bytes(example_item),
                         cfg.replay.hbm_budget_gb,
                         "R2D2 replay (sequence storage)",
                         cfg.replay.capacity)
        replay_state = replay.init(example_item)

    optimizer = make_optimizer(
        lr=lc.lr, decay=lc.rmsprop_decay, eps=lc.rmsprop_eps,
        centered=lc.rmsprop_centered, max_grad_norm=lc.max_grad_norm,
        lr_decay_steps=lc.lr_decay_steps, lr_decay_rate=lc.lr_decay_rate)
    params = model.init(key, jnp.zeros((1, t_total) + obs_shape, obs_dtype),
                        model.initial_state(1))
    train_state = TrainState(
        params=params, target_params=jax.tree.map(jnp.copy, params),
        opt_state=optimizer.init(params), step=jnp.int32(0))
    core = R2D2Core(model=model, replay=replay, optimizer=optimizer,
                    batch_size=lc.batch_size,
                    target_update_interval=lc.target_update_interval,
                    burn_in=rc.burn_in, n_steps=lc.n_steps)
    return (model_spec, obs_shape, obs_dtype, model, replay, replay_state,
            train_state, core)


def _r2d2_evaluate(self, episodes: int = 10, epsilon: float = 0.0,
                   max_steps: int = 10_000) -> float:
    """Greedy recurrent eval shared by both R2D2 drivers: the carry
    threads within each episode and resets between them."""
    from apex_tpu.training.checkpoint import run_policy_episodes

    if not hasattr(self, "_eval_env"):
        self._eval_env = make_eval_env(self.cfg.env.env_id, self.cfg.env,
                                       seed=self.cfg.env.seed + 999)
    carry_box = [self.model.initial_state(1)]

    def step_fn(obs, eps, k):
        a, _, carry_box[0] = self._policy(self.train_state.params, obs,
                                          carry_box[0], eps, k)
        return int(a[0])

    self.key, eval_key = jax.random.split(self.key)
    rewards = run_policy_episodes(
        self._eval_env, step_fn, eval_key, episodes, epsilon, max_steps,
        seed_base=self.cfg.env.seed + 1000,
        reset_hook=lambda: carry_box.__setitem__(
            0, self.model.initial_state(1)))
    return float(np.mean(rewards))


class R2D2Trainer(CheckpointableTrainer):
    """Single-process recurrent driver, mirroring :class:`DQNTrainer`'s
    loop with a stateful policy: the recurrent carry threads through the
    episode and resets at boundaries; each env step feeds the
    SequenceBuilder with the carry that produced the action."""

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 train_every: int = 4, checkpoint_dir: str | None = None):
        import dataclasses as _dc
        cfg = config or ApexConfig()
        # single frames for the recurrent family: the LSTM is the memory,
        # a frame stack would quadruple sequence-replay HBM for nothing
        # (models/recurrent.py module docstring); the replaced cfg is what
        # checkpoints save, so enjoy/eval rebuild the same env
        cfg = cfg.replace(env=_dc.replace(cfg.env, frame_stack=1))
        self.cfg = cfg
        self.key = set_global_seeds(cfg.env.seed)
        self.env = make_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed,
                            max_episode_steps=cfg.actor.max_episode_length)
        rc, lc = cfg.r2d2, cfg.learner
        self.key, init_key = jax.random.split(self.key)
        (self.model_spec, _obs_shape, _obs_dtype, self.model, self.replay,
         self.replay_state, self.train_state, self.core) = build_r2d2(
            cfg, init_key)
        self._train_step = self.core.jit_train_step()
        self._ingest = self.core.jit_ingest()
        self._policy = jax.jit(make_recurrent_policy_fn(self.model))

        from apex_tpu.replay.seq_pool import SequenceFramePoolReplay
        self.pooled = isinstance(self.replay, SequenceFramePoolReplay)
        self._message_fn = (pooled_sequence_message if self.pooled
                            else sequence_message)
        self.builder = SequenceBuilder(rc.burn_in, rc.unroll, lc.n_steps,
                                       lc.gamma, stride=rc.stride,
                                       pooled=self.pooled)
        self._pending: list[dict] = []
        self.transitions = 0
        self.ingest_group = rc.sequence_group
        self.train_every = train_every
        self.epsilon = EpsilonSchedule()
        self.beta = BetaSchedule(start=cfg.replay.beta)
        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.frames_rate = RateCounter()
        self.steps_rate = RateCounter()
        self.sequences = 0
        self.checkpointer = (Checkpointer(checkpoint_dir)
                             if checkpoint_dir else None)

    # -- checkpointing (A4) ------------------------------------------------

    def _counters(self) -> dict:
        return dict(sequences=self.sequences, frames=self.frames_rate.total,
                    steps=self.steps_rate.total, transitions=self.transitions)

    def _apply_counters(self, meta: dict) -> None:
        self.sequences = meta["sequences"]
        self.frames_rate.total = meta["frames"]
        self.steps_rate.total = meta["steps"]
        # absent in pre-round-5 checkpoints: fall back to the old
        # sequence-derived estimate so resumes stay monotonic
        self.transitions = meta.get(
            "transitions", meta["sequences"] * self.builder.t_total)

    # -- main loop ---------------------------------------------------------

    def train(self, total_frames: int, log_every: int = 1000,
              warmup_sequences: int | None = None):
        cfg = self.cfg
        # warmup gates on UNIQUE env transitions accumulated (sum of each
        # sequence's n_new), not sequence count: with stride < t_total the
        # windows overlap, so seq_count * t_total overstates coverage
        # ~t_total/stride-fold.  Matches the concurrent trainer's
        # ``ingested >= warmup`` semantics.  A sequence floor of one full
        # batch keeps early sampling from being all-duplicates.
        warmup_seqs = (warmup_sequences if warmup_sequences is not None
                       else cfg.learner.batch_size)
        warmup_trans = 0 if warmup_sequences is not None \
            else cfg.replay.warmup
        obs, _ = self.env.reset(seed=cfg.env.seed)
        carry = self.model.initial_state(1)
        episode_reward, episode_len, episode_idx = 0.0, 0, 0
        start = self.frames_rate.total

        for frame in range(start + 1, start + total_frames + 1):
            eps = self.epsilon(frame)
            self.key, act_key = jax.random.split(self.key)
            obs_np = np.asarray(obs)
            # materialize the pre-action carry only at sequence starts —
            # the builder reads nothing else, and each np.asarray is a
            # blocking device sync
            if self.builder.needs_carry:
                cc = np.asarray(carry[0][0])
                ch = np.asarray(carry[1][0])
            else:
                cc = ch = None
            actions, q, carry = self._policy(
                self.train_state.params, obs_np[None], carry,
                jnp.float32(eps), act_key)
            action = int(actions[0])

            next_obs, reward, terminated, truncated, _ = self.env.step(action)
            self.builder.add_step(obs_np, action, float(reward),
                                  bool(terminated), cc, ch,
                                  q_values=np.asarray(q[0]))
            obs = next_obs
            episode_reward += float(reward)
            episode_len += 1
            self.frames_rate.tick()

            if terminated or truncated:
                self.builder.end_episode(
                    truncated=bool(truncated and not terminated))
                # grouped fixed-shape ingest: stacks of exactly
                # ingest_group sequences -> one transfer + one dispatch,
                # no per-count retrace; remainders wait for the next
                # episode's drain
                self._pending.extend(self.builder.drain())
                for msg in drain_grouped(self._pending, self.ingest_group,
                                         self._message_fn):
                    self.replay_state = self._ingest(
                        self.replay_state, msg["payload"],
                        jnp.asarray(msg["priorities"]))
                    self.sequences += self.ingest_group
                    self.transitions += int(msg["n_trans"])
                obs, _ = self.env.reset()
                carry = self.model.initial_state(1)
                self.log.scalars({"episode_reward": episode_reward,
                                  "episode_length": episode_len}, episode_idx)
                episode_reward, episode_len = 0.0, 0
                episode_idx += 1

            if (self.sequences >= warmup_seqs
                    and self.transitions >= warmup_trans
                    and frame % self.train_every == 0):
                self.key, step_key = jax.random.split(self.key)
                self.train_state, self.replay_state, metrics = \
                    self._train_step(self.train_state, self.replay_state,
                                     step_key, jnp.float32(self.beta(frame)))
                self.steps_rate.tick()
                if (self.checkpointer is not None and self.steps_rate.total
                        % cfg.learner.save_interval == 0):
                    self.save_checkpoint()
                if self.steps_rate.total % log_every == 0:
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate,
                           "sequences": self.sequences},
                        self.steps_rate.total)
        return self

    # -- evaluation (shared with the concurrent trainer) -------------------

    evaluate = _r2d2_evaluate


class R2D2ApexTrainer(ConcurrentTrainer):
    """Concurrent distributed R2D2 — the third family on the shared
    Ape-X machinery: worker processes act statefully through
    :class:`apex_tpu.actors.r2d2.R2D2WorkerFamily` (epsilon ladder,
    conflating param queues, respawn) and ship grouped sequence messages;
    the learner runs the fused sequence ingest+train step, optionally
    scan-dispatched (``config.scan_steps``) or dp-sharded
    (``config.learner.mesh_shape``).

    Unit note: the replay-ratio knobs (``train_ratio``/
    ``min_train_ratio``) compare learner SEQUENCES consumed (batch_size
    counts sequences) against TRANSITIONS ingested — set them with the
    sequence length in mind, or leave None (fully decoupled, the
    reference behavior).
    """

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 publish_min_seconds: float = 0.2,
                 train_ratio: float | None = None,
                 min_train_ratio: float | None = None,
                 checkpoint_dir: str | None = None,
                 pool=None, respawn_workers: bool = True):
        import dataclasses as _dc

        from apex_tpu.actors.pool import ActorPool
        from apex_tpu.actors.r2d2 import r2d2_worker_main

        cfg = config or ApexConfig()
        cfg = cfg.replace(env=_dc.replace(cfg.env, frame_stack=1))
        self.cfg = cfg
        self.key = set_global_seeds(cfg.env.seed)
        self.publish_min_seconds = publish_min_seconds
        self.train_ratio = train_ratio
        self.min_train_ratio = min_train_ratio
        self.respawn_workers = respawn_workers
        if (train_ratio is not None and min_train_ratio is not None
                and min_train_ratio > train_ratio):
            raise ValueError("min_train_ratio must be <= train_ratio")

        rc, lc = cfg.r2d2, cfg.learner
        self.key, init_key = jax.random.split(self.key)
        (self.model_spec, obs_shape, obs_dtype, self.model, self.replay,
         self.replay_state, self.train_state, self.core) = build_r2d2(
            cfg, init_key)
        self._policy = jax.jit(make_recurrent_policy_fn(self.model))

        if pool is not None:
            self.pool = pool
        else:
            worker = r2d2_worker_main
            if cfg.actor.n_envs_per_actor > 1:
                from apex_tpu.actors.r2d2 import vector_r2d2_worker_main
                worker = vector_r2d2_worker_main
            group = rc.sequence_group
            t_total = rc.burn_in + rc.unroll + lc.n_steps
            obs_bytes = int(np.prod(obs_shape)) * np.dtype(obs_dtype).itemsize
            # covers BOTH layouts: stacked ships G*T obs windows; pooled
            # ships <= G*T+1 frame rows plus the i32 obs_ref table
            slot = (group * t_total + 1) * obs_bytes \
                + group * t_total * 24 \
                + group * 8 * rc.lstm_features + 65536
            self.pool = ActorPool(cfg, self.model_spec,
                                  chunk_transitions=group,
                                  worker_fn=worker,
                                  shm_slot_bytes=slot)

        self.n_dp = int(np.prod(lc.mesh_shape))
        if self.n_dp > 1:
            self._init_sharded()
        else:
            self._fused = self.core.jit_fused_step()
            self._train = self.core.jit_train_step()
            self._ingest = self.core.jit_ingest()
            if lc.scan_steps > 1:
                self.scan_steps = lc.scan_steps
                self._multi = self.core.jit_fused_multi_step()

        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.steps_rate = RateCounter()
        self.frames_rate = RateCounter()
        self.ingested = 0
        self.param_version = 0
        self.checkpointer = (Checkpointer(checkpoint_dir)
                             if checkpoint_dir else None)

    evaluate = _r2d2_evaluate
