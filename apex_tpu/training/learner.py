"""The fused learner step: one XLA program per update.

The reference's learner hot loop (``origin_repo/learner.py:152-170``) crosses
the host/device boundary five times per update: queue.get -> H2D copy ->
forward x3 -> backward -> optimizer -> D2H of new priorities -> queue.put.
On TPU all of it fuses into ONE compiled program over donated HBM buffers:

    ingest K transitions -> PER-sample B -> loss/grads -> clip+RMSprop ->
    periodic target sync -> priority write-back

The only host<->device traffic per step is the staged ingest chunk in and a
few scalar metrics out.  Replay never leaves HBM; priorities never leave HBM.
Target sync (``learner.py:163-165``) is a ``lax.cond`` on the step counter,
compiled into the same program instead of a host-side branch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops.losses import double_dqn_loss, make_optimizer
from apex_tpu.replay.device import DeviceReplay, ReplayState
from apex_tpu.training.state import TrainState, create_train_state


class ReplayLike(Protocol):
    """The duck-typed replay contract LearnerCore depends on — satisfied by
    both :class:`DeviceReplay` (stacked pytree batches) and
    :class:`apex_tpu.replay.frame_pool.FramePoolReplay` (frame chunks)."""

    def add(self, state, batch, priorities): ...

    def sample(self, state, key, batch_size, beta,
               axis_name: str | None = None): ...
    # axis_name: the sharded learner passes the dp mesh axis so IS-weight
    # normalization can collective over it (PERMethods.is_weights)

    def update_priorities(self, state, idx, priorities): ...


@dataclass(frozen=True)
class LearnerCore:
    """Static wiring of model/replay/optimizer into jitted step functions.

    ``apply_fn`` must be a plain callable ``(params, obs) -> q_values``.
    """

    apply_fn: Callable[..., jax.Array]
    replay: ReplayLike
    optimizer: optax.GradientTransformation
    batch_size: int = 512
    target_update_interval: int = 2500

    # -- step functions ----------------------------------------------------

    def update_from_batch(self, train_state: TrainState, batch: Any,
                          weights: jax.Array, axis_name: str | None = None):
        """The update body shared by every single-optimizer learner
        variant — see :func:`td_update`."""

        def loss_fn(params):
            return double_dqn_loss(self.apply_fn, params,
                                   train_state.target_params, batch, weights)

        return td_update(self.optimizer, self.target_update_interval,
                         train_state, loss_fn, axis_name)

    def train_step(self, train_state: TrainState, replay_state: ReplayState,
                   key: jax.Array, beta: jax.Array):
        """Sample -> loss -> update -> priorities.  Pure; jit via make_*."""
        batch, weights, idx = self.replay.sample(
            replay_state, key, self.batch_size, beta)
        train_state, priorities, metrics = self.update_from_batch(
            train_state, batch, weights)
        replay_state = self.replay.update_priorities(replay_state, idx,
                                                     priorities)
        return train_state, replay_state, metrics

    def ingest(self, replay_state: ReplayState, batch: Any,
               priorities: jax.Array) -> ReplayState:
        return self.replay.add(replay_state, batch, priorities)

    def fused_step(self, train_state: TrainState, replay_state: ReplayState,
                   ingest_batch: Any, ingest_prios: jax.Array,
                   key: jax.Array, beta: jax.Array):
        """ingest + train in one program — the Ape-X learner inner loop."""
        replay_state = self.ingest(replay_state, ingest_batch, ingest_prios)
        return self.train_step(train_state, replay_state, key, beta)

    def fused_multi_step(self, train_state: TrainState,
                         replay_state: ReplayState, ingest_batches: Any,
                         ingest_prios: jax.Array, keys: jax.Array,
                         beta: jax.Array):
        """K fused steps in ONE dispatch — see :func:`scan_fused_steps`."""
        return scan_fused_steps(self, train_state, replay_state,
                                ingest_batches, ingest_prios, keys, beta)

    # -- jitted entry points (donated buffers) -----------------------------

    def jit_train_step(self):
        return jax.jit(self.train_step, donate_argnums=(0, 1))

    def jit_ingest(self):
        return jax.jit(self.ingest, donate_argnums=(0,))

    def jit_fused_step(self):
        return jax.jit(self.fused_step, donate_argnums=(0, 1))

    def jit_fused_multi_step(self):
        return jax.jit(self.fused_multi_step, donate_argnums=(0, 1))


def td_update(optimizer, target_update_interval: int,
              train_state: TrainState, loss_fn, axis_name: str | None):
    """The single-optimizer TD update body: loss/grads -> (optional
    cross-chip pmean) -> clip+optimizer -> periodic target sync.

    ``loss_fn(params) -> (loss, TDOutput)`` is the only family-specific
    piece — the DQN core passes the stacked-batch double-DQN loss, the
    recurrent core the sequence loss.  ``axis_name`` is the mesh axis to
    all-reduce gradients/metrics over (the sharded learner passes
    ``"dp"``); ``None`` = single chip.  One body, one numerical contract
    (SURVEY.md §3.3); AQL's two-optimizer update is the one deliberate
    exception (:class:`apex_tpu.training.aql.AQLCore`).

    Returns ``(train_state, priorities, metrics)``.
    """
    (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        train_state.params)
    if axis_name is not None:
        grads = jax.lax.pmean(grads, axis_name)         # ICI all-reduce
        loss = jax.lax.pmean(loss, axis_name)
    updates, opt_state = optimizer.update(
        grads, train_state.opt_state, train_state.params)
    params = optax.apply_updates(train_state.params, updates)

    step = train_state.step + 1
    target_params = jax.lax.cond(
        step % target_update_interval == 0,
        lambda: jax.tree.map(jnp.copy, params),
        lambda: train_state.target_params)

    q_mean = aux.q_taken.mean()
    td_mean = aux.td_abs.mean()
    if axis_name is not None:
        q_mean = jax.lax.pmean(q_mean, axis_name)
        td_mean = jax.lax.pmean(td_mean, axis_name)
    metrics = {
        "loss": loss,
        "grad_norm": optax.global_norm(grads),
        "q_mean": q_mean,
        "td_mean": td_mean,
    }
    train_state = TrainState(params=params, target_params=target_params,
                             opt_state=opt_state, step=step)
    return train_state, aux.priorities, metrics


def scan_fused_steps(core, train_state, replay_state, ingest_batches,
                     ingest_prios, keys, beta):
    """K fused steps in ONE dispatch: ``lax.scan`` over chunk/prio/key
    stacks with a leading axis of K.  Works for ANY core exposing
    ``ingest`` + ``train_step`` with the shared signature (DQN
    :class:`LearnerCore`, :class:`apex_tpu.training.aql.AQLCore`).

    Each scan iteration is bit-identical to one ``fused_step`` (same
    ingest -> sample -> update -> write-back program, same keys -> same
    samples), so the numerical contract is unchanged — only the
    host<->device round-trip count drops from K to 1.  That matters
    because dispatch latency is pure overhead on the learner hot path
    (the reference pays it as queue.get + H2D per batch,
    ``origin_repo/learner.py:152-170``; this framework pays it as an RPC
    on relay-backed chips).  Metrics come back stacked ``[K]``.

    ``beta`` may be a scalar (one annealing value for all K steps) or a
    ``[K]`` stack — the concurrent trainer passes the per-step stack the
    single-dispatch path would have computed as ingestion advanced, so
    the two dispatch shapes anneal identically.
    """
    k_steps = keys.shape[0]
    betas = jnp.broadcast_to(jnp.asarray(beta, jnp.float32), (k_steps,))

    def body(carry, xs):
        ts, rs = carry
        chunk, prios, key, b = xs
        rs = core.ingest(rs, chunk, prios)
        ts, rs, metrics = core.train_step(ts, rs, key, b)
        return (ts, rs), metrics

    (train_state, replay_state), metrics = jax.lax.scan(
        body, (train_state, replay_state),
        (ingest_batches, ingest_prios, keys, betas))
    return train_state, replay_state, metrics


def make_multi_ingest(core):
    """K ingest-only steps in ONE dispatch: ``lax.scan`` over chunk/prio
    stacks with a leading axis of K — the ingest half of
    :func:`scan_fused_steps`, for chunks the replay-ratio cap (or warmup
    gate) says to absorb WITHOUT training.  Each scan iteration is the
    same ``core.ingest`` program a per-chunk dispatch runs, so the final
    replay state is bit-identical to K sequential ``jit_ingest`` calls;
    only the host round-trip count drops from K to 1.  Works for any core
    exposing ``ingest`` with the shared signature (DQN
    :class:`LearnerCore`, :class:`apex_tpu.training.aql.AQLCore`)."""

    def ingest_multi(replay_state, ingest_batches, ingest_prios):
        def body(rs, xs):
            chunk, prios = xs
            return core.ingest(rs, chunk, prios), ()

        replay_state, _ = jax.lax.scan(
            body, replay_state, (ingest_batches, ingest_prios))
        return replay_state

    return jax.jit(ingest_multi, donate_argnums=(0,))


def build_learner(model, replay_capacity: int, example_obs, key: jax.Array,
                  *, alpha: float = 0.6, batch_size: int = 512,
                  lr: float = 6.25e-5, max_grad_norm: float = 40.0,
                  rmsprop_decay: float = 0.95, rmsprop_eps: float = 1.5e-7,
                  rmsprop_centered: bool = True, replay_eps: float = 1e-6,
                  target_update_interval: int = 2500,
                  lr_decay_steps: int | None = 1000,
                  lr_decay_rate: float = 0.99,
                  obs_dtype=None, hbm_budget_gb: float | None = None
                  ) -> tuple[LearnerCore, TrainState, ReplayState]:
    """Convenience constructor used by drivers and benches."""
    optimizer = make_optimizer(lr=lr, decay=rmsprop_decay, eps=rmsprop_eps,
                               centered=rmsprop_centered,
                               max_grad_norm=max_grad_norm,
                               lr_decay_steps=lr_decay_steps,
                               lr_decay_rate=lr_decay_rate)
    train_state = create_train_state(model, optimizer, key, example_obs)
    replay = DeviceReplay(capacity=replay_capacity, alpha=alpha,
                          eps=replay_eps)
    example_item = dict(
        obs=jnp.zeros(example_obs.shape[1:],
                      obs_dtype or example_obs.dtype),
        action=jnp.int32(0),
        reward=jnp.float32(0),
        next_obs=jnp.zeros(example_obs.shape[1:],
                           obs_dtype or example_obs.dtype),
        discount=jnp.float32(0),
    )
    if hbm_budget_gb is not None:
        from apex_tpu.replay.base import check_hbm_budget
        check_hbm_budget(replay.hbm_bytes(example_item), hbm_budget_gb,
                         "replay (stacked obs storage)", replay_capacity)
    replay_state = replay.init(example_item)
    core = LearnerCore(apply_fn=model.apply, replay=replay,
                       optimizer=optimizer, batch_size=batch_size,
                       target_update_interval=target_update_interval)
    return core, train_state, replay_state
