"""On-device Anakin rollouts: env + policy + chunk assembly in one scan.

The host actor plane (:mod:`apex_tpu.actors.vector`) pays one policy
dispatch, B python ``env.step`` calls, and B ``FrameChunkBuilder.add_step``
calls per vector step — ~50 env-frames/s end to end on the 1-core CI box.
For the jittable envs (:func:`apex_tpu.envs.registry.make_jax_env`) the
whole loop moves inside the accelerator: ONE ``lax.scan`` of ``T`` steps
over ``B`` vectorized envs runs

    acting-stack gather -> epsilon-greedy policy -> env step (auto-reset)
    -> n-step window -> chunk assembly

per step, emitting sealed chunks that are schema- and bit-compatible with
:class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder` output — the SAME
message dicts ``drain_builder_chunks`` ships, so they flow into the
existing replay path (in-learner fused ingest, the ingest pipeline's
merge/stack contract, the sharded replay service) unchanged
(tests/test_anakin.py pins chunk-for-chunk equality and FramePoolReplay
ingest parity against a host builder replaying the same trajectory).

The builder port is an exact state machine twin: per-episode frame
registration with chunk-relative refs, the n-step window with full-window
``gamma**n`` emission and terminal tails, flush-on-K and flush-for-frames
with episode frame carry, pad-rows-repeat-last, and acting-time TD
priorities.  n-step returns fold host-precomputed ``float32(gamma**i)``
coefficients left-to-right, which is bit-identical to the host builder's
float64 fold whenever a window holds at most one nonzero reward — always
true for Catch/Rally, whose scores are >= n steps apart.

Two consumers:

* :class:`AnakinPool` — an ActorPool-shaped adapter co-locating rollouts
  with the learner (``--rollout ondevice``): params hand over as on-device
  arrays (never leaving the device), chunks surface through the standard
  ``poll_chunks`` interface, heartbeats/episode stats through
  ``poll_stats``.  Optionally wraps an inner pool (socket RemotePool) so a
  fleet can mix on-device rollouts with host actors/evaluators.
* ``--role loadgen`` (:func:`apex_tpu.runtime.roles.run_loadgen`) — the
  standalone synthetic-traffic generator driving the replay shards and the
  learner ingest at device rate.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, NamedTuple

import numpy as np

from apex_tpu.config import ApexConfig
from apex_tpu.replay.frame_chunks import FRAME_MARGIN

# per-step key derivation tags (the parity tests replay these)
T_POLICY = 0      # policy_fn key for the step
T_ENV = 1         # env key root; per-slot keys fold the slot index on top


class RolloutCarry(NamedTuple):
    """Vectorized builder + env state between scan steps (leading axis B).

    Deliberately SMALL: the scan carry holds only bookkeeping (int32 row
    maps, the n-step window, the S-frame acting stack) — frame BYTES leave
    the scan as per-step outputs, land in an append-only per-dispatch ring,
    and materialize into chunk layout once per dispatch (``fmap`` maps each
    chunk row to its ring row).  A first cut kept ``[B, M, Kf, D]`` frame
    buffers in the carry and lost 30x to XLA:CPU copying them per scan
    step; with index bookkeeping the hot loop moves 4 bytes where it used
    to move a frame.

    The outbox holds ``M`` chunk slots per env slot; slot ``sealed[b]`` is
    the in-progress chunk (at most one seal per step — ``_flush``),
    earlier slots are sealed this dispatch."""

    env: Any                # env-state pytree
    stack: Any              # u8[B, S, D] acting stack, oldest frame first
    fmap: Any               # i32[B, M, Kf] chunk row -> dispatch ring row
    action: Any             # i32[B, M, K]
    rd: Any                 # f32[B, M, K, 2] (reward, discount) pairs
    refs: Any               # i32[B, M, K, 2, S] (obs_ref, next_ref) pairs
    q: Any                  # f32[B, M, K, 2, A] (q0, qn) pairs
    counts: Any             # i32[B, M, 2] (n_frames, n_trans) at seal
    sealed: Any             # i32[B] chunks sealed this dispatch (= cur slot)
    cur_nf: Any             # i32[B] in-progress frame count
    cur_nt: Any             # i32[B] in-progress transition count
    ep_step: Any            # i32[B] episode frame index of newest frame
    rows: Any               # i32[B, W] chunk rows of the last W ep frames
    w_obs: Any              # i32[B, n+1]
    w_act: Any              # i32[B, n+1]
    w_rew: Any              # f32[B, n+1]
    w_q: Any                # f32[B, n+1, A]
    w_len: Any              # i32[B]
    ep_ret: Any             # f32[B]
    ep_len: Any             # i32[B]


class AnakinRollout:
    """The fused rollout engine for one jittable env.

    ``rollout(params)`` runs one jitted dispatch of ``rollout_len`` scanned
    steps over ``n_envs`` slots and returns ``(messages, stats)`` — chunk
    messages in the ``drain_builder_chunks`` schema plus
    :class:`~apex_tpu.actors.pool.EpisodeStat` records for episodes that
    ended inside the dispatch.  Between dispatches the in-progress chunk's
    frames persist in ``carry_frames`` (ring rows ``[0, Kf)`` of the next
    dispatch); everything else carries as index bookkeeping.
    """

    def __init__(self, env, policy_fn, *, n_envs: int, epsilons,
                 slot_ids=None, n_steps: int = 3, gamma: float = 0.99,
                 frame_stack: int = 4, chunk_transitions: int = 64,
                 rollout_len: int | None = None,
                 frame_margin: int = FRAME_MARGIN, seed: int = 0):
        import jax

        self.env = env
        self.policy_fn = policy_fn
        self.B = int(n_envs)
        self.n = int(n_steps)
        self.S = int(frame_stack)
        self.K = int(chunk_transitions)
        self.Kf = self.K + int(frame_margin)
        self.W = self.S + self.n + 1
        self.T = int(rollout_len or chunk_transitions)
        # transitions emitted per dispatch <= leftover window + T + n, and
        # every seal consumes at least one; +1 in-progress slot, +1 slack
        # for frame-overflow partial seals (overflow past M is detected
        # loudly in rollout(), never silent corruption)
        self.M = (self.T + self.n + self.K - 1) // self.K + 3
        self.A = int(env.num_actions)
        self.D = int(np.prod(env.frame_shape))
        self.frame_shape = tuple(env.frame_shape)
        self.slot_ids = list(slot_ids if slot_ids is not None
                             else range(self.B))
        self.epsilons = np.asarray(epsilons, np.float32)
        if len(self.epsilons) != self.B:
            raise ValueError(
                f"epsilons arity {len(self.epsilons)} != n_envs {self.B}")
        # host-f64 gamma powers as f32 constants: the device return fold
        # uses the exact coefficients the host builder's f64 math rounds to
        self.gpow = np.asarray([np.float64(gamma) ** i
                                for i in range(self.n + 1)], np.float32)
        self.key = jax.random.key(seed)
        self.key, init_key = jax.random.split(self.key)
        self.carry, self.carry_frames = self._init_carry(init_key)
        self._jit = jax.jit(self._dispatch)
        # counters (host-side observability)
        self.dispatches = 0
        self.chunks = 0
        self.frames = 0
        self.transitions = 0

    # -- construction ------------------------------------------------------

    def reset_keys(self, key):
        """Per-slot env reset keys — ``fold_in(key, slot)`` (the parity
        replay reproduces this chain)."""
        import jax
        return jax.vmap(jax.random.fold_in, (None, 0))(
            key, np.arange(self.B, dtype=np.uint32))

    def _init_carry(self, key):
        import jax
        import jax.numpy as jnp

        states, obs = jax.vmap(self.env.reset)(self.reset_keys(key))
        B, M, K, Kf, S, A = (self.B, self.M, self.K, self.Kf, self.S,
                             self.A)
        flat = obs.reshape(B, self.D)
        # begin_episode: reset frame is episode frame 0 = chunk row 0;
        # the acting stack starts as S copies of it (host FrameStack.reset)
        carry_frames = jnp.zeros((B, Kf, self.D), jnp.uint8).at[:, 0].set(
            flat)
        carry = RolloutCarry(
            env=states,
            stack=jnp.broadcast_to(flat[:, None], (B, S, self.D)),
            fmap=jnp.zeros((B, M, Kf), jnp.int32),
            action=jnp.zeros((B, M, K), jnp.int32),
            rd=jnp.zeros((B, M, K, 2), jnp.float32),
            refs=jnp.zeros((B, M, K, 2, S), jnp.int32),
            q=jnp.zeros((B, M, K, 2, A), jnp.float32),
            counts=jnp.zeros((B, M, 2), jnp.int32),
            sealed=jnp.zeros(B, jnp.int32),
            cur_nf=jnp.ones(B, jnp.int32),
            cur_nt=jnp.zeros(B, jnp.int32),
            ep_step=jnp.zeros(B, jnp.int32),
            rows=jnp.zeros((B, self.W), jnp.int32),
            w_obs=jnp.zeros((B, self.n + 1), jnp.int32),
            w_act=jnp.zeros((B, self.n + 1), jnp.int32),
            w_rew=jnp.zeros((B, self.n + 1), jnp.float32),
            w_q=jnp.zeros((B, self.n + 1, A), jnp.float32),
            w_len=jnp.zeros(B, jnp.int32),
            ep_ret=jnp.zeros(B, jnp.float32),
            ep_len=jnp.zeros(B, jnp.int32))
        return carry, carry_frames

    # -- builder-port primitives (all batched over B, masked) --------------

    def _row_of(self, c: RolloutCarry, ep_idx):
        """Chunk row of episode frame ``ep_idx`` (clamped to frame 0, the
        host builder's episode-start repeat) via the recent-rows ring."""
        import jax.numpy as jnp
        idx = (self.W - 1) - (c.ep_step - jnp.maximum(ep_idx, 0))
        idx = jnp.clip(idx, 0, self.W - 1)
        return c.rows[jnp.arange(self.B), idx]

    def _rows_of(self, c: RolloutCarry, ep_idx):
        """Batched :meth:`_row_of` over a ``[B, J]`` episode-index matrix
        — ONE gather where a per-column loop would issue J."""
        import jax.numpy as jnp
        idx = (self.W - 1) - (c.ep_step[:, None]
                              - jnp.maximum(ep_idx, 0))
        idx = jnp.clip(idx, 0, self.W - 1)
        return jnp.take_along_axis(c.rows, idx, axis=1)

    def _flush(self, c: RolloutCarry, do) -> RolloutCarry:
        """``FrameChunkBuilder._flush``: seal when transitions exist (else
        drop the frame-only chunk), then carry the episode frames the live
        window and acting stack still need into the fresh chunk — an int32
        remap of ``fmap`` rows, no frame bytes move."""
        import jax.numpy as jnp
        ar = jnp.arange(self.B)
        seal = do & (c.cur_nt >= 1)
        active = do & ((c.cur_nt >= 1) | (c.cur_nf >= 1))
        # sealed-slot counts (write-through; masked writes drop)
        sl = jnp.where(seal, c.sealed, self.M)
        counts = c.counts.at[ar, sl].set(
            jnp.stack([c.cur_nf, c.cur_nt], 1), mode="drop")
        new_cur = c.sealed + seal.astype(jnp.int32)
        # frame carry: episode frames oldest..ep_step -> rows 0..count-1
        has_ep = c.ep_step >= 0
        head = jnp.where(c.w_len > 0, c.w_obs[:, 0], c.ep_step)
        oldest = jnp.maximum(head - (self.S - 1), 0)
        count = jnp.where(active & has_ep, c.ep_step - oldest + 1, 0)
        # gather the carried ring rows first, then ONE batched scatter
        # (functional, so a same-slot carry — dropped frame-only chunk —
        # cannot self-clobber); per-row validity folds into the slot index
        src_rows = self._rows_of(c, oldest[:, None]
                                 + jnp.arange(self.W)[None, :])
        carried = c.fmap[ar[:, None], c.sealed[:, None], src_rows]
        j_idx = jnp.arange(self.W)[None, :]
        dst_slot = jnp.where(active[:, None] & (j_idx < count[:, None]),
                             new_cur[:, None], self.M)
        fmap = c.fmap.at[ar[:, None], dst_slot, j_idx].set(
            carried, mode="drop")
        # recent-rows remap: ep frame f's new chunk row is f - oldest
        ring_ep = (jnp.arange(self.W)[None, :]
                   + (c.ep_step - (self.W - 1))[:, None])
        rows = jnp.where(active[:, None] & has_ep[:, None],
                         ring_ep - oldest[:, None], c.rows)
        return c._replace(
            fmap=fmap, counts=counts, rows=rows,
            sealed=jnp.where(seal, new_cur, c.sealed),
            cur_nf=jnp.where(active, count, c.cur_nf),
            cur_nt=jnp.where(seal, 0, c.cur_nt))

    def _register(self, c: RolloutCarry, ring_row, do) -> RolloutCarry:
        """Append one frame (already written at ``ring_row`` of the
        dispatch ring) to the in-progress chunk + shift the recent ring."""
        import jax.numpy as jnp
        ar = jnp.arange(self.B)
        row = c.cur_nf
        fmap = c.fmap.at[
            ar, jnp.where(do, c.sealed, self.M), row].set(
            jnp.full(self.B, ring_row, jnp.int32), mode="drop")
        rows = jnp.where(do[:, None],
                         jnp.concatenate([c.rows[:, 1:], row[:, None]], 1),
                         c.rows)
        return c._replace(fmap=fmap, rows=rows,
                          cur_nf=c.cur_nf + do.astype(jnp.int32))

    def _stack_refs(self, c: RolloutCarry, end):
        """Rows of the S-stack ending at episode frame ``end`` (oldest
        first) — ``FrameChunkBuilder._stack_refs``."""
        import jax.numpy as jnp
        offs = jnp.arange(self.S - 1, -1, -1)[None, :]
        return self._rows_of(c, end[:, None] - offs)

    def _push(self, c: RolloutCarry, ret, next_end, disc, qn_row, do):
        """Emit one transition from the window head, then flush at K."""
        import jax.numpy as jnp
        ar = jnp.arange(self.B)
        head = c.w_obs[:, 0]
        obs_ref = self._stack_refs(c, head)
        next_ref = self._stack_refs(c, next_end)
        sl = jnp.where(do, c.sealed, self.M)
        pos = c.cur_nt
        c = c._replace(
            action=c.action.at[ar, sl, pos].set(c.w_act[:, 0],
                                                mode="drop"),
            rd=c.rd.at[ar, sl, pos].set(jnp.stack([ret, disc], 1),
                                        mode="drop"),
            refs=c.refs.at[ar, sl, pos].set(
                jnp.stack([obs_ref, next_ref], 1), mode="drop"),
            q=c.q.at[ar, sl, pos].set(
                jnp.stack([c.w_q[:, 0], qn_row], 1), mode="drop"),
            cur_nt=c.cur_nt + do.astype(jnp.int32))
        return self._flush(c, do & (c.cur_nt == self.K))

    def _popleft(self, c: RolloutCarry, do) -> RolloutCarry:
        import jax.numpy as jnp
        m = do[:, None]

        def roll(a):
            r = jnp.concatenate([a[:, 1:], a[:, :1]], 1)
            mm = m[..., None] if a.ndim == 3 else m
            return jnp.where(mm, r, a)

        return c._replace(w_obs=roll(c.w_obs), w_act=roll(c.w_act),
                          w_rew=roll(c.w_rew), w_q=roll(c.w_q),
                          w_len=c.w_len - do.astype(jnp.int32))

    def _nstep_return(self, c: RolloutCarry, k):
        """Left-fold of ``gpow[i] * w_rew[i]`` over ``i < k`` — the host
        builder's ``sum(gamma**i * r_i)`` with host-rounded coefficients
        (bit-identical whenever a window holds at most one nonzero reward,
        which Catch/Rally score spacing guarantees)."""
        import jax.numpy as jnp
        acc = jnp.zeros(self.B, jnp.float32)
        for i in range(self.n + 1):
            acc = acc + jnp.where(i < k, self.gpow[i] * c.w_rew[:, i],
                                  jnp.float32(0.0))
        return acc

    # -- the scanned step --------------------------------------------------

    def _policy_obs(self, c: RolloutCarry):
        import jax.numpy as jnp
        shp = self.frame_shape
        stk = c.stack.reshape(self.B, self.S, *shp)
        stk = jnp.moveaxis(stk, 1, -2)
        return stk.reshape(self.B, *shp[:-1], self.S * shp[-1])

    def _step(self, params, eps, c: RolloutCarry, xs):
        import jax
        import jax.numpy as jnp

        step_key, t = xs
        actions, q = self.policy_fn(params, self._policy_obs(c), eps,
                                    jax.random.fold_in(step_key, T_POLICY))
        env_key = jax.random.fold_in(step_key, T_ENV)
        env_state, obs, reward, done, final_frame = jax.vmap(
            lambda s, a, i: self.env.step(s, a,
                                          jax.random.fold_in(env_key, i)))(
            c.env, actions, jnp.arange(self.B, dtype=jnp.uint32))
        c = c._replace(env=env_state)
        always = jnp.ones(self.B, bool)
        final_flat = final_frame.reshape(self.B, self.D)
        obs_flat = obs.reshape(self.B, self.D)
        # dispatch-ring rows of this step's two frames (epilogue layout:
        # carry region [0, Kf) then the interleaved per-step pairs)
        final_row = self.Kf + 2 * t
        obs_row = final_row + 1

        # add_step: flush-for-frames, register, window append
        c = self._flush(c, c.cur_nf + 1 > self.Kf)
        obs_idx = c.ep_step
        c = c._replace(ep_step=c.ep_step + 1)
        c = self._register(c, final_row, always)
        ar = jnp.arange(self.B)
        pos = c.w_len
        c = c._replace(
            w_obs=c.w_obs.at[ar, pos].set(obs_idx),
            w_act=c.w_act.at[ar, pos].set(actions.astype(jnp.int32)),
            w_rew=c.w_rew.at[ar, pos].set(reward),
            w_q=c.w_q.at[ar, pos].set(q.astype(jnp.float32)),
            w_len=c.w_len + 1)
        # full-window emission (gamma**n bootstrap)
        full = c.w_len == self.n + 1
        c = self._push(c, self._nstep_return(c, jnp.int32(self.n)),
                       c.w_obs[:, 0] + self.n,
                       jnp.full(self.B, self.gpow[self.n]),
                       c.w_q[:, self.n], full)
        c = self._popleft(c, full)
        # terminal tails (discount 0, next stack = masked obs stack)
        for _ in range(self.n):
            m = done & (c.w_len > 0)
            k = c.w_len
            qn_row = c.w_q[ar, jnp.clip(k - 1, 0, self.n)]
            c = self._push(c, self._nstep_return(c, k), c.w_obs[:, 0],
                           jnp.zeros(self.B, jnp.float32), qn_row, m)
            c = self._popleft(c, m)
        c = c._replace(ep_step=jnp.where(done, -1, c.ep_step))
        # auto-reset: begin_episode(obs) for done slots
        c = self._flush(c, done & (c.cur_nf + 1 > self.Kf))
        c = c._replace(ep_step=jnp.where(done, 0, c.ep_step),
                       w_len=jnp.where(done, 0, c.w_len))
        c = self._register(c, obs_row, done)
        # acting stack: roll the new frame in; a reset rebuilds all S
        # positions from the reset frame (host bind_acting_view semantics)
        stack = jnp.concatenate([c.stack[:, 1:], final_flat[:, None]], 1)
        stack = jnp.where(done[:, None, None],
                          jnp.broadcast_to(obs_flat[:, None],
                                           stack.shape), stack)
        # episode accounting
        ep_ret = c.ep_ret + reward
        ep_len = c.ep_len + 1
        c = c._replace(stack=stack,
                       ep_ret=jnp.where(done, 0.0, ep_ret),
                       ep_len=jnp.where(done, 0, ep_len))
        return c, (final_flat, obs_flat, done, ep_ret, ep_len)

    # -- the jitted dispatch ----------------------------------------------

    def _rebase(self, c: RolloutCarry) -> RolloutCarry:
        """Dispatch prologue: the in-progress chunk moves to slot 0, its
        frames now live at identity rows of the ring's carry region."""
        import jax.numpy as jnp
        ar = jnp.arange(self.B)
        src = jnp.minimum(c.sealed, self.M - 1)

        def move(a):
            return a.at[:, 0].set(a[ar, src])

        fmap = move(c.fmap).at[:, 0].set(
            jnp.arange(self.Kf, dtype=jnp.int32)[None, :])
        return c._replace(
            fmap=fmap, action=move(c.action), rd=move(c.rd),
            refs=move(c.refs), q=move(c.q),
            rows=jnp.clip(c.rows, 0, self.Kf - 1),
            sealed=jnp.zeros(self.B, jnp.int32))

    def _dispatch(self, params, eps, c: RolloutCarry, carry_frames, key):
        import jax
        import jax.numpy as jnp

        c = self._rebase(c)
        keys = jax.random.split(key, self.T)
        c, ys = jax.lax.scan(
            lambda cc, xs: self._step(params, eps, cc, xs), c,
            (keys, jnp.arange(self.T)))
        final_flat, obs_flat, done, ep_ret, ep_len = ys
        # the dispatch ring: carry region + this dispatch's frame pairs
        pairs = jnp.stack([jnp.moveaxis(final_flat, 0, 1),
                           jnp.moveaxis(obs_flat, 0, 1)], 2)
        ring = jnp.concatenate(
            [carry_frames, pairs.reshape(self.B, 2 * self.T, self.D)], 1)
        # write-through the in-progress counts, then pad + materialize
        ar = jnp.arange(self.B)
        sl = jnp.minimum(c.sealed, self.M - 1)
        counts = c.counts.at[ar, sl].set(
            jnp.stack([c.cur_nf, c.cur_nt], 1))
        nf, nt = counts[..., 0], counts[..., 1]

        def pad(a, counts, length):
            idx = jnp.minimum(jnp.arange(length)[None, None, :],
                              jnp.maximum(counts - 1, 0)[:, :, None])
            idx = idx.reshape(idx.shape + (1,) * (a.ndim - 3))
            return jnp.take_along_axis(a, idx, axis=2)

        fmap = pad(c.fmap, nf, self.Kf)
        frames = jnp.take_along_axis(
            ring, fmap.reshape(self.B, self.M * self.Kf, 1), axis=1
        ).reshape(self.B, self.M, self.Kf, self.D)
        carry_next = frames[ar, sl]
        rd = pad(c.rd, nt, self.K)
        refs = pad(c.refs, nt, self.K)
        q = pad(c.q, nt, self.K)
        out = dict(frames=frames, action=pad(c.action, nt, self.K),
                   reward=rd[..., 0], discount=rd[..., 1],
                   obs_ref=refs[..., 0, :], next_ref=refs[..., 1, :],
                   q0=q[..., 0, :], qn=q[..., 1, :],
                   nf=nf, nt=nt, sealed=c.sealed,
                   stepped=(done, ep_ret, ep_len))
        return c, carry_next, out

    # -- host surface ------------------------------------------------------

    def rollout(self, params):
        """One dispatch; returns ``(messages, stats)``."""
        import jax

        from apex_tpu.actors.pool import EpisodeStat
        from apex_tpu.obs import spans as obs_spans

        self.key, k = jax.random.split(self.key)
        self.carry, self.carry_frames, out = self._jit(
            params, self.epsilons, self.carry, self.carry_frames, k)
        got = jax.device_get(out)
        sealed = got["sealed"]
        if int(sealed.max(initial=0)) > self.M - 1:
            raise RuntimeError(
                f"anakin outbox overflow: {int(sealed.max())} seals > "
                f"{self.M - 1} sealed slots — raise rollout_len headroom")
        # acting-time TD priorities in the exact numpy ops the host
        # builder runs (FrameChunkBuilder._materialize): on device XLA
        # fuses reward + discount*max into an FMA, which rounds once
        # where numpy rounds twice — a 1-ulp drift the bit-compat
        # contract forbids.  Vectorized host epilogue, not per-step work.
        q_taken = np.take_along_axis(
            got["q0"], got["action"][..., None], -1)[..., 0]
        target = got["reward"] + got["discount"] * got["qn"].max(-1)
        priorities = (np.abs(target - q_taken).astype(np.float32)
                      + np.float32(1e-6))
        stamped = obs_spans.enabled()
        msgs = []
        for b in range(self.B):
            for j in range(int(sealed[b])):
                chunk = dict(
                    frames=got["frames"][b, j],
                    n_frames=np.int32(got["nf"][b, j]),
                    n_trans=np.int32(got["nt"][b, j]),
                    action=got["action"][b, j],
                    reward=got["reward"][b, j],
                    discount=got["discount"][b, j],
                    obs_ref=got["obs_ref"][b, j],
                    next_ref=got["next_ref"][b, j])
                msg = {"payload": chunk,
                       "priorities": priorities[b, j],
                       "n_trans": int(got["nt"][b, j])}
                if stamped:
                    msg[obs_spans.SPAN_KEY] = [
                        obs_spans.new_span(hop="sealed")]
                msgs.append(msg)
        done, ep_ret, ep_len = got["stepped"]
        stats = [EpisodeStat(self.slot_ids[b], float(ep_ret[t, b]),
                             int(ep_len[t, b]))
                 for t in range(self.T) for b in range(self.B)
                 if done[t, b]]
        self.dispatches += 1
        self.chunks += len(msgs)
        self.frames += self.T * self.B
        self.transitions += sum(m["n_trans"] for m in msgs)
        return msgs, stats


def make_anakin_engine(cfg: ApexConfig, rollout_len: int | None = None,
                       n_envs: int | None = None, slot_band: int = 0,
                       total_slots: int | None = None) -> AnakinRollout:
    """Engine wired from the shared config: jittable env port (guarded by
    :func:`~apex_tpu.envs.registry.make_jax_env`'s ValueError for
    non-jittable ids), the DQN policy, and the epsilon ladder.

    Defaults build the co-located engine owning the WHOLE fleet's slots
    (``n_actors * n_envs_per_actor`` env lanes, ladder spanning them all).
    A loadgen process ``i`` of ``N`` passes ``n_envs=n_envs_per_actor,
    slot_band=i, total_slots=N * n_envs_per_actor`` — the same contiguous
    ladder band a host vector worker with that actor id would own
    (:func:`apex_tpu.actors.vector.worker_slots`)."""
    from apex_tpu.actors.pool import actor_epsilons
    from apex_tpu.envs.registry import make_jax_env
    from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
    from apex_tpu.training.apex import dqn_env_specs

    env = make_jax_env(cfg.env.env_id, cfg.env)
    model_spec, _shape, _dtype, frame_stack = dqn_env_specs(cfg)
    b = n_envs or max(cfg.actor.n_actors, 1) * max(
        1, cfg.actor.n_envs_per_actor)
    total = max(total_slots or 0, (slot_band + 1) * b)
    ladder = actor_epsilons(total, cfg.actor.eps_base, cfg.actor.eps_alpha)
    slot_ids = list(range(slot_band * b, (slot_band + 1) * b))
    return AnakinRollout(
        env, make_policy_fn(DuelingDQN(**model_spec)),
        n_envs=b, epsilons=ladder[slot_ids], slot_ids=slot_ids,
        n_steps=cfg.learner.n_steps, gamma=cfg.learner.gamma,
        frame_stack=frame_stack,
        chunk_transitions=cfg.actor.send_interval,
        rollout_len=rollout_len,
        # distinct key chains per ladder band so N loadgen processes
        # explore different trajectories (the host fleet's per-slot seed
        # discipline, lifted to the band level)
        seed=cfg.env.seed + 1000 * (slot_band + 1))


class AnakinPool:
    """ActorPool-shaped adapter over :class:`AnakinRollout` for the
    co-located training mode (``--rollout ondevice``).

    Params hand over as ON-DEVICE arrays (``accepts_device_params`` — the
    trainer and ingest pipeline skip their ``device_get``), rollout
    dispatches run lazily inside ``poll_chunks`` (so the trainer's
    replay-ratio backpressure gates collection for free), and heartbeats +
    episode stats surface through ``poll_stats`` like any worker fleet.
    ``inner`` (a socket RemotePool) keeps host actors/evaluators riding
    alongside: their chunks/stats merge in, and publishes fan out to them
    as host params."""

    accepts_device_params = True

    def __init__(self, cfg: ApexConfig, engine: AnakinRollout | None = None,
                 inner=None, identity: str = "ondevice-0"):
        from apex_tpu.fleet.heartbeat import HeartbeatEmitter

        self.cfg = cfg
        self.engine = engine or make_anakin_engine(cfg)
        self.inner = inner
        self._params = None
        self._version = 0
        self._pending: deque = deque()
        self._stats: deque = deque()
        self._beat = HeartbeatEmitter(
            identity, role="rollout",
            interval_s=cfg.comms.heartbeat_interval_s,
            gauges_fn=self.ondevice_counters)
        self._t0 = time.monotonic()

    def __getattr__(self, name):
        # unknown surface (wire_rejected, rejoin_admitted, acks_withheld,
        # ...) delegates to the inner pool so the trainer's getattr-probed
        # counters stay live in hybrid mode; pure on-device pools simply
        # lack them
        inner = self.__dict__.get("inner")
        if inner is not None:
            return getattr(inner, name)
        raise AttributeError(name)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.inner is not None:
            self.inner.start()

    def cleanup(self) -> None:
        if self.inner is not None:
            self.inner.cleanup()

    # -- param plane -------------------------------------------------------

    def publish_params(self, version: int, params) -> None:
        """Keep the device reference for the engine; the host copy is made
        only when an inner fleet needs wire params."""
        self._version, self._params = version, params
        if self.inner is not None:
            import jax
            self.inner.publish_params(version, jax.device_get(params))

    @property
    def needs_warmup_republish(self) -> bool:
        return bool(getattr(self.inner, "needs_warmup_republish", False))

    def set_learner_epoch(self, epoch: int) -> None:
        setter = getattr(self.inner, "set_learner_epoch", None)
        if setter is not None:
            setter(epoch)

    def peer_seen(self):
        seen = getattr(self.inner, "peer_seen", None)
        return seen() if callable(seen) else {}

    # -- data plane --------------------------------------------------------

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        out = []
        if self.inner is not None:
            out = self.inner.poll_chunks(max_chunks, timeout=0)
        dry = 0
        while len(out) < max_chunks:
            if not self._pending:
                # a short-rollout dispatch can seal nothing (the n-step
                # window lags the first emissions); each dispatch strictly
                # advances the stream, so a couple of retries always
                # produce — the cap only guards a pathological config
                if self._params is None or dry >= 4:
                    break
                msgs, stats = self.engine.rollout(self._params)
                self._pending.extend(msgs)
                self._stats.extend(stats)
                dry = 0 if msgs else dry + 1
                continue
            out.append(self._pending.popleft())
        return out

    def poll_stats(self) -> list:
        out = list(self._stats)
        self._stats.clear()
        self._beat.tick(0)
        hb = self._beat.maybe_beat(self._version)
        if hb is not None:
            e = self.engine
            hb.fps = round(e.frames / max(time.monotonic() - self._t0,
                                          1e-9), 1)
            hb.chunks_sent = e.chunks
            out.append(hb)
        if self.inner is not None:
            out.extend(self.inner.poll_stats())
        return out

    def ondevice_counters(self) -> dict:
        """``fleet_summary.json``'s ``ondevice`` section (the anakin-smoke
        CI job asserts these are nonzero)."""
        e = self.engine
        return {"dispatches": e.dispatches, "chunks": e.chunks,
                "frames": e.frames, "transitions": e.transitions,
                "rollout_len": e.T, "n_envs": e.B}
