"""Async ingest pipeline: overlap host decode, H2D staging, and compute.

The serial concurrent loop (:meth:`ConcurrentTrainer.train`) does all of
this on ONE thread, in sequence, per step: poll the chunk queue (pickle /
shm decode), stack arrays with host numpy, hand host buffers to the jitted
step (whose H2D copy runs synchronously inside the dispatch), then poll
again.  Host decode, H2D transfer, and device compute therefore never
overlap — the exact decoupling failure Ape-X exists to avoid (Horgan et
al. 2018), and the standard fix is double-buffered staging (Stooke &
Abbeel 2018, PAPERS.md "Accelerated Methods for Deep RL").

This module runs a single background STAGING thread that:

* drains ``pool.poll_chunks`` (the decode cost — mp.Queue pickle or shm
  copy — moves off the hot loop with it);
* groups chunks by what the trainer will do with them, predicted from the
  live counters (``state_fn``):

  - train-eligible chunks -> a ``lax.scan`` stack of j chunks (one
    dispatch, j bit-identical fused steps) — this also fixes the serial
    scan shortfall where j < scan_steps chunks degraded to j separate
    dispatches;
  - ingest-only chunks (warmup fill, replay-ratio cap) -> ONE merged
    payload via :func:`merge_chunk_messages` — m dispatches and m H2D
    copies become one, bit-identically (see below);

* ``jax.device_put``\\ s each staged slot so the next dispatch's data is
  already in HBM while the current fused step runs, into a bounded
  depth-``depth`` ring (default 2: classic double buffering).

Ordering / backpressure / numerics contract:

* Chunks enter slots strictly in poll order and the ring is FIFO — the
  replay sees the same transition stream as the serial loop.
* The ring is BOUNDED and the staging thread polls nothing while it is
  full (or while the replay-ratio floor says the learner is behind), so
  the bounded worker chunk queue backpressures the actor fleet exactly as
  before; the pipeline can hold at most ``depth`` slots plus one group in
  flight.
* Merging is numerics-free: :func:`merge_chunk_messages` rebases the
  chunk-relative ``obs_ref``/``next_ref`` tables with cumulative frame
  offsets and carries per-transition ``epoch_off`` so one merged
  :meth:`FramePoolReplay.add` writes the SAME cells, priorities, and
  epochs as ingesting the chunks one by one — exploiting the
  duplicate-pad-write invariant (pads repeat the last real row, so they
  remain deterministic no-ops after merging).  Bit-parity is pinned in
  ``tests/test_ingest_pipeline.py``.

Param publishes also ride the staging thread: the trainer hands over an
on-device param copy and the thread performs the blocking
``jax.device_get`` + serialization that used to drain the whole device
pipeline from inside the hot loop (apexlint J006 now guards against that
pattern coming back).

Sharded (dp>1) plan: the same staging stage drives the multi-chip
learner.  ``ChunkAggregator`` already assembles whole ROUND-ROBIN groups
(``n_dp`` worker chunks stacked on a leading dp axis — chunk i of a group
lands on chip i), so each polled message is one group and the pipeline
stages group-granular slots:

* train-eligible groups stage as ``"single"`` slots whose payload is
  ``device_put`` with a ``NamedSharding`` over the dp axis (H2D lands
  each shard's slice on its chip ahead of the dispatch);
* ingest-only groups merge PER SHARD via :func:`merge_group_messages`:
  shard s's m chunks compact exactly as the single-shard merge does
  (frame refs rebased by cumulative real-frame offsets, ``epoch_off``
  carried), then the n_dp merged payloads restack on the dp axis —
  shards are independent replays, so bit-parity reduces to the
  single-shard merge contract per shard;
* per-chip PRNG keys are PRE-SPLIT and PRE-PLACED by a
  :class:`KeyPrefetcher` that owns the trainer's dispatch key chain: the
  serial loop pays a host ``jax.random.split`` + sharded ``device_put``
  inside every dispatch (``ShardedLearner.device_keys``); the prefetcher
  generates the exact same chain ahead of time on the staging side.
"""

from __future__ import annotations

import queue as queue_lib
import threading
import time
from collections import deque
from dataclasses import dataclass

import jax
import numpy as np

from apex_tpu.obs import spans as obs_spans
from apex_tpu.obs.trace import get_ring

#: payload keys that identify a self-contained frame chunk
#: (replay/frame_chunks.py contract) — the only payload schema
#: merge_chunk_messages understands.  Everything else (stacked AQL
#: batches, R2D2 sequence messages) stages as single slots.
FRAME_CHUNK_KEYS = frozenset((
    "frames", "n_frames", "n_trans", "action", "reward", "discount",
    "obs_ref", "next_ref"))


def is_frame_chunk(payload) -> bool:
    return isinstance(payload, dict) and FRAME_CHUNK_KEYS <= payload.keys()


def merge_chunk_messages(msgs: list[dict]) -> dict:
    """Merge m frame-chunk messages into ONE ingest message.

    Real rows from every chunk are compacted front-to-back (frames and
    transitions separately), ``obs_ref``/``next_ref`` are rebased by each
    chunk's cumulative REAL frame offset, and ``epoch_off`` records that
    same offset per transition so the pool stamps sequential-identical
    frame epochs.  The tail pads by repeating the last real row —
    priorities included — preserving the duplicate-pad-write invariant.
    Output shapes are fixed per m (``[m*K]`` / ``[m*Kf, D]``), so each
    distinct merge width compiles exactly one ingest program.

    Bit-parity contract: ``add(merge(c1..cm))`` == ``add(c1); ...;
    add(cm)`` on every :class:`FramePoolState` field (frames, id tables,
    trees, epochs, cursors) — tests/test_ingest_pipeline.py.
    """
    if len(msgs) == 1:
        return msgs[0]
    payloads = [m["payload"] for m in msgs]
    k = payloads[0]["action"].shape[0]
    kf, d = payloads[0]["frames"].shape
    stack = payloads[0]["obs_ref"].shape[1]
    for p in payloads[1:]:
        if (p["action"].shape[0] != k or p["frames"].shape != (kf, d)
                or p["obs_ref"].shape[1] != stack):
            raise ValueError("merge_chunk_messages needs uniform chunk "
                             "shapes (one builder config per pool)")
    m = len(msgs)
    n_tr = [int(p["n_trans"]) for p in payloads]
    n_fr = [int(p["n_frames"]) for p in payloads]
    tot_tr, tot_fr = sum(n_tr), sum(n_fr)
    out_k, out_kf = m * k, m * kf
    # cumulative REAL frame offset of each source chunk — the ref rebase
    # and the per-transition epoch offsets both come from this
    cum_fr = np.concatenate(([0], np.cumsum(n_fr)[:-1])).astype(np.int64)

    frames = np.empty((out_kf, d), payloads[0]["frames"].dtype)
    off = 0
    for p, nf in zip(payloads, n_fr):
        frames[off:off + nf] = p["frames"][:nf]
        off += nf
    frames[tot_fr:] = frames[tot_fr - 1]

    def cat(rows: list[np.ndarray], dtype) -> np.ndarray:
        arr = np.concatenate(rows).astype(dtype, copy=False)
        out = np.empty((out_k,) + arr.shape[1:], dtype)
        out[:tot_tr] = arr
        out[tot_tr:] = arr[tot_tr - 1]
        return out

    payload = dict(
        frames=frames,
        n_frames=np.int32(tot_fr),
        n_trans=np.int32(tot_tr),
        action=cat([p["action"][:nt] for p, nt in zip(payloads, n_tr)],
                   np.int32),
        reward=cat([p["reward"][:nt] for p, nt in zip(payloads, n_tr)],
                   np.float32),
        discount=cat([p["discount"][:nt] for p, nt in zip(payloads, n_tr)],
                     np.float32),
        obs_ref=cat([p["obs_ref"][:nt] + c
                     for p, nt, c in zip(payloads, n_tr, cum_fr)], np.int32),
        next_ref=cat([p["next_ref"][:nt] + c
                      for p, nt, c in zip(payloads, n_tr, cum_fr)], np.int32),
        epoch_off=cat([np.full(nt, c)
                       for nt, c in zip(n_tr, cum_fr)], np.int32),
    )
    if "extras" in payloads[0]:
        payload["extras"] = {
            name: cat([p["extras"][name][:nt]
                       for p, nt in zip(payloads, n_tr)], np.float32)
            for name in payloads[0]["extras"]}
    prios = cat([np.asarray(msg["priorities"])[:nt]
                 for msg, nt in zip(msgs, n_tr)], np.float32)
    out = {"payload": payload, "priorities": prios, "n_trans": tot_tr}
    # lineage spans ride MESSAGE metadata, never the payload — the
    # bit-parity contract above compares payloads field for field and
    # must keep holding with stamping on (tests re-pin it)
    spans = obs_spans.merge_spans(msgs)
    if spans:
        out[obs_spans.SPAN_KEY] = spans
    return out


def merge_group_messages(msgs: list[dict], n_dp: int) -> dict:
    """Merge m stacked round-robin GROUP messages into ONE sharded ingest
    message.

    Each input message carries ``n_dp`` chunks on a leading dp axis
    (``ChunkAggregator``'s stacking).  Shard s receives chunk s of every
    group, in group order — exactly the stream it would ingest group by
    group — so its m chunks merge with :func:`merge_chunk_messages`
    (refs rebased, ``epoch_off`` carried) and the n_dp merged payloads
    restack on the dp axis.  Shards own independent replays, so the
    sharded bit-parity contract ``add(merge(g1..gm)) == add(g1); ...;
    add(gm)`` holds per shard by the single-shard merge contract
    (tests/test_sharded_pipeline.py pins it through the real pool).
    """
    if len(msgs) == 1:
        return msgs[0]
    per_shard = []
    for s in range(n_dp):
        shard_msgs = [
            {"payload": jax.tree.map(lambda x: x[s], m["payload"]),
             "priorities": np.asarray(m["priorities"])[s],
             "n_trans": int(np.asarray(m["payload"]["n_trans"])[s])}
            for m in msgs]
        per_shard.append(merge_chunk_messages(shard_msgs))
    payload = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *[p["payload"] for p in per_shard])
    prios = np.stack([np.asarray(p["priorities"], np.float32)
                      for p in per_shard])
    out = {"payload": payload, "priorities": prios,
           "n_trans": sum(int(p["n_trans"]) for p in per_shard)}
    spans = obs_spans.merge_spans(msgs)    # metadata, not payload (above)
    if spans:
        out[obs_spans.SPAN_KEY] = spans
    return out


class KeyPrefetcher:
    """Pre-split, pre-placed per-chip PRNG keys for the sharded plan.

    Owns the trainer's dispatch key chain while the pipeline is live.
    Entry i is ``(device_keys(k_i), chain_{i+1})`` where ``chain_{i+1},
    k_i = split(chain_i)`` — the EXACT per-dispatch sequence the serial
    loop produces with ``self.key, k = split(self.key)`` followed by
    ``ShardedLearner.device_keys(k)``.  The consumer pops entries in
    dispatch order and assigns the returned chain state back to its
    ``self.key``, so pipelined runs consume bit-identical keys to serial
    runs of the same dispatch count AND leave the trainer's key where a
    serial run would (checkpoints taken mid-train stay exact).

    The staging thread refills between polls; an empty queue (startup,
    key-hungry burst) generates synchronously under the same lock, so
    the chain never forks.
    """

    def __init__(self, sharded, key, depth: int = 4):
        self._sharded = sharded
        self._chain = key
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._queue: deque = deque()

    def _gen(self) -> None:
        self._chain, k = jax.random.split(self._chain)
        self._queue.append((self._sharded.device_keys(k), self._chain))

    def refill(self) -> None:
        """Top the queue up to ``depth`` (staging-thread side)."""
        with self._lock:
            while len(self._queue) < self.depth:
                self._gen()

    def take(self):
        """``(placed_per_chip_keys, chain_state_after)`` for the next
        dispatch, generating inline if the prefetch ran dry."""
        with self._lock:
            if not self._queue:
                self._gen()
            return self._queue.popleft()


@dataclass
class PipelineState:
    """Trainer-counter snapshot the staging thread groups by.  ``behind``
    mirrors the replay-ratio floor (pause draining so the bounded queue
    backpressures the fleet); ``train_eligible`` predicts whether the
    NEXT chunk will be trained on or absorbed ingest-only — computed from
    the monotone :meth:`IngestPipeline.polled_total` (plus
    :meth:`IngestPipeline.staged_train_steps` on the budget side) so the
    prediction sees exactly what the serial loop's warm/budget gate would
    see when that chunk reaches the front of the queue."""

    behind: bool = False
    train_eligible: bool = True
    #: replay-service mode only: may the staging thread pull another
    #: pre-sampled batch?  The ratio budget alone (warmup is enforced
    #: shard-side; pulling IS training, so the floor never gates it).
    pull_eligible: bool = True


@dataclass
class StagedSlot:
    """One ready-on-device unit of ingest work, in stream order.

    kind:
      ``"single"`` — one chunk, the fused-step shape;
      ``"scan"``   — j chunks stacked on a leading axis for the
                     lax.scan dispatch (``n_per`` holds per-chunk
                     transition counts for the per-step beta stack);
      ``"merged"`` — m chunks compacted into one ingest payload.
    """

    kind: str
    payload: object
    prios: object
    n_trans: int
    n_per: tuple[int, ...] = ()
    chunks: int = 1
    #: replay-service ``"batch"`` slots (payload = staged sample batch,
    #: prios = staged IS weights): the sampled tree rows (host numpy —
    #: they round-trip to the owning shard with the new priorities), the
    #: owning shard/sequence ids, and the shard-split update key for
    #: families whose update consumes one (AQL NoisyNet)
    idx: object = None
    shard: int = -1
    seq: int = -1
    update_key: object = None
    #: train steps this slot was STAGED to take (scan j / eligible single
    #: 1 / ingest-only 0) — folded into the budget prediction so chunks
    #: behind an unconsumed trainable slot see the step count they will
    #: actually meet at the front of the queue
    planned_steps: int = 0
    #: lineage spans of the slot's source chunks (obs plane metadata —
    #: the trainer joins them into frame-age / param-lag at consume)
    spans: tuple = ()


def _pow2_floor(n: int) -> int:
    return 1 << (max(1, n).bit_length() - 1)


class IngestPipeline:
    """The background staging stage (module docstring).

    Construction does not start the thread; drive it with
    :meth:`start` / :meth:`stop`.  Single producer (the staging thread),
    single consumer (the trainer loop) — FIFO order is structural.
    """

    def __init__(self, pool, *, depth: int = 2, scan_steps: int = 1,
                 merge_max: int = 8, state_fn=None,
                 capacity: int | None = None,
                 frame_capacity: int | None = None,
                 poll_timeout: float = 0.01,
                 put_device: bool | None = None,
                 sharded=None, key=None, key_prefetch: int = 4,
                 replay_client=None):
        self.pool = pool
        # replay-service mode (apex_tpu/replay_service): the staging
        # thread ALSO pulls pre-sampled batches round-robin from the
        # shard fleet and ships priority write-backs back to the owning
        # shard — the client's sockets are driven by this thread alone
        # (RemotePool's migrate-then-use thread-affinity contract)
        self.client = replay_client
        self._wb_lock = threading.Lock()
        self._wb_q: deque = deque()
        self.depth = max(1, int(depth))
        # dp>1 (``sharded`` = the ShardedLearner): every polled message is
        # one whole round-robin group; the scan stack doesn't apply (the
        # sharded plan has no multi-step program) — group merging is the
        # ingest-only coalescing dimension instead
        self.sharded = sharded
        self.scan_steps = 1 if sharded is not None else max(1,
                                                            int(scan_steps))
        self.merge_max = max(1, int(merge_max))
        self.keys = (KeyPrefetcher(sharded, key, depth=key_prefetch)
                     if sharded is not None and key is not None else None)
        self.state_fn = state_fn or PipelineState
        self.capacity = capacity
        self.frame_capacity = frame_capacity
        self.poll_timeout = poll_timeout
        if put_device is None:
            # pre-staging into device memory only pays when there IS a
            # transfer to hide; on the CPU backend an explicit per-slot
            # device_put costs more than the jit call's own zero-distance
            # ingestion of numpy operands (measured ~150us/leaf)
            put_device = jax.default_backend() != "cpu"
        if not put_device:
            self._stage = lambda x: x
        elif sharded is not None:
            # group slots carry the dp axis in front: place each shard's
            # slice on its chip (NamedSharding over dp) so the sharded
            # dispatch finds its operands already in local HBM
            self._stage = sharded.shard_put
        else:
            self._stage = jax.device_put
        self.put_device = put_device
        self._ring: queue_lib.Queue = queue_lib.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        # set whenever the staging thread is parked with NOTHING in hand:
        # poll_slot treats "ring empty + staging idle" as dry and may
        # return None; while work is in flight it waits for the slot
        # instead of letting the trainer burn a replay-only step on data
        # that is milliseconds away (the serial loop's queue poll has the
        # same preference for fresh data)
        self._idle = threading.Event()
        self._idle.set()
        self._error: BaseException | None = None
        self._pub_lock = threading.Lock()
        self._pub: tuple | None = None
        self._ahead_lock = threading.Lock()
        self._staged_ahead = 0          # transitions polled but not consumed
        self._polled_total = 0          # transitions EVER polled (monotone)
        self._staged_steps = 0          # planned train steps not yet consumed
        self.stats = {"slots": 0, "scan_slots": 0, "merged_slots": 0,
                      "merged_chunks": 0, "publishes": 0,
                      "batch_slots": 0, "writebacks": 0}
        # obs plane: staging-thread activity lands on its own track of
        # the learner's trace ring (host clocks only — J006/J010 clean)
        self.ring = get_ring()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="apex-ingest-staging")

    # -- trainer side ------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def staged_ahead(self) -> int:
        """Transitions the pipeline holds (staged or in flight) that the
        trainer has not consumed yet — observability only."""
        return self._staged_ahead

    def polled_total(self) -> int:
        """Transitions EVER polled off the pool — monotone, so the
        warm/budget prediction in ``state_fn`` is race-free: when the
        staging thread asks about the NEXT chunk, this is exactly the
        transition count preceding it in the (order-preserved) stream,
        i.e. the value the serial loop's per-chunk warm gate would see.
        (``ingested + staged_ahead`` is the same quantity only between
        consumptions — mid-consume it undercounts and a train-eligible
        chunk could get merged into an ingest-only payload.)"""
        return self._polled_total

    def staged_train_steps(self) -> int:
        """Train steps staged but not yet consumed: the budget prediction
        adds these to the live step counter, else every chunk behind one
        pending fused step looks budget-eligible and the ingest-only
        stream degrades to unmerged singles."""
        return self._staged_steps

    def publish(self, version: int, params) -> None:
        """Latest-wins async param publish: the staging thread performs
        the blocking device_get + pool serialization.  ``params`` must be
        a tree the hot loop will NOT donate later — the trainer hands an
        on-device ``jnp.copy`` for exactly that reason."""
        with self._pub_lock:
            self._pub = (version, params)

    def write_back(self, shard: int, seq: int, idx, priorities) -> None:
        """Hand one consumed batch's TD priorities to the staging thread,
        which performs the blocking ``device_get`` and ships them to the
        owning shard — the write-back's host sync never lands on the hot
        loop (the same discipline as param publishes)."""
        with self._wb_lock:
            self._wb_q.append((shard, seq, idx, priorities))

    def poll_slot(self, timeout: float = 0.0) -> StagedSlot | None:
        """Next ready slot in stream order, or None when the pipeline is
        dry (no slot staged, none in flight, and the pool poll came up
        empty) and ``timeout`` has elapsed."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                # blocking get: a condition-variable wakeup on put, not a
                # sleep-quantum poll (matters on few-core hosts where the
                # staging and consumer threads share the GIL)
                slot = self._ring.get(timeout=0.005)
            except queue_lib.Empty:
                if self._error is not None:
                    raise RuntimeError(
                        "ingest pipeline staging thread died"
                    ) from self._error
                if self._stop.is_set():
                    return None
                if self._idle.is_set() and time.monotonic() >= deadline:
                    return None
                continue
            with self._ahead_lock:
                self._staged_ahead -= slot.n_trans
                self._staged_steps -= slot.planned_steps
            return slot

    # -- staging thread ----------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self._serve_publish()
                if self.keys is not None:
                    # keep the per-chip key prefetch full: each entry
                    # buys one dispatch a host split + sharded put it no
                    # longer pays on the hot loop
                    self.keys.refill()
                # NOTE: no ring-full pre-check — the blocking _put IS the
                # backpressure (bound: depth slots + one group in flight),
                # and a condition-variable wakeup hands the consumer the
                # next slot immediately where a sleep-poll would add a
                # millisecond quantum per slot
                st = self.state_fn()
                if self.client is not None:
                    # replay-service mode: ship pending write-backs, then
                    # prefer a pre-sampled batch; the chunk path below
                    # stays live as the direct-ingest FALLBACK (actors
                    # reroute to the learner when their shard wedges)
                    self._serve_writebacks()
                    if st.pull_eligible:
                        item = self.client.poll_batch(timeout=0)
                        if item is not None:
                            self._idle.clear()
                            t0 = time.perf_counter()
                            slot = self._build_batch_slot(item)
                            self.ring.complete("stage_batch", t0,
                                               time.perf_counter() - t0,
                                               track="ingest-staging")
                            self._put(slot)
                            continue
                if st.behind:
                    # replay-ratio floor: pause draining so the bounded
                    # worker queue backpressures the actor fleet
                    self._idle.set()
                    time.sleep(0.002)
                    continue
                msgs = self._poll(1, timeout=self.poll_timeout)
                if not msgs:
                    self._idle.set()
                    continue
                self._idle.clear()
                t0 = time.perf_counter()
                slot = self._build_slot(msgs[0], st)
                self.ring.complete(f"stage_{slot.kind}", t0,
                                   time.perf_counter() - t0,
                                   track="ingest-staging")
                self._put(slot)
        except BaseException as exc:      # surface to poll_slot, loudly
            self._error = exc
            self._idle.set()
            return
        # clean stop: the shards are waiting on the final write-backs
        # (strict ordering) — flush what the trainer queued before stop()
        if self.client is not None:
            self._serve_writebacks()

    def _poll(self, n: int, timeout: float = 0.0) -> list:
        msgs = self.pool.poll_chunks(n, timeout=timeout)
        if msgs:
            n_trans = sum(int(m["n_trans"]) for m in msgs)
            with self._ahead_lock:
                self._staged_ahead += n_trans
                self._polled_total += n_trans
            for m in msgs:
                # first-wins: the socket receiver's decode stamp (truer)
                # survives; mp-queue chunks get their recv time here
                obs_spans.stamp(m, "recv")
        return msgs

    def _build_slot(self, first: dict, st: PipelineState) -> StagedSlot:
        """Group ``first`` with immediately-available successors into one
        staged slot, honoring stream order and the predicted consume
        mode."""
        if st.train_eligible and self.scan_steps > 1:
            return self._build_scan_slot(first)
        if not st.train_eligible:
            cap = self._merge_cap(first["payload"])
            if cap > 1:
                return self._build_merged_slot(first)
        return self._single_slot(first,
                                 planned=1 if st.train_eligible else 0)

    def _merge(self, msgs: list[dict]) -> dict:
        if self.sharded is not None:
            return merge_group_messages(msgs, self.sharded.n_dp)
        return merge_chunk_messages(msgs)

    def _build_scan_slot(self, first: dict) -> StagedSlot:
        from apex_tpu.parallel.aggregate import stack_chunk_messages
        msgs = [first] + self._poll(self.scan_steps - 1, timeout=0)
        # quantize to powers of two so scan-shortfall widths compile
        # O(log K) programs, not one per j; leftovers become singles in
        # order (never reordered past the stack)
        j = _pow2_floor(len(msgs))
        take, rest = msgs[:j], msgs[j:]
        if j == 1:
            slot = self._single_slot(take[0])
        else:
            payload, prios, n_new = stack_chunk_messages(take)
            spans = obs_spans.merge_spans(take)     # scan stack = merge hop
            obs_spans.stamp_spans(spans, "stage")
            slot = StagedSlot(
                kind="scan", payload=self._stage(payload),
                prios=self._stage(prios), n_trans=n_new,
                n_per=tuple(int(m["n_trans"]) for m in take), chunks=j,
                planned_steps=j, spans=tuple(spans))
            with self._ahead_lock:
                self._staged_steps += j
            self.stats["scan_slots"] += 1
            self.stats["slots"] += 1
        for msg in rest:                 # order-preserving spillover
            self._put(slot)
            slot = self._single_slot(msg, planned=1)
        return slot

    def _build_merged_slot(self, first: dict) -> StagedSlot:
        cap = self._merge_cap(first["payload"])
        msgs = [first]
        # extend only while the NEXT chunk is still predicted ingest-only:
        # polled_total already counts everything in msgs, so state_fn sees
        # the effective warm/budget position of the chunk about to join —
        # a merge group never straddles the warmup (or budget) boundary
        while len(msgs) < cap:
            st = self.state_fn()
            if st.train_eligible:
                break
            more = self._poll(1, timeout=0)
            if not more:
                break
            msgs.extend(more)
        # quantize merge widths to powers of two (like the scan widths):
        # every distinct ingest shape is one XLA compile, and arbitrary
        # widths would scatter compiles across the whole run — O(log
        # merge_max) programs total instead.  Spillover stays in order.
        slot = None
        while msgs:
            j = _pow2_floor(min(len(msgs), cap))
            take, msgs = msgs[:j], msgs[j:]
            if slot is not None:
                self._put(slot)
            if j == 1:
                slot = self._single_slot(take[0], planned=0)
                continue
            merged = self._merge(take)
            self.stats["merged_slots"] += 1
            self.stats["merged_chunks"] += j
            self.stats["slots"] += 1
            spans = obs_spans.spans_of(merged)      # merge hop stamped there
            obs_spans.stamp_spans(spans, "stage")
            slot = StagedSlot(
                kind="merged", payload=self._stage(merged["payload"]),
                prios=self._stage(np.asarray(merged["priorities"],
                                             np.float32)),
                n_trans=int(merged["n_trans"]), chunks=j,
                spans=tuple(spans))
        return slot

    def _build_batch_slot(self, item: dict) -> StagedSlot:
        """Stage one pre-sampled shard batch: the sample payload and IS
        weights go on device ahead of the dispatch; the tree rows stay
        host-side (they only round-trip back to the shard with the new
        priorities)."""
        spans = obs_spans.spans_of(item)
        obs_spans.stamp_spans(spans, "stage")
        with self._ahead_lock:
            self._staged_steps += 1
        self.stats["batch_slots"] += 1
        self.stats["slots"] += 1
        return StagedSlot(
            kind="batch",
            payload=self._stage(item["batch"]),
            prios=self._stage(np.asarray(item["weights"], np.float32)),
            n_trans=0, planned_steps=1, spans=tuple(spans),
            idx=np.asarray(item["idx"]),
            shard=int(item.get("shard", 0)), seq=int(item["seq"]),
            update_key=item.get("update_key"))

    def _serve_writebacks(self) -> None:
        while True:
            with self._wb_lock:
                if not self._wb_q:
                    return
                shard, seq, idx, prios = self._wb_q.popleft()
            t0 = time.perf_counter()
            self.client.push_priorities(shard, seq, np.asarray(idx),
                                        np.asarray(jax.device_get(prios),
                                                   np.float32))
            self.stats["writebacks"] += 1
            self.ring.complete("prio_writeback", t0,
                               time.perf_counter() - t0,
                               track="ingest-staging",
                               args={"shard": shard})

    def _single_slot(self, msg: dict, planned: int = 1) -> StagedSlot:
        self.stats["slots"] += 1
        if planned:
            with self._ahead_lock:
                self._staged_steps += planned
        spans = obs_spans.spans_of(msg)
        obs_spans.stamp_spans(spans, "stage")
        return StagedSlot(
            kind="single", payload=self._stage(msg["payload"]),
            prios=self._stage(np.asarray(msg["priorities"], np.float32)),
            n_trans=int(msg["n_trans"]), planned_steps=planned,
            spans=tuple(spans))

    def _merge_cap(self, payload) -> int:
        """Max chunks (dp>1: groups) mergeable with ``payload`` as the
        first member: the payload must be a frame chunk and the merged
        shapes must still fit the pool's validation bounds (m*K <=
        capacity keeps the transition scatter duplicate-free; m*Kf <=
        frame_capacity keeps the ring write in bounds).  Sharded group
        payloads carry the dp axis in front, and the bounds are
        PER-SHARD (capacity/frame_capacity describe one chip's shard),
        so the per-shard chunk shapes at axis 1 are what must fit."""
        if not is_frame_chunk(payload):
            return 1
        ax = 1 if self.sharded is not None else 0
        cap = self.merge_max
        if self.capacity is not None:
            cap = min(cap,
                      self.capacity // max(1, payload["action"].shape[ax]))
        if self.frame_capacity is not None:
            cap = min(cap, self.frame_capacity
                      // max(1, payload["frames"].shape[ax]))
        return max(1, cap)

    def _put(self, slot: StagedSlot) -> None:
        while not self._stop.is_set():
            try:
                self._ring.put(slot, timeout=0.1)
                return
            except queue_lib.Full:
                # param publishes (and shard write-backs — a strict shard
                # is wedged until its priorities land) must not starve
                # behind a full ring
                self._serve_publish()
                if self.client is not None:
                    self._serve_writebacks()
                continue

    def _serve_publish(self) -> None:
        with self._pub_lock:
            req, self._pub = self._pub, None
        if req is None:
            return
        version, params = req
        t0 = time.perf_counter()
        if getattr(self.pool, "accepts_device_params", False):
            # co-located on-device rollouts (training/anakin.py): the pool
            # consumes the device copy directly — params never leave the
            # device; the pool device_gets internally only when an inner
            # socket fleet needs wire params (still on THIS thread)
            self.pool.publish_params(version, params)
        else:
            self.pool.publish_params(version, jax.device_get(params))
        self.stats["publishes"] += 1
        self.ring.complete("publish", t0, time.perf_counter() - t0,
                           track="ingest-staging", args={"version": version})
