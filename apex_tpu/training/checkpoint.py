"""Whole-state checkpointing + eval-from-checkpoint (reference C16/A4).

The reference persists weights only — ``torch.save(model.state_dict())``
every ``save_interval`` (``origin_repo/learner.py:166-168``, ``DQN.py:112-114``)
— so a resumed run restarts the optimizer, replay, and RNG from scratch.
Here the learner state is ONE pytree by construction
(:mod:`apex_tpu.training.state`), so a checkpoint is the full bundle:

    train_state (params + target + optimizer + step) as one tree
    replay_state (HBM ring, sum/min trees, cursors) — optional, large
    RNG key, host counters (frames ingested, param version)
    config + model spec as JSON metadata

which makes kill/restore resume *bit-exact* on the learner side, and lets
``evaluate_checkpoint`` rebuild the policy with no trainer object at all
(the ``enjoy.py:29-48`` path).

Format: one msgpack file (flax.serialization) with the state-dict tree plus
a JSON metadata string; writes are atomic (tmp + rename) and pruned to the
newest ``keep`` files, so a crash mid-save can never corrupt the newest
restorable checkpoint.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np
from flax import serialization


def _to_host_state_dict(bundle: Any) -> dict:
    return jax.tree.map(np.asarray,
                        serialization.to_state_dict(jax.device_get(bundle)))


def save_bundle(path: str, bundle: Any, meta: dict | None = None) -> str:
    """Atomically serialize ``bundle`` (any pytree of arrays/scalars) plus
    JSON-able ``meta`` to ``path``."""
    payload = {
        "state": _to_host_state_dict(bundle),
        "meta": json.dumps(meta or {}),
    }
    blob = serialization.msgpack_serialize(payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_raw(path: str) -> tuple[dict, dict]:
    """Read a checkpoint as (raw nested state dict, metadata dict) — no
    target structure needed (the ``enjoy`` path)."""
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return payload["state"], json.loads(payload["meta"])


def restore_bundle(path: str, target: Any) -> tuple[Any, dict]:
    """Impose the saved state onto ``target`` (a freshly-constructed bundle
    with matching structure); returns ``(restored_bundle, meta)``."""
    raw, meta = load_raw(path)
    return serialization.from_state_dict(target, raw), meta


@dataclass
class Checkpointer:
    """Directory of ``ckpt_<step>.msgpack`` files, newest ``keep`` retained."""

    directory: str
    keep: int = 3

    def __post_init__(self):
        os.makedirs(self.directory, exist_ok=True)

    def _step_of(self, name: str) -> int:
        return int(name[len("ckpt_"):-len(".msgpack")])

    def _all(self) -> list[str]:
        names = [n for n in os.listdir(self.directory)
                 if n.startswith("ckpt_") and n.endswith(".msgpack")]
        return sorted(names, key=self._step_of)

    def save(self, step: int, bundle: Any, meta: dict | None = None) -> str:
        path = os.path.join(self.directory, f"ckpt_{step}.msgpack")
        save_bundle(path, bundle, meta)
        for stale in self._all()[:-self.keep]:
            os.remove(os.path.join(self.directory, stale))
        return path

    def latest_path(self) -> str | None:
        names = self._all()
        return os.path.join(self.directory, names[-1]) if names else None

    def restore_latest(self, target: Any) -> tuple[Any, dict, int] | None:
        """``(bundle, meta, step)`` from the newest checkpoint, or None."""
        path = self.latest_path()
        if path is None:
            return None
        bundle, meta = restore_bundle(path, target)
        step = self._step_of(os.path.basename(path))
        return bundle, meta, step


class CheckpointableTrainer:
    """Shared save/restore plumbing for every trainer class.

    A trainer mixes this in and provides: ``cfg``, ``model_spec``,
    ``train_state``, ``replay_state``, ``key``, ``checkpointer``
    (``Checkpointer | None``), ``steps_rate``, and ``_counters()`` /
    ``_apply_counters(meta)`` for its host-side progress counters — one
    checkpoint format, one implementation, no per-trainer drift.
    """

    def _counters(self) -> dict:
        raise NotImplementedError

    def _apply_counters(self, meta: dict) -> None:
        raise NotImplementedError

    def _bundle(self) -> dict:
        return dict(train_state=self.train_state,
                    replay_state=self.replay_state,
                    key=jax.random.key_data(self.key))

    def _meta(self) -> dict:
        spec = dict(self.model_spec)
        spec["compute_dtype"] = str(np.dtype(spec["compute_dtype"]))
        return dict(config=config_to_meta(self.cfg), model_spec=spec,
                    **self._counters())

    def save_checkpoint(self) -> str:
        if self.checkpointer is None:
            raise ValueError("no checkpoint directory configured "
                             "(pass checkpoint_dir)")
        return self.checkpointer.save(self.steps_rate.total, self._bundle(),
                                      self._meta())

    def restore(self, path: str | None = None):
        """Restore the full learner bundle (params, target, optimizer,
        replay contents, RNG) + host counters; the learner side of a resumed
        run continues bit-exactly."""
        if path is None:
            if self.checkpointer is None:
                raise ValueError("no checkpoint directory configured "
                                 "(pass checkpoint_dir)")
            path = self.checkpointer.latest_path()
            if path is None:
                raise FileNotFoundError(
                    f"no checkpoint found in "
                    f"{self.checkpointer.directory!r}")
        bundle, meta = restore_bundle(path, self._bundle())
        self.train_state = bundle["train_state"]
        self.replay_state = bundle["replay_state"]
        self.key = jax.random.wrap_key_data(bundle["key"])
        self._apply_counters(meta)
        return self


# -- config/meta round-tripping -------------------------------------------

def config_to_meta(cfg) -> dict:
    """ApexConfig -> JSON-able nested dict."""
    return dataclasses.asdict(cfg)


def config_from_meta(meta_cfg: dict):
    """Rebuild an ApexConfig from :func:`config_to_meta` output."""
    from apex_tpu.config import (ActorConfig, ApexConfig, AQLConfig,
                                 CommsConfig, EnvConfig, LearnerConfig,
                                 R2D2Config, ReplayConfig)

    def build(cls, d):
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: (tuple(v) if isinstance(v, list) else v)
              for k, v in d.items() if k in fields}
        return cls(**kw)

    return ApexConfig(
        env=build(EnvConfig, meta_cfg["env"]),
        replay=build(ReplayConfig, meta_cfg["replay"]),
        learner=build(LearnerConfig, meta_cfg["learner"]),
        actor=build(ActorConfig, meta_cfg["actor"]),
        aql=build(AQLConfig, meta_cfg["aql"]),
        # older checkpoints predate the r2d2 section: default it
        r2d2=build(R2D2Config, meta_cfg.get("r2d2", {})),
        comms=build(CommsConfig, meta_cfg["comms"]),
    )


def run_policy_episodes(env, step_fn, key, episodes: int, epsilon: float,
                        max_steps: int, seed_base: int,
                        reset_hook=None, render_hook=None) -> list[float]:
    """The one greedy-eval episode loop (``eval.py:49-87`` semantics)
    shared by trainer ``evaluate`` methods and
    :func:`evaluate_checkpoint` — env reset seeding, key splitting, step
    accounting, and render flushing live here exactly once.

    ``step_fn(obs_batch, epsilon, key) -> action`` hides the family
    (params binding, recurrent carry); ``reset_hook()`` runs per episode
    (recurrent policies reset their carry)."""
    import jax
    import jax.numpy as jnp

    rewards = []
    for ep in range(episodes):
        obs, _ = env.reset(seed=seed_base + ep)
        if reset_hook is not None:
            reset_hook()
        total, done, steps = 0.0, False, 0
        while not done and steps < max_steps:
            key, k = jax.random.split(key)
            a = step_fn(np.asarray(obs)[None], jnp.float32(epsilon), k)
            obs, r, term, trunc, _ = env.step(a)
            if render_hook is not None:
                render_hook(obs)
            total += float(r)
            done = term or trunc
            steps += 1
        rewards.append(total)
        flush = getattr(render_hook, "flush_episode", None)
        if flush is not None:      # save-mode hooks write one file/episode
            flush()
    return rewards


# -- eval-from-checkpoint (the reference's `enjoy` role) -------------------

def evaluate_checkpoint(path: str, episodes: int = 10, epsilon: float = 0.0,
                        max_steps: int = 10_000, seed: int = 7,
                        render_hook=None) -> float:
    """Rebuild env + model purely from checkpoint metadata, load params, and
    run unclipped epsilon-greedy episodes (``enjoy.py:29-48``;
    ``DQN.py:124-149``).  No trainer object is constructed.

    ``render_hook(obs) -> None``, if given, is called every step with the
    raw observation (the reference renders to screen; headless hosts log or
    record instead).
    """
    import jax.numpy as jnp

    from apex_tpu.envs.registry import make_eval_env

    raw, meta = load_raw(path)
    cfg = config_from_meta(meta["config"])
    spec = dict(meta["model_spec"])
    spec["compute_dtype"] = jnp.dtype(spec["compute_dtype"])
    params = raw["train_state"]["params"]

    # family dispatch by spec shape: AQL specs carry action_dim (Box
    # actions), recurrent specs carry lstm_features, DQN specs carry
    # num_actions only
    reset_policy = None
    if "action_dim" in spec:
        from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
        model = AQLNetwork(**spec, noisy_deterministic=True)
        aql_policy = jax.jit(make_aql_policy_fn(model))

        def policy(params, obs, eps, key):
            a, _, _, _ = aql_policy(params, obs, eps, key)
            return np.asarray(a[0])
    elif "lstm_features" in spec:
        from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                               make_recurrent_policy_fn)
        model = RecurrentDuelingDQN(**spec)
        rec_policy = jax.jit(make_recurrent_policy_fn(model))
        carry_box = [model.initial_state(1)]

        def policy(params, obs, eps, key):
            a, _, carry_box[0] = rec_policy(params, obs, carry_box[0],
                                            eps, key)
            return int(a[0])

        def reset_policy():       # fresh carry each episode
            carry_box[0] = model.initial_state(1)
    else:
        from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
        model = DuelingDQN(**spec)
        dqn_policy = jax.jit(make_policy_fn(model))

        def policy(params, obs, eps, key):
            a, _ = dqn_policy(params, obs, eps, key)
            return int(a[0])

    env = make_eval_env(cfg.env.env_id, cfg.env, seed=seed)
    rewards = run_policy_episodes(
        env, lambda obs, eps, k: policy(params, obs, eps, k),
        jax.random.key(seed), episodes, epsilon, max_steps,
        seed_base=seed, reset_hook=reset_policy, render_hook=render_hook)
    env.close()
    return float(np.mean(rewards))
