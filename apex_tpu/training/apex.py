"""Ape-X driver: actor pool + fused TPU learner, actually concurrent.

Capability parity with the reference ``ApeX.py`` (C11) and its
``origin_repo`` flagship topology, on one host:

* N worker processes explore continuously with the epsilon ladder and ship
  fixed-shape frame chunks with precomputed priorities
  (:mod:`apex_tpu.actors.pool`).
* The learner ingests chunks into the HBM frame-pool replay and runs the
  fused sample/loss/update/priority step — ingest+train fuse into one XLA
  program whenever a chunk is pending.
* Params publish version-stamped every ``publish_interval`` learner steps
  with a wall-clock floor (``publish_min_seconds``) — the reference's
  every-25-steps cadence (``learner.py:169-170``) assumed an 11-steps/s
  learner; at TPU step rates a pure step cadence would saturate the host
  queues.
* Warmup gate: no training until ``replay.warmup`` transitions are resident
  (``arguments.py:47-48``, ``replay.py:104-106``).

The reference's ``ApeX.py`` accidentally ran acting and learning
sequentially (``Process(target=test.sampling_data())`` calls the method
eagerly — ``ApeX.py:94-97``); here they genuinely overlap: workers are
independent processes, and the learner thread blocks only on device results.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.actors.pool import ActorPool, ActorTimingStat
from apex_tpu.config import ApexConfig
from apex_tpu.fleet.heartbeat import Heartbeat
from apex_tpu.fleet.registry import FleetRegistry
from apex_tpu.obs import spans as obs_spans
from apex_tpu.parallel.aggregate import stack_chunk_messages
from apex_tpu.envs.registry import (make_env, make_eval_env, num_actions,
                                    unstacked_env_spec)
from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.ops.losses import make_optimizer
from apex_tpu.replay.base import check_hbm_budget
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.population.controller import PopulationStat
from apex_tpu.runtime.codec import KeyframeRequest
from apex_tpu.serving.deploy import ServingStat
from apex_tpu.tenancy.scheduler import TenancyStat
from apex_tpu.training.checkpoint import (CheckpointableTrainer,
                                          Checkpointer)
from apex_tpu.training.learner import LearnerCore
from apex_tpu.training.state import create_train_state
from apex_tpu.utils.metrics import MetricLogger, RateCounter
from apex_tpu.utils.seeding import set_global_seeds


def dqn_env_specs(cfg: ApexConfig):
    """(model_spec, frame_shape, frame_dtype, frame_stack) from a probe env
    — shared by the driver, the multi-host actor role, and the evaluator."""
    probe = make_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed,
                     stack_frames=False)
    frame_shape, frame_dtype, frame_stack = unstacked_env_spec(probe, cfg.env)
    model_spec = dict(
        num_actions=num_actions(probe),
        obs_is_image=len(frame_shape) == 3,
        compute_dtype=jnp.dtype(cfg.learner.compute_dtype),
        scale_uint8=np.dtype(frame_dtype) == np.uint8)
    probe.close()
    return model_spec, frame_shape, frame_dtype, frame_stack


def dqn_model_spec(cfg: ApexConfig) -> dict:
    return dqn_env_specs(cfg)[0]


class ConcurrentTrainer(CheckpointableTrainer):
    """The concurrent learner loop shared by every distributed family
    (Ape-X DQN, Ape-X AQL): drain worker chunk messages, fuse ingest+train,
    enforce the replay-ratio band, publish versioned params, checkpoint.

    Chunk messages are family-agnostic dicts:
    ``{"payload": <ingest pytree>, "priorities": f32[K], "n_trans": int}`` —
    the payload goes straight into the family's fused step.

    Subclasses construct: ``cfg, key, pool, replay, replay_state,
    train_state, core, _fused, _train, _ingest, log, steps_rate,
    frames_rate, ingested, param_version, checkpointer`` and the replay-ratio
    knobs (see :class:`ApexTrainer` for the reference wiring).
    """

    # scan dispatch: ApexTrainer sets these when config.scan_steps > 1 on
    # a single-shard DQN learner; None = chunk-at-a-time everywhere else
    _multi = None
    scan_steps = 1
    scan_dispatches = 0      # K-step dispatches taken (observability)
    # async ingest pipeline (training/ingest_pipeline.py): live only
    # inside train() when config.learner.ingest_pipeline — single-shard
    # (chunk-granular) and dp>1 (round-robin-group-granular, pre-placed
    # per-chip keys) alike; _ingest_multi is the scan-of-ingests dispatch
    # for slots the replay-ratio cap says to absorb without training
    _pipeline = None
    _pipeline_base = 0       # self.ingested when the pipeline started
    _ingest_multi = None
    _dispatch_gap = None
    _pipeline_last_stats = None
    # checkpoint/log bookkeeping persists ACROSS train() calls: a driver
    # interleaving short train() bursts with eval must still hit its
    # save/log cadence (per-call resets would silence both whenever
    # interval > steps-per-call)
    _last_save = 0
    _last_log = 0
    # actor-plane observability: latest ActorTimingStat per worker (the
    # vector workers' periodic policy-wait/env-step/drain splits) and the
    # cumulative count of stats workers dropped on a full stat queue
    actor_timing: dict | None = None
    stat_drops = 0
    # fleet control plane (apex_tpu/fleet): the membership registry fed by
    # Heartbeats off the stat drain (+ message-arrival liveness on socket
    # pools), its REP status server (socket pools only), and where the
    # periodic fleet_summary.json lands
    fleet: FleetRegistry | None = None
    _fleet_status = None
    # obs plane (apex_tpu/obs): the learner-side span join — publish-time
    # ledger + frame-age-at-train / param-propagation-lag histograms +
    # sampled chunk-lineage trace events (persists across train() calls
    # like the checkpoint marks)
    _obs = None
    # sharded replay service (apex_tpu/replay_service): when a
    # ReplayServiceClient is attached, sampling lives in the shard fleet —
    # the loop consumes pre-sampled batches (pipeline "batch" slots, or
    # direct client polls on the serial path), trains via
    # core.update_from_batch, and routes priority write-backs to the
    # owning shard.  The chunk path stays live as the direct-ingest
    # fallback (actors reroute to the learner when their shard wedges).
    replay_client = None
    _train_batch = None
    service_steps = 0        # train steps taken on shard-served batches
    # learner-epoch fencing (PR 8): bumped once per learner LIFE (restore
    # reads the predecessor's epoch from checkpoint meta and adds one),
    # stamped onto every param publish and replay write-back so parked
    # actors can tell a restarted learner from a stalled one and shards
    # can reject a dead learner's ghost write-backs
    learner_epoch = 1
    # registry reactions (PR 8): when >= relax_floor_dead_frac of the
    # actor fleet is DEAD, the replay-ratio floor relaxes (the surviving
    # actors must not be starved by a throughput target sized for the
    # full fleet) and restores as peers rejoin
    _floor_relaxed = False
    floor_relaxes = 0        # times the floor reaction engaged
    # fleet SLO engine (apex_tpu/obs/slo): declarative objectives judged
    # by multi-window burn rates on every health tick; alert states land
    # in fleet_summary.json / the status table / apex_slo_* Prometheus
    # rows, and the scale supervisor's --scale-signal slo keys off the
    # snapshot's severity.  Lazily built on the first health tick so
    # knob env twins set by a drill are honored.
    _slo = None
    # serving tier (apex_tpu/serving): the deployment controller's
    # latest snapshot, shipped as a ServingStat on the stat channel —
    # folded into fleet_summary.json ("serving" section), the status
    # table, the SLO signal space, and the apex_serving_* rows, so the
    # canary timeline survives the controller the way the registry
    # survives an actor
    serving_state: dict | None = None
    # multi-tenant plane (apex_tpu/tenancy): the placement controller's
    # latest snapshot off the stat channel — folded into
    # fleet_summary.json ("tenancy" section), the status table's
    # tenancy lines, and the apex_tenancy_* Prometheus rows
    tenancy_state: dict | None = None
    # population plane (apex_tpu/population): the PBT controller's
    # latest snapshot off the stat channel ("population" section /
    # status lines / apex_population_* rows), plus the learner-side
    # half — a bounded ctl command queue the status-server thread
    # enqueues into and the trainer thread drains on its health tick
    # (exploit = donor-checkpoint weight copy + epoch bump, explore =
    # live hyperparameter application), with the applied-command
    # evidence surfaced as metrics["population_ctl"]
    population_state: dict | None = None
    _ctl_queue = None
    _population_ctl: dict | None = None
    hparams_live: dict | None = None
    # episode-scalar log index for the stats drain (reset per train()
    # call; an attribute so the fused on-device loop shares the drain)
    _episode_idx = 0

    # -- param plane -------------------------------------------------------

    def _publish(self) -> None:
        self.param_version += 1
        if self._obs is not None:
            # the param-propagation-lag join key: when THIS version left
            self._obs.note_publish(self.param_version)
        if self._pipeline is not None:
            # hand the staging thread an on-device COPY: the hot loop's
            # next fused step donates train_state, which would invalidate
            # the original buffers under the staging thread's device_get.
            # The copy dispatch is async — no hot-loop drain (the serial
            # path below drains the whole device pipeline per publish).
            params = jax.tree.map(jnp.copy, self.train_state.params)
            self._pipeline.publish(self.param_version, params)
            return
        if getattr(self.pool, "accepts_device_params", False):
            # co-located on-device rollouts (training/anakin.py): hand the
            # engine an on-device COPY (the next fused step donates
            # train_state) — params never leave the device on this path
            params = jax.tree.map(jnp.copy, self.train_state.params)
            self.pool.publish_params(self.param_version, params)
            return
        host_params = jax.device_get(self.train_state.params)
        self.pool.publish_params(self.param_version, host_params)

    # -- cooperative shutdown ---------------------------------------------

    _stop_requested = None      # lazily a threading.Event (request_stop)

    def request_stop(self) -> None:
        """Ask a running :meth:`train` (possibly in another thread) to
        return at its next loop iteration — graceful shutdown without
        waiting out ``max_seconds``."""
        import threading
        if self._stop_requested is None:
            self._stop_requested = threading.Event()
        self._stop_requested.set()

    # -- multi-chip plan (shared by both families) ------------------------

    def _init_sharded(self) -> None:
        """dp > 1: shard the replay per chip, pmean grads over ICI,
        round-robin whole chunks across shards (BASELINE.json north star:
        HBM replay + 8-chip learner).  Total replay capacity = per-chip
        capacity x dp.  Requires ``self.core``/``self.replay_state``/
        ``self.train_state``/``self.pool`` already built single-shard;
        AQL's NoisyNet update key is handled by ``ShardedLearner`` via
        ``core.update_needs_key``."""
        from apex_tpu.parallel.aggregate import ChunkAggregator
        from apex_tpu.parallel.learner import ShardedLearner
        from apex_tpu.parallel.mesh import make_mesh

        n = self.n_dp
        devices = jax.devices()
        if len(devices) < n:
            raise ValueError(
                f"mesh_shape={self.cfg.learner.mesh_shape} needs {n} "
                f"devices, have {len(devices)}")
        mesh = make_mesh(dp=n, devices=devices[:n])
        sl = ShardedLearner(self.core, mesh)
        self.replay_state = sl.shard_replay_state(self.replay_state)
        self.train_state = sl.replicate_train_state(self.train_state)
        self.pool = ChunkAggregator(self.pool, n)
        self._make_sharded_fns(mesh)

    def _make_sharded_fns(self, mesh=None) -> None:
        """(Re)build the sharded plan's jitted dispatches off the CURRENT
        core — construction calls this with the fresh mesh; a live lr
        application (``apply_hparams``) calls it bare to re-jit against
        the rebuilt optimizer on the mesh already in hand."""
        from apex_tpu.parallel.learner import ShardedLearner

        sl = self.sharded = ShardedLearner(
            self.core, mesh if mesh is not None else self.sharded.mesh)
        fused = sl.make_fused_step()
        train = sl.make_train_step()
        ingest = sl.make_ingest()

        def _keys(key):
            # pre-split + pre-placed per-chip keys (the pipeline's
            # KeyPrefetcher hands raw uint32 key data already sharded
            # over the mesh) pass straight through; a raw chain key pays
            # the serial per-dispatch split + sharded put
            if getattr(key, "dtype", None) == jnp.uint32:
                return key
            return sl.device_keys(key)

        def _fused(ts, rs, payload, prios, key, beta):
            return fused(ts, rs, payload, prios, _keys(key), beta)

        def _train(ts, rs, key, beta):
            return train(ts, rs, _keys(key), beta)

        self._fused, self._train, self._ingest = _fused, _train, ingest

    # -- main loop ---------------------------------------------------------

    def train(self, total_steps: int, max_seconds: float = 3600.0,
              log_every: int = 200):
        """Run ``total_steps`` MORE learner updates (or until the wall
        clock).  On a restored trainer the step counter continues from the
        checkpoint — same resume contract as the single-process drivers."""
        cfg = self.cfg
        pool = self.pool
        target_steps = self.steps_rate.total + total_steps
        if self.actor_timing is None:
            self.actor_timing = {}
        from apex_tpu.obs.trace import get_ring, set_process_label
        from apex_tpu.utils.profiling import DispatchGapTimer
        set_process_label("learner")
        ring = get_ring()
        if self._obs is None:
            self._obs = obs_spans.LearnerObs(ring=ring)
        gap = self._dispatch_gap = DispatchGapTimer(ring=ring,
                                                    track="learner-hot-loop")
        client = self.replay_client
        if client is not None and self._train_batch is None:
            # dp>1 included: _make_batch_train shards the service batch
            # over the mesh and pmeans the update (PR 17)
            self._train_batch = self._make_batch_train()
        pipeline = None
        if self._use_pipeline():
            from apex_tpu.training.ingest_pipeline import IngestPipeline
            sharded = getattr(self, "sharded", None)
            pipeline = IngestPipeline(
                pool,
                depth=getattr(cfg.learner, "pipeline_depth", 2),
                scan_steps=(self.scan_steps if self._multi is not None
                            else 1),
                merge_max=getattr(cfg.learner, "pipeline_merge", 8),
                state_fn=self._pipeline_state,
                capacity=getattr(self.replay, "capacity", None),
                frame_capacity=getattr(self.replay, "f_capacity", None),
                # dp>1: group-granular staging + the key prefetcher takes
                # over the dispatch key chain (seeded with self.key;
                # _dispatch_key writes the advanced chain state back)
                sharded=sharded,
                key=self.key if sharded is not None else None,
                replay_client=client)
            self._pipeline = pipeline
            self._pipeline_base = self.ingested
        if self.fleet is None:
            self.fleet = FleetRegistry(cfg.comms)
        try:
            pool.start()
        except BaseException:
            self._pipeline = None      # never started; don't route to it
            raise
        # learner-epoch fencing: stamp the param plane and the replay
        # write-back plane with this life's epoch (socket pools only —
        # in-host fleets die with the learner, nothing to fence)
        set_epoch = getattr(pool, "set_learner_epoch", None)
        if set_epoch is not None:
            set_epoch(self.learner_epoch)
        if client is not None:
            client.learner_epoch = self.learner_epoch
        self._start_status_server()
        if pipeline is not None:
            # staging starts only once the pool is live: its thread owns
            # every poll_chunks/publish_params call from here to stop()
            # (see RemotePool's thread-affinity contract)
            pipeline.start()
        try:
            self._publish()
            last_publish = time.monotonic()
            t_end = last_publish + max_seconds
            self._episode_idx = 0
            # interval-since-last semantics (not ``% interval == 0``): a
            # scan dispatch ticks the step counter by K, which can jump
            # over any exact multiple.  Save/log marks live on self.
            last_pub_step = self.steps_rate.total
            last_health = last_publish
            metrics = None      # no update has run yet this call (a restored
                                # trainer can hit the log gate before one)

            while self.steps_rate.total < target_steps:
                now = time.monotonic()
                stop = self._stop_requested
                if now > t_end or (stop is not None and stop.is_set()):
                    break
                # ``warm`` gates the LOCAL replay's train paths (train-only
                # steps, fused chunk-train) — in service mode the local
                # pool only fills through the fallback, so those paths
                # stay cold until it genuinely warms.  The ratio budget
                # and floor run on the EFFECTIVE ingest count (local +
                # what the shard fleet reports), so service-mode training
                # is budgeted against real fleet-wide ingest.
                warm = self.ingested >= cfg.replay.warmup
                ingested_eff = self.ingested + (
                    client.ingested_total() if client is not None else 0)
                consumed = self.steps_rate.total * self.core.batch_size
                budget = (float("inf") if self.train_ratio is None
                          else ingested_eff * self.train_ratio
                          / self.core.batch_size)
                # Replay-ratio floor: learner behind -> pause draining so the
                # bounded chunk queue backpressures the actor fleet.  The
                # EFFECTIVE floor is None while the dead-fleet reaction
                # has relaxed it (see _react_to_fleet).
                floor = self._min_ratio_effective()
                behind = (warm and floor is not None
                          and consumed < ingested_eff * floor)

                got_data = False
                if pipeline is not None:
                    # pipelined: consume ready-on-device slots; the
                    # staging thread already polled/decoded/merged/staged
                    # while the previous dispatch ran.  Service mode
                    # consumes even when "behind" — behind means the
                    # learner owes MORE training, and batch slots are
                    # exactly that
                    slot = None
                    if not behind or client is not None:
                        slot = pipeline.poll_slot(
                            timeout=0 if (warm or client is not None)
                            else 0.05)
                    if slot is not None:
                        got_data = True
                        m = self._consume_slot(slot, warm, budget,
                                               target_steps)
                        if m is not None:
                            metrics = m
                else:
                    if client is not None \
                            and self.steps_rate.total < budget:
                        # serial service path: one pre-sampled batch per
                        # iteration, write-back shipped inline
                        item = client.poll_batch(timeout=0.02)
                        if item is not None:
                            got_data = True
                            m = self._consume_slot(
                                self._host_batch_slot(item), warm, budget,
                                target_steps)
                            if m is not None:
                                metrics = m
                    # serial: scan dispatch (config.scan_steps > 1) asks
                    # for K chunks only when the learner can take all K
                    # steps within BOTH the ratio budget and the
                    # remaining total_steps contract ("run total_steps
                    # MORE updates" — a K-dispatch must not overshoot
                    # it) — exactly the chunk-backlog regime where
                    # dispatch latency, not data supply, bounds throughput
                    want = 1
                    if (self._multi is not None and warm
                            and target_steps - self.steps_rate.total
                            >= self.scan_steps
                            and self.steps_rate.total + self.scan_steps - 1
                            < budget):
                        want = self.scan_steps

                    msgs = []
                    if not behind:
                        msgs = pool.poll_chunks(want,
                                                timeout=0 if warm else 0.05)
                    if msgs:
                        got_data = True
                        m = self._drain_serial(msgs, want, warm, budget)
                        if m is not None:
                            metrics = m
                if not got_data and warm \
                        and self.steps_rate.total < budget:
                    k = self._dispatch_key()
                    gap.about_to_dispatch()
                    self.train_state, self.replay_state, metrics = \
                        self._train(self.train_state, self.replay_state, k,
                                    jnp.float32(self._beta()))
                    gap.dispatch_returned()
                    self.steps_rate.tick()
                elif not got_data and warm:
                    time.sleep(0.002)   # replay-ratio cap reached

                steps = self.steps_rate.total
                if (self.checkpointer is not None
                        and steps - self._last_save
                        >= cfg.learner.save_interval):
                    self.save_checkpoint()
                    self._last_save = steps
                # Pre-first-step republish (slow cadence) is needed only for
                # socket pools: a TCP subscriber that joined after the
                # initial publish would otherwise never receive params
                # (PUB/SUB has no replay — the zmq slow-joiner race) and an
                # actor fleet without params produces no chunks: deadlock.
                # mp pools have pre-existing queues, so the initial publish
                # cannot be lost and warmup republishes would only burn the
                # ingest thread on param serialization.
                if steps:
                    due = (now - last_publish >= self.publish_min_seconds
                           and (steps - last_pub_step
                                >= cfg.learner.publish_interval
                                or now - last_publish
                                > 10 * self.publish_min_seconds))
                else:
                    due = (getattr(pool, "needs_warmup_republish", False)
                           and now - last_publish
                           > 10 * self.publish_min_seconds)
                if due:
                    self._publish()
                    last_publish = now
                    last_pub_step = steps

                # Failure detection (beyond the reference, SURVEY.md §5.3:
                # its fleets never notice actor death): crashed workers are
                # logged and respawned on the same ladder slot; remote
                # peers run the fleet registry's JOINING/ALIVE/SUSPECT/DEAD
                # machine (config thresholds in CommsConfig — this
                # replaced the old hardcoded silent_peers(60.0) report).
                if self.respawn_workers and now - last_health >= 5.0:
                    self._health_tick(steps)
                    last_health = now

                self._drain_stats(steps)

                # metrics is None until the first train dispatch, so the
                # gate needs no warm check — and in service mode the
                # LOCAL pool never warms while shard batches train fine
                if metrics is not None \
                        and steps - self._last_log >= log_every:
                    extra = gap.snapshot()
                    if pipeline is not None:
                        extra |= {f"pipeline_{k}": v
                                  for k, v in pipeline.stats.items()}
                    if self._obs is not None:
                        extra |= self._obs.scalars()
                    if client is not None:
                        extra |= {"service_batches": client.batches,
                                  "service_steps": self.service_steps,
                                  "service_ingested":
                                      client.ingested_total()}
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate,
                           "param_version": self.param_version,
                           "ingested": ingested_eff} | extra, steps)
                    self._last_log = steps
        finally:
            if pipeline is not None:
                # stop staging BEFORE the pool teardown (the staging
                # thread is the pool's only chunk consumer while live)
                self._pipeline_last_stats = dict(pipeline.stats)
                pipeline.stop()
                self._pipeline = None
            if self._fleet_status is not None:
                self._fleet_status.stop()
                self._fleet_status = None
            self._dump_fleet_summary()     # final registry state on disk
            pool.cleanup()
            stop = self._stop_requested
            if stop is not None:
                # honored (or stale) requests clear at EXIT, never at
                # entry: a request racing train() startup must still stop
                # this run; the NEXT call then starts fresh
                stop.clear()
        return self

    def _start_status_server(self) -> None:
        """Socket learner: serve live registry snapshots for
        ``--role status`` (own REP socket + thread; a bind failure —
        e.g. two learners on one host — degrades to no status surface,
        never to a dead learner).  Shared by the chunk-driven loop and
        the fused on-device loop (:mod:`apex_tpu.ondevice.fused`)."""
        if not hasattr(self.pool, "peer_seen") \
                or self._fleet_status is not None:
            return
        try:
            from apex_tpu.fleet.registry import FleetStatusServer
            if self._ctl_queue is None:
                # built BEFORE the server thread starts (the enqueue
                # hook runs on that thread); bounded so a runaway
                # controller can only ever park 8 commands
                import queue as queue_lib
                self._ctl_queue = queue_lib.Queue(maxsize=8)
            self._fleet_status = FleetStatusServer(
                self.cfg.comms, self.fleet,
                metrics_fn=self._metrics_text,
                snapshot_fn=self.fleet_summary,
                ctl_fn=self._enqueue_ctl)
            self._fleet_status.start()
        except Exception:
            self._fleet_status = None

    def _health_tick(self, steps: int) -> None:
        """One health tick: respawns, registry machine, SLO judgment,
        fleet reactions, ctl drain, summary dump.  Shared by both hot
        loops — the caller owns the 5s cadence gate."""
        pool = self.pool
        if hasattr(pool, "dead_workers"):      # local fleets
            for dead in pool.dead_workers():
                self.log.scalars({"worker_respawn": dead}, steps)
                pool.respawn_worker(dead)
        if hasattr(pool, "peer_seen"):         # socket fleets:
            # chunk arrivals count as liveness even when a
            # backpressured actor's stat puts drop
            self.fleet.observe_seen(pool.peer_seen())
        for ident, old, new in self.fleet.tick():
            self.log.scalars(
                {f"fleet_{new.lower()}_transition": 1.0}, steps)
            if self.log.verbose or new in ("SUSPECT", "DEAD"):
                print(f"fleet: {ident} {old} -> {new}", flush=True)
        fm = self.fleet.metrics()
        if fm["peers"]:
            self.log.scalars(
                {"fleet_alive": fm["alive"],
                 "fleet_suspect": fm["suspect"],
                 "fleet_dead": fm["dead"],
                 "fleet_parked": fm["parked"],
                 "fleet_rejoins": fm["rejoins"]}, steps)
        # judge BEFORE reacting: the floor reaction consults
        # the actor-capacity alert the sample just advanced
        self._slo_tick(steps)
        self._react_to_fleet(steps)
        # PBT ctl commands drain HERE (trainer thread): the
        # status thread only ever enqueued them, so the
        # weight copy / optimizer rebuild touch learner
        # state from exactly one thread
        self._drain_ctl(steps)
        self._dump_fleet_summary()

    def _drain_stats(self, steps: int) -> None:
        """Drain the pool's stat stream: heartbeats into the registry,
        controller snapshots into their sections, timing/episode stats
        into the scalar log.  Shared by both hot loops."""
        for stat in self.pool.poll_stats():
            self.stat_drops += getattr(stat, "dropped_stats", 0)
            if isinstance(stat, Heartbeat):
                self.fleet.observe(stat)
                continue
            if isinstance(stat, ServingStat):
                self.serving_state = dict(stat.snapshot)
                continue
            if isinstance(stat, TenancyStat):
                self.tenancy_state = dict(stat.snapshot)
                continue
            if isinstance(stat, PopulationStat):
                self.population_state = dict(stat.snapshot)
                continue
            if isinstance(stat, KeyframeRequest):
                # a subscriber could not apply a param delta (missed
                # keyframe / checksum mismatch): force the next publish
                # dense.  No-op on dense-mode pools.
                fk = getattr(self.pool, "force_keyframe", None)
                if callable(fk):
                    fk()
                continue
            if isinstance(stat, ActorTimingStat):
                self.actor_timing[stat.actor_id] = stat
                self.log.scalars(
                    {"actor_fps": stat.frames_per_sec,
                     "actor_policy_wait_frac":
                         stat.policy_wait_frac,
                     "actor_env_step_frac": stat.env_step_frac,
                     "actor_drain_frac": stat.drain_frac,
                     "actor_dispatch_gap_ms_p50":
                         stat.dispatch_gap_ms_p50}, steps)
                continue
            self.log.scalars(
                {"episode_reward": stat.reward,
                 "episode_length": stat.length,
                 "actor_id": stat.actor_id}, self._episode_idx)
            self._episode_idx += 1

    def actor_plane(self) -> dict | None:
        """Aggregate actor-plane view from the latest per-worker
        :class:`~apex_tpu.actors.pool.ActorTimingStat`\\ s (the e2e bench
        surfaces this next to ``env_frames_per_sec``), or None when no
        worker has reported yet (scalar fleets / timing_interval=0)."""
        if not self.actor_timing:
            return None
        ts = list(self.actor_timing.values())

        def mean(vals):
            return round(float(np.mean(vals)), 4)

        return {
            "workers_reporting": len(ts),
            "double_buffer": all(t.double_buffer for t in ts),
            "frames_per_sec_sum":
                round(sum(t.frames_per_sec for t in ts), 1),
            "policy_wait_frac": mean([t.policy_wait_frac for t in ts]),
            "env_step_frac": mean([t.env_step_frac for t in ts]),
            "drain_frac": mean([t.drain_frac for t in ts]),
            "dispatch_gap_ms_p50":
                mean([t.dispatch_gap_ms_p50 for t in ts]),
            "stat_drops": self.stat_drops,
        }

    def latency_summary(self) -> dict | None:
        """The e2e bench ``latency`` section: the chunk-lineage
        histograms (frame-age-at-train, param-propagation-lag) plus the
        hot-loop dispatch-gap percentiles, or None before train()."""
        if self._obs is None:
            return None
        out = self._obs.summary()
        if self._dispatch_gap is not None:
            out["dispatch_gap_ms"] = self._dispatch_gap.snapshot()
        return out

    def _metrics_text(self) -> str:
        """Prometheus exposition for the status server's ``b"metrics"``
        request (runs on the server thread: every read here is either a
        locked snapshot or a GIL-atomic tail read)."""
        from apex_tpu.obs import metrics as obs_metrics

        gauges = dict(obs_metrics.scalar_tails(self.log.history))
        gauges["learner_steps_per_sec"] = self.steps_rate.rate
        gauges["learner_frames_per_sec"] = self.frames_rate.rate
        counters = {
            "learner_steps_total": self.steps_rate.total,
            "transitions_ingested_total": self.ingested,
            "param_version": self.param_version,
            "stat_drops_total": self.stat_drops,
        }
        wire_fn = getattr(self.pool, "wire_summary", None)
        if callable(wire_fn):
            # apex_wire_* rows (runtime/codec.py): decode counts + the
            # param-delta publisher's byte counters.  Registered
            # families in obs.metrics — J015 keeps this dict honest.
            w = wire_fn()
            counters.update({
                "wire_codec_chunks": w.get("codec_chunks"),
                "wire_codec_rejected": w.get("codec_rejected"),
                "wire_param_publishes": w.get("param_publishes"),
                "wire_param_keyframes": w.get("param_keyframes"),
                "wire_param_deltas": w.get("param_deltas"),
                "wire_param_delta_bytes": w.get("param_delta_bytes"),
                "wire_param_bytes_out": w.get("param_bytes_out"),
                "wire_param_bytes_raw": w.get("param_bytes_raw"),
                "wire_keyframes_forced": w.get("keyframes_forced"),
            })
        labeled: dict = {}
        if self.fleet is not None:
            fleet_gauges, labeled = obs_metrics.render_fleet(
                self.fleet.snapshot())
            gauges.update(fleet_gauges)
        histograms = {}
        if self._obs is not None:
            s = self._obs.summary()
            histograms = {
                "frame_age_at_train_seconds": s["frame_age_at_train_s"],
                "param_propagation_lag_seconds":
                    s["param_propagation_lag_s"],
            }
        if self._dispatch_gap is not None:
            snap = self._dispatch_gap.snapshot()
            gauges.update({f"learner_{k}": v for k, v in snap.items()})
        if self._slo is not None:
            # apex_slo_* rows: objective states/burns/compliance, so a
            # stock alertmanager can page off the same machine the
            # autoscaler scales from
            from apex_tpu.obs import slo as obs_slo
            slo_gauges, slo_labeled = obs_slo.prometheus_sections(
                self._slo.snapshot())
            gauges.update(slo_gauges)
            labeled.update(slo_labeled)
        if self.serving_state is not None:
            # apex_serving_* rows: the canary machine + per-shard pin
            # view, scraped from the same surface as the slo rows
            from apex_tpu.serving import deploy as serving_deploy
            srv_gauges, srv_labeled = serving_deploy.prometheus_sections(
                self.serving_state)
            gauges.update(srv_gauges)
            labeled.update(srv_labeled)
        if self.tenancy_state is not None:
            # apex_tenancy_* rows: the placement machine — per-tenant
            # state codes and band sizes next to the serving rows
            from apex_tpu.tenancy import scheduler as tenancy_sched
            tn_gauges, tn_labeled = tenancy_sched.prometheus_sections(
                self.tenancy_state)
            gauges.update(tn_gauges)
            labeled.update(tn_labeled)
        if self.population_state is not None:
            # apex_population_* rows: the PBT machine — per-lineage
            # liveness/generation/score next to the tenancy rows
            from apex_tpu.population import controller as population_ctl
            pp_gauges, pp_labeled = population_ctl.prometheus_sections(
                self.population_state)
            gauges.update(pp_gauges)
            labeled.update(pp_labeled)
        return obs_metrics.render(gauges=gauges, counters=counters,
                                  histograms=histograms, labeled=labeled)

    # -- fleet SLO engine (apex_tpu/obs/slo) -------------------------------

    def _slo_signals(self) -> dict:
        """The signal space one engine sample judges: registry peers +
        metrics, the obs-plane latency histograms, and the learner's
        rate counters — the same sections ``fleet_summary`` publishes,
        so an objective's signal path reads identically off the live
        engine and the persisted JSON."""
        snap = self.fleet.snapshot()
        m = snap["metrics"]
        m["dead_actor_frac"] = round(
            self.fleet.dead_fraction(roles=("actor",)), 4)
        return {
            "peers": snap["peers"], "metrics": m,
            "latency": (self._obs.summary()
                        if self._obs is not None else {}),
            "rates": {"steps_per_s": self.steps_rate.rate,
                      "frames_per_s": self.frames_rate.rate},
            # serving-tier counters ("serving.rollbacks" objective):
            # the dotted walk judges the controller's reported machine
            "serving": self.serving_state or {},
        }

    def _slo_tick(self, steps: int) -> None:
        """One engine sample per health tick (trainer thread ONLY — the
        status thread reads snapshots; sampling per scrape would make
        burn windows a function of scrape traffic).  Transitions print
        like fleet transitions do and land in the scalar log."""
        if self.fleet is None:
            return
        if self._slo is None:
            from apex_tpu.obs.slo import SloEngine, default_slos
            self._slo = SloEngine(default_slos(
                actor_dead_thresh=getattr(self.cfg.comms,
                                          "relax_floor_dead_frac", None)))
        for tr in self._slo.sample(self._slo_signals()):
            print(f"slo: {tr['objective']} {tr['from']} -> {tr['to']} "
                  f"(value={tr['value']})", flush=True)
            self.log.scalars(
                {f"slo_{tr['to'].lower()}_transition": 1.0}, steps)

    def fleet_summary(self) -> dict | None:
        """Registry snapshot + wire counters (the e2e bench ``fleet``
        section, ``--role status``'s JSON sibling), or None before the
        first train() call."""
        if self.fleet is None:
            return None
        snap = self.fleet.snapshot()
        rejected = getattr(self.pool, "wire_rejected", None)
        snap["metrics"]["wire_rejected"] = (rejected()
                                            if callable(rejected) else 0)
        m = snap["metrics"]
        # elastic-fleet surface (PR 8): epoch, reaction state, the
        # backpressure signal scale supervisors key off, re-admissions,
        # and the chaos receiver's withheld-ack count
        m["learner_epoch"] = self.learner_epoch
        # the published model fence (epoch-major, version-minor —
        # serving/fence.py): the serving tier's deployment controller
        # buckets deployable VERSIONS off exactly this pair, so the
        # status surface is the one place "what model is newest" lives
        m["param_version"] = self.param_version
        m["floor_relaxed"] = self._floor_relaxed
        m["floor_relaxes"] = self.floor_relaxes
        m["dead_actor_frac"] = round(
            self.fleet.dead_fraction(roles=("actor",)), 4)
        plane = self.actor_plane()
        m["actor_drain_frac"] = (plane["drain_frac"]
                                 if plane is not None else None)
        admitted = getattr(self.pool, "rejoin_admitted", None)
        m["barrier_admitted"] = (admitted() if callable(admitted) else 0)
        # population plane inputs/evidence (apex_tpu/population): the
        # newest donor-able checkpoint (the PBT controller reads it off
        # this surface to source exploit copies), the live-applied
        # hyperparameter vector, and the applied-ctl record the
        # pbt-smoke drill asserts (exploit count + post-copy epoch)
        m["checkpoint_latest"] = (self.checkpointer.latest_path()
                                  if self.checkpointer is not None
                                  else None)
        if self.hparams_live:
            m["hparams_live"] = dict(self.hparams_live)
        if self._population_ctl is not None:
            m["population_ctl"] = dict(self._population_ctl)
        withheld = getattr(self.pool, "acks_withheld", None)
        m["acks_withheld"] = (withheld() if callable(withheld) else 0)
        wire_fn = getattr(self.pool, "wire_summary", None)
        if callable(wire_fn):
            # wire-codec plane (runtime/codec.py): compressed-chunk
            # decode counts (codec_rejected must be 0 in a healthy
            # fleet — the codec-smoke CI drill asserts it) + the
            # param-delta publisher's byte counters
            m["wire"] = wire_fn()
        ondevice = getattr(self.pool, "ondevice_counters", None)
        if callable(ondevice):
            # on-device rollout plane (training/anakin.py): dispatch/
            # chunk/frame counters — the anakin-smoke CI drill asserts
            # these are nonzero from the persisted summary
            m["ondevice"] = ondevice()
        # SLO signal space + verdicts (apex_tpu/obs/slo): the sections
        # the engine judges ride the summary so an objective's signal
        # path resolves identically against the live engine, the status
        # snapshot, and the persisted JSON a soak/drill asserts on.
        # steps/ingested live HERE (not only in the disk dump) so the
        # soak's status-port samples can difference real progress.
        snap["steps"] = self.steps_rate.total
        snap["ingested"] = self.ingested
        snap["rates"] = {"steps_per_s": self.steps_rate.rate,
                         "frames_per_s": self.frames_rate.rate}
        lat = self.latency_summary()
        if lat is not None:
            snap["latency"] = lat
        if self._slo is not None:
            snap["slo"] = self._slo.snapshot()
        if self.serving_state is not None:
            # the serving tier's deployment machine (canary state,
            # per-shard pins, bounded timeline) — the serve-smoke drill
            # asserts its promotion/rollback edges from this persisted
            # section after the fleet is gone
            snap["serving"] = self.serving_state
        if self.tenancy_state is not None:
            # the tenancy placement machine (admissions, per-tenant
            # bands, eviction timeline) — the tenant-smoke drill asserts
            # both tenants' admissions from this persisted section
            snap["tenancy"] = self.tenancy_state
        if self.population_state is not None:
            # the PBT machine (task ladders, per-lineage score/
            # generation/survival, exploit/explore timeline) — the
            # pbt-smoke drill asserts its events from this persisted
            # section after the fleet is gone
            snap["population"] = self.population_state
        if self.replay_client is not None:
            c = self.replay_client
            snap["metrics"]["replay_service"] = {
                "shards": c.n_shards,
                "batches_pulled": c.batches,
                "service_steps": self.service_steps,
                "ingested_total": c.ingested_total(),
                "prio_sent": c.prio_sent,
                "prio_dropped": c.prio_dropped,
                "rejected": c.rejected,
                "shard_status": c.shard_status(),
            }
        return snap

    def _dump_fleet_summary(self) -> None:
        """Persist the registry view next to the logs.  The on-disk copy
        is the part of the control plane that SURVIVES the learner — the
        chaos rejoin test reads a SIGKILLed learner's last periodic dump
        to prove its registry saw the actor die and rejoin."""
        logdir = getattr(self.log, "logdir", None)
        if logdir is None or self.fleet is None:
            return
        import json
        import os
        summary = self.fleet_summary()
        path = os.path.join(logdir, "fleet_summary.json")
        try:
            os.makedirs(logdir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(summary, fh, indent=2)
            os.replace(tmp, path)      # readers never see a torn write
        except OSError:
            pass                       # observability must not kill a run

    # -- registry reactions (PR 8) -----------------------------------------

    def _min_ratio_effective(self) -> float | None:
        """The replay-ratio floor the loop actually enforces: the
        configured ``min_train_ratio``, or None while the dead-fleet
        reaction has relaxed it."""
        return None if self._floor_relaxed else self.min_train_ratio

    def _react_to_fleet(self, steps: int) -> None:
        """Close the registry loop: when the DEAD fraction of the actor
        fleet reaches the config threshold, relax the replay-ratio floor
        (survivors must not be throttled against a throughput target the
        dead capacity was part of); restore it as peers rejoin.

        The reaction consults the SLO engine's actor-capacity alert
        (which judges the SAME threshold — default_slos wires
        relax_floor_dead_frac into the ``actor_dead_frac`` objective),
        so the two surfaces cannot disagree: while that alert is
        BREACHED the floor stays relaxed even if the instantaneous
        fraction dips under the bar mid-flap — the alert's own
        resolve damping is the hysteresis, the raw threshold keeps the
        reaction instant on a fresh mass death."""
        thresh = getattr(self.cfg.comms, "relax_floor_dead_frac", None)
        if (thresh is None or self.fleet is None
                or self.min_train_ratio is None):
            return
        frac = self.fleet.dead_fraction(roles=("actor",))
        slo_breached = (self._slo is not None
                        and self._slo.state_of("actor_dead_frac")
                        == "BREACHED")
        fire = frac >= thresh or slo_breached
        if not self._floor_relaxed and fire:
            self._floor_relaxed = True
            self.floor_relaxes += 1
            why = (f"{frac:.0%} of actor capacity DEAD" if frac >= thresh
                   else "actor-capacity SLO BREACHED")
            print(f"fleet reaction: {why} — relaxing the replay-ratio "
                  f"floor (min_train_ratio={self.min_train_ratio})",
                  flush=True)
        elif self._floor_relaxed and not fire:
            self._floor_relaxed = False
            print(f"fleet reaction: actor capacity back "
                  f"({frac:.0%} DEAD) — replay-ratio floor restored",
                  flush=True)
        self.log.scalars({"fleet_dead_actor_frac": frac,
                          "fleet_floor_relaxed":
                              float(self._floor_relaxed)}, steps)

    # -- population ctl (apex_tpu/population) ------------------------------

    def _enqueue_ctl(self, cmd: dict) -> dict:
        """Status-server-thread half of the ctl surface: enqueue ONLY
        (the trainer thread applies at its next health tick — learner
        state is single-threaded by contract)."""
        import queue as queue_lib
        q = self._ctl_queue
        if q is None:
            return {"accepted": False, "error": "no ctl queue"}
        try:
            q.put_nowait(dict(cmd))
        except queue_lib.Full:
            return {"accepted": False, "error": "ctl queue full"}
        return {"accepted": True, "pending": q.qsize()}

    def _drain_ctl(self, steps: int) -> None:
        """Trainer-thread half: apply every parked command."""
        import queue as queue_lib
        q = self._ctl_queue
        if q is None:
            return
        while True:
            try:
                cmd = q.get_nowait()
            except queue_lib.Empty:
                return
            self._apply_ctl(cmd, steps)

    def _apply_ctl(self, cmd: dict, steps: int) -> None:
        """One PBT command.  ``exploit`` = the donor-checkpoint weight
        copy (epoch bumped, fleet re-fenced, fresh publish) + the
        explore half's hyperparameter vector; ``hparams`` = the vector
        alone.  A failed copy is counted evidence, never a dead
        learner."""
        op = str(cmd.get("op") or "")
        rec = self._population_ctl or {"applied": 0, "exploits": 0,
                                       "explores": 0, "errors": 0}
        event: dict = {"op": op, "donor": cmd.get("donor"),
                       "step": steps}
        if op == "exploit":
            path = str(cmd.get("restore_from") or "")
            import os as os_lib
            if path and not os_lib.path.exists(path) \
                    and os_lib.path.isdir(os_lib.path.dirname(path)):
                # the donor's Checkpointer prunes to its newest few
                # files, and this command sat in the ctl queue up to
                # one health tick — a pruned path means a NEWER donor
                # checkpoint exists in the same directory; copy that
                # (strictly fresher weights, same lineage)
                from apex_tpu.training.checkpoint import Checkpointer
                newer = Checkpointer(
                    os_lib.path.dirname(path)).latest_path()
                if newer is not None:
                    path = newer
            try:
                self.restore_weights(path)
            except Exception as e:
                rec["errors"] += 1
                event["error"] = f"{type(e).__name__}: {e}"
                rec["last"] = event
                self._population_ctl = rec
                print(f"population: exploit failed ({event['error']})",
                      flush=True)
                return
            rec["exploits"] += 1
            event["restored_from"] = path
            event["learner_epoch"] = self.learner_epoch
            # re-fence the fleet on the new epoch, then publish the
            # copied weights promptly — actors/infer shards fence out
            # the pre-copy life's params, shards its write-backs
            set_epoch = getattr(self.pool, "set_learner_epoch", None)
            if set_epoch is not None:
                set_epoch(self.learner_epoch)
            if self.replay_client is not None:
                self.replay_client.learner_epoch = self.learner_epoch
            applied = self.apply_hparams(cmd.get("hparams") or {})
            if applied or cmd.get("hparams"):
                rec["explores"] += 1
                event["applied"] = applied
            self._publish()
        elif op == "hparams":
            applied = self.apply_hparams(cmd.get("hparams") or {})
            rec["explores"] += 1
            event["applied"] = applied
        else:
            rec["errors"] += 1
            event["error"] = f"unknown op {op!r}"
        rec["applied"] += 1
        rec["last"] = event
        self._population_ctl = rec
        print(f"population: applied {op} "
              f"(donor={cmd.get('donor') or '-'}, "
              f"epoch={self.learner_epoch})", flush=True)
        self.log.scalars({"population_ctl_applied": rec["applied"]},
                         steps)

    def restore_weights(self, path: str) -> dict:
        """The PBT exploit weight copy: impose the donor checkpoint's
        ``train_state`` — params, target, optimizer state — onto THIS
        live learner (PR 8 snapshot machinery,
        :func:`apex_tpu.training.checkpoint.load_raw`), leaving replay
        state, PRNG chain, and progress counters alone, and bump the
        learner epoch so the pre-copy life's params and write-backs are
        fenced out exactly as a restart's would be.  Returns the donor
        checkpoint's metadata."""
        from flax import serialization

        from apex_tpu.training.checkpoint import load_raw
        raw, meta = load_raw(path)
        self.train_state = serialization.from_state_dict(
            self.train_state, raw["train_state"])
        self.learner_epoch += 1
        return meta

    def apply_hparams(self, h: dict) -> dict:
        """Live half of the lineage hyperparameter vector
        (:data:`apex_tpu.population.lineage.LIVE_HPARAMS`): ``lr``
        rebuilds the optimizer chain (same structure, so the running
        ``opt_state`` carries over; one recompile per explore event),
        ``prio_beta`` re-points the IS-weight anneal the very next
        ``_beta()`` call reads.  The acting-side fields (n_steps /
        prio_alpha / eps_base shape chunk assembly, insert exponents,
        and the epsilon ladder) are recorded in ``hparams_live`` and
        apply to the lineage's next worker generation via
        ``population.lineage.apply_lineage``.  Returns the subset
        applied live."""
        import dataclasses as _dc
        applied: dict = {}
        lr = h.get("lr")
        if lr is not None and isinstance(self.core, LearnerCore):
            lc = self.cfg.learner
            optimizer = make_optimizer(
                lr=float(lr), decay=lc.rmsprop_decay, eps=lc.rmsprop_eps,
                centered=lc.rmsprop_centered,
                max_grad_norm=lc.max_grad_norm,
                lr_decay_steps=lc.lr_decay_steps,
                lr_decay_rate=lc.lr_decay_rate)
            self.core = _dc.replace(self.core, optimizer=optimizer)
            if getattr(self, "n_dp", 1) > 1:
                # the sharded plan closed over the old core — rebuild it
                # on the mesh already in hand (one recompile per explore,
                # same contract as the single-shard re-jits below)
                self._make_sharded_fns()
            else:
                self._fused = self.core.jit_fused_step()
                self._train = self.core.jit_train_step()
                self._ingest = self.core.jit_ingest()
                if self._multi is not None:
                    self._multi = self.core.jit_fused_multi_step()
            self._ingest_multi = None       # re-jit lazily off the new core
            if self._train_batch is not None:
                self._train_batch = self._make_batch_train()
            self.cfg = self.cfg.replace(
                learner=_dc.replace(lc, lr=float(lr)))
            applied["lr"] = float(lr)
        beta = h.get("prio_beta")
        if beta is not None:
            self.cfg = self.cfg.replace(
                replay=_dc.replace(self.cfg.replay, beta=float(beta)))
            applied["prio_beta"] = float(beta)
        recorded = {k: v for k, v in h.items() if v is not None}
        if recorded:
            self.hparams_live = {**(self.hparams_live or {}), **recorded}
        return applied

    def _beta(self, ingested: int | None = None) -> float:
        n = self.ingested if ingested is None else ingested
        frac = min(1.0, n / max(1, self.cfg.replay.beta_anneal))
        return self.cfg.replay.beta + (1.0 - self.cfg.replay.beta) * frac

    # -- async ingest pipeline (training/ingest_pipeline.py) ---------------

    def _use_pipeline(self) -> bool:
        """Pipeline staging applies to every concurrent learner,
        single-shard and dp>1 alike: the sharded plan stages whole
        round-robin groups (ChunkAggregator-stacked, per-shard-merged
        when ingest-only) plus pre-split per-chip keys ahead of the
        sharded dispatch.  ``ingest_pipeline=False`` keeps the serial
        drain for A/B."""
        return bool(getattr(self.cfg.learner, "ingest_pipeline", False))

    def _dispatch_key(self):
        """One dispatch's PRNG key, advancing the key chain exactly as
        the serial loop's ``self.key, k = split(self.key)`` does.  While
        a sharded pipelined run is live, the pipeline's KeyPrefetcher
        owns the chain: it hands back keys already split per chip and
        placed over the mesh, plus the chain state the inline split
        would have left in ``self.key`` (so mid-train checkpoints and
        post-train ``self.key`` stay bit-identical to a serial run of
        the same dispatch count)."""
        pipe = self._pipeline
        if pipe is not None and pipe.keys is not None:
            placed, self.key = pipe.keys.take()
            return placed
        self.key, k = jax.random.split(self.key)
        return k

    def _pipeline_state(self):
        """Counter snapshot for the staging thread's grouping decisions.
        ``train_eligible`` is predicted with the pipeline's monotone
        polled-transition total (plus the ingested count the pipeline
        started from): when the chunk under consideration reaches the
        front of the (order-preserving) pipeline, the trainer's
        ``ingested`` will equal exactly that — so the prediction
        reproduces the serial loop's per-chunk warm/budget gating, and a
        merge group never straddles the warmup boundary (bit-parity
        depends on this)."""
        from apex_tpu.training.ingest_pipeline import PipelineState
        cfg = self.cfg
        pipe = self._pipeline
        client = self.replay_client
        effective = self._pipeline_base + (0 if pipe is None
                                           else pipe.polled_total())
        # service mode: the shard fleet's reported ingest counts toward
        # the ratio budget (pulls ARE training), but NOT toward the
        # local-chunk warmup prediction — fallback chunks train against
        # the local pool, which only the local stream fills
        client_tot = client.ingested_total() if client is not None else 0
        consumed = self.steps_rate.total * self.core.batch_size
        floor = self._min_ratio_effective()
        behind = (self.ingested >= cfg.replay.warmup
                  and floor is not None
                  and consumed < (self.ingested + client_tot) * floor)
        # the step counter the chunk will MEET includes the train steps
        # already staged ahead of it — without them every chunk queued
        # behind one pending fused step looks budget-eligible and the
        # ingest-only stream degrades to unmerged singles
        steps_at_front = (self.steps_rate.total
                          + (0 if pipe is None
                             else pipe.staged_train_steps()))
        budget_ok = (self.train_ratio is None
                     or steps_at_front
                     < (effective + client_tot) * self.train_ratio
                     / self.core.batch_size)
        return PipelineState(
            behind=behind,
            train_eligible=effective >= cfg.replay.warmup and budget_ok,
            pull_eligible=budget_ok)

    # -- sharded replay service (apex_tpu/replay_service) ------------------

    def _make_batch_train(self):
        """The service-mode train dispatch: the family's shared update
        body over a shard-sampled batch (the sample half already ran on
        the shard).  Families whose update consumes a PRNG key (AQL
        NoisyNet) receive the shard-split update key with the batch, so
        the one chain never forks.

        dp>1 (PR 17): the service batch splits over the mesh as
        contiguous per-chip blocks, the update ``pmean``s over ``dp``,
        and the per-chip priorities reassemble ``[batch]`` in sample
        order — the shard write-back path is unchanged."""
        import jax as _jax
        core = self.core
        needs_key = getattr(core, "update_needs_key", False)
        sl = getattr(self, "sharded", None)
        if sl is None or getattr(self, "n_dp", 1) == 1:
            if needs_key:
                def train_on_batch(ts, batch, weights, key):
                    return core.update_from_batch(ts, batch, weights, key)
            else:
                def train_on_batch(ts, batch, weights):
                    return core.update_from_batch(ts, batch, weights)
            return _jax.jit(train_on_batch, donate_argnums=(0,))

        from jax.sharding import PartitionSpec as _P

        from apex_tpu.parallel.mesh import shard_map_compat
        sl._per_chip_batch()    # loud divisibility check, names the knobs

        if needs_key:
            def per_chip(ts, batch, weights, kd):
                # one replicated update key, folded per chip so the
                # NoisyNet draws decorrelate (ShardedLearner semantics)
                key = _jax.random.fold_in(
                    _jax.random.wrap_key_data(kd),
                    _jax.lax.axis_index("dp"))
                return core.update_from_batch(ts, batch, weights, key,
                                              axis_name="dp")
            in_specs = (_P(), _P("dp"), _P("dp"), _P())
        else:
            def per_chip(ts, batch, weights):
                return core.update_from_batch(ts, batch, weights,
                                              axis_name="dp")
            in_specs = (_P(), _P("dp"), _P("dp"))
        mapped = shard_map_compat(
            per_chip, mesh=sl.mesh, in_specs=in_specs,
            out_specs=(_P(), _P("dp"), _P()), check_vma=False)
        jitted = _jax.jit(mapped, donate_argnums=(0,))
        if needs_key:
            def train_on_batch(ts, batch, weights, key):
                return jitted(ts, batch, weights,
                              _jax.random.key_data(key))
            return train_on_batch
        return jitted

    def _host_batch_slot(self, item: dict):
        """Serial-path twin of the pipeline's ``_build_batch_slot``:
        host arrays go straight into the dispatch (the jit call ingests
        numpy operands; there is no staging thread to hide an H2D)."""
        from apex_tpu.training.ingest_pipeline import StagedSlot
        spans = obs_spans.spans_of(item)
        obs_spans.stamp_spans(spans, "stage")
        return StagedSlot(
            kind="batch", payload=item["batch"],
            prios=np.asarray(item["weights"], np.float32),
            n_trans=0, planned_steps=1, spans=tuple(spans),
            idx=np.asarray(item["idx"]),
            shard=int(item.get("shard", 0)), seq=int(item["seq"]),
            update_key=item.get("update_key"))

    def _consume_batch_slot(self, slot):
        """Train on one shard-sampled batch and route the priority
        write-back to its owning shard (via the staging thread when the
        pipeline is live — the device_get must not land on the hot
        loop)."""
        gap = self._dispatch_gap
        gap.about_to_dispatch()
        if slot.update_key is not None:
            k = jax.random.wrap_key_data(jnp.asarray(slot.update_key))
            self.train_state, prios, metrics = self._train_batch(
                self.train_state, slot.payload, slot.prios, k)
        else:
            self.train_state, prios, metrics = self._train_batch(
                self.train_state, slot.payload, slot.prios)
        gap.dispatch_returned()
        self.steps_rate.tick()
        self.service_steps += 1
        if self._pipeline is not None:
            self._pipeline.write_back(slot.shard, slot.seq, slot.idx,
                                      prios)
        else:
            self.replay_client.push_priorities(
                slot.shard, slot.seq, slot.idx,
                np.asarray(jax.device_get(prios), np.float32))
        return metrics

    def _consume_slot(self, slot, warm: bool, budget: float,
                      target_steps: int):
        """Dispatch one staged slot; returns metrics or None.  Mirrors
        the serial drain's gating chunk for chunk: train-eligible singles
        run the fused step, eligible scan stacks run the K-step scan
        dispatch, everything else is absorbed ingest-only (the
        replay-ratio cap is re-checked at consume time, so a stale
        staging prediction can only under-train, never over-train)."""
        gap = self._dispatch_gap
        obs = self._obs
        if obs is not None and slot.spans:
            obs.pre_consume(slot.spans)     # "consume": dispatch issued
        metrics = None
        if slot.kind == "batch":
            # shard-sampled: always trained (a staged batch skipped here
            # would leave its strict shard wedged on the write-back it
            # will never get; the budget re-check already gated the PULL,
            # so overshoot is bounded by the staged depth)
            metrics = self._consume_batch_slot(slot)
            if obs is not None and slot.spans:
                obs.post_consume(slot.spans)
            return metrics
        if slot.kind == "scan":
            j = slot.chunks
            trainable = (warm and self._multi is not None
                         and self.steps_rate.total + j - 1 < budget
                         and target_steps - self.steps_rate.total >= j)
            if trainable:
                offsets = np.concatenate(
                    [[0], np.cumsum(slot.n_per)[:-1]])
                betas = np.asarray(
                    [self._beta(self.ingested + int(o)) for o in offsets],
                    np.float32)
                # scan slots exist only on the single-shard plan, so the
                # key is a raw chain key here — never prefetcher output
                k = self._dispatch_key()
                gap.about_to_dispatch()
                self.train_state, self.replay_state, mm = \
                    self._multi(self.train_state, self.replay_state,
                                slot.payload, slot.prios,
                                jax.random.split(k, j), betas)
                gap.dispatch_returned()
                metrics = jax.tree.map(lambda x: x.mean(0), mm)
                self.steps_rate.tick(j)
                self.scan_dispatches += 1
            else:
                if self._ingest_multi is None:
                    from apex_tpu.training.learner import make_multi_ingest
                    self._ingest_multi = make_multi_ingest(self.core)
                gap.about_to_dispatch()
                self.replay_state = self._ingest_multi(
                    self.replay_state, slot.payload, slot.prios)
                gap.dispatch_returned()
        elif slot.kind == "single" and warm \
                and self.steps_rate.total < budget:
            k = self._dispatch_key()
            gap.about_to_dispatch()
            self.train_state, self.replay_state, metrics = \
                self._fused(self.train_state, self.replay_state,
                            slot.payload, slot.prios, k,
                            jnp.float32(self._beta()))
            gap.dispatch_returned()
            self.steps_rate.tick()
        else:
            # merged ingest payloads, and singles the cap says to absorb
            gap.about_to_dispatch()
            self.replay_state = self._ingest(self.replay_state,
                                             slot.payload, slot.prios)
            gap.dispatch_returned()
        if obs is not None and slot.spans:
            obs.post_consume(slot.spans)    # "prio_wb" + the two joins
        self.ingested += slot.n_trans
        self.frames_rate.tick(slot.n_trans)
        return metrics

    def _drain_serial(self, msgs: list, want: int, warm: bool,
                      budget: float):
        """The serial (pipeline-off) drain of one poll's messages.
        Returns metrics or None."""
        gap = self._dispatch_gap
        obs = self._obs
        if obs is not None:
            for m in msgs:
                obs_spans.stamp(m, "recv")  # no staging thread: poll=recv
        metrics = None
        if want > 1 and len(msgs) > 1:
            # scan batch: j chunks -> one device dispatch, quantized to a
            # power of two so shortfalls (j < K) compile O(log K) scan
            # programs instead of degrading to j separate dispatches;
            # the remainder falls through to the per-chunk path IN ORDER.
            # Betas are the per-step stack the single-dispatch path would
            # have produced (step i sees ingestion through chunk i-1), so
            # the annealing schedule is dispatch-shape-invariant.
            from apex_tpu.training.ingest_pipeline import _pow2_floor
            j = _pow2_floor(len(msgs))
            take, msgs = msgs[:j], msgs[j:]
            payload, prios, n_new = stack_chunk_messages(take)
            spans = obs_spans.merge_spans(take) if obs is not None else ()
            n_per = np.asarray([int(m["n_trans"]) for m in take])
            offsets = np.concatenate([[0], np.cumsum(n_per)[:-1]])
            betas = np.asarray(
                [self._beta(self.ingested + int(o))
                 for o in offsets], np.float32)
            k = self._dispatch_key()
            if spans:
                obs.pre_consume(spans)
            gap.about_to_dispatch()
            self.train_state, self.replay_state, mm = \
                self._multi(self.train_state, self.replay_state,
                            payload, prios, jax.random.split(k, j), betas)
            gap.dispatch_returned()
            if spans:
                obs.post_consume(spans)
            # scalar observability coarsens to per-dispatch under scan:
            # report the mean over the j stacked steps
            metrics = jax.tree.map(lambda x: x.mean(0), mm)
            self.steps_rate.tick(j)
            self.scan_dispatches += 1
            self.ingested += n_new
            self.frames_rate.tick(n_new)
        for msg in msgs:
            # single-chunk path (and scan spillover, one by one)
            prios = jnp.asarray(msg["priorities"])
            n_new = int(msg["n_trans"])
            payload = msg["payload"]
            spans = obs_spans.spans_of(msg) if obs is not None else ()
            if spans:
                obs.pre_consume(spans)
            # The replay-ratio cap applies on the chunk path too: an
            # over-budget learner ingests WITHOUT the fused train half,
            # so the documented ``train_ratio`` really is the ceiling
            # (ingesting raises the budget for later steps).
            if warm and self.steps_rate.total < budget:
                k = self._dispatch_key()
                gap.about_to_dispatch()
                self.train_state, self.replay_state, metrics = \
                    self._fused(self.train_state, self.replay_state,
                                payload, prios, k,
                                jnp.float32(self._beta()))
                gap.dispatch_returned()
                self.steps_rate.tick()
            else:
                gap.about_to_dispatch()
                self.replay_state = self._ingest(
                    self.replay_state, payload, prios)
                gap.dispatch_returned()
            if spans:
                obs.post_consume(spans)
            self.ingested += n_new
            self.frames_rate.tick(n_new)
        return metrics

    # -- checkpointing (A4): format/IO in CheckpointableTrainer ------------
    # (restore note: the actor fleet re-syncs from the first post-restore
    # publish — actors are stateless consumers)

    def _counters(self) -> dict:
        return dict(ingested=self.ingested, steps=self.steps_rate.total,
                    param_version=self.param_version,
                    learner_epoch=self.learner_epoch)

    def _apply_counters(self, meta: dict) -> None:
        self.ingested = meta["ingested"]
        self.steps_rate.total = meta["steps"]
        self.param_version = meta["param_version"]
        # epoch fencing: restoring from a checkpoint IS a new learner
        # life — bump past the saved epoch so parked actors and replay
        # shards see the restart (pre-fencing checkpoints restore as
        # epoch 2: their writer was life 1 by definition)
        self.learner_epoch = int(meta.get("learner_epoch", 1)) + 1
        # a restored trainer does not owe an immediate save/log: its marks
        # continue from the restored step count
        self._last_save = self._last_log = meta["steps"]


class ApexTrainer(ConcurrentTrainer):
    """train_DQN-equivalent driver (``ApeX.py:13-82``), frame-pool edition."""

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 publish_min_seconds: float = 0.2,
                 train_ratio: float | None = None,
                 min_train_ratio: float | None = None,
                 checkpoint_dir: str | None = None,
                 pool=None, respawn_workers: bool = True):
        """Replay-ratio control (samples consumed per transition ingested):

        ``train_ratio`` caps the ratio — the learner idles when it has
        consumed too much per ingested transition (prevents overfitting a
        slow actor fleet).  ``min_train_ratio`` FLOORS it — when the learner
        falls behind, chunk draining pauses so the bounded queue
        backpressures the actors (workers block on put), throttling
        collection to what the learner can digest.  Without the floor, a
        fast fleet can flood the buffer with data from a still-bad policy
        faster than the learner improves it — the failure mode does not
        exist in the reference only because its single-GPU learner was never
        outpaced this way.  ``None`` = fully decoupled (reference behavior).
        """
        self.cfg = cfg = config or ApexConfig()
        self.key = set_global_seeds(cfg.env.seed)
        self.publish_min_seconds = publish_min_seconds
        self.train_ratio = train_ratio
        self.min_train_ratio = min_train_ratio
        self.respawn_workers = respawn_workers
        if (train_ratio is not None and min_train_ratio is not None
                and min_train_ratio > train_ratio):
            raise ValueError("min_train_ratio must be <= train_ratio")

        self.model_spec, frame_shape, frame_dtype, frame_stack = \
            dqn_env_specs(cfg)

        self.model = DuelingDQN(**self.model_spec)
        self.replay = FramePoolReplay(
            capacity=cfg.replay.capacity, frame_shape=frame_shape,
            frame_stack=frame_stack, frame_dtype=np.dtype(frame_dtype).name,
            alpha=cfg.replay.alpha, eps=cfg.replay.eps)
        check_hbm_budget(self.replay.hbm_bytes(), cfg.replay.hbm_budget_gb,
                         "frame-pool replay", cfg.replay.capacity)
        lc = cfg.learner
        optimizer = make_optimizer(
            lr=lc.lr, decay=lc.rmsprop_decay, eps=lc.rmsprop_eps,
            centered=lc.rmsprop_centered, max_grad_norm=lc.max_grad_norm,
            lr_decay_steps=lc.lr_decay_steps, lr_decay_rate=lc.lr_decay_rate)
        stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
        self.key, init_key = jax.random.split(self.key)
        self.train_state = create_train_state(
            self.model, optimizer, init_key,
            jnp.zeros((1,) + stacked, frame_dtype))
        self.core = LearnerCore(
            apply_fn=self.model.apply, replay=self.replay, optimizer=optimizer,
            batch_size=lc.batch_size,
            target_update_interval=lc.target_update_interval)
        self._policy = jax.jit(make_policy_fn(self.model))

        # pool injection: the multi-host learner passes a socket-backed
        # RemotePool; default is the in-host process pool
        if pool is not None:
            self.pool = pool
        else:
            from apex_tpu.native.ring import chunk_slot_bytes
            from apex_tpu.replay.frame_chunks import FRAME_MARGIN
            slot = chunk_slot_bytes(
                frame_dim=int(np.prod(frame_shape)),
                frame_dtype_size=np.dtype(frame_dtype).itemsize,
                kf=cfg.actor.send_interval + FRAME_MARGIN,
                k=cfg.actor.send_interval, stack=frame_stack)
            self.pool = ActorPool(cfg, self.model_spec,
                                  chunk_transitions=cfg.actor.send_interval,
                                  shm_slot_bytes=slot)

        self.n_dp = int(np.prod(lc.mesh_shape))
        self.replay_state = self.replay.init()
        if self.n_dp > 1:
            self._init_sharded()
        else:
            self._fused = self.core.jit_fused_step()
            self._train = self.core.jit_train_step()
            self._ingest = self.core.jit_ingest()
            if lc.scan_steps > 1:
                self.scan_steps = lc.scan_steps
                self._multi = self.core.jit_fused_multi_step()

        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.steps_rate = RateCounter()
        self.frames_rate = RateCounter()
        self.ingested = 0
        self.param_version = 0
        self.checkpointer = (Checkpointer(checkpoint_dir)
                             if checkpoint_dir else None)

    # _init_sharded: ConcurrentTrainer (shared with the AQL family)

    # -- evaluation --------------------------------------------------------

    def evaluate(self, episodes: int = 10, epsilon: float = 0.0,
                 max_steps: int = 10_000) -> float:
        """True-score eval on the unclipped, full-episode env
        (``eval.py:49-87``)."""
        if not hasattr(self, "_eval_env"):
            self._eval_env = make_eval_env(self.cfg.env.env_id, self.cfg.env,
                                           seed=self.cfg.env.seed + 999)
        rewards = []
        for ep in range(episodes):
            obs, _ = self._eval_env.reset(seed=self.cfg.env.seed + 1000 + ep)
            total, done, steps = 0.0, False, 0
            while not done and steps < max_steps:
                self.key, k = jax.random.split(self.key)
                a, _ = self._policy(self.train_state.params,
                                    np.asarray(obs)[None],
                                    jnp.float32(epsilon), k)
                obs, r, term, trunc, _ = self._eval_env.step(int(a[0]))
                total += float(r)
                done = term or trunc
                steps += 1
            rewards.append(total)
        return float(np.mean(rewards))
