"""Ape-X driver: actor pool + fused TPU learner, actually concurrent.

Capability parity with the reference ``ApeX.py`` (C11) and its
``origin_repo`` flagship topology, on one host:

* N worker processes explore continuously with the epsilon ladder and ship
  fixed-shape frame chunks with precomputed priorities
  (:mod:`apex_tpu.actors.pool`).
* The learner ingests chunks into the HBM frame-pool replay and runs the
  fused sample/loss/update/priority step — ingest+train fuse into one XLA
  program whenever a chunk is pending.
* Params publish version-stamped every ``publish_interval`` learner steps
  with a wall-clock floor (``publish_min_seconds``) — the reference's
  every-25-steps cadence (``learner.py:169-170``) assumed an 11-steps/s
  learner; at TPU step rates a pure step cadence would saturate the host
  queues.
* Warmup gate: no training until ``replay.warmup`` transitions are resident
  (``arguments.py:47-48``, ``replay.py:104-106``).

The reference's ``ApeX.py`` accidentally ran acting and learning
sequentially (``Process(target=test.sampling_data())`` calls the method
eagerly — ``ApeX.py:94-97``); here they genuinely overlap: workers are
independent processes, and the learner thread blocks only on device results.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.actors.pool import ActorPool
from apex_tpu.config import ApexConfig
from apex_tpu.envs.registry import (make_env, make_eval_env, num_actions,
                                    unstacked_env_spec)
from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.ops.losses import make_optimizer
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.training.learner import LearnerCore
from apex_tpu.training.state import create_train_state
from apex_tpu.utils.metrics import MetricLogger, RateCounter
from apex_tpu.utils.seeding import set_global_seeds


class ApexTrainer:
    """train_DQN-equivalent driver (``ApeX.py:13-82``), frame-pool edition."""

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 publish_min_seconds: float = 0.2,
                 train_ratio: float | None = None,
                 min_train_ratio: float | None = None):
        """Replay-ratio control (samples consumed per transition ingested):

        ``train_ratio`` caps the ratio — the learner idles when it has
        consumed too much per ingested transition (prevents overfitting a
        slow actor fleet).  ``min_train_ratio`` FLOORS it — when the learner
        falls behind, chunk draining pauses so the bounded queue
        backpressures the actors (workers block on put), throttling
        collection to what the learner can digest.  Without the floor, a
        fast fleet can flood the buffer with data from a still-bad policy
        faster than the learner improves it — the failure mode does not
        exist in the reference only because its single-GPU learner was never
        outpaced this way.  ``None`` = fully decoupled (reference behavior).
        """
        self.cfg = cfg = config or ApexConfig()
        self.key = set_global_seeds(cfg.env.seed)
        self.publish_min_seconds = publish_min_seconds
        self.train_ratio = train_ratio
        self.min_train_ratio = min_train_ratio
        if (train_ratio is not None and min_train_ratio is not None
                and min_train_ratio > train_ratio):
            raise ValueError("min_train_ratio must be <= train_ratio")

        probe = make_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed,
                         stack_frames=False)
        frame_shape, frame_dtype, frame_stack = unstacked_env_spec(
            probe, cfg.env)
        self.model_spec = dict(
            num_actions=num_actions(probe),
            obs_is_image=len(frame_shape) == 3,
            compute_dtype=jnp.dtype(cfg.learner.compute_dtype),
            scale_uint8=np.dtype(frame_dtype) == np.uint8)
        probe.close()

        self.model = DuelingDQN(**self.model_spec)
        self.replay = FramePoolReplay(
            capacity=cfg.replay.capacity, frame_shape=frame_shape,
            frame_stack=frame_stack, frame_dtype=np.dtype(frame_dtype).name,
            alpha=cfg.replay.alpha, eps=cfg.replay.eps)
        lc = cfg.learner
        optimizer = make_optimizer(
            lr=lc.lr, decay=lc.rmsprop_decay, eps=lc.rmsprop_eps,
            centered=lc.rmsprop_centered, max_grad_norm=lc.max_grad_norm)
        stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
        self.key, init_key = jax.random.split(self.key)
        self.train_state = create_train_state(
            self.model, optimizer, init_key,
            jnp.zeros((1,) + stacked, frame_dtype))
        self.core = LearnerCore(
            apply_fn=self.model.apply, replay=self.replay, optimizer=optimizer,
            batch_size=lc.batch_size,
            target_update_interval=lc.target_update_interval)
        self.replay_state = self.replay.init()
        self._fused = self.core.jit_fused_step()
        self._train = self.core.jit_train_step()
        self._ingest = self.core.jit_ingest()
        self._policy = jax.jit(make_policy_fn(self.model))

        self.pool = ActorPool(cfg, self.model_spec,
                              chunk_transitions=cfg.actor.send_interval)
        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.steps_rate = RateCounter()
        self.frames_rate = RateCounter()
        self.ingested = 0
        self.param_version = 0

    # -- param plane -------------------------------------------------------

    def _publish(self) -> None:
        self.param_version += 1
        host_params = jax.device_get(self.train_state.params)
        self.pool.publish_params(self.param_version, host_params)

    # -- main loop ---------------------------------------------------------

    def train(self, total_steps: int, max_seconds: float = 3600.0,
              log_every: int = 200):
        """Run until ``total_steps`` learner updates (or the wall clock)."""
        cfg = self.cfg
        pool = self.pool
        pool.start()
        try:
            self._publish()
            last_publish = time.monotonic()
            t_end = last_publish + max_seconds
            episode_idx = 0

            while self.steps_rate.total < total_steps:
                now = time.monotonic()
                if now > t_end:
                    break
                warm = self.ingested >= cfg.replay.warmup
                consumed = self.steps_rate.total * self.core.batch_size
                budget = (float("inf") if self.train_ratio is None
                          else self.ingested * self.train_ratio
                          / self.core.batch_size)
                # Replay-ratio floor: learner behind -> pause draining so the
                # bounded chunk queue backpressures the actor fleet.
                behind = (warm and self.min_train_ratio is not None
                          and consumed < self.ingested * self.min_train_ratio)

                chunk = None
                if not behind:
                    chunks = pool.poll_chunks(1, timeout=0 if warm else 0.05)
                    if chunks:
                        chunk = chunks[0]

                if chunk is not None:
                    prios = jnp.asarray(chunk.pop("priorities"))
                    n_new = int(chunk["n_trans"])
                    if warm:
                        self.key, k = jax.random.split(self.key)
                        self.train_state, self.replay_state, metrics = \
                            self._fused(self.train_state, self.replay_state,
                                        chunk, prios, k,
                                        jnp.float32(self._beta()))
                        self.steps_rate.tick()
                    else:
                        self.replay_state = self._ingest(
                            self.replay_state, chunk, prios)
                    self.ingested += n_new
                    self.frames_rate.tick(n_new)
                elif warm and self.steps_rate.total < budget:
                    self.key, k = jax.random.split(self.key)
                    self.train_state, self.replay_state, metrics = \
                        self._train(self.train_state, self.replay_state, k,
                                    jnp.float32(self._beta()))
                    self.steps_rate.tick()
                elif warm:
                    time.sleep(0.002)   # replay-ratio cap reached

                steps = self.steps_rate.total
                if steps and (steps % cfg.learner.publish_interval == 0
                              or now - last_publish
                              > 10 * self.publish_min_seconds) \
                        and now - last_publish >= self.publish_min_seconds:
                    self._publish()
                    last_publish = now

                for stat in pool.poll_stats():
                    self.log.scalars(
                        {"episode_reward": stat.reward,
                         "episode_length": stat.length,
                         "actor_id": stat.actor_id}, episode_idx)
                    episode_idx += 1

                if warm and steps and steps % log_every == 0:
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate,
                           "param_version": self.param_version,
                           "ingested": self.ingested}, steps)
        finally:
            pool.cleanup()
        return self

    def _beta(self) -> float:
        frac = min(1.0, self.ingested / max(1, 10 * self.cfg.replay.warmup))
        return self.cfg.replay.beta + (1.0 - self.cfg.replay.beta) * frac

    # -- evaluation --------------------------------------------------------

    def evaluate(self, episodes: int = 10, epsilon: float = 0.0,
                 max_steps: int = 10_000) -> float:
        """True-score eval on the unclipped, full-episode env
        (``eval.py:49-87``)."""
        if not hasattr(self, "_eval_env"):
            self._eval_env = make_eval_env(self.cfg.env.env_id, self.cfg.env,
                                           seed=self.cfg.env.seed + 999)
        rewards = []
        for ep in range(episodes):
            obs, _ = self._eval_env.reset(seed=self.cfg.env.seed + 1000 + ep)
            total, done, steps = 0.0, False, 0
            while not done and steps < max_steps:
                self.key, k = jax.random.split(self.key)
                a, _ = self._policy(self.train_state.params,
                                    np.asarray(obs)[None],
                                    jnp.float32(epsilon), k)
                obs, r, term, trunc, _ = self._eval_env.step(int(a[0]))
                total += float(r)
                done = term or trunc
                steps += 1
            rewards.append(total)
        return float(np.mean(rewards))
