"""Single-process DQN driver — the minimum end-to-end slice.

Capability parity with ``DQN.py`` (reference C10): inline act -> step -> add ->
sample -> loss -> update loop with exponential epsilon decay (``DQN.py:41``),
linear beta anneal (``DQN.py:40``), periodic target sync (``DQN.py:108-110``),
checkpointing, and an evaluation mode replaying a checkpoint
(``DQN.py:124-149``).

This driver defines the numerical contract every distributed variant must
match (SURVEY.md §3.3).  TPU shape: the env + epsilon-greedy actor run on the
host; transitions accumulate through the n-step window and are ingested into
the HBM replay in fixed-size chunks (fixed shapes = no retrace); the learner
update is the fused XLA step from :mod:`apex_tpu.training.learner`.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.config import ApexConfig
from apex_tpu.envs.registry import make_env, make_eval_env, num_actions
from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.replay.nstep import NStepAccumulator
from apex_tpu.training import learner as learner_lib
from apex_tpu.training.checkpoint import (CheckpointableTrainer,
                                          Checkpointer)
from apex_tpu.utils.metrics import MetricLogger, RateCounter
from apex_tpu.utils.seeding import set_global_seeds


@dataclass
class EpsilonSchedule:
    """eps_final + (eps_start - eps_final) * exp(-frame / decay)  (DQN.py:41)."""

    start: float = 1.0
    final: float = 0.01
    decay: float = 30_000.0

    def __call__(self, frame: int) -> float:
        return self.final + (self.start - self.final) * math.exp(
            -frame / self.decay)


@dataclass
class BetaSchedule:
    """Linear anneal of the IS exponent toward 1 (DQN.py:40)."""

    start: float = 0.4
    frames: int = 100_000

    def __call__(self, frame: int) -> float:
        return min(1.0, self.start + (1.0 - self.start) * frame / self.frames)


class DQNTrainer(CheckpointableTrainer):
    """train_DQN equivalent (``DQN.py:15-75``)."""

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 train_every: int = 1, checkpoint_dir: str | None = None):
        self.cfg = config or ApexConfig()
        self.key = set_global_seeds(self.cfg.env.seed)
        self.env = make_env(self.cfg.env.env_id, self.cfg.env,
                            seed=self.cfg.env.seed,
                            max_episode_steps=self.cfg.actor.max_episode_length)
        obs_shape = self.env.observation_space.shape
        self.model_spec = dict(
            num_actions=num_actions(self.env),
            obs_is_image=len(obs_shape) == 3,
            compute_dtype=jnp.dtype(self.cfg.learner.compute_dtype),
            scale_uint8=self.env.observation_space.dtype == np.uint8)
        self.model = DuelingDQN(**self.model_spec)

        lc = self.cfg.learner
        example_obs = jnp.zeros((1,) + obs_shape,
                                self.env.observation_space.dtype)
        self.key, init_key = jax.random.split(self.key)
        self.core, self.train_state, self.replay_state = \
            learner_lib.build_learner(
                self.model, self.cfg.replay.capacity, example_obs, init_key,
                alpha=self.cfg.replay.alpha, batch_size=lc.batch_size,
                lr=lc.lr, max_grad_norm=lc.max_grad_norm,
                lr_decay_steps=lc.lr_decay_steps,
                lr_decay_rate=lc.lr_decay_rate,
                rmsprop_decay=lc.rmsprop_decay, rmsprop_eps=lc.rmsprop_eps,
                rmsprop_centered=lc.rmsprop_centered,
                replay_eps=self.cfg.replay.eps,
                target_update_interval=lc.target_update_interval,
                hbm_budget_gb=self.cfg.replay.hbm_budget_gb)
        self._train_step = self.core.jit_train_step()
        self._ingest = self.core.jit_ingest()
        self._policy = jax.jit(make_policy_fn(self.model))

        self.accumulator = NStepAccumulator(lc.n_steps, lc.gamma)
        self.ingest_chunk = lc.ingest_chunk
        self.train_every = train_every
        self.epsilon = EpsilonSchedule()
        self.beta = BetaSchedule(start=self.cfg.replay.beta)
        self.log = MetricLogger("learner", logdir, verbose=verbose)
        self.frames_rate = RateCounter()
        self.steps_rate = RateCounter()
        self.ingested = 0
        self._pending: list[tuple[dict, np.ndarray]] = []
        self._pending_count = 0
        self.checkpointer = (Checkpointer(checkpoint_dir)
                             if checkpoint_dir else None)

    # -- checkpointing (A4): format/IO in CheckpointableTrainer ------------

    def _counters(self) -> dict:
        return dict(ingested=self.ingested, frames=self.frames_rate.total,
                    steps=self.steps_rate.total)

    def _apply_counters(self, meta: dict) -> None:
        self.ingested = meta["ingested"]
        self.frames_rate.total = meta["frames"]
        self.steps_rate.total = meta["steps"]

    # -- data plane --------------------------------------------------------

    def _flush_accumulator(self) -> None:
        if len(self.accumulator) == 0:
            return
        batch, prios = self.accumulator.make_batch()
        self._pending.append((batch, prios))
        self._pending_count += len(prios)
        while self._pending_count >= self.ingest_chunk:
            self._ingest_chunk()

    def _ingest_chunk(self) -> None:
        """Ingest exactly ``ingest_chunk`` transitions (fixed shape, no retrace)."""
        merged = {k: np.concatenate([b[k] for b, _ in self._pending])
                  for k in self._pending[0][0]}
        prios = np.concatenate([p for _, p in self._pending])
        take = self.ingest_chunk
        chunk = {k: v[:take] for k, v in merged.items()}
        rest = {k: v[take:] for k, v in merged.items()}
        self.replay_state = self._ingest(self.replay_state, chunk,
                                         jnp.asarray(prios[:take]))
        self.ingested += take
        self._pending = ([(rest, prios[take:])]
                         if len(prios) > take else [])
        self._pending_count = len(prios) - take

    # -- main loop ---------------------------------------------------------

    def train(self, total_frames: int, log_every: int = 1000):
        """Run ``total_frames`` MORE env frames.  On a restored trainer the
        frame counter (and with it the epsilon/beta schedules) continues
        from the checkpoint instead of rewinding to frame 1."""
        cfg = self.cfg
        obs, _ = self.env.reset(seed=cfg.env.seed)
        episode_reward, episode_len, episode_idx = 0.0, 0, 0
        start = self.frames_rate.total

        for frame in range(start + 1, start + total_frames + 1):
            eps = self.epsilon(frame)
            self.key, act_key = jax.random.split(self.key)
            obs_np = np.asarray(obs)
            actions, q = self._policy(self.train_state.params,
                                      obs_np[None], jnp.float32(eps), act_key)
            action = int(actions[0])

            next_obs, reward, terminated, truncated, _ = self.env.step(action)
            done = terminated or truncated
            # q materializes at its use site, after the env step (J008)
            q_np = np.asarray(q[0])
            self.accumulator.add(obs_np, action, float(reward), q_np,
                                 terminated=bool(terminated),
                                 truncated=bool(truncated),
                                 final_obs=(np.asarray(next_obs)
                                            if truncated else None))
            obs = next_obs
            episode_reward += float(reward)
            episode_len += 1
            self.frames_rate.tick()

            if done:
                self._flush_accumulator()
                obs, _ = self.env.reset()
                self.log.scalars({"episode_reward": episode_reward,
                                  "episode_length": episode_len}, episode_idx)
                episode_reward, episode_len = 0.0, episode_len * 0
                episode_idx += 1
            elif len(self.accumulator) >= cfg.actor.send_interval:
                self._flush_accumulator()

            warm = self.ingested >= cfg.replay.warmup
            if warm and frame % self.train_every == 0:
                self.key, step_key = jax.random.split(self.key)
                self.train_state, self.replay_state, metrics = \
                    self._train_step(self.train_state, self.replay_state,
                                     step_key, jnp.float32(self.beta(frame)))
                self.steps_rate.tick()
                if (self.checkpointer is not None and self.steps_rate.total
                        % cfg.learner.save_interval == 0):
                    self.save_checkpoint()
                # host-side counter for the log gate: reading
                # train_state.step would sync the async device step
                if self.steps_rate.total % log_every == 0:
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate},
                        self.steps_rate.total)
        return self

    # -- evaluation (DQN.py:124-149 equivalent) ----------------------------

    def evaluate(self, episodes: int = 10, epsilon: float = 0.0,
                 max_steps: int = 10_000) -> float:
        """True-score evaluation on a dedicated unclipped/full-episode env
        (reference: eval.py:52 evaluates on the unclipped env)."""
        from apex_tpu.training.checkpoint import run_policy_episodes

        if not hasattr(self, "_eval_env"):
            self._eval_env = make_eval_env(self.cfg.env.env_id, self.cfg.env,
                                           seed=self.cfg.env.seed + 999)
        self.key, eval_key = jax.random.split(self.key)
        rewards = run_policy_episodes(
            self._eval_env,
            lambda obs, eps, k: int(self._policy(
                self.train_state.params, obs, eps, k)[0][0]),
            eval_key, episodes, epsilon, max_steps,
            seed_base=self.cfg.env.seed + 1000)
        return float(np.mean(rewards))
