"""Device-mesh utilities.

The reference has NO gradient data-parallelism — its learner is a single GPU
(SURVEY.md §2 parallelism table; no torch.distributed anywhere in the tree).
Scaling the learner across a TPU slice is therefore a new capability, designed
the XLA way: one ``jax.sharding.Mesh``, shardings annotated per-array, and
collectives (``psum``/``pmean``) riding ICI inside the compiled step — the
role NCCL would have played in a scaled-out reference learner.

Axes: ``dp`` (data/replay parallel) is the only sized axis for these model
scales; ``tp`` exists in the API so tensor-parallel sharding rules can be
added without re-plumbing (kept size 1, see SURVEY.md §2).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int | None = None, tp: int = 1,
              devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    dp = dp if dp is not None else len(devices) // tp
    if dp * tp > len(devices):
        raise ValueError(f"mesh {dp}x{tp} needs {dp*tp} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:dp * tp]).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs,
                     check_vma: bool = True):
    """``jax.shard_map`` across JAX versions.

    The top-level ``jax.shard_map`` (whose replication-check knob is
    named ``check_vma``) landed after the 0.4.x line this image ships;
    there the same transform lives at ``jax.experimental.shard_map``
    with the knob named ``check_rep``.  Every shard_map in the tree goes
    through this wrapper so the sharded plan runs on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh, axis: int = 0) -> NamedSharding:
    spec = [None] * (axis + 1)
    spec[axis] = "dp"
    return NamedSharding(mesh, P(*spec))
