"""Device-mesh utilities.

The reference has NO gradient data-parallelism — its learner is a single GPU
(SURVEY.md §2 parallelism table; no torch.distributed anywhere in the tree).
Scaling the learner across a TPU slice is therefore a new capability, designed
the XLA way: one ``jax.sharding.Mesh``, shardings annotated per-array, and
collectives (``psum``/``pmean``) riding ICI inside the compiled step — the
role NCCL would have played in a scaled-out reference learner.

Axes: ``dp`` (data/replay parallel) is the only sized axis for these model
scales; ``tp`` exists in the API so tensor-parallel sharding rules can be
added without re-plumbing (kept size 1, see SURVEY.md §2).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int | None = None, tp: int = 1,
              devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    dp = dp if dp is not None else len(devices) // tp
    if dp * tp > len(devices):
        raise ValueError(f"mesh {dp}x{tp} needs {dp*tp} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:dp * tp]).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh, axis: int = 0) -> NamedSharding:
    spec = [None] * (axis + 1)
    spec[axis] = "dp"
    return NamedSharding(mesh, P(*spec))
