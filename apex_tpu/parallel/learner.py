"""Multi-chip learner: sharded replay + psum-grad training in one program.

Design (BASELINE.json north star; SURVEY.md §7 step 5):

* The replay buffer is SHARDED across the ``dp`` axis — every chip owns an
  independent ring + sum/min trees in its own HBM.  Ingest chunks are split
  across chips; each chip samples ``batch/dp`` locally (its own stratified
  descent, no cross-chip tree walk); gradients are ``pmean``-ed over ICI;
  priority write-back is local.  This dissolves the reference's central
  replay-server bottleneck (``origin_repo/README.md:11``) instead of
  re-implementing it: there is no global lock because there is no global
  tree.
* Params/optimizer state are replicated; identical pmean'd updates keep them
  bit-identical per chip (standard DP invariant).
* Everything — ingest, sample, loss, all-reduce, update, priority write —
  is ONE ``shard_map``-ped, jitted program with donated buffers.

Sampling semantics note: each shard samples ``batch/dp`` from its OWN tree,
so a transition's true inclusion probability is ``leaf / (dp *
shard_total)`` — under heavy priority skew (one shard holding more mass
than the others) that deviates from the reference's global stratification;
round-robin chunk ingest spreads bursts evenly but cannot equalize
heavy-tailed leaf values.  The IS weights therefore correct for the sampler
ACTUALLY USED: local total/size (whose product equals the true effective
global probability times the global size) with one ``pmax``-collectived
max-weight normalizer so every shard scales identically — an unbiased
estimator regardless of how mass concentrates, reducing bit-for-bit to the
single-buffer formula when shards are balanced.  ``tests/test_parallel.py``
pins both properties under a x1000 priority burst.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.parallel.mesh import shard_map_compat
from apex_tpu.replay.device import ReplayState
from apex_tpu.training.learner import LearnerCore
from apex_tpu.training.state import TrainState


def _stack_leading(tree_obj: Any, n: int) -> Any:
    """Tile a pytree with a new leading device axis of size n."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (n,) + x.shape), tree_obj)


@dataclass(frozen=True)
class ShardedLearner:
    """Wraps a learner core with a dp-sharded execution plan.

    Works for any core with the :class:`LearnerCore` method shape
    (``replay``/``batch_size``/``update_from_batch``); cores whose update
    consumes a PRNG key (AQL's NoisyNet draws) set ``update_needs_key =
    True`` and the per-chip body splits its key between sampling and the
    update, mirroring ``AQLCore.train_step``."""

    core: LearnerCore
    mesh: Mesh

    @property
    def n_dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def _needs_key(self) -> bool:
        return getattr(self.core, "update_needs_key", False)

    def _update(self, ts, batch, weights, key):
        if self._needs_key:
            return self.core.update_from_batch(ts, batch, weights, key,
                                               axis_name="dp")
        return self.core.update_from_batch(ts, batch, weights,
                                           axis_name="dp")

    # -- state construction ------------------------------------------------

    def init_replay(self, example_item: Any) -> ReplayState:
        """Per-chip replay shards, stacked on a sharded leading axis.

        Total capacity = ``core.replay.capacity * n_dp`` — capacity scales
        with the slice, which is exactly how HBM grows.
        """
        return self.shard_replay_state(self.core.replay.init(example_item))

    def shard_replay_state(self, shard: ReplayState) -> ReplayState:
        """Tile a freshly-initialized single-shard state onto the sharded
        leading axis (drivers that already built their replay state pass
        it here instead of re-deriving an example item)."""
        stacked = _stack_leading(shard, self.n_dp)
        sharding = NamedSharding(self.mesh, P("dp"))
        return jax.tree.map(
            lambda x: jax.device_put(x, sharding), stacked)

    def replicate_train_state(self, ts: TrainState) -> TrainState:
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(self.mesh, P())), ts)

    # -- the sharded fused step --------------------------------------------

    def _per_chip_batch(self) -> int:
        """batch/dp, validated loudly (a ``ValueError`` survives
        ``python -O`` where an assert would vanish into a silent
        shape mismatch inside the shard_map trace)."""
        per_chip, rem = divmod(self.core.batch_size, self.n_dp)
        if rem:
            raise ValueError(
                f"learner.batch_size={self.core.batch_size} must be "
                f"divisible by the dp axis (dp={self.n_dp}, from "
                f"learner.mesh_shape) — raise batch_size or shrink "
                f"the mesh")
        return per_chip

    def make_fused_step(self):
        core = self.core
        per_chip_batch = self._per_chip_batch()

        def per_chip(ts: TrainState, rs: ReplayState, ingest: Any,
                     prios: jax.Array, key: jax.Array, beta: jax.Array):
            # leading shard axis of size 1 inside shard_map -> strip it
            rs = jax.tree.map(lambda x: x[0], rs)
            ingest = jax.tree.map(lambda x: x[0], ingest)
            prios = prios[0]
            key = jax.random.wrap_key_data(key[0])

            if self._needs_key:
                key, k_update = jax.random.split(key)
            else:
                k_update = None
            rs = core.replay.add(rs, ingest, prios)
            batch, weights, idx = core.replay.sample(
                rs, key, per_chip_batch, beta, axis_name="dp")
            new_ts, priorities, metrics = self._update(
                ts, batch, weights, k_update)
            rs = core.replay.update_priorities(rs, idx, priorities)
            rs = jax.tree.map(lambda x: x[None], rs)    # restore shard axis
            return new_ts, rs, metrics

        shard = P("dp")
        repl = P()
        mapped = shard_map_compat(
            per_chip, mesh=self.mesh,
            in_specs=(repl, shard, shard, shard, shard, repl),
            out_specs=(repl, shard, repl),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0, 1))

    def make_train_step(self):
        """Sample/update only (no ingest) — the learner's catch-up step when
        no chunk is pending."""
        core = self.core
        per_chip_batch = self._per_chip_batch()

        def per_chip(ts: TrainState, rs: ReplayState, key: jax.Array,
                     beta: jax.Array):
            rs = jax.tree.map(lambda x: x[0], rs)
            key = jax.random.wrap_key_data(key[0])
            if self._needs_key:
                key, k_update = jax.random.split(key)
            else:
                k_update = None
            batch, weights, idx = core.replay.sample(
                rs, key, per_chip_batch, beta, axis_name="dp")
            new_ts, priorities, metrics = self._update(
                ts, batch, weights, k_update)
            rs = core.replay.update_priorities(rs, idx, priorities)
            rs = jax.tree.map(lambda x: x[None], rs)
            return new_ts, rs, metrics

        mapped = shard_map_compat(
            per_chip, mesh=self.mesh,
            in_specs=(P(), P("dp"), P("dp"), P()),
            out_specs=(P(), P("dp"), P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0, 1))

    def make_ingest(self):
        """Ingest only (pre-warmup): one chunk per chip, no training."""
        core = self.core

        def per_chip(rs: ReplayState, ingest: Any, prios: jax.Array):
            rs = jax.tree.map(lambda x: x[0], rs)
            ingest = jax.tree.map(lambda x: x[0], ingest)
            rs = core.replay.add(rs, ingest, prios[0])
            return jax.tree.map(lambda x: x[None], rs)

        mapped = shard_map_compat(
            per_chip, mesh=self.mesh,
            in_specs=(P("dp"), P("dp"), P("dp")),
            out_specs=P("dp"),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0,))

    # -- host-side helpers -------------------------------------------------

    def split_ingest(self, batch: dict[str, jax.Array], prios: jax.Array):
        """Reshape a host chunk (K, ...) -> (dp, K/dp, ...) for sharded ingest.

        Round-robin interleave: consecutive transitions land on different
        chips, keeping shard statistics identical in distribution.
        """
        n = self.n_dp

        def split(x):
            k = x.shape[0]
            if k % n != 0:
                raise ValueError(
                    f"ingest chunk of {k} transitions must be divisible "
                    f"by the dp axis (dp={n}, from learner.mesh_shape) — "
                    f"align actor.send_interval / learner.ingest_chunk "
                    f"with the mesh")
            return x.reshape(k // n, n, *x.shape[1:]).swapaxes(0, 1)

        return ({k: split(v) for k, v in batch.items()}, split(prios))

    def shard_put(self, tree_obj: Any) -> Any:
        """Place a host tree whose leading axis is the dp shard axis into
        device memory, one shard slice per chip (NamedSharding over dp).
        The ingest pipeline's staging thread uses this so the sharded
        dispatch finds its operands already resident (H2D overlaps the
        previous step's compute)."""
        sharding = NamedSharding(self.mesh, P("dp"))
        return jax.tree.map(lambda x: jax.device_put(x, sharding), tree_obj)

    def device_keys(self, key: jax.Array) -> jax.Array:
        """One PRNG key per chip as raw key data (uint32), sharded over dp.

        Raw data rather than typed keys so the leading axis shards cleanly;
        the per-chip body re-wraps with ``wrap_key_data``.
        """
        keys = jax.random.key_data(jax.random.split(key, self.n_dp))
        return jax.device_put(keys, NamedSharding(self.mesh, P("dp")))
