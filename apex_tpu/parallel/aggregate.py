"""Chunk aggregation for the dp-sharded learner.

Frame chunks are SELF-CONTAINED (internal frame refs), so a single chunk
cannot be split across replay shards; instead whole chunks round-robin onto
chips: the aggregator buffers worker messages until it holds one per chip,
then stacks them on a leading ``dp`` axis for the sharded fused step.  This
preserves the interleaved-stream assumption behind local per-shard sampling
(:mod:`apex_tpu.parallel.learner` docstring) — consecutive chunks, which
come from different actors, land on different chips.
"""

from __future__ import annotations

import jax
import numpy as np


def stack_chunk_messages(msgs: list) -> tuple:
    """Stack K chunk messages on a new leading axis, HOST-side.

    ``(payload, priorities, total_n_trans)`` — np.stack so the stacked
    trees cross to the device in ONE transfer at the jitted call
    boundary (per-item device ops would add exactly the dispatch
    overhead the consumers exist to amortize).  Payloads may nest (frame
    chunks carry an "extras" dict of per-transition sidecars).  Used by
    the dp aggregator (leading axis = chips) and the scan dispatch
    (leading axis = scan steps)."""
    payload = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *[m["payload"] for m in msgs])
    prios = np.stack([np.asarray(m["priorities"]) for m in msgs])
    return payload, prios, sum(int(m["n_trans"]) for m in msgs)


class ChunkAggregator:
    """Pool wrapper: groups ``n_dp`` chunk messages into one stacked
    sharded message; every other pool method delegates, so the shared
    concurrent loop drives it unchanged."""

    def __init__(self, pool, n_dp: int):
        self.pool = pool
        self.n_dp = n_dp
        self._buf: list[dict] = []

    # -- delegation ---------------------------------------------------------

    def start(self) -> None:
        self.pool.start()

    def cleanup(self) -> None:
        self.pool.cleanup()

    def publish_params(self, version: int, params) -> None:
        self.pool.publish_params(version, params)

    def poll_stats(self):
        return self.pool.poll_stats()

    @property
    def procs(self):
        return self.pool.procs

    @property
    def needs_warmup_republish(self):
        return getattr(self.pool, "needs_warmup_republish", False)

    # failure detection passes through so sharded runs keep respawn-on-death
    # (the trainer feature-detects via hasattr)

    def __getattr__(self, name):
        if name in ("dead_workers", "respawn_worker", "worker_deaths",
                    "silent_peers", "peer_seen", "wire_rejected",
                    "set_learner_epoch", "rejoin_admitted",
                    "acks_withheld"):
            return getattr(self.pool, name)
        raise AttributeError(name)

    # -- aggregation --------------------------------------------------------

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        """Return one stacked message per ``n_dp`` buffered chunks."""
        out = []
        for _ in range(max_chunks):
            need = self.n_dp - len(self._buf)
            if need > 0:
                self._buf.extend(self.pool.poll_chunks(need, timeout))
            if len(self._buf) < self.n_dp:
                break
            msgs, self._buf = self._buf[:self.n_dp], self._buf[self.n_dp:]
            payload, prios, n_trans = stack_chunk_messages(msgs)
            group = {"payload": payload, "priorities": prios,
                     "n_trans": n_trans}
            # lineage spans ride message metadata through the stacking
            # (one span per source chunk, "merge" hop = group assembly)
            from apex_tpu.obs import spans as obs_spans
            spans = obs_spans.merge_spans(msgs)
            if spans:
                group[obs_spans.SPAN_KEY] = spans
            out.append(group)
        return out
