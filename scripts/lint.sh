#!/usr/bin/env bash
# Canonical lint entry point (mirrors scripts/test.sh).
#
# PALLAS_AXON_POOL_IPS must be cleared BEFORE the interpreter starts: the
# image's sitecustomize dials the single-client axon TPU relay at python
# startup, and a lint run would block forever if any other process holds
# the chip.  apexlint itself is pure stdlib (it never imports JAX), so the
# env discipline is about interpreter startup, not the analyzer.
#
# Usage: scripts/lint.sh [paths...] [--strict] [--json] [--write-baseline]
# No args = the [tool.apexlint] scope from pyproject.toml, strict mode
# (new findings AND stale baseline entries fail).
#
# Fast path: `scripts/lint.sh --changed-only` lints just the git-diff
# file set (worktree + index vs HEAD, plus untracked), strict, while the
# whole-program context still spans the full tree — the pre-commit loop.
set -euo pipefail
cd "$(dirname "$0")/.."
if [ "$#" -eq 0 ]; then
    set -- --strict
elif [ "$#" -eq 1 ] && [ "$1" = "--changed-only" ]; then
    set -- --strict --changed-only
fi
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m apex_tpu.analysis "$@"
