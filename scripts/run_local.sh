#!/usr/bin/env bash
# Localhost all-roles topology (reference origin_repo/run.sh:1-5: tmux panes
# for replay/learner/actor/eval on 127.0.0.1).  By default replay is
# dissolved into the learner, so the topology is learner + N actors +
# evaluator.  Export APEX_REPLAY_SHARDS=N (N > 0) to restore the
# reference's standalone replay role as N shard processes
# (apex_tpu/replay_service): actors hash chunks to shards, the learner
# pulls pre-sampled batches round-robin and ships priority write-backs.
#
# Usage: scripts/run_local.sh [ENV_ID] [N_ACTORS] [TOTAL_STEPS] [ENVS_PER_ACTOR]
set -euo pipefail
cd "$(dirname "$0")/.."

ENV_ID="${1:-ApexCartPole-v0}"
N_ACTORS="${2:-2}"
TOTAL_STEPS="${3:-2000}"
ENVS_PER_ACTOR="${4:-1}"

# CPU platform for every role: actors/evaluator must never dial the
# single-client TPU tunnel; drop the env vars on the learner line to put its
# fused step on the chip.
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu

# Deterministic fault injection (apex_tpu/fleet/chaos.py): export
# CHAOS_SEED (+ optional CHAOS_SPEC JSON) before launching and every role
# inherits the same seeded fault schedule — kills at message N, chunk
# drops/delays, publish stalls — replayable run after run.  Example:
#   CHAOS_SEED=7 CHAOS_SPEC='{"kill":{"actor-0":200},"drop_frac":0.05}' \
#     scripts/run_local.sh
export CHAOS_SEED="${CHAOS_SEED:-}" CHAOS_SPEC="${CHAOS_SPEC:-}"

# Multi-tenancy (apex_tpu/tenancy): export APEX_TENANT=<name> and every
# role this script launches runs namespaced — qualified wire identities,
# tenant-prefixed chunk ids, topic-tagged param publishes — so N
# invocations of this script (one per tenant, distinct APEX_BATCH_PORT/
# APEX_PARAM_PORT/APEX_BARRIER_PORT/APEX_STATUS_PORT blocks) share ONE
# externally-launched replay/infer plane.  APEX_LAUNCH_SHARED=0 skips
# launching the shard/infer/controller processes here (the shared plane
# already runs elsewhere, carrying the APEX_TENANTS roster);
# APEX_TENANT_CTL=1 adds the tenancy placement controller
# (--role tenant-ctl) next to the shared planes.
export APEX_TENANT="${APEX_TENANT:-}" APEX_TENANTS="${APEX_TENANTS:-}"
LAUNCH_SHARED="${APEX_LAUNCH_SHARED:-1}"

# Population plane (apex_tpu/population): export APEX_POPULATION (JSON
# lineage roster — each lineage IS a tenant) and run one invocation of
# this script per lineage (APEX_TENANT=<lineage>, its own port block;
# the lineage's env id + hyperparameter vector apply from the roster).
# APEX_PBT_CTL=1 adds the PBT controller (--role pbt-ctl) next to the
# shared planes: it probes each lineage's status port, and bottom-of-
# ladder lineages restore the top's checkpoint with a mutated vector.
export APEX_POPULATION="${APEX_POPULATION:-}"

# Observability (apex_tpu/obs): every role dumps a per-process trace ring
# (chunk lineage spans, phase/gap events) into APEX_TRACE_DIR — dumped on
# exit AND flushed periodically, so the actors killed by the EXIT trap
# still leave near-complete traces.  The learner's fleet_summary.json
# lands in the same dir, giving obs.merge the heartbeat-derived clock
# offsets for the single merged perfetto timeline.
TRACE_DIR="${APEX_TRACE_DIR:-/tmp/apex-obs-$$}"
export APEX_TRACE_DIR="$TRACE_DIR"
mkdir -p "$TRACE_DIR"

# Sharded replay service (apex_tpu/replay_service): the flag set below
# must agree fleet-wide, so it rides COMMON like the ports do.  0 =
# in-learner replay (the default topology).
REPLAY_SHARDS="${APEX_REPLAY_SHARDS:-0}"
export APEX_REPLAY_SHARDS="$REPLAY_SHARDS"

# On-device Anakin rollouts (apex_tpu/training/anakin): export
# APEX_ROLLOUT=ondevice and the learner co-locates a fused
# env+policy+chunk-assembly scan with the fused trainer — params never
# leave the device, sealed chunks enter the normal replay path, and the
# topology can run with ZERO host actors (N_ACTORS=0; the evaluator
# still rides the param stream).  APEX_ROLLOUT=fused goes all the way
# (apex_tpu/ondevice): rollout + ingest + prioritized sample + train +
# priority write-back run as ONE jitted program per dispatch, the host
# waking once per APEX_STEPS_PER_DISPATCH macro steps (requires
# APEX_REPLAY_SHARDS=0 — the fused loop owns replay on-device).
# Jittable envs only (ApexCatch*/ApexRally* — the CLI fails loud
# otherwise).
#
# Data-parallel mesh (PR 17): export APEX_MESH_DP=N (the --mesh-dp env
# twin — the CLI reads it, nothing to wire here) and the learner shards
# over N chips in EVERY rollout mode, fused included: env lanes split
# into per-chip blocks, each chip owns a replay pool partition, and
# gradients pmean across the mesh.  Divisibility is checked loud at
# startup (batch-size % N, ENVS_PER_ACTOR x actors % N).  On a CPU box,
# emulate the mesh with
#   XLA_FLAGS=--xla_force_host_platform_device_count=N
export APEX_ROLLOUT="${APEX_ROLLOUT:-host}"

# Centralized inference plane (apex_tpu/infer_service): export
# APEX_REMOTE_POLICY=1 to launch a `--role infer` policy server and make
# the actors ship half-group observations to it (one batched device
# dispatch across actor processes) instead of running the policy on
# their own CPU.  Every role reads the env twin, so the flag agrees
# fleet-wide for free; a killed server never stalls actors — they fall
# back to local policies within APEX_INFER_WAIT and re-probe.
REMOTE_POLICY="${APEX_REMOTE_POLICY:-0}"
export APEX_REMOTE_POLICY="$REMOTE_POLICY"

# Wire codec (apex_tpu/runtime/codec.py): APEX_WIRE_CODEC=raw|delta|dict
# picks the chunk wire codec for every role this script launches (raw =
# bit-identical legacy pickles; delta = frame XOR + RLE for ~sparse
# frames; dict = per-chunk byte dictionary for pixel stacks).
# Negotiation is per-chunk — mixed fleets interoperate, and
# APEX_WIRE_CODEC_MIXED=1 pins actor 0 to the raw codec to exercise
# exactly that (the CI codec-smoke lane's mixed-version rehearsal).
# APEX_PARAM_DELTA=1 turns on sparse param-delta publish (per-leaf diff
# vs the last keyframe + tree checksum; APEX_PARAM_KEYFRAME_EVERY sets
# the dense-keyframe cadence, default 16).
export APEX_WIRE_CODEC="${APEX_WIRE_CODEC:-}"
export APEX_PARAM_DELTA="${APEX_PARAM_DELTA:-}"
export APEX_PARAM_KEYFRAME_EVERY="${APEX_PARAM_KEYFRAME_EVERY:-}"
WIRE_CODEC_MIXED="${APEX_WIRE_CODEC_MIXED:-0}"

COMMON=(--env-id "$ENV_ID" --n-actors "$N_ACTORS"
        --n-envs-per-actor "$ENVS_PER_ACTOR"
        --batch-size 64 --capacity 8192 --warmup 500
        --barrier-timeout 600)

pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; }
trap cleanup EXIT

if [ "$REPLAY_SHARDS" -gt 0 ] && [ "$LAUNCH_SHARED" = "1" ]; then
  # shard s binds replay_port_base + s; shards skip the startup barrier
  # (useful the moment the ROUTER binds), so launch them first and the
  # actor fleet's first sealed chunks route straight to them.
  #
  # Durability (PR 8): APEX_REPLAY_SNAPSHOT_DIR (+ _S cadence) makes each
  # shard snapshot its whole replay state and restore it on respawn;
  # APEX_SUPERVISE_REPLAY=1 wraps each shard in the host supervisor so a
  # chaos-killed shard respawns automatically and rejoins WARM from its
  # snapshot (the chaos kill disarms on the supervised life).
  export APEX_REPLAY_SNAPSHOT_DIR="${APEX_REPLAY_SNAPSHOT_DIR:-}"
  export APEX_REPLAY_SNAPSHOT_S="${APEX_REPLAY_SNAPSHOT_S:-}"
  for s in $(seq 0 $((REPLAY_SHARDS - 1))); do
    if [ "${APEX_SUPERVISE_REPLAY:-0}" = "1" ]; then
      python -m apex_tpu.fleet.supervise --min-uptime 1 \
        --backoff 0.5 --backoff-max 2 -- \
        python -m apex_tpu.runtime --role replay --shard-id "$s" \
        "${COMMON[@]}" &
    else
      python -m apex_tpu.runtime --role replay --shard-id "$s" \
        "${COMMON[@]}" &
    fi
    pids+=($!)
  done
fi

if [ "$REMOTE_POLICY" = "1" ] && [ "$LAUNCH_SHARED" = "1" ]; then
  # Sharded serving tier (apex_tpu/serving): APEX_INFER_SHARDS=N runs N
  # infer servers, shard s binding infer_port + s; remote-policy workers
  # hash to a home shard by identity.  The servers skip the startup
  # barrier (useful the moment their ROUTERs bind); launch before the
  # actors so their first vector steps already batch centrally instead
  # of burning one fallback wait each.  APEX_SUPERVISE_INFER=1 wraps
  # each shard in the host supervisor so a chaos-killed server respawns
  # in seconds (the kill disarms on the supervised life) and the SLO
  # engine's round-trip alert can walk the full BREACHED -> RESOLVED
  # cycle — the slo-smoke drill's topology.
  INFER_SHARDS="${APEX_INFER_SHARDS:-1}"
  export APEX_INFER_SHARDS="$INFER_SHARDS"
  for s in $(seq 0 $((INFER_SHARDS - 1))); do
    if [ "${APEX_SUPERVISE_INFER:-0}" = "1" ]; then
      python -m apex_tpu.fleet.supervise --min-uptime 1 \
        --backoff 0.5 --backoff-max 2 -- \
        python -m apex_tpu.runtime --role infer --infer-shard-id "$s" \
        "${COMMON[@]}" &
    else
      python -m apex_tpu.runtime --role infer --infer-shard-id "$s" \
        "${COMMON[@]}" &
    fi
    pids+=($!)
  done
  # Canary deployment controller (apex_tpu/serving/deploy, --role
  # serve-ctl): APEX_SERVE_CTL=1 launches it against the shard tier —
  # new model versions canary onto APEX_SERVE_CANARY_FRAC of the
  # shards, promote after APEX_SERVE_SOAK_S of healthy SLO, roll back
  # by epoch on breach; the deployment timeline lands in the learner's
  # fleet_summary.json and apex_serving_* Prometheus rows.
  if [ "${APEX_SERVE_CTL:-0}" = "1" ]; then
    python -m apex_tpu.runtime --role serve-ctl "${COMMON[@]}" &
    pids+=($!)
  fi
fi

# Tenancy placement controller (apex_tpu/tenancy/scheduler, --role
# tenant-ctl): admits the APEX_TENANTS roster, assigns weighted replay/
# infer shard bands, probes each tenant's learner status port, evicts
# and rebalances on death; the admission timeline lands in the host
# learner's fleet_summary.json ("tenancy") and apex_tenancy_* rows.
if [ "${APEX_TENANT_CTL:-0}" = "1" ] && [ "$LAUNCH_SHARED" = "1" ]; then
  python -m apex_tpu.runtime --role tenant-ctl "${COMMON[@]}" &
  pids+=($!)
fi

# PBT controller (apex_tpu/population/controller, --role pbt-ctl):
# truncation-selection exploit (donor checkpoint copy + learner-epoch
# bump through the lineage learners' ctl surfaces) and perturb/resample
# explore over the APEX_POPULATION roster; the population timeline
# lands in the host learner's fleet_summary.json ("population") and
# apex_population_* rows.
if [ "${APEX_PBT_CTL:-0}" = "1" ] && [ "$LAUNCH_SHARED" = "1" ]; then
  python -m apex_tpu.runtime --role pbt-ctl "${COMMON[@]}" &
  pids+=($!)
fi

# SLO soak traffic (apex_tpu/obs/soak.py): APEX_LOADGEN=N spawns N
# standalone on-device loadgen roles (jittable envs only — the CLI fails
# loud otherwise) that saturate the chunk plane at device rate.  They
# skip the startup barrier like replay/infer roles, so they are NOT
# counted in --n-actors.
LOADGEN="${APEX_LOADGEN:-0}"
for g in $(seq 0 $((LOADGEN - 1))); do   # LOADGEN=0: no loadgen roles
  python -m apex_tpu.runtime --role loadgen --actor-id "$g" \
    "${COMMON[@]}" &
  pids+=($!)
done

for i in $(seq 0 $((N_ACTORS - 1))); do   # N_ACTORS=0: no host actors
  if [ "$WIRE_CODEC_MIXED" = "1" ] && [ "$i" = "0" ]; then
    # mixed-version fleet rehearsal: actor 0 stays on the legacy raw
    # codec while the rest follow APEX_WIRE_CODEC — per-chunk
    # negotiation means the learner ingests both streams untouched
    APEX_WIRE_CODEC=raw python -m apex_tpu.runtime --role actor \
      --actor-id "$i" "${COMMON[@]}" &
  else
    python -m apex_tpu.runtime --role actor --actor-id "$i" \
      "${COMMON[@]}" &
  fi
  pids+=($!)
done
python -m apex_tpu.runtime --role evaluator --episodes 0 --verbose \
  "${COMMON[@]}" &
pids+=($!)

# learner runs in the foreground; barrier holds until every peer dials in
python -m apex_tpu.runtime --role learner --total-steps "$TOTAL_STEPS" \
  --verbose --logdir "$TRACE_DIR" "${COMMON[@]}"

# one perfetto-loadable fleet timeline (clock-aligned via the heartbeat
# offsets in fleet_summary.json); load it at https://ui.perfetto.dev
sleep 1   # let the periodic flushers land their last dumps
python -m apex_tpu.obs.merge "$TRACE_DIR" \
  -o "$TRACE_DIR/merged_trace.json" || true
