#!/usr/bin/env bash
# Canonical test entry point.
#
# PALLAS_AXON_POOL_IPS must be cleared BEFORE the interpreter starts: the
# image's sitecustomize dials the single-client axon TPU relay at python
# startup, and a test run would block forever if any other process holds the
# chip (conftest.py runs too late to prevent the dial).  Tests always run on
# the 8-device virtual CPU mesh (tests/conftest.py).
set -euo pipefail
cd "$(dirname "$0")/.."
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m pytest tests/ "$@"
