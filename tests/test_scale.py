"""North-star scale-shape rehearsal (BASELINE.md: 256 actors vs an 8-chip
learner; reference fleet: origin_repo/terraform.tfvars:4-5, 192 actors).

CI cannot run 256 processes against 8 real chips, but it CAN rehearse the
SHAPE: 256 env slots (8 vector worker processes x 32 envs, the full
epsilon-ladder spectrum) feeding the dp=8 sharded learner on the virtual
CPU mesh — exercising the aggregated round-robin ingest, the publish
fan-out at fleet size, bounded-queue backpressure, and clean shutdown at
a topology one order above the other tests."""

import dataclasses
import time

import jax
import numpy as np
import pytest

from apex_tpu.config import small_test_config
from apex_tpu.training.apex import ApexTrainer


@pytest.mark.slow
def test_north_star_topology_256_slots_dp8():
    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=8)
    cfg = cfg.replace(
        learner=dataclasses.replace(cfg.learner, mesh_shape=(8,),
                                    ingest_chunk=32,
                                    compute_dtype="float32"),
        actor=dataclasses.replace(cfg.actor, n_envs_per_actor=32,
                                  send_interval=32))
    t = ApexTrainer(cfg, publish_min_seconds=0.05)
    assert t.n_dp == 8
    ladder = 256
    assert cfg.actor.n_actors * cfg.actor.n_envs_per_actor == ladder

    # publish fan-out cost at fleet size, measured on the live queue set
    # (pre-start: the broadcast cost is the serialization + enqueue to all
    # 8 worker param queues, identical machinery mid-run)
    t1 = time.monotonic()
    t._publish()
    publish_s = time.monotonic() - t1
    assert publish_s < 5.0, f"publish fan-out took {publish_s:.2f}s"

    t0 = time.monotonic()
    t.train(total_steps=30, max_seconds=900)
    elapsed = time.monotonic() - t0

    # learner progressed through the sharded plane, every shard ingested
    # (round-robin chunk aggregation stayed balanced at fleet scale)
    assert t.steps_rate.total >= 30
    sizes = np.asarray(t.replay_state.size)
    assert sizes.shape == (8,) and (sizes > 0).all(), sizes
    spread = sizes.max() / max(1, sizes.min())
    assert spread <= 4, f"shard imbalance {sizes}"

    # the wide ladder actually acted: episode stats arrived from slots
    # across the whole 256-slot range (not just the first worker's)
    slots = {int(v) for _, v in t.log.history.get("learner/actor_id", [])}
    assert len(slots) >= 32, f"only {len(slots)} slots reported episodes"
    assert max(slots) >= ladder * 3 // 4, \
        f"high ladder rungs silent (max slot {max(slots)})"

    # no worker died mid-run; the bounded chunk plane backpressured
    # instead of growing (queue depth is a hard bound by construction —
    # fleet-scale liveness is what this asserts)
    assert t.pool.worker_deaths == 0

    drain_rate = t.ingested / max(elapsed, 1e-9)
    print(f"[scale] 256 slots / dp8: ingested={t.ingested} "
          f"({drain_rate:.0f} trans/s), steps={t.steps_rate.total}, "
          f"publish_fanout={publish_s * 1000:.0f}ms, "
          f"shard sizes={sizes.tolist()}, slots_reporting={len(slots)}, "
          f"wall={elapsed:.0f}s")

    assert all(not p.is_alive() for p in t.pool.procs)   # clean shutdown
    assert np.isfinite(t.evaluate(episodes=1, max_steps=200))
