"""NStepAccumulator vs. a brute-force trajectory oracle."""

import numpy as np
import pytest

from apex_tpu.replay.nstep import NStepAccumulator


def _run_episode(acc, rewards, gamma, n, end="terminated"):
    """Feed a synthetic episode; obs at step t is t, q_values are fixed."""
    T = len(rewards)
    for t in range(T):
        q = np.asarray([0.5, 1.5], np.float32)  # max=1.5, action 0 -> q=0.5
        last = t == T - 1
        acc.add(obs=np.float32(t), action=0, reward=rewards[t],
                q_values=q, terminated=(last and end == "terminated"),
                truncated=(last and end == "truncated"),
                final_obs=np.float32(T) if (last and end == "truncated")
                else None)


def test_nstep_returns_match_bruteforce():
    n, gamma = 3, 0.9
    rewards = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
    acc = NStepAccumulator(n, gamma)
    _run_episode(acc, rewards, gamma, n)
    batch, prios = acc.make_batch()

    assert len(batch["obs"]) == 6  # every step emitted
    # bootstrapped transitions: t=0,1,2 (episode len 6, window n=3)
    for t in range(3):
        want = sum(gamma ** i * rewards[t + i] for i in range(n))
        np.testing.assert_allclose(batch["reward"][t], want, rtol=1e-6)
        np.testing.assert_allclose(batch["discount"][t], gamma ** n, rtol=1e-6)
        assert batch["obs"][t] == t and batch["next_obs"][t] == t + n
    # terminal flush: t=3,4,5 get truncated sums and discount=0
    for t in range(3, 6):
        want = sum(gamma ** i * rewards[t + i] for i in range(6 - t))
        np.testing.assert_allclose(batch["reward"][t], want, rtol=1e-6)
        assert batch["discount"][t] == 0.0


def test_truncation_bootstraps_from_final_obs():
    """A time-limit cut is not a terminal: the tail must keep a gamma**k
    bootstrap from the final observation instead of discount=0."""
    n, gamma = 3, 0.9
    rewards = [1.0, 2.0, 3.0, 4.0, 5.0]
    acc = NStepAccumulator(n, gamma)
    _run_episode(acc, rewards, gamma, n, end="truncated")
    batch, prios = acc.make_batch()

    assert len(batch["obs"]) == 5
    # t=0,1: full windows
    for t in range(2):
        np.testing.assert_allclose(batch["discount"][t], gamma ** n, rtol=1e-6)
    # tail t=2,3,4: k = 3,2,1 remaining rewards, bootstrap from final_obs=5
    for t, k in [(2, 3), (3, 2), (4, 1)]:
        want_ret = sum(gamma ** i * rewards[t + i] for i in range(k))
        np.testing.assert_allclose(batch["reward"][t], want_ret, rtol=1e-6)
        np.testing.assert_allclose(batch["discount"][t], gamma ** k, rtol=1e-6)
        assert batch["next_obs"][t] == 5.0
    # priorities use the bootstrap: target = R + gamma**k * max_q(=1.5)
    want_p = abs(rewards[4] + gamma * 1.5 - 0.5) + 1e-6
    np.testing.assert_allclose(prios[4], want_p, rtol=1e-5)


def test_truncated_requires_final_obs():
    acc = NStepAccumulator(2, 0.99)
    with pytest.raises(ValueError):
        acc.add(np.float32(0), 0, 1.0, np.zeros(2, np.float32),
                terminated=False, truncated=True)


def test_priorities_match_manual_td():
    n, gamma = 2, 0.99
    acc = NStepAccumulator(n, gamma)
    _run_episode(acc, [1.0, 1.0, 1.0], gamma, n)
    batch, prios = acc.make_batch()
    # t=0: bootstrap: R=1+0.99, target = R + 0.99^2*1.5, q_taken=0.5
    want0 = abs((1 + 0.99) + 0.99 ** 2 * 1.5 - 0.5) + 1e-6
    np.testing.assert_allclose(prios[0], want0, rtol=1e-5)
    # terminal ones: target = R only
    want_last = abs(1.0 - 0.5) + 1e-6
    np.testing.assert_allclose(prios[-1], want_last, rtol=1e-5)
    assert (prios > 0).all()


def test_multi_episode_no_window_leak():
    acc = NStepAccumulator(3, 0.99)
    _run_episode(acc, [1.0, 1.0], 0.99, 3)   # short episode, all terminal
    _run_episode(acc, [5.0] * 5, 0.99, 3)
    batch, _ = acc.make_batch()
    assert len(batch["obs"]) == 7
    # first episode transitions must not see episode-2 rewards
    np.testing.assert_allclose(batch["reward"][0], 1.0 + 0.99 * 1.0, rtol=1e-6)
    assert batch["discount"][0] == 0.0 and batch["discount"][1] == 0.0


def test_uint8_image_obs_roundtrip():
    acc = NStepAccumulator(2, 0.99)
    frames = [np.full((8, 8, 1), t, np.uint8) for t in range(4)]
    for t in range(4):
        acc.add(frames[t], action=1, reward=1.0,
                q_values=np.asarray([0.0, 1.0], np.float32),
                terminated=(t == 3))
    batch, _ = acc.make_batch()
    assert batch["obs"].dtype == np.uint8
    assert batch["obs"].shape == (4, 8, 8, 1)
    np.testing.assert_array_equal(batch["next_obs"][0], frames[2])
