"""Fused learner step: mechanics (target sync, priority write-back, donation)
and a small end-to-end learning test on the numpy CartPole env."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.config import small_test_config
from apex_tpu.models.dueling import DuelingDQN
from apex_tpu.training.learner import build_learner
from apex_tpu.training.dqn import DQNTrainer


def _setup(key, batch_size=16, capacity=256, target_interval=5):
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                      compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    core, ts, rs = build_learner(
        model, capacity, example, key, batch_size=batch_size,
        target_update_interval=target_interval)
    return core, ts, rs


def _fill(core, rs, n, seed=0):
    rng = np.random.default_rng(seed)
    batch = dict(
        obs=rng.normal(size=(n, 6)).astype(np.float32),
        action=rng.integers(0, 3, n).astype(np.int32),
        reward=rng.normal(size=n).astype(np.float32),
        next_obs=rng.normal(size=(n, 6)).astype(np.float32),
        discount=np.full(n, 0.99 ** 3, np.float32))
    return core.jit_ingest()(rs, batch, jnp.ones(n))


def test_train_step_updates_params_and_priorities(key):
    core, ts, rs = _setup(key)
    rs = _fill(core, rs, 64)
    step = core.jit_train_step()

    p_before = jax.tree.leaves(ts.params)[0].copy()
    sum_before = float(rs.sum_tree[1])
    ts2, rs2, metrics = step(ts, rs, jax.random.key(1), jnp.float32(0.4))

    assert int(ts2.step) == 1
    assert not np.allclose(np.asarray(jax.tree.leaves(ts2.params)[0]),
                           np.asarray(p_before))
    assert float(rs2.sum_tree[1]) != sum_before  # priorities written back
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["grad_norm"]) > 0


def test_target_sync_interval(key):
    core, ts, rs = _setup(key, target_interval=3)
    rs = _fill(core, rs, 64)
    step = core.jit_train_step()

    tgt0 = np.asarray(jax.tree.leaves(ts.target_params)[0]).copy()
    for i in range(2):
        ts, rs, _ = step(ts, rs, jax.random.key(i), jnp.float32(0.4))
    # after 2 steps (< interval), target unchanged
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(ts.target_params)[0]), tgt0)
    ts, rs, _ = step(ts, rs, jax.random.key(9), jnp.float32(0.4))
    # at step 3 == interval, target == online
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(ts.target_params)[0]),
        np.asarray(jax.tree.leaves(ts.params)[0]))


def test_fused_step_ingests_and_trains(key):
    core, ts, rs = _setup(key)
    rs = _fill(core, rs, 32)
    fused = core.jit_fused_step()
    rng = np.random.default_rng(1)
    batch = dict(
        obs=rng.normal(size=(16, 6)).astype(np.float32),
        action=rng.integers(0, 3, 16).astype(np.int32),
        reward=rng.normal(size=16).astype(np.float32),
        next_obs=rng.normal(size=(16, 6)).astype(np.float32),
        discount=np.full(16, 0.99 ** 3, np.float32))
    ts2, rs2, metrics = fused(ts, rs, batch, jnp.ones(16),
                              jax.random.key(2), jnp.float32(0.4))
    assert int(rs2.size) == 48 and int(ts2.step) == 1


def test_fused_multi_step_matches_sequential(key):
    """scan-of-K dispatch is bit-identical to K sequential fused steps:
    same keys -> same samples -> same params/trees/metrics."""
    k_steps = 4
    rng = np.random.default_rng(5)

    def chunk(i):
        r = np.random.default_rng(100 + i)
        return dict(
            obs=r.normal(size=(16, 6)).astype(np.float32),
            action=r.integers(0, 3, 16).astype(np.int32),
            reward=r.normal(size=16).astype(np.float32),
            next_obs=r.normal(size=(16, 6)).astype(np.float32),
            discount=np.full(16, 0.99 ** 3, np.float32))

    chunks = [chunk(i) for i in range(k_steps)]
    prios = [np.abs(rng.normal(size=16)).astype(np.float32) + 0.1
             for _ in range(k_steps)]
    keys = jax.random.split(jax.random.key(3), k_steps)

    core, ts_a, rs_a = _setup(key, target_interval=2)  # sync INSIDE the scan
    rs_a = _fill(core, rs_a, 32)
    ts_b = jax.tree.map(jnp.copy, ts_a)
    rs_b = jax.tree.map(jnp.copy, rs_a)

    fused = core.jit_fused_step()
    for i in range(k_steps):
        ts_a, rs_a, m_a = fused(ts_a, rs_a, chunks[i], jnp.asarray(prios[i]),
                                keys[i], jnp.float32(0.4))

    multi = core.jit_fused_multi_step()
    stacked = {kk: jnp.stack([jnp.asarray(c[kk]) for c in chunks])
               for kk in chunks[0]}
    ts_m, rs_m, m_m = multi(ts_b, rs_b, stacked,
                            jnp.stack([jnp.asarray(p) for p in prios]),
                            keys, jnp.float32(0.4))

    assert int(ts_m.step) == k_steps
    assert m_m["loss"].shape == (k_steps,)
    for a, b in zip(jax.tree.leaves(ts_a.params), jax.tree.leaves(ts_m.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(ts_a.target_params),
                    jax.tree.leaves(ts_m.target_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(rs_a.sum_tree),
                                  np.asarray(rs_m.sum_tree))
    np.testing.assert_allclose(float(m_a["loss"]),
                               float(np.asarray(m_m["loss"])[-1]))


@pytest.mark.slow
def test_dqn_learns_cartpole():
    """End-to-end slice: reward must clearly beat random play.

    Random play on this CartPole lasts ~20 steps/episode; a learning agent
    should exceed 60 within a small frame budget.  (The Pong>=18 north star
    needs ALE + long runs; this is the CI-scale equivalent.)
    """
    cfg = small_test_config(capacity=4096, batch_size=64)
    trainer = DQNTrainer(cfg, train_every=2)
    trainer.epsilon.decay = 4000.0
    trainer.train(total_frames=14_000)
    # robust learning signal (RL variance at this scale makes a single eval
    # threshold flaky): online episode reward must clearly improve AND the
    # greedy policy must beat random play (~22/episode).
    eps = [v for _, v in trainer.log.history["learner/episode_reward"]]
    first, last = float(np.mean(eps[:20])), float(np.mean(eps[-20:]))
    score = trainer.evaluate(episodes=5, epsilon=0.0, max_steps=500)
    assert last > 1.5 * first, f"no training-curve improvement: {first}->{last}"
    assert score > 40.0, f"eval reward {score} <= 40: not learning"


def test_profiling_flops_and_mfu(key):
    """XLA cost analysis drives the MFU metric (A1)."""
    import jax

    from apex_tpu.utils import profiling

    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 64), jnp.float32)
    flops = profiling.flops_per_call(f, a, a)
    if flops is not None:                  # backend-dependent availability
        assert flops >= 2 * 64 ** 3 * 0.9
        util = profiling.mfu(flops, calls_per_sec=1000.0,
                             peak_flops=1e12)
        assert 0 < util < 1
    assert profiling.mfu(None, 10.0) is None
