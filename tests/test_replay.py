"""DeviceReplay semantics: ring writes, proportional sampling, IS weights,
priority updates — all under jit, matching reference memory.py behavior."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.replay.device import DeviceReplay

CAP = 128


def _example_item(obs_shape=(4,)):
    return dict(
        obs=np.zeros(obs_shape, np.uint8),
        action=np.int32(0),
        reward=np.float32(0),
        next_obs=np.zeros(obs_shape, np.uint8),
        discount=np.float32(0),
    )


def _batch(rng, k, obs_shape=(4,)):
    return dict(
        obs=rng.integers(0, 255, size=(k,) + obs_shape).astype(np.uint8),
        action=rng.integers(0, 4, size=k).astype(np.int32),
        reward=rng.normal(size=k).astype(np.float32),
        next_obs=rng.integers(0, 255, size=(k,) + obs_shape).astype(np.uint8),
        discount=np.where(rng.random(k) < 0.1, 0.0, 0.99 ** 3
                          ).astype(np.float32),
    )


def test_add_ring_semantics():
    rng = np.random.default_rng(0)
    rb = DeviceReplay(capacity=CAP, alpha=0.6)
    state = rb.init(_example_item())
    add = jax.jit(rb.add)

    b1 = _batch(rng, 100)
    state = add(state, b1, jnp.ones(100))
    assert int(state.size) == 100 and int(state.pos) == 100

    b2 = _batch(rng, 50)  # wraps: 28 at tail, 22 at head
    state = add(state, b2, jnp.ones(50))
    assert int(state.size) == CAP and int(state.pos) == 22

    stored = np.asarray(state.storage["reward"])
    np.testing.assert_array_equal(stored[100:], b2["reward"][:28])
    np.testing.assert_array_equal(stored[:22], b2["reward"][28:])
    np.testing.assert_array_equal(stored[22:100], b1["reward"][22:])


def test_sample_returns_matching_transitions():
    rng = np.random.default_rng(1)
    rb = DeviceReplay(capacity=CAP, alpha=0.6)
    state = rb.init(_example_item())
    batch = _batch(rng, CAP)
    state = rb.add(state, batch, jnp.asarray(rng.uniform(0.1, 2.0, CAP)))

    sample = jax.jit(lambda s, k: rb.sample(s, k, 32, 0.4))
    out, weights, idx = sample(state, jax.random.key(0))
    idx = np.asarray(idx)
    np.testing.assert_array_equal(np.asarray(out["action"]), batch["action"][idx])
    np.testing.assert_array_equal(np.asarray(out["obs"]), batch["obs"][idx])
    assert weights.shape == (32,) and np.all(np.asarray(weights) > 0)
    assert np.all(np.asarray(weights) <= 1.0 + 1e-5)  # normalized by max weight


def test_is_weights_formula():
    rng = np.random.default_rng(2)
    rb = DeviceReplay(capacity=CAP, alpha=0.6)
    state = rb.init(_example_item())
    prios = rng.uniform(0.1, 3.0, CAP).astype(np.float32)
    state = rb.add(state, _batch(rng, CAP), jnp.asarray(prios))

    beta = 0.4
    idx = jnp.asarray([0, 5, 17, 99])
    got = np.asarray(rb.is_weights(state, idx, beta))

    p_alpha = np.maximum(prios, 1e-6) ** 0.6
    p = p_alpha / p_alpha.sum()
    w = (p * CAP) ** (-beta)
    want = w[np.asarray(idx)] / w.max()
    np.testing.assert_allclose(got, want, rtol=2e-4)


def test_update_priorities_shifts_sampling_mass():
    rng = np.random.default_rng(3)
    rb = DeviceReplay(capacity=CAP, alpha=1.0)
    state = rb.init(_example_item())
    state = rb.add(state, _batch(rng, CAP), jnp.full(CAP, 0.01))
    state = rb.update_priorities(state, jnp.asarray([42]), jnp.asarray([100.0]))

    _, _, idx = rb.sample(state, jax.random.key(1), 64, 0.4)
    frac = (np.asarray(idx) == 42).mean()
    assert frac > 0.9  # leaf 42 holds ~98.7% of the mass
    assert float(state.max_priority) == 100.0


def test_add_max_priority_uses_running_max():
    rng = np.random.default_rng(4)
    rb = DeviceReplay(capacity=CAP, alpha=1.0)
    state = rb.init(_example_item())
    state = rb.add(state, _batch(rng, 4), jnp.asarray([1.0, 5.0, 1.0, 1.0]))
    state = rb.add_max_priority(state, _batch(rng, 2))
    leaves = np.asarray(state.sum_tree[CAP:CAP + 6])
    np.testing.assert_allclose(leaves[4:6], [5.0, 5.0], rtol=1e-6)


def test_fused_add_sample_update_roundtrip_jit():
    """The learner-step shape: one jitted fn doing add -> sample -> update."""
    rng = np.random.default_rng(5)
    rb = DeviceReplay(capacity=CAP, alpha=0.6)
    state = rb.init(_example_item())

    @jax.jit
    def step(state, batch, prios, key):
        state = rb.add(state, batch, prios)
        out, w, idx = rb.sample(state, key, 16, 0.4)
        new_prios = jnp.abs(out["reward"]) + 1e-3
        state = rb.update_priorities(state, idx, new_prios)
        return state, w

    for i in range(4):
        state, w = step(state, _batch(rng, 32), jnp.ones(32),
                        jax.random.key(i))
    assert int(state.size) == CAP and np.isfinite(np.asarray(w)).all()
