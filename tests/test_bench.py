"""bench.py resilience plumbing: late backend re-probe decision logic and
the e2e budget math (VERDICT r5 weak #1/#8 — unit-tested by FAKING the
probe, no jax / no subprocess), plus a slow part-1d smoke that runs the
real actor-plane A/B at toy scale."""

import importlib.util
import sys
from pathlib import Path

import pytest

BENCH_PATH = Path(__file__).resolve().parent.parent / "bench.py"


def _load_bench():
    """Fresh module instance per test (bench keeps mutable module state:
    RESULT, stage dict)."""
    spec = importlib.util.spec_from_file_location("_bench_under_test",
                                                  BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_bench_under_test"] = mod
    spec.loader.exec_module(mod)
    return mod


# -- late re-probe decision table -------------------------------------------

def test_reprobe_reexecs_when_tpu_appears_late():
    bench = _load_bench()
    calls = {"probe": 0, "reexec": 0}

    def probe():
        calls["probe"] += 1
        return "tpu"

    def reexec():
        calls["reexec"] += 1

    result = {"backend_probe": "backend init exceeded 240.0s"}
    assert bench.maybe_reprobe("cpu", environ={}, probe=probe,
                               reexec=reexec, result=result) is True
    assert calls == {"probe": 1, "reexec": 1}
    assert result["late_reprobe"] == "tpu"


def test_reprobe_records_failure_and_continues_on_cpu():
    bench = _load_bench()
    result = {"backend_probe": "relay dead"}
    assert bench.maybe_reprobe(
        "cpu", environ={}, probe=lambda: None,
        reexec=lambda: (_ for _ in ()).throw(AssertionError("no reexec")),
        result=result) is False
    assert result["late_reprobe"] == "no-answer"

    result = {"backend_probe": "relay dead"}
    assert bench.maybe_reprobe("cpu", environ={}, probe=lambda: "cpu",
                               reexec=None, result=result) is False
    assert result["late_reprobe"] == "cpu"


def test_reprobe_skipped_when_initial_probe_succeeded():
    """No fallback happened -> the operator ASKED for this platform; a
    re-probe would second-guess an explicit choice."""
    bench = _load_bench()

    def boom():
        raise AssertionError("must not probe")

    assert bench.maybe_reprobe("cpu", environ={}, probe=boom,
                               reexec=boom, result={}) is False
    assert bench.maybe_reprobe("tpu", environ={}, probe=boom, reexec=boom,
                               result={"backend_probe": "x"}) is False


def test_reprobe_runs_at_most_once():
    """The re-exec'd process carries BENCH_NO_REPROBE=1 — a flapping
    relay cannot trigger an exec loop."""
    bench = _load_bench()

    def boom():
        raise AssertionError("must not probe")

    assert bench.maybe_reprobe(
        "cpu", environ={"BENCH_NO_REPROBE": "1"}, probe=boom, reexec=boom,
        result={"backend_probe": "x"}) is False


def test_relay_child_env_restores_original_backend():
    bench = _load_bench()
    bench._ORIG_RELAY_ENV = {"JAX_PLATFORMS": None,
                             "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
    env = bench._relay_child_env({"JAX_PLATFORMS": "cpu",
                                  "PALLAS_AXON_POOL_IPS": "",
                                  "OTHER": "kept"})
    assert "JAX_PLATFORMS" not in env          # fallback pin removed
    assert env["PALLAS_AXON_POOL_IPS"] == "10.0.0.1"
    assert env["OTHER"] == "kept"


# -- e2e budget math --------------------------------------------------------

def test_e2e_budgets_leave_compile_margin(monkeypatch):
    monkeypatch.delenv("BENCH_E2E_SECONDS", raising=False)
    bench = _load_bench()
    for platform in ("tpu", "cpu"):
        soak, train_s, stage_s = bench.e2e_budgets(platform)
        assert soak == bench._e2e_seconds(platform)
        # the soak must sit INSIDE the train budget with the compile
        # margin to spare, and the stage must contain the train run with
        # room for trainer construction + actor spawn + teardown
        assert train_s == soak + bench.E2E_COMPILE_MARGIN
        assert bench.E2E_COMPILE_MARGIN >= 60.0
        assert stage_s == train_s + bench.PART2_MARGIN
        assert bench.PART2_MARGIN >= 120.0


def test_e2e_budgets_honor_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_E2E_SECONDS", "30")
    bench = _load_bench()
    soak, train_s, stage_s = bench.e2e_budgets("tpu")
    assert soak == 30.0
    assert train_s > soak and stage_s > train_s


# -- part 1d: actor-plane A/B -----------------------------------------------

@pytest.mark.slow
def test_actor_plane_ab_smoke(monkeypatch):
    """Part 1d end to end at toy scale: both geometries report per-mode
    frames/s + overlap fractions and a speedup ratio.  The effective-core
    probe is stubbed: its spawn children resolve ``_burn_child`` by
    importing ``bench`` under its real module name, which this loader's
    alias breaks — the probe's own bounded-wait fallback (0.0) covers
    that in production, and here a stub keeps the smoke fast.  Slow:
    compiles the pixel policy."""
    monkeypatch.setenv("BENCH_ACTOR_STEPS", "3")
    monkeypatch.setenv("BENCH_ACTOR_REPS", "1")
    bench = _load_bench()
    monkeypatch.setattr(bench, "_effective_cores", lambda: 1.0)
    out = bench.bench_actor_plane()
    assert out["effective_cores"] == 1.0
    for lane in ("toy", "pixel"):
        d = out[lane]
        assert d["speedup"] is None or d["speedup"] > 0
        for mode in ("off", "on"):
            m = d[mode]
            assert m["frames_per_sec"] > 0
            assert 0.0 <= m["policy_wait_frac"] <= 1.0
            assert 0.0 <= m["env_step_frac"] <= 1.0
            assert len(m["reps"]) == 1
