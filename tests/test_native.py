"""Native shared-memory ring: semantics, cross-process transport, pool
integration.  Skips wholesale when the image can't build the C++ side (the
runtime then falls back to mp.Queue — exercised by every other test)."""

import multiprocessing as mp
import pickle
import queue as queue_lib
import time

import numpy as np
import pytest

from apex_tpu.native import shm_available

pytestmark = pytest.mark.skipif(not shm_available(),
                                reason="native shm ring unavailable")


def _ring(name, slot_size=4096, n_slots=4):
    from apex_tpu.native.ring import ShmRing
    return ShmRing(name, slot_size=slot_size, n_slots=n_slots, create=True)


def test_ring_fifo_roundtrip():
    r = _ring("/apexshm-test-fifo")
    try:
        msgs = [bytes([i]) * (i + 1) for i in range(10)]
        for i, m in enumerate(msgs[:4]):
            assert r.push(m, timeout_ms=100)
        assert r.pending() == 4
        out = [r.pop(timeout_ms=100) for _ in range(4)]
        assert out == msgs[:4]
        # interleaved
        for m in msgs[4:]:
            assert r.push(m, timeout_ms=100)
            assert r.pop(timeout_ms=100) == m
        assert r.pending() == 0
        assert r.pop(timeout_ms=1) is None           # empty -> timeout
    finally:
        r.close()


def test_ring_full_timeout_then_drain():
    r = _ring("/apexshm-test-full", slot_size=256, n_slots=2)
    try:
        assert r.push(b"a", timeout_ms=50)
        assert r.push(b"b", timeout_ms=50)
        assert not r.push(b"c", timeout_ms=50)       # full: clean timeout
        assert r.push_timeouts() == 1
        assert r.pop(timeout_ms=50) == b"a"
        assert r.push(b"c", timeout_ms=50)           # freed slot reusable
        assert r.pop(timeout_ms=50) == b"b"
        assert r.pop(timeout_ms=50) == b"c"
    finally:
        r.close()


def test_ring_rejects_oversized_payload():
    from apex_tpu.native.ring import ShmRingError
    r = _ring("/apexshm-test-big", slot_size=64, n_slots=2)
    try:
        with pytest.raises(ShmRingError, match="slot size"):
            r.push(b"x" * 64, timeout_ms=10)         # 64 + 8 prefix > 64
    finally:
        r.close()


def _producer(name: str, worker: int, n_msgs: int) -> None:
    from apex_tpu.native.ring import ShmRing
    r = ShmRing(name)                                # open, not create
    for i in range(n_msgs):
        payload = pickle.dumps((worker, i, np.full(128, worker * 1000 + i)))
        while not r.push(payload, timeout_ms=200):
            pass
    r.close()


@pytest.mark.slow
def test_ring_many_producers_one_consumer():
    """3 producer processes, one consuming parent: every message arrives
    exactly once, per-producer order preserved (MPSC contract)."""
    name = "/apexshm-test-mpsc"
    r = _ring(name, slot_size=8192, n_slots=8)
    try:
        ctx = mp.get_context("spawn")
        n_msgs = 40
        procs = [ctx.Process(target=_producer, args=(name, w, n_msgs),
                             daemon=True) for w in range(3)]
        for p in procs:
            p.start()
        seen = {w: [] for w in range(3)}
        for _ in range(3 * n_msgs):
            got = r.pop(timeout_ms=10_000)
            assert got is not None, "consumer starved"
            # raw-ring fixture decoding its own test payloads; the
            # production facade (ShmChunkQueue) routes through
            # wire.restricted_loads
            # apexlint: disable=C005 -- self-made test payloads
            w, i, arr = pickle.loads(got)
            assert (arr == w * 1000 + i).all()
            seen[w].append(i)
        for p in procs:
            p.join(timeout=10)
        assert all(seen[w] == list(range(n_msgs)) for w in range(3))
        assert r.pop(timeout_ms=10) is None
    finally:
        r.close()


def test_force_skip_recovers_wedged_ring():
    """A producer killed between claim and publish starves the consumer;
    force_skip plants a tombstone so later (published) messages flow."""
    from apex_tpu import native

    r = _ring("/apexshm-test-wedge", slot_size=256, n_slots=4)
    try:
        lib = native._load()
        lib.apex_shm_test_claim(r._h)        # dead producer: claim, no publish
        assert r.push(b"real", timeout_ms=100)   # live producer on ticket 1
        assert r.pop(timeout_ms=50) is None      # starved behind ticket 0
        assert r.pending() == 2
        assert r.force_skip()                    # dispose + free in one CAS
        assert not r.force_skip()                # head now a published ticket
        assert r.pop(timeout_ms=100) == b"real"  # data flows again
        assert r.pending() == 0
        # the freed slot is reusable by a later ticket
        assert r.push(b"again", timeout_ms=100)
        assert r.pop(timeout_ms=100) == b"again"
    finally:
        r.close()


def test_chunk_queue_auto_recovers_from_dead_producer(monkeypatch):
    """The facade applies the force-skip judgment itself: after
    STUCK_SECONDS of starvation with pending messages, the wedged head is
    skipped and queued messages deliver."""
    from apex_tpu import native
    from apex_tpu.native.ring import ShmChunkQueue

    monkeypatch.setattr(ShmChunkQueue, "STUCK_SECONDS", 0.3)
    q = ShmChunkQueue("/apexshm-test-autoskip", slot_bytes=4096, depth=4)
    try:
        native._load().apex_shm_test_claim(q._ring._h)   # wedge ticket 0
        q.put(("chunk", 1, {"n_trans": 3}))
        deadline = time.monotonic() + 10
        got = None
        while got is None and time.monotonic() < deadline:
            try:
                got = q.get(timeout=0.1)
            except queue_lib.Empty:
                pass
        assert got == ("chunk", 1, {"n_trans": 3})
        assert q.skipped == 1
        assert q._ring.disposed() == 1     # the skip counted exactly once
    finally:
        q.close()


def test_ring_random_sequences_match_fifo_model():
    """Property test: arbitrary interleavings of push/pop against a deque
    model — contents, order, pending count, and full/empty behavior all
    agree (single-process; the MPSC test covers cross-process)."""
    from collections import deque

    # gate, don't fail: some images ship without hypothesis, and the
    # MPSC + FIFO unit tests above still cover the ring's contract there
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    ops = st.lists(st.one_of(
        st.tuples(st.just("push"), st.binary(min_size=0, max_size=40)),
        st.tuples(st.just("pop"), st.none()),
    ), min_size=1, max_size=200)

    @settings(max_examples=50, deadline=None)
    @given(ops=ops)
    def run(ops):
        r = _ring("/apexshm-test-prop", slot_size=64, n_slots=4)
        model: deque = deque()
        try:
            for op, arg in ops:
                if op == "push":
                    ok = r.push(arg, timeout_ms=0)
                    assert ok == (len(model) < 4)
                    if ok:
                        model.append(arg)
                else:
                    got = r.pop(timeout_ms=0)
                    want = model.popleft() if model else None
                    assert got == want
                assert r.pending() == len(model)
        finally:
            r.close()

    run()


def test_chunk_queue_facade():
    """The mp.Queue-shaped surface ActorPool drives: put/get/get_nowait,
    Empty on empty, pickle-through of chunk-message dicts."""
    from apex_tpu.native.ring import ShmChunkQueue
    q = ShmChunkQueue("/apexshm-test-facade", slot_bytes=1 << 16, depth=4)
    try:
        msg = {"payload": {"frames": np.arange(100, dtype=np.uint8)},
               "priorities": np.ones(3, np.float32), "n_trans": 3}
        q.put(("chunk", 0, msg))
        kind, actor_id, out = q.get(timeout=0.5)
        assert (kind, actor_id) == ("chunk", 0)
        np.testing.assert_array_equal(out["payload"]["frames"],
                                      msg["payload"]["frames"])
        with pytest.raises(queue_lib.Empty):
            q.get_nowait()
        with pytest.raises(queue_lib.Empty):
            q.get(timeout=0.05)
    finally:
        q.close()


@pytest.mark.slow
def test_actor_pool_uses_shm_plane():
    """ApexTrainer's pool rides the native ring end-to-end: chunks from real
    worker processes cross shared memory, training proceeds, shutdown is
    clean and the segment is unlinked."""
    import os

    from apex_tpu.config import small_test_config
    from apex_tpu.native.ring import ShmChunkQueue
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    assert isinstance(trainer.pool.chunk_queue, ShmChunkQueue), \
        "shm plane expected by default when shm_available()"
    seg = "/dev/shm/" + trainer.pool.chunk_queue.name.lstrip("/")
    trainer.train(total_steps=30, max_seconds=120)
    assert trainer.steps_rate.total >= 30
    assert trainer.ingested >= cfg.replay.warmup
    assert all(not p.is_alive() for p in trainer.pool.procs)
    assert not os.path.exists(seg), "segment must be unlinked on cleanup"


def test_actor_pool_falls_back_without_shm():
    """shm_data_plane=False (or an unavailable ring) must yield a plain
    mp.Queue — the fleet still runs."""
    import dataclasses

    from apex_tpu.actors.pool import ActorPool
    from apex_tpu.config import small_test_config

    cfg = small_test_config(n_actors=1)
    cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                shm_data_plane=False))
    pool = ActorPool(cfg, {"num_actions": 2, "obs_is_image": False},
                     chunk_transitions=16)
    from apex_tpu.native.ring import ShmChunkQueue
    assert not isinstance(pool.chunk_queue, ShmChunkQueue)
    for q in [pool.chunk_queue, pool.stat_queue, *pool.param_queues]:
        q.cancel_join_thread()
        q.close()
