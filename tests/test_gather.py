"""Pallas frame-gather kernel: interpret-mode parity against the XLA path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.gather import gather_rows


@pytest.mark.parametrize("n,f,d,dtype", [
    (32, 64, 256, jnp.uint8),       # aligned lanes
    (13, 16, 2048, jnp.uint8),      # padded 42x42 rows, group padding
    (48, 128, 136, jnp.float32),    # lane-unaligned (d%8==0) vector rows
])
def test_pallas_gather_matches_xla(n, f, d, dtype):
    key = jax.random.key(0)
    frames = jax.random.randint(key, (f, d), 0, 255).astype(dtype)
    ids = jax.random.randint(jax.random.key(1), (n,), 0, f, jnp.int32)
    want = gather_rows(frames, ids, mode="xla")
    got = gather_rows(frames, ids, mode="interpret")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_gather_repeated_and_boundary_ids():
    frames = jnp.arange(8 * 384, dtype=jnp.uint8).reshape(8, 384)
    ids = jnp.asarray([0, 7, 7, 3, 0, 0, 7, 1, 2], jnp.int32)
    got = gather_rows(frames, ids, mode="interpret")
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(frames)[np.asarray(ids)])


def test_frame_pool_sample_parity_with_pallas_gather():
    """Same replay state + key: sampling through the pallas kernel
    (interpret) returns the exact batch of the XLA gather path."""
    import dataclasses

    from apex_tpu.replay.frame_pool import FramePoolReplay

    spec_x = FramePoolReplay(capacity=64, frame_shape=(8, 8, 1),
                             frame_stack=3, gather_mode="xla")
    spec_p = dataclasses.replace(spec_x, gather_mode="interpret")
    state = spec_x.init()
    kf, k = 12, 8
    rng = np.random.default_rng(7)
    for c in range(4):
        chunk = dict(
            frames=rng.integers(0, 255, (kf, 64), np.uint8),
            n_frames=np.int32(kf), n_trans=np.int32(k),
            action=rng.integers(0, 4, k).astype(np.int32),
            reward=rng.normal(size=k).astype(np.float32),
            discount=np.full(k, 0.97, np.float32),
            obs_ref=np.sort(rng.integers(0, kf, (k, 3)), axis=1)
                      .astype(np.int32),
            next_ref=np.sort(rng.integers(0, kf, (k, 3)), axis=1)
                       .astype(np.int32),
        )
        chunk = {kk: jnp.asarray(v) for kk, v in chunk.items()}
        state = spec_x.add(state, chunk,
                           jnp.abs(jax.random.normal(jax.random.key(c),
                                                     (k,))) + 0.1)
    key = jax.random.key(42)
    bx, wx, ix = spec_x.sample(state, key, 16, 0.5)
    # apexlint: disable=J004 -- parity test: both gather paths must sample with the identical key
    bp, wp, ip = spec_p.sample(state, key, 16, 0.5)
    np.testing.assert_array_equal(np.asarray(ix), np.asarray(ip))
    np.testing.assert_array_equal(np.asarray(bx["obs"]), np.asarray(bp["obs"]))
    np.testing.assert_array_equal(np.asarray(bx["next_obs"]),
                                  np.asarray(bp["next_obs"]))
    np.testing.assert_allclose(np.asarray(wx), np.asarray(wp))


def test_row_padding_and_eligibility():
    """Pixel rings pad rows to whole (8,128) tiles for the kernel; small
    vector rings stay unpadded and auto-route to XLA; the kernel itself
    refuses layouts it cannot slice."""
    from apex_tpu.ops.gather import ROW_UNIT, pallas_eligible
    from apex_tpu.replay.frame_pool import FramePoolReplay

    atari = FramePoolReplay(capacity=64, frame_shape=(84, 84, 1))
    assert atari.row_dim == 7168 and atari.row_dim % ROW_UNIT == 0
    catch = FramePoolReplay(capacity=64, frame_shape=(42, 42, 1))
    assert catch.row_dim == 2048
    cart = FramePoolReplay(capacity=64, frame_shape=(4,),
                           frame_stack=1, frame_dtype="float32")
    assert cart.row_dim == 4                 # unpadded -> XLA path
    assert not pallas_eligible(4, jnp.float32)
    assert pallas_eligible(7168, jnp.uint8)

    with pytest.raises(ValueError, match="row dim"):
        gather_rows(jnp.zeros((8, 36), jnp.uint8),
                    jnp.zeros(4, jnp.int32), mode="interpret")


def test_padded_ring_roundtrips_through_sample():
    """A padded ring (42x42 -> 2048-wide rows) must store and return the
    exact unpadded frames through add + sample."""
    from apex_tpu.replay.frame_pool import FramePoolReplay

    spec = FramePoolReplay(capacity=32, frame_shape=(42, 42, 1),
                           frame_stack=2)
    assert spec.row_dim == 2048
    state = spec.init()
    rng = np.random.default_rng(3)
    kf, k = 6, 4
    chunk = dict(
        frames=rng.integers(0, 255, (kf, 1764), np.uint8),
        n_frames=np.int32(kf), n_trans=np.int32(k),
        action=np.zeros(k, np.int32), reward=np.zeros(k, np.float32),
        discount=np.ones(k, np.float32),
        obs_ref=np.stack([np.arange(k), np.arange(k) + 1], 1).astype(np.int32),
        next_ref=np.stack([np.arange(k) + 1, np.arange(k) + 2], 1)
                   .astype(np.int32),
    )
    state = spec.add(state, {kk: jnp.asarray(v) for kk, v in chunk.items()},
                     jnp.ones(k))
    batch, _, idx = spec.sample(state, jax.random.key(0), 8, 0.4)
    assert batch["obs"].shape == (8, 42, 42, 2)
    i = int(idx[0])
    got = np.asarray(batch["obs"][0])
    want = np.stack([chunk["frames"][i].reshape(42, 42),
                     chunk["frames"][i + 1].reshape(42, 42)], -1)
    np.testing.assert_array_equal(got, want)


def test_env_pallas_optin_gates_per_operand(monkeypatch):
    """APEX_GATHER_MODE=pallas is process-global, but eligibility is
    per-operand: an eligible tiled 3-D ring resolves to the kernel while
    a small 2-D vector ring quietly keeps the XLA path (it would hand
    Mosaic an unsliceable layout otherwise)."""
    from apex_tpu.ops.gather import ROW_UNIT, resolved_mode

    monkeypatch.setenv("APEX_GATHER_MODE", "pallas")
    eligible = jnp.zeros((16, 8, ROW_UNIT // 8), jnp.uint8)
    vector = jnp.zeros((16, 8), jnp.float32)
    assert resolved_mode(eligible) == "pallas"
    assert resolved_mode(vector) == "xla"
    monkeypatch.setenv("APEX_GATHER_MODE", "xla")
    assert resolved_mode(eligible) == "xla"
    monkeypatch.delenv("APEX_GATHER_MODE")
    assert resolved_mode(eligible) == "xla"    # opt-in only


def test_auto_mode_uses_xla_off_tpu():
    """On the CPU CI platform auto must route to jnp.take (the kernel is
    TPU-only); the call must still be correct under jit."""
    frames = jnp.arange(16 * 128, dtype=jnp.float32).reshape(16, 128)
    ids = jnp.asarray([5, 1, 14], jnp.int32)

    @jax.jit
    def f(fr, i):
        return gather_rows(fr, i)

    np.testing.assert_array_equal(np.asarray(f(frames, ids)),
                                  np.asarray(frames)[np.asarray(ids)])
