"""Fleet control plane (apex_tpu/fleet): registry machine, heartbeats,
park-and-rejoin, the chaos harness, the restricted wire, and the host
supervisor.

Everything here is tier-1: deterministic (fake clocks / seeded schedules)
and fast (the socket tests run whole learner-death dramas in-process on
localhost with sub-second thresholds).  The multi-process SIGKILL soak
lives in ``tests/test_fleet_rejoin.py`` behind ``-m slow``.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time

import pytest

from apex_tpu.config import CommsConfig
from apex_tpu.fleet.chaos import (ChaosChunkSender, ChaosConfig,
                                  ChaosParamPublisher, chaos_from_env)
from apex_tpu.fleet.heartbeat import Heartbeat, HeartbeatEmitter
from apex_tpu.fleet.park import ParkController
from apex_tpu.fleet.registry import (ALIVE, DEAD, JOINING, SUSPECT,
                                     FleetRegistry, FleetStatusServer,
                                     format_fleet_table, status_request)
from apex_tpu.fleet.supervise import supervise


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _comms(**overrides) -> CommsConfig:
    batch, param, barrier, status = _free_ports(4)
    return CommsConfig(batch_port=batch, param_port=param,
                       barrier_port=barrier, status_port=status,
                       **overrides)


# -- registry state machine -------------------------------------------------

def test_registry_state_machine_and_rejoin_accounting():
    """JOINING -> ALIVE -> SUSPECT -> DEAD -> ALIVE under a fake clock;
    DEAD->ALIVE counts as a rejoin, SUSPECT->ALIVE recovery does not."""
    t = [0.0]
    comms = CommsConfig(suspect_after_s=2.0, dead_after_s=5.0)
    reg = FleetRegistry(comms, clock=lambda: t[0])

    reg.observe(Heartbeat("actor-0", fps=100.0, param_version=3))
    assert reg.peers["actor-0"].state == ALIVE
    assert ("actor-0", JOINING, ALIVE) in reg.tick()

    t[0] = 3.0                              # silent past suspect_after_s
    assert ("actor-0", ALIVE, SUSPECT) in reg.tick()
    reg.observe(Heartbeat("actor-0"))       # recovery: NOT a rejoin
    assert reg.peers["actor-0"].state == ALIVE
    assert reg.metrics()["rejoins"] == 0

    t[0] = 10.0                             # silent past dead_after_s
    trans = reg.tick()
    assert ("actor-0", ALIVE, SUSPECT) in trans
    assert ("actor-0", SUSPECT, DEAD) in trans
    assert reg.metrics()["dead"] == 1 and reg.metrics()["deaths"] == 1

    reg.observe(Heartbeat("actor-0"))       # back from the dead: a rejoin
    m = reg.metrics()
    assert m["alive"] == 1 and m["dead_to_alive"] == 1 and m["rejoins"] == 1


def test_registry_merges_self_reported_rejoins_and_seen_liveness():
    """fleet_rejoins survives a learner restart: a FRESH registry credits
    the fleet's self-reported park->resume cycles; chunk-arrival times
    (observe_seen) keep a stat-dropping peer alive."""
    t = [0.0]
    comms = CommsConfig(suspect_after_s=2.0, dead_after_s=5.0)
    reg = FleetRegistry(comms, clock=lambda: t[0])
    reg.observe(Heartbeat("actor-0", rejoins=1))
    reg.observe(Heartbeat("actor-1", rejoins=1))
    assert reg.rejoins() == 2               # no DEAD->ALIVE seen here

    # chunks keep flowing while heartbeats drop: stays ALIVE
    t[0] = 4.0
    reg.observe_seen({"actor-0": 3.9})
    trans = reg.tick()
    assert ("actor-1", ALIVE, SUSPECT) in trans
    assert reg.peers["actor-0"].state == ALIVE

    # a DEAD peer revived by message arrival also counts as a rejoin
    t[0] = 20.0
    reg.tick()
    assert reg.peers["actor-0"].state == DEAD
    reg.observe_seen({"actor-0": 20.0})
    assert reg.peers["actor-0"].state == ALIVE
    assert reg.rejoins() == 3


def test_registry_gap_percentiles_and_table():
    t = [0.0]
    reg = FleetRegistry(CommsConfig(), clock=lambda: t[0])
    for i in range(1, 11):
        t[0] = float(i)
        reg.observe(Heartbeat("actor-0", fps=50.0))
    m = reg.metrics()
    assert m["hb_gap_p50_s"] == pytest.approx(1.0)
    assert m["hb_gap_p99_s"] == pytest.approx(1.0)
    table = format_fleet_table(reg.snapshot())
    assert "actor-0" in table and "ALIVE" in table and "rejoins" in table


def test_heartbeat_emitter_cadence_and_hooks():
    t = [0.0]
    beats = []
    em = HeartbeatEmitter(
        "actor-7", role="actor", interval_s=2.0,
        counters_fn=lambda: {"chunks_sent": 42, "acks_received": 40},
        park_fn=lambda: (True, 3), clock=lambda: t[0])
    assert em.maybe_beat(1) is None         # not due yet
    t[0] = 2.5
    em.tick(50)
    hb = em.maybe_beat(9)
    assert hb is not None and hb.identity == "actor-7"
    assert hb.param_version == 9 and hb.chunks_sent == 42
    assert hb.parked and hb.rejoins == 3
    assert hb.fps == pytest.approx(50 / 2.5, rel=0.01)
    assert em.maybe_beat(9) is None         # window reset
    beats.append(hb)


# -- restricted wire --------------------------------------------------------

def test_wire_roundtrips_every_message_type():
    import numpy as np

    from apex_tpu.actors.pool import ActorTimingStat, EpisodeStat
    from apex_tpu.runtime import wire

    msgs = [
        ("chunk", {"payload": {"frames": np.zeros((4, 3), np.uint8)},
                   "priorities": np.ones(4, np.float32), "n_trans": 4}),
        ("stat", EpisodeStat(1, 2.5, 30, 7)),
        ("stat", ActorTimingStat(0, 100.0, .1, .2, .3, .4, 256, True)),
        ("stat", Heartbeat("actor-0", fps=12.5, chunks_sent=3)),
        (5, {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}),
        np.float32(1.5),
    ]
    for msg in msgs:
        got = wire.restricted_loads(wire.dumps(msg))
        assert type(got) is type(msg)


def test_wire_rejects_non_allowlisted_globals():
    import os
    import pickle

    from apex_tpu.runtime import wire

    class Evil:
        def __reduce__(self):
            return (os.system, ("true",))

    with pytest.raises(wire.WireRejected):
        wire.restricted_loads(pickle.dumps(Evil()))
    # even benign-but-unlisted classes are rejected: allowlist, not
    # blocklist
    with pytest.raises(wire.WireRejected):
        wire.restricted_loads(pickle.dumps(CommsConfig()))


def test_receiver_counts_and_drops_rejected_payloads():
    """A hostile payload on the chunk socket costs one message (counted),
    earns no ack, and the pipe keeps working for honest peers."""
    import pickle

    import zmq

    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    comms = _comms()
    recv = ChunkReceiver(comms, queue_depth=8)
    recv.start()
    try:
        evil = zmq.Context.instance().socket(zmq.DEALER)
        evil.setsockopt(zmq.IDENTITY, b"mallory")
        evil.connect(f"tcp://127.0.0.1:{comms.batch_port}")

        class Evil:
            def __reduce__(self):
                import os
                return (os.system, ("true",))

        evil.send(pickle.dumps(("chunk", Evil())))
        deadline = time.monotonic() + 10
        while recv.rejected == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert recv.rejected == 1
        evil.close(linger=0)

        s = ChunkSender(comms, "actor-0")
        assert s.send_chunk({"n": 1})
        assert recv.chunks.get(timeout=5.0) == {"n": 1}
        s.close()
    finally:
        recv.stop()


# -- chaos harness ----------------------------------------------------------

class _StubSender:
    def __init__(self):
        self.sent = []
        self.chunks_sent = 0
        self.acks_received = 0

    def send_chunk(self, msg, stop_event=None, max_wait_s=None):
        self.sent.append(msg)
        self.chunks_sent += 1
        return True

    def send_stat(self, stat):
        pass

    def reset_credits(self):
        pass

    def close(self, *a, **kw):
        pass


def test_chaos_schedule_is_deterministic_per_identity():
    """Same seed + identity -> the same per-message drop/delay decisions,
    run after run; a different identity draws a different stream."""
    spec = {"drop_frac": 0.3, "delay_frac": 0.2, "delay_s": 0.0}

    def fates(identity, seed=7):
        plan = ChaosConfig(seed, spec).plan_for(identity)
        inner = _StubSender()
        cs = ChaosChunkSender(inner, plan, sleep=lambda s: None)
        fate = []
        for i in range(200):
            before = len(inner.sent)
            delayed_before = cs.delayed
            cs.send_chunk({"i": i})
            fate.append(("drop" if len(inner.sent) == before else
                         "delay" if cs.delayed > delayed_before else "send"))
        return fate

    a1, a2 = fates("actor-0"), fates("actor-0")
    assert a1 == a2
    assert fates("actor-1") != a1
    assert 30 < a1.count("drop") < 90          # ~0.3 of 200


def test_chaos_kill_disarms_on_respawned_lives(monkeypatch):
    """APEX_RESPAWN_COUNT>0 (exported by the supervisor) disarms kill
    entries so a deterministic kill-at-N cannot become a kill loop;
    drop/delay schedules stay live."""
    monkeypatch.setenv("CHAOS_SEED", "3")
    monkeypatch.setenv("CHAOS_SPEC",
                       '{"kill": {"actor-0": 5}, "drop_frac": 0.5}')
    cfg = chaos_from_env()
    assert cfg.plan_for("actor-0").kill_at == 5
    assert cfg.plan_for("actor-1").kill_at is None

    monkeypatch.setenv("APEX_RESPAWN_COUNT", "1")
    cfg = chaos_from_env()
    assert cfg.plan_for("actor-0").kill_at is None
    assert cfg.plan_for("actor-0").drop_frac == 0.5

    monkeypatch.setenv("CHAOS_SEED", "")      # empty string = chaos off
    assert chaos_from_env() is None


def test_chaos_publisher_stall_schedule():
    class _StubPub:
        def __init__(self):
            self.published = []

        def publish(self, version, params):
            self.published.append(version)

        def close(self):
            pass

    slept = []
    plan = ChaosConfig(1, {"stall_at": 2, "stall_s": 1.5}).plan_for(
        "learner")
    pub = ChaosParamPublisher(_StubPub(), plan, sleep=slept.append)
    for v in range(5):
        pub.publish(v, None)
    assert pub.inner.published == [0, 1, 2, 3, 4]   # stall delays, never drops
    assert slept == [1.5] and pub.stalls == 1


def test_chaos_drop_frac_over_real_sockets():
    """Dropped chunks consume no credit: with drop_frac=0.5 a
    window-of-3 sender still completes 40 sends, and the receiver gets
    exactly the non-dropped ones."""
    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    comms = _comms()
    recv = ChunkReceiver(comms, queue_depth=64)
    recv.start()
    try:
        plan = ChaosConfig(11, {"drop_frac": 0.5}).plan_for("actor-0")
        cs = ChaosChunkSender(ChunkSender(comms, "actor-0"), plan)
        for i in range(40):
            assert cs.send_chunk({"i": i})
        assert 5 < cs.dropped < 35
        expected = 40 - cs.dropped
        got = []
        deadline = time.monotonic() + 15
        while len(got) < expected and time.monotonic() < deadline:
            try:
                got.append(recv.chunks.get(timeout=0.5))
            except Exception:
                pass
        assert len(got) == expected
        cs.close()
    finally:
        recv.stop()


# -- park-and-rejoin --------------------------------------------------------

def test_park_controller_parks_and_rejoins_respawned_learner():
    """The whole drama in-process: params flow, the 'learner' dies (stops
    publishing), the actor parks; a 'respawned learner' re-releases the
    barrier and publishes — the parked actor reattaches in under a
    second, its credit window reset, rejoins counted."""
    from apex_tpu.runtime.transport import (ChunkSender, ParamPublisher,
                                            ParamSubscriber,
                                            barrier_release)

    comms = _comms(park_after_s=0.3, rejoin_backoff_s=0.05,
                   rejoin_backoff_max_s=0.2, rejoin_attempt_s=0.5)
    stop = threading.Event()
    sub = ParamSubscriber(comms)
    sender = ChunkSender(comms, "actor-0")
    park = ParkController(comms, "actor-0", stop, sub=sub, sender=sender)

    pub1 = ParamPublisher(comms)
    try:
        time.sleep(0.2)                       # SUB connect (slow joiner)
        pub1.publish(1, {"w": 1})
        deadline = time.monotonic() + 5
        got = None
        while got is None and time.monotonic() < deadline:
            got = sub.poll(100)
        assert got is not None and got[0] == 1
        park.note_params()
        pub1.close()                          # learner dies

        # wedge the window as an in-flight send would leave it
        sender._in_flight = sender.max_outstanding

        result = {}

        def parked_actor():
            result["got"] = park.park_and_rejoin()

        t = threading.Thread(target=parked_actor, daemon=True)
        time.sleep(0.4)                       # past park_after_s
        assert park.stale()
        t.start()
        time.sleep(0.3)
        assert park.parked

        # respawned learner: barrier for 1 peer, then first publish
        released = {}

        def learner2():
            released["n"] = barrier_release(comms, 1, timeout_s=10)
            pub2 = ParamPublisher(comms)
            try:
                end = time.monotonic() + 5
                while not result and time.monotonic() < end:
                    pub2.publish(2, {"w": 2})
                    time.sleep(0.05)
            finally:
                pub2.close()

        lt = threading.Thread(target=learner2, daemon=True)
        lt.start()
        t.join(timeout=15)
        lt.join(timeout=15)
        assert not t.is_alive(), "actor never rejoined"
        assert released["n"] == 1, "rejoin hello never reached the barrier"
        assert result["got"] is not None and result["got"][0] >= 2
        assert park.rejoins == 1 and not park.parked
        assert sender._in_flight == 0, "credit window not reset on rejoin"
        # the rejoin stashed the params for the adapter's next poll
        assert park.take_pending() is not None
        assert park.take_pending() is None
    finally:
        stop.set()
        sender.close(drain_s=0)
        sub.close()


def test_park_controller_does_not_park_while_params_flow():
    """Wedge-path false alarm guard: a backpressured-but-alive learner
    keeps publishing, so park_and_rejoin probes, stashes the params, and
    returns without parking or resetting credits."""
    from apex_tpu.runtime.transport import ParamPublisher, ParamSubscriber

    comms = _comms(park_after_s=0.2)
    stop = threading.Event()
    sub = ParamSubscriber(comms)
    park = ParkController(comms, "actor-0", stop, sub=sub)
    pub = ParamPublisher(comms)
    try:
        time.sleep(0.2)
        pub.publish(5, {"w": 5})
        time.sleep(0.3)                       # stale by clock, but a
        got = park.park_and_rejoin()          # publish is waiting
        assert got is not None and got[0] == 5
        assert park.parks == 0 and park.rejoins == 0
    finally:
        stop.set()
        pub.close()
        sub.close()


# -- status surface ---------------------------------------------------------

def test_status_server_round_trip():
    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-0", fps=123.0, param_version=4))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    try:
        snap = status_request(comms, learner_ip="127.0.0.1", timeout_s=5)
        assert snap is not None
        assert snap["peers"][0]["identity"] == "actor-0"
        assert snap["peers"][0]["fps"] == 123.0
        assert snap["metrics"]["alive"] == 1
    finally:
        srv.stop()


def test_status_cli_prints_fleet_table(capsys):
    from apex_tpu.runtime import cli

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-2", role="actor", fps=55.0))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    try:
        rc = cli.main(["--role", "status",
                       "--status-port", str(comms.status_port)])
        out = capsys.readouterr().out
        assert rc in (0, None)
        assert "actor-2" in out and "ALIVE" in out
    finally:
        srv.stop()


def test_metrics_scrape_round_trip():
    """The Prometheus surface on the SAME status REP socket: b"metrics"
    returns text exposition (trainer-provided metrics_fn, or the
    registry-only fallback), and the pickled snapshot path keeps working
    beside it."""
    from apex_tpu.obs.metrics import metrics_request

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-0", role="actor", fps=88.0, wall_ts=1.0))

    calls = []

    def metrics_fn():
        calls.append(1)
        return ("# TYPE apex_fleet_alive gauge\n"
                "apex_fleet_alive 1.0\n"
                "apex_custom_gauge 42.0\n")

    srv = FleetStatusServer(comms, reg, metrics_fn=metrics_fn)
    srv.start()
    try:
        text = metrics_request(comms, learner_ip="127.0.0.1", timeout_s=5)
        assert text is not None and calls == [1]
        assert "apex_fleet_alive 1.0" in text
        assert "apex_custom_gauge 42.0" in text
        # the snapshot request still answers on the same socket
        snap = status_request(comms, learner_ip="127.0.0.1", timeout_s=5)
        assert snap is not None
        assert snap["peers"][0]["identity"] == "actor-0"
        assert snap["peers"][0]["clock_offset_s"] is not None
    finally:
        srv.stop()


def test_metrics_scrape_registry_fallback_and_cli(capsys):
    """Without a metrics_fn the server renders a fleet-only exposition
    from the registry; `--role status --metrics` prints it."""
    from apex_tpu.runtime import cli

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-3", role="actor", fps=12.0))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    try:
        rc = cli.main(["--role", "status", "--metrics",
                       "--status-port", str(comms.status_port)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "# TYPE apex_fleet_alive gauge" in out
        assert 'apex_fleet_peer_fps{identity="actor-3"} 12.0' in out
    finally:
        srv.stop()


# -- host supervisor --------------------------------------------------------

def test_supervisor_respawn_budget_and_backoff():
    """ActorPool semantics at process scale: short-lived crashes double
    the backoff and burn budget; exhausting the budget halts with rc=1;
    the respawn count is exported to each life."""
    t = [0.0]
    sleeps = []
    lives = []

    def fake_run(cmd, env):
        lives.append(int(env["APEX_RESPAWN_COUNT"]))
        t[0] += 1.0                     # every life dies after 1s
        return 9

    rc = supervise(["role"], max_respawns=3, window_s=600, min_uptime_s=60,
                   backoff_s=1.0, backoff_max_s=4.0,
                   sleep=sleeps.append, clock=lambda: t[0], run=fake_run)
    assert rc == 1
    assert lives == [0, 1, 2, 3]        # initial life + 3 budgeted respawns
    assert len(sleeps) == 3
    # exponential with jitter in [0.5, 1.5) of the doubling base
    assert 1.0 <= sleeps[0] / 1.0 + 0.5 and sleeps[1] >= sleeps[0] * 0.5


def test_supervisor_clean_exit_and_budget_refresh():
    t = [0.0]

    def run_clean(cmd, env):
        t[0] += 120.0
        return 0

    assert supervise(["role"], run=run_clean, clock=lambda: t[0],
                     sleep=lambda s: None) == 0

    # long-lived lives never exhaust the budget: the window refreshes
    calls = []

    def run_long_then_clean(cmd, env):
        calls.append(1)
        t[0] += 700.0                   # outlives the window every time
        return 0 if len(calls) >= 6 else 5

    rc = supervise(["role"], max_respawns=2, window_s=600,
                   min_uptime_s=60, run=run_long_then_clean,
                   clock=lambda: t[0], sleep=lambda s: None)
    assert rc == 0 and len(calls) == 6


def test_supervisor_cli_subprocess_end_to_end():
    """The real module entry: a child that always exits nonzero exhausts
    a budget of 1 quickly; the supervisor reports and exits 1."""
    import subprocess
    import sys

    p = subprocess.run(
        [sys.executable, "-m", "apex_tpu.fleet.supervise",
         "--max-respawns", "1", "--min-uptime", "0.01",
         "--backoff", "0.01", "--backoff-max", "0.02", "--",
         sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 1
    assert "crash loop" in p.stdout


def test_supervisor_sigterm_terminates_child():
    """Killing a supervisor must take its child with it (PR 8 fix): the
    un-forwarded child used to survive as an orphan still bound to its
    role's ports, shadowing the next fleet on the same host."""
    import signal
    import subprocess
    import sys

    marker = "apex_supervise_child_marker"
    p = subprocess.Popen(
        [sys.executable, "-m", "apex_tpu.fleet.supervise", "--",
         sys.executable, "-c",
         f"import time; {marker} = 1; time.sleep(120)"])
    try:
        deadline = time.monotonic() + 30
        child_pid = None
        while child_pid is None and time.monotonic() < deadline:
            probe = subprocess.run(["pgrep", "-f", marker],
                                   capture_output=True, text=True)
            pids = [int(x) for x in probe.stdout.split()
                    if int(x) != p.pid]
            child_pid = pids[0] if pids else None
            time.sleep(0.1)
        assert child_pid is not None, "child never came up"
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=30) != 0
        import os
        deadline = time.monotonic() + 10
        gone = False
        while time.monotonic() < deadline and not gone:
            try:
                os.kill(child_pid, 0)
                time.sleep(0.1)
            except ProcessLookupError:
                gone = True
        assert gone, "supervised child survived its supervisor"
    finally:
        if p.poll() is None:
            p.kill()


def test_supervisor_cli_rejects_missing_command():
    import subprocess
    import sys

    p = subprocess.run([sys.executable, "-m", "apex_tpu.fleet.supervise"],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 2


# -- learner-epoch fencing on the param plane (PR 8) ------------------------

def test_param_plane_carries_learner_epoch():
    """An epoch-stamped publish updates the subscriber's learner_epoch
    while every consumer still sees the plain (version, params) tuple;
    unstamped (legacy) publishes leave the epoch untouched."""
    from apex_tpu.runtime.transport import ParamPublisher, ParamSubscriber

    comms = _comms()
    sub = ParamSubscriber(comms)
    pub = ParamPublisher(comms)
    try:
        time.sleep(0.2)                        # SUB connect (slow joiner)

        def publish_until_seen(version):
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                pub.publish(version, {"w": version})
                got = sub.poll(100)
                if got is not None and got[0] == version:
                    return got
            raise AssertionError("publish never arrived")

        got = publish_until_seen(1)            # unstamped: legacy 2-tuple
        assert got == (1, {"w": 1})
        assert sub.learner_epoch == 0

        pub.epoch = 7                          # stamped: 3-tuple on the wire
        got = publish_until_seen(2)
        assert got == (2, {"w": 2})
        assert sub.learner_epoch == 7
    finally:
        pub.close()
        sub.close()


class _EpochSub:
    """Scripted param stream with an epoch stamp, for the park decision
    table (no sockets: the barrier is monkeypatched).  ``delay_polls``
    makes the first probes miss, so the controller genuinely parks
    before the stream resumes."""

    def __init__(self, delay_polls: int = 1):
        self.learner_epoch = 0
        self.queue: list = []
        self.delay_polls = delay_polls

    def poll(self, timeout_ms: int = 0):
        if self.delay_polls > 0:
            self.delay_polls -= 1
            return None
        if self.queue:
            version, params, epoch = self.queue.pop(0)
            self.learner_epoch = epoch
            return (version, params)
        return None


@pytest.mark.parametrize("resume_epoch,expect_reset", [
    (1, False),      # same epoch: the learner STALLED — acks still coming
    (2, True),       # bumped epoch: a RESTART took the ack window with it
])
def test_park_decision_table_restart_vs_stall(monkeypatch, resume_epoch,
                                              expect_reset):
    monkeypatch.setattr("apex_tpu.runtime.transport.barrier_wait",
                        lambda *a, **kw: True)
    comms = CommsConfig(park_after_s=0.0)      # instantly stale
    stop = threading.Event()
    sub = _EpochSub()
    sub.learner_epoch = 1                      # epoch seen before the park
    sender = _StubSender()
    sender.resets = 0
    sender.reset_credits = lambda: setattr(
        sender, "resets", sender.resets + 1)
    park = ParkController(comms, "actor-0", stop, sub=sub, sender=sender,
                          sleep=lambda s: None)
    park._last_params = -1e9                   # long stale
    sub.queue.append((9, {"w": 9}, resume_epoch))
    got = park.park_and_rejoin()
    assert got == (9, {"w": 9})
    assert park.rejoins == 1
    assert sender.resets == (1 if expect_reset else 0)
    if expect_reset:
        assert park.restarts_seen == 1 and park.stall_resumes == 0
    else:
        assert park.stall_resumes == 1 and park.restarts_seen == 0


def test_park_unstamped_stream_keeps_legacy_reset():
    """A pre-fencing learner (no epoch stamps) must keep today's
    conservative behavior: every rejoin resets the credit window."""
    import unittest.mock as mock

    with mock.patch("apex_tpu.runtime.transport.barrier_wait",
                    return_value=True):
        comms = CommsConfig(park_after_s=0.0)
        stop = threading.Event()
        sub = _EpochSub()                      # epoch stays 0
        sender = _StubSender()
        sender.resets = 0
        sender.reset_credits = lambda: setattr(
            sender, "resets", sender.resets + 1)
        park = ParkController(comms, "actor-0", stop, sub=sub,
                              sender=sender, sleep=lambda s: None)
        park._last_params = -1e9
        sub.queue.append((3, {"w": 3}, 0))
        assert park.park_and_rejoin() == (3, {"w": 3})
        assert sender.resets == 1


# -- registry reactions (PR 8) -----------------------------------------------

def test_registry_dead_fraction_counts_roles_separately():
    t = [0.0]
    comms = CommsConfig(suspect_after_s=2.0, dead_after_s=5.0)
    reg = FleetRegistry(comms, clock=lambda: t[0])
    reg.observe(Heartbeat("actor-0", role="actor"))
    reg.observe(Heartbeat("actor-1", role="actor"))
    reg.observe(Heartbeat("replay-0", role="replay"))
    assert reg.dead_fraction() == 0.0
    t[0] = 20.0
    reg.tick()                                  # everyone DEAD
    reg.observe(Heartbeat("actor-1", role="actor"))   # one actor back
    assert reg.dead_fraction() == pytest.approx(0.5)
    assert reg.dead_fraction(roles=("replay",)) == 1.0
    assert reg.dead_fraction(roles=("evaluator",)) == 0.0   # none seen


def test_rejoin_barrier_admits_late_peers():
    from apex_tpu.runtime import transport

    comms = _comms()
    rb = transport.RejoinBarrier(comms)
    rb.start()
    try:
        assert transport.barrier_wait(comms, "late-actor", timeout_s=10)
        assert transport.barrier_wait(comms, "respawned-actor",
                                      timeout_s=10)
        deadline = time.monotonic() + 5
        while rb.admitted < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rb.admitted == 2
    finally:
        rb.stop()


def test_heartbeat_resend_and_reroute_counters_reach_snapshot():
    reg = FleetRegistry(CommsConfig())
    reg.observe(Heartbeat("actor-0", role="actor", resends=4, rerouted=2))
    peer = reg.snapshot()["peers"][0]
    assert peer["resends"] == 4 and peer["rerouted"] == 2


# -- ack withholding (learner ingress fault) ---------------------------------

def test_ack_withholding_delays_acks_but_loses_no_chunk(monkeypatch):
    """The seeded ingress fault: acks for a scheduled chunk window park
    for hold_s, the sender's credit window exhausts (bounded sends fail
    and are RETRIED — counted as resends), then the withheld acks
    release and everything recovers with zero chunk loss."""
    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    monkeypatch.setenv("CHAOS_SEED", "5")
    monkeypatch.setenv(
        "CHAOS_SPEC",
        '{"ack_withhold": {"at": 0, "n": 2, "hold_s": 1.0}}')
    comms = _comms(max_outstanding_sends=2)
    recv = ChunkReceiver(comms, queue_depth=8, n_decoders=1)
    recv.start()
    sender = ChunkSender(comms, "actor-0")
    try:
        assert sender.send_chunk({"i": 0})
        assert sender.send_chunk({"i": 1})     # window now full, acks parked
        deadline = time.monotonic() + 10
        while recv.acks_withheld < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert recv.acks_withheld == 2
        # no credit: the bounded send fails and the caller retries
        assert not sender.send_chunk({"i": 2}, max_wait_s=0.2)
        sender.note_resend()
        ok = False
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:     # hold_s elapses mid-loop
            if sender.send_chunk({"i": 2}, max_wait_s=0.5):
                ok = True
                break
            sender.note_resend()
        assert ok, "withheld acks never released"
        got = [recv.chunks.get(timeout=5) for _ in range(3)]
        assert [g["i"] for g in got] == [0, 1, 2]   # delayed, never lost
        assert sender.resends >= 1
        # every chunk eventually acked — the window fully recovered
        deadline = time.monotonic() + 10
        while sender.acks_received < 3 and time.monotonic() < deadline:
            sender._drain_acks(50)
        assert sender.acks_received == 3
    finally:
        sender.close(drain_s=0)
        recv.stop()


# -- elastic scale supervision (PR 8) ----------------------------------------

def test_scale_decision_table():
    from apex_tpu.fleet.supervise import scale_decision

    assert scale_decision(0.9, 4, 1, 8) == 3    # drain-bound: retire one
    assert scale_decision(0.05, 4, 1, 8) == 5   # learner starving: add one
    assert scale_decision(0.3, 4, 1, 8) == 4    # healthy band: hold
    assert scale_decision(None, 4, 1, 8) == 4   # unreadable signal: hold
    assert scale_decision(0.9, 1, 1, 8) == 1    # clamped at the floor
    assert scale_decision(0.0, 8, 1, 8) == 8    # clamped at the ceiling


class _FakeChild:
    def __init__(self, cmd, env):
        self.cmd, self.env = cmd, env
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        self.rc = -15


def test_scale_supervisor_spawns_substitutes_and_scales():
    from apex_tpu.fleet.supervise import ScaleSupervisor

    spawned: list[_FakeChild] = []

    def spawn(cmd, env):
        child = _FakeChild(cmd, env)
        spawned.append(child)
        return child

    probes = [0.05, 0.9]                        # starving, then drain-bound
    sup = ScaleSupervisor(["run", "--actor-id", "{slot}"], n_min=2,
                          n_max=4, probe=lambda: probes.pop(0),
                          spawn=spawn)
    sup._apply_target()
    assert sorted(sup.children) == [0, 1]
    assert spawned[0].cmd == ["run", "--actor-id", "0"]
    assert spawned[1].cmd == ["run", "--actor-id", "1"]
    assert spawned[0].env["APEX_RESPAWN_COUNT"] == "0"

    sup.tick()                                  # 0.05 -> scale up to 3
    assert sup.target == 3 and sorted(sup.children) == [0, 1, 2]
    assert sup.scale_ups == 1

    sup.children[1].rc = 137                    # a chaos kill: respawn
    sup.tick()                                  # 0.9 -> scale down to 2
    assert sup.target == 2 and sorted(sup.children) == [0, 1]
    assert sup.scale_downs == 1
    respawned = [c for c in spawned if c.cmd == ["run", "--actor-id", "1"]]
    assert len(respawned) == 2                  # original + one respawn
    assert respawned[1].env["APEX_RESPAWN_COUNT"] == "1"
    highest = [c for c in spawned if c.cmd == ["run", "--actor-id", "2"]]
    assert highest[0].terminated                # scale-down retires slot 2


def test_fleet_drain_frac_probe_reads_trainer_summary():
    """The scale supervisor's backpressure probe: one status round-trip
    to a server whose snapshot_fn is the trainer's fleet summary."""
    from apex_tpu.fleet.supervise import fleet_drain_frac

    comms = _comms()
    reg = FleetRegistry(comms)
    srv = FleetStatusServer(
        comms, reg,
        snapshot_fn=lambda: {"peers": [],
                             "metrics": {"actor_drain_frac": 0.42}})
    srv.start()
    try:
        got = fleet_drain_frac(learner_ip="127.0.0.1",
                               status_port=comms.status_port)
        assert got == pytest.approx(0.42)
    finally:
        srv.stop()


class _NullPool:
    """Interface-complete pool stub for trainer-level reaction tests."""

    procs: list = []

    def start(self):
        pass

    def cleanup(self):
        pass

    def poll_chunks(self, n, timeout=0.0):
        return []

    def poll_stats(self):
        return []

    def publish_params(self, version, params):
        pass


def test_learner_relaxes_and_restores_floor_on_dead_actor_capacity():
    """The registry-reaction loop closed (tentpole leg 1): with half the
    actor fleet DEAD the replay-ratio floor relaxes (the effective floor
    reads None), and it restores when the peers rejoin.  The reaction
    state and the dead fraction surface in fleet_summary."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config()
    cfg = cfg.replace(comms=dataclasses.replace(
        cfg.comms, relax_floor_dead_frac=0.5))
    trainer = ApexTrainer(cfg, pool=_NullPool(), respawn_workers=False,
                          train_ratio=8.0, min_train_ratio=0.5)
    t = [0.0]
    reg = FleetRegistry(cfg.comms, clock=lambda: t[0])
    trainer.fleet = reg
    reg.observe(Heartbeat("actor-0", role="actor"))
    reg.observe(Heartbeat("actor-1", role="actor"))
    trainer._react_to_fleet(0)
    assert not trainer._floor_relaxed
    assert trainer._min_ratio_effective() == 0.5

    t[0] = 100.0
    reg.tick()                                   # both DEAD
    reg.observe(Heartbeat("actor-1", role="actor"))  # one rejoins
    assert reg.dead_fraction() == pytest.approx(0.5)
    trainer._react_to_fleet(0)
    assert trainer._floor_relaxed
    assert trainer._min_ratio_effective() is None
    assert trainer.floor_relaxes == 1

    reg.observe(Heartbeat("actor-0", role="actor"))  # capacity back
    trainer._react_to_fleet(0)
    assert not trainer._floor_relaxed
    assert trainer._min_ratio_effective() == 0.5

    summary = trainer.fleet_summary()["metrics"]
    assert summary["floor_relaxes"] == 1
    assert summary["floor_relaxed"] is False
    assert summary["dead_actor_frac"] == 0.0
    assert summary["learner_epoch"] == 1


def test_learner_epoch_survives_and_bumps_through_restore(tmp_path):
    """Epoch fencing through --restore: each restored life is one epoch
    past the checkpoint's writer, monotonically, including pre-fencing
    checkpoints (no learner_epoch in meta -> restore as life 2)."""
    from apex_tpu.config import small_test_config
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config()
    trainer = ApexTrainer(cfg, pool=_NullPool(), respawn_workers=False,
                          checkpoint_dir=str(tmp_path))
    assert trainer.learner_epoch == 1            # first life
    trainer.save_checkpoint()
    trainer.restore()
    assert trainer.learner_epoch == 2            # restart bumps
    trainer.steps_rate.total += 1                # a newer checkpoint
    trainer.save_checkpoint()
    trainer.restore()
    assert trainer.learner_epoch == 3            # monotone across lives
    # a pre-fencing checkpoint (no epoch key) restores as life 2
    trainer._apply_counters({"ingested": 0, "steps": 0,
                             "param_version": 0})
    assert trainer.learner_epoch == 2


# -- HTTP metrics sidecar (PR 6 follow-up) -----------------------------------

def test_http_metrics_sidecar_round_trip():
    import urllib.request

    from apex_tpu.obs.metrics import make_http_sidecar

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-5", role="actor", fps=9.0))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    http_port = _free_ports(1)[0]
    sidecar = make_http_sidecar(comms, port=http_port,
                                learner_ip="127.0.0.1", bind="127.0.0.1")
    t = threading.Thread(target=sidecar.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "# TYPE apex_fleet_alive gauge" in body
        assert 'apex_fleet_peer_fps{identity="actor-5"} 9.0' in body
        # non-metrics paths 404 instead of scraping
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/nope", timeout=10)
    finally:
        sidecar.shutdown()
        sidecar.server_close()
        srv.stop()


def test_http_metrics_sidecar_503_when_learner_gone():
    import urllib.error
    import urllib.request

    from apex_tpu.obs.metrics import make_http_sidecar

    comms = _comms()                            # nothing listening
    http_port = _free_ports(1)[0]
    sidecar = make_http_sidecar(comms, port=http_port,
                                learner_ip="127.0.0.1", bind="127.0.0.1",
                                timeout_s=0.3)
    t = threading.Thread(target=sidecar.serve_forever, daemon=True)
    t.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics", timeout=10)
        assert exc.value.code == 503
    finally:
        sidecar.shutdown()
        sidecar.server_close()


# -- adapters ---------------------------------------------------------------

def test_socket_adapters_expose_fleet_hooks():
    """The roles.py adapters surface wire counters and park state to the
    worker loops' HeartbeatEmitter without the loops knowing about
    sockets."""
    from apex_tpu.runtime.roles import _ChunkQueueAdapter, _ParamQueueAdapter

    comms = _comms()
    stop = threading.Event()

    class _Sub:
        def poll(self, timeout_ms=0):
            return None

    sender = _StubSender()
    park = ParkController(comms, "actor-0", stop, sub=_Sub(), sender=sender)
    chunk_ad = _ChunkQueueAdapter(sender, stop, park=park)
    param_ad = _ParamQueueAdapter(_Sub(), park=park)
    assert chunk_ad.wire_counters() == {"chunks_sent": 0,
                                        "acks_received": 0,
                                        "resends": 0, "rerouted": 0}
    assert param_ad.park_state() == (False, 0)
    chunk_ad.put(("chunk", 0, {"n": 1}))
    assert sender.sent == [{"n": 1}]
    assert chunk_ad.wire_counters()["chunks_sent"] == 1
