"""Fleet control plane (apex_tpu/fleet): registry machine, heartbeats,
park-and-rejoin, the chaos harness, the restricted wire, and the host
supervisor.

Everything here is tier-1: deterministic (fake clocks / seeded schedules)
and fast (the socket tests run whole learner-death dramas in-process on
localhost with sub-second thresholds).  The multi-process SIGKILL soak
lives in ``tests/test_fleet_rejoin.py`` behind ``-m slow``.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time

import pytest

from apex_tpu.config import CommsConfig
from apex_tpu.fleet.chaos import (ChaosChunkSender, ChaosConfig,
                                  ChaosParamPublisher, chaos_from_env)
from apex_tpu.fleet.heartbeat import Heartbeat, HeartbeatEmitter
from apex_tpu.fleet.park import ParkController
from apex_tpu.fleet.registry import (ALIVE, DEAD, JOINING, SUSPECT,
                                     FleetRegistry, FleetStatusServer,
                                     format_fleet_table, status_request)
from apex_tpu.fleet.supervise import supervise


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _comms(**overrides) -> CommsConfig:
    batch, param, barrier, status = _free_ports(4)
    return CommsConfig(batch_port=batch, param_port=param,
                       barrier_port=barrier, status_port=status,
                       **overrides)


# -- registry state machine -------------------------------------------------

def test_registry_state_machine_and_rejoin_accounting():
    """JOINING -> ALIVE -> SUSPECT -> DEAD -> ALIVE under a fake clock;
    DEAD->ALIVE counts as a rejoin, SUSPECT->ALIVE recovery does not."""
    t = [0.0]
    comms = CommsConfig(suspect_after_s=2.0, dead_after_s=5.0)
    reg = FleetRegistry(comms, clock=lambda: t[0])

    reg.observe(Heartbeat("actor-0", fps=100.0, param_version=3))
    assert reg.peers["actor-0"].state == ALIVE
    assert ("actor-0", JOINING, ALIVE) in reg.tick()

    t[0] = 3.0                              # silent past suspect_after_s
    assert ("actor-0", ALIVE, SUSPECT) in reg.tick()
    reg.observe(Heartbeat("actor-0"))       # recovery: NOT a rejoin
    assert reg.peers["actor-0"].state == ALIVE
    assert reg.metrics()["rejoins"] == 0

    t[0] = 10.0                             # silent past dead_after_s
    trans = reg.tick()
    assert ("actor-0", ALIVE, SUSPECT) in trans
    assert ("actor-0", SUSPECT, DEAD) in trans
    assert reg.metrics()["dead"] == 1 and reg.metrics()["deaths"] == 1

    reg.observe(Heartbeat("actor-0"))       # back from the dead: a rejoin
    m = reg.metrics()
    assert m["alive"] == 1 and m["dead_to_alive"] == 1 and m["rejoins"] == 1


def test_registry_merges_self_reported_rejoins_and_seen_liveness():
    """fleet_rejoins survives a learner restart: a FRESH registry credits
    the fleet's self-reported park->resume cycles; chunk-arrival times
    (observe_seen) keep a stat-dropping peer alive."""
    t = [0.0]
    comms = CommsConfig(suspect_after_s=2.0, dead_after_s=5.0)
    reg = FleetRegistry(comms, clock=lambda: t[0])
    reg.observe(Heartbeat("actor-0", rejoins=1))
    reg.observe(Heartbeat("actor-1", rejoins=1))
    assert reg.rejoins() == 2               # no DEAD->ALIVE seen here

    # chunks keep flowing while heartbeats drop: stays ALIVE
    t[0] = 4.0
    reg.observe_seen({"actor-0": 3.9})
    trans = reg.tick()
    assert ("actor-1", ALIVE, SUSPECT) in trans
    assert reg.peers["actor-0"].state == ALIVE

    # a DEAD peer revived by message arrival also counts as a rejoin
    t[0] = 20.0
    reg.tick()
    assert reg.peers["actor-0"].state == DEAD
    reg.observe_seen({"actor-0": 20.0})
    assert reg.peers["actor-0"].state == ALIVE
    assert reg.rejoins() == 3


def test_registry_gap_percentiles_and_table():
    t = [0.0]
    reg = FleetRegistry(CommsConfig(), clock=lambda: t[0])
    for i in range(1, 11):
        t[0] = float(i)
        reg.observe(Heartbeat("actor-0", fps=50.0))
    m = reg.metrics()
    assert m["hb_gap_p50_s"] == pytest.approx(1.0)
    assert m["hb_gap_p99_s"] == pytest.approx(1.0)
    table = format_fleet_table(reg.snapshot())
    assert "actor-0" in table and "ALIVE" in table and "rejoins" in table


def test_heartbeat_emitter_cadence_and_hooks():
    t = [0.0]
    beats = []
    em = HeartbeatEmitter(
        "actor-7", role="actor", interval_s=2.0,
        counters_fn=lambda: {"chunks_sent": 42, "acks_received": 40},
        park_fn=lambda: (True, 3), clock=lambda: t[0])
    assert em.maybe_beat(1) is None         # not due yet
    t[0] = 2.5
    em.tick(50)
    hb = em.maybe_beat(9)
    assert hb is not None and hb.identity == "actor-7"
    assert hb.param_version == 9 and hb.chunks_sent == 42
    assert hb.parked and hb.rejoins == 3
    assert hb.fps == pytest.approx(50 / 2.5, rel=0.01)
    assert em.maybe_beat(9) is None         # window reset
    beats.append(hb)


# -- restricted wire --------------------------------------------------------

def test_wire_roundtrips_every_message_type():
    import numpy as np

    from apex_tpu.actors.pool import ActorTimingStat, EpisodeStat
    from apex_tpu.runtime import wire

    msgs = [
        ("chunk", {"payload": {"frames": np.zeros((4, 3), np.uint8)},
                   "priorities": np.ones(4, np.float32), "n_trans": 4}),
        ("stat", EpisodeStat(1, 2.5, 30, 7)),
        ("stat", ActorTimingStat(0, 100.0, .1, .2, .3, .4, 256, True)),
        ("stat", Heartbeat("actor-0", fps=12.5, chunks_sent=3)),
        (5, {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}),
        np.float32(1.5),
    ]
    for msg in msgs:
        got = wire.restricted_loads(wire.dumps(msg))
        assert type(got) is type(msg)


def test_wire_rejects_non_allowlisted_globals():
    import os
    import pickle

    from apex_tpu.runtime import wire

    class Evil:
        def __reduce__(self):
            return (os.system, ("true",))

    with pytest.raises(wire.WireRejected):
        wire.restricted_loads(pickle.dumps(Evil()))
    # even benign-but-unlisted classes are rejected: allowlist, not
    # blocklist
    with pytest.raises(wire.WireRejected):
        wire.restricted_loads(pickle.dumps(CommsConfig()))


def test_receiver_counts_and_drops_rejected_payloads():
    """A hostile payload on the chunk socket costs one message (counted),
    earns no ack, and the pipe keeps working for honest peers."""
    import pickle

    import zmq

    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    comms = _comms()
    recv = ChunkReceiver(comms, queue_depth=8)
    recv.start()
    try:
        evil = zmq.Context.instance().socket(zmq.DEALER)
        evil.setsockopt(zmq.IDENTITY, b"mallory")
        evil.connect(f"tcp://127.0.0.1:{comms.batch_port}")

        class Evil:
            def __reduce__(self):
                import os
                return (os.system, ("true",))

        evil.send(pickle.dumps(("chunk", Evil())))
        deadline = time.monotonic() + 10
        while recv.rejected == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert recv.rejected == 1
        evil.close(linger=0)

        s = ChunkSender(comms, "actor-0")
        assert s.send_chunk({"n": 1})
        assert recv.chunks.get(timeout=5.0) == {"n": 1}
        s.close()
    finally:
        recv.stop()


# -- chaos harness ----------------------------------------------------------

class _StubSender:
    def __init__(self):
        self.sent = []
        self.chunks_sent = 0
        self.acks_received = 0

    def send_chunk(self, msg, stop_event=None, max_wait_s=None):
        self.sent.append(msg)
        self.chunks_sent += 1
        return True

    def send_stat(self, stat):
        pass

    def reset_credits(self):
        pass

    def close(self, *a, **kw):
        pass


def test_chaos_schedule_is_deterministic_per_identity():
    """Same seed + identity -> the same per-message drop/delay decisions,
    run after run; a different identity draws a different stream."""
    spec = {"drop_frac": 0.3, "delay_frac": 0.2, "delay_s": 0.0}

    def fates(identity, seed=7):
        plan = ChaosConfig(seed, spec).plan_for(identity)
        inner = _StubSender()
        cs = ChaosChunkSender(inner, plan, sleep=lambda s: None)
        fate = []
        for i in range(200):
            before = len(inner.sent)
            delayed_before = cs.delayed
            cs.send_chunk({"i": i})
            fate.append(("drop" if len(inner.sent) == before else
                         "delay" if cs.delayed > delayed_before else "send"))
        return fate

    a1, a2 = fates("actor-0"), fates("actor-0")
    assert a1 == a2
    assert fates("actor-1") != a1
    assert 30 < a1.count("drop") < 90          # ~0.3 of 200


def test_chaos_kill_disarms_on_respawned_lives(monkeypatch):
    """APEX_RESPAWN_COUNT>0 (exported by the supervisor) disarms kill
    entries so a deterministic kill-at-N cannot become a kill loop;
    drop/delay schedules stay live."""
    monkeypatch.setenv("CHAOS_SEED", "3")
    monkeypatch.setenv("CHAOS_SPEC",
                       '{"kill": {"actor-0": 5}, "drop_frac": 0.5}')
    cfg = chaos_from_env()
    assert cfg.plan_for("actor-0").kill_at == 5
    assert cfg.plan_for("actor-1").kill_at is None

    monkeypatch.setenv("APEX_RESPAWN_COUNT", "1")
    cfg = chaos_from_env()
    assert cfg.plan_for("actor-0").kill_at is None
    assert cfg.plan_for("actor-0").drop_frac == 0.5

    monkeypatch.setenv("CHAOS_SEED", "")      # empty string = chaos off
    assert chaos_from_env() is None


def test_chaos_publisher_stall_schedule():
    class _StubPub:
        def __init__(self):
            self.published = []

        def publish(self, version, params):
            self.published.append(version)

        def close(self):
            pass

    slept = []
    plan = ChaosConfig(1, {"stall_at": 2, "stall_s": 1.5}).plan_for(
        "learner")
    pub = ChaosParamPublisher(_StubPub(), plan, sleep=slept.append)
    for v in range(5):
        pub.publish(v, None)
    assert pub.inner.published == [0, 1, 2, 3, 4]   # stall delays, never drops
    assert slept == [1.5] and pub.stalls == 1


def test_chaos_drop_frac_over_real_sockets():
    """Dropped chunks consume no credit: with drop_frac=0.5 a
    window-of-3 sender still completes 40 sends, and the receiver gets
    exactly the non-dropped ones."""
    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    comms = _comms()
    recv = ChunkReceiver(comms, queue_depth=64)
    recv.start()
    try:
        plan = ChaosConfig(11, {"drop_frac": 0.5}).plan_for("actor-0")
        cs = ChaosChunkSender(ChunkSender(comms, "actor-0"), plan)
        for i in range(40):
            assert cs.send_chunk({"i": i})
        assert 5 < cs.dropped < 35
        expected = 40 - cs.dropped
        got = []
        deadline = time.monotonic() + 15
        while len(got) < expected and time.monotonic() < deadline:
            try:
                got.append(recv.chunks.get(timeout=0.5))
            except Exception:
                pass
        assert len(got) == expected
        cs.close()
    finally:
        recv.stop()


# -- park-and-rejoin --------------------------------------------------------

def test_park_controller_parks_and_rejoins_respawned_learner():
    """The whole drama in-process: params flow, the 'learner' dies (stops
    publishing), the actor parks; a 'respawned learner' re-releases the
    barrier and publishes — the parked actor reattaches in under a
    second, its credit window reset, rejoins counted."""
    from apex_tpu.runtime.transport import (ChunkSender, ParamPublisher,
                                            ParamSubscriber,
                                            barrier_release)

    comms = _comms(park_after_s=0.3, rejoin_backoff_s=0.05,
                   rejoin_backoff_max_s=0.2, rejoin_attempt_s=0.5)
    stop = threading.Event()
    sub = ParamSubscriber(comms)
    sender = ChunkSender(comms, "actor-0")
    park = ParkController(comms, "actor-0", stop, sub=sub, sender=sender)

    pub1 = ParamPublisher(comms)
    try:
        time.sleep(0.2)                       # SUB connect (slow joiner)
        pub1.publish(1, {"w": 1})
        deadline = time.monotonic() + 5
        got = None
        while got is None and time.monotonic() < deadline:
            got = sub.poll(100)
        assert got is not None and got[0] == 1
        park.note_params()
        pub1.close()                          # learner dies

        # wedge the window as an in-flight send would leave it
        sender._in_flight = sender.max_outstanding

        result = {}

        def parked_actor():
            result["got"] = park.park_and_rejoin()

        t = threading.Thread(target=parked_actor, daemon=True)
        time.sleep(0.4)                       # past park_after_s
        assert park.stale()
        t.start()
        time.sleep(0.3)
        assert park.parked

        # respawned learner: barrier for 1 peer, then first publish
        released = {}

        def learner2():
            released["n"] = barrier_release(comms, 1, timeout_s=10)
            pub2 = ParamPublisher(comms)
            try:
                end = time.monotonic() + 5
                while not result and time.monotonic() < end:
                    pub2.publish(2, {"w": 2})
                    time.sleep(0.05)
            finally:
                pub2.close()

        lt = threading.Thread(target=learner2, daemon=True)
        lt.start()
        t.join(timeout=15)
        lt.join(timeout=15)
        assert not t.is_alive(), "actor never rejoined"
        assert released["n"] == 1, "rejoin hello never reached the barrier"
        assert result["got"] is not None and result["got"][0] >= 2
        assert park.rejoins == 1 and not park.parked
        assert sender._in_flight == 0, "credit window not reset on rejoin"
        # the rejoin stashed the params for the adapter's next poll
        assert park.take_pending() is not None
        assert park.take_pending() is None
    finally:
        stop.set()
        sender.close(drain_s=0)
        sub.close()


def test_park_controller_does_not_park_while_params_flow():
    """Wedge-path false alarm guard: a backpressured-but-alive learner
    keeps publishing, so park_and_rejoin probes, stashes the params, and
    returns without parking or resetting credits."""
    from apex_tpu.runtime.transport import ParamPublisher, ParamSubscriber

    comms = _comms(park_after_s=0.2)
    stop = threading.Event()
    sub = ParamSubscriber(comms)
    park = ParkController(comms, "actor-0", stop, sub=sub)
    pub = ParamPublisher(comms)
    try:
        time.sleep(0.2)
        pub.publish(5, {"w": 5})
        time.sleep(0.3)                       # stale by clock, but a
        got = park.park_and_rejoin()          # publish is waiting
        assert got is not None and got[0] == 5
        assert park.parks == 0 and park.rejoins == 0
    finally:
        stop.set()
        pub.close()
        sub.close()


# -- status surface ---------------------------------------------------------

def test_status_server_round_trip():
    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-0", fps=123.0, param_version=4))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    try:
        snap = status_request(comms, learner_ip="127.0.0.1", timeout_s=5)
        assert snap is not None
        assert snap["peers"][0]["identity"] == "actor-0"
        assert snap["peers"][0]["fps"] == 123.0
        assert snap["metrics"]["alive"] == 1
    finally:
        srv.stop()


def test_status_cli_prints_fleet_table(capsys):
    from apex_tpu.runtime import cli

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-2", role="actor", fps=55.0))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    try:
        rc = cli.main(["--role", "status",
                       "--status-port", str(comms.status_port)])
        out = capsys.readouterr().out
        assert rc in (0, None)
        assert "actor-2" in out and "ALIVE" in out
    finally:
        srv.stop()


def test_metrics_scrape_round_trip():
    """The Prometheus surface on the SAME status REP socket: b"metrics"
    returns text exposition (trainer-provided metrics_fn, or the
    registry-only fallback), and the pickled snapshot path keeps working
    beside it."""
    from apex_tpu.obs.metrics import metrics_request

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-0", role="actor", fps=88.0, wall_ts=1.0))

    calls = []

    def metrics_fn():
        calls.append(1)
        return ("# TYPE apex_fleet_alive gauge\n"
                "apex_fleet_alive 1.0\n"
                "apex_custom_gauge 42.0\n")

    srv = FleetStatusServer(comms, reg, metrics_fn=metrics_fn)
    srv.start()
    try:
        text = metrics_request(comms, learner_ip="127.0.0.1", timeout_s=5)
        assert text is not None and calls == [1]
        assert "apex_fleet_alive 1.0" in text
        assert "apex_custom_gauge 42.0" in text
        # the snapshot request still answers on the same socket
        snap = status_request(comms, learner_ip="127.0.0.1", timeout_s=5)
        assert snap is not None
        assert snap["peers"][0]["identity"] == "actor-0"
        assert snap["peers"][0]["clock_offset_s"] is not None
    finally:
        srv.stop()


def test_metrics_scrape_registry_fallback_and_cli(capsys):
    """Without a metrics_fn the server renders a fleet-only exposition
    from the registry; `--role status --metrics` prints it."""
    from apex_tpu.runtime import cli

    comms = _comms()
    reg = FleetRegistry(comms)
    reg.observe(Heartbeat("actor-3", role="actor", fps=12.0))
    srv = FleetStatusServer(comms, reg)
    srv.start()
    try:
        rc = cli.main(["--role", "status", "--metrics",
                       "--status-port", str(comms.status_port)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "# TYPE apex_fleet_alive gauge" in out
        assert 'apex_fleet_peer_fps{identity="actor-3"} 12.0' in out
    finally:
        srv.stop()


# -- host supervisor --------------------------------------------------------

def test_supervisor_respawn_budget_and_backoff():
    """ActorPool semantics at process scale: short-lived crashes double
    the backoff and burn budget; exhausting the budget halts with rc=1;
    the respawn count is exported to each life."""
    t = [0.0]
    sleeps = []
    lives = []

    def fake_run(cmd, env):
        lives.append(int(env["APEX_RESPAWN_COUNT"]))
        t[0] += 1.0                     # every life dies after 1s
        return 9

    rc = supervise(["role"], max_respawns=3, window_s=600, min_uptime_s=60,
                   backoff_s=1.0, backoff_max_s=4.0,
                   sleep=sleeps.append, clock=lambda: t[0], run=fake_run)
    assert rc == 1
    assert lives == [0, 1, 2, 3]        # initial life + 3 budgeted respawns
    assert len(sleeps) == 3
    # exponential with jitter in [0.5, 1.5) of the doubling base
    assert 1.0 <= sleeps[0] / 1.0 + 0.5 and sleeps[1] >= sleeps[0] * 0.5


def test_supervisor_clean_exit_and_budget_refresh():
    t = [0.0]

    def run_clean(cmd, env):
        t[0] += 120.0
        return 0

    assert supervise(["role"], run=run_clean, clock=lambda: t[0],
                     sleep=lambda s: None) == 0

    # long-lived lives never exhaust the budget: the window refreshes
    calls = []

    def run_long_then_clean(cmd, env):
        calls.append(1)
        t[0] += 700.0                   # outlives the window every time
        return 0 if len(calls) >= 6 else 5

    rc = supervise(["role"], max_respawns=2, window_s=600,
                   min_uptime_s=60, run=run_long_then_clean,
                   clock=lambda: t[0], sleep=lambda s: None)
    assert rc == 0 and len(calls) == 6


def test_supervisor_cli_subprocess_end_to_end():
    """The real module entry: a child that always exits nonzero exhausts
    a budget of 1 quickly; the supervisor reports and exits 1."""
    import subprocess
    import sys

    p = subprocess.run(
        [sys.executable, "-m", "apex_tpu.fleet.supervise",
         "--max-respawns", "1", "--min-uptime", "0.01",
         "--backoff", "0.01", "--backoff-max", "0.02", "--",
         sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 1
    assert "crash loop" in p.stdout


def test_supervisor_cli_rejects_missing_command():
    import subprocess
    import sys

    p = subprocess.run([sys.executable, "-m", "apex_tpu.fleet.supervise"],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 2


# -- adapters ---------------------------------------------------------------

def test_socket_adapters_expose_fleet_hooks():
    """The roles.py adapters surface wire counters and park state to the
    worker loops' HeartbeatEmitter without the loops knowing about
    sockets."""
    from apex_tpu.runtime.roles import _ChunkQueueAdapter, _ParamQueueAdapter

    comms = _comms()
    stop = threading.Event()

    class _Sub:
        def poll(self, timeout_ms=0):
            return None

    sender = _StubSender()
    park = ParkController(comms, "actor-0", stop, sub=_Sub(), sender=sender)
    chunk_ad = _ChunkQueueAdapter(sender, stop, park=park)
    param_ad = _ParamQueueAdapter(_Sub(), park=park)
    assert chunk_ad.wire_counters() == {"chunks_sent": 0,
                                        "acks_received": 0}
    assert param_ad.park_state() == (False, 0)
    chunk_ad.put(("chunk", 0, {"n": 1}))
    assert sender.sent == [{"n": 1}]
    assert chunk_ad.wire_counters()["chunks_sent"] == 1
