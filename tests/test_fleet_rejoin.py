"""The chaos rejoin proof (ISSUE 5 acceptance): a seeded fault schedule
kills one actor and then the learner mid-run; the actor's host supervisor
respawns it, the restarted learner resumes from its newest checkpoint, and
every surviving role reattaches through the park/rejoin path — no operator
action anywhere.

Everything runs as real ``python -m apex_tpu.runtime`` subprocesses over
TCP, exactly the deploy topology: learner + 2 actors (actor-0 under
``python -m apex_tpu.fleet.supervise``) + 1 evaluator.  The learner's
periodic ``fleet_summary.json`` dumps are the observability spine: the
SIGKILLed phase-1 learner's last dump proves its registry saw actor-0 die
and rejoin (DEAD -> ALIVE), and the phase-2 learner's final dump proves
the whole fleet reattached with ``fleet_rejoins >= 2`` and the run
reaching its step target.

Only in-host worker death was covered before (tests/test_failure.py);
this is the cross-host story.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent

# the seeded schedule: actor-0 dies at its 5th chunk send (early, so its
# DEAD -> ALIVE rejoin is on the books well before the learner dies at
# its 150th param publish, ~30-60s in — checkpoints land every 20 steps
# long before that)
CHAOS_SEED = "7"
CHAOS_SPEC = '{"kill": {"actor-0": 5, "learner": 150}}'


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait(cond, timeout, what, also_check=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        if also_check is not None:
            also_check()
        time.sleep(0.5)
    pytest.fail(f"timed out waiting for {what}")


def _summary(logdir: Path) -> dict | None:
    path = logdir / "fleet_summary.json"
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None          # mid-replace read; the dump is atomic, retry


def test_chaos_kills_actor_and_learner_fleet_rejoins(tmp_path):
    batch, param, barrier, status = _free_ports(4)
    ckpt = tmp_path / "ckpt"
    log1, log2 = tmp_path / "log1", tmp_path / "log2"
    for d in (ckpt, log1, log2):
        d.mkdir()

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
        APEX_BATCH_PORT=str(batch), APEX_PARAM_PORT=str(param),
        APEX_BARRIER_PORT=str(barrier), APEX_STATUS_PORT=str(status),
        # snappy control plane so the drama fits a CI soak
        APEX_HEARTBEAT_INTERVAL="0.5", APEX_SUSPECT_AFTER="2",
        APEX_DEAD_AFTER="4", APEX_PARK_AFTER="5",
        CHAOS_SEED=CHAOS_SEED, CHAOS_SPEC=CHAOS_SPEC,
    )
    common = ["--env-id", "ApexCartPole-v0", "--frame-stack", "1",
              "--no-clip-rewards", "--no-episodic-life",
              "--n-actors", "2", "--n-evaluators", "1",
              "--warmup", "128", "--capacity", "2048",
              "--batch-size", "32", "--barrier-timeout", "180"]

    def runtime(*extra):
        return [sys.executable, "-m", "apex_tpu.runtime",
                *common, *extra]

    def learner_cmd(logdir, *extra):
        return runtime("--role", "learner", "--save-interval", "20",
                       "--train-ratio", "8", "--max-seconds", "600",
                       "--checkpoint-dir", str(ckpt),
                       "--logdir", str(logdir), *extra)

    procs: list[subprocess.Popen] = []

    def spawn(cmd, **kw):
        p = subprocess.Popen(cmd, env=dict(env, **kw.pop("extra_env", {})),
                             cwd=REPO, **kw)
        procs.append(p)
        return p

    learner = spawn(learner_cmd(log1, "--total-steps", "1000000"))
    # actor-0 under the real host supervisor: the chaos kill at chunk 5
    # exercises respawn + barrier-less rejoin; APEX_RESPAWN_COUNT from the
    # supervisor disarms the kill on the second life
    spawn([sys.executable, "-m", "apex_tpu.fleet.supervise",
           "--max-respawns", "5", "--window", "600",
           "--min-uptime", "0.5", "--backoff", "0.5",
           "--backoff-max", "1", "--",
           *runtime("--role", "actor", "--actor-id", "0")])
    spawn(runtime("--role", "actor", "--actor-id", "1"))
    spawn(runtime("--role", "evaluator", "--episodes", "0"))

    def learner_must_live():
        if learner.poll() is not None and learner.returncode != 137:
            pytest.fail(f"phase-1 learner died unexpectedly "
                        f"rc={learner.returncode}")

    try:
        # phase 1: fleet up, actor-0 chaos-killed + respawned -> the
        # learner's registry must record the DEAD -> ALIVE rejoin in its
        # periodic on-disk dump (which survives the learner's own death)
        _wait(lambda: (_summary(log1) or {}).get("metrics", {})
              .get("dead_to_alive", 0) >= 1,
              240, "phase-1 registry DEAD->ALIVE for chaos-killed actor-0",
              also_check=learner_must_live)

        # phase 2: the seeded schedule kills the learner at publish 150
        _wait(lambda: learner.poll() is not None, 240,
              "chaos learner kill (publish 150)")
        assert learner.returncode == 137, learner.returncode
        s1 = _summary(log1)
        assert s1 is not None and s1["metrics"]["dead_to_alive"] >= 1
        assert any(c.name.startswith("ckpt_")
                   or c.suffix for c in ckpt.iterdir()), \
            "no checkpoint on disk before the learner died"

        # restart from the newest checkpoint: 200 MORE steps, then a
        # clean exit.  The parked fleet (actor-1, evaluator, respawned
        # actor-0) must reattach on its own via the barrier/param race.
        learner2 = spawn(learner_cmd(log2, "--total-steps", "200",
                                     "--restore"),
                         extra_env={"APEX_RESPAWN_COUNT": "1"})
        _wait(lambda: learner2.poll() is not None, 420,
              "restarted learner completing its step target")
        assert learner2.returncode == 0, learner2.returncode

        s2 = _summary(log2)
        assert s2 is not None, "restarted learner wrote no fleet summary"
        m = s2["metrics"]
        # every surviving role reattached without operator action …
        assert m["peers"] >= 3, s2
        assert m["alive"] >= 2, s2
        # … and the fleet's self-reported park->resume cycles survive the
        # registry restart: at least actor-1 and the evaluator each
        # parked during the learner outage and rejoined
        assert m["rejoins"] >= 2, s2
        # the run resumed from the checkpoint and reached its target
        assert s2["steps"] >= 200, s2
        assert s2["steps"] >= s1["steps"], (s1["steps"], s2["steps"])
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 15
        for p in procs:
            if p.poll() is None and time.monotonic() < deadline:
                try:
                    p.wait(timeout=max(0.1,
                                       deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    pass
        for p in procs:
            if p.poll() is None:
                p.kill()
