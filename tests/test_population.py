"""Population plane (apex_tpu/population) — lineage roster + config
dispatch, controller exploit/explore/mutation under fake clocks,
population-of-1 parity with a plain run, checkpoint-copy epoch fencing
into a live learner, the learner ctl surface, tenant-partition snapshots
on the replay shards, per-tenant roster SLOs, and the CLI twins.

The load-bearing contract is population-of-1 TRANSPARENCY: one lineage
with no overrides configures exactly the plain single-tenant run
(identities, config, replay tree state, param wire), and the controller
never exploits a single-lineage ladder — several tests pin exactly that
next to the new multi-lineage behavior.
"""

from __future__ import annotations

import json
import os
import queue as queue_lib
import socket

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import drain_builder_chunks
from apex_tpu.config import ApexConfig, CommsConfig, small_test_config
from apex_tpu.fleet.registry import (FleetRegistry, FleetStatusServer,
                                     ctl_request, format_fleet_table,
                                     status_request)
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs.slo import SloEngine, SloKnobs, resolve_signal, roster_slos
from apex_tpu.population.controller import (PbtCtl, PopulationController,
                                            PopulationStat,
                                            format_population_lines,
                                            prometheus_sections,
                                            resolve_vector)
from apex_tpu.population.lineage import (HPARAM_BANDS, LineageSpec,
                                         apply_lineage, load_population)
from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.replay_service.service import (ReplayShardServer,
                                             snapshot_path_for)
from apex_tpu.replay_service.shard import ReplayShardCore
from apex_tpu.runtime import wire
from apex_tpu.tenancy import namespace as ns
from apex_tpu.training.apex import ApexTrainer

FRAME_SHAPE = (3,)
STACK = 2
K = 8
BATCH = 16


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _chunk_messages(seed: int, n_chunks: int) -> list[dict]:
    rng = np.random.default_rng(seed)
    builder = FrameChunkBuilder(2, 0.9, STACK, FRAME_SHAPE,
                                chunk_transitions=K, frame_margin=4,
                                frame_dtype=np.uint8)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        builder.begin_episode(rng.integers(0, 255, FRAME_SHAPE))
        ep_len = int(rng.integers(1, 3 * K))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 4)), float(rng.normal()),
                             rng.normal(size=4).astype(np.float32),
                             rng.integers(0, 255, FRAME_SHAPE),
                             terminated=t == ep_len - 1, truncated=False)
        msgs.extend(drain_builder_chunks(builder))
    return msgs[:n_chunks]


def _core(seed=0, quota=0, warmup=10_000) -> ReplayShardCore:
    replay = FramePoolReplay(capacity=64, frame_shape=FRAME_SHAPE,
                             frame_stack=STACK, frame_capacity=128,
                             frame_dtype="uint8")
    return ReplayShardCore(replay, jax.random.key(seed), batch_size=BATCH,
                           warmup=warmup, n_shards=1, strict_order=True,
                           quota=quota)


def _population() -> dict[str, LineageSpec]:
    return {
        "t0": LineageSpec(name="t0", env_id="ApexCatchSmall-v0",
                          lr=1e-4, prio_beta=0.4, eps_base=0.4),
        "c1": LineageSpec(name="c1", env_id="ApexCatchSmall-v0",
                          lr=2e-4, prio_beta=0.5, eps_base=0.3),
        "r0": LineageSpec(name="r0", env_id="ApexRallySmall-v0"),
    }


# -- roster + namespace merge ------------------------------------------------

def test_lineage_roster_and_namespace_merge():
    pop_json = json.dumps([
        {"name": "t0", "env_id": "ApexCatchSmall-v0", "lr": 1e-3,
         "n_steps": 2},
        {"name": "r0", "env_id": "ApexRallySmall-v0",
         "replay_quota": 4096, "parent": "t0", "generation": 3},
    ])
    pop = load_population(environ={"APEX_POPULATION": pop_json})
    assert set(pop) == {"t0", "r0"}
    assert pop["t0"].lr == 1e-3 and pop["t0"].n_steps == 2
    assert pop["r0"].generation == 3 and pop["r0"].parent == "t0"
    assert pop["r0"].replay_quota == 4096      # TenantSpec fields ride
    assert load_population(environ={}) == {}
    with pytest.raises(ValueError):
        load_population(environ={"APEX_POPULATION": json.dumps(
            [{"name": "a"}, {"name": "a"}])})
    with pytest.raises(ValueError):
        LineageSpec.from_dict({"name": "a", "nope": 1})

    # lineages ARE tenants: the shared planes admit them off the one
    # export, LineageSpec and all (partitions read the vector)
    roster = ns.load_roster(environ={"APEX_POPULATION": pop_json})
    assert set(roster) == {"t0", "r0"}
    assert isinstance(roster["r0"], LineageSpec)
    # an explicit APEX_TENANTS entry of the same name wins
    tenants_json = json.dumps([{"name": "r0",
                                "env_id": "ApexCartPole-v0"}])
    merged = ns.load_roster(environ={"APEX_TENANTS": tenants_json,
                                     "APEX_POPULATION": pop_json})
    assert merged["r0"].env_id == "ApexCartPole-v0"
    assert not isinstance(merged["r0"], LineageSpec)
    assert merged["t0"].env_id == "ApexCatchSmall-v0"
    # an inherited env id is defaulted for the admission plane
    bare = ns.load_roster(environ={"APEX_POPULATION": json.dumps(
        [{"name": "x"}])})
    assert bare["x"].env_id == ns.TenantSpec.env_id


def test_apply_lineage_dispatch_and_population_of_one_parity():
    cfg = ApexConfig()
    spec = LineageSpec(name="c1", env_id="ApexCatchSmall-v0", lr=1e-3,
                       n_steps=4, prio_alpha=0.7, prio_beta=0.6,
                       eps_base=0.2)
    out = apply_lineage(cfg, spec)
    assert out.env.env_id == "ApexCatchSmall-v0"
    assert out.learner.lr == 1e-3 and out.learner.n_steps == 4
    assert out.replay.alpha == 0.7 and out.replay.beta == 0.6
    assert out.actor.eps_base == 0.2
    # population-of-1 parity: a no-override lineage leaves the config
    # IDENTICAL — and the default tenant's identities stay bare, so a
    # one-lineage run is byte-for-byte the plain single-tenant run
    assert apply_lineage(cfg, LineageSpec(name="t0")) == cfg
    assert ns.qualify(ns.DEFAULT_TENANT, "actor-0") == "actor-0"
    assert LineageSpec(name="t0").hparams() == {
        k: None for k in HPARAM_BANDS}


# -- mutation ----------------------------------------------------------------

def test_resolve_vector_and_mutation_stays_in_bands():
    # unset fields resolve to band defaults, deterministically
    vec = resolve_vector(LineageSpec(name="x"))
    assert set(vec) == set(HPARAM_BANDS)
    assert isinstance(vec["n_steps"], int)
    for name, (lo, hi) in HPARAM_BANDS.items():
        assert lo <= vec[name] <= hi
    # explicit fields pass through
    assert resolve_vector(LineageSpec(name="x", lr=1e-3))["lr"] == 1e-3

    pop = {"a": LineageSpec(name="a", lr=1e-4, n_steps=3,
                            prio_alpha=0.6, prio_beta=0.4, eps_base=0.4)}
    c1 = PopulationController(pop, seed=11)
    c2 = PopulationController(pop, seed=11)
    base = resolve_vector(pop["a"])
    m1, notes1 = c1.mutate(dict(base))
    m2, _ = c2.mutate(dict(base))
    assert m1 == m2                     # seeded: deterministic
    assert notes1                       # something moved
    for name, (lo, hi) in HPARAM_BANDS.items():
        assert lo <= m1[name] <= hi     # clamped to the band
    assert isinstance(m1["n_steps"], int)
    assert abs(m1["n_steps"] - base["n_steps"]) <= 1
    # resample_prob=1: every field redraws uniformly from its band
    c3 = PopulationController(pop, seed=5, resample_prob=1.0)
    m3, notes3 = c3.mutate(dict(base))
    assert all("resample" in n for n in notes3)
    for name, (lo, hi) in HPARAM_BANDS.items():
        assert lo <= m3[name] <= hi


# -- the controller under fake clocks ----------------------------------------

def test_controller_exploit_explore_under_fake_clock():
    now = [1000.0]
    ctl = PopulationController(_population(), decide_every_s=10.0,
                               min_episodes=2, seed=3,
                               clock=lambda: now[0], wall=lambda: 7.0)
    # task ladders group by env id: 2 Catch lineages share one, Rally
    # is alone on its own
    assert ctl.ladders() == {"ApexCatchSmall-v0": ["c1", "t0"],
                             "ApexRallySmall-v0": ["r0"]}
    # below min_episodes nothing is judged
    ctl.observe("t0", alive=True, score=5.0, episodes=1,
                checkpoint="/ck/t0.msgpack")
    ctl.observe("c1", alive=True, score=-95.0, episodes=1)
    ctl.observe("r0", alive=True, score=1.0, episodes=9)
    assert ctl.tick() == []
    # donor has a checkpoint, loser is clearly behind -> one exploit
    ctl.observe("t0", alive=True, score=5.0, episodes=9, steps=120,
                checkpoint="/ck/t0.msgpack")
    ctl.observe("c1", alive=True, score=-95.0, episodes=9, steps=100)
    now[0] += 11.0
    cmds = ctl.tick()
    assert len(cmds) == 1
    lineage, cmd = cmds[0]
    assert lineage == "c1"
    assert cmd["op"] == "exploit"
    assert cmd["restore_from"] == "/ck/t0.msgpack"
    assert cmd["donor"] == "t0" and cmd["generation"] == 1
    assert set(cmd["hparams"]) == set(HPARAM_BANDS)
    events = [(e["event"], e["lineage"]) for e in ctl.timeline]
    assert ("EXPLOIT", "c1") in events and ("EXPLORE", "c1") in events
    assert ctl.exploits == 1 and ctl.explores == 1
    # lineage record advanced; the single-lineage Rally ladder is quiet
    assert ctl.lineages["c1"].generation == 1
    assert ctl.lineages["c1"].parent == "t0"
    assert ctl.lineages["t0"].exploits_donated == 1
    assert ctl.lineages["r0"].exploits_taken == 0
    # pacing + cooldown: the next period cannot re-exploit c1
    assert ctl.tick() == []                     # same period
    now[0] += 11.0
    assert ctl.tick() == []                     # cooldown (2 periods)
    # after the cooldown, a still-losing c1 exploits again
    now[0] += 21.0
    ctl.observe("c1", alive=True, score=-95.0, episodes=12)
    assert len(ctl.tick()) == 1
    assert ctl.lineages["c1"].generation == 2


def test_controller_gates_skips_and_flat_ladders():
    now = [0.0]
    pop = {"a": LineageSpec(name="a", env_id="E"),
           "b": LineageSpec(name="b", env_id="E")}
    ctl = PopulationController(pop, decide_every_s=5.0, min_episodes=2,
                               seed=1, clock=lambda: now[0],
                               wall=lambda: 0.0)
    # a flat ladder (scores within min_delta) never exploits
    ctl.observe("a", alive=True, score=1.0, episodes=5, checkpoint="/a")
    ctl.observe("b", alive=True, score=1.0, episodes=5, checkpoint="/b")
    assert ctl.tick() == []
    # a donor without a checkpoint defers (recorded, not silent)
    now[0] += 6.0
    ctl2 = PopulationController(pop, decide_every_s=5.0, min_episodes=2,
                                seed=1, clock=lambda: now[0],
                                wall=lambda: 0.0)
    ctl2.observe("a", alive=True, score=9.0, episodes=5)   # no ckpt
    ctl2.observe("b", alive=True, score=1.0, episodes=5)
    assert ctl2.tick() == []
    assert [e["event"] for e in ctl2.timeline] == ["SKIPPED"]
    # a dead lineage is never judged (and never exploited)
    ctl2.observe("b", alive=False)
    now[0] += 6.0
    assert ctl2.tick() == []


def test_population_of_one_never_exploits():
    now = [0.0]
    pop = {"solo": LineageSpec(name="solo", env_id="ApexCatchSmall-v0")}
    ctl = PopulationController(pop, decide_every_s=1.0, min_episodes=1,
                               seed=0, clock=lambda: now[0],
                               wall=lambda: 0.0)
    for _ in range(20):
        ctl.observe("solo", alive=True, score=3.0, episodes=50,
                    steps=1000, checkpoint="/ck")
        now[0] += 2.0
        assert ctl.tick() == []
    snap = ctl.snapshot()
    assert snap["exploits"] == 0 and snap["explores"] == 0
    assert snap["timeline"] == []
    assert snap["lineages"]["solo"]["generation"] == 0
    assert snap["lineages"]["solo"]["exploits_taken"] == 0


# -- snapshot schema + exposition + wire -------------------------------------

def test_population_snapshot_schema_exposition_and_wire():
    now = [0.0]
    ctl = PopulationController(_population(), decide_every_s=1.0,
                               min_episodes=1, seed=3,
                               clock=lambda: now[0], wall=lambda: 2.0)
    ctl.observe("t0", alive=True, score=4.0, episodes=6,
                checkpoint="/ck/t0")
    ctl.observe("c1", alive=True, score=-6.0, episodes=6)
    now[0] += 2.0
    assert ctl.tick()
    snap = ctl.snapshot()
    # tests pin this schema: the pbt-smoke drill asserts off it
    assert snap["kind"] == "apex_population" and snap["version"] == 1
    assert set(snap) >= {"lineages", "decisions", "exploits", "explores",
                         "timeline", "decide_every_s", "frac"}
    assert set(snap["lineages"]["c1"]) >= {
        "task", "alive", "score", "episodes", "steps", "generation",
        "parent", "exploits_taken", "exploits_donated", "checkpoint",
        "hparams"}
    e = snap["timeline"][0]
    assert set(e) >= {"t_s", "wall", "event", "lineage", "reason"}
    # wire-safe inside a PopulationStat
    stat = wire.restricted_loads(wire.dumps(PopulationStat("pbt-ctl",
                                                           snap)))
    assert stat.snapshot["exploits"] == 1
    # exposition rows ride registered families only (J015 contract)
    gauges, labeled = prometheus_sections(snap)
    assert gauges["population_lineages"] == 3
    assert gauges["population_exploits"] == 1
    for fam in list(gauges) + list(labeled):
        assert fam in obs_metrics.REGISTERED_FAMILIES, fam
    gens = dict((row[0]["lineage"], row[1])
                for row in labeled["population_lineage_generation"])
    assert gens["c1"] == 1
    lines = format_population_lines(snap)
    assert any("lineage c1" in ln and "gen=1" in ln for ln in lines)
    assert any("EXPLOIT c1" in ln for ln in lines)
    # the status table renders the section when present
    reg = FleetRegistry(CommsConfig())
    table_snap = reg.snapshot()
    table_snap["population"] = snap
    table = format_fleet_table(table_snap)
    assert "population: 3 lineage(s)" in table


# -- checkpoint-copy into a live learner + epoch fencing ---------------------

@pytest.fixture(scope="module")
def _trainers(tmp_path_factory):
    """Two small live learners (distinct seeds -> distinct params) with
    dummy pools; A has a checkpoint directory."""
    class _DummyPool:
        accepts_device_params = False

        def __init__(self):
            self.published = []
            self.epochs = []

        def publish_params(self, version, params):
            self.published.append(version)

        def set_learner_epoch(self, epoch):
            self.epochs.append(epoch)

    ck = tmp_path_factory.mktemp("pbt-ck")
    cfg_a = small_test_config()
    cfg_b = small_test_config()
    import dataclasses
    cfg_b = cfg_b.replace(env=dataclasses.replace(cfg_b.env, seed=777))
    a = ApexTrainer(cfg_a, pool=_DummyPool(), checkpoint_dir=str(ck))
    b = ApexTrainer(cfg_b, pool=_DummyPool())
    return a, b


def test_restore_weights_copies_params_and_bumps_epoch(_trainers):
    a, b = _trainers
    path = a.save_checkpoint()
    # distinct seeds -> distinct params before the copy
    la = jax.tree_util.tree_leaves(a.train_state.params)
    lb = jax.tree_util.tree_leaves(b.train_state.params)
    assert any(not np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))
    replay_before = b.replay_state
    key_before = np.asarray(jax.random.key_data(b.key)).copy()
    steps_before = b.steps_rate.total
    epoch_before = b.learner_epoch
    b.restore_weights(path)
    # the weight copy: params AND target AND optimizer state are the
    # donor's, bit for bit
    for x, y in zip(jax.tree_util.tree_leaves(a.train_state.params),
                    jax.tree_util.tree_leaves(b.train_state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(
            jax.tree_util.tree_leaves(a.train_state.target_params),
            jax.tree_util.tree_leaves(b.train_state.target_params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # ...while replay state, PRNG chain, and progress stay THIS life's
    assert b.replay_state is replay_before
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(b.key)), key_before)
    assert b.steps_rate.total == steps_before
    # the epoch fence bumped: the pre-copy life is a dead predecessor
    assert b.learner_epoch == epoch_before + 1
    # a replay shard rejects the pre-copy life's write-backs once it
    # has seen the post-copy epoch (the PR 8 fence, reused verbatim)
    core = _core(warmup=1)
    core.note_epoch(b.learner_epoch)
    assert not core.write_back(0, np.zeros(1, np.int32),
                               np.ones(1, np.float32),
                               epoch=epoch_before)
    assert core.stale_wb == 1


def test_apply_hparams_live_half(_trainers):
    _, b = _trainers
    fused_before = b._fused
    applied = b.apply_hparams({"lr": 1e-3, "prio_beta": 0.7,
                               "n_steps": 4, "prio_alpha": None})
    assert applied == {"lr": 1e-3, "prio_beta": 0.7}
    assert b.cfg.learner.lr == 1e-3
    assert b.cfg.replay.beta == 0.7
    assert b._beta(0) == 0.7            # the anneal re-pointed
    assert b._fused is not fused_before  # optimizer rebuilt + re-jitted
    assert b.core.optimizer is not None
    # the acting-side half is recorded for the next worker generation
    assert b.hparams_live["n_steps"] == 4
    assert "prio_alpha" not in applied


def test_ctl_queue_exploit_applies_and_publishes(_trainers):
    a, b = _trainers
    path = a.checkpointer.latest_path()
    assert path is not None
    b._ctl_queue = queue_lib.Queue(maxsize=8)
    info = b._enqueue_ctl({"op": "exploit", "restore_from": path,
                           "hparams": {"prio_beta": 0.6}, "donor": "t0"})
    assert info == {"accepted": True, "pending": 1}
    epoch_before = b.learner_epoch
    published_before = len(b.pool.published)
    b._drain_ctl(steps=0)
    rec = b._population_ctl
    assert rec["exploits"] == 1 and rec["applied"] == 1
    assert rec["last"]["op"] == "exploit"
    assert rec["last"]["learner_epoch"] == epoch_before + 1
    # the copied weights published promptly under the NEW epoch
    assert len(b.pool.published) == published_before + 1
    assert b.pool.epochs[-1] == b.learner_epoch
    # evidence rides fleet_summary metrics (checkpoint_latest is the
    # controller's donor-sourcing input; population_ctl the smoke's
    # applied-copy assert)
    a.fleet = FleetRegistry(a.cfg.comms)
    b.fleet = FleetRegistry(b.cfg.comms)
    assert a.fleet_summary()["metrics"]["checkpoint_latest"] == path
    mb = b.fleet_summary()["metrics"]
    assert mb["population_ctl"]["exploits"] == 1
    assert mb["hparams_live"]["prio_beta"] == 0.6
    # an unreadable donor path is counted evidence, never a crash
    b._enqueue_ctl({"op": "exploit", "restore_from": "/nope.msgpack"})
    b._drain_ctl(steps=0)
    assert b._population_ctl["errors"] == 1
    assert b.learner_epoch == epoch_before + 1      # no bump on failure


def test_ctl_exploit_pruned_path_falls_back_to_newest(_trainers, tmp_path):
    """The donor's Checkpointer prunes to its newest files; a command
    naming a pruned path restores the NEWEST donor checkpoint in the
    same directory instead of failing (live-rehearsal finding)."""
    import shutil

    a, b = _trainers
    src = a.checkpointer.latest_path()
    donor_dir = tmp_path / "donor"
    donor_dir.mkdir()
    shutil.copy(src, donor_dir / "ckpt_9.msgpack")
    b._ctl_queue = queue_lib.Queue(maxsize=8)
    b._enqueue_ctl({"op": "exploit", "donor": "t0",
                    "restore_from": str(donor_dir / "ckpt_1.msgpack")})
    errors_before = b._population_ctl["errors"]
    b._drain_ctl(steps=0)
    assert b._population_ctl["errors"] == errors_before
    assert b._population_ctl["last"]["restored_from"].endswith(
        "ckpt_9.msgpack")


def test_status_server_ctl_round_trip():
    comms = CommsConfig(status_port=_free_port())
    seen = []

    def ctl_fn(cmd):
        seen.append(cmd)
        return {"accepted": True, "echo": cmd["op"]}

    server = FleetStatusServer(comms, FleetRegistry(comms),
                               ctl_fn=ctl_fn)
    server.start()
    try:
        info = ctl_request(comms, {"op": "hparams",
                                   "hparams": {"lr": 1e-3}},
                           timeout_s=10.0)
        assert info == {"accepted": True, "echo": "hparams"}
        assert seen and seen[0]["hparams"]["lr"] == 1e-3
        # the plain status request still answers on the same socket
        snap = status_request(comms, timeout_s=10.0)
        assert snap is not None and "peers" in snap
    finally:
        server.stop()
    # a ctl-less server (pre-population learner) degrades a ctl frame
    # to a status reply — the controller reads "no ack", never wedges
    comms2 = CommsConfig(status_port=_free_port())
    server2 = FleetStatusServer(comms2, FleetRegistry(comms2))
    server2.start()
    try:
        assert ctl_request(comms2, {"op": "exploit"},
                           timeout_s=10.0) is None
    finally:
        server2.stop()


# -- satellite: tenant-partition snapshots on the replay shards --------------

def test_tenant_partition_snapshots_restore(tmp_path):
    comms = CommsConfig(replay_port_base=_free_port())
    specs = {"rally": ns.TenantSpec(name="rally")}

    def factory(tenant):
        spec = specs.get(tenant)
        return None if spec is None else _core(seed=1234)

    snap_dir = str(tmp_path)
    default_path = snapshot_path_for(snap_dir, 0)
    # naming pin: the default partition keeps the pre-tenancy file, a
    # tenant partition gets its own per-(shard, tenant) file
    assert default_path.endswith("replay_shard_0.msgpack")
    rally_path = snapshot_path_for(snap_dir, 0, tenant="rally")
    assert rally_path.endswith("replay_shard_0.rally.msgpack")

    server = ReplayShardServer(comms, 0, _core(seed=5),
                               bind_ip="127.0.0.1", heartbeat=False,
                               snapshot_path=default_path,
                               snapshot_s=0.01, tenant_factory=factory,
                               snapshot_dir=snap_dir)
    try:
        rally_core = server._core_for("rally")
        assert rally_core is not None
        for msg in _chunk_messages(11, 3):
            server.core.ingest_msg(dict(msg))
        for msg in _chunk_messages(12, 2):
            rally_core.ingest_msg(dict(msg))
        server._last_snapshot = 0.0         # force the cadence gate
        server._maybe_snapshot()
        assert os.path.exists(default_path)
        assert os.path.exists(rally_path)
        assert server.tenant_snapshots == {"rally": 1}
        assert server.stats()["tenant_snapshots"] == {"rally": 1}
        want_default = server.core.ingested
        want_rally = rally_core.ingested
        rally_leaves = [np.asarray(x).copy() for x in
                        jax.tree_util.tree_leaves(rally_core.state)]
    finally:
        server.close()

    # a respawned shard restores BOTH partitions warm: the default on
    # startup (the existing path), the tenant on first sight (lazily,
    # exactly where the partition builds)
    comms2 = CommsConfig(replay_port_base=_free_port())
    core2 = _core(seed=5)
    core2.restore_snapshot(default_path)
    server2 = ReplayShardServer(comms2, 0, core2, bind_ip="127.0.0.1",
                                heartbeat=False,
                                snapshot_path=default_path,
                                snapshot_s=0.01, tenant_factory=factory,
                                snapshot_dir=snap_dir)
    try:
        assert server2.core.ingested == want_default
        rally2 = server2._core_for("rally")
        assert rally2.ingested == want_rally
        assert rally2.restored == want_rally
        for a, b in zip(rally_leaves,
                        jax.tree_util.tree_leaves(rally2.state)):
            np.testing.assert_array_equal(a, np.asarray(b))
    finally:
        server2.close()


# -- satellite: per-tenant roster SLOs ---------------------------------------

def test_roster_slos_declared_and_judged():
    roster = {"c1": ns.TenantSpec(name="c1"),
              "r0": ns.TenantSpec(name="r0")}
    objs = roster_slos(roster, environ={})
    names = [o.name for o in objs]
    assert names == ["steps_floor@c1", "eval_score@c1",
                     "steps_floor@r0", "eval_score@r0"]
    by_name = {o.name: o for o in objs}
    # progress floor judged, eval score observe-only by default
    assert by_name["steps_floor@c1"].threshold == 0.01
    assert by_name["eval_score@c1"].threshold is None
    # env twins: one export sets the bar for EVERY roster tenant
    tuned = roster_slos(roster, environ={
        "APEX_SLO_TENANT_STEPS_RATE": "off",
        "APEX_SLO_TENANT_EVAL_SCORE": "1.5"})
    by_name = {o.name: o for o in tuned}
    assert by_name["steps_floor@r0"].threshold is None
    assert by_name["eval_score@r0"].threshold == 1.5
    # signals walk the controller's probe summary
    summary = {"tenants": {"c1": {"steps_rate": 2.5, "eval_score": 3.0},
                           "r0": {"steps_rate": None}}}
    assert resolve_signal(summary, "tenants.c1.steps_rate") == 2.5
    assert resolve_signal(summary, "tenants.r0.steps_rate") is None
    # a stalled lineage walks OK -> BURNING -> BREACHED under the
    # ordinary engine machinery (fake clocks, compressed knobs)
    now = [0.0]
    eng = SloEngine(roster_slos(roster, environ={}),
                    knobs=SloKnobs(fast=(10.0, 10.0), slow=(20.0, 20.0),
                                   page_burn=1.0, warn_burn=1.0,
                                   breach_after_s=4.0,
                                   resolve_after_s=5.0, ok_after_s=5.0,
                                   min_samples=1),
                    clock=lambda: now[0], wall=lambda: 0.0)
    stalled = {"tenants": {"c1": {"steps_rate": 0.0},
                           "r0": {"steps_rate": 5.0}}}
    for _ in range(40):
        now[0] += 5.0
        eng.sample(stalled)
        if eng.state_of("steps_floor@c1") == "BREACHED":
            break
    assert eng.state_of("steps_floor@c1") == "BREACHED"
    assert eng.state_of("steps_floor@r0") == "OK"


def test_pbt_ctl_probe_summary_shape():
    """The socket wrapper's SLO summary builder is pure given the
    controller's lineage states (no sockets needed to pin it)."""
    cfg = ApexConfig()
    pop = _population()
    ctl = PbtCtl.__new__(PbtCtl)        # state only; no sockets
    ctl.ctrl = PopulationController(pop, seed=0)
    ctl._probe_rates = {"t0": 1.5, "c1": None}
    ctl.ctrl.observe("t0", alive=True, score=2.0, episodes=3)
    del cfg
    summary = ctl._slo_summary()
    assert summary["tenants"]["t0"] == {"steps_rate": 1.5,
                                        "eval_score": 2.0}
    assert summary["tenants"]["c1"]["steps_rate"] is None
    assert set(summary["tenants"]) == {"t0", "c1", "r0"}


# -- CLI twins ---------------------------------------------------------------

def test_cli_pbt_flags_and_env_twins(monkeypatch):
    from apex_tpu.runtime.cli import build_parser

    args = build_parser().parse_args(["--role", "pbt-ctl"])
    assert args.role == "pbt-ctl"
    assert args.pbt_decide == 30.0 and args.pbt_frac == 0.25
    assert args.pbt_resample == 0.25 and args.pbt_min_episodes == 4
    assert args.save_interval == 5000

    monkeypatch.setenv("APEX_PBT_DECIDE_S", "10")
    monkeypatch.setenv("APEX_PBT_FRAC", "0.5")
    monkeypatch.setenv("APEX_PBT_RESAMPLE", "0.75")
    monkeypatch.setenv("APEX_PBT_MIN_EPISODES", "2")
    monkeypatch.setenv("APEX_SAVE_INTERVAL", "30")
    args = build_parser().parse_args([])
    assert args.pbt_decide == 10.0 and args.pbt_frac == 0.5
    assert args.pbt_resample == 0.75 and args.pbt_min_episodes == 2
    assert args.save_interval == 30
    # flags beat env twins
    args = build_parser().parse_args(["--pbt-decide", "99",
                                      "--save-interval", "77"])
    assert args.pbt_decide == 99.0 and args.save_interval == 77
    # the roster env twin feeds the same loader the CLI dispatch uses
    monkeypatch.setenv("APEX_POPULATION", json.dumps(
        [{"name": "z", "env_id": "ApexCatchSmall-v0", "lr": 1e-3}]))
    pop = load_population()
    assert pop["z"].lr == 1e-3
