"""Centralized batched inference plane (apex_tpu/infer_service).

The acceptance anchor is bit-parity: for identical params and key
chains, remote-served actions/chunks/priorities must be BIT-IDENTICAL to
the local-policy path — for even and odd B (uneven half-groups), through
real sockets, and through the local fallback (which makes a dead server
a scheduling event, never a trajectory fork).
"""

import socket
import threading
import time

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import actor_epsilons
from apex_tpu.actors.vector import VectorDQNWorkerFamily
from apex_tpu.config import CommsConfig, small_test_config
from apex_tpu.infer_service import (InferClient, InferServer,
                                    quantize_pow2)
from apex_tpu.infer_service.service import make_batched_policy
from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.ops.losses import make_optimizer
from apex_tpu.runtime import wire
from apex_tpu.training.apex import dqn_env_specs
from apex_tpu.training.state import create_train_state


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cfg(**comms_kw):
    cfg = small_test_config()
    return cfg.replace(comms=CommsConfig(infer_port=_free_port(),
                                         **comms_kw))


def _params(cfg, model_spec):
    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
    model = DuelingDQN(**model_spec)
    ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                            np.zeros((1,) + stacked, frame_dtype))
    return model, ts.params


def _serve(cfg, model, params, version=3, epoch=0):
    """A live InferServer on a background thread (tests drive params
    directly — the subscriber path is the same set_params call)."""
    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    if params is not None:
        server.set_params(version, params, epoch=epoch)
    stop = threading.Event()
    t = threading.Thread(target=server.run, kwargs={"stop_event": stop},
                         daemon=True)
    t.start()
    return server, stop, t


def _drive(fam, params, n_steps, seed=1):
    """Fixed key chain through n_steps vector steps; returns
    (stats, chunk messages incl. flush) — the test_vector contract."""
    fam.reset_all()
    key = jax.random.key(seed)
    stats, msgs = [], []
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        stats.extend(fam.step_all(params, k))
        msgs.extend(fam.poll_msgs())
    msgs.extend(m for b in fam.builders
                for m in ({"payload": c, "priorities": c.pop("priorities"),
                           "n_trans": int(c["n_trans"])}
                          for c in b.force_flush()))
    fam.close()
    return stats, msgs


def _family(cfg, model_spec, n_envs):
    return VectorDQNWorkerFamily(
        cfg, model_spec, seeds=[100 + i for i in range(n_envs)],
        slot_ids=list(range(n_envs)), epsilons=actor_epsilons(n_envs),
        chunk_transitions=16)


def _chunk_msgs_equal(a, b):
    assert len(a) == len(b)
    for ma, mb in zip(a, b):
        assert ma["n_trans"] == mb["n_trans"]
        np.testing.assert_array_equal(ma["priorities"], mb["priorities"])
        pa, pb = ma["payload"], mb["payload"]
        assert set(pa) == set(pb)
        for k in pa:
            np.testing.assert_array_equal(np.asarray(pa[k]),
                                          np.asarray(pb[k]),
                                          err_msg=f"payload[{k}] diverged")


# -- pow2 batch quantization -------------------------------------------------

def test_quantize_pow2_pins():
    assert [quantize_pow2(n, 16) for n in (1, 2, 3, 4, 5, 8, 9, 16, 40)] \
        == [1, 2, 4, 4, 8, 8, 16, 16, 16]
    assert quantize_pow2(7, 4) == 4          # cap wins
    assert quantize_pow2(0, 16) == 1         # degenerate floor


# -- the acceptance pin: cross-wire bit-parity -------------------------------

@pytest.mark.parametrize("n_envs", [2, 5])
def test_remote_policy_bit_identical_to_local(n_envs):
    """Remote-served acting equals local acting bit for bit — actions
    (via the recorded transitions), sealed chunks, and priorities — for
    even and odd B (uneven half-groups exercise BOTH group shapes on the
    server), through real sockets.  Every remote step must actually be
    remote (zero fallbacks), or the pin would pass vacuously."""
    cfg = _cfg()
    model_spec, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)
    server, stop, t = _serve(cfg, model, params)
    try:
        local = _family(cfg, model_spec, n_envs)
        stats_l, msgs_l = _drive(local, params, 120)

        remote = _family(cfg, model_spec, n_envs)
        remote.attach_infer(InferClient(cfg.comms, "actor-0", wait_s=30.0))
        client = remote.infer
        stats_r, msgs_r = _drive(remote, params, 120)
    finally:
        stop.set()
        t.join(timeout=10)
        server.close()

    assert client.remote_steps > 0 and client.fallbacks == 0, \
        (client.remote_steps, client.fallbacks)
    assert stats_l, "no episodes ended: the pin never exercised resets"
    assert [(s.actor_id, s.reward, s.length) for s in stats_l] \
        == [(s.actor_id, s.reward, s.length) for s in stats_r]
    _chunk_msgs_equal(msgs_l, msgs_r)
    # both half-groups went remote: group ids 0 and 1 each served
    assert server.requests == client.remote_steps
    assert server.dispatches > 0


def test_fallback_on_timeout_is_bit_identical_and_bounded():
    """No server at all: every step falls back to the local policy after
    infer_wait_s — trajectories identical to pure-local acting (the
    fallback IS the local program), and the down-marker means the wait is
    paid once, not per step."""
    cfg = _cfg()
    model_spec, *_ = dqn_env_specs(cfg)
    _, params = _params(cfg, model_spec)

    local = _family(cfg, model_spec, 3)
    stats_l, msgs_l = _drive(local, params, 60)

    remote = _family(cfg, model_spec, 3)
    remote.attach_infer(InferClient(cfg.comms, "actor-0", wait_s=0.3,
                                    reprobe_s=60.0))
    client = remote.infer
    t0 = time.monotonic()
    stats_r, msgs_r = _drive(remote, params, 60)
    elapsed = time.monotonic() - t0

    assert client.remote_steps == 0 and client.fallbacks == 120
    # the down-marker: only the first submit(s) paid the wire wait (both
    # half-group requests of the first step were already in flight when
    # the first timeout landed), everything after ran local-immediate
    assert elapsed < 30.0, f"fallback path stalled the loop: {elapsed:.1f}s"
    assert [(s.actor_id, s.reward, s.length) for s in stats_l] \
        == [(s.actor_id, s.reward, s.length) for s in stats_r]
    _chunk_msgs_equal(msgs_l, msgs_r)


def test_reprobe_regains_traffic_after_respawn():
    """The PR 8 re-probe discipline, applied to the infer server: a
    client that marked the server down keeps probing every reprobe_s, so
    a (re)spawned server gets its traffic back with no actor restart —
    and the probe traffic is bit-transparent either way."""
    cfg = _cfg()
    model_spec, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)

    fam = _family(cfg, model_spec, 2)
    fam.attach_infer(InferClient(cfg.comms, "actor-0", wait_s=0.5,
                                 reprobe_s=0.3))
    client = fam.infer
    fam.reset_all()
    key = jax.random.key(1)

    # phase 1: no server — fall back, mark down
    for _ in range(3):
        key, k = jax.random.split(key)
        fam.step_all(params, k)
    assert client.fallbacks > 0 and client.remote_steps == 0

    # phase 2: the server comes up; the next probe re-attaches
    server, stop, t = _serve(cfg, model, params)
    try:
        deadline = time.monotonic() + 30.0
        while client.remote_steps == 0 and time.monotonic() < deadline:
            key, k = jax.random.split(key)
            fam.step_all(params, k)
            time.sleep(0.05)
    finally:
        fam.close()
        stop.set()
        t.join(timeout=10)
        server.close()
    assert client.remote_steps > 0, "re-probe never regained the server"
    assert client.reprobes > 0


def test_dry_reply_before_params_falls_back_immediately():
    """A server without params answers ("dry", rid) so the client acts
    locally NOW instead of burning the full timeout per step."""
    cfg = _cfg()
    model_spec, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)
    server, stop, t = _serve(cfg, model, params=None)   # no params yet
    try:
        fam = _family(cfg, model_spec, 2)
        fam.attach_infer(InferClient(cfg.comms, "actor-0", wait_s=20.0,
                                     reprobe_s=0.0))
        client = fam.infer
        fam.reset_all()
        key = jax.random.key(1)
        t0 = time.monotonic()
        for _ in range(4):
            key, k = jax.random.split(key)
            fam.step_all(params, k)
        elapsed = time.monotonic() - t0
        assert client.fallbacks == 8 and client.remote_steps == 0
        assert elapsed < 10.0, \
            f"dry replies should beat the 20s timeout ({elapsed:.1f}s)"
        assert server.dry_replies >= 8
        # params arrive: the same fleet goes remote with no reconnect
        server.set_params(1, params)
        deadline = time.monotonic() + 30.0
        while client.remote_steps == 0 and time.monotonic() < deadline:
            key, k = jax.random.split(key)
            fam.step_all(params, k)
        assert client.remote_steps > 0
        fam.close()
    finally:
        stop.set()
        t.join(timeout=10)
        server.close()


# -- epoch fencing -----------------------------------------------------------

def test_stale_epoch_reply_discarded():
    """A reply stamped with an OLDER learner epoch than the newest seen
    is a dead life's straggler: counted, discarded, and the step falls
    back to the local policy (PR 8 fencing on the inference plane)."""
    import zmq

    port = _free_port()
    comms = CommsConfig(infer_port=port)
    router = zmq.Context.instance().socket(zmq.ROUTER)
    router.bind(f"tcp://127.0.0.1:{port}")
    client = InferClient(comms, "actor-0", wait_s=1.0)
    try:
        obs = np.zeros((2, 4), np.float32)
        eps = np.zeros(2, np.float32)
        fb = lambda: (np.full(2, 7, np.int64), np.zeros((2, 3),
                                                        np.float32))

        def roundtrip(epoch):
            pend = client.submit(obs, eps, jax.random.key(0), 0, fb)
            ident, payload = router.recv_multipart()
            got = wire.restricted_loads(payload)
            rid = got[1]["rid"]
            router.send_multipart([ident, wire.dumps(("act", {
                "rid": rid, "actions": np.zeros(2, np.int64),
                "q": np.ones((2, 3), np.float32), "pv": 1,
                "epoch": epoch}))])
            return pend.materialize()

        a1, _ = roundtrip(epoch=5)          # fresh epoch: accepted
        assert client.epoch_seen == 5 and client.remote_steps == 1
        np.testing.assert_array_equal(a1, np.zeros(2, np.int64))

        a2, _ = roundtrip(epoch=3)          # stale: discarded -> fallback
        assert client.stale_epoch == 1
        assert client.fallbacks == 1
        np.testing.assert_array_equal(a2, np.full(2, 7, np.int64))
    finally:
        client.close()
        router.close(linger=0)


# -- hostile payloads --------------------------------------------------------

def test_hostile_payload_rejected_on_infer_router():
    """A payload outside the wire allowlist is counted and dropped with
    NO reply — the hostile sender eats its own fallback wait; a
    well-formed request right behind it is served normally."""
    import pickle

    import zmq

    cfg = _cfg()
    model_spec, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)

    class Evil:
        def __reduce__(self):
            return (print, ("pwned",))

    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    server.set_params(1, params)
    hostile = zmq.Context.instance().socket(zmq.DEALER)
    hostile.setsockopt(zmq.IDENTITY, b"hostile")
    hostile.connect(f"tcp://127.0.0.1:{cfg.comms.infer_port}")
    client = InferClient(cfg.comms, "actor-0", wait_s=10.0)
    try:
        hostile.send(pickle.dumps(("infer", Evil())))
        hostile.send(pickle.dumps("not even a tuple"))
        _, frame_shape, *_ = dqn_env_specs(cfg)
        obs = np.zeros((2,) + frame_shape, np.float32)
        pend = client.submit(obs, np.zeros(2, np.float32),
                             jax.random.key(0), 0,
                             lambda: (np.zeros(2, np.int64),
                                      np.zeros((2, 2), np.float32)))
        deadline = time.monotonic() + 30.0
        served = 0
        while served == 0 and time.monotonic() < deadline:
            served = server.step(timeout_ms=100)
        actions, q = pend.materialize()
        assert client.remote_steps == 1 and client.fallbacks == 0
        assert server.rejected == 2
        assert q.shape[0] == 2
        assert not hostile.poll(200, zmq.POLLIN), \
            "hostile sender must not receive a reply (no earned credit)"
    finally:
        client.close()
        hostile.close(linger=0)
        server.close()


# -- batch coalescing --------------------------------------------------------

def test_coalesce_batches_across_clients_and_pads_deterministically():
    """Requests from DIFFERENT clients queued together serve as ONE
    scan-stacked dispatch (padded to the pow2 width), and each reply is
    bit-identical to what a lone dispatch of that request returns — the
    batching is invisible to results, visible only to throughput."""
    cfg = _cfg()
    model_spec, frame_shape, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)
    policy = make_policy_fn(model)
    server = InferServer(cfg.comms, policy, heartbeat=False)
    server.set_params(1, params)
    clients = [InferClient(cfg.comms, f"actor-{i}", wait_s=30.0)
               for i in range(3)]
    try:
        rng = np.random.default_rng(0)
        reqs, pends = [], []
        for i, c in enumerate(clients):
            obs = rng.standard_normal((2,) + frame_shape).astype(
                np.float32)
            eps = rng.random(2).astype(np.float32)
            key, group = jax.random.key(50 + i), i % 2
            reqs.append((obs, eps, key, group))
            pends.append(c.submit(obs, eps, key, group,
                                  lambda: (None, None)))
        time.sleep(0.2)                   # let all three hit the socket
        served = server.step(timeout_ms=1000)
        assert served == 3
        assert server.dispatches == 1, "3 queued requests -> ONE dispatch"
        assert server.batch_hist.max == 3.0

        # bit-parity vs a direct single-request evaluation of the same
        # program (fold_in(key, group), exactly the actor-local math)
        lone = jax.jit(policy)
        for (obs, eps, key, group), pend in zip(reqs, pends):
            actions, q = pend.materialize()
            want_a, want_q = lone(
                params, obs, eps, jax.random.fold_in(key, group))
            np.testing.assert_array_equal(actions, np.asarray(want_a))
            np.testing.assert_array_equal(q, np.asarray(want_q))
        for c in clients:
            assert c.remote_steps == 1 and c.fallbacks == 0
    finally:
        for c in clients:
            c.close()
        server.close()


def test_scan_batching_matches_unbatched_program():
    """The scan-of-identical-bodies contract at the numeric level: the
    server's padded scan produces bit-identical rows to one-at-a-time
    evaluation, for mixed groups and a non-pow2 request count."""
    cfg = _cfg()
    model_spec, frame_shape, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)
    policy = make_policy_fn(model)
    batched = make_batched_policy(policy)
    lone = jax.jit(policy)

    rng = np.random.default_rng(1)
    n, width = 5, quantize_pow2(5, 16)
    obs = rng.standard_normal((n, 3) + frame_shape).astype(np.float32)
    eps = rng.random((n, 3)).astype(np.float32)
    keys = [jax.random.key(10 + i) for i in range(n)]
    groups = np.asarray([0, 1, 0, 1, 0], np.int32)
    idx = list(range(n)) + [n - 1] * (width - n)
    a, q = batched(params, obs[idx],
                   eps[idx],
                   np.stack([np.asarray(jax.random.key_data(keys[i]))
                             for i in idx]),
                   groups[idx])
    for i in range(n):
        want_a, want_q = lone(params, obs[i], eps[i],
                              jax.random.fold_in(keys[i],
                                                 int(groups[i])))
        np.testing.assert_array_equal(np.asarray(a)[i],
                                      np.asarray(want_a))
        np.testing.assert_array_equal(np.asarray(q)[i],
                                      np.asarray(want_q))


# -- observability: gauges on the status surface -----------------------------

def test_heartbeat_gauges_flow_to_registry_status_and_prometheus():
    """The infer role's serving gauges (and remote-policy actors'
    fallback counts) ride ordinary heartbeats into the registry, the
    `--role status` table, and the Prometheus exposition — the new role
    is not a blind spot on day one."""
    from apex_tpu.fleet.heartbeat import Heartbeat
    from apex_tpu.fleet.registry import FleetRegistry, format_fleet_table
    from apex_tpu.obs import metrics as obs_metrics

    reg = FleetRegistry(CommsConfig())
    reg.observe(Heartbeat(identity="infer-0", role="infer",
                          gauges={"queue_depth": 3, "batch_p50": 2.0,
                                  "batch_p90": 4.0}))
    reg.observe(Heartbeat(identity="actor-0", role="actor",
                          gauges={"infer_fallbacks": 7,
                                  "infer_rt_ms_p50": 1.5}))
    snap = reg.snapshot()
    by_id = {p["identity"]: p for p in snap["peers"]}
    assert by_id["infer-0"]["gauges"]["queue_depth"] == 3
    assert by_id["actor-0"]["gauges"]["infer_fallbacks"] == 7

    table = format_fleet_table(snap)
    assert "infer-0: " in table and "batch_p50=2.0" in table
    assert "infer_fallbacks=7" in table

    gauges, labeled = obs_metrics.render_fleet(snap)
    rows = {(lab["identity"], lab["gauge"]): v
            for lab, v in labeled["fleet_peer_gauge"]}
    assert rows[("infer-0", "queue_depth")] == 3
    assert rows[("actor-0", "infer_fallbacks")] == 7
    text = obs_metrics.render(gauges=gauges, labeled=labeled)
    assert 'apex_fleet_peer_gauge{gauge="queue_depth",' \
           'identity="infer-0"} 3' in text


def test_heartbeat_gauges_survive_the_restricted_wire():
    """gauges is a plain dict of builtins, so the allowlisted unpickler
    carries it unchanged (the field must never force an allowlist
    growth)."""
    from apex_tpu.fleet.heartbeat import Heartbeat

    hb = Heartbeat(identity="infer-0", role="infer",
                   gauges={"queue_depth": 2, "batch_p50": 1.5})
    got = wire.restricted_loads(wire.dumps(hb))
    assert got.gauges == {"queue_depth": 2, "batch_p50": 1.5}


# -- CLI ---------------------------------------------------------------------

def test_cli_infer_flags_and_env_twins(monkeypatch):
    from apex_tpu.runtime.cli import build_parser, config_from_args

    monkeypatch.setenv("APEX_REMOTE_POLICY", "1")
    monkeypatch.setenv("APEX_INFER_PORT", "54321")
    monkeypatch.setenv("APEX_INFER_IP", "10.4.4.4")
    monkeypatch.setenv("APEX_INFER_BATCH_MAX", "8")
    monkeypatch.setenv("APEX_INFER_WINDOW_MS", "3.5")
    monkeypatch.setenv("APEX_INFER_WAIT", "0.25")
    monkeypatch.setenv("APEX_INFER_REPROBE", "2.5")
    args = build_parser().parse_args([])
    cfg = config_from_args(args)
    assert cfg.actor.remote_policy is True
    assert cfg.comms.infer_port == 54321
    assert cfg.comms.infer_ip == "10.4.4.4"
    assert cfg.comms.infer_batch_max == 8
    assert cfg.comms.infer_window_ms == 3.5
    assert cfg.comms.infer_wait_s == 0.25
    assert cfg.comms.infer_reprobe_s == 2.5
    # the infer role parses and dispatches (guard: dqn-only)
    args2 = build_parser().parse_args(["--role", "infer",
                                       "--family", "aql"])
    from apex_tpu.infer_service.service import run_infer_server
    with pytest.raises(NotImplementedError, match="dqn"):
        run_infer_server(config_from_args(args2), family="aql")


def test_remote_policy_guards():
    """Non-vector families refuse attach (loud beats silently local),
    and the aql/r2d2 socket roles refuse --remote-policy outright."""
    import dataclasses

    from apex_tpu.actors.vector import VectorFamilyBase
    from apex_tpu.config import RoleIdentity
    from apex_tpu.runtime.roles import run_actor

    class NoRemote(VectorFamilyBase):
        def _make_env(self, seed):
            from apex_tpu.envs.registry import make_env
            return make_env("ApexCartPole-v0", small_test_config().env,
                            seed=seed)

        def _on_reset(self, i, obs):
            pass

    fam = NoRemote(small_test_config(), [1], [0], [0.4])
    with pytest.raises(NotImplementedError, match="remote"):
        fam.attach_infer(object())
    fam.close()

    cfg = small_test_config()
    cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                remote_policy=True))
    with pytest.raises(NotImplementedError, match="dqn"):
        run_actor(cfg, RoleIdentity(role="actor"), family="aql")
