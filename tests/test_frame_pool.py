"""Frame-pool replay: chunk builder + device pool vs. the stacked-obs oracle.

The oracle is the already-tested NStepAccumulator fed by a host-side
FrameStack emulation: for the same trajectory both paths must produce
identical transitions, and gathering stacks from the device frame ring must
reproduce the oracle's materialized stacked observations exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.replay.nstep import NStepAccumulator

H = W = 8
SHAPE = (H, W, 1)


def _frame(rng):
    return rng.integers(0, 255, SHAPE).astype(np.uint8)


def _run_trajectory(rng, builder, oracle, n_episodes, ep_len_range,
                    frame_stack, truncate_prob=0.3):
    """Drive both paths with identical data; returns #transitions emitted."""
    total = 0
    for _ in range(n_episodes):
        ep_len = int(rng.integers(*ep_len_range))
        truncated_end = bool(rng.random() < truncate_prob)
        f = _frame(rng)
        builder.begin_episode(f)
        stack = [f] * frame_stack
        for t in range(ep_len):
            action = int(rng.integers(0, 3))
            reward = float(rng.normal())
            q = rng.normal(size=3).astype(np.float32)
            new_f = _frame(rng)
            last = t == ep_len - 1
            term = last and not truncated_end
            trunc = last and truncated_end

            obs_stacked = np.concatenate(stack, axis=-1)
            np.testing.assert_array_equal(builder.current_stack(),
                                          obs_stacked)
            builder.add_step(action, reward, q, new_f, term, trunc)
            next_stacked = np.concatenate((stack + [new_f])[1:], axis=-1)
            oracle.add(obs_stacked, action, reward, q, terminated=term,
                       truncated=trunc, final_obs=next_stacked)
            stack = (stack + [new_f])[1:]
            total += 1
    return total


@pytest.mark.parametrize("chunk_transitions", [8, 64])
def test_matches_nstep_oracle(chunk_transitions):
    n_steps, gamma, s = 3, 0.9, 4
    rng = np.random.default_rng(0)
    builder = FrameChunkBuilder(n_steps, gamma, s, SHAPE,
                                chunk_transitions=chunk_transitions)
    oracle = NStepAccumulator(n_steps, gamma)
    n_trans = _run_trajectory(rng, builder, oracle, n_episodes=6,
                              ep_len_range=(1, 12), frame_stack=s)

    pool = FramePoolReplay(capacity=256, frame_shape=SHAPE, frame_stack=s)
    state = pool.init()
    add = jax.jit(pool.add)
    for chunk in builder.force_flush():
        prios = chunk.pop("priorities")
        state = add(state, chunk, jnp.asarray(prios))

    want_batch, want_prios = oracle.make_batch()
    assert int(state.size) == n_trans == len(want_prios)

    got_obs = np.asarray(pool._gather_stacks(state, state.obs_ids[:n_trans]))
    got_next = np.asarray(pool._gather_stacks(state,
                                              state.next_ids[:n_trans]))
    np.testing.assert_array_equal(got_obs, want_batch["obs"])
    np.testing.assert_array_equal(np.asarray(state.action[:n_trans]),
                                  want_batch["action"])
    np.testing.assert_allclose(np.asarray(state.reward[:n_trans]),
                               want_batch["reward"], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(state.discount[:n_trans]),
                               want_batch["discount"], rtol=1e-6)
    # next_obs equality only where the bootstrap is live (discount > 0):
    # terminated placeholders differ by design and are masked in the loss.
    live = want_batch["discount"] > 0
    np.testing.assert_array_equal(got_next[live], want_batch["next_obs"][live])
    # priorities identical (same stored-Q trick both sides)
    got_p = np.asarray(state.sum_tree[pool.capacity:pool.capacity + n_trans])
    np.testing.assert_allclose(
        got_p, np.maximum(want_prios, pool.eps) ** pool.alpha, rtol=1e-5)


def test_wraparound_keeps_live_transitions_consistent():
    """After the ring wraps, every live transition's gathered stack must
    still reconstruct bit-exactly (frames outlive transitions)."""
    n_steps, gamma, s = 2, 0.99, 2
    rng = np.random.default_rng(1)
    builder = FrameChunkBuilder(n_steps, gamma, s, SHAPE,
                                chunk_transitions=8)
    pool = FramePoolReplay(capacity=32, frame_shape=SHAPE, frame_stack=s)
    state = pool.init()
    add = jax.jit(pool.add)

    # mirror of every transition ever emitted, in emission order
    oracle = NStepAccumulator(n_steps, gamma)
    emitted_obs, emitted_reward = [], []
    for _ in range(10):  # 10 episodes x ~12 steps >> capacity 32
        f = _frame(rng)
        builder.begin_episode(f)
        stack = [f] * s
        ep_len = int(rng.integers(6, 14))
        for t in range(ep_len):
            a, r = int(rng.integers(0, 3)), float(rng.normal())
            q = rng.normal(size=3).astype(np.float32)
            new_f = _frame(rng)
            term = t == ep_len - 1
            obs_stacked = np.concatenate(stack, axis=-1)
            builder.add_step(a, r, q, new_f, term, False)
            oracle.add(obs_stacked, a, r, q, terminated=term)
            stack = (stack + [new_f])[1:]
        for chunk in builder.force_flush():
            prios = chunk.pop("priorities")
            state = add(state, chunk, jnp.asarray(prios))
        b, _ = oracle.make_batch()
        emitted_obs.extend(list(b["obs"]))
        emitted_reward.extend(list(b["reward"]))

    n_total = len(emitted_obs)
    assert int(state.size) == 32
    pos = int(state.pos)
    # live slot i holds emission (n_total - 32) + ((i - pos) % 32)
    got_obs = np.asarray(pool._gather_stacks(state, state.obs_ids))
    for slot in range(32):
        emission = n_total - 32 + ((slot - pos) % 32)
        np.testing.assert_array_equal(got_obs[slot], emitted_obs[emission])
        np.testing.assert_allclose(float(state.reward[slot]),
                                   emitted_reward[emission], rtol=1e-6)


def test_early_flush_on_frame_overflow_pads_by_repeating_last_row():
    """Degenerate 1-step episodes overflow the frame budget before the
    transition budget; the early-flushed chunk must pad every array by
    repeating the last real row (the device collapses pads onto that row's
    slot, so identical values are required)."""
    builder = FrameChunkBuilder(3, 0.99, 2, SHAPE, chunk_transitions=16,
                                frame_margin=2)  # Kf=18 < 2*16
    rng = np.random.default_rng(2)
    for _ in range(12):  # 12 episodes x 2 frames = 24 frames > 18
        f = _frame(rng)
        builder.begin_episode(f)
        builder.add_step(0, 1.0, np.zeros(3, np.float32), _frame(rng),
                         True, False)
    chunks = builder.force_flush()
    assert len(chunks) >= 2
    early = chunks[0]
    n_trans = int(early["n_trans"])
    assert 1 <= n_trans < 16  # flushed before the transition budget filled
    assert 1 <= int(early["n_frames"]) <= 18
    for k in ("priorities", "action", "reward", "discount", "obs_ref",
              "next_ref"):
        for pad_row in early[k][n_trans:]:
            np.testing.assert_array_equal(pad_row, early[k][n_trans - 1])
    nf = int(early["n_frames"])
    for pad_row in early["frames"][nf:]:
        np.testing.assert_array_equal(pad_row, early["frames"][nf - 1])
    # every chunk self-contained: refs within the frame rows
    for c in chunks:
        assert c["obs_ref"].max() < int(c["n_frames"])
        assert c["next_ref"].max() < int(c["n_frames"])


def test_stale_transitions_redirect_to_newest_slot(key):
    """When frames outpace transitions and age out of the ring, sampling
    must redirect the stale transitions to the newest slot instead of
    returning stacks mixing unrelated episodes."""
    s = 2
    pool = FramePoolReplay(capacity=16, frame_shape=SHAPE, frame_stack=s,
                           frame_capacity=8)
    state = pool.init()
    rng = np.random.default_rng(5)

    def mk_chunk(tag):
        # 4 transitions over 8 frames: deliberately 2x frame rate
        frames = np.full((8, H * W), tag, np.uint8)
        refs = np.stack([np.arange(4), np.arange(4) + 1], axis=1)
        return dict(frames=frames, n_frames=np.int32(8), n_trans=np.int32(4),
                    action=np.full(4, tag % 3, np.int32),
                    reward=np.full(4, float(tag), np.float32),
                    discount=np.full(4, 0.97, np.float32),
                    obs_ref=refs.astype(np.int32),
                    next_ref=(refs + 2).astype(np.int32))

    for tag in range(1, 4):  # 3 chunks: 24 frame epochs >> F=8
        state = pool.add(state, mk_chunk(tag), jnp.full(4, 1.0))

    batch, weights, idx = pool.sample(state, key, 64, jnp.float32(0.4))
    idx = np.asarray(idx)
    newest = (int(state.pos) - 1) % 16
    # slots 0..7 (chunks 1-2, epochs 0/8 vs f_epoch 24 -> age 24/16 > 8) are
    # stale; only chunk-3 slots (8..11) and the newest-redirect are legal
    assert set(idx.tolist()) <= {8, 9, 10, 11, newest}
    # every sampled obs comes from chunk 3 (uniform tag 3)
    np.testing.assert_array_equal(np.asarray(batch["obs"]),
                                  np.full_like(np.asarray(batch["obs"]), 3))
    assert bool(jnp.isfinite(weights).all())


def test_sample_under_jit_shapes_and_weights(key):
    s = 4
    rng = np.random.default_rng(3)
    builder = FrameChunkBuilder(3, 0.99, s, SHAPE, chunk_transitions=32)
    pool = FramePoolReplay(capacity=128, frame_shape=SHAPE, frame_stack=s)
    state = pool.init()
    for _ in range(4):
        f = _frame(rng)
        builder.begin_episode(f)
        for t in range(20):
            builder.add_step(int(rng.integers(0, 3)), float(rng.normal()),
                             rng.normal(size=3).astype(np.float32),
                             _frame(rng), t == 19, False)
    for chunk in builder.force_flush():
        prios = chunk.pop("priorities")
        state = pool.add(state, chunk, jnp.asarray(prios))

    @jax.jit
    def sample(state, key):
        return pool.sample(state, key, 16, jnp.float32(0.4))

    batch, weights, idx = sample(state, key)
    assert batch["obs"].shape == (16, H, W, s) and batch["obs"].dtype == jnp.uint8
    assert batch["next_obs"].shape == (16, H, W, s)
    assert bool(jnp.isfinite(weights).all()) and bool((weights > 0).all())
    assert bool((idx < state.size).all())

    state = pool.update_priorities(state, idx, weights + 1.0)
    assert bool(jnp.isfinite(state.sum_tree[1]))


def test_learner_core_end_to_end_with_frame_pool(key):
    """LearnerCore is duck-typed over the replay: the fused
    ingest+sample+update step must run with FramePoolReplay."""
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.learner import LearnerCore
    from apex_tpu.training.state import create_train_state

    s, shape = 4, (16, 16, 1)
    pool = FramePoolReplay(capacity=128, frame_shape=shape, frame_stack=s)
    model = DuelingDQN(num_actions=3, compute_dtype=jnp.float32)
    optimizer = make_optimizer(lr=1e-3)
    ts = create_train_state(model, optimizer, key,
                            jnp.zeros((1, 16, 16, s), jnp.uint8))
    core = LearnerCore(apply_fn=model.apply, replay=pool,
                       optimizer=optimizer, batch_size=16,
                       target_update_interval=100)
    state = pool.init()

    rng = np.random.default_rng(4)
    builder = FrameChunkBuilder(3, 0.99, s, shape, chunk_transitions=32)
    for _ in range(3):
        builder.begin_episode(rng.integers(0, 255, shape).astype(np.uint8))
        for t in range(25):
            builder.add_step(int(rng.integers(0, 3)), float(rng.normal()),
                             rng.normal(size=3).astype(np.float32),
                             rng.integers(0, 255, shape).astype(np.uint8),
                             t == 24, False)
    ingest = core.jit_ingest()
    for chunk in builder.force_flush():
        prios = chunk.pop("priorities")
        state = ingest(state, chunk, jnp.asarray(prios))
    assert int(state.size) == 75

    step = core.jit_train_step()
    ts2, state2, metrics = step(ts, state, jax.random.key(7),
                                jnp.float32(0.4))
    assert int(ts2.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["grad_norm"]) > 0

    # scan-of-K dispatch parity on the frame-chunk layout (scalar fields
    # n_frames/n_trans and ref tables must slice correctly under scan):
    # K fused steps == one fused_multi_step, bit-exact
    k_steps = 2
    rng2 = np.random.default_rng(9)
    chunks, prios_l = [], []
    for _ in range(k_steps):
        c = _valid_chunk(pool, 8, 12, rng2)
        c["obs_ref"] = np.tile(np.arange(s, dtype=np.int32), (8, 1))
        c["next_ref"] = c["obs_ref"] + 1
        chunks.append(c)
        prios_l.append(np.abs(rng2.normal(size=8)).astype(np.float32) + .1)
    keys = jax.random.split(jax.random.key(11), k_steps)
    ts_a, st_a = ts2, state2
    ts_b = jax.tree.map(jnp.copy, ts2)
    st_b = jax.tree.map(jnp.copy, state2)
    fused = core.jit_fused_step()
    for i in range(k_steps):
        ts_a, st_a, _ = fused(ts_a, st_a, chunks[i],
                              jnp.asarray(prios_l[i]), keys[i],
                              jnp.float32(0.4))
    multi = core.jit_fused_multi_step()
    stacked = {kk: jnp.stack([jnp.asarray(c[kk]) for c in chunks])
               for kk in chunks[0]}
    ts_m, st_m, mm = multi(ts_b, st_b, stacked,
                           jnp.stack([jnp.asarray(p) for p in prios_l]),
                           keys, jnp.float32(0.4))
    assert mm["loss"].shape == (k_steps,)
    np.testing.assert_array_equal(np.asarray(st_a.sum_tree),
                                  np.asarray(st_m.sum_tree))
    np.testing.assert_array_equal(np.asarray(st_a.frames),
                                  np.asarray(st_m.frames))
    for a, b in zip(jax.tree.leaves(ts_a.params),
                    jax.tree.leaves(ts_m.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- config/shape validation (fail loudly, never corrupt the ring) ---------

def _valid_chunk(pool, k, kf, rng):
    s = pool.frame_stack
    return dict(
        frames=rng.integers(0, 255, (kf, pool.frame_dim)).astype(np.uint8),
        n_frames=np.int32(kf), n_trans=np.int32(k),
        action=np.zeros(k, np.int32), reward=np.zeros(k, np.float32),
        discount=np.zeros(k, np.float32),
        obs_ref=np.zeros((k, s), np.int32),
        next_ref=np.zeros((k, s), np.int32))


@pytest.mark.parametrize("frame_shape,stack", [((5, 5, 1), 3), ((4,), 1)])
def test_view_backed_acting_stack_matches_copy_path(frame_shape, stack):
    """bind_acting_view: the in-place acting stack is bit-identical to the
    concatenate path at EVERY step — across episode starts (reset-frame
    padding), mid-episode rolls, chunk-boundary flush carries, and
    auto-resets — and the emitted chunks are unchanged."""
    rng = np.random.default_rng(0)

    def build():
        return FrameChunkBuilder(2, 0.9, stack, frame_shape,
                                 chunk_transitions=8, frame_margin=4,
                                 frame_dtype=np.uint8)

    copy_b = build()
    view_b = build()
    stacked = view_b.stacked_shape()
    buf = np.zeros((1,) + stacked, np.uint8)    # a vector family's row
    view_b.bind_acting_view(buf[0])

    chunks_copy, chunks_view = [], []
    for _ in range(4):                           # episodes
        f0 = rng.integers(0, 255, frame_shape).astype(np.uint8)
        copy_b.begin_episode(f0)
        view_b.begin_episode(f0)
        np.testing.assert_array_equal(view_b.current_stack(),
                                      copy_b.current_stack())
        assert np.shares_memory(view_b.current_stack(), buf)   # no copy
        ep_len = int(rng.integers(3, 30))
        for t in range(ep_len):
            f = rng.integers(0, 255, frame_shape).astype(np.uint8)
            args = (int(rng.integers(0, 3)), float(rng.normal()),
                    rng.normal(size=4).astype(np.float32), f,
                    t == ep_len - 1, False)
            copy_b.add_step(*args)
            view_b.add_step(*args)
            if t < ep_len - 1:       # stack undefined after episode end
                np.testing.assert_array_equal(view_b.current_stack(),
                                              copy_b.current_stack())
        chunks_copy.extend(copy_b.poll())
        chunks_view.extend(view_b.poll())

    chunks_copy.extend(copy_b.force_flush())
    chunks_view.extend(view_b.force_flush())
    assert len(chunks_copy) == len(chunks_view) > 0
    for ca, cb in zip(chunks_copy, chunks_view):
        for k in ca:
            np.testing.assert_array_equal(np.asarray(ca[k]),
                                          np.asarray(cb[k]))


def test_bind_acting_view_validates_shape_and_dtype():
    b = FrameChunkBuilder(2, 0.9, 3, (5, 5, 1), chunk_transitions=8)
    with pytest.raises(ValueError, match="acting view"):
        b.bind_acting_view(np.zeros((5, 5, 2), np.uint8))   # wrong shape
    with pytest.raises(ValueError, match="acting view"):
        b.bind_acting_view(np.zeros((5, 5, 3), np.float32))  # wrong dtype
    b.bind_acting_view(np.zeros((5, 5, 3), np.uint8))


def test_add_rejects_oversized_and_misshapen_chunks():
    pool = FramePoolReplay(capacity=8, frame_capacity=16,
                           frame_shape=SHAPE, frame_stack=2)
    state = pool.init()
    rng = np.random.default_rng(0)
    prios = np.ones(4, np.float32)

    with pytest.raises(ValueError, match="frame rows"):
        pool.add(state, _valid_chunk(pool, 4, 32, rng), prios)
    with pytest.raises(ValueError, match="transition rows"):
        pool.add(state, _valid_chunk(pool, 16, 8, rng),
                 np.ones(16, np.float32))
    bad = _valid_chunk(pool, 4, 8, rng)
    bad["frames"] = bad["frames"][:, :-1]
    with pytest.raises(ValueError, match="frame_dim"):
        pool.add(state, bad, prios)
    bad = _valid_chunk(pool, 4, 8, rng)
    bad["obs_ref"] = np.zeros((4, 3), np.int32)
    with pytest.raises(ValueError, match="obs_ref"):
        pool.add(state, bad, prios)
    # the happy path still works after all those rejections
    state = pool.add(state, _valid_chunk(pool, 4, 8, rng), prios)
    assert int(state.size) == 4


def test_spec_rejects_ring_smaller_than_one_stack():
    with pytest.raises(ValueError, match="stack"):
        FramePoolReplay(capacity=8, frame_capacity=2, frame_shape=SHAPE,
                        frame_stack=4)


def test_hbm_bytes_estimate_matches_allocated_state():
    pool = FramePoolReplay(capacity=64, frame_shape=SHAPE, frame_stack=4)
    state = pool.init()
    actual = sum(x.size * x.dtype.itemsize
                 for x in jax.tree.leaves(state))
    est = pool.hbm_bytes()
    # estimate covers everything but scalar cursors (a few bytes)
    assert abs(est - actual) / actual < 0.01


def test_extras_roundtrip_through_pool_and_builder(key):
    """Per-transition sidecars (the AQL a_mu candidate set): declared rows
    ride the chunk from FrameChunkBuilder through FramePoolReplay.add and
    come back at sample time keyed to the SAME transition (checked via a
    value fingerprint written into the extra)."""
    rng = np.random.default_rng(5)
    stack, t_cand, a_dim = 2, 6, 3
    builder = FrameChunkBuilder(2, 0.9, stack, SHAPE, chunk_transitions=8,
                                extra_shapes={"a_mu": (t_cand, a_dim)})
    pool = FramePoolReplay(capacity=64, frame_shape=SHAPE, frame_stack=stack,
                           extra_spec=(("a_mu", (t_cand, a_dim)),))
    rs = pool.init()
    # fingerprint: extras[j, 0, 0] = reward of the acting step, so each
    # sampled transition can be matched against its sidecar
    f = _frame(rng)
    builder.begin_episode(f)
    n_steps = 20
    for i in range(n_steps):
        r = float(i)
        ex = rng.normal(size=(t_cand, a_dim)).astype(np.float32)
        ex[0, 0] = r
        builder.add_step(int(rng.integers(0, 3)), r,
                         rng.normal(size=t_cand).astype(np.float32),
                         _frame(rng), terminated=(i == n_steps - 1),
                         truncated=False, extras={"a_mu": ex})
    add = jax.jit(pool.add)
    total = 0
    for chunk in builder.force_flush():
        prios = chunk.pop("priorities")
        assert chunk["extras"]["a_mu"].shape == (8, t_cand, a_dim)
        rs = add(rs, chunk, jnp.asarray(prios))
        total += int(chunk["n_trans"])
    assert total == n_steps
    batch, w, idx = pool.sample(rs, jax.random.key(1), 16, 0.4)
    assert batch["a_mu"].shape == (16, t_cand, a_dim)
    # n-step return of transition i starts with reward i -> the head
    # reward is recoverable: for 2-step full windows ret = i + 0.9(i+1);
    # instead match directly against stored state rows by idx
    stored = np.asarray(rs.extras["a_mu"])
    np.testing.assert_allclose(np.asarray(batch["a_mu"]),
                               stored[np.asarray(idx)], rtol=0)
    # and every stored real row carries its acting step's reward stamp
    rewards = np.asarray(rs.reward)[:total]
    stamps = stored[:total, 0, 0]
    # ret(i) = i + 0.9*(i+1) for full windows; tail windows differ — only
    # assert the stamp is one of the summed rewards' head, i.e. the stamp
    # equals the largest j with ret >= stamp... keep it simple: stamps are
    # exactly the integers 0..n-1 in ingest order
    np.testing.assert_allclose(np.sort(stamps), np.arange(n_steps), rtol=0)


def test_extras_shape_validation():
    pool = FramePoolReplay(capacity=32, frame_shape=SHAPE, frame_stack=2,
                           extra_spec=(("a_mu", (4, 2)),))
    rs = pool.init()
    chunk = dict(
        frames=np.zeros((4, H * W), np.uint8), n_frames=np.int32(4),
        n_trans=np.int32(2),
        action=np.zeros(2, np.int32), reward=np.zeros(2, np.float32),
        discount=np.zeros(2, np.float32),
        obs_ref=np.zeros((2, 2), np.int32),
        next_ref=np.zeros((2, 2), np.int32),
        extras={"a_mu": np.zeros((2, 3, 2), np.float32)})  # wrong T
    with pytest.raises(ValueError, match="extras"):
        pool.add(rs, chunk, jnp.ones(2))


def test_extra_spec_rejects_builtin_collisions():
    with pytest.raises(ValueError, match="collides"):
        FramePoolReplay(capacity=32, frame_shape=SHAPE,
                        extra_spec=(("obs", (2,)),))
