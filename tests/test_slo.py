"""Fleet SLO engine (apex_tpu/obs/slo), soak artifact, scale parity.

Everything time-like runs under fake clocks — the engine's burn windows
and alert damping are pure functions of (verdict stream, clock), so the
transitions pinned here are deterministic by construction.
"""

from __future__ import annotations

import json

import pytest

from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs.slo import (BREACHED, BURNING, OK, RESOLVED, SloEngine,
                              SloKnobs, SloObjective, check_regression,
                              default_slos, format_slo_lines,
                              knobs_from_env, prometheus_sections,
                              resolve_signal)
from apex_tpu.obs.slo import main as slo_main
from apex_tpu.obs.soak import build_artifact, make_sample, offered_frames

# -- signal resolution -------------------------------------------------------

SUMMARY = {
    "peers": [
        {"role": "actor", "state": "ALIVE", "fps": 10.0,
         "gauges": {"infer_rt_ms_p99": 12.0}},
        {"role": "actor", "state": "DEAD", "fps": 99.0,
         "gauges": {"infer_rt_ms_p99": 500.0}},
        {"role": "infer", "state": "ALIVE", "fps": 0.0, "gauges": {}},
        {"role": "evaluator", "state": "ALIVE", "fps": 0.0,
         "gauges": {"eval_score_mean": 0.8}},
    ],
    "metrics": {"dead_actor_frac": 0.5},
    "latency": {"frame_age_at_train_s": {"p99_s": 3.2}},
    "rates": {"steps_per_s": 4.0, "frames_per_s": 80.0},
}


def test_resolve_signal_forms():
    # gauge aggregation excludes DEAD peers (their last values are stale
    # by definition — a dead peer must not pin the fleet's p99)
    assert resolve_signal(SUMMARY, "gauge:actor:infer_rt_ms_p99:max") \
        == 12.0
    assert resolve_signal(SUMMARY,
                          "gauge:evaluator:eval_score_mean:min") == 0.8
    assert resolve_signal(SUMMARY, "gauge:actor:nonexistent:max") is None
    # derived dead fractions, per role and fleet-wide
    assert resolve_signal(SUMMARY, "derived.dead_frac.actor") == 0.5
    assert resolve_signal(SUMMARY, "derived.dead_frac.infer") == 0.0
    assert resolve_signal(SUMMARY, "derived.dead_frac.all") == 0.25
    assert resolve_signal(SUMMARY, "derived.dead_frac.loadgen") is None
    assert resolve_signal(SUMMARY, "derived.role_fps.actor") == 10.0
    # dotted walks; dicts and missing leaves resolve to None, never raise
    assert resolve_signal(SUMMARY, "metrics.dead_actor_frac") == 0.5
    assert resolve_signal(SUMMARY,
                          "latency.frame_age_at_train_s.p99_s") == 3.2
    assert resolve_signal(SUMMARY, "rates.steps_per_s") == 4.0
    assert resolve_signal(SUMMARY, "rates.missing") is None
    assert resolve_signal(SUMMARY, "latency") is None
    assert resolve_signal({}, "gauge:actor:x:max") is None


# -- the engine under fake clocks --------------------------------------------

KNOBS = SloKnobs(fast=(10.0, 30.0), slow=(60.0, 120.0), page_burn=10.0,
                 warn_burn=3.0, breach_after_s=4.0, resolve_after_s=10.0,
                 ok_after_s=15.0, min_samples=2)


def _engine(threshold=100.0, op="<=", knobs=KNOBS, grace_s=0.0):
    t = {"now": 0.0}
    obj = SloObjective("rt", "rates.rt", threshold, op, grace_s=grace_s)
    eng = SloEngine([obj], knobs=knobs, clock=lambda: t["now"],
                    wall=lambda: 1_000_000.0 + t["now"])
    return eng, t


def _feed(eng, t, values, dt=5.0):
    """One sample per value, ticking the fake clock dt apart."""
    events = []
    for v in values:
        events += eng.sample({"rates": {"rt": v}})
        t["now"] += dt
    return events


def test_burn_rate_math():
    eng, t = _engine()
    # below min_samples: no judgment yet
    _feed(eng, t, [10.0])
    o = eng.snapshot()["objectives"][0]
    assert o["burn_fast"] is None and o["state"] == OK
    # 2 good + 2 bad in the 30s window: bad_frac 0.5 / budget 0.01 = 50
    _feed(eng, t, [10.0, 500.0, 500.0])
    o = eng.snapshot()["objectives"][0]
    assert o["burn_fast"] == pytest.approx(50.0)
    assert o["value"] == 500.0
    assert o["verdicts"] == 4
    assert o["compliance_pct"] == 50.0


def test_alert_cycle_ok_burning_breached_resolved_ok():
    eng, t = _engine()
    _feed(eng, t, [10.0, 10.0, 10.0])            # healthy baseline
    assert eng.state_of("rt") == OK

    # sustained violation: page fires (both fast windows), then the
    # breach_after damping window elapses -> BREACHED
    events = _feed(eng, t, [500.0, 500.0, 500.0, 500.0])
    states = [(e["from"], e["to"]) for e in events]
    assert (OK, BURNING) in states
    assert (BURNING, BREACHED) in states
    assert eng.state_of("rt") == BREACHED
    assert eng.severity() == 2

    # recovery: quiet must SUSTAIN resolve_after_s before RESOLVED,
    # then ok_after_s more before OK — no strobing
    events = _feed(eng, t, [10.0] * 10)
    states = [(e["from"], e["to"]) for e in events]
    assert (BREACHED, RESOLVED) in states
    assert (RESOLVED, OK) in states
    assert eng.state_of("rt") == OK
    # the slow-window WARN outlives the page: the budget spent during
    # the breach still burns above warn rate until it ages out
    assert eng.severity() == 1
    _feed(eng, t, [10.0] * 20)
    assert eng.severity() == 0

    # the bounded timeline recorded the full cycle in order
    tl = [(e["from"], e["to"]) for e in eng.snapshot()["timeline"]]
    assert tl == [(OK, BURNING), (BURNING, BREACHED),
                  (BREACHED, RESOLVED), (RESOLVED, OK)]
    snap = eng.snapshot()["objectives"][0]
    assert snap["breaches"] == 1


def test_flap_damping_transient_spike_never_pages():
    # breach_after of 12s = three 5s ticks of sustained burn; a single
    # bad tick visits BURNING and falls back to OK without ever paging
    knobs = SloKnobs(fast=(10.0, 30.0), slow=(60.0, 120.0),
                     page_burn=10.0, warn_burn=3.0, breach_after_s=12.0,
                     resolve_after_s=10.0, ok_after_s=15.0,
                     min_samples=2)
    eng, t = _engine(knobs=knobs)
    _feed(eng, t, [10.0, 10.0, 500.0, 10.0, 10.0, 10.0, 10.0])
    assert eng.state_of("rt") == OK
    o = eng.snapshot()["objectives"][0]
    assert o["breaches"] == 0
    tl = [(e["from"], e["to"]) for e in eng.snapshot()["timeline"]]
    assert (BURNING, BREACHED) not in tl


def test_observe_only_and_grace_record_no_verdicts():
    eng, t = _engine(threshold=None)              # observe-only
    _feed(eng, t, [500.0] * 5)
    o = eng.snapshot()["objectives"][0]
    assert o["state"] == OK and o["verdicts"] == 0
    assert o["value"] == 500.0 and o["enabled"] is False

    eng, t = _engine(grace_s=11.0)                # warmup grace
    _feed(eng, t, [500.0, 500.0, 500.0, 500.0])   # ticks at 0/5/10/15
    o = eng.snapshot()["objectives"][0]
    assert o["verdicts"] == 1                     # only the post-grace tick


def test_idle_needs_zero_burn_over_slow_window():
    eng, t = _engine()
    _feed(eng, t, [10.0, 10.0, 10.0])
    assert eng.snapshot()["idle"] is True
    _feed(eng, t, [500.0])
    assert eng.snapshot()["idle"] is False        # budget was burned
    # ...and stays non-idle until the bad verdict ages out of the slow
    # window (120s = 24 ticks), not merely until the state recovers
    _feed(eng, t, [10.0] * 10)
    assert eng.state_of("rt") == OK
    assert eng.snapshot()["idle"] is False
    _feed(eng, t, [10.0] * 20)
    assert eng.snapshot()["idle"] is True


def test_default_slos_env_twins_and_threshold_sharing():
    names = {o.name for o in default_slos()}
    assert {"infer_rt_p99_ms", "frame_age_p99_s", "param_lag_p99_s",
            "learner_steps_rate", "fleet_frames_rate", "actor_fps",
            "dead_peer_frac", "actor_dead_frac", "infer_up",
            "eval_score"} <= names
    by = {o.name: o for o in default_slos(
        actor_dead_thresh=0.25,
        environ={"APEX_SLO_INFER_RT_MS": "off",
                 "APEX_SLO_FRAME_AGE_S": "33"})}
    assert by["infer_rt_p99_ms"].threshold is None    # disabled
    assert by["frame_age_p99_s"].threshold == 33.0
    # the floor reaction and the SLO judge the SAME bar by construction
    assert by["actor_dead_frac"].threshold == 0.25

    k = knobs_from_env({"APEX_SLO_FAST": "10,30",
                        "APEX_SLO_BREACH_AFTER": "4"})
    assert k.fast == (10.0, 30.0) and k.breach_after_s == 4.0
    assert k.slow == SloKnobs.slow                    # untouched default


# -- per-objective burn/damping overrides (PR 11 carried follow-up) ----------

def test_objective_knob_env_twins_parse_and_merge():
    from apex_tpu.obs.slo import (SloKnobOverrides, objective_knobs_from_env,
                                  resolve_knobs)

    # unset twins: no override record at all (engine-global knobs rule)
    assert objective_knobs_from_env("eval_score", {}) is None
    over = objective_knobs_from_env(
        "eval_score", {"APEX_SLO_EVAL_SCORE_FAST": "6,12",
                       "APEX_SLO_EVAL_SCORE_BREACH_AFTER": "2",
                       "APEX_SLO_EVAL_SCORE_MIN_SAMPLES": "1"})
    assert over.fast == (6.0, 12.0) and over.breach_after_s == 2.0
    assert over.min_samples == 1 and over.slow is None
    # merge: non-None fields win, everything else inherits the base
    base = SloKnobs(fast=(60.0, 300.0), breach_after_s=10.0)
    merged = resolve_knobs(base, SloObjective("eval_score", "x", 1.0,
                                              knobs=over))
    assert merged.fast == (6.0, 12.0) and merged.breach_after_s == 2.0
    assert merged.slow == base.slow and merged.ok_after_s == base.ok_after_s
    # default_slos wires the twins per objective
    by = {o.name: o for o in default_slos(
        environ={"APEX_SLO_EVAL_SCORE_FAST": "6,12"})}
    assert by["eval_score"].knobs.fast == (6.0, 12.0)
    assert by["infer_rt_p99_ms"].knobs is None


def test_per_objective_windows_tighten_one_objective_only():
    """The canary-gate shape: eval_score runs tighter fast windows +
    damping than the engine default, so it BREACHES while a sibling
    objective judging the SAME bad signal is still only BURNING."""
    from apex_tpu.obs.slo import SloKnobOverrides

    t = {"now": 0.0}
    tight = SloKnobOverrides(fast=(10.0, 30.0), breach_after_s=4.0)
    objs = [SloObjective("tight", "rates.rt", 100.0, "<=", knobs=tight),
            SloObjective("loose", "rates.rt", 100.0, "<=")]
    # engine-global knobs: huge fast windows + long damping — 'loose'
    # cannot breach inside this test's horizon
    base = SloKnobs(fast=(300.0, 600.0), slow=(600.0, 1200.0),
                    page_burn=10.0, warn_burn=3.0, breach_after_s=60.0,
                    resolve_after_s=10.0, ok_after_s=15.0, min_samples=2)
    eng = SloEngine(objs, knobs=base, clock=lambda: t["now"],
                    wall=lambda: t["now"])
    for v in [10.0, 10.0] + [500.0] * 6:
        eng.sample({"rates": {"rt": v}})
        t["now"] += 5.0
    assert eng.state_of("tight") == BREACHED
    assert eng.state_of("loose") != BREACHED
    # snapshot burns use each objective's OWN windows
    snap = {o["name"]: o for o in eng.snapshot()["objectives"]}
    assert snap["tight"]["burn_fast"] is not None


def test_serving_rollbacks_objective_resolves_from_summary():
    by = {o.name: o for o in default_slos()}
    o = by["serving_rollbacks"]
    assert o.threshold is None          # observe-only until opted in
    assert resolve_signal({"serving": {"rollbacks": 2}},
                          "serving.rollbacks") == 2.0
    assert resolve_signal({}, "serving.rollbacks") is None
    enabled = {o2.name: o2 for o2 in default_slos(
        environ={"APEX_SLO_SERVING_ROLLBACKS": "0"})}
    assert enabled["serving_rollbacks"].judge(1) is False  # any rollback
    assert enabled["serving_rollbacks"].judge(0) is True


# -- scale decisions: drain-frac vs slo parity -------------------------------

def test_scale_decision_parity_drain_vs_slo():
    from apex_tpu.fleet.supervise import (scale_decision,
                                          scale_decision_slo)

    # same decision table, two signals: capacity-short -> up,
    # over-provisioned -> down, ambiguous/unreadable -> hold, clamped
    breached = {"severity": 2, "idle": False}
    burning = {"severity": 1, "idle": False}
    idle = {"severity": 0, "idle": True}
    okay = {"severity": 0, "idle": False}
    assert scale_decision_slo(breached, 2, 1, 8) == 3 \
        == scale_decision(0.05, 2, 1, 8)              # up
    assert scale_decision_slo(idle, 4, 1, 8) == 3 \
        == scale_decision(0.9, 4, 1, 8)               # down
    assert scale_decision_slo(burning, 4, 1, 8) == 4 \
        == scale_decision(0.3, 4, 1, 8)               # hold
    assert scale_decision_slo(okay, 4, 1, 8) == 4
    assert scale_decision_slo(None, 4, 1, 8) == 4 \
        == scale_decision(None, 4, 1, 8)              # unreadable: hold
    assert scale_decision_slo(breached, 8, 1, 8) == 8  # ceiling clamp
    assert scale_decision_slo(idle, 1, 1, 8) == 1      # floor clamp


class _FakeChild:
    def __init__(self, cmd, env):
        self.cmd, self.env = cmd, env
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        self.rc = -15


def test_scale_supervisor_slo_signal_changes_fleet_size():
    """The acceptance pin: --scale-signal slo demonstrably resizes the
    fleet on scripted snapshots (breach -> grow, idle -> shrink)."""
    from apex_tpu.fleet.supervise import (ScaleSupervisor,
                                          scale_decision_slo)

    snaps = [{"severity": 2, "idle": False},      # breach: up
             {"severity": 1, "idle": False},      # burning: hold
             {"severity": 0, "idle": True}]       # idle: down
    sup = ScaleSupervisor(["serve", "--slot", "{slot}"], n_min=1,
                          n_max=4, probe=lambda: snaps.pop(0),
                          spawn=lambda c, e: _FakeChild(c, e),
                          decide=scale_decision_slo)
    sup._apply_target()
    assert sorted(sup.children) == [0]
    sup.tick()
    assert sup.target == 2 and sorted(sup.children) == [0, 1]
    sup.tick()
    assert sup.target == 2                        # hold under BURNING
    sup.tick()
    assert sup.target == 1 and sorted(sup.children) == [0]
    assert sup.scale_ups == 1 and sup.scale_downs == 1


# -- prometheus + status-table surfaces --------------------------------------

def test_prometheus_apex_slo_rows_round_trip():
    eng, t = _engine()
    _feed(eng, t, [10.0, 10.0, 500.0, 500.0, 500.0, 500.0])
    snap = eng.snapshot()
    gauges, labeled = prometheus_sections(snap)
    # every family the sections mint is declared in the registry (the
    # J015 contract, asserted from the emitting side too)
    for name in list(gauges) + list(labeled):
        assert name in obs_metrics.REGISTERED_FAMILIES, name
    text = obs_metrics.render(gauges=gauges, labeled=labeled)
    assert "# TYPE apex_slo_state gauge" in text
    assert ('apex_slo_state{objective="rt",state="BREACHED"} 2'
            in text)
    assert 'apex_slo_value{objective="rt"} 500.0' in text
    assert 'apex_slo_breaches{objective="rt"} 1' in text
    assert 'apex_slo_compliance_pct{objective="rt"}' in text
    assert "apex_slo_severity 2" in text


def test_status_table_carries_slo_lines():
    from apex_tpu.fleet.registry import format_fleet_table

    eng, t = _engine()
    _feed(eng, t, [10.0, 10.0, 500.0, 500.0, 500.0])
    table = format_fleet_table(
        {"peers": [], "metrics": {}, "slo": eng.snapshot()})
    assert "slo rt: BREACHED" in table
    assert "slo severity=2" in table
    # an engine-less snapshot renders the plain table unchanged
    assert "slo " not in format_fleet_table({"peers": [], "metrics": {}})


def test_format_slo_lines_skips_silent_disabled_objectives():
    lines = format_slo_lines({"objectives": [
        {"name": "a", "state": OK, "enabled": False, "value": None,
         "threshold": None, "op": ">=", "breaches": 0},
    ], "severity": 0, "idle": True, "ticks": 3})
    assert lines == []                            # nothing judged, no noise


# -- trainer integration -----------------------------------------------------

class _NullPool:
    procs: list = []

    def start(self):
        pass

    def cleanup(self):
        pass

    def poll_chunks(self, n, timeout=0.0):
        return []

    def poll_stats(self):
        return []

    def publish_params(self, version, params):
        pass


def test_trainer_slo_tick_sections_and_floor_coupling():
    """The engine rides the health tick: fleet_summary carries rates/
    latency/slo sections, and a BREACHED actor-capacity alert relaxes
    the replay-ratio floor even when the instantaneous dead fraction
    sits under the raw threshold (flap hysteresis — the two surfaces
    cannot disagree)."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.fleet.heartbeat import Heartbeat
    from apex_tpu.fleet.registry import FleetRegistry
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config()
    cfg = cfg.replace(comms=dataclasses.replace(
        cfg.comms, relax_floor_dead_frac=0.5))
    trainer = ApexTrainer(cfg, pool=_NullPool(), respawn_workers=False,
                          train_ratio=8.0, min_train_ratio=0.5)
    trainer.fleet = FleetRegistry(cfg.comms)
    trainer.fleet.observe(Heartbeat("actor-0", role="actor"))
    trainer._slo_tick(0)
    assert trainer._slo is not None
    # the shared-threshold wiring reached the engine
    by = {o.name: o for o in trainer._slo.objectives}
    assert by["actor_dead_frac"].threshold == 0.5

    summary = trainer.fleet_summary()
    assert "slo" in summary and "rates" in summary
    assert summary["slo"]["objectives"]
    assert summary["rates"]["steps_per_s"] == 0.0

    # drive the actor-capacity alert to BREACHED on a scripted engine,
    # with the REGISTRY healthy: the floor must still relax
    from apex_tpu.obs.slo import SloEngine, SloObjective
    t = {"now": 0.0}
    eng = SloEngine([SloObjective("actor_dead_frac", "metrics.d", 0.5)],
                    knobs=KNOBS, clock=lambda: t["now"])
    for _ in range(6):
        eng.sample({"metrics": {"d": 1.0}})
        t["now"] += 5.0
    assert eng.state_of("actor_dead_frac") == BREACHED
    trainer._slo = eng
    assert trainer.fleet.dead_fraction() == 0.0   # registry: all alive
    trainer._react_to_fleet(0)
    assert trainer._floor_relaxed
    assert trainer._min_ratio_effective() is None

    # the alert resolving restores the floor
    for _ in range(12):
        eng.sample({"metrics": {"d": 0.0}})
        t["now"] += 5.0
    assert eng.state_of("actor_dead_frac") == OK
    trainer._react_to_fleet(0)
    assert not trainer._floor_relaxed


# -- soak artifact schema pin ------------------------------------------------

def _soak_summary(steps, ingested, offered, slo_snap):
    return {
        "steps": steps, "ingested": ingested,
        "peers": [{"role": "loadgen", "state": "ALIVE",
                   "gauges": {"ondevice_frames": offered}}],
        "metrics": {"alive": 3, "dead": 0},
        "rates": {"steps_per_s": 5.0, "frames_per_s": 100.0},
        "slo": slo_snap,
    }


def test_soak_artifact_schema_and_math():
    eng, t = _engine()
    _feed(eng, t, [10.0, 10.0, 500.0, 500.0, 10.0, 10.0])
    snap = eng.snapshot()
    samples = [make_sample(_soak_summary(100, 1_000, 10_000, snap), 10.0),
               make_sample(_soak_summary(200, 3_000, 50_000, snap), 110.0)]
    meta = {"env_id": "ApexCatchSmall-v0", "budget_s": 120.0,
            "effective_cores": 1.0}
    art = build_artifact(meta, samples,
                         _soak_summary(200, 3_000, 50_000, snap))
    # schema pin: the standing artifact's shape is a contract — the CI
    # drill, the --check differ, and future dashboards all read it
    assert art["kind"] == "apex_soak" and art["version"] == 1
    assert set(art) == {"kind", "version", "meta", "samples", "slo",
                        "throughput"}
    assert set(art["slo"]) == {"compliance", "breaches", "timeline",
                               "severity_final", "objectives"}
    assert set(art["throughput"]) == {
        "steps_final", "ingested_final", "offered_frames_final",
        "steps_per_s", "ingest_per_s", "offered_per_s", "saturation"}
    s0 = art["samples"][0]
    assert {"t_s", "steps", "ingested", "offered_frames", "rates",
            "severity", "states", "alive", "dead"} <= set(s0)
    # throughput math over the sampled span
    assert art["throughput"]["steps_per_s"] == 1.0
    assert art["throughput"]["ingest_per_s"] == 20.0
    assert art["throughput"]["offered_per_s"] == 400.0
    assert art["throughput"]["saturation"] == 20.0
    # SLO evidence folded in from the engine snapshot
    assert art["slo"]["compliance"]["rt"] == pytest.approx(66.67)
    assert art["slo"]["breaches"].get("rt", 0) >= 1
    assert any(e["to"] == BREACHED for e in art["slo"]["timeline"])
    # artifact is pure JSON (the file the soak writes round-trips)
    json.loads(json.dumps(art))


def test_offered_frames_sums_loadgen_gauges_only():
    s = {"peers": [
        {"role": "loadgen", "gauges": {"ondevice_frames": 100}},
        {"role": "loadgen", "gauges": {"ondevice_frames": 50}},
        {"role": "actor", "gauges": {"ondevice_frames": 999}},
    ]}
    assert offered_frames(s) == 150


# -- the --check regression differ -------------------------------------------

BASE_BENCH = {
    "part1e": {"remote": {"frames_per_sec": 100.0,
                          "rt_ms": {"p50": 2.0, "p99": 8.0}},
               "local": {"frames_per_sec": 110.0}},
    "latency": {"frame_age_at_train_s": {"p99_s": 10.0, "count": 500}},
    "effective_cores": 1.0,
    "platform_note": "cpu",                      # non-numeric: ignored
}


def _cand(**over):
    cand = json.loads(json.dumps(BASE_BENCH))
    for path, v in over.items():
        node = cand
        parts = path.split("__")
        for p in parts[:-1]:
            node = node[p]
        node[parts[-1]] = v
    return cand


def test_check_regression_direction_and_band():
    # inside the band: no verdicts beyond ok
    rows = check_regression(BASE_BENCH, _cand(), tol=0.15)
    assert rows and all(r["verdict"] == "ok" for r in rows)
    # lower-better leaf regressing (latency p99 up 50%)
    rows = check_regression(
        BASE_BENCH, _cand(latency__frame_age_at_train_s__p99_s=15.0))
    bad = [r for r in rows if r["verdict"] == "REGRESSED"]
    assert [r["path"] for r in bad] == \
        ["latency.frame_age_at_train_s.p99_s"]
    # higher-better leaf regressing (throughput down 40%)
    rows = check_regression(
        BASE_BENCH, _cand(part1e__remote__frames_per_sec=60.0))
    bad = [r for r in rows if r["verdict"] == "REGRESSED"]
    assert [r["path"] for r in bad] == ["part1e.remote.frames_per_sec"]
    # improvements are labeled, never failed
    rows = check_regression(
        BASE_BENCH, _cand(part1e__remote__rt_ms__p99=4.0))
    assert [r["path"] for r in rows if r["verdict"] == "improved"] \
        == ["part1e.remote.rt_ms.p99"]
    # "count" is informational, not a lane
    assert not any("count" in r["path"].rsplit(".", 1)[-1]
                   for r in check_regression(BASE_BENCH, _cand()))


def test_check_cli_exit_codes(tmp_path, capsys):
    a = tmp_path / "a.json"
    b_ok = tmp_path / "b_ok.json"
    b_bad = tmp_path / "b_bad.json"
    a.write_text(json.dumps(BASE_BENCH))
    b_ok.write_text(json.dumps(_cand()))
    b_bad.write_text(json.dumps(
        _cand(latency__frame_age_at_train_s__p99_s=30.0)))
    assert slo_main(["--check", str(a), str(b_ok)]) == 0
    out = capsys.readouterr().out
    assert "0 regressed" in out
    assert slo_main(["--check", str(a), str(b_bad)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "frame_age_at_train_s.p99_s" in out
    # machine-readable mode round-trips
    assert slo_main(["--check", str(a), str(b_bad), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["regressed"] == 1 and doc["compared"] >= 4
    # a widened band forgives the same pair
    assert slo_main(["--check", str(a), str(b_bad), "--tol", "3.0"]) == 0
    capsys.readouterr()


def test_objective_table_prints_without_args(capsys):
    assert slo_main([]) == 0
    out = capsys.readouterr().out
    assert "infer_rt_p99_ms" in out and "burn windows" in out
