"""Wire codec (apex_tpu/runtime/codec.py): chunk round-trip BYTE parity
on real env traffic, pad-row-free encoding, mixed-codec fleet ingest,
the param-delta plane (keyframe/delta/recovery/epoch fencing), hostile
payload handling, and the CLI env twins.

The parity bar is deliberately brutal: a decoded chunk must re-pickle to
the EXACT bytes of the original's raw wire form — not "arrays equal",
bit-identical serialization.  That is what lets the replay/ingest planes
treat compressed and legacy chunks as the same object downstream.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time

import numpy as np
import pytest

from apex_tpu.config import CommsConfig, EnvConfig
from apex_tpu.envs.registry import make_env
from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.runtime import codec


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _comms(**overrides) -> CommsConfig:
    batch, param, barrier, status = _free_ports(4)
    return CommsConfig(batch_port=batch, param_port=param,
                       barrier_port=barrier, status_port=status,
                       **overrides)


def _record_chunks(env_id: str, n_chunks: int = 3,
                   chunk_k: int = 32) -> list[dict]:
    """Real actor traffic: drive the env through FrameChunkBuilder and
    collect sender-shaped msgs (payload + priorities + n_trans) — the
    exact dicts ChunkSender.send_chunk sees."""
    env = make_env(env_id, EnvConfig(env_id=env_id), seed=0,
                   stack_frames=False)
    obs, _ = env.reset(seed=0)
    builder = FrameChunkBuilder(3, 0.99, 4, obs.shape,
                                chunk_transitions=chunk_k,
                                frame_dtype=np.uint8)
    builder.begin_episode(obs)
    rng = np.random.default_rng(0)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        a = int(rng.integers(env.action_space.n))
        obs, r, term, trunc, _ = env.step(a)
        q = rng.standard_normal(env.action_space.n).astype(np.float32)
        builder.add_step(a, r, q, obs, term, trunc)
        if term or trunc:
            obs, _ = env.reset()
            builder.begin_episode(obs)
        for chunk in builder.poll():
            prios = chunk.pop("priorities")
            msgs.append({"payload": chunk, "priorities": prios,
                         "n_trans": int(chunk["n_trans"])})
    return msgs[:n_chunks]


def _raw_wire(msg: dict) -> bytes:
    return pickle.dumps(("chunk", msg), protocol=5)


def _canon_wire(msg: dict) -> bytes:
    """Raw wire bytes after dtype canonicalization.  The LEGACY raw lane
    has always delivered arrays with fresh (non-singleton) dtype objects
    out of the unpickler — numpy 2.x pickle behavior, not codec's — so a
    same-lane re-pickle can differ by a few memo bytes.  Rebinding each
    array's dtype to its interned singleton (what codec._canon does for
    decoded chunks) makes byte comparison well-defined across lanes."""
    def canon(v):
        if isinstance(v, dict):
            return {k: canon(x) for k, x in v.items()}
        return codec._canon(v)
    return _raw_wire(canon(msg))


# -- round-trip byte parity -------------------------------------------------

@pytest.mark.parametrize("env_id", ["ApexCatchSmall-v0", "ApexRally-v0"])
@pytest.mark.parametrize("codec_name", ["delta", "dict"])
def test_round_trip_byte_parity_on_real_env_chunks(env_id, codec_name):
    """Decoded chunks re-pickle to the ORIGINAL raw wire bytes — Catch
    binary frames and Rally pixel rows alike, every chunk."""
    msgs = _record_chunks(env_id)
    compressed = 0
    for msg in msgs:
        before = _raw_wire(msg)
        payload, raw_n, wire_n = codec.encode_chunk(msg, codec_name)
        # apexlint: disable=C005 -- same-process test payload
        kind, body = pickle.loads(payload)
        if kind == "chunk":        # negotiation fell back (tiny chunk)
            assert payload == before
            continue
        compressed += 1
        assert kind == "chunkc" and wire_n == len(payload)
        assert wire_n < len(before)
        decoded = codec.decode_chunk(body)
        assert _raw_wire(decoded) == before
    assert compressed > 0, "no chunk took the compressed path"


def test_raw_codec_is_bit_identical_to_legacy_wire():
    msg = _record_chunks("ApexCatchSmall-v0", n_chunks=1)[0]
    payload, raw_n, wire_n = codec.encode_chunk(msg, "raw")
    assert payload == _raw_wire(msg)
    assert raw_n == wire_n == len(payload)


def test_pad_rows_cost_zero_wire_bytes():
    """A terminal-truncated chunk (half pad rows) ships only its real
    rows: the frm spec carries n_frames rows, arr columns carry n_trans
    rows, and decode regrows the repeat-last padding bit-exactly."""
    msgs = _record_chunks("ApexCatchSmall-v0", n_chunks=6, chunk_k=16)
    padded = [m for m in msgs
              if int(m["payload"]["n_frames"])
              < m["payload"]["frames"].shape[0]]
    assert padded, "recording produced no terminal-padded chunk"
    msg = padded[0]
    payload, _, _ = codec.encode_chunk(msg, "delta")
    # apexlint: disable=C005 -- same-process test payload
    kind, enc = pickle.loads(payload)
    assert kind == "chunkc"
    frm = enc["cols"]["frames"]
    n_frames = int(msg["payload"]["n_frames"])
    assert frm[0] == "frm"
    assert frm[2] == n_frames                       # shipped rows
    assert frm[3] == msg["payload"]["frames"].shape[0]   # regrown total
    act = enc["cols"]["action"]
    assert act[0] == "arr"
    assert act[1].shape[0] == int(msg["payload"]["n_trans"])
    assert _raw_wire(codec.decode_chunk(enc)) == _raw_wire(msg)


def test_compression_never_loses_on_noise():
    """Adversarial entropy: pure-noise frames defeat both codecs, so the
    encoder ships the legacy raw payload instead of a larger one."""
    rng = np.random.default_rng(7)
    k = 8
    msg = {"payload": {
        "frames": rng.integers(0, 256, (k + 3, 12, 12), np.uint8),
        "n_frames": np.int32(k + 3), "n_trans": np.int32(k),
        "action": rng.integers(0, 4, (k,), np.int32),
        "reward": rng.standard_normal(k).astype(np.float32)},
        "priorities": rng.random(k).astype(np.float32),
        "n_trans": k}
    for name in ("delta", "dict"):
        payload, raw_n, wire_n = codec.encode_chunk(msg, name)
        assert payload == _raw_wire(msg)
        assert raw_n == wire_n == len(payload)


def test_resolve_codec_arg_env_twin_and_unknown(monkeypatch):
    monkeypatch.delenv("APEX_WIRE_CODEC", raising=False)
    assert codec.resolve_codec(None) == "raw"
    assert codec.resolve_codec("dict") == "dict"
    monkeypatch.setenv("APEX_WIRE_CODEC", "delta")
    assert codec.resolve_codec(None) == "delta"
    assert codec.resolve_codec("raw") == "raw"     # explicit beats env
    with pytest.raises(ValueError):
        codec.resolve_codec("gzip")
    monkeypatch.setenv("APEX_WIRE_CODEC", "snappy")
    with pytest.raises(ValueError):
        codec.resolve_codec(None)


# -- hostile payloads --------------------------------------------------------

def _one_compressed(codec_name: str = "delta"):
    msg = _record_chunks("ApexCatchSmall-v0", n_chunks=1)[0]
    payload, _, _ = codec.encode_chunk(msg, codec_name)
    # apexlint: disable=C005 -- same-process test payload
    kind, enc = pickle.loads(payload)
    assert kind == "chunkc"
    return msg, enc


def test_decode_rejects_corrupt_future_and_garbage():
    msg, enc = _one_compressed()
    # bit-flip the frame blob: blob crc catches it before any decode
    bad = dict(enc, cols=dict(enc["cols"]))
    blob = bytearray(bad["cols"]["frames"][1])
    blob[len(blob) // 2] ^= 0xFF
    bad["cols"]["frames"] = (("frm", bytes(blob))
                             + tuple(bad["cols"]["frames"][2:]))
    with pytest.raises(codec.CodecError):
        codec.decode_chunk(bad)
    # a future wire version is rejected, never guessed at
    with pytest.raises(codec.CodecError):
        codec.decode_chunk(dict(enc, v=codec.WIRE_VERSION + 1))
    # structural garbage
    for garbage in (None, [], {"v": 1}, dict(enc, codec="raw"),
                    dict(enc, cols={"frames": ("frm", b"x")})):
        with pytest.raises(codec.CodecError):
            codec.decode_chunk(garbage)
    # implausible RLE geometry never allocates terabytes
    import struct
    with pytest.raises(codec.CodecError):
        codec._rle_decode(b"\x01" + struct.pack("<QI", 1 << 40, 1) + b"xxxxx")


def test_receiver_counts_and_drops_hostile_codec_payloads():
    """A corrupt chunkc payload costs one message (codec_rejected), earns
    NO ack, and honest compressed + legacy senders keep flowing."""
    import zmq

    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    msg, enc = _one_compressed()
    bad = dict(enc, cols=dict(enc["cols"]))
    blob = bytearray(bad["cols"]["frames"][1])
    blob[0] ^= 0xFF
    bad["cols"]["frames"] = (("frm", bytes(blob))
                             + tuple(bad["cols"]["frames"][2:]))

    comms = _comms()
    recv = ChunkReceiver(comms, bind_ip="127.0.0.1", queue_depth=8)
    recv.start()
    evil = None
    try:
        evil = zmq.Context.instance().socket(zmq.DEALER)
        evil.setsockopt(zmq.IDENTITY, b"mallory")
        evil.connect(f"tcp://127.0.0.1:{comms.batch_port}")
        evil.send(pickle.dumps(("chunkc", bad), protocol=5))
        deadline = time.monotonic() + 10
        while recv.codec_rejected == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert recv.codec_rejected == 1
        assert not evil.poll(200, zmq.POLLIN), "garbage earned an ack"

        s = ChunkSender(comms, "actor-0", ip="127.0.0.1", codec="delta")
        assert s.send_chunk(msg)
        got = recv.chunks.get(timeout=5.0)
        assert _raw_wire(got) == _raw_wire(msg)
        assert recv.codec_chunks == 1
        s.close()
    finally:
        if evil is not None:
            evil.close(linger=0)
        recv.stop()


# -- mixed-codec fleet ingest ------------------------------------------------

def test_mixed_codec_fleet_ingest_parity():
    """One legacy raw actor and one delta actor feed the same receiver;
    every ingested chunk is byte-par with its original regardless of
    which lane it rode — per-chunk negotiation, no handshake."""
    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    msgs = _record_chunks("ApexCatchSmall-v0", n_chunks=4)
    comms = _comms()
    recv = ChunkReceiver(comms, bind_ip="127.0.0.1", queue_depth=32)
    recv.start()
    try:
        legacy = ChunkSender(comms, "actor-0", ip="127.0.0.1", codec="raw")
        modern = ChunkSender(comms, "actor-1", ip="127.0.0.1",
                             codec="delta")
        for m in msgs:
            assert legacy.send_chunk(m)
            assert modern.send_chunk(m)
        want = {_canon_wire(m) for m in msgs}
        seen_raw: list[bytes] = []
        for _ in range(2 * len(msgs)):
            seen_raw.append(_canon_wire(recv.chunks.get(timeout=10.0)))
        assert set(seen_raw) == want
        # every original arrived twice — once per lane, byte-par both ways
        for w in want:
            assert seen_raw.count(w) == 2
        assert recv.codec_chunks == len(msgs)      # only actor-1's lane
        assert recv.codec_rejected == 0
        assert legacy.wire_gauges()["codec_ratio"] == 1.0
        assert modern.wire_gauges()["codec_ratio"] > 1.0
        legacy.close()
        modern.close()
    finally:
        recv.stop()


# -- param-delta plane -------------------------------------------------------

def _params(v: float, extra: float = 0.0):
    return {"dense": {"w": np.full((8, 4), v, np.float32),
                      "b": np.zeros((4,), np.float32)},
            "head": (np.arange(6, dtype=np.float32) + extra,)}


def test_diff_apply_checksum_round_trip():
    p0, p1 = _params(1.0), _params(1.0, extra=0.5)
    _, base_bytes, raw_total = codec.diff_tree(p0, {})
    assert raw_total > 0
    assert codec.bytes_checksum(base_bytes) == codec.tree_checksum(p0)
    updates, new_bytes, _ = codec.diff_tree(p1, base_bytes)
    assert set(updates) == {"head/0"}       # only the changed leaf rides
    rebuilt = codec.apply_delta(p0, updates)
    assert codec.tree_checksum(rebuilt) == codec.tree_checksum(p1)
    assert isinstance(rebuilt["head"], tuple)   # containers keep type
    with pytest.raises(codec.CodecError):
        codec.apply_delta(p0, {"no/such/leaf": np.zeros(1)})


def test_publisher_keyframe_cadence_epoch_bump_and_force():
    """Counter pins on the publisher state machine: first publish and
    every epoch bump are ALWAYS keyframes; force_keyframe() makes the
    next publish dense; steady state is deltas."""
    from apex_tpu.runtime.transport import ParamPublisher

    comms = _comms()
    pub = ParamPublisher(comms, bind_ip="127.0.0.1", delta=True,
                         keyframe_every=1000)
    try:
        pub.publish(1, _params(1.0))
        assert (pub.param_keyframes, pub.param_deltas) == (1, 0)
        pub.publish(2, _params(2.0))
        pub.publish(3, _params(3.0))
        assert (pub.param_keyframes, pub.param_deltas) == (1, 2)
        pub.epoch = 2                       # learner restart / PBT bump
        pub.publish(4, _params(4.0))
        assert (pub.param_keyframes, pub.param_deltas) == (2, 2)
        pub.force_keyframe()                # KeyframeRequest answer
        pub.publish(5, _params(5.0))
        assert (pub.param_keyframes, pub.keyframes_forced) == (3, 1)
        pub.publish(6, _params(6.0))
        assert pub.param_deltas == 3
        assert pub.param_publishes == 6
        assert pub.param_delta_bytes > 0
        assert pub.param_bytes_raw > 0
    finally:
        pub.close()


def _keyframe_frame(seq: int, version: int, params, epoch: int = 0):
    return {"pdelta": 1, "v": version, "epoch": epoch, "seq": seq,
            "key": True, "crc": codec.tree_checksum(params),
            "params": params}


def test_subscriber_reassembles_deltas_and_recovers_via_keyframe():
    """Deterministic reassembly pins (frames applied directly, no socket
    races): keyframe -> delta -> bit-identical tree; corrupt delta ->
    dropped, counted, on_mismatch fired, want_keyframe latched; the next
    keyframe clears it.  Deltas base on the KEYFRAME, so a CONFLATE-
    dropped intermediate delta is harmless."""
    from apex_tpu.runtime.transport import ParamSubscriber

    comms = _comms()
    sub = ParamSubscriber(comms, learner_ip="127.0.0.1")
    asked: list[int] = []
    sub.on_mismatch = asked.append
    try:
        p0, p1, p2 = _params(1.0), _params(1.0, 0.5), _params(1.0, 0.75)
        _, base_bytes, _ = codec.diff_tree(p0, {})
        got = sub._apply_pdelta(_keyframe_frame(0, 1, p0))
        assert got == (1, p0) and sub.keyframes_seen == 1

        # seq 1 delta lost to CONFLATE; seq 2 still applies (same base)
        updates, new_bytes, _ = codec.diff_tree(p2, base_bytes)
        got = sub._apply_pdelta(
            {"pdelta": 1, "v": 3, "epoch": 0, "seq": 2, "key": False,
             "base": 0, "crc": codec.bytes_checksum(new_bytes),
             "updates": updates})
        assert got is not None and got[0] == 3
        assert codec.tree_checksum(got[1]) == codec.tree_checksum(p2)
        assert pickle.dumps(got[1]) == pickle.dumps(p2)   # bit-identical
        assert sub.deltas_applied == 1

        # corrupt delta: dropped + counted + KeyframeRequest hook fired
        upd1, nb1, _ = codec.diff_tree(p1, base_bytes)
        got = sub._apply_pdelta(
            {"pdelta": 1, "v": 4, "epoch": 0, "seq": 3, "key": False,
             "base": 0, "crc": codec.bytes_checksum(nb1) ^ 0xDEAD,
             "updates": upd1})
        assert got is None and sub.delta_mismatches == 1
        assert sub.want_keyframe and asked == [4]

        # a delta against a keyframe we never saw is the same story
        got = sub._apply_pdelta(
            {"pdelta": 1, "v": 5, "epoch": 0, "seq": 9, "key": False,
             "base": 7, "crc": 0, "updates": {}})
        assert got is None and sub.delta_mismatches == 2

        # recovery: the forced dense keyframe lands and clears the latch
        got = sub._apply_pdelta(_keyframe_frame(10, 6, p1, epoch=3))
        assert got == (6, p1) and not sub.want_keyframe
        assert sub.learner_epoch == 3
    finally:
        sub.close()


def test_param_delta_converges_bit_identical_across_epoch_bump():
    """End-to-end over real PUB/SUB sockets: a delta-mode publisher keeps
    publishing while the subscriber polls through CONFLATE; after an
    epoch bump the subscriber lands on a post-bump version whose tree is
    BIT-identical to what the publisher sent for that version."""
    from apex_tpu.runtime.transport import ParamPublisher, ParamSubscriber

    comms = _comms()
    sub = ParamSubscriber(comms, learner_ip="127.0.0.1")
    pub = ParamPublisher(comms, bind_ip="127.0.0.1", delta=True,
                         keyframe_every=3)
    published: dict[int, bytes] = {}
    try:
        time.sleep(0.2)                     # SUB connect (slow joiner)

        def settle(first_version: int) -> tuple:
            v = first_version
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                params = _params(float(v), extra=v * 0.25)
                published[v] = pickle.dumps(params)
                pub.publish(v, params)
                v += 1
                got = sub.poll(50)
                if got is not None and got[0] >= first_version:
                    return got
            raise AssertionError("subscriber never converged")

        got = settle(1)
        assert pickle.dumps(got[1]) == published[got[0]]

        pub.epoch = 7                       # restart/PBT fencing bump
        bumped_from = max(published) + 1
        got = settle(bumped_from)
        assert pickle.dumps(got[1]) == published[got[0]]
        assert sub.learner_epoch == 7
        assert pub.param_keyframes >= 2     # first publish + epoch bump
        assert sub.delta_mismatches == 0 or sub.keyframes_seen >= 1
    finally:
        pub.close()
        sub.close()


# -- CLI env twins -----------------------------------------------------------

def test_cli_wire_codec_env_twins(monkeypatch):
    """APEX_WIRE_CODEC / APEX_PARAM_DELTA / APEX_PARAM_KEYFRAME_EVERY
    configure the whole fleet via run_local.sh-style exports; flags beat
    the env twins."""
    from apex_tpu.runtime.cli import build_parser, config_from_args

    monkeypatch.delenv("APEX_WIRE_CODEC", raising=False)
    monkeypatch.delenv("APEX_PARAM_DELTA", raising=False)
    monkeypatch.delenv("APEX_PARAM_KEYFRAME_EVERY", raising=False)
    cfg = config_from_args(build_parser().parse_args([]))
    assert cfg.comms.wire_codec == "raw"         # default: legacy raw
    assert not cfg.comms.param_delta
    assert cfg.comms.param_keyframe_every == 16

    monkeypatch.setenv("APEX_WIRE_CODEC", "delta")
    monkeypatch.setenv("APEX_PARAM_DELTA", "1")
    monkeypatch.setenv("APEX_PARAM_KEYFRAME_EVERY", "5")
    cfg = config_from_args(build_parser().parse_args([]))
    assert cfg.comms.wire_codec == "delta"
    assert cfg.comms.param_delta
    assert cfg.comms.param_keyframe_every == 5

    cfg = config_from_args(build_parser().parse_args(
        ["--wire-codec", "dict", "--param-keyframe-every", "9"]))
    assert cfg.comms.wire_codec == "dict"        # flags beat env twins
    assert cfg.comms.param_keyframe_every == 9
    assert cfg.comms.param_delta                 # env twin still applies

    # APEX_PARAM_DELTA=0 is off, not bool("0")
    monkeypatch.setenv("APEX_PARAM_DELTA", "0")
    cfg = config_from_args(build_parser().parse_args([]))
    assert not cfg.comms.param_delta


def test_slo_check_directions_for_wire_lanes():
    """bytes-per-transition lanes gate lower-better; codec-ratio lanes
    gate higher-better — a compression IMPROVEMENT must never read as a
    regression in obs.slo --check."""
    from apex_tpu.obs.slo import _direction, check_regression

    assert _direction("wire_codec.catch.delta.bytes_per_transition") == -1
    assert _direction("wire_codec.catch.delta.codec_ratio") == 1
    assert _direction("wire_codec.pixel.ingest_delta_vs_raw") == 0

    base = {"wire_codec": {"catch": {"delta": {
        "bytes_per_transition": 300.0, "codec_ratio": 8.0}}}}
    better = {"wire_codec": {"catch": {"delta": {
        "bytes_per_transition": 100.0, "codec_ratio": 24.0}}}}
    rows = {r["path"]: r["verdict"]
            for r in check_regression(base, better)}
    assert rows[
        "wire_codec.catch.delta.bytes_per_transition"] == "improved"
    assert rows["wire_codec.catch.delta.codec_ratio"] == "improved"
    worse = {"wire_codec": {"catch": {"delta": {
        "bytes_per_transition": 900.0, "codec_ratio": 2.0}}}}
    rows = {r["path"]: r["verdict"]
            for r in check_regression(base, worse)}
    assert rows[
        "wire_codec.catch.delta.bytes_per_transition"] == "REGRESSED"
    assert rows["wire_codec.catch.delta.codec_ratio"] == "REGRESSED"
