"""Segment-tree ops vs. independent numpy oracles (cumsum/searchsorted)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import tree as T

CAP = 64


def _random_leaves(rng, cap=CAP, fill=None):
    n = fill if fill is not None else cap
    vals = rng.uniform(0.1, 5.0, size=n).astype(np.float32)
    leaves = np.zeros(cap, np.float32)
    leaves[:n] = vals
    return leaves


def test_update_sum_matches_numpy():
    rng = np.random.default_rng(0)
    tree = T.init_sum_tree(CAP)
    leaves = _random_leaves(rng)
    tree = T.update_sum(tree, jnp.arange(CAP), jnp.asarray(leaves))
    assert np.isclose(float(T.tree_total(tree)), leaves.sum(), rtol=1e-5)
    # overwrite a random subset; sum follows
    idx = rng.choice(CAP, size=17, replace=False)
    new = rng.uniform(0.1, 5.0, size=17).astype(np.float32)
    tree = T.update_sum(tree, jnp.asarray(idx), jnp.asarray(new))
    leaves[idx] = new
    assert np.isclose(float(T.tree_total(tree)), leaves.sum(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(T.get_leaves(tree, jnp.arange(CAP))),
                               leaves, rtol=1e-6)


def test_update_min_matches_numpy():
    rng = np.random.default_rng(1)
    tree = T.init_min_tree(CAP)
    leaves = _random_leaves(rng, fill=40)
    active = jnp.arange(40)
    tree = T.update_min(tree, active, jnp.asarray(leaves[:40]))
    assert np.isclose(float(T.tree_min(tree)), leaves[:40].min(), rtol=1e-6)
    # lower one leaf, min tracks it; raise it back, min recovers
    tree2 = T.update_min(tree, jnp.asarray([7]), jnp.asarray([0.01]))
    assert np.isclose(float(T.tree_min(tree2)), 0.01, rtol=1e-6)
    tree3 = T.update_min(tree2, jnp.asarray([7]), jnp.asarray([leaves[7]]))
    assert np.isclose(float(T.tree_min(tree3)), leaves[:40].min(), rtol=1e-6)


def test_find_prefixsum_matches_searchsorted():
    rng = np.random.default_rng(2)
    leaves = _random_leaves(rng)
    tree = T.update_sum(T.init_sum_tree(CAP), jnp.arange(CAP), jnp.asarray(leaves))
    cum = np.cumsum(leaves)
    u = rng.uniform(0, cum[-1] * 0.999999, size=256).astype(np.float32)
    got = np.asarray(T.find_prefixsum_idx(tree, jnp.asarray(u)))
    want = np.searchsorted(cum, u, side="right")
    # float accumulation order differs between tree and cumsum; allow off-by-one
    # only where u lands within float eps of a stratum boundary.
    mismatch = got != want
    if mismatch.any():
        near = np.abs(cum[np.minimum(want, CAP - 1)] - u[..., ]) < 1e-3
        assert np.all(~mismatch | near)


def test_stratified_sample_proportional():
    rng = np.random.default_rng(3)
    leaves = np.zeros(CAP, np.float32)
    leaves[:32] = rng.uniform(0.05, 1.0, 32)
    leaves[5] = 10.0  # dominant priority
    tree = T.update_sum(T.init_sum_tree(CAP), jnp.arange(CAP), jnp.asarray(leaves))

    @jax.jit
    def draw(key):
        return T.stratified_sample(tree, key, 64, jnp.int32(32))

    counts = np.zeros(CAP)
    n_rounds = 200
    keys = jax.random.split(jax.random.key(0), n_rounds)
    for k in keys:
        idx = np.asarray(draw(k))
        assert (idx >= 0).all() and (idx < 32).all()
        np.add.at(counts, idx, 1)
    emp = counts / counts.sum()
    expect = leaves / leaves.sum()
    np.testing.assert_allclose(emp[:32], expect[:32], atol=0.02)


def test_capacity_validation():
    with pytest.raises(ValueError):
        T.init_sum_tree(48)
