"""Multi-host plane: localhost all-roles topology over real TCP sockets.

The reference exercises its multi-node system by running every role on
127.0.0.1 (``origin_repo/run.sh:1-5``); same trick here, in CI: the learner
(with its socket RemotePool) runs in the test process, actors and the
evaluator run as real spawned processes connected only by TCP — barrier,
CONFLATE param stream, credit-windowed chunk stream, stat stream all live.
"""

import dataclasses
import multiprocessing as mp
import os
import socket

import numpy as np
import pytest

from apex_tpu.config import RoleIdentity, small_test_config


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _test_config(n_actors: int):
    cfg = small_test_config(capacity=2048, batch_size=32, n_actors=n_actors)
    cfg = cfg.replace(actor=dataclasses.replace(
        cfg.actor, eps_anneal_steps=500, eps_alpha=3.0))
    batch_port, param_port, barrier_port = _free_ports(3)
    cfg = cfg.replace(comms=dataclasses.replace(
        cfg.comms, batch_port=batch_port, param_port=param_port,
        barrier_port=barrier_port))
    return cfg


def _actor_main(cfg, actor_id, n_actors):
    from apex_tpu.runtime.roles import run_actor
    run_actor(cfg, RoleIdentity(role="actor", actor_id=actor_id,
                                n_actors=n_actors), barrier_timeout_s=60)


def _evaluator_main(cfg):
    from apex_tpu.runtime.roles import run_evaluator
    run_evaluator(cfg, RoleIdentity(role="evaluator"), episodes=0,
                  max_steps=200, barrier_timeout_s=60)


@pytest.mark.slow
def test_localhost_all_roles_topology():
    n_actors = 2
    cfg = _test_config(n_actors)
    ctx = mp.get_context("spawn")

    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    procs = []
    try:
        for i in range(n_actors):
            procs.append(ctx.Process(target=_actor_main,
                                     args=(cfg, i, n_actors), daemon=True))
        procs.append(ctx.Process(target=_evaluator_main, args=(cfg,),
                                 daemon=True))
        for p in procs:
            p.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    from apex_tpu.runtime.roles import run_learner
    try:
        trainer = run_learner(cfg, n_peers=n_actors + 1, total_steps=120,
                              max_seconds=180, barrier_timeout_s=60,
                              train_ratio=8.0)
        # the fused learner trained on socket-delivered chunks
        assert trainer.steps_rate.total >= 120
        assert trainer.ingested >= cfg.replay.warmup
        assert trainer.param_version >= 2
        # actor episode stats crossed the wire
        rewards = trainer.log.history.get("learner/episode_reward")
        assert rewards, "no episode stats arrived over TCP"
        # the evaluator role reported scores (actor_id == -1)
        ids = [v for _, v in trainer.log.history.get("learner/actor_id", [])]
        assert -1.0 in ids, "no evaluator stats arrived"
        # learner-side policy sanity via the standard eval path
        assert np.isfinite(trainer.evaluate(episodes=1, max_steps=100))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10)


@pytest.mark.slow
def test_topology_sharded_learner_vector_actors():
    """The flagship scale topology in miniature: VECTORIZED actors (2
    processes x 3 env slots) feed the dp=8 SHARDED learner over real TCP —
    chunk aggregation round-robins whole chunks across 8 per-chip frame
    pools, gradients pmean over the virtual mesh, params broadcast back to
    the fleet.  This is 'N remote actors vs an 8-chip learner'
    (BASELINE.md north star) end to end in CI."""
    n_actors = 2
    cfg = _test_config(n_actors)
    cfg = cfg.replace(
        actor=dataclasses.replace(cfg.actor, n_envs_per_actor=3),
        learner=dataclasses.replace(cfg.learner, mesh_shape=(8,)))
    ctx = mp.get_context("spawn")

    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    procs = []
    try:
        for i in range(n_actors):
            procs.append(ctx.Process(target=_actor_main,
                                     args=(cfg, i, n_actors), daemon=True))
        for p in procs:
            p.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    from apex_tpu.runtime.roles import run_learner
    try:
        trainer = run_learner(cfg, n_peers=n_actors, total_steps=60,
                              max_seconds=240, barrier_timeout_s=60,
                              train_ratio=8.0)
        assert trainer.n_dp == 8
        assert trainer.steps_rate.total >= 60
        assert trainer.ingested >= cfg.replay.warmup
        # stats carry GLOBAL slot ids from the vector workers: 2 procs x 3
        # slots = ids in 0..5, with at least one beyond the scalar range
        ids = [v for _, v in trainer.log.history.get("learner/actor_id", [])]
        assert ids and max(ids) >= 2, f"vector slots missing: {set(ids)}"
        assert np.isfinite(trainer.evaluate(episodes=1, max_steps=100))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10)


def test_remote_pool_reports_silent_peers():
    """A remote actor that stops sending shows up in silent_peers after
    the threshold (the learner can't respawn remote processes, but it no
    longer loses them silently)."""
    import time as time_mod

    from apex_tpu.runtime.transport import RemotePool

    cfg = _test_config(1)
    pool = RemotePool(cfg.comms, n_peers=0, barrier_timeout_s=1)
    try:
        now = time_mod.monotonic()
        pool.receiver.last_seen = {"actor-0": now - 100.0,
                                   "actor-1": now - 1.0,
                                   "evaluator-0": now - 500.0}
        # only chunk senders count: the quiet evaluator is NOT a false alarm
        pool.receiver._chunk_senders = {"actor-0", "actor-1"}
        assert pool.silent_peers(threshold_s=30.0) == ["actor-0"]
        assert pool.silent_peers(threshold_s=200.0) == []
    finally:
        pool.receiver.stop()


def test_cli_parser_roles_and_env_twins(monkeypatch):
    from apex_tpu.runtime.cli import (build_parser, config_from_args,
                                      identity_from_args)
    monkeypatch.setenv("APEX_ROLE", "actor")
    monkeypatch.setenv("ACTOR_ID", "3")
    monkeypatch.setenv("N_ACTORS", "8")
    monkeypatch.setenv("LEARNER_IP", "10.1.2.3")
    args = build_parser().parse_args(["--env-id", "ApexCartPole-v0"])
    assert args.role == "actor"
    ident = identity_from_args(args)
    assert (ident.actor_id, ident.n_actors, ident.learner_ip) == \
        (3, 8, "10.1.2.3")
    cfg = config_from_args(args)
    assert cfg.env.env_id == "ApexCartPole-v0"
    # flags beat env vars
    args2 = build_parser().parse_args(["--role", "evaluator"])
    assert args2.role == "evaluator"
    # vector actors reachable from the CLI and its env-var twin
    monkeypatch.setenv("N_ENVS_PER_ACTOR", "16")
    cfg3 = config_from_args(build_parser().parse_args([]))
    assert cfg3.actor.n_envs_per_actor == 16
    cfg4 = config_from_args(
        build_parser().parse_args(["--n-envs-per-actor", "32"]))
    assert cfg4.actor.n_envs_per_actor == 32


def test_cli_replay_service_flags_and_env_twins(monkeypatch):
    """The replay-service topology flags ride the shared COMMON set with
    env twins, like the ports — one export configures the whole fleet."""
    from apex_tpu.runtime.cli import (build_parser, config_from_args,
                                      identity_from_args)
    args = build_parser().parse_args([])
    cfg = config_from_args(args)
    assert cfg.comms.replay_shards == 0           # default: in-learner
    assert cfg.comms.replay_strict_order

    monkeypatch.setenv("APEX_REPLAY_SHARDS", "4")
    monkeypatch.setenv("APEX_REPLAY_PORT_BASE", "54001")
    monkeypatch.setenv("REPLAY_IP", "10.9.8.7")
    monkeypatch.setenv("SHARD_ID", "2")
    args = build_parser().parse_args(["--role", "replay"])
    cfg = config_from_args(args)
    assert cfg.comms.replay_shards == 4
    assert cfg.comms.replay_port_base == 54001
    assert args.shard_id == 2
    assert identity_from_args(args).replay_ip == "10.9.8.7"
    # flags beat env twins; --replay-loose flips the ordering contract
    args = build_parser().parse_args(["--replay-shards", "2",
                                      "--replay-loose"])
    cfg = config_from_args(args)
    assert cfg.comms.replay_shards == 2
    assert not cfg.comms.replay_strict_order


def test_cli_shard_snapshot_flags_and_env_twins(monkeypatch):
    """Shard durability knobs (PR 8): snapshot dir/cadence have env
    twins so run_local.sh and the deploy bootstraps configure the whole
    shard fleet with two exports."""
    from apex_tpu.runtime.cli import build_parser, config_from_args

    args = build_parser().parse_args([])
    assert args.replay_snapshot_dir is None
    assert config_from_args(args).comms.replay_snapshot_s == 0.0

    monkeypatch.setenv("APEX_REPLAY_SNAPSHOT_DIR", "/tmp/snaps")
    monkeypatch.setenv("APEX_REPLAY_SNAPSHOT_S", "2.5")
    args = build_parser().parse_args([])
    assert args.replay_snapshot_dir == "/tmp/snaps"
    assert config_from_args(args).comms.replay_snapshot_s == 2.5

    args = build_parser().parse_args(["--replay-snapshot-dir", "/e",
                                      "--replay-snapshot-every", "9"])
    assert args.replay_snapshot_dir == "/e"     # flags beat env twins
    assert config_from_args(args).comms.replay_snapshot_s == 9.0


@pytest.mark.slow
def test_actor_rejoin_after_kill_clears_silent_peers():
    """The supervisor-respawn contract (deploy/actor.sh + roles.py
    _join_fleet / transport.barrier_wait rejoin): kill the only actor
    mid-run; the learner's
    silent_peers flags it; a respawned actor with the SAME identity
    rejoins PAST the long-gone startup barrier by observing the param
    stream, resumes shipping chunks, and silent_peers clears."""
    import threading
    import time as time_mod

    import pytest

    from apex_tpu.runtime.transport import RemotePool
    from apex_tpu.training.apex import ApexTrainer

    cfg = _test_config(1)
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay, warmup=128))
    ctx = mp.get_context("spawn")
    pool = RemotePool(cfg.comms, n_peers=1, barrier_timeout_s=60)
    trainer = ApexTrainer(cfg, publish_min_seconds=0.1, train_ratio=8.0,
                          pool=pool)

    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    try:
        actor = ctx.Process(target=_actor_main, args=(cfg, 0, 1),
                            daemon=True)
        actor.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    done = threading.Event()
    respawn = None
    try:
        t = threading.Thread(
            target=lambda: (trainer.train(total_steps=10 ** 9,
                                          max_seconds=300), done.set()),
            daemon=True)
        t.start()

        def wait_for(cond, timeout, what):
            deadline = time_mod.monotonic() + timeout
            while time_mod.monotonic() < deadline:
                if cond():
                    return
                time_mod.sleep(0.25)
            pytest.fail(f"timed out waiting for {what}")

        # phase 1: the actor joined and ships chunks.  Liveness is WAITED
        # for, not asserted instantly: during the first ingest compile the
        # bounded queues fill and the socket thread stops receiving, so
        # last_seen can legitimately be seconds stale at this moment.
        wait_for(lambda: trainer.ingested > 0, 60, "first chunks")
        wait_for(lambda: pool.silent_peers(threshold_s=5.0) == [], 30,
                 "initial liveness")

        # phase 2: SIGKILL the actor; it goes silent
        actor.kill()
        actor.join(timeout=10)
        wait_for(lambda: pool.silent_peers(threshold_s=3.0) == ["actor-0"],
                 30, "silence detection")

        # phase 3: respawn with the same identity — the barrier is gone,
        # so this exercises the param-stream rejoin path
        ingested_before = trainer.ingested
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        try:
            respawn = ctx.Process(target=_actor_main, args=(cfg, 0, 1),
                                  daemon=True)
            respawn.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        wait_for(lambda: pool.silent_peers(threshold_s=3.0) == []
                 and trainer.ingested > ingested_before,
                 90, "rejoin + silence clearing")
    finally:
        for p in (actor, respawn):
            if p is not None:
                p.terminate()
                p.join(timeout=10)
        trainer.request_stop()  # train() returns at its next iteration,
        done.wait(timeout=60)   # unwinding pool.cleanup() (bound ports)


def _aql_actor_main(cfg, actor_id, n_actors):
    from apex_tpu.runtime.roles import run_actor
    run_actor(cfg, RoleIdentity(role="actor", actor_id=actor_id,
                                n_actors=n_actors), family="aql",
              barrier_timeout_s=60)


def _r2d2_actor_main(cfg, actor_id, n_actors):
    from apex_tpu.runtime.roles import run_actor
    run_actor(cfg, RoleIdentity(role="actor", actor_id=actor_id,
                                n_actors=n_actors), family="r2d2",
              barrier_timeout_s=60)


@pytest.mark.slow
def test_localhost_r2d2_topology():
    """The recurrent family over real TCP (C13/C14 for the third model
    family): VECTORIZED stateful actor processes (2 env slots each, one
    batched [B, H] carry) ship grouped sequence messages to the socket
    learner, which trains the fused sequence step and publishes back."""
    n_actors = 2
    cfg = _test_config(n_actors)
    cfg = cfg.replace(
        env=dataclasses.replace(cfg.env, env_id="ApexCartPolePO-v0"),
        actor=dataclasses.replace(cfg.actor, n_envs_per_actor=2))
    ctx = mp.get_context("spawn")

    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    procs = []
    try:
        for i in range(n_actors):
            procs.append(ctx.Process(target=_r2d2_actor_main,
                                     args=(cfg, i, n_actors), daemon=True))
        for p in procs:
            p.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    from apex_tpu.runtime.roles import run_learner
    try:
        trainer = run_learner(cfg, n_peers=n_actors, total_steps=25,
                              max_seconds=180, family="r2d2",
                              barrier_timeout_s=60)
        assert trainer.steps_rate.total >= 25
        assert trainer.ingested >= cfg.replay.warmup
        assert trainer.param_version >= 2
        assert trainer.log.history.get("learner/episode_reward")
        assert np.isfinite(trainer.evaluate(episodes=1, max_steps=60))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10)


@pytest.mark.slow
def test_localhost_aql_topology():
    """The AQL family over real TCP (C13/C14 for the second model family):
    AQL actor processes ship a_mu-carrying chunks to the socket learner,
    which trains the fused two-loss step and publishes back."""
    n_actors = 2
    cfg = _test_config(n_actors)
    cfg = cfg.replace(
        env=dataclasses.replace(cfg.env, env_id="ApexContinuousNav-v0"),
        aql=dataclasses.replace(cfg.aql, propose_sample=8,
                                uniform_sample=16))
    ctx = mp.get_context("spawn")

    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    procs = []
    try:
        for i in range(n_actors):
            procs.append(ctx.Process(target=_aql_actor_main,
                                     args=(cfg, i, n_actors), daemon=True))
        for p in procs:
            p.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    from apex_tpu.runtime.roles import run_learner
    try:
        trainer = run_learner(cfg, n_peers=n_actors, total_steps=30,
                              max_seconds=180, family="aql",
                              barrier_timeout_s=60, train_ratio=8.0)
        assert trainer.steps_rate.total >= 30
        assert trainer.ingested >= cfg.replay.warmup
        assert trainer.param_version >= 2
        assert trainer.log.history.get("learner/episode_reward")
        assert np.isfinite(trainer.evaluate(episodes=1, max_steps=40))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10)


def test_chunk_sender_close_drains_inflight_window():
    """close() must not drop the last window of chunks: linger=0 discards
    unflushed messages, so close drains the ack-credit window first — a
    full window sent then immediately closed still arrives intact."""
    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    cfg = _test_config(1)
    recv = ChunkReceiver(cfg.comms, queue_depth=16)
    recv.start()
    try:
        s = ChunkSender(cfg.comms, "actor-0")
        w = cfg.comms.max_outstanding_sends
        for i in range(w):                 # exactly one full credit window
            assert s.send_chunk({"i": i, "blob": b"y" * 20_000})
        s.close(drain_s=10.0)              # returns early once acks land
        got = sorted(recv.chunks.get(timeout=5.0)["i"] for _ in range(w))
        assert got == list(range(w))
    finally:
        recv.stop()


def test_chunk_receiver_decode_pipeline_credits_flow():
    """The decoder-pool receiver (reference learner.py:71-114's N pullers):
    with a credit window of 3, a sender can only complete >3 sends if acks
    flow back through the decode pipeline; chunks and stats all arrive
    intact across 4 decoder threads."""
    import threading as th

    from apex_tpu.runtime.transport import ChunkReceiver, ChunkSender

    cfg = _test_config(1)
    recv = ChunkReceiver(cfg.comms, queue_depth=64, n_decoders=4)
    assert len(recv._decoders) == 4
    recv.start()
    n_chunks, senders = 12, 2
    try:
        def sender_body(sid):
            s = ChunkSender(cfg.comms, f"actor-{sid}")
            try:
                for i in range(n_chunks):
                    assert s.send_chunk({"sid": sid, "i": i,
                                         "blob": b"x" * 50_000})
                    s.send_stat({"sid": sid, "ep": i})
            finally:
                s.close()

        threads = [th.Thread(target=sender_body, args=(sid,))
                   for sid in range(senders)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "sender wedged: credits not flowing"

        got = []
        deadline = 20.0
        import time as time_mod
        end = time_mod.monotonic() + deadline
        while len(got) < senders * n_chunks and time_mod.monotonic() < end:
            try:
                got.append(recv.chunks.get(timeout=0.5))
            except Exception:
                pass
        assert len(got) == senders * n_chunks
        # per-sender arrival order is preserved enough to recover every
        # message exactly once
        per = {sid: sorted(m["i"] for m in got if m["sid"] == sid)
               for sid in range(senders)}
        for sid in range(senders):
            assert per[sid] == list(range(n_chunks))
        with recv._peers_lock:
            assert recv._chunk_senders == {"actor-0", "actor-1"}
    finally:
        recv.stop()
