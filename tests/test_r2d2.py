"""Recurrent (R2D2-style) family: model, loss oracle, sequence builder,
driver mechanics, and the partially-observable learning certificate."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.config import small_test_config
from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                       make_recurrent_policy_fn)
from apex_tpu.ops.losses import r2d2_loss
from apex_tpu.training.r2d2 import R2D2Trainer, SequenceBuilder


@pytest.fixture
def key():
    return jax.random.key(0)


# -- model ------------------------------------------------------------------

def test_recurrent_step_matches_unroll(key):
    """Stepping one frame at a time through the carry must reproduce the
    full-sequence unroll exactly — the actor/learner consistency contract
    (actors step, the loss unrolls)."""
    m = RecurrentDuelingDQN(num_actions=3, obs_is_image=False,
                            compute_dtype=jnp.float32, scale_uint8=False,
                            lstm_features=16)
    carry0 = m.initial_state(2)
    xs = jax.random.normal(key, (2, 5, 4))
    params = m.init(jax.random.key(1), xs, carry0)
    q_seq, carry_end = m.apply(params, xs, carry0)
    assert q_seq.shape == (2, 5, 3)

    c = carry0
    qs = []
    for t in range(5):
        q1, c = m.apply(params, xs[:, t:t + 1], c)
        qs.append(q1[:, 0])
    np.testing.assert_allclose(np.asarray(jnp.stack(qs, 1)),
                               np.asarray(q_seq), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c[0]), np.asarray(carry_end[0]),
                               rtol=2e-5, atol=2e-5)


def test_recurrent_image_trunk_and_policy(key):
    m = RecurrentDuelingDQN(num_actions=4, lstm_features=32)
    carry = m.initial_state(2)
    x = jnp.zeros((2, 3, 84, 84, 1), jnp.uint8)
    params = m.init(key, x, carry)
    q, _ = m.apply(params, x, carry)
    assert q.shape == (2, 3, 4) and q.dtype == jnp.float32

    policy = jax.jit(make_recurrent_policy_fn(m))
    a, qv, c2 = policy(params, x[:, 0], carry, jnp.float32(0.0),
                       jax.random.key(5))
    assert a.shape == (2,) and qv.shape == (2, 4)
    # greedy at epsilon 0
    np.testing.assert_array_equal(np.asarray(a),
                                  np.asarray(qv.argmax(axis=1)))


# -- loss oracle ------------------------------------------------------------

def test_r2d2_loss_matches_numpy_oracle():
    """Brute-force oracle over a hand-built q function: n-step returns,
    discount truncation at terminals, mask handling, per-sequence
    eta-mixed priorities."""
    b, burn, unroll, n, a = 3, 2, 4, 2, 3
    t_total = burn + unroll + n
    rng = np.random.default_rng(0)

    # a fake recurrent net: q depends only on obs (carry passthrough),
    # so the oracle can evaluate it without an RNN
    w_online = rng.normal(size=(5, a)).astype(np.float32)
    w_target = rng.normal(size=(5, a)).astype(np.float32)

    def apply_fn(params, obs_seq, carry):
        q = jnp.einsum("btd,da->bta", obs_seq, jnp.asarray(params))
        return q, carry

    obs = rng.normal(size=(b, t_total, 5)).astype(np.float32)
    action = rng.integers(0, a, (b, t_total)).astype(np.int32)
    reward = rng.normal(size=(b, t_total)).astype(np.float32)
    gamma = 0.9
    discount = np.full((b, t_total), gamma, np.float32)
    discount[0, 4] = 0.0                        # a terminal mid-sequence
    mask = np.ones((b, t_total), np.float32)
    mask[2, -3:] = 0.0                          # a padded tail
    discount[2, -3:] = 0.0
    reward[2, -3:] = 0.0
    batch = dict(obs=jnp.asarray(obs), action=jnp.asarray(action),
                 reward=jnp.asarray(reward), discount=jnp.asarray(discount),
                 mask=jnp.asarray(mask),
                 state_c=jnp.zeros((b, 1)), state_h=jnp.zeros((b, 1)))
    weights = jnp.asarray(rng.uniform(0.5, 1.5, b).astype(np.float32))

    loss, out = r2d2_loss(apply_fn, w_online, w_target, batch, weights,
                          burn_in=burn, n_steps=n)

    # ---- numpy oracle ----
    q_on = obs @ w_online                       # [b, t, a]
    q_tg = obs @ w_target
    eta, eps = 0.9, 1e-6
    exp_prios, exp_loss_terms, exp_td_means = [], [], []
    for i in range(b):
        tds, masks = [], []
        for t in range(burn, burn + unroll):
            g, dp = 0.0, 1.0
            for j in range(n):
                g += dp * reward[i, t + j]
                dp *= discount[i, t + j]
            a_star = int(q_on[i, t + n].argmax())
            target = g + dp * q_tg[i, t + n, a_star]
            td = target - q_on[i, t, action[i, t]]
            tds.append(td)
            masks.append(mask[i, t])
        tds, masks = np.array(tds), np.array(masks)
        nv = max(masks.sum(), 1.0)
        h = np.where(np.abs(tds) < 1, 0.5 * tds ** 2, np.abs(tds) - 0.5)
        exp_loss_terms.append((h * masks).sum() / nv * float(weights[i]))
        abs_m = np.abs(tds) * masks
        exp_prios.append(eta * abs_m.max() + (1 - eta) * abs_m.sum() / nv
                         + eps)
        exp_td_means.append(abs_m.sum() / nv)
    np.testing.assert_allclose(float(loss), np.mean(exp_loss_terms),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.priorities), exp_prios,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.td_abs), exp_td_means,
                               rtol=1e-5)


def test_r2d2_burn_in_carries_no_gradient():
    """Gradient w.r.t. params must not flow through the burn-in prefix.
    With a carry-accumulating fake net (``c += p * o_t``, ``q = [c, -c]``)
    and geometry burn=1/unroll=1/n=1, the real loss's gradient must equal
    a closed-form recomputation in which the prefix carry is an explicit
    ``stop_gradient(p) * o0`` constant — a leaky implementation would add
    the prefix term ``o0`` to the gradient."""
    from apex_tpu.ops.losses import huber

    rng = np.random.default_rng(1)
    o = jnp.asarray(rng.normal(size=3).astype(np.float32))
    r1, d1 = 0.4, 0.9
    pt = jnp.float32(0.7)          # target params
    p0 = jnp.float32(1.3)

    def apply_fn(params, obs_seq, carry):
        c, h = carry
        outs = []
        for t in range(obs_seq.shape[1]):
            c = c + params * obs_seq[:, t, :1]
            outs.append(jnp.concatenate([c, -c], axis=1))
        return jnp.stack(outs, 1), (c, h)

    batch = dict(obs=o.reshape(1, 3, 1),
                 action=jnp.zeros((1, 3), jnp.int32),
                 reward=jnp.asarray([[0.0, r1, 0.0]]),
                 discount=jnp.full((1, 3), d1),
                 mask=jnp.ones((1, 3)),
                 state_c=jnp.zeros((1, 1)), state_h=jnp.zeros((1, 1)))

    def loss_real(p):
        l, _ = r2d2_loss(apply_fn, p, pt, batch, jnp.ones(1),
                         burn_in=1, n_steps=1)
        return l

    def loss_manual(p):
        c0 = jax.lax.stop_gradient(p) * o[0]     # detached prefix carry
        c1 = c0 + p * o[1]                       # q at t=1 (loss position)
        c2 = c1 + p * o[2]                       # q at t=2 (bootstrap)
        ct2 = pt * o[0] + pt * o[1] + pt * o[2]  # target net carry at t=2
        q2 = jnp.stack([c2, -c2])
        qt2 = jnp.stack([ct2, -ct2])
        target = r1 + d1 * qt2[jnp.argmax(q2)]
        td = jax.lax.stop_gradient(target) - c1  # action 0 -> q_taken = c1
        return huber(td)

    np.testing.assert_allclose(float(loss_real(p0)),
                               float(loss_manual(p0)), rtol=1e-5)
    np.testing.assert_allclose(float(jax.grad(loss_real)(p0)),
                               float(jax.grad(loss_manual)(p0)), rtol=1e-5)
    # sanity: the leaky version WOULD differ (prefix term is nonzero)
    assert abs(float(o[0])) > 1e-3


# -- sequence builder -------------------------------------------------------

def test_sequence_builder_segmentation_and_padding():
    burn, unroll, n, stride = 2, 4, 2, 3
    t_total = burn + unroll + n
    b = SequenceBuilder(burn, unroll, n, gamma=0.9, stride=stride)
    ep_len = 11
    for t in range(ep_len):
        b.add_step(np.full(3, t, np.float32), t % 2, float(t),
                   terminated=(t == ep_len - 1),
                   carry_c=np.full(4, t, np.float32),
                   carry_h=np.full(4, -t, np.float32))
    b.end_episode()
    seqs = b.drain()
    # starts at 0, 3, 6, 9; start=9 has 9+burn(2) = 11 >= ep_len -> dropped
    assert len(seqs) == 3
    for i, s in enumerate(seqs):
        start = i * stride
        real = min(t_total, ep_len - start)
        np.testing.assert_array_equal(
            s["mask"], np.pad(np.ones(real), (0, t_total - real)))
        np.testing.assert_array_equal(s["obs"][:real, 0],
                                      np.arange(start, start + real))
        np.testing.assert_array_equal(s["state_c"],
                                      np.full(4, start, np.float32))
        # terminal step carries discount 0; padding too
        d = s["discount"]
        for t in range(t_total):
            step = start + t
            if t >= real or step == ep_len - 1:
                assert d[t] == 0.0
            else:
                assert d[t] == pytest.approx(0.9)
    # every env step is attributed to exactly ONE sequence despite the
    # stride overlap: transition-denominated gates stay honest
    assert sum(s["n_new"] for s in seqs) == ep_len
    assert b.drain() == []


def test_pooled_builder_rejects_stride_beyond_window():
    """The pooled message packer's union-coverage packing assumes
    OVERLAPPING windows (stride <= t_total); the guard is a ValueError at
    layout selection — it must survive ``python -O``, where the bare
    pack-time assert it replaced would vanish (ADVICE)."""
    burn, unroll, n = 2, 4, 2          # t_total = 8
    with pytest.raises(ValueError, match="stride <= t_total"):
        SequenceBuilder(burn, unroll, n, gamma=0.9, stride=9, pooled=True)
    # boundary and stacked layouts stay legal: stride == t_total packs
    # gap-free, and the stacked layout copies windows (no union packing)
    SequenceBuilder(burn, unroll, n, gamma=0.9, stride=8, pooled=True)
    SequenceBuilder(burn, unroll, n, gamma=0.9, stride=9, pooled=False)


def test_sequence_builder_emits_nothing_for_empty_episode():
    b = SequenceBuilder(2, 4, 2, gamma=0.9)
    b.end_episode()
    assert b.drain() == []
    # an episode no longer than burn_in has an all-padding loss region:
    # nothing is emitted (a max-priority zero-gradient item would waste
    # batch slots)
    for t in range(2):
        b.add_step(np.zeros(3, np.float32), 0, 0.0, t == 1,
                   np.zeros(4, np.float32), np.zeros(4, np.float32))
    b.end_episode()
    assert b.drain() == []


def test_sequence_builder_masks_truncation_boundary():
    """Loss positions whose n-step window crosses a TRUNCATION boundary
    must be masked out — they would otherwise bootstrap from padded
    all-zero observations at weight gamma^n.  Terminated boundaries stay
    unmasked (discount 0 already truncates the product)."""
    burn, unroll, n = 2, 4, 2
    ep_len = 12
    for truncated in (True, False):
        b = SequenceBuilder(burn, unroll, n, gamma=0.9, stride=3)
        for t in range(ep_len):
            b.add_step(np.zeros(3, np.float32), 0, 1.0,
                       terminated=(not truncated and t == ep_len - 1),
                       carry_c=np.zeros(4, np.float32),
                       carry_h=np.zeros(4, np.float32))
        b.end_episode(truncated=truncated)
        seqs = b.drain()
        assert seqs
        got_mask = np.zeros(ep_len)
        for i, s in enumerate(seqs):
            start = i * 3
            real = min(burn + unroll + n, ep_len - start)
            got_mask[start:start + real] = np.maximum(
                got_mask[start:start + real], s["mask"][:real])
        if truncated:
            # the last n real steps are masked in EVERY sequence
            np.testing.assert_array_equal(got_mask[-n:], 0.0)
            np.testing.assert_array_equal(got_mask[:ep_len - n], 1.0)
        else:
            np.testing.assert_array_equal(got_mask, 1.0)


# -- driver -----------------------------------------------------------------

def test_r2d2_trainer_mechanics():
    """Env loop with stateful policy, sequence ingest, fused train steps,
    eval — short mechanics run on the PO env."""
    cfg = small_test_config(capacity=512, batch_size=16,
                            env_id="ApexCartPolePO-v0")
    t = R2D2Trainer(cfg)
    t.train(total_frames=1200, log_every=10 ** 9, warmup_sequences=16)
    assert t.frames_rate.total == 1200
    assert t.steps_rate.total > 0
    assert t.sequences > 10
    assert t.env.observation_space.shape == (2,)     # velocities hidden
    assert np.isfinite(t.evaluate(episodes=1, max_steps=100))


def test_r2d2_checkpoint_roundtrip(tmp_path):
    cfg = small_test_config(capacity=512, batch_size=16,
                            env_id="ApexCartPolePO-v0")
    t = R2D2Trainer(cfg, checkpoint_dir=str(tmp_path))
    t.train(total_frames=800, log_every=10 ** 9, warmup_sequences=8)
    t.save_checkpoint()

    t2 = R2D2Trainer(cfg, checkpoint_dir=str(tmp_path))
    t2.restore()
    assert t2.steps_rate.total == t.steps_rate.total
    assert t2.sequences == t.sequences
    for a, b in zip(jax.tree.leaves(t.train_state.params),
                    jax.tree.leaves(t2.train_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_r2d2_enjoy_from_checkpoint(tmp_path):
    """evaluate_checkpoint dispatches recurrent specs (lstm_features) to a
    carry-threading policy — the trainer-free enjoy path works for this
    family's checkpoints too."""
    from apex_tpu.training.checkpoint import evaluate_checkpoint

    cfg = small_test_config(capacity=512, batch_size=16,
                            env_id="ApexCartPolePO-v0")
    t = R2D2Trainer(cfg, checkpoint_dir=str(tmp_path))
    t.train(total_frames=600, log_every=10 ** 9, warmup_sequences=8)
    path = t.save_checkpoint()
    score = evaluate_checkpoint(path, episodes=1, max_steps=100)
    assert np.isfinite(score)


@pytest.mark.slow
def test_r2d2_apex_pipeline_mechanics():
    """Distributed R2D2 (third family on the Ape-X machinery): worker
    processes act STATEFULLY (carry threading + stride-aligned stored
    state), ship grouped sequence messages with acting-time priorities,
    and the concurrent learner ingests and trains; stats flow, shutdown
    is clean."""
    from apex_tpu.training.r2d2 import R2D2ApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=16, n_actors=2,
                            env_id="ApexCartPolePO-v0")
    t = R2D2ApexTrainer(cfg, publish_min_seconds=0.05)
    t.train(total_steps=25, max_seconds=240)
    assert t.steps_rate.total >= 25
    assert t.ingested >= cfg.replay.warmup
    assert t.param_version >= 2
    assert t.log.history.get("learner/episode_reward")
    assert all(not p.is_alive() for p in t.pool.procs)
    assert np.isfinite(t.evaluate(episodes=1, max_steps=100))


@pytest.mark.slow
def test_r2d2_apex_scan_dispatch_mechanics():
    """config.scan_steps wires the R2D2 core's fused_multi_step into the
    concurrent loop like the other families (sequence ingest + unrolled
    update inside lax.scan)."""
    from apex_tpu.training.r2d2 import R2D2ApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=16, n_actors=2,
                            env_id="ApexCartPolePO-v0")
    cfg = cfg.replace(learner=dataclasses.replace(cfg.learner,
                                                  scan_steps=2))
    t = R2D2ApexTrainer(cfg, publish_min_seconds=0.05)
    assert t._multi is not None
    t.train(total_steps=25, max_seconds=240)
    assert t.steps_rate.total >= 25
    assert t.scan_dispatches > 0, "scan path never fired"
    assert all(not p.is_alive() for p in t.pool.procs)


@pytest.mark.slow
def test_r2d2_pixel_pipeline_mechanics():
    """The recurrent family on PIXELS: single 42x42 uint8 frames (no
    stack — the LSTM is the memory), conv trunk per step around the
    lax.scan unroll, sequence replay holding image sequences.  A few
    training steps prove the shape plumbing end to end."""
    cfg = small_test_config(capacity=256, batch_size=8,
                            env_id="ApexCatchSmall-v0")
    t = R2D2Trainer(cfg)
    assert t.env.observation_space.shape == (42, 42, 1)   # single frame
    t.train(total_frames=700, log_every=10 ** 9, warmup_sequences=8)
    assert t.steps_rate.total > 0
    assert t.sequences >= 8
    assert np.isfinite(t.evaluate(episodes=1, max_steps=30))


@pytest.mark.slow
def test_r2d2_apex_vector_actors():
    """Vectorized recurrent actors: 1 process x 4 env slots act through
    ONE batched policy call advancing a [B, H] carry; a slot's carry row
    zeroes on its episode reset; slots carry global ladder ids."""
    from apex_tpu.training.r2d2 import R2D2ApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=16, n_actors=1,
                            env_id="ApexCartPolePO-v0")
    cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                n_envs_per_actor=4))
    t = R2D2ApexTrainer(cfg, publish_min_seconds=0.05)
    t.train(total_steps=25, max_seconds=240)
    assert t.steps_rate.total >= 25
    assert t.ingested >= cfg.replay.warmup
    slots = {int(v) for _, v in t.log.history.get("learner/actor_id", [])}
    assert slots and max(slots) > 0, f"vector slots missing: {slots}"
    assert all(not p.is_alive() for p in t.pool.procs)


def test_sequence_builder_acting_time_priorities():
    """Insert priorities from acting-time Q vectors: per-step 1-step
    |TD| -> per-sequence 0.9*max + 0.1*mean over the loss region,
    matching the learner's eta-mix; sequences built without Q default to
    priority 1."""
    burn, unroll, n = 1, 2, 1
    b = SequenceBuilder(burn, unroll, n, gamma=0.5, stride=4)
    qs = [np.array([1.0, 3.0]), np.array([2.0, 0.5]),
          np.array([0.0, 1.0]), np.array([4.0, 4.0])]
    acts = [1, 0, 1, 0]
    rews = [1.0, -1.0, 0.5, 2.0]
    for t in range(4):
        b.add_step(np.zeros(2, np.float32), acts[t], rews[t],
                   terminated=(t == 3),
                   carry_c=np.zeros(3, np.float32),
                   carry_h=np.zeros(3, np.float32), q_values=qs[t])
    b.end_episode()
    seqs = b.drain()
    assert len(seqs) == 1
    # oracle: td[t] = |r + 0.5 * (1 - done) * max q[t+1] - q[t][a]|
    tds = []
    for t in range(4):
        boot = 0.0 if t == 3 else 0.5 * qs[t + 1].max()
        tds.append(abs(rews[t] + boot - qs[t][acts[t]]))
    # loss region = positions 1..2 (burn 1, unroll 2)
    region = np.array(tds[1:3])
    want = 0.9 * region.max() + 0.1 * region.mean() + 1e-6
    np.testing.assert_allclose(seqs[0]["priority"], want, rtol=1e-6)

    b2 = SequenceBuilder(burn, unroll, n, gamma=0.5, stride=4)
    for t in range(4):
        b2.add_step(np.zeros(2, np.float32), 0, 0.0, t == 3,
                    np.zeros(3, np.float32), np.zeros(3, np.float32))
    b2.end_episode()
    assert b2.drain()[0]["priority"] == 1.0


@pytest.mark.slow
def test_r2d2_learns_partially_observable_cartpole():
    """THE recurrence certificate: CartPole with velocities hidden is
    unsolvable for a memoryless policy beyond short balancing streaks —
    the LSTM must integrate position history into velocity estimates.
    Measured at this exact recipe: random ~20/episode, feedforward
    DQNTrainer ceiling ~42, this trainer ~192 — the 60 threshold sits
    well above the memoryless ceiling and well below the recurrent
    result."""
    cfg = small_test_config(capacity=2048, batch_size=32,
                            env_id="ApexCartPolePO-v0")
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, lr=5e-4, target_update_interval=200))
    t = R2D2Trainer(cfg, train_every=2)
    t.epsilon.decay = 5000.0
    t.train(total_frames=30_000, log_every=10 ** 9)
    eps = [v for _, v in t.log.history["learner/episode_reward"]]
    first, last = float(np.mean(eps[:15])), float(np.mean(eps[-15:]))
    score = t.evaluate(episodes=5, epsilon=0.0, max_steps=500)
    assert last > 1.5 * first, f"no training-curve improvement: {first}->{last}"
    assert score > 60.0, f"eval reward {score} <= 60: recurrence not learning"


@pytest.mark.slow
def test_r2d2_apex_learns_partially_observable_cartpole():
    """The DISTRIBUTED recurrence certificate: the same >60 bar as the
    single-process test, but learned THROUGH the concurrent plane —
    vectorized stateful worker processes (batched [B, H] carry, epsilon
    ladder) shipping grouped sequence messages over the chunk queue into
    the fused sequence learner, with params flowing back over the
    conflating publish path.  This is the recurrent analogue of the
    reference's de-facto distributed verification (SURVEY.md §4): the
    flagship bar is learning through worker processes + sequence chunks,
    not just mechanics."""
    from apex_tpu.training.r2d2 import R2D2ApexTrainer

    cfg = small_test_config(capacity=2048, batch_size=32,
                            env_id="ApexCartPolePO-v0")
    cfg = cfg.replace(
        learner=dataclasses.replace(cfg.learner, lr=5e-4,
                                    target_update_interval=200),
        # 2 procs x 2 env slots: a 4-rung ladder (0.4 .. 0.0016) with the
        # small-fleet anneal (config.py ActorConfig.eps_anneal_steps)
        actor=dataclasses.replace(cfg.actor, n_actors=2,
                                  n_envs_per_actor=2,
                                  eps_anneal_steps=4000))
    # pace the learner to the single-process recipe's ~1 update per 2
    # transitions (train_ratio counts batch_size SEQUENCES vs transitions)
    t = R2D2ApexTrainer(cfg, publish_min_seconds=0.2,
                        train_ratio=16.0, min_train_ratio=1.0)
    t.train(total_steps=8000, max_seconds=900)
    eps = [v for _, v in t.log.history["learner/episode_reward"]]
    assert len(eps) >= 30, f"too few worker episodes arrived: {len(eps)}"
    first, last = float(np.mean(eps[:15])), float(np.mean(eps[-15:]))
    score = t.evaluate(episodes=5, epsilon=0.0, max_steps=500)
    assert last > 1.5 * first, f"no training-curve improvement: {first}->{last}"
    assert score > 60.0, (f"eval reward {score} <= 60: recurrence not "
                          f"learning through the distributed plane")
    assert all(not p.is_alive() for p in t.pool.procs)
