"""Multi-tenant plane (apex_tpu/tenancy) — namespace grammar pins,
default-tenant bit-parity, per-tenant replay partitions + quota
enforcement over real sockets, per-tenant infer isolation, the placement
scheduler under fake clocks, and the tenant-labeled operator surfaces.

The load-bearing contract is default-tenant TRANSPARENCY: a fleet that
never sets APEX_TENANT must produce byte-identical identities, chunk
ids, param frames, replay state, and infer replies to the pre-tenancy
code — several tests here pin exactly that, next to the new multi-tenant
behavior.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.actors.pool import drain_builder_chunks
from apex_tpu.config import CommsConfig, small_test_config
from apex_tpu.fleet.chaos import ChaosConfig
from apex_tpu.fleet.heartbeat import Heartbeat
from apex_tpu.fleet.registry import FleetRegistry, format_fleet_table
from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs.slo import resolve_signal
from apex_tpu.ops.losses import make_optimizer
from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.replay_service import (ReplayServiceClient, ReplayShardCore,
                                     ReplayShardServer, chunk_shard)
from apex_tpu.runtime import transport, wire
from apex_tpu.tenancy import namespace as ns
from apex_tpu.tenancy.scheduler import (ACTIVE, EVICTED, PlacementScheduler,
                                        TenancyStat, assign_bands,
                                        format_tenancy_lines, place,
                                        prometheus_sections)
from apex_tpu.training.state import create_train_state

FRAME_SHAPE = (3,)
STACK = 2
K = 8
BATCH = 16


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _chunk_messages(seed: int, n_chunks: int) -> list[dict]:
    rng = np.random.default_rng(seed)
    builder = FrameChunkBuilder(2, 0.9, STACK, FRAME_SHAPE,
                                chunk_transitions=K, frame_margin=4,
                                frame_dtype=np.uint8)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        builder.begin_episode(rng.integers(0, 255, FRAME_SHAPE))
        ep_len = int(rng.integers(1, 3 * K))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 4)), float(rng.normal()),
                             rng.normal(size=4).astype(np.float32),
                             rng.integers(0, 255, FRAME_SHAPE),
                             terminated=t == ep_len - 1, truncated=False)
        msgs.extend(drain_builder_chunks(builder))
    return msgs[:n_chunks]


def _core(seed=0, quota=0, warmup=10_000) -> ReplayShardCore:
    replay = FramePoolReplay(capacity=64, frame_shape=FRAME_SHAPE,
                             frame_stack=STACK, frame_capacity=128,
                             frame_dtype="uint8")
    return ReplayShardCore(replay, jax.random.key(seed), batch_size=BATCH,
                           warmup=warmup, n_shards=1, strict_order=True,
                           quota=quota)


# -- namespace grammar pins --------------------------------------------------

def test_qualify_split_round_trip_and_default_passthrough():
    # default tenant is TRANSPARENT: bare ids in, bare ids out — the
    # whole single-tenant fleet's identities/hashes are untouched
    assert ns.qualify(ns.DEFAULT_TENANT, "actor-3") == "actor-3"
    assert ns.split("actor-3") == (ns.DEFAULT_TENANT, "actor-3")
    assert ns.param_topic(ns.DEFAULT_TENANT) == b""
    # qualified round trip
    q = ns.qualify("rally", "actor-3")
    assert q == "rally/actor-3"
    assert ns.split(q) == ("rally", "actor-3")
    assert ns.tenant_of(q) == "rally"
    assert ns.base_of(q) == "actor-3"
    # chunk ids ride the identity grammar, so tenant_of parses them too
    cid = ns.chunk_id(q, 17)
    assert cid == "rally/actor-3:17"
    assert ns.tenant_of(cid) == "rally"
    assert ns.chunk_id("actor-0", 5) == "actor-0:5"   # the pinned
    # pre-tenancy grammar — the crc32 shard-hash population is unchanged
    assert ns.tenant_of("actor-0:5") == ns.DEFAULT_TENANT


def test_tenant_name_validation():
    for bad in ("", "a/b", "a|b", "a:b"):
        assert not ns.valid_name(bad)
        with pytest.raises(ValueError):
            ns.qualify(bad or "x/y", "actor-0")
    with pytest.raises(ValueError):
        ns.TenantSpec(name="ra/lly")


def test_param_topic_framing_round_trip():
    topic = ns.param_topic("rally")
    assert topic == b"apxt/rally|"
    payload = pickle.dumps((3, {"w": 1.0}), protocol=5)
    framed = topic + payload
    assert ns.strip_topic(topic, framed) == payload
    # the wrong tenant's frame strips to None (counted + dropped)
    assert ns.strip_topic(ns.param_topic("catch"), framed) is None
    # empty topic (default tenant) passes frames through untouched —
    # EXCEPT the reserved apxt/ head, dropped by grammar so a foreign
    # tenant's frame never reaches the default tenant's unpickler
    assert ns.strip_topic(b"", payload) == payload
    assert ns.strip_topic(b"", framed) is None


def test_current_tenant_env_twin():
    assert ns.current_tenant({}) == ns.DEFAULT_TENANT
    assert ns.current_tenant({"APEX_TENANT": ""}) == ns.DEFAULT_TENANT
    assert ns.current_tenant({"APEX_TENANT": "rally"}) == "rally"
    with pytest.raises(ValueError):
        ns.current_tenant({"APEX_TENANT": "a/b"})


def test_roster_load_and_tenant_comms():
    import json
    roster = ns.load_roster({"APEX_TENANTS": json.dumps([
        {"name": "catch", "env_id": "ApexCatchSmall-v0", "weight": 3.0,
         "replay_quota": 32, "param_port": 61001, "status_port": 61003},
        {"name": "rally", "env_id": "ApexRallySmall-v0", "accel": True},
    ])})
    assert set(roster) == {"catch", "rally"}
    assert roster["catch"].replay_quota == 32
    assert roster["rally"].accel is True
    assert ns.load_roster({}) == {}
    with pytest.raises(ValueError):
        ns.load_roster({"APEX_TENANTS": json.dumps(
            [{"name": "a"}, {"name": "a"}])})
    with pytest.raises(ValueError):
        ns.TenantSpec.from_dict({"name": "a", "nope": 1})
    comms = CommsConfig()
    tc = ns.tenant_comms(comms, roster["catch"])
    assert (tc.param_port, tc.status_port) == (61001, 61003)
    # 0-ports inherit the shared defaults
    tc2 = ns.tenant_comms(comms, roster["rally"])
    assert (tc2.param_port, tc2.status_port) == (comms.param_port,
                                                 comms.status_port)


def test_shard_in_band_stays_in_band():
    band = [2, 5, 7]
    picks = {ns.shard_in_band(f"rally/actor-{i}:0", band)
             for i in range(64)}
    assert picks <= set(band) and len(picks) > 1
    assert ns.shard_in_band("x", [4]) == 4
    with pytest.raises(ValueError):
        ns.shard_in_band("x", [])


# -- param channel topics over real sockets ----------------------------------

def test_tenant_param_channel_isolated_over_sockets():
    """A rally-tenant publisher tags frames; a rally subscriber gets the
    params, and a default-tenant subscriber on the SAME endpoint rejects
    the foreign frames instead of acting on them."""
    port = _free_port()
    comms = CommsConfig(param_port=port)
    pub = transport.ParamPublisher(comms, bind_ip="127.0.0.1",
                                   topic=ns.param_topic("rally"))
    sub = transport.ParamSubscriber(comms, topic=ns.param_topic("rally"))
    default_sub = transport.ParamSubscriber(comms, topic=b"")
    try:
        time.sleep(0.3)             # slow-joiner settle
        got = None
        deadline = time.monotonic() + 10
        while got is None and time.monotonic() < deadline:
            pub.publish(7, {"w": np.float32(1.5)})
            got = sub.poll(100)
        assert got is not None
        version, params = got
        assert version == 7 and float(params["w"]) == 1.5
        # the default subscriber saw only undecodable foreign frames
        assert default_sub.poll(200) is None
        assert default_sub.rejected > 0
    finally:
        pub.close()
        sub.close()
        default_sub.close()


def test_default_param_wire_byte_identical():
    """The default tenant's publish frame is the bare pickle — the
    pre-tenancy wire format, byte for byte."""
    port = _free_port()
    comms = CommsConfig(param_port=port)
    pub = transport.ParamPublisher(comms, bind_ip="127.0.0.1", topic=b"")
    assert pub.topic == b""
    import zmq
    raw = zmq.Context.instance().socket(zmq.SUB)
    raw.setsockopt(zmq.SUBSCRIBE, b"")
    raw.connect(f"tcp://127.0.0.1:{port}")
    try:
        time.sleep(0.3)
        frame = None
        deadline = time.monotonic() + 10
        while frame is None and time.monotonic() < deadline:
            pub.publish(3, {"b": 1})
            if raw.poll(100, zmq.POLLIN):
                frame = raw.recv()
        assert frame == pickle.dumps((3, {"b": 1}), protocol=5)
    finally:
        pub.close()
        raw.close(linger=0)


# -- replay shard: per-tenant partitions over real sockets -------------------

class _TenantShard:
    """One ReplayShardServer thread with a tenant factory."""

    def __init__(self, comms, specs: dict, seed=77, warmup=10_000):
        self.core = _core(seed=seed, warmup=warmup)

        def factory(tenant):
            spec = specs.get(tenant)
            if spec is None:
                return None
            return _core(seed=seed + 1000, warmup=warmup,
                         quota=spec.replay_quota)

        self.server = ReplayShardServer(comms, 0, self.core,
                                        bind_ip="127.0.0.1",
                                        heartbeat=False,
                                        tenant_factory=factory)
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self.server.run, kwargs={"stop_event": self.stop},
            daemon=True)
        self.thread.start()

    def close(self):
        self.stop.set()
        self.thread.join(timeout=10)
        self.server.close()


def _shard_comms() -> CommsConfig:
    return CommsConfig(replay_shards=1, replay_port_base=_free_port(),
                       batch_port=_free_port())


def test_replay_partitions_isolate_and_default_stays_bit_identical():
    """Default and rally chunks land in DISJOINT partitions; the default
    partition's replay state is bit-identical to a core driven directly
    with the same messages (tenancy costs the single-tenant path
    nothing)."""
    comms = _shard_comms()
    specs = {"rally": ns.TenantSpec(name="rally")}
    shard = _TenantShard(comms, specs)
    sender = transport.ChunkSender(comms, "actor-0",
                                   port=comms.replay_port_base)
    rally_ident = ns.qualify("rally", "actor-0")
    rally_sender = transport.ChunkSender(comms, rally_ident,
                                         port=comms.replay_port_base)
    reference = _core(seed=7)       # the direct-drive twin
    try:
        default_msgs = _chunk_messages(21, 6)
        rally_msgs = _chunk_messages(99, 4)
        for i, msg in enumerate(default_msgs):
            cid = ns.chunk_id("actor-0", i)
            assert sender.send_chunk(dict(msg, chunk_id=cid))
            reference.ingest_msg(dict(msg))
        for i, msg in enumerate(rally_msgs):
            assert rally_sender.send_chunk(
                dict(msg, chunk_id=ns.chunk_id(rally_ident, i)))
        want_default = sum(int(m["n_trans"]) for m in default_msgs)
        want_rally = sum(int(m["n_trans"]) for m in rally_msgs)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if (shard.core.ingested == want_default
                    and shard.server.cores.get("rally") is not None
                    and shard.server.cores["rally"].ingested
                    == want_rally):
                break
            time.sleep(0.05)
        assert shard.core.ingested == want_default
        rally_core = shard.server.cores["rally"]
        assert rally_core.ingested == want_rally
        assert shard.server.unknown_tenant == 0
        # bit-parity: the socket-fed default partition equals the
        # direct-drive twin, leaf for leaf
        ref_leaves = jax.tree_util.tree_leaves(reference.state)
        got_leaves = jax.tree_util.tree_leaves(shard.core.state)
        assert len(ref_leaves) == len(got_leaves)
        for a, b in zip(ref_leaves, got_leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # per-tenant stats surfaced
        stats = shard.server.stats()
        assert set(stats["tenants"]) == {ns.DEFAULT_TENANT, "rally"}
        assert stats["tenants"]["rally"]["ingested"] == want_rally
    finally:
        sender.close(drain_s=0)
        rally_sender.close(drain_s=0)
        shard.close()


def test_unadmitted_tenant_refused_but_never_wedged():
    comms = _shard_comms()
    shard = _TenantShard(comms, specs={})
    ghost = ns.qualify("ghost", "actor-0")
    sender = transport.ChunkSender(comms, ghost,
                                   port=comms.replay_port_base)
    try:
        msgs = _chunk_messages(5, 4)
        for i, msg in enumerate(msgs):
            # acked (the sender's window keeps moving) but refused
            assert sender.send_chunk(
                dict(msg, chunk_id=ns.chunk_id(ghost, i)))
        deadline = time.monotonic() + 10
        while shard.server.unknown_tenant < 4 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert shard.server.unknown_tenant == 4
        assert shard.core.ingested == 0
        assert "ghost" not in shard.server.cores
    finally:
        sender.close(drain_s=0)
        shard.close()


def test_quota_enforced_under_full_partition():
    """A rally partition at its quota refuses further ingest (counted,
    acked) while the default partition keeps ingesting — one tenant can
    never squeeze another out of the shared shard."""
    comms = _shard_comms()
    specs = {"rally": ns.TenantSpec(name="rally", replay_quota=2 * K)}
    shard = _TenantShard(comms, specs)
    rally_ident = ns.qualify("rally", "actor-0")
    rally_sender = transport.ChunkSender(comms, rally_ident,
                                         port=comms.replay_port_base)
    sender = transport.ChunkSender(comms, "actor-0",
                                   port=comms.replay_port_base)
    try:
        rally_msgs = _chunk_messages(31, 6)     # 6*K trans >> quota 2*K
        # quota enforcement is CHUNK-granular: ingest while resident <
        # quota, refuse once at/over it — compute the greedy expectation
        want_rally, rally_dropped = 0, 0
        for msg in rally_msgs:
            if want_rally < 2 * K:
                want_rally += int(msg["n_trans"])
            else:
                rally_dropped += 1
        for i, msg in enumerate(rally_msgs):
            assert rally_sender.send_chunk(
                dict(msg, chunk_id=ns.chunk_id(rally_ident, i)))
        default_msgs = _chunk_messages(32, 3)
        for i, msg in enumerate(default_msgs):
            assert sender.send_chunk(
                dict(msg, chunk_id=ns.chunk_id("actor-0", i)))
        want_default = sum(int(m["n_trans"]) for m in default_msgs)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            rc = shard.server.cores.get("rally")
            if rc is not None and rc.quota_dropped >= rally_dropped \
                    and shard.core.ingested == want_default:
                break
            time.sleep(0.05)
        rc = shard.server.cores["rally"]
        assert rc.quota == 2 * K
        assert rc.ingested == want_rally    # filled to quota, then shut
        assert rc.quota_dropped == rally_dropped    # refused, acked
        assert rc.over_quota()
        assert shard.core.ingested == want_default      # unaffected
        assert shard.core.quota_dropped == 0
    finally:
        sender.close(drain_s=0)
        rally_sender.close(drain_s=0)
        shard.close()


def test_tenant_pulls_route_to_own_partition(monkeypatch):
    """Each tenant's learner pulls ITS partition's batches and its
    write-backs land on ITS core — pull/prio tuples carry the tenant,
    and the legacy tuple shapes stay the default tenant's."""
    comms = _shard_comms()
    specs = {"rally": ns.TenantSpec(name="rally")}
    shard = _TenantShard(comms, specs, warmup=1)
    sender = transport.ChunkSender(comms, "actor-0",
                                   port=comms.replay_port_base)
    rally_ident = ns.qualify("rally", "actor-0")
    rally_sender = transport.ChunkSender(comms, rally_ident,
                                         port=comms.replay_port_base)
    client = ReplayServiceClient(comms, identity="learner-a")
    monkeypatch.setenv("APEX_TENANT", "rally")
    rally_client = ReplayServiceClient(comms, identity="learner-b")
    monkeypatch.delenv("APEX_TENANT")
    assert rally_client.tenant == "rally"
    try:
        for i, msg in enumerate(_chunk_messages(41, 3)):
            assert sender.send_chunk(
                dict(msg, chunk_id=ns.chunk_id("actor-0", i)))
        for i, msg in enumerate(_chunk_messages(42, 3)):
            assert rally_sender.send_chunk(
                dict(msg, chunk_id=ns.chunk_id(rally_ident, i)))
        got = client.poll_batch(timeout=20)
        rally_got = rally_client.poll_batch(timeout=20)
        assert got is not None and rally_got is not None
        assert client.push_priorities(0, got["seq"], got["idx"],
                                      np.ones(BATCH, np.float32))
        assert rally_client.push_priorities(
            0, rally_got["seq"], rally_got["idx"],
            np.ones(BATCH, np.float32))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if shard.core.wb_applied >= 1 and \
                    shard.server.cores["rally"].wb_applied >= 1:
                break
            time.sleep(0.05)
        assert shard.core.wb_applied >= 1
        assert shard.server.cores["rally"].wb_applied >= 1
    finally:
        client.close()
        rally_client.close()
        sender.close(drain_s=0)
        rally_sender.close(drain_s=0)
        shard.close()


# -- infer server: per-(tenant, group) isolation ----------------------------

def _infer_model(seed: int):
    model = DuelingDQN(num_actions=4, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=True)
    ts = create_train_state(model, make_optimizer(), jax.random.key(seed),
                            np.zeros((1, 3 * STACK), np.uint8))
    return model, ts.params


def _ask(sock, zmq, msg, timeout_s=20.0):
    sock.send(wire.dumps(("infer", msg)))
    assert sock.poll(int(timeout_s * 1000), zmq.POLLIN)
    return wire.restricted_loads(sock.recv())


def test_infer_server_never_mixes_tenant_params():
    """Same obs geometry, two tenants, two param sets: each reply is
    bit-identical to ITS tenant's policy and the default reply to the
    default policy — the (tenant, group) coalesce key in action.  A
    tenant with no params yet gets dry replies."""
    import zmq

    from apex_tpu.infer_service.service import InferServer

    port = _free_port()
    comms = CommsConfig(infer_port=port)
    model, params = _infer_model(0)
    _, rally_params = _infer_model(1)
    policy = make_policy_fn(model)
    server = InferServer(comms, policy, heartbeat=False,
                         bind_ip="127.0.0.1")
    server.set_params(3, params, epoch=1)
    server.add_tenant("rally", policy)
    server.add_tenant("catch", policy)          # no params yet -> dry
    server.set_tenant_params("rally", 9, rally_params, epoch=2)
    stop = threading.Event()
    t = threading.Thread(target=server.run, kwargs={"stop_event": stop},
                         daemon=True)
    t.start()

    obs = np.random.default_rng(5).integers(
        0, 255, (2, 3 * STACK)).astype(np.uint8)
    eps = np.zeros(2, np.float32)
    key = jax.random.key(11)
    kd = np.asarray(jax.random.key_data(key))
    jp = jax.jit(policy)

    def expect(p):
        a, q = jp(p, obs, jnp.float32(0.0),
                  jax.random.fold_in(jax.random.wrap_key_data(kd), 0))
        return np.asarray(a), np.asarray(q)

    sock = zmq.Context.instance().socket(zmq.DEALER)
    sock.setsockopt(zmq.IDENTITY, b"probe")
    sock.connect(f"tcp://127.0.0.1:{port}")
    try:
        base = {"obs": obs, "eps": eps, "key": kd, "group": 0}
        kind, body = _ask(sock, zmq, dict(base, rid=1))
        assert kind == "act" and (body["pv"], body["epoch"]) == (3, 1)
        ea, eq = expect(params)
        np.testing.assert_array_equal(body["actions"], ea)
        np.testing.assert_array_equal(body["q"], eq)

        kind, body = _ask(sock, zmq, dict(base, rid=2, tenant="rally"))
        assert kind == "act" and (body["pv"], body["epoch"]) == (9, 2)
        ra, rq = expect(rally_params)
        np.testing.assert_array_equal(body["actions"], ra)
        np.testing.assert_array_equal(body["q"], rq)
        assert not np.array_equal(rq, eq), \
            "two distinct param sets should disagree somewhere"

        kind, body = _ask(sock, zmq, dict(base, rid=3, tenant="catch"))
        assert kind == "dry" and body["rid"] == 3       # no params yet

        kind, body = _ask(sock, zmq, dict(base, rid=4, tenant="ghost"))
        assert kind == "dry"                # unadmitted: local fallback
        assert server.unknown_tenant == 1
        assert server.gauges()["tenants"] == 3
    finally:
        stop.set()
        t.join(timeout=10)
        server.close()
        sock.close(linger=0)


def test_infer_client_stamps_tenant(monkeypatch):
    import zmq

    from apex_tpu.infer_service.client import InferClient

    port = _free_port()
    comms = CommsConfig(infer_port=port)
    router = zmq.Context.instance().socket(zmq.ROUTER)
    router.bind(f"tcp://127.0.0.1:{port}")
    monkeypatch.setenv("APEX_TENANT", "rally")
    client = InferClient(comms, ns.qualify("rally", "actor-0"),
                         wait_s=5.0)
    monkeypatch.delenv("APEX_TENANT")
    default_client = InferClient(comms, "actor-1", wait_s=5.0)
    try:
        obs = np.zeros((2, 4), np.float32)
        fb = lambda: (np.zeros(2, np.int64), np.zeros((2, 3), np.float32))
        client.submit(obs, np.zeros(2, np.float32), jax.random.key(0),
                      0, fb)
        _, payload = router.recv_multipart()
        got = wire.restricted_loads(payload)
        assert got[1]["tenant"] == "rally"
        default_client.submit(obs, np.zeros(2, np.float32),
                              jax.random.key(0), 0, fb)
        _, payload = router.recv_multipart()
        got = wire.restricted_loads(payload)
        assert "tenant" not in got[1]       # pre-tenancy request schema
    finally:
        client.close()
        default_client.close()
        router.close(linger=0)


# -- the placement scheduler -------------------------------------------------

def test_assign_bands_weighted_and_round_robin():
    assert assign_bands({"a": 1.0, "b": 1.0}, 4) == {"a": [0, 1],
                                                     "b": [2, 3]}
    assert assign_bands({"a": 3.0, "b": 1.0}, 4) == {"a": [0, 1, 2],
                                                     "b": [3]}
    # every tenant gets a shard even when outnumbered (shared bands)
    assert assign_bands({"a": 1.0, "b": 1.0, "c": 1.0}, 2) \
        == {"a": [0], "b": [1], "c": [0]}
    assert assign_bands({}, 4) == {}
    # all bands cover the tier exactly once when shards >= tenants
    bands = assign_bands({"a": 2.0, "b": 1.0, "c": 1.0}, 8)
    flat = sorted(s for band in bands.values() for s in band)
    assert flat == list(range(8))
    assert len(bands["a"]) == 4


def test_place_prefers_backend_by_tenant_kind():
    hosts = {"cpu-box": False, "tpu-box": True}
    assert place(ns.TenantSpec(name="conv", accel=True), hosts) \
        == "tpu-box"
    assert place(ns.TenantSpec(name="toy"), hosts) == "cpu-box"
    assert place(ns.TenantSpec(name="x"), {}) is None


def test_scheduler_admit_evict_rebalance_under_fake_clock():
    now = [100.0]
    sched = PlacementScheduler(4, 2, dead_after_s=10.0,
                               clock=lambda: now[0],
                               wall=lambda: now[0] + 1e9)
    catch = ns.TenantSpec(name="catch", weight=1.0)
    rally = ns.TenantSpec(name="rally", weight=1.0, accel=True)
    sched.admit(catch)
    sched.admit(rally)
    assert sched.admissions == 2
    assert sched.replay_bands == {"catch": [0, 1], "rally": [2, 3]}
    assert sched.infer_bands == {"catch": [0], "rally": [1]}
    # idempotent reconcile: re-admitting an unchanged ACTIVE spec is free
    sched.admit(catch)
    assert sched.admissions == 2

    now[0] += 5.0
    sched.observe("catch", alive=True, severity=0, steps=50)
    sched.observe("rally", alive=True, severity=2, steps=10)
    events = sched.tick({"cpu-box": False, "tpu-box": True})
    assert events == []
    snap = sched.snapshot()
    assert snap["tenants"]["rally"]["host"] == "tpu-box"
    assert snap["tenants"]["catch"]["host"] == "cpu-box"
    assert snap["tenants"]["rally"]["severity"] == 2

    # rally's learner goes silent past dead_after_s: evicted, and the
    # survivor's band grows to the whole tier
    now[0] += 11.0
    sched.observe("catch", alive=True)
    events = sched.tick()
    assert [e["event"] for e in events] == ["EVICTED", "REBALANCED"]
    assert sched.tenants["rally"].state == EVICTED
    assert sched.replay_bands == {"catch": [0, 1, 2, 3]}
    assert sched.evictions == 1

    # the learner answers again: re-admitted, bands rebalance back
    sched.observe("rally", alive=True)
    assert sched.tenants["rally"].state == ACTIVE
    assert sched.replay_bands == {"catch": [0, 1], "rally": [2, 3]}
    assert sched.admissions == 3

    snap = sched.snapshot()
    assert snap["kind"] == "apex_tenancy" and snap["version"] == 1
    assert set(snap) >= {"tenants", "admissions", "evictions",
                         "rebalances", "timeline", "n_replay_shards",
                         "n_infer_shards"}
    assert set(snap["tenants"]["rally"]) >= {
        "state", "env_id", "weight", "replay_quota", "replay_band",
        "infer_band", "host", "severity", "silent_s", "evictions"}
    # the snapshot is wire-safe inside a TenancyStat
    stat = wire.restricted_loads(wire.dumps(TenancyStat("tenant-ctl",
                                                        snap)))
    assert stat.snapshot["evictions"] == 1


def test_tenancy_exposition_and_status_lines():
    now = [0.0]
    sched = PlacementScheduler(2, 1, clock=lambda: now[0],
                               wall=lambda: 0.0)
    sched.admit(ns.TenantSpec(name="catch"))
    sched.evict("catch", "drill")
    snap = sched.snapshot()
    gauges, labeled = prometheus_sections(snap)
    assert gauges["tenancy_tenants"] == 1
    assert gauges["tenancy_evictions"] == 1
    states = dict((row[0]["tenant"], row[1])
                  for row in labeled["tenancy_tenant_state"])
    assert states["catch"] == 2             # EVICTED code
    lines = format_tenancy_lines(snap)
    assert any("tenant catch: EVICTED" in ln for ln in lines)
    assert any("EVICTED catch (drill)" in ln for ln in lines)
    # registered families cover every emitted row name (J015 contract)
    for fam in list(gauges) + list(labeled):
        assert fam in obs_metrics.REGISTERED_FAMILIES \
            or fam in {"tenancy_tenants", "tenancy_admissions",
                       "tenancy_evictions", "tenancy_rebalances"}


# -- tenant-labeled registry / status / SLO surfaces -------------------------

def test_registry_labels_peers_by_tenant_and_table_groups():
    reg = FleetRegistry(CommsConfig())
    reg.observe(Heartbeat("actor-0", role="actor", fps=10.0))
    reg.observe(Heartbeat(ns.qualify("rally", "actor-0"), role="actor",
                          fps=20.0))
    reg.observe(Heartbeat(ns.qualify("rally", "evaluator-0-ab"),
                          role="evaluator"))
    snap = reg.snapshot()
    tenants = {p["identity"]: p["tenant"] for p in snap["peers"]}
    assert tenants == {"actor-0": "t0", "rally/actor-0": "rally",
                       "rally/evaluator-0-ab": "rally"}
    table = format_fleet_table(snap)
    assert "-- tenant t0 --" in table
    assert "-- tenant rally --" in table
    # default tenant's block prints first
    assert table.index("-- tenant t0 --") \
        < table.index("-- tenant rally --")
    # tenancy timeline tail rides the status table when present
    snap["tenancy"] = {"tenants": {}, "admissions": 1, "evictions": 0,
                       "rebalances": 1,
                       "timeline": [{"t_s": 1.0, "wall": 0.0,
                                     "event": "ADMITTED",
                                     "tenant": "rally",
                                     "reason": "roster"}]}
    table = format_fleet_table(snap)
    assert "tenancy: 0 tenant(s)" in table
    assert "ADMITTED rally (roster)" in table
    # single-tenant fleets keep the pre-tenancy table (no group headers)
    solo = FleetRegistry(CommsConfig())
    solo.observe(Heartbeat("actor-0", role="actor"))
    assert "-- tenant" not in format_fleet_table(solo.snapshot())


def test_render_fleet_rows_carry_tenant_label():
    reg = FleetRegistry(CommsConfig())
    reg.observe(Heartbeat(ns.qualify("rally", "actor-0"), role="actor"))
    _, labeled = obs_metrics.render_fleet(reg.snapshot())
    labels, _v = labeled["fleet_peer_up"][0]
    assert labels["tenant"] == "rally"


def test_slo_signal_tenant_suffix_filters_peers():
    summary = {"peers": [
        {"identity": "actor-0", "tenant": "t0", "role": "actor",
         "state": "DEAD", "fps": 0.0, "gauges": {}},
        {"identity": "rally/actor-0", "tenant": "rally", "role": "actor",
         "state": "ALIVE", "fps": 30.0,
         "gauges": {"infer_rt_ms_p99": 12.0}},
        {"identity": "rally/actor-1", "tenant": "rally", "role": "actor",
         "state": "ALIVE", "fps": 20.0,
         "gauges": {"infer_rt_ms_p99": 44.0}},
    ]}
    assert resolve_signal(summary, "derived.dead_frac.actor") == 1 / 3
    assert resolve_signal(summary, "derived.dead_frac.actor@rally") == 0.0
    assert resolve_signal(summary, "derived.dead_frac.actor@t0") == 1.0
    assert resolve_signal(summary, "derived.role_fps.actor@rally") == 50.0
    assert resolve_signal(
        summary, "gauge:actor:infer_rt_ms_p99:max@rally") == 44.0
    assert resolve_signal(
        summary, "derived.dead_frac.actor@ghost") is None


# -- chaos: tenant-scoped targeting ------------------------------------------

def test_chaos_tenant_scoped_blast_radius():
    spec = {"tenant": "rally", "kill": {"actor-0": 5},
            "mute": ["replay-0"], "epoch_skew": {"learner": -1},
            "drop_frac": 0.5,
            "score_bias": {"evaluator": {"after_s": 1, "delta": -9.0}}}
    chaos = ChaosConfig(7, spec)
    hit = chaos.plan_for(ns.qualify("rally", "actor-0"))
    assert hit.kill_at == 5 and hit.drop_frac == 0.5
    assert chaos.plan_for(ns.qualify("rally", "replay-0")).mute_replies
    assert chaos.plan_for(
        ns.qualify("rally", "learner")).epoch_skew == -1
    sb = chaos.plan_for(ns.qualify("rally", "evaluator-0-ab12"))
    assert sb.score_bias_delta == -9.0
    # zero blast radius into other tenants AND the default tenant
    for other in (ns.qualify("catch", "actor-0"), "actor-0",
                  "replay-0", "evaluator-0-ab12"):
        plan = chaos.plan_for(other)
        assert plan.kill_at is None and plan.drop_frac == 0.0
        assert not plan.mute_replies and plan.epoch_skew == 0
        assert plan.score_bias_after_s is None
    # without the tenant field, full-identity keys still target exactly
    scoped = ChaosConfig(7, {"kill": {"rally/actor-0": 3}})
    assert scoped.plan_for("rally/actor-0").kill_at == 3
    assert scoped.plan_for("actor-0").kill_at is None


# -- CLI twin ---------------------------------------------------------------

def test_cli_tenant_flag_env_twin(monkeypatch):
    from apex_tpu.runtime.cli import build_parser
    monkeypatch.setenv("APEX_TENANT", "rally")
    args = build_parser().parse_args([])
    assert args.tenant == "rally"
    monkeypatch.delenv("APEX_TENANT")
    args = build_parser().parse_args(["--tenant", "catch"])
    assert args.tenant == "catch"
    assert "tenant-ctl" in build_parser().parse_args(
        ["--role", "tenant-ctl"]).role
