"""RallyEnv — the Pong-shaped adversarial pixel task (ALE stand-in):
mechanics, deflection physics, and the measured strategy ladder that makes
it a real certificate (random loses, tracking ~breaks even, edge-shot play
wins every point)."""

import numpy as np
import pytest

from apex_tpu.envs.registry import make_env
from apex_tpu.envs.toy import RallyEnv


def test_spaces_and_render():
    env = RallyEnv(grid=14, pixels=42, points=2)
    obs, _ = env.reset(seed=0)
    assert obs.shape == (42, 42, 1) and obs.dtype == np.uint8
    assert (obs == 255).any(), "ball not rendered"
    assert (obs == 128).any(), "paddles not rendered"
    # both goal columns carry a paddle
    assert (obs[:, :3] == 128).any() and (obs[:, -3:] == 128).any()


def test_registry_ids_and_stack():
    env = make_env("ApexRallySmall-v0", stack_frames=False)
    assert env.observation_space.shape == (42, 42, 1)
    stacked = make_env("ApexRallySmall-v0")       # default frame_stack=4
    assert stacked.observation_space.shape == (42, 42, 4)
    full = make_env("ApexRally-v0", stack_frames=False)
    assert full.observation_space.shape == (84, 84, 1)


def test_wall_reflection_keeps_ball_in_court():
    env = RallyEnv(grid=14, pixels=42, points=4)
    env.reset(seed=1)
    env._by, env._vy = 1.0, -RallyEnv.MAX_VY          # heading off the top
    for _ in range(50):
        env.step(0)
        assert 0 <= env._by <= env.grid - 1


def test_deflection_center_vs_edge():
    env = RallyEnv(grid=14, pixels=42, points=2)
    env.reset(seed=2)
    assert abs(env._deflect(0.0)) == RallyEnv.MIN_VY   # no stalemates
    assert env._deflect(1.0) == RallyEnv.MAX_VY
    assert env._deflect(-1.0) == -RallyEnv.MAX_VY


def test_scoring_and_episode_termination():
    env = RallyEnv(grid=14, pixels=42, points=2)
    env.reset(seed=3)
    # park the agent away from the incoming ball: every point is a miss
    total, rewards = 0, []
    env._vx, env._bx, env._by, env._vy = 1, 5.0, 2.0, 0.0
    env._agent_y = env.grid - 2.0
    done = False
    steps = 0
    while not done and steps < 500:
        _, r, done, trunc, _ = env.step(0)
        if r:
            rewards.append(r)
        # keep parking the paddle far from the rally line
        env._agent_y = env.grid - 2.0
        steps += 1
    assert rewards.count(-1.0) >= 1
    assert done and env._played == 2


# -- the strategy ladder (what makes this env a certificate) ---------------

def _run(policy, episodes=40, seed=0, env=None):
    env = env or RallyEnv(grid=14, pixels=42, points=2)
    rng = np.random.default_rng(seed)
    scores = []
    for ep in range(episodes):
        env.reset(seed=seed + ep)
        total, done, steps = 0.0, False, 0
        while not done and steps < 2000:
            _, r, done, _, _ = env.step(policy(env, rng))
            total += r
            steps += 1
        scores.append(total)
    return float(np.mean(scores))


def _toward(env, target):
    d = target - env._agent_y
    return 0 if abs(d) < 0.5 else (2 if d > 0 else 1)


def _predict_arrival(env):
    g = env.grid
    steps = (g - 1) - env._bx if env._vx > 0 else 2 * (g - 1) - env._bx
    y = (env._by + env._vy * steps) % (2 * (g - 1))
    return 2 * (g - 1) - y if y > g - 1 else y


def _edge_policy(env, rng):
    g = env.grid
    arr = _predict_arrival(env)
    if env._vx > 0 and (g - 1) - env._bx <= 3:
        sign = 1.0 if env._opp_y < (g - 1) / 2 else -1.0
        # strike with the AGENT paddle's edge (distinct from the
        # opponent's half when agent_half widens it)
        return _toward(env, arr - sign * env.agent_half)
    return _toward(env, arr)


def test_strategy_ladder_random_loses_edge_wins():
    """The adversarial structure, measured: random play loses clearly;
    the edge-shot strategy (predict arrival, strike with the paddle edge
    to steer away from the opponent) wins essentially every point —
    proof that beating the speed-1 tracking opponent is achievable
    through the deflection mechanic within the action space."""
    random_score = _run(lambda env, rng: int(rng.integers(0, 3)))
    edge_score = _run(_edge_policy)
    assert random_score < -0.5, f"random unexpectedly strong: {random_score}"
    assert edge_score > 1.5, f"edge strategy should dominate: {edge_score}"


def test_small_variant_ladder_backs_the_certificate():
    """The certificate's bar lives on the REGISTERED Small geometry (wide
    agent paddle, 0.45-speed opponent): random must still lose, plain
    tracking must win, edge play must dominate — so 'best > 0' in the
    slow certificate can never be satisfied by chance play, and a
    registry regression that collapses the Small difficulty fails HERE
    (fast) instead of as a 50-minute flaky certificate."""
    def tracker(env, rng):
        return _toward(env, env._by)

    mk = lambda: make_env("ApexRallySmall-v0",
                          stack_frames=False).unwrapped
    random_score = _run(lambda env, rng: int(rng.integers(0, 3)), env=mk())
    tracker_score = _run(tracker, env=mk())
    edge_score = _run(_edge_policy, env=mk())
    assert random_score < -0.5, f"random too strong on Small: {random_score}"
    assert tracker_score > 0.9, f"tracking should win on Small: {tracker_score}"
    assert edge_score > 1.5, f"edge should dominate on Small: {edge_score}"


@pytest.mark.slow
def test_apex_learns_rally_small(tmp_path):
    """THE adversarial pixel certificate (VERDICT r4 item 6): DQN through
    the full concurrent pipeline must BEAT the scripted opponent on net
    (score > 0 over evaluation episodes).  Context for the bar, measured
    at the Small geometry (wide agent paddle, 0.45-speed opponent —
    calibrated so a CI-budget DQN gets dense enough reward; the full
    ApexRally-v0 keeps the symmetric speed-1 duel): random play -0.68,
    plain ball-tracking +1.65, the edge-shot strategy +2.0.  A >0 score
    requires real receive-and-return play against an opponent that
    returns most shots and punishes every miss.  Scored best-over-
    retained-checkpoints like the other learning certificates (eval
    convention: origin_repo/eval.py:49-87).  Calibration history (5
    concurrent runs, 24-48k steps): symmetric Small never learned (flat
    -1.5); the 0.6-speed variant reached break-even greedy skill
    (+0.5/24k, 0.0/48k, best-checkpoint -0.2 in the full-suite run) —
    this 0.45-speed recipe adds the margin that run lacked."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.training.apex import ApexTrainer
    from apex_tpu.training.checkpoint import evaluate_checkpoint

    cfg = small_test_config(capacity=8192, batch_size=32, n_actors=3,
                            env_id="ApexRallySmall-v0")
    cfg = cfg.replace(
        env=dataclasses.replace(cfg.env, frame_stack=4),
        actor=dataclasses.replace(cfg.actor, eps_anneal_steps=2000,
                                  eps_alpha=3.0),
        learner=dataclasses.replace(cfg.learner, gamma=0.98,
                                    target_update_interval=300,
                                    save_interval=4000))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0,
                          min_train_ratio=1.0,
                          checkpoint_dir=str(tmp_path / "ck"))
    trainer.checkpointer.keep = 15
    trainer.train(total_steps=48000, max_seconds=3000)

    scores = [trainer.evaluate(episodes=10, epsilon=0.0, max_steps=400)]
    for name in trainer.checkpointer._all():
        scores.append(evaluate_checkpoint(str(tmp_path / "ck" / name),
                                          episodes=10, max_steps=400))
    best = max(scores)
    assert best > 0.0, (f"best rally policy scored {best} <= 0: not "
                        f"beating the scripted opponent")
