"""Golden tests: loss/priorities vs. a hand-written numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.losses import (double_dqn_loss, huber, make_optimizer,
                                 mixed_max_priorities)


def _numpy_oracle(q, next_q, tgt_next_q, actions, rewards, discounts,
                  weights):
    """Independent re-derivation of utils.py:64-81 semantics in numpy
    (with the per-transition discount replacing gamma**n * (1 - done))."""
    q_taken = q[np.arange(len(q)), actions]
    next_act = next_q.argmax(1)
    boot = tgt_next_q[np.arange(len(q)), next_act]
    target = rewards + discounts * boot
    td = np.abs(target - q_taken)
    prios = 0.9 * td.max() + 0.1 * td + 1e-6
    l = np.where(td < 1, 0.5 * td ** 2, td - 0.5)
    return (l * weights).mean(), td, prios


class _TableModel:
    """Deterministic 'network': Q(s) = s @ W, linear in the obs vector."""

    def __init__(self, n_actions, dim, seed):
        rng = np.random.default_rng(seed)
        self.w = rng.normal(size=(dim, n_actions)).astype(np.float32)

    def apply(self, params, x):
        return x.astype(jnp.float32) @ jnp.asarray(params)


def test_double_dqn_loss_matches_oracle():
    rng = np.random.default_rng(0)
    B, D, A, gamma = 32, 6, 4, 0.99
    m = _TableModel(A, D, 1)
    w_online = m.w
    w_target = rng.normal(size=(D, A)).astype(np.float32)

    # mix of full-window (gamma^3), truncated-tail (gamma^1) and terminal (0)
    discounts = rng.choice([gamma ** 3, gamma, 0.0], B).astype(np.float32)
    batch = dict(
        obs=rng.normal(size=(B, D)).astype(np.float32),
        next_obs=rng.normal(size=(B, D)).astype(np.float32),
        action=rng.integers(0, A, B).astype(np.int32),
        reward=rng.normal(size=B).astype(np.float32),
        discount=discounts,
    )
    weights = rng.uniform(0.2, 1.0, B).astype(np.float32)

    loss, aux = jax.jit(
        lambda p, tp, b, w: double_dqn_loss(m.apply, p, tp, b, w)
    )(w_online, w_target, batch, jnp.asarray(weights))

    q = batch["obs"] @ w_online
    nq = batch["next_obs"] @ w_online
    tnq = batch["next_obs"] @ w_target
    want_loss, want_td, want_prios = _numpy_oracle(
        q, nq, tnq, batch["action"], batch["reward"], discounts, weights)

    np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(aux.td_abs), want_td, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(aux.priorities), want_prios,
                               rtol=1e-4)


def test_huber_branches():
    x = jnp.asarray([-2.0, -1.0, -0.5, 0.0, 0.5, 1.0, 3.0])
    want = np.asarray([1.5, 0.5, 0.125, 0.0, 0.125, 0.5, 2.5])
    np.testing.assert_allclose(np.asarray(huber(x)), want, rtol=1e-6)


def test_mixed_max_priorities_positive():
    td = jnp.asarray([0.0, 1.0, 5.0])
    p = np.asarray(mixed_max_priorities(td))
    np.testing.assert_allclose(p, 0.9 * 5.0 + 0.1 * td + 1e-6, rtol=1e-6)
    assert (p > 0).all()


def test_optimizer_clips_global_norm():
    opt = make_optimizer(lr=1.0, max_grad_norm=1.0)
    params = {"w": jnp.zeros(4)}
    state = opt.init(params)
    big = {"w": jnp.full(4, 100.0)}
    updates, _ = opt.update(big, state, params)
    # after clipping to norm 1, rmsprop normalizes; update must be finite
    assert np.isfinite(np.asarray(updates["w"])).all()


def test_gradient_flows_only_through_online_q(key):
    """stop_gradient on the target: grads wrt target params must be zero."""
    m = _TableModel(3, 4, 2)
    batch = dict(
        obs=np.ones((8, 4), np.float32), next_obs=np.ones((8, 4), np.float32),
        action=np.zeros(8, np.int32), reward=np.ones(8, np.float32),
        discount=np.full(8, 0.99 ** 3, np.float32))
    w = jnp.ones(8)

    def loss_wrt_target(tp):
        return double_dqn_loss(m.apply, m.w, tp, batch, w)[0]

    g = jax.grad(loss_wrt_target)(jnp.asarray(m.w))
    np.testing.assert_allclose(np.asarray(g), 0.0, atol=1e-7)


def test_lr_schedule_steplr_parity():
    """make_optimizer's staircase decay must reproduce torch
    StepLR(step_size, gamma) (DQN.py:39): updates 0..steps-1 at base lr,
    then one multiplicative decay per boundary."""
    import optax

    opt_sched = make_optimizer(lr=1e-2, lr_decay_steps=3, lr_decay_rate=0.5)
    opt_const = make_optimizer(lr=1e-2, lr_decay_steps=0)
    params = {"w": jnp.ones(4)}
    s1, s2 = opt_sched.init(params), opt_const.init(params)
    p1, p2 = params, params
    g = {"w": jnp.full(4, 0.1)}
    for _ in range(3):               # before the boundary: identical
        u1, s1 = opt_sched.update(g, s1, p1)
        u2, s2 = opt_const.update(g, s2, p2)
        np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                                   rtol=1e-6)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    # update #3 (0-based) crosses the first staircase boundary: exactly
    # one 0.5x decay relative to the constant-lr twin
    u1, _ = opt_sched.update(g, s1, p1)
    u2, _ = opt_const.update(g, s2, p2)
    np.testing.assert_allclose(np.asarray(u1["w"]), 0.5 * np.asarray(u2["w"]),
                               rtol=1e-6)


def test_cosine_annealing_matches_torch_closed_form():
    """cosine_annealing pins torch CosineAnnealingLR's value curve
    (AQL.py:48-49): lr(0)=base, lr(T/2)=(base+eta_min)/2, lr(T)=eta_min,
    then held."""
    from apex_tpu.ops.losses import cosine_annealing

    base, t_max = 1e-4, 1000
    sched = cosine_annealing(base, t_max, base / 1000.0)
    np.testing.assert_allclose(float(sched(0)), base, rtol=1e-6)
    np.testing.assert_allclose(float(sched(t_max // 2)),
                               (base + base / 1000.0) / 2, rtol=1e-6)
    np.testing.assert_allclose(float(sched(t_max)), base / 1000.0, rtol=1e-6)
    np.testing.assert_allclose(float(sched(t_max + 500)), base / 1000.0,
                               rtol=1e-6)
    # monotone non-increasing on the annealing window
    vals = [float(sched(t)) for t in range(0, t_max + 1, 100)]
    assert all(a >= b for a, b in zip(vals, vals[1:]))
