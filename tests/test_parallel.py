"""Sharded learner on the 8-device virtual CPU mesh: compiles, runs, keeps
params replicated, and matches single-device grad math."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.dueling import DuelingDQN
from apex_tpu.parallel.learner import ShardedLearner
from apex_tpu.parallel.mesh import make_mesh
from apex_tpu.training.learner import build_learner


def _mk_batch(rng, k, dim=6, n_act=3):
    return dict(
        obs=rng.normal(size=(k, dim)).astype(np.float32),
        action=rng.integers(0, n_act, k).astype(np.int32),
        reward=rng.normal(size=k).astype(np.float32),
        next_obs=rng.normal(size=(k, dim)).astype(np.float32),
        discount=np.full(k, 0.99 ** 3, np.float32))


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1


def test_sharded_fused_step_runs_and_replicates(key):
    mesh = make_mesh()
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    core, ts, _ = build_learner(model, 256, example, key, batch_size=64,
                                target_update_interval=4)
    sl = ShardedLearner(core, mesh)

    example_item = dict(obs=jnp.zeros(6), action=jnp.int32(0),
                        reward=jnp.float32(0), next_obs=jnp.zeros(6),
                        discount=jnp.float32(0))
    rs = sl.init_replay(example_item)
    assert rs.sum_tree.shape == (8, 2 * 256)
    ts = sl.replicate_train_state(ts)

    step = sl.make_fused_step()
    rng = np.random.default_rng(0)

    for i in range(5):
        ingest, prios = sl.split_ingest(_mk_batch(rng, 64),
                                        np.ones(64, np.float32))
        keys = sl.device_keys(jax.random.key(i))
        ts, rs, metrics = step(ts, rs, ingest, prios, keys,
                               jnp.float32(0.4))

    assert int(ts.step) == 5
    assert np.isfinite(float(metrics["loss"]))
    # every shard ingested 5 * 8 = 40 transitions
    np.testing.assert_array_equal(np.asarray(rs.size), np.full(8, 40))
    # params replicated: all device shards identical
    p = jax.tree.leaves(ts.params)[0]
    assert p.sharding.is_fully_replicated


def test_split_ingest_round_robin():
    mesh = make_mesh()
    core_dummy = None  # split_ingest only uses n_dp

    class SL(ShardedLearner):
        pass

    sl = ShardedLearner.__new__(ShardedLearner)
    object.__setattr__(sl, "core", core_dummy)
    object.__setattr__(sl, "mesh", mesh)

    batch = {"x": np.arange(16)}
    prios = np.arange(16.0)
    split, sp = sl.split_ingest(batch, prios)
    # transition i lands on chip i % 8
    np.testing.assert_array_equal(split["x"][:, 0], np.arange(8))
    np.testing.assert_array_equal(split["x"][:, 1], np.arange(8, 16))
    np.testing.assert_array_equal(sp[3], [3.0, 11.0])


def test_dp8_update_matches_single_device_math(key):
    """The DP numerical contract: pmean of per-shard grads on an evenly
    split batch == full-batch gradient, so the sharded update must produce
    (near-)identical params to the single-device update on the same data."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh()
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    core, ts, _ = build_learner(model, 256, example, key, batch_size=64)
    rng = np.random.default_rng(3)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(rng, 64).items()}
    weights = jnp.asarray(rng.uniform(0.5, 1.0, 64).astype(np.float32))

    ts1, _, m1 = core.update_from_batch(ts, batch, weights)

    def per_chip(ts, b, w):
        b = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), b)
        new_ts, prios, m = core.update_from_batch(ts, b, w.reshape(-1),
                                                  axis_name="dp")
        return new_ts, m

    shard = lambda x: x.reshape((8, 8) + x.shape[1:])  # noqa: E731
    mapped = jax.shard_map(
        per_chip, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
        out_specs=(P(), P()), check_vma=False)
    ts8, m8 = jax.jit(mapped)(ts, jax.tree.map(shard, batch),
                              shard(weights))

    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(ts1.params),
                    jax.tree.leaves(ts8.params), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-7)


def test_apex_trainer_on_virtual_mesh():
    """ApexTrainer(mesh_shape=(8,)): sharded frame-pool replay + aggregated
    chunk ingest + pmean training, end to end with real actor processes."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, mesh_shape=(8,), batch_size=32, ingest_chunk=32,
        compute_dtype="float32"))
    t = ApexTrainer(cfg, publish_min_seconds=0.05)
    assert t.n_dp == 8
    t.train(total_steps=25, max_seconds=180)
    assert t.steps_rate.total >= 25
    assert t.ingested >= cfg.replay.warmup
    sizes = np.asarray(t.replay_state.size)
    assert sizes.shape == (8,) and (sizes > 0).all()
    # params stayed replicated across the mesh
    p = jax.tree.leaves(t.train_state.params)[0]
    assert p.sharding.is_fully_replicated
    assert np.isfinite(t.evaluate(episodes=1, max_steps=200))
