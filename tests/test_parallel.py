"""Sharded learner on the 8-device virtual CPU mesh: compiles, runs, keeps
params replicated, and matches single-device grad math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.dueling import DuelingDQN
from apex_tpu.parallel.learner import ShardedLearner
from apex_tpu.parallel.mesh import make_mesh, shard_map_compat
from apex_tpu.training.learner import build_learner


def _mk_batch(rng, k, dim=6, n_act=3):
    return dict(
        obs=rng.normal(size=(k, dim)).astype(np.float32),
        action=rng.integers(0, n_act, k).astype(np.int32),
        reward=rng.normal(size=k).astype(np.float32),
        next_obs=rng.normal(size=(k, dim)).astype(np.float32),
        discount=np.full(k, 0.99 ** 3, np.float32))


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1


def test_sharded_fused_step_runs_and_replicates(key):
    mesh = make_mesh()
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    core, ts, _ = build_learner(model, 256, example, key, batch_size=64,
                                target_update_interval=4)
    sl = ShardedLearner(core, mesh)

    example_item = dict(obs=jnp.zeros(6), action=jnp.int32(0),
                        reward=jnp.float32(0), next_obs=jnp.zeros(6),
                        discount=jnp.float32(0))
    rs = sl.init_replay(example_item)
    assert rs.sum_tree.shape == (8, 2 * 256)
    ts = sl.replicate_train_state(ts)

    step = sl.make_fused_step()
    rng = np.random.default_rng(0)

    for i in range(5):
        ingest, prios = sl.split_ingest(_mk_batch(rng, 64),
                                        np.ones(64, np.float32))
        keys = sl.device_keys(jax.random.key(i))
        ts, rs, metrics = step(ts, rs, ingest, prios, keys,
                               jnp.float32(0.4))

    assert int(ts.step) == 5
    assert np.isfinite(float(metrics["loss"]))
    # every shard ingested 5 * 8 = 40 transitions
    np.testing.assert_array_equal(np.asarray(rs.size), np.full(8, 40))
    # params replicated: all device shards identical
    p = jax.tree.leaves(ts.params)[0]
    assert p.sharding.is_fully_replicated


def test_sharded_r2d2_fused_step_runs_and_replicates(key):
    """The recurrent family on the dp mesh: sequence replay shards + the
    same pmean plan — ShardedLearner is duck-typed over cores, and
    R2D2Core's update signature matches the single-optimizer shape."""
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.models.recurrent import RecurrentDuelingDQN
    from apex_tpu.replay.device import DeviceReplay
    from apex_tpu.training.r2d2 import R2D2Core
    from apex_tpu.training.state import TrainState

    mesh = make_mesh()
    burn, unroll, n, t_total, h = 2, 4, 2, 8, 8
    model = RecurrentDuelingDQN(num_actions=3, obs_is_image=False,
                                compute_dtype=jnp.float32,
                                scale_uint8=False, lstm_features=h)
    optimizer = make_optimizer(lr=1e-3)
    carry0 = model.initial_state(1)
    params = model.init(key, jnp.zeros((1, t_total, 5)), carry0)
    ts = TrainState(params=params,
                    target_params=jax.tree.map(jnp.copy, params),
                    opt_state=optimizer.init(params), step=jnp.int32(0))
    replay = DeviceReplay(capacity=64)
    core = R2D2Core(model=model, replay=replay, optimizer=optimizer,
                    batch_size=16, target_update_interval=4,
                    burn_in=burn, n_steps=n)
    sl = ShardedLearner(core, mesh)
    example_item = dict(
        obs=jnp.zeros((t_total, 5)), action=jnp.zeros(t_total, jnp.int32),
        reward=jnp.zeros(t_total), discount=jnp.zeros(t_total),
        mask=jnp.zeros(t_total),
        state_c=jnp.zeros(h), state_h=jnp.zeros(h))
    rs = sl.init_replay(example_item)
    ts = sl.replicate_train_state(ts)

    step = sl.make_fused_step()
    rng = np.random.default_rng(3)

    def seq_chunk(k):
        return dict(
            obs=rng.normal(size=(k, t_total, 5)).astype(np.float32),
            action=rng.integers(0, 3, (k, t_total)).astype(np.int32),
            reward=rng.normal(size=(k, t_total)).astype(np.float32),
            discount=np.full((k, t_total), 0.97, np.float32),
            mask=np.ones((k, t_total), np.float32),
            state_c=np.zeros((k, h), np.float32),
            state_h=np.zeros((k, h), np.float32))

    for i in range(3):
        ingest, prios = sl.split_ingest(seq_chunk(16),
                                        np.ones(16, np.float32))
        ts, rs, metrics = step(ts, rs, ingest, prios,
                               sl.device_keys(jax.random.key(i)),
                               jnp.float32(0.4))

    assert int(ts.step) == 3
    assert np.isfinite(float(metrics["loss"]))
    np.testing.assert_array_equal(np.asarray(rs.size), np.full(8, 6))
    assert jax.tree.leaves(ts.params)[0].sharding.is_fully_replicated


def test_dp_divisibility_guards_are_value_errors(key):
    """The batch/dp and chunk/dp guards must survive ``python -O`` (a
    bare assert would vanish and fail later as an opaque reshape inside
    the shard_map trace) and must name the config knobs to fix."""
    mesh = make_mesh()
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    core, _, _ = build_learner(model, 256, example, key, batch_size=60)
    sl = ShardedLearner(core, mesh)            # 60 % 8 != 0
    with pytest.raises(ValueError, match="batch_size"):
        sl.make_fused_step()
    with pytest.raises(ValueError, match="mesh_shape"):
        sl.make_train_step()
    with pytest.raises(ValueError, match="send_interval"):
        sl.split_ingest({"x": np.arange(12)}, np.arange(12.0))


def test_split_ingest_round_robin():
    mesh = make_mesh()
    core_dummy = None  # split_ingest only uses n_dp

    class SL(ShardedLearner):
        pass

    sl = ShardedLearner.__new__(ShardedLearner)
    object.__setattr__(sl, "core", core_dummy)
    object.__setattr__(sl, "mesh", mesh)

    batch = {"x": np.arange(16)}
    prios = np.arange(16.0)
    split, sp = sl.split_ingest(batch, prios)
    # transition i lands on chip i % 8
    np.testing.assert_array_equal(split["x"][:, 0], np.arange(8))
    np.testing.assert_array_equal(split["x"][:, 1], np.arange(8, 16))
    np.testing.assert_array_equal(sp[3], [3.0, 11.0])


def test_dp8_update_matches_single_device_math(key):
    """The DP numerical contract: pmean of per-shard grads on an evenly
    split batch == full-batch gradient, so the sharded update must produce
    (near-)identical params to the single-device update on the same data."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh()
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    core, ts, _ = build_learner(model, 256, example, key, batch_size=64)
    rng = np.random.default_rng(3)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(rng, 64).items()}
    weights = jnp.asarray(rng.uniform(0.5, 1.0, 64).astype(np.float32))

    ts1, _, m1 = core.update_from_batch(ts, batch, weights)

    def per_chip(ts, b, w):
        b = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), b)
        new_ts, prios, m = core.update_from_batch(ts, b, w.reshape(-1),
                                                  axis_name="dp")
        return new_ts, m

    shard = lambda x: x.reshape((8, 8) + x.shape[1:])  # noqa: E731
    mapped = shard_map_compat(
        per_chip, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
        out_specs=(P(), P()), check_vma=False)
    ts8, m8 = jax.jit(mapped)(ts, jax.tree.map(shard, batch),
                              shard(weights))

    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(ts1.params),
                    jax.tree.leaves(ts8.params), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-7)


@pytest.mark.slow
def test_apex_trainer_on_virtual_mesh():
    """ApexTrainer(mesh_shape=(8,)): sharded frame-pool replay + aggregated
    chunk ingest + pmean training, end to end with real actor processes."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, mesh_shape=(8,), batch_size=32, ingest_chunk=32,
        compute_dtype="float32"))
    t = ApexTrainer(cfg, publish_min_seconds=0.05)
    assert t.n_dp == 8
    t.train(total_steps=25, max_seconds=180)
    assert t.steps_rate.total >= 25
    assert t.ingested >= cfg.replay.warmup
    sizes = np.asarray(t.replay_state.size)
    assert sizes.shape == (8,) and (sizes > 0).all()
    # params stayed replicated across the mesh
    p = jax.tree.leaves(t.train_state.params)[0]
    assert p.sharding.is_fully_replicated
    assert np.isfinite(t.evaluate(episodes=1, max_steps=200))


def test_sharded_is_weights_correct_under_skew(key):
    """VERDICT r3 weak #5: the dp-sharded IS weights must be the correct
    bias correction for the sampler actually used — per-shard stratified
    draws — under a heavily skewed, bursty priority distribution, with a
    globally consistent normalizer (PERMethods.is_weights docstring).

    Oracle: true inclusion probability of a drawn transition is
    leaf / (dp * shard_total); weight = (p_eff * N_total)^-beta, normalized
    by the max such weight over ALL shards (the pmax collective).  The
    local-total/local-size formula must reproduce this exactly, and with
    balanced shards it must equal the reference single-buffer formula."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh()
    model = DuelingDQN(num_actions=3, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    example = jnp.zeros((1, 6), jnp.float32)
    cap = 512                                     # per shard; 4096 total
    core, ts, _ = build_learner(model, cap, example, key, batch_size=64)
    sl = ShardedLearner(core, mesh)
    example_item = dict(obs=jnp.zeros(6), action=jnp.int32(0),
                        reward=jnp.float32(0), next_obs=jnp.zeros(6),
                        discount=jnp.float32(0))
    rs = sl.init_replay(example_item)
    ingest = sl.make_ingest()

    rng = np.random.default_rng(7)
    n_total = 2048
    prios_all = rng.lognormal(0.0, 2.0, n_total).astype(np.float32)
    prios_all[100:140] *= 1000.0                  # concentrated burst
    for i in range(n_total // 64):
        chunk, prios = sl.split_ingest(_mk_batch(rng, 64),
                                       prios_all[i * 64:(i + 1) * 64])
        rs = ingest(rs, chunk, prios)

    # round-robin ingest spreads the 40-row burst exactly evenly
    burst_shard = np.arange(100, 140) % 8
    np.testing.assert_array_equal(np.bincount(burst_shard, minlength=8),
                                  np.full(8, 5))

    replay = core.replay

    def per_chip(rs_, key_):
        rs_ = jax.tree.map(lambda x: x[0], rs_)
        key_ = jax.random.wrap_key_data(key_[0])
        _, w, idx = replay.sample(rs_, key_, 8, jnp.float32(0.4),
                                  axis_name="dp")
        return w[None], idx[None]

    sample = jax.jit(shard_map_compat(
        per_chip, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False))
    w, idx = sample(rs, sl.device_keys(jax.random.key(3)))

    trees = np.asarray(rs.sum_tree)               # (8, 2*cap)
    mins = np.asarray(rs.min_tree)
    shard_total = trees[:, 1]
    shard_min = mins[:, 1]
    n_shard = float(n_total) / 8                  # local size per shard
    # heavy skew is present (shard mass up to ~2x the mean): the exactness
    # below is being tested in the regime that broke the old prose claim
    assert shard_total.max() / shard_total.mean() > 1.5
    # globally consistent normalizer = pmax of per-shard max weights
    max_w = ((shard_min / shard_total * n_shard) ** (-0.4)).max()
    w, idx = np.asarray(w), np.asarray(idx)       # (8, 8) each
    for s in range(8):
        leaves = trees[s, cap + idx[s]]
        expect = (leaves / shard_total[s] * n_shard) ** (-0.4) / max_w
        np.testing.assert_allclose(w[s], expect, rtol=2e-4)
        # the local formula IS the true-sampler correction:
        # leaf/shard_total * n_shard == leaf/(8*shard_total) * n_total
        p_eff = leaves / (8.0 * shard_total[s])
        np.testing.assert_allclose(
            (p_eff * n_total) ** (-0.4) / max_w, expect, rtol=1e-5)

    # balanced-shards reduction: uniform priorities -> identical to the
    # reference single-buffer formula on every shard
    rs_u = sl.init_replay(example_item)
    for i in range(4):
        chunk, prios = sl.split_ingest(_mk_batch(rng, 64),
                                       np.full(64, 2.5, np.float32))
        rs_u = ingest(rs_u, chunk, prios)
    w_u, idx_u = sample(rs_u, sl.device_keys(jax.random.key(4)))
    w_u = np.asarray(w_u)
    # global formula: every leaf equal -> every weight exactly 1
    np.testing.assert_allclose(w_u, 1.0, rtol=1e-5)


@pytest.mark.slow
def test_aql_trainer_on_virtual_mesh():
    """AQLApexTrainer(mesh_shape=(8,)): the AQL family on the SAME sharded
    plan as the DQN flagship — per-chip replay shards with a_mu candidate
    sets, chunk aggregation, NoisyNet update keys split per chip, pmean'd
    two-loss gradients — end to end with real actor processes."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(
        learner=dataclasses.replace(cfg.learner, mesh_shape=(8,),
                                    batch_size=32, ingest_chunk=32,
                                    compute_dtype="float32"),
        aql=dataclasses.replace(cfg.aql, propose_sample=8,
                                uniform_sample=16))
    t = AQLApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0)
    assert t.n_dp == 8
    t.train(total_steps=25, max_seconds=240)
    assert t.steps_rate.total >= 25
    assert t.ingested >= cfg.replay.warmup
    sizes = np.asarray(t.replay_state.size)
    assert sizes.shape == (8,) and (sizes > 0).all()
    p = jax.tree.leaves(t.train_state.params)[0]
    assert p.sharding.is_fully_replicated
    assert np.isfinite(t.evaluate(episodes=1, max_steps=30))
