"""Test harness: force an 8-device virtual CPU platform so every sharding /
multi-chip path runs in CI without TPUs (SURVEY.md §4 implication)."""

import os

# Force-set (not setdefault): the image presets JAX_PLATFORMS=axon, and the
# axon TPU tunnel serves one client at a time — concurrent test runs would
# block forever on its TCP socket.  Tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import after env vars take effect)

# The image's sitecustomize imports jax at interpreter startup (before this
# conftest runs), so JAX_PLATFORMS=axon from the environment is already baked
# into the config default.  jax.config.update still wins as long as no backend
# has been initialized, which is the case at collection time.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def key():
    return jax.random.key(0)
