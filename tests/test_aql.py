"""AQL: model shapes/semantics, loss oracles, two-optimizer isolation,
transition builder oracle, and end-to-end learning on the continuous env."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.config import small_test_config
from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
from apex_tpu.ops.losses import (aql_param_labels, aql_proposal_loss,
                                 aql_q_loss, make_aql_optimizer)
from apex_tpu.training.aql import AQLTrainer, AQLTransitionBuilder

A, T_P, T_U = 2, 8, 16
T = T_P + T_U


def _model(**kw):
    return AQLNetwork(action_dim=A, propose_sample=T_P, uniform_sample=T_U,
                      **kw)


def _params(m, obs_dim=3, batch=4):
    obs = jnp.zeros((batch, obs_dim), jnp.float32)
    a_mu = jnp.zeros((batch, T, A), jnp.float32)
    return m.init({"params": jax.random.key(0), "noise": jax.random.key(1),
                   "sample": jax.random.key(2)}, obs, a_mu,
                  method=AQLNetwork.full_init)


def test_propose_shapes_and_bounds(key):
    m = _model(action_low=-1.5, action_high=0.5)
    params = _params(m)
    obs = jax.random.normal(key, (4, 3))
    a_mu = m.apply(params, obs, method=AQLNetwork.propose,
                   rngs={"sample": jax.random.key(3)})
    assert a_mu.shape == (4, T, A)
    # uniform candidates (first T_U rows) respect the box exactly
    uni = a_mu[:, :T_U]
    assert float(uni.min()) >= -1.5 and float(uni.max()) <= 0.5
    # proposal candidates concentrate around the learned mean
    mu = m.apply(params, obs, method=AQLNetwork.proposal_mean)
    prop = a_mu[:, T_U:]
    spread = np.abs(np.asarray(prop) - np.asarray(mu)[:, None, :]).mean()
    assert spread < 4 * np.sqrt(m.action_var)


def test_policy_epsilon_extremes(key):
    m = _model()
    params = _params(m)
    policy = jax.jit(make_aql_policy_fn(m))
    obs = jax.random.normal(key, (64, 3))
    # eps=0: the returned action IS the argmax candidate
    act, idx, a_mu, q = policy(params, obs, jnp.float32(0.0),
                               jax.random.key(5))
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(q.argmax(1)))
    chosen = np.take_along_axis(np.asarray(a_mu),
                                np.asarray(idx)[:, None, None], axis=1)[:, 0]
    np.testing.assert_array_equal(np.asarray(act), chosen)
    # eps=1: indices spread across the whole candidate set
    _, idx1, _, _ = policy(params, obs, jnp.float32(1.0), jax.random.key(6))
    assert len(np.unique(np.asarray(idx1))) > T // 4


def test_q_loss_matches_numpy_oracle(key):
    """Deterministic heads -> the TD math is checkable by hand."""
    m = _model(noisy_deterministic=True)
    params = _params(m)
    rng = np.random.default_rng(0)
    b = 8
    batch = dict(
        obs=rng.normal(size=(b, 3)).astype(np.float32),
        action=rng.integers(0, T, b).astype(np.int32),
        reward=rng.normal(size=b).astype(np.float32),
        next_obs=rng.normal(size=(b, 3)).astype(np.float32),
        discount=np.full(b, 0.99, np.float32),
        a_mu=rng.normal(size=(b, T, A)).astype(np.float32))
    weights = rng.uniform(0.5, 1.0, b).astype(np.float32)

    def score(p, obs, a_mu, noise_key):
        return m.apply(p, obs, a_mu, rngs={"noise": noise_key})

    k = jax.random.key(7)
    # apexlint: disable=J004 -- online==target here; the TD reconstruction below needs IDENTICAL noise draws
    loss, aux = aql_q_loss(score, params, params, batch, weights, k, k)

    # apexlint: disable=J004 -- same-draw reconstruction (see above)
    q = np.asarray(score(params, batch["obs"], batch["a_mu"], k))
    # apexlint: disable=J004 -- same-draw reconstruction (see above)
    qn = np.asarray(score(params, batch["next_obs"], batch["a_mu"], k))
    q_taken = q[np.arange(b), batch["action"]]
    # online==target params here, so double-DQN reduces to max
    target = batch["reward"] + batch["discount"] * qn.max(1)
    td = np.abs(target - q_taken)
    np.testing.assert_allclose(np.asarray(aux.td_abs), td, rtol=1e-5)
    huber = np.where(td < 1, 0.5 * td ** 2, td - 0.5)
    np.testing.assert_allclose(float(loss), (huber * weights).mean(),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(aux.priorities),
                               0.9 * td.max() + 0.1 * td + 1e-6, rtol=1e-5)


def test_proposal_loss_matches_gaussian_nll_oracle():
    m = _model(noisy_deterministic=True)
    params = _params(m)
    rng = np.random.default_rng(1)
    b = 8
    batch = dict(obs=rng.normal(size=(b, 3)).astype(np.float32),
                 a_mu=rng.normal(size=(b, T, A)).astype(np.float32))
    best_idx = jnp.asarray(rng.integers(0, T, b).astype(np.int32))

    def log_prob(p, obs, actions):
        return m.apply(p, obs, actions,
                       method=AQLNetwork.proposal_log_prob)

    ent_coef = 0.01
    loss = aql_proposal_loss(log_prob, params, batch, best_idx, ent_coef)

    mu = np.asarray(m.apply(params, batch["obs"],
                            method=AQLNetwork.proposal_mean))
    best = batch["a_mu"][np.arange(b), np.asarray(best_idx)]
    var = m.action_var
    lp = (-0.5 * ((best - mu) ** 2).sum(-1) / var
          - 0.5 * A * np.log(2 * np.pi * var))
    ent = 0.5 * A * (1 + np.log(2 * np.pi * var))
    np.testing.assert_allclose(float(loss), (-lp - ent_coef * ent).mean(),
                               rtol=1e-5)


@pytest.mark.slow
def test_two_optimizer_isolation():
    """The proposal loss moves ONLY proposal params; the Q loss moves only
    the rest (reference interleaved zero_grad/step, AQL_dis.py:87-101)."""
    cfg = small_test_config(capacity=256, batch_size=16,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(aql=dataclasses.replace(cfg.aql, propose_sample=T_P,
                                              uniform_sample=T_U))
    t = AQLTrainer(cfg)
    rng = np.random.default_rng(2)
    b = 16
    obs_dim = t.env.observation_space.shape[0]
    batch = dict(
        obs=rng.normal(size=(b, obs_dim)).astype(np.float32),
        action=rng.integers(0, T, b).astype(np.int32),
        reward=rng.normal(size=b).astype(np.float32),
        next_obs=rng.normal(size=(b, obs_dim)).astype(np.float32),
        discount=np.full(b, 0.99, np.float32),
        a_mu=rng.normal(size=(b, T, A)).astype(np.float32))
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    ts2, prios, metrics = t.core.update_from_batch(
        t.train_state, batch, jnp.ones(b), jax.random.key(3))
    labels = aql_param_labels(t.train_state.params)
    changed = jax.tree.map(
        lambda a, b_: bool(np.any(np.asarray(a) != np.asarray(b_))),
        t.train_state.params, ts2.params)
    for lbl, ch in zip(jax.tree.leaves(labels), jax.tree.leaves(changed),
                       strict=True):
        assert ch, f"some {lbl} leaf did not update"
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["loss_proposal"]))
    assert prios.shape == (b,)


def test_transition_builder_oracle():
    gamma = 0.9
    b = AQLTransitionBuilder(gamma)
    q0 = np.array([1.0, 5.0, 3.0])     # taken idx 1 -> q_taken 5
    q1 = np.array([2.0, 0.0, 7.0])     # max 7 bootstraps transition 0
    q2 = np.array([4.0, 1.0, 0.0])
    a_mu = np.zeros((3, 1), np.float32)
    b.add_step([0.0], 1, 1.0, [1.0], a_mu, q0, False, False)
    assert len(b) == 0                 # emission delayed one step
    b.add_step([1.0], 2, -1.0, [2.0], a_mu, q1, False, False)
    assert len(b) == 1
    b.add_step([2.0], 0, 2.0, [3.0], a_mu, q2, True, False)
    assert len(b) == 3                 # pending + terminal both flushed
    batch, prios = b.drain(3)
    np.testing.assert_allclose(batch["reward"], [1.0, -1.0, 2.0])
    np.testing.assert_allclose(batch["discount"], [gamma, gamma, 0.0])
    np.testing.assert_array_equal(batch["action"], [1, 2, 0])
    np.testing.assert_allclose(
        prios,
        [abs(1.0 + gamma * 7.0 - 5.0) + 1e-6,      # boot from q1.max
         abs(-1.0 + gamma * 4.0 - 7.0) + 1e-6,     # boot from q2.max
         abs(2.0 + 0.0 - 4.0) + 1e-6],             # terminal: no bootstrap
        rtol=1e-6)

    # truncation: learner bootstraps (discount=gamma); the priority uses the
    # current state's max-Q as proxy for the never-scored final state
    b.add_step([0.0], 0, 0.5, [1.0], a_mu, q0, False, True)
    batch, prios = b.drain(1)
    np.testing.assert_allclose(batch["discount"], [gamma])
    np.testing.assert_allclose(prios, [abs(0.5 + gamma * 5.0 - 1.0) + 1e-6],
                               rtol=1e-6)


@pytest.mark.slow
def test_aql_apex_pipeline_mechanics():
    """Distributed AQL (C9+C12): worker processes act through the
    proposal+Q policy and ship a_mu-carrying chunks; the learner ingests
    and trains concurrently, publishes versioned params, shuts down clean."""
    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=2048, batch_size=32, n_actors=2,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(aql=dataclasses.replace(cfg.aql, propose_sample=8,
                                              uniform_sample=16))
    t = AQLApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0)
    t.train(total_steps=30, max_seconds=120)
    assert t.steps_rate.total >= 30
    assert t.ingested >= cfg.replay.warmup
    assert t.param_version >= 2
    assert t.log.history.get("learner/episode_reward")
    assert all(not p.is_alive() for p in t.pool.procs)
    assert np.isfinite(t.evaluate(episodes=1, max_steps=50))


def test_aql_fused_multi_step_matches_sequential(key):
    """scan-of-K parity for the AQL core: the two-loss update with its
    NoisyNet key splits must be bit-identical inside lax.scan."""
    from apex_tpu.envs.registry import make_env
    from apex_tpu.training.aql import aql_model_spec, build_aql

    cfg = small_test_config(capacity=256, batch_size=16,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(aql=dataclasses.replace(cfg.aql, propose_sample=4,
                                              uniform_sample=4))
    env = make_env(cfg.env.env_id, cfg.env, seed=0)
    obs_shape = env.observation_space.shape
    spec = aql_model_spec(cfg, env)
    env.close()
    model, ts, replay, rs, core = build_aql(
        cfg, spec, obs_shape, np.float32, key)
    t = model.total_sample
    a_dim = spec["action_dim"]
    k_steps = 3
    rng = np.random.default_rng(2)

    def chunk(i):
        r = np.random.default_rng(50 + i)
        n = 16
        return dict(
            obs=r.normal(size=(n,) + obs_shape).astype(np.float32),
            action=r.integers(0, t, n).astype(np.int32),
            reward=r.normal(size=n).astype(np.float32),
            next_obs=r.normal(size=(n,) + obs_shape).astype(np.float32),
            discount=np.full(n, 0.99, np.float32),
            a_mu=r.normal(size=(n, t, a_dim)).astype(np.float32))

    chunks = [chunk(i) for i in range(k_steps)]
    prios = [np.abs(rng.normal(size=16)).astype(np.float32) + 0.1
             for _ in range(k_steps)]
    keys = jax.random.split(jax.random.key(4), k_steps)
    # warm the buffer so sampling has mass before the first scanned step
    rs = core.jit_ingest()(rs, chunks[0], jnp.asarray(prios[0]))
    ts_b = jax.tree.map(jnp.copy, ts)
    rs_b = jax.tree.map(jnp.copy, rs)

    fused = core.jit_fused_step()
    for i in range(k_steps):
        ts, rs, m_a = fused(ts, rs, chunks[i], jnp.asarray(prios[i]),
                            keys[i], jnp.float32(0.4))
    multi = core.jit_fused_multi_step()
    stacked = {kk: jnp.stack([jnp.asarray(c[kk]) for c in chunks])
               for kk in chunks[0]}
    ts_m, rs_m, m_m = multi(ts_b, rs_b, stacked,
                            jnp.stack([jnp.asarray(p) for p in prios]),
                            keys, jnp.float32(0.4))
    assert int(ts_m.step) == k_steps
    assert m_m["loss"].shape == (k_steps,)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ts_m.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(rs.sum_tree),
                                  np.asarray(rs_m.sum_tree))
    np.testing.assert_allclose(float(m_a["loss"]),
                               float(np.asarray(m_m["loss"])[-1]))


@pytest.mark.slow
def test_aql_apex_scan_dispatch_mechanics():
    """config.scan_steps wires the AQL core's fused_multi_step into the
    concurrent loop exactly like the DQN family (two-loss update +
    NoisyNet keys inside lax.scan)."""
    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=2048, batch_size=32, n_actors=2,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(
        aql=dataclasses.replace(cfg.aql, propose_sample=8,
                                uniform_sample=16),
        learner=dataclasses.replace(cfg.learner, scan_steps=2))
    t = AQLApexTrainer(cfg, publish_min_seconds=0.05)
    assert t._multi is not None
    t.train(total_steps=30, max_seconds=120)
    assert t.steps_rate.total >= 30
    assert t.scan_dispatches > 0, "scan path never fired"
    assert all(not p.is_alive() for p in t.pool.procs)


@pytest.mark.slow
def test_aql_apex_vector_actors():
    """Vectorized AQL actors: 1 process x 4 env slots act through ONE
    batched propose+score call; slots carry global ladder ids; the
    concurrent learner trains and shuts down clean."""
    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=2048, batch_size=32, n_actors=1,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(
        aql=dataclasses.replace(cfg.aql, propose_sample=8,
                                uniform_sample=16),
        actor=dataclasses.replace(cfg.actor, n_envs_per_actor=4))
    t = AQLApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0)
    t.train(total_steps=30, max_seconds=180)
    assert t.steps_rate.total >= 30
    assert t.ingested >= cfg.replay.warmup
    slots = {int(v) for _, v in t.log.history.get("learner/actor_id", [])}
    assert slots and max(slots) > 0, f"vector slots missing: {slots}"
    assert all(not p.is_alive() for p in t.pool.procs)


@pytest.mark.slow
def test_aql_learns_continuous_nav():
    """AQL must beat random play on ContinuousNav: random returns ~-40,
    competent proposals reach > -20 within a small CI budget."""
    cfg = small_test_config(capacity=8192, batch_size=64,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(aql=dataclasses.replace(
        cfg.aql, propose_sample=16, uniform_sample=32,
        q_lr=1e-3, proposal_lr=1e-3))
    t = AQLTrainer(cfg)
    t.epsilon.decay = 1500.0
    before = t.evaluate(episodes=5, max_steps=50)
    t.train(total_frames=6000)
    after = t.evaluate(episodes=5, max_steps=50)
    assert after > -20.0, f"eval {before} -> {after}: AQL not learning"
    assert after > before + 5.0, f"no improvement: {before} -> {after}"


# -- discrete-action AQL (reference model.py:370-376) ----------------------

def _discrete_model(n=5, propose=12, uniform=4):
    return AQLNetwork(action_dim=n, discrete=True, propose_sample=propose,
                      uniform_sample=uniform, compute_dtype=jnp.float32)


def test_discrete_propose_shapes_and_uniform_distinct(key):
    m = _discrete_model()
    t = m.total_sample
    obs = jax.random.normal(key, (6, 3))
    params = m.init({"params": jax.random.key(0),
                     "noise": jax.random.key(1),
                     "sample": jax.random.key(2)},
                    obs, jnp.zeros((6, t, 1)), method=AQLNetwork.full_init)
    a_mu = m.apply(params, obs, method=AQLNetwork.propose,
                   rngs={"sample": jax.random.key(3)})
    assert a_mu.shape == (6, t, 1)
    vals = np.asarray(a_mu)[..., 0]
    # all candidates are valid integer action indices
    np.testing.assert_array_equal(vals, np.round(vals))
    assert vals.min() >= 0 and vals.max() < m.action_dim
    # the uniform half is distinct WITHIN each row (model.py:371-373
    # replace=False semantics), per-row independent
    uni = vals[:, :m.uniform_sample]
    for row in uni:
        assert len(np.unique(row)) == m.uniform_sample


def test_discrete_log_prob_matches_softmax_oracle(key):
    m = _discrete_model()
    t = m.total_sample
    obs = jax.random.normal(key, (8, 3))
    params = m.init({"params": jax.random.key(0),
                     "noise": jax.random.key(1),
                     "sample": jax.random.key(2)},
                    obs, jnp.zeros((8, t, 1)), method=AQLNetwork.full_init)
    logits = np.asarray(m.apply(params, obs,
                                method=AQLNetwork.proposal_mean))
    actions = jnp.asarray(
        np.random.default_rng(0).integers(0, m.action_dim, 8)
    ).astype(jnp.float32)[:, None]
    lp, ent = m.apply(params, obs, actions,
                      method=AQLNetwork.proposal_log_prob)
    # numpy oracle: log softmax at the action index; categorical entropy
    z = logits - logits.max(axis=1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    idx = np.asarray(actions[:, 0], np.int32)
    np.testing.assert_allclose(np.asarray(lp),
                               logp[np.arange(8), idx], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ent),
                               -(np.exp(logp) * logp).sum(axis=1), rtol=1e-5)


def test_discrete_policy_returns_int_actions(key):
    m = _discrete_model()
    t = m.total_sample
    obs = jax.random.normal(key, (4, 3))
    params = m.init({"params": jax.random.key(0),
                     "noise": jax.random.key(1),
                     "sample": jax.random.key(2)},
                    obs, jnp.zeros((4, t, 1)), method=AQLNetwork.full_init)
    policy = jax.jit(make_aql_policy_fn(m))
    act, idx, a_mu, q = policy(params, obs, jnp.float32(0.0),
                               jax.random.key(5))
    assert act.dtype == jnp.int32 and act.shape == (4,)
    assert int(act.min()) >= 0 and int(act.max()) < m.action_dim
    # the returned action IS the argmax candidate's index value
    chosen = np.take_along_axis(np.asarray(a_mu),
                                np.asarray(q.argmax(1))[:, None, None],
                                axis=1)[:, 0, 0]
    np.testing.assert_array_equal(np.asarray(act), chosen.astype(np.int32))


@pytest.mark.slow
def test_discrete_aql_trainer_mechanics():
    """The full single-process AQL pipeline on a Discrete env (CartPole):
    spec routing, candidate storage, fused two-loss step, eval — the
    capability the r3 framework refused (VERDICT missing #4)."""
    cfg = small_test_config(capacity=1024, batch_size=16,
                            env_id="ApexCartPole-v0")
    cfg = cfg.replace(aql=dataclasses.replace(
        cfg.aql, propose_sample=12, uniform_sample=8))
    t = AQLTrainer(cfg)
    assert t.model.discrete and t.model.action_dim == 2
    assert t.model.uniform_sample == 2          # clamped to n (model.py:180)
    t.train(total_frames=400, log_every=25)
    assert t.steps_rate.total > 0
    hist = t.log.history
    losses = [v for k, series in hist.items() if "loss" in k
              for _, v in series]
    assert losses and np.isfinite(losses).all()
    assert np.isfinite(t.evaluate(episodes=2, max_steps=50))


@pytest.mark.slow
def test_aql_pixel_frame_pool_pipeline():
    """Pixel AQL end to end (VERDICT r3 weak #4): 84x84x4 uint8 Catch
    through the FRAME-POOL replay with a_mu sidecars — actor workers use
    the chunk-builder family, the learner's fused step gathers stacks on
    device and re-scores the shipped candidate sets.  Also exercises the
    Categorical (discrete) proposal on pixels."""
    import dataclasses as dc

    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=2048, batch_size=16, n_actors=1,
                            env_id="ApexCatch-v0")
    cfg = cfg.replace(
        env=dc.replace(cfg.env, frame_stack=4),
        replay=dc.replace(cfg.replay, warmup=128),
        aql=dc.replace(cfg.aql, propose_sample=8, uniform_sample=16))
    t = AQLApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0)
    # the replay really is the frame-pool layout with the sidecar declared
    assert isinstance(t.replay, FramePoolReplay)
    assert t.replay.frame_shape == (84, 84, 1)
    assert t.replay.frame_stack == 4
    assert dict(t.replay.extra_spec)["a_mu"] == (t.model.total_sample, 1)
    assert t.model.discrete and t.model.uniform_sample == 3  # clamped to n
    t.train(total_steps=10, max_seconds=240)
    assert t.steps_rate.total >= 10
    assert t.ingested >= cfg.replay.warmup
    # candidate sidecars are resident (some row was written)
    assert float(np.abs(np.asarray(t.replay_state.extras["a_mu"])).max()) > 0
    assert all(not p.is_alive() for p in t.pool.procs)
    assert np.isfinite(t.evaluate(episodes=1, max_steps=60))


@pytest.mark.slow
def test_aql_pixel_vector_actors():
    """VectorAQLPixelWorkerFamily: one process x 3 env slots of 84x84
    Catch act through ONE batched propose+score call, per-slot chunk
    builders shipping a_mu sidecars into the frame-pool learner."""
    import dataclasses as dc

    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=2048, batch_size=16, n_actors=1,
                            env_id="ApexCatch-v0")
    cfg = cfg.replace(
        env=dc.replace(cfg.env, frame_stack=2),
        replay=dc.replace(cfg.replay, warmup=128),
        actor=dc.replace(cfg.actor, n_envs_per_actor=3),
        aql=dc.replace(cfg.aql, propose_sample=8, uniform_sample=16))
    t = AQLApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0)
    assert isinstance(t.replay, FramePoolReplay)
    t.train(total_steps=8, max_seconds=240)
    assert t.steps_rate.total >= 8
    # stats carry global slot ids from the vector lanes
    slots = {int(v) for _, v in t.log.history.get("learner/actor_id", [])}
    assert slots and max(slots) >= 1, f"vector slots missing: {slots}"
    assert all(not p.is_alive() for p in t.pool.procs)
