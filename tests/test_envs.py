"""Env stack: toy envs, wrappers, FrameStack/LazyFrames, registry gating."""

import numpy as np
import pytest

from apex_tpu.config import EnvConfig
from apex_tpu.envs.registry import make_env, make_atari, num_actions
from apex_tpu.envs.toy import CartPoleEnv, CatchEnv
from apex_tpu.envs.wrappers import (ClipRewardEnv, FrameStack, LazyFrames,
                                    TimeLimit)


def test_cartpole_api_and_termination():
    env = CartPoleEnv()
    obs, info = env.reset(seed=0)
    assert obs.shape == (4,) and obs.dtype == np.float32
    steps, terminated, truncated = 0, False, False
    while not (terminated or truncated):
        obs, r, terminated, truncated, _ = env.step(0)  # constant push: falls
        assert r == 1.0
        steps += 1
        assert steps <= 500
    assert terminated  # pole falls well before the 500-step truncation


def test_cartpole_balancing_policy_outlasts_random():
    env = CartPoleEnv()

    def run(policy_fn, seed):
        obs, _ = env.reset(seed=seed)
        for t in range(500):
            obs, _, term, trunc, _ = env.step(policy_fn(obs))
            if term or trunc:
                return t + 1
        return 500

    rng = np.random.default_rng(0)
    rand = np.mean([run(lambda o: int(rng.integers(2)), s) for s in range(8)])
    # lean-correcting heuristic: push toward the fall
    good = np.mean([run(lambda o: int(o[2] + o[3] > 0), s) for s in range(8)])
    assert good > 3 * rand


def test_catch_env_pixels_and_reward():
    env = CatchEnv(balls=2)
    obs, _ = env.reset(seed=1)
    assert obs.shape == (84, 84, 1) and obs.dtype == np.uint8
    assert obs.max() == 255  # ball visible
    total, terminated = 0.0, False
    while not terminated:
        obs, r, terminated, _, _ = env.step(0)
        total += r
    assert total != 0.0  # every ball scores +-1


def test_catch_perfect_play_scores_positive():
    env = CatchEnv(balls=3)
    obs, _ = env.reset(seed=2)
    total, terminated = 0.0, False
    while not terminated:
        # track the ball: move paddle toward the bright column
        ball_col = int(np.asarray(obs)[:-4].max(axis=0).argmax()) // env._scale
        a = 0 if ball_col == env._paddle else (1 if ball_col < env._paddle else 2)
        obs, r, terminated, _, _ = env.step(a)
        total += r
    assert total == 3.0


def test_frame_stack_lazyframes_dedup():
    env = FrameStack(CatchEnv(balls=1), 4)
    obs, _ = env.reset(seed=0)
    assert isinstance(obs, LazyFrames)
    assert obs.shape == (84, 84, 4)
    arr = np.asarray(obs)
    # at reset all 4 stacked frames are the same first frame
    for c in range(1, 4):
        np.testing.assert_array_equal(arr[..., c], arr[..., 0])
    obs2, *_ = env.step(0)
    arr2 = np.asarray(obs2)
    np.testing.assert_array_equal(arr2[..., :3], arr[..., 1:])  # shifted


def test_clip_reward_sign():
    class R(CatchEnv):
        def step(self, a):
            o, r, t, tr, i = super().step(a)
            return o, r * 7.3, t, tr, i

    env = ClipRewardEnv(R(balls=1))
    env.reset(seed=0)
    rewards = set()
    term = False
    while not term:
        _, r, term, _, _ = env.step(0)
        rewards.add(r)
    assert rewards <= {-1.0, 0.0, 1.0}


def test_time_limit_truncates():
    env = TimeLimit(CartPoleEnv(max_episode_steps=10_000), 7)
    env.reset(seed=3)
    for i in range(7):
        obs, r, term, trunc, _ = env.step(int(i % 2))
    assert trunc


def test_registry_make_and_atari_gating():
    env = make_env("ApexCartPole-v0", seed=0)
    assert num_actions(env) == 2
    env = make_env("ApexCatch-v0", EnvConfig(frame_stack=4), seed=0)
    assert env.observation_space.shape == (84, 84, 4)
    with pytest.raises(ImportError, match="ale_py"):
        make_atari("PongNoFrameskip-v4")


def test_continuous_nav_env_contract():
    from apex_tpu.envs.registry import make_env
    from apex_tpu.envs.toy import ContinuousNavEnv

    env = make_env("ApexContinuousNav-v0", EnvConfig(frame_stack=1), seed=3)
    assert isinstance(env, ContinuousNavEnv)
    obs, _ = env.reset(seed=3)
    assert obs.shape == (2,) and obs.dtype == np.float32
    total = 0.0
    for t in range(30):
        obs, r, term, trunc, _ = env.step(np.array([0.5, -0.5]))
        assert r <= 0.0 and not term
        total += r
    assert trunc                     # fixed-horizon truncation
    # driving straight at the origin from a known corner improves return
    obs, _ = env.reset(seed=3)
    for _ in range(30):
        action = np.clip(-obs / 0.2, -1, 1)
        obs, r, _, _, _ = env.step(action)
    assert abs(float(np.linalg.norm(obs))) < 0.05


def test_catch_small_variant_geometry():
    from apex_tpu.envs.registry import make_env

    env = make_env("ApexCatchSmall-v0", EnvConfig(frame_stack=2), seed=0,
                   stack_frames=False)
    obs, _ = env.reset(seed=0)
    assert obs.shape == (42, 42, 1) and obs.dtype == np.uint8


def test_atari_full_stack_roundtrip():
    """One real-ALE episode through the COMPLETE wrapper stack
    (NoopReset -> MaxAndSkip -> EpisodicLife -> FireReset -> WarpFrame ->
    ClipReward -> FrameStack; reference wrapper.py:255-329).  Runs only
    when ale_py is present — absent from this image (pip has no route out;
    no vendored wheel or ROMs exist, see ROUND4_NOTES.md), so this is the
    ready-to-fire evidence the moment an emulator appears.
    """
    import pytest

    from apex_tpu.envs.registry import _ale_available
    if not _ale_available():
        pytest.skip("ale_py not installed in this image")

    from apex_tpu.config import EnvConfig
    cfg = EnvConfig(env_id="PongNoFrameskip-v4", frame_stack=4,
                    frame_skip=4)
    env = make_env(cfg.env_id, cfg, seed=7)
    obs, _ = env.reset(seed=7)
    arr = np.asarray(obs)
    assert arr.shape == (84, 84, 4) and arr.dtype == np.uint8
    assert num_actions(env) >= 4                     # Pong: 6
    steps, done, rewards = 0, False, set()
    while not done and steps < 2000:
        obs, r, term, trunc, _ = env.step(env.action_space.sample())
        rewards.add(float(r))
        done = term or trunc
        steps += 1
    assert steps > 10                                # a real episode ran
    assert rewards <= {-1.0, 0.0, 1.0}               # ClipReward active
    arr = np.asarray(obs)
    assert arr.shape == (84, 84, 4) and arr.dtype == np.uint8
    env.close()
