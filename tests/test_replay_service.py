"""Sharded replay service (apex_tpu/replay_service): N=1 strict-mode
bit-parity vs in-learner replay, chunk->shard hash stability, priority
write-back routing, shard-kill degradation (registry DEAD + learner
fallback), and hostile-payload rejection on the shard socket.

The parity pin is the load-bearing test: with ``strict_order=True`` and
one shard, the decomposed ingest -> sample -> update -> write-back
program sequence must produce bit-identical params, replay-tree state,
and PRNG key chain to the serial loop's fused dispatches under the same
event schedule (each serial-loop event — ingest-only chunk, fused
chunk+train, train-only step — maps to one shard driving sequence; the
test drives the canonical one)."""

import socket
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.actors.pool import drain_builder_chunks
from apex_tpu.config import CommsConfig, small_test_config
from apex_tpu.models.dueling import DuelingDQN
from apex_tpu.ops.losses import make_optimizer
from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.replay_service import (ReplayServiceClient, ReplayShardCore,
                                     ReplayShardServer, ShardedChunkSender,
                                     chunk_shard, shard_warmup)
from apex_tpu.runtime import transport, wire
from apex_tpu.training.learner import LearnerCore
from apex_tpu.training.state import create_train_state

# -- fixtures ---------------------------------------------------------------

FRAME_SHAPE = (3,)
STACK = 2
K = 8
BATCH = 16
WARMUP = 24


def _chunk_messages(seed: int, n_chunks: int) -> list[dict]:
    rng = np.random.default_rng(seed)
    builder = FrameChunkBuilder(2, 0.9, STACK, FRAME_SHAPE,
                                chunk_transitions=K, frame_margin=4,
                                frame_dtype=np.uint8)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        builder.begin_episode(rng.integers(0, 255, FRAME_SHAPE))
        ep_len = int(rng.integers(1, 3 * K))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 4)), float(rng.normal()),
                             rng.normal(size=4).astype(np.float32),
                             rng.integers(0, 255, FRAME_SHAPE),
                             terminated=t == ep_len - 1, truncated=False)
        msgs.extend(drain_builder_chunks(builder))
    return msgs[:n_chunks]


def _pool_spec() -> FramePoolReplay:
    return FramePoolReplay(capacity=64, frame_shape=FRAME_SHAPE,
                           frame_stack=STACK, frame_capacity=128,
                           frame_dtype="uint8")


def _learner(seed=0):
    """A compact (model, LearnerCore, TrainState) over the frame pool."""
    model = DuelingDQN(num_actions=4, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=True)
    replay = _pool_spec()
    optimizer = make_optimizer(lr=1e-3, decay=0.95, eps=1e-7, centered=True,
                               max_grad_norm=40.0, lr_decay_steps=100,
                               lr_decay_rate=0.99)
    ts = create_train_state(model, optimizer, jax.random.key(seed + 123),
                            jnp.zeros((1, 3 * STACK), jnp.uint8))
    core = LearnerCore(apply_fn=model.apply, replay=replay,
                       optimizer=optimizer, batch_size=BATCH,
                       target_update_interval=5)
    return core, ts, replay


def _beta(ingested: int, beta0=0.4, anneal=200) -> float:
    frac = min(1.0, ingested / max(1, anneal))
    return beta0 + (1.0 - beta0) * frac


def _free_port_block(n: int, tries: int = 64) -> int:
    """Base port with ``n`` consecutive free ports (shard s binds
    base + s)."""
    for _ in range(tries):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        if base + n >= 65535:
            continue
        probes = []
        try:
            for i in range(n):
                p = socket.socket()
                p.bind(("127.0.0.1", base + i))
                probes.append(p)
            return base
        except OSError:
            continue
        finally:
            for p in probes:
                p.close()
    raise RuntimeError("no consecutive free port block found")


def _comms(n_shards: int, **kw) -> CommsConfig:
    base = _free_port_block(n_shards)
    batch = _free_port_block(1)
    return CommsConfig(replay_shards=n_shards, replay_port_base=base,
                       batch_port=batch, **kw)


# -- chunk -> shard hash ----------------------------------------------------

def test_chunk_shard_hash_stable_and_uniform():
    # the routing IS the sharding function: pin it to crc32 so any
    # process (actor, shard, offline tooling) recomputes the same owner
    for cid in ("actor-0:0", "actor-3:17", "evaluator-1-ab:5"):
        for n in (1, 2, 4, 7):
            assert chunk_shard(cid, n) == zlib.crc32(cid.encode()) % n
    # regression pins (crc32 is platform-stable; these must never move)
    assert chunk_shard("actor-0:0", 4) == zlib.crc32(b"actor-0:0") % 4
    assert chunk_shard("x", 1) == 0 and chunk_shard("x", 0) == 0
    # uniform-ish over realistic ids: no shard starves
    counts = np.zeros(4, np.int64)
    for a in range(8):
        for s in range(256):
            counts[chunk_shard(f"actor-{a}:{s}", 4)] += 1
    assert counts.min() > 0.7 * counts.mean()


def test_shard_warmup_split_preserves_global_gate():
    assert shard_warmup(1000, 1) == 1000
    assert shard_warmup(1000, 4) == 250
    assert shard_warmup(1001, 4) == 251          # ceil: never train earlier
    assert shard_warmup(3, 8) == 1


# -- N=1 strict-mode bit-parity (the acceptance pin) ------------------------

def test_n1_strict_service_bit_identical_to_in_learner():
    """params + every replay-state field + the PRNG key chain after the
    same event schedule: warmup ingest-only chunks, fused chunk+train
    steps, then two train-only steps."""
    msgs = _chunk_messages(3, 14)

    # in-learner serial loop: fused ingest+train per warm chunk
    core_a, ts_a, replay_a = _learner()
    rs = replay_a.init()
    fused = core_a.jit_fused_step()
    ingest = core_a.jit_ingest()
    train = core_a.jit_train_step()
    key_a = jax.random.key(999)
    ingested = 0
    for msg in msgs:
        prios = jnp.asarray(np.asarray(msg["priorities"], np.float32))
        if ingested >= WARMUP:
            key_a, k = jax.random.split(key_a)
            ts_a, rs, _ = fused(ts_a, rs, msg["payload"], prios, k,
                                jnp.float32(_beta(ingested)))
        else:
            rs = ingest(rs, msg["payload"], prios)
        ingested += int(msg["n_trans"])
    for _ in range(2):                   # learner outpacing ingest
        key_a, k = jax.random.split(key_a)
        ts_a, rs, _ = train(ts_a, rs, k, jnp.float32(_beta(ingested)))

    # replay-service strict mode: same programs, decomposed across the
    # shard (ingest/sample/write-back) and the learner (update)
    core_b, ts_b, replay_b = _learner()
    shard = ReplayShardCore(replay_b, jax.random.key(999), batch_size=BATCH,
                            warmup=WARMUP, beta=0.4, beta_anneal=200,
                            n_shards=1, strict_order=True)
    train_b = jax.jit(core_b.update_from_batch, donate_argnums=(0,))

    def pull_train_writeback():
        nonlocal ts_b
        b = shard.next_batch()
        assert b is not None
        ts_b, prios_out, _ = train_b(ts_b, b["batch"],
                                     jnp.asarray(b["weights"]))
        shard.write_back(b["seq"], b["idx"],
                         np.asarray(jax.device_get(prios_out), np.float32))

    for msg in msgs:
        assert shard.can_ingest()
        warm_pre = shard.warm
        shard.ingest_msg(dict(msg))
        if warm_pre:
            pull_train_writeback()
    for _ in range(2):
        pull_train_writeback()

    # params bitwise
    for la, lb in zip(jax.tree.leaves(ts_a.params),
                      jax.tree.leaves(ts_b.params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert int(ts_a.step) == int(ts_b.step)
    # replay tree state bitwise, field for field
    for name in ("frames", "action", "reward", "discount", "obs_ids",
                 "next_ids", "frame_epoch", "sum_tree", "min_tree",
                 "pos", "f_epoch", "size", "max_priority"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rs, name)),
            np.asarray(getattr(shard.state, name)), err_msg=name)
    # key chain position
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(key_a)),
        np.asarray(jax.random.key_data(shard.key)))


# -- strict ordering / forgiveness ------------------------------------------

def test_strict_shard_defers_ingest_and_forgives_dead_learner():
    _, _, replay = _learner(seed=5)
    shard = ReplayShardCore(replay, jax.random.key(5), batch_size=BATCH,
                            warmup=WARMUP, strict_order=True)
    msgs = iter(_chunk_messages(11, 20))
    while not shard.warm:                # warm the shard (ingest-only)
        assert shard.can_ingest()
        shard.ingest_msg(next(msgs))
    b = shard.next_batch()               # on-demand sample (learner idle)
    assert b is not None and b["seq"] == 0
    # outstanding write-back wedges both ingest and further sampling
    assert shard.outstanding() == 1
    assert not shard.can_ingest()
    assert shard.next_batch() is None
    # a learner death between pull and write-back must not wedge forever
    assert shard.forgive_outstanding() == 1
    assert shard.can_ingest()
    # the late write-back for a forgiven batch is a counted duplicate
    assert not shard.write_back(b["seq"], b["idx"],
                                np.ones(BATCH, np.float32))
    assert shard.dup_wb == 1
    # lockstep resumes cleanly: a warm ingest pre-samples one batch, and
    # a PROPER write-back reopens the ingest gate
    shard.ingest_msg(next(msgs))
    b = shard.next_batch()
    assert b is not None and b["seq"] == 1
    assert shard.write_back(b["seq"], b["idx"],
                            np.ones(BATCH, np.float32))
    assert shard.can_ingest() and shard.outstanding() == 0


def test_loose_shard_presamples_ahead_and_never_defers():
    _, _, replay = _learner(seed=6)
    shard = ReplayShardCore(replay, jax.random.key(6), batch_size=BATCH,
                            warmup=WARMUP, strict_order=False,
                            presample_depth=2)
    msgs = iter(_chunk_messages(12, 20))
    while not shard.warm:
        assert shard.can_ingest()
        shard.ingest_msg(next(msgs))
    for _ in range(4):                   # loose mode never waits
        assert shard.can_ingest()
        shard.ingest_msg(next(msgs))
    # pre-sampled ahead, bounded by presample_depth
    assert shard.stats()["outbox"] == 2
    b0, b1 = shard.next_batch(), shard.next_batch()
    assert (b0["seq"], b1["seq"]) == (0, 1)
    # write-backs land out of band, in any order the wire delivers
    assert shard.write_back(b1["seq"], b1["idx"],
                            np.ones(BATCH, np.float32))
    assert shard.wb_applied == 2


# -- socket plane: routing, write-backs, fallback, hostile payloads ---------

class _ShardFleet:
    """N in-process ReplayShardServer threads over real TCP.

    ``warmup`` defaults high so the send phases of the socket tests stay
    wedge-free (a cold strict shard never defers ingest); tests that
    want batches lower ``servers[s].core.warmup`` afterwards — a plain
    GIL-atomic int the serving thread re-reads per message."""

    def __init__(self, comms: CommsConfig, n: int, heartbeat=False,
                 seed=77, warmup: int = 10_000):
        self.comms = comms
        self.servers = []
        self.threads = []
        self.stops = [threading.Event() for _ in range(n)]
        for s in range(n):
            _, _, replay = _learner(seed=seed + s)
            core = ReplayShardCore(replay, jax.random.key(seed + s),
                                   batch_size=BATCH, warmup=warmup,
                                   n_shards=n, strict_order=True)
            self.servers.append(ReplayShardServer(comms, s, core,
                                                  bind_ip="127.0.0.1",
                                                  heartbeat=heartbeat))
        for s, srv in enumerate(self.servers):
            t = threading.Thread(target=srv.run,
                                 kwargs={"stop_event": self.stops[s]},
                                 daemon=True)
            t.start()
            self.threads.append(t)

    def kill(self, s: int) -> None:
        """Take shard ``s`` off the air: stop its loop, close its ROUTER
        (the port goes dark — senders see an exhausted credit window,
        the learner's pulls go unanswered, heartbeats stop: the same
        observable surface as a SIGKILL)."""
        self.stops[s].set()
        self.threads[s].join(timeout=10)
        self.servers[s].close()

    def close(self) -> None:
        for s, stop in enumerate(self.stops):
            if not stop.is_set():
                stop.set()
                self.threads[s].join(timeout=5)
                self.servers[s].close()


def test_sender_routes_by_hash_and_client_round_trips():
    comms = _comms(2, max_outstanding_sends=2)
    fleet = _ShardFleet(comms, 2)
    sender = ShardedChunkSender(comms, "actor-0", shard_wait_s=5.0)
    client = ReplayServiceClient(comms, identity="learner-t")
    try:
        msgs = _chunk_messages(21, 10)
        expect = [0, 0]
        for i, msg in enumerate(msgs):
            cid = f"actor-0:{i}"
            expect[chunk_shard(cid, 2)] += int(msg["n_trans"])
            assert sender.send_chunk(dict(msg, chunk_id=cid))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            done = [srv.core.ingested for srv in fleet.servers]
            if done == expect:
                break
            time.sleep(0.05)
        assert [srv.core.ingested for srv in fleet.servers] == expect, \
            "chunks landed on the wrong shard for their id hash"
        assert sender.rerouted == 0

        # warm both shards (ingest already happened above) and pull
        # pre-sampled batches round-robin; write back to the OWNER
        for srv in fleet.servers:
            srv.core.warmup = 1
        seen_shards = set()
        for _ in range(2):
            item = client.poll_batch(timeout=20)
            assert item is not None
            seen_shards.add(item["shard"])
            assert client.push_priorities(item["shard"], item["seq"],
                                          item["idx"],
                                          np.ones(BATCH, np.float32))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(fleet.servers[s].core.wb_applied >= 1
                   for s in seen_shards):
                break
            time.sleep(0.05)
        for s in seen_shards:
            assert fleet.servers[s].core.wb_applied >= 1, \
                f"write-back never reached owning shard {s}"
        assert client.ingested_total() > 0
        assert {st["shard"] for st in client.shard_status()} == {0, 1}
    finally:
        client.close()
        sender.close(drain_s=0)
        fleet.close()


def test_dead_shard_falls_back_to_learner_and_registry_marks_dead():
    """The degradation contract: a dead shard's chunks reroute to the
    learner's direct ingest, the survivor keeps serving batches, and the
    registry (fed by shard heartbeats on the learner channel) walks
    replay-0 through SUSPECT to DEAD."""
    from apex_tpu.fleet.heartbeat import Heartbeat
    from apex_tpu.fleet.registry import DEAD, FleetRegistry

    comms = _comms(2, max_outstanding_sends=2, heartbeat_interval_s=0.2,
                   suspect_after_s=1.0, dead_after_s=2.0)
    receiver = transport.ChunkReceiver(comms, bind_ip="127.0.0.1",
                                       queue_depth=64)
    receiver.start()
    fleet = _ShardFleet(comms, 2, heartbeat=True)
    # shard_wait_s must comfortably exceed the survivor's first-chunk jit
    # compile, or a slow-but-alive shard's chunks fall back too and the
    # reroute accounting below goes soft
    sender = ShardedChunkSender(comms, "actor-0", shard_wait_s=5.0)
    registry = FleetRegistry(comms)
    try:
        def drain_beats():
            while True:
                try:
                    stat = receiver.stats.get_nowait()
                except Exception:
                    return
                if isinstance(stat, Heartbeat):
                    registry.observe(stat)

        # both shards beat into the learner channel -> ALIVE
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            drain_beats()
            if {"replay-0", "replay-1"} <= set(registry.peers):
                break
            time.sleep(0.05)
        assert {"replay-0", "replay-1"} <= set(registry.peers)

        fleet.kill(0)
        # chunks hashed to the dead shard reroute to the learner channel
        # once its credit window exhausts (the first max_outstanding
        # sends sit in the zmq buffer "in flight" — exactly what a
        # process dying mid-buffer loses)
        msgs = _chunk_messages(31, 12)
        dead_shard_chunks = 0
        for i, msg in enumerate(msgs):
            cid = f"actor-0:{i}"
            assert sender.send_chunk(dict(msg, chunk_id=cid), max_wait_s=8)
            if chunk_shard(cid, 2) == 0:
                dead_shard_chunks += 1
        assert dead_shard_chunks > comms.max_outstanding_sends, \
            "hash never filled shard 0's window — stream too short"
        expected_fallback = dead_shard_chunks - comms.max_outstanding_sends
        assert sender.rerouted == expected_fallback
        deadline = time.monotonic() + 10
        got = 0
        while time.monotonic() < deadline and got < expected_fallback:
            got += len(receiver_poll(receiver))
            time.sleep(0.02)
        assert got >= expected_fallback, \
            "fallback chunks never reached the learner"

        # the survivor keeps serving; registry walks replay-0 to DEAD
        fleet.servers[1].core.warmup = 1
        client = ReplayServiceClient(comms, identity="learner-t2")
        try:
            item = client.poll_batch(timeout=20)
            assert item is not None and item["shard"] == 1
        finally:
            client.close()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            drain_beats()
            registry.tick()
            if registry.peers["replay-0"].state == DEAD:
                break
            time.sleep(0.1)
        assert registry.peers["replay-0"].state == DEAD
        assert registry.peers["replay-1"].state != DEAD
        snap = registry.snapshot()
        dead_roles = [p["role"] for p in snap["peers"]
                      if p["state"] == DEAD]
        assert dead_roles == ["replay"]
    finally:
        sender.close(drain_s=0)
        fleet.close()
        receiver.stop()


def receiver_poll(receiver, n: int = 64) -> list:
    out = []
    for _ in range(n):
        try:
            out.append(receiver.chunks.get_nowait())
        except Exception:
            break
    return out


def test_shard_socket_rejects_hostile_payload_without_ack():
    import pickle
    import zmq

    comms = _comms(1)
    fleet = _ShardFleet(comms, 1)
    try:
        sock = zmq.Context.instance().socket(zmq.DEALER)
        sock.setsockopt(zmq.IDENTITY, b"mallory")
        sock.connect(f"tcp://127.0.0.1:{comms.replay_port_base}")

        class Evil:
            def __reduce__(self):
                return (print, ("pwned",))

        sock.send(pickle.dumps(("chunk", Evil())))
        sock.send(wire.dumps(("not-a-kind", 1)))    # well-pickled garbage
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if fleet.servers[0].rejected >= 2:
                break
            time.sleep(0.05)
        assert fleet.servers[0].rejected >= 2
        # no ack came back for either (an ack would grant hostile credit)
        assert not sock.poll(200, zmq.POLLIN)
        # and the shard still serves honest traffic afterwards
        honest = ShardedChunkSender(comms, "actor-9", shard_wait_s=5.0)
        try:
            assert honest.send_chunk(dict(_chunk_messages(41, 1)[0],
                                          chunk_id="actor-9:0"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if fleet.servers[0].core.chunks:
                    break
                time.sleep(0.05)
            assert fleet.servers[0].core.chunks == 1
        finally:
            honest.close(drain_s=0)
        sock.close(linger=0)
    finally:
        fleet.close()


# -- chaos: the replay-shard fault gate -------------------------------------

def test_replay_shard_chaos_drop_is_deterministic(monkeypatch):
    from apex_tpu.fleet.chaos import ChaosConfig
    from apex_tpu.replay_service.service import _ShardChaos

    spec = {"drop_frac": 0.3, "kill": {"replay-1": 5}}

    def run(identity):
        chaos = _ShardChaos(ChaosConfig(7, spec).plan_for(identity))
        return [chaos.on_chunk() for _ in range(50)], chaos.dropped

    fates_a, dropped_a = run("replay-0")
    fates_b, dropped_b = run("replay-0")
    assert fates_a == fates_b and dropped_a == dropped_b > 0

    # kill fires on the scheduled ingest index — and only for its shard
    died = []
    monkeypatch.setattr("apex_tpu.fleet.chaos._die",
                        lambda ident, i: died.append((ident, i)) or
                        (_ for _ in ()).throw(SystemExit))
    chaos = _ShardChaos(ChaosConfig(7, spec).plan_for("replay-1"))
    with pytest.raises(SystemExit):
        for _ in range(50):
            chaos.on_chunk()
    assert died == [("replay-1", 5)]


# -- shard durability: kill -> snapshot restore bit-parity (PR 8) -----------

def test_shard_kill_restore_bit_parity_with_in_learner(tmp_path):
    """The PR 8 acceptance pin: strict-mode N=1 stays bit-identical to
    the in-learner fused path ACROSS a kill/restore cycle — the shard is
    snapshotted at a quiescent point, a FRESH core (different construction
    key, proving restore overwrites everything) restores it, and the
    remaining schedule lands on identical params, replay tree, and PRNG
    chain."""
    msgs = _chunk_messages(3, 14)
    split_at = 9                         # kill after this many chunks

    # reference: the in-learner serial loop (as in the N=1 parity test)
    core_a, ts_a, replay_a = _learner()
    rs = replay_a.init()
    fused = core_a.jit_fused_step()
    ingest = core_a.jit_ingest()
    train = core_a.jit_train_step()
    key_a = jax.random.key(999)
    ingested = 0
    for msg in msgs:
        prios = jnp.asarray(np.asarray(msg["priorities"], np.float32))
        if ingested >= WARMUP:
            key_a, k = jax.random.split(key_a)
            ts_a, rs, _ = fused(ts_a, rs, msg["payload"], prios, k,
                                jnp.float32(_beta(ingested)))
        else:
            rs = ingest(rs, msg["payload"], prios)
        ingested += int(msg["n_trans"])
    for _ in range(2):
        key_a, k = jax.random.split(key_a)
        ts_a, rs, _ = train(ts_a, rs, k, jnp.float32(_beta(ingested)))

    # service path with a mid-schedule kill/restore
    core_b, ts_b, replay_b = _learner()
    shard = ReplayShardCore(replay_b, jax.random.key(999), batch_size=BATCH,
                            warmup=WARMUP, beta=0.4, beta_anneal=200,
                            n_shards=1, strict_order=True)
    train_b = jax.jit(core_b.update_from_batch, donate_argnums=(0,))

    def pull_train_writeback(s):
        nonlocal ts_b
        b = s.next_batch()
        assert b is not None
        ts_b, prios_out, _ = train_b(ts_b, b["batch"],
                                     jnp.asarray(b["weights"]))
        s.write_back(b["seq"], b["idx"],
                     np.asarray(jax.device_get(prios_out), np.float32))

    for msg in msgs[:split_at]:
        warm_pre = shard.warm
        shard.ingest_msg(dict(msg))
        if warm_pre:
            pull_train_writeback(shard)

    assert shard.quiescent()             # lockstep: nothing in flight
    snap = str(tmp_path / "replay_shard_0.msgpack")
    shard.save_snapshot(snap)

    # the "respawned" shard: fresh core, deliberately different key —
    # every restored field must come from the snapshot, none survive
    _, _, replay_c = _learner()
    shard2 = ReplayShardCore(replay_c, jax.random.key(424242),
                             batch_size=BATCH, warmup=WARMUP, beta=0.4,
                             beta_anneal=200, n_shards=1,
                             strict_order=True)
    meta = shard2.restore_snapshot(snap)
    assert meta["ingested"] == shard.ingested
    assert shard2.restored == shard.ingested
    assert shard2.warm == shard.warm

    for msg in msgs[split_at:]:
        warm_pre = shard2.warm
        shard2.ingest_msg(dict(msg))
        if warm_pre:
            pull_train_writeback(shard2)
    for _ in range(2):
        pull_train_writeback(shard2)

    for la, lb in zip(jax.tree.leaves(ts_a.params),
                      jax.tree.leaves(ts_b.params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert int(ts_a.step) == int(ts_b.step)
    for name in ("frames", "action", "reward", "discount", "obs_ids",
                 "next_ids", "frame_epoch", "sum_tree", "min_tree",
                 "pos", "f_epoch", "size", "max_priority"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rs, name)),
            np.asarray(getattr(shard2.state, name)), err_msg=name)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(key_a)),
        np.asarray(jax.random.key_data(shard2.key)))


def test_shard_restore_refuses_spec_mismatch(tmp_path):
    _, _, replay = _learner(seed=9)
    shard = ReplayShardCore(replay, jax.random.key(9), batch_size=BATCH,
                            warmup=WARMUP, strict_order=True)
    path = str(tmp_path / "snap.msgpack")
    shard.save_snapshot(path)
    _, _, replay2 = _learner(seed=9)
    other = ReplayShardCore(replay2, jax.random.key(9),
                            batch_size=BATCH // 2,     # shape-shifted
                            warmup=WARMUP, strict_order=True)
    with pytest.raises(ValueError, match="batch_size"):
        other.restore_snapshot(path)


# -- learner-epoch fencing on the replay plane (PR 8) ------------------------

def test_epoch_fence_rejects_stale_writebacks_and_forgives_on_bump():
    _, _, replay = _learner(seed=7)
    shard = ReplayShardCore(replay, jax.random.key(7), batch_size=BATCH,
                            warmup=WARMUP, strict_order=True)
    msgs = iter(_chunk_messages(13, 20))
    while not shard.warm:
        shard.ingest_msg(next(msgs))
    # epoch-1 learner pulls a batch, then dies before the write-back
    assert shard.note_epoch(1) == 0
    b0 = shard.next_batch()
    assert b0 is not None and shard.outstanding() == 1
    assert not shard.can_ingest()
    # the restarted (epoch-2) learner's first pull forgives immediately —
    # no dead_after_s wait — and reopens the ingest gate
    assert shard.note_epoch(2) == 1
    assert shard.epoch_forgiven == 1 and shard.can_ingest()
    # the dead learner's ghost write-back: REJECTED, tree untouched
    tree_before = np.asarray(shard.state.sum_tree).copy()
    assert not shard.write_back(b0["seq"], b0["idx"],
                                np.full(BATCH, 99.0, np.float32), epoch=1)
    assert shard.stale_wb == 1
    np.testing.assert_array_equal(tree_before,
                                  np.asarray(shard.state.sum_tree))
    # the live epoch trains on: sample, write back, applied
    shard.ingest_msg(next(msgs))
    b1 = shard.next_batch()
    assert shard.write_back(b1["seq"], b1["idx"],
                            np.ones(BATCH, np.float32), epoch=2)
    assert shard.wb_applied == shard.sampled
    # unstamped (legacy) write-backs keep working when fencing is off
    stats = shard.stats()
    assert stats["learner_epoch"] == 2 and stats["stale_wb"] == 1


def test_epoch_skew_chaos_drill_over_sockets(monkeypatch):
    """Seeded epoch-skew injection: the learner's write-backs arrive one
    epoch STALE; the shard rejects and counts every one, reports the
    count on the dry reply, and its priorities stay uncorrupted."""
    monkeypatch.setenv("CHAOS_SEED", "11")
    monkeypatch.setenv("CHAOS_SPEC", '{"epoch_skew": {"learner": -1}}')
    comms = _comms(1)
    fleet = _ShardFleet(comms, 1, warmup=1)
    sender = ShardedChunkSender(comms, "actor-0", shard_wait_s=5.0)
    client = ReplayServiceClient(comms, identity="learner")
    client.learner_epoch = 2                  # the trainer's stamp
    assert client.epoch_skew == -1            # seeded plan applied
    try:
        for i, msg in enumerate(_chunk_messages(51, 3)):
            assert sender.send_chunk(dict(msg, chunk_id=f"actor-0:{i}"))
        item = client.poll_batch(timeout=20)
        assert item is not None
        assert client.push_priorities(item["shard"], item["seq"],
                                      item["idx"],
                                      np.ones(BATCH, np.float32))
        core = fleet.servers[0].core
        deadline = time.monotonic() + 10
        while core.stale_wb == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert core.stale_wb == 1             # rejected, counted
        assert core.wb_applied < core.sampled  # never applied
        # the dry reply carries the reject count back to the learner
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            client.poll_batch(timeout=0.2)
            if client.shard_status()[0]["stale_wb"] >= 1:
                break
        assert client.shard_status()[0]["stale_wb"] >= 1
    finally:
        client.close()
        sender.close(drain_s=0)
        fleet.close()


# -- partition-grade chaos plans (PR 8) --------------------------------------

def test_chaos_partition_plan_fields():
    from apex_tpu.fleet.chaos import ChaosConfig

    cfg = ChaosConfig(7, {"ack_withhold": {"at": 3, "n": 2},
                          "mute": ["replay-0"],
                          "epoch_skew": {"learner": -1}})
    p = cfg.plan_for("learner")
    assert p.ack_withhold_at == 3 and p.ack_withhold_n == 2
    assert p.ack_withhold_s == 3.0            # hold_s default
    assert p.epoch_skew == -1 and not p.mute_replies
    q = cfg.plan_for("replay-0")
    assert q.mute_replies and q.epoch_skew == 0
    # a respawned life keeps the partition faults (only kills disarm)
    r = ChaosConfig(7, {"mute": ["replay-0"], "kill": {"replay-0": 5}},
                    respawn_count=1).plan_for("replay-0")
    assert r.mute_replies and r.kill_at is None


def test_directional_drop_shard_ingests_but_replies_vanish(monkeypatch):
    """actor->shard up while shard->learner down: chunks keep landing
    and acking (the ingress direction is healthy), pulls arrive but
    every reply dies on the muted link — counted, and the learner's
    status for that shard stays dark."""
    monkeypatch.setenv("CHAOS_SEED", "13")
    monkeypatch.setenv("CHAOS_SPEC", '{"mute": ["replay-0"]}')
    comms = _comms(1)
    fleet = _ShardFleet(comms, 1)
    sender = ShardedChunkSender(comms, "actor-0", shard_wait_s=5.0)
    client = ReplayServiceClient(comms, identity="learner-dd")
    try:
        msgs = _chunk_messages(61, 3)
        for i, msg in enumerate(msgs):
            assert sender.send_chunk(dict(msg, chunk_id=f"actor-0:{i}"))
        deadline = time.monotonic() + 10
        while (fleet.servers[0].core.chunks < 3
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert fleet.servers[0].core.chunks == 3    # ingress healthy
        assert client.poll_batch(timeout=1.5) is None   # egress dark
        assert fleet.servers[0].chaos_muted >= 1
        assert client.shard_status()[0]["ingested"] == 0
    finally:
        client.close()
        sender.close(drain_s=0)
        fleet.close()


# -- dead-shard re-probe (PR 8 fix) ------------------------------------------

def test_recovered_shard_gets_its_traffic_back_via_reprobe():
    """The satellite fix: a dead shard's stale credit window used to
    wedge it out FOREVER (every later chunk fell back to the learner,
    even after the shard respawned).  With periodic re-probing the
    window resets and a recovered shard takes its stream back — no
    actor restart."""
    comms = _comms(1, max_outstanding_sends=2)
    receiver = transport.ChunkReceiver(comms, bind_ip="127.0.0.1",
                                       queue_depth=64)
    receiver.start()
    fleet = _ShardFleet(comms, 1)
    sender = ShardedChunkSender(comms, "actor-0", shard_wait_s=0.3,
                                shard_reprobe_s=0.6)
    try:
        msgs = _chunk_messages(71, 12)
        # first chunk alone: its ingest jit-compiles, which would blow
        # the deliberately short shard_wait_s for the chunks behind it
        assert sender.send_chunk(dict(msgs[0], chunk_id="actor-0:0"))
        deadline = time.monotonic() + 20
        while (fleet.servers[0].core.chunks < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert fleet.servers[0].core.chunks == 1
        for i in range(1, 3):
            assert sender.send_chunk(dict(msgs[i],
                                          chunk_id=f"actor-0:{i}"))
        deadline = time.monotonic() + 10
        while (fleet.servers[0].core.chunks < 3
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert fleet.servers[0].core.chunks == 3

        fleet.kill(0)
        # drain the last acks, then wedge the window exactly as a
        # mid-flight kill leaves it (same idiom as the park test — in
        # this in-process topology zmq would otherwise buffer and
        # redeliver, which a crashed remote host does not)
        deadline = time.monotonic() + 5
        while (sender.shards[0]._in_flight > 0
               and time.monotonic() < deadline):
            sender.shards[0]._drain_acks(50)
        sender.shards[0]._in_flight = comms.max_outstanding_sends
        for i in range(3, 7):               # window wedged -> fallback
            assert sender.send_chunk(dict(msgs[i],
                                          chunk_id=f"actor-0:{i}"),
                                     max_wait_s=5)
        # wedged chunks fell back (the burst may outlast the re-probe
        # period, in which case the last one rides an early probe into
        # the still-dead shard — the documented bounded loss)
        assert sender.rerouted >= 3

        # the shard respawns on the same port (fresh core = no memory
        # of the old acks)
        _, _, replay = _learner(seed=99)
        core2 = ReplayShardCore(replay, jax.random.key(99),
                                batch_size=BATCH, warmup=10_000,
                                strict_order=True)
        stop2 = threading.Event()
        srv2 = ReplayShardServer(comms, 0, core2, bind_ip="127.0.0.1",
                                 heartbeat=False)
        t2 = threading.Thread(target=srv2.run,
                              kwargs={"stop_event": stop2}, daemon=True)
        t2.start()
        try:
            time.sleep(0.7)                 # past shard_reprobe_s
            deadline = time.monotonic() + 20
            i = 7
            while core2.chunks == 0 and time.monotonic() < deadline:
                assert sender.send_chunk(dict(msgs[i % len(msgs)],
                                              chunk_id=f"actor-0:{i}"),
                                         max_wait_s=5)
                i += 1
                time.sleep(0.05)
            assert core2.chunks > 0, \
                "recovered shard never got its traffic back"
            assert sender.reprobes >= 1
        finally:
            stop2.set()
            t2.join(timeout=10)
            srv2.close()
    finally:
        sender.close(drain_s=0)
        fleet.close()
        receiver.stop()


class _StubPool:
    """No-chunk pool: the trainer must train on SERVICE batches alone."""

    procs: list = []

    def start(self):
        pass

    def cleanup(self):
        pass

    def poll_chunks(self, n, timeout=0.0):
        if timeout:
            time.sleep(min(timeout, 0.005))
        return []

    def poll_stats(self):
        return []

    def publish_params(self, version, params):
        pass


class _StubClient:
    """Serves pre-fabricated batches with the client's interface; records
    the write-backs the trainer routes back."""

    def __init__(self, batches):
        self._lock = threading.Lock()
        self._batches = list(batches)
        self.n_shards = 2
        self.batches = 0
        self.prio = []                   # (shard, seq) routed back
        self.rejected = self.prio_sent = self.prio_dropped = 0

    def poll_batch(self, timeout=0.0):
        with self._lock:
            if not self._batches:
                return None
            self.batches += 1
            return self._batches.pop(0)

    def push_priorities(self, shard, seq, idx, priorities):
        assert np.asarray(priorities).dtype == np.float32
        assert np.asarray(priorities).shape == np.asarray(idx).shape
        with self._lock:
            self.prio.append((int(shard), int(seq)))
            self.prio_sent += 1
        return True

    def ingested_total(self):
        return 4096                      # "the shard fleet is warm"

    def shard_status(self):
        return []

    def close(self):
        pass


def test_trainer_trains_on_service_batches_and_routes_writebacks():
    """Learner-side integration without sockets: with a replay client
    attached and NO chunk stream, the trainer must train exclusively on
    shard-served batches through the family's update body and route each
    batch's TD priorities back to its owning shard — the local pool
    never warms and is never sampled."""
    from apex_tpu.training.apex import ApexTrainer, dqn_env_specs

    cfg = small_test_config(capacity=256, batch_size=BATCH)
    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
    rng = np.random.default_rng(0)

    def fake_batch(shard, seq):
        return {
            "batch": {
                "obs": rng.normal(size=(BATCH,) + stacked)
                .astype(frame_dtype) if np.dtype(frame_dtype) != np.uint8
                else rng.integers(0, 255, (BATCH,) + stacked, np.uint8),
                "action": rng.integers(0, 2, BATCH).astype(np.int32),
                "reward": rng.normal(size=BATCH).astype(np.float32),
                "next_obs": rng.integers(0, 255, (BATCH,) + stacked,
                                         np.uint8)
                if np.dtype(frame_dtype) == np.uint8
                else rng.normal(size=(BATCH,) + stacked)
                .astype(frame_dtype),
                "discount": np.full(BATCH, 0.97, np.float32),
            },
            "weights": np.ones(BATCH, np.float32),
            "idx": rng.integers(0, 256, BATCH).astype(np.int32),
            "seq": seq, "shard": shard, "ingested": 2048,
        }

    client = _StubClient([fake_batch(0, 0), fake_batch(1, 0),
                          fake_batch(0, 1), fake_batch(1, 1)])
    trainer = ApexTrainer(cfg, pool=_StubPool(), respawn_workers=False)
    trainer.replay_client = client
    p_before = np.asarray(
        jax.tree.leaves(trainer.train_state.params)[0]).copy()
    trainer.train(total_steps=4, max_seconds=120, log_every=2)

    assert trainer.service_steps == 4
    assert trainer.steps_rate.total == 4
    assert sorted(client.prio) == [(0, 0), (0, 1), (1, 0), (1, 1)]
    assert trainer.ingested == 0         # local pool untouched
    p_after = np.asarray(jax.tree.leaves(trainer.train_state.params)[0])
    assert not np.array_equal(p_before, p_after)
    svc = trainer.fleet_summary()["metrics"]["replay_service"]
    assert svc["service_steps"] == 4 and svc["batches_pulled"] == 4


def test_build_shard_core_matches_trainer_replay_spec():
    """One spec, two owners: the shard role must build the EXACT
    FramePoolReplay the DQN learner builds, or N=1 parity (and every
    frame shape on the wire) silently breaks."""
    from apex_tpu.replay_service.service import (build_shard_core,
                                                 dqn_replay_spec)
    from apex_tpu.training.apex import dqn_env_specs

    cfg = small_test_config(capacity=256, batch_size=16)
    cfg = cfg.replace(comms=CommsConfig(replay_shards=2))
    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    spec = dqn_replay_spec(cfg)
    assert spec.frame_shape == frame_shape
    assert spec.frame_stack == frame_stack
    assert spec.capacity == cfg.replay.capacity
    core = build_shard_core(cfg, shard_id=1)
    assert core.replay == spec                   # frozen dataclass equality
    assert core.warmup == shard_warmup(cfg.replay.warmup, 2)
    assert core.n_shards == 2 and core.strict_order
    with pytest.raises(NotImplementedError):
        build_shard_core(cfg, 0, family="r2d2")
