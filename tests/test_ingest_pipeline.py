"""Async ingest pipeline: merge bit-parity, end-to-end loop bit-parity,
order preservation, and bounded-ring backpressure
(``apex_tpu/training/ingest_pipeline.py``)."""

import copy
import dataclasses
import time

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import drain_builder_chunks
from apex_tpu.config import small_test_config
from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.training.ingest_pipeline import (IngestPipeline, PipelineState,
                                               is_frame_chunk,
                                               merge_chunk_messages)

# -- chunk stream fixtures --------------------------------------------------

FRAME_SHAPE = (3,)
STACK = 2
K = 8          # transitions per chunk
N_STEPS = 2


def _random_chunk_messages(seed: int, n_chunks: int,
                           frame_shape=FRAME_SHAPE, stack=STACK,
                           k=K, extra_shapes=None) -> list[dict]:
    """Drive a real FrameChunkBuilder through random episodes until it has
    emitted ``n_chunks`` fixed-shape chunks — the exact payloads actor
    workers ship."""
    rng = np.random.default_rng(seed)
    builder = FrameChunkBuilder(N_STEPS, 0.9, stack, frame_shape,
                                chunk_transitions=k, frame_margin=4,
                                frame_dtype=np.uint8,
                                extra_shapes=extra_shapes)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        builder.begin_episode(rng.integers(0, 255, frame_shape))
        ep_len = int(rng.integers(1, 3 * k))
        for t in range(ep_len):
            extras = None
            if extra_shapes:
                extras = {name: rng.normal(size=shape).astype(np.float32)
                          for name, shape in extra_shapes.items()}
            builder.add_step(int(rng.integers(0, 4)),
                             float(rng.normal()),
                             rng.normal(size=4).astype(np.float32),
                             rng.integers(0, 255, frame_shape),
                             terminated=t == ep_len - 1, truncated=False,
                             extras=extras)
        msgs.extend(drain_builder_chunks(builder))
    return msgs[:n_chunks]


def _pool_spec(extra_spec=()):
    return FramePoolReplay(capacity=64, frame_shape=FRAME_SHAPE,
                           frame_stack=STACK, frame_capacity=128,
                           frame_dtype="uint8", extra_spec=extra_spec)


def _assert_states_identical(a, b):
    for name in ("frames", "action", "reward", "discount", "obs_ids",
                 "next_ids", "frame_epoch", "sum_tree", "min_tree",
                 "pos", "f_epoch", "size", "max_priority"):
        va, vb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert np.array_equal(va, vb), f"state field {name} diverged"
    for key in a.extras:
        assert np.array_equal(np.asarray(a.extras[key]),
                              np.asarray(b.extras[key])), \
            f"extras[{key}] diverged"


# -- merge bit-parity (the property the whole pipeline rests on) ------------

@pytest.mark.parametrize("m", [2, 3, 5, 8])
def test_merged_ingest_bit_identical_to_sequential(m):
    """add(merge(c1..cm)) == add(c1); ...; add(cm) on EVERY state field:
    frames, id tables, trees, per-transition frame epochs, cursors."""
    msgs = _random_chunk_messages(seed=m, n_chunks=m)
    pool = _pool_spec()

    seq = pool.init()
    for msg in msgs:
        seq = pool.add(seq, msg["payload"],
                       np.asarray(msg["priorities"], np.float32))

    merged = merge_chunk_messages(copy.deepcopy(msgs))
    assert merged["n_trans"] == sum(int(x["n_trans"]) for x in msgs)
    one = pool.add(pool.init(), merged["payload"],
                   np.asarray(merged["priorities"], np.float32))

    _assert_states_identical(seq, one)


def test_merged_ingest_bit_identical_with_extras_and_wraparound():
    """Extras sidecars merge per-name, and parity survives the frame ring
    wrapping (chunks straddling the f_capacity boundary)."""
    extra_shapes = {"a_mu": (5,)}
    msgs = _random_chunk_messages(seed=7, n_chunks=30,
                                  extra_shapes=extra_shapes)
    pool = _pool_spec(extra_spec=(("a_mu", (5,)),))

    seq = pool.init()
    one = pool.init()
    # interleave merged widths over a long stream so cursors wrap
    i = 0
    widths = [3, 1, 4, 2, 5]
    w = 0
    while i < len(msgs):
        take = msgs[i:i + widths[w % len(widths)]]
        w += 1
        i += len(take)
        for msg in take:
            seq = pool.add(seq, msg["payload"],
                           np.asarray(msg["priorities"], np.float32))
        merged = merge_chunk_messages(copy.deepcopy(take))
        one = pool.add(one, merged["payload"],
                       np.asarray(merged["priorities"], np.float32))
    assert int(seq.f_epoch) > pool.f_capacity, "stream too short to wrap"
    _assert_states_identical(seq, one)


def test_merge_is_schema_gated():
    assert is_frame_chunk(_random_chunk_messages(1, 1)[0]["payload"])
    assert not is_frame_chunk({"obs": 1, "action": 2})
    assert not is_frame_chunk([1, 2])
    with pytest.raises(ValueError, match="uniform"):
        a = _random_chunk_messages(1, 1)[0]
        b = _random_chunk_messages(2, 1, k=4)[0]
        merge_chunk_messages([a, b])


# -- pipeline mechanics: scripted pool --------------------------------------

class ScriptedPool:
    """Deterministic in-process chunk source with the pool interface the
    trainer drives; counts polls so backpressure is observable."""

    def __init__(self, msgs):
        self._msgs = list(msgs)
        self.procs = []
        self.polled = 0
        self.published = []

    def start(self):
        pass

    def cleanup(self):
        pass

    def publish_params(self, version, params):
        self.published.append(version)

    def poll_stats(self):
        return []

    def poll_chunks(self, max_chunks, timeout=0.0):
        out = []
        while self._msgs and len(out) < max_chunks:
            out.append(self._msgs.pop(0))
        self.polled += len(out)
        return out


def test_pipeline_backpressures_when_behind_and_bounds_the_ring():
    """The replay-ratio floor pauses draining entirely; without the floor
    the bounded ring caps how much the pipeline will buffer ahead of the
    learner — it never drains the pool unboundedly."""
    msgs = _random_chunk_messages(seed=3, n_chunks=64)
    pool = ScriptedPool(msgs)
    state = {"behind": True}
    pipe = IngestPipeline(
        pool, depth=2, scan_steps=1, merge_max=4,
        state_fn=lambda: PipelineState(behind=state["behind"],
                                       train_eligible=False),
        capacity=1 << 20, frame_capacity=1 << 20)
    pipe.start()
    try:
        time.sleep(0.3)
        assert pool.polled == 0, "behind-learner must pause draining"

        state["behind"] = False          # floor released, but no consumer:
        time.sleep(0.5)                  # the depth-2 ring must backpressure
        # at most: depth slots of merge_max chunks + one group in flight
        bound = (2 + 1) * 4
        assert 0 < pool.polled <= bound, \
            f"ring buffered {pool.polled} chunks > bound {bound}"
        assert len(msgs) - pool.polled > 0, "pool fully drained: unbounded"

        # draining the ring lets staging make progress — order preserved
        seen = []
        for _ in range(100):
            slot = pipe.poll_slot(timeout=0.2)
            if slot is None:
                break
            seen.append(slot)
        assert sum(s.n_trans for s in seen) \
            == sum(int(m["n_trans"]) for m in msgs)
    finally:
        pipe.stop()


def test_pipeline_publish_rides_staging_thread():
    pool = ScriptedPool([])
    pipe = IngestPipeline(pool, state_fn=lambda: PipelineState())
    pipe.start()
    try:
        pipe.publish(3, {"w": jax.numpy.ones(4)})
        deadline = time.monotonic() + 2.0
        while not pool.published and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.published == [3]
    finally:
        pipe.stop()


def test_pipeline_staging_error_surfaces_to_consumer():
    class ExplodingPool(ScriptedPool):
        def poll_chunks(self, max_chunks, timeout=0.0):
            raise RuntimeError("decode blew up")

    pipe = IngestPipeline(ExplodingPool([]),
                          state_fn=lambda: PipelineState())
    pipe.start()
    try:
        with pytest.raises(RuntimeError, match="staging thread died"):
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                pipe.poll_slot(timeout=0.05)
    finally:
        pipe.stop()


# -- end-to-end bit-parity: pipelined vs serial trainer loop ----------------

def _run_trainer(pipeline_on: bool, msgs, total_steps: int):
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config(capacity=256, batch_size=16, n_actors=1)
    cfg = cfg.replace(
        replay=dataclasses.replace(cfg.replay, warmup=64),
        learner=dataclasses.replace(cfg.learner,
                                    ingest_pipeline=pipeline_on,
                                    target_update_interval=20))
    pool = ScriptedPool(copy.deepcopy(msgs))
    trainer = ApexTrainer(cfg, pool=pool, publish_min_seconds=10.0,
                          respawn_workers=False)
    trainer.train(total_steps=total_steps, max_seconds=120,
                  log_every=10 ** 9)
    return jax.device_get(trainer.train_state.params), trainer


def _cartpole_chunk_messages(n_chunks: int) -> list[dict]:
    """Chunks matching small_test_config's ApexCartPole spec: (4,) float32
    frames, stack 1 — what ApexTrainer's replay expects."""
    rng = np.random.default_rng(0)
    builder = FrameChunkBuilder(3, 0.99, 1, (4,), chunk_transitions=16,
                                frame_dtype=np.float32)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        builder.begin_episode(rng.normal(size=4).astype(np.float32))
        ep_len = int(rng.integers(4, 40))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 2)), float(rng.normal()),
                             rng.normal(size=2).astype(np.float32),
                             rng.normal(size=4).astype(np.float32),
                             terminated=t == ep_len - 1, truncated=False)
        msgs.extend(drain_builder_chunks(builder))
    return msgs[:n_chunks]


def test_pipelined_loop_bit_parity_with_serial():
    """The acceptance pin: the SAME deterministic chunk stream through the
    pipelined and serial trainer loops yields bit-identical params after N
    fused steps.  The stream crosses the warmup boundary, so the pipeline
    exercises merged warmup ingest, staged fused singles, AND replay-only
    steps — and must reproduce the serial key/beta/schedule exactly."""
    msgs = _cartpole_chunk_messages(24)      # 24 * 16 = 384 transitions
    n = 40                                   # > post-warm chunk count:
    #                                          tail steps sample replay only
    serial, t_serial = _run_trainer(False, msgs, n)
    piped, t_piped = _run_trainer(True, msgs, n)

    assert t_serial.steps_rate.total == t_piped.steps_rate.total == n
    assert t_serial.ingested == t_piped.ingested == 384
    flat_s = jax.tree_util.tree_leaves_with_path(serial)
    flat_p = dict(jax.tree_util.tree_leaves_with_path(piped))
    assert flat_s and len(flat_s) == len(flat_p)
    for path, leaf in flat_s:
        assert np.array_equal(np.asarray(leaf), np.asarray(flat_p[path])), \
            f"params diverged at {jax.tree_util.keystr(path)}"
    # the pipelined run must actually have staged slots (not silently
    # fallen back to the serial drain)
    stats = t_piped._pipeline_last_stats
    assert stats is not None and stats["slots"] > 0
    assert stats["merged_chunks"] >= 2, \
        "warmup fill never exercised the merged-ingest path"


def test_trainer_pipeline_gate():
    """ingest_pipeline=False keeps the serial drain (the A/B lane);
    default-on covers single-shard AND dp>1 (the sharded plan's parity
    pin lives in tests/test_sharded_pipeline.py)."""
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config()
    cfg_off = cfg.replace(learner=dataclasses.replace(
        cfg.learner, ingest_pipeline=False))
    t = ApexTrainer(cfg_off, pool=ScriptedPool([]))
    assert not t._use_pipeline()
    t2 = ApexTrainer(cfg, pool=ScriptedPool([]))
    assert t2._use_pipeline()
    cfg_dp = cfg.replace(learner=dataclasses.replace(
        cfg.learner, mesh_shape=(4,), batch_size=32, ingest_chunk=32))
    t3 = ApexTrainer(cfg_dp, pool=ScriptedPool([]))
    assert t3.n_dp == 4 and t3._use_pipeline()
