"""On-device replay plane (apex_tpu/ondevice).

The load-bearing pins:

* :class:`DeviceFramePool` is BIT-identical to a host-orchestrated
  :class:`FramePoolReplay` across dispatch boundaries — every tree
  field, the PRNG key chain, the sampled indices and batches (there is
  only one implementation; the pin keeps it that way).
* ``FramePoolReplay.add(valid=...)``: True is bit-identical to the
  unmasked call, False is a bit-exact no-op on every state field — the
  contract the fused loop's fixed chunk-slot grid ingests through.
* The fused step's scan composition is pure dispatch amortization:
  ``steps_per_dispatch=N`` once == ``steps_per_dispatch=1`` N times,
  bit-identical train state, replay state, and key chains at fixed
  seeds.
* The snapshot path round-trips through the PR 8 checkpoint machinery
  and refuses a shape-shifting restore.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

pytest.importorskip("jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from apex_tpu.config import (ActorConfig, ApexConfig,  # noqa: E402
                             EnvConfig, LearnerConfig, ReplayConfig)
from apex_tpu.ondevice.fused import (FusedApexTrainer,  # noqa: E402
                                     acting_priorities)
from apex_tpu.ondevice.replay import DeviceFramePool  # noqa: E402
from apex_tpu.replay.frame_pool import FramePoolReplay  # noqa: E402

REPLAY_FIELDS = ("frames", "action", "reward", "discount", "obs_ids",
                 "next_ids", "frame_epoch", "sum_tree", "min_tree",
                 "pos", "f_epoch", "size", "max_priority")


def _assert_states_equal(a, b, context=""):
    for f in REPLAY_FIELDS:
        # parity assertion, not a hot loop: the drain-per-iteration IS
        # the test
        x = np.asarray(jax.device_get(getattr(a, f)))  # apexlint: disable=J006
        y = np.asarray(jax.device_get(getattr(b, f)))  # apexlint: disable=J006
        assert np.array_equal(x, y), f"{f} diverged {context}"


def _spec(capacity=64, frame_capacity=128):
    return FramePoolReplay(capacity=capacity, frame_shape=(5,),
                           frame_stack=2, frame_capacity=frame_capacity)


def _chunk(rng, kf=10, k=8):
    nf = int(rng.integers(2, kf + 1))
    nt = int(rng.integers(1, k + 1))
    return dict(
        frames=jnp.asarray(rng.integers(0, 255, (kf, 5), dtype=np.uint8)),
        n_frames=jnp.int32(nf), n_trans=jnp.int32(nt),
        action=jnp.asarray(rng.integers(0, 3, (k,)), jnp.int32),
        reward=jnp.asarray(rng.normal(size=k), jnp.float32),
        discount=jnp.asarray(rng.random(k), jnp.float32),
        obs_ref=jnp.asarray(rng.integers(0, nf, (k, 2)), jnp.int32),
        next_ref=jnp.asarray(rng.integers(0, nf, (k, 2)), jnp.int32))


# -- DeviceFramePool vs host-orchestrated FramePoolReplay ------------------

def test_device_pool_bit_parity_vs_host_pool():
    """Same chunks, same key chain -> identical tree fields, sampled
    indices, batches, and IS weights across three add/sample/update
    rounds (the 'dispatch boundary' is every host round-trip)."""
    spec = _spec()
    rng = np.random.default_rng(7)
    pool = DeviceFramePool(spec, seed=11)

    # the host twin, driven exactly as the concurrent trainer drives it
    h_state = spec.init()
    h_key = jax.random.key(11)
    h_add = jax.jit(spec.add)
    h_sample = jax.jit(spec.sample, static_argnums=(2,))
    h_update = jax.jit(spec.update_priorities)

    for round_i in range(3):
        for _ in range(4):
            ch = _chunk(rng)
            pr = jnp.asarray(rng.random(8), jnp.float32)
            pool.add(ch, pr)
            h_state = h_add(h_state, ch, pr)
        batch, weights, idx = pool.sample(16, 0.5)
        h_key, k = jax.random.split(h_key)
        hb, hw, hi = h_sample(h_state, k, 16, jnp.float32(0.5))
        assert np.array_equal(np.asarray(idx), np.asarray(hi)), round_i
        assert np.array_equal(np.asarray(weights), np.asarray(hw))
        for key in ("obs", "action", "reward", "next_obs", "discount"):
            assert np.array_equal(np.asarray(batch[key]),
                                  np.asarray(hb[key])), (round_i, key)
        new_pr = jnp.asarray(rng.random(16), jnp.float32)
        pool.update_priorities(idx, new_pr)
        h_state = h_update(h_state, hi, new_pr)
        _assert_states_equal(pool.state, h_state,
                             f"after round {round_i}")
    # the key chains stayed in lockstep too
    assert np.array_equal(np.asarray(jax.random.key_data(pool.key)),
                          np.asarray(jax.random.key_data(h_key)))


def test_masked_add_true_is_plain_false_is_identity():
    spec = _spec()
    rng = np.random.default_rng(3)
    st = spec.init()
    # warm two chunks in so trees/cursors are nontrivial
    for _ in range(2):
        st = spec.add(st, _chunk(rng), jnp.asarray(rng.random(8),
                                                   jnp.float32))
    ch = _chunk(rng)
    pr = jnp.asarray(rng.random(8), jnp.float32)
    masked = jax.jit(lambda s, c, p, v: spec.add(s, c, p, valid=v))
    plain = spec.add(st, ch, pr)
    _assert_states_equal(plain, masked(st, ch, pr, jnp.bool_(True)),
                         "valid=True vs unmasked")
    _assert_states_equal(st, masked(st, ch, pr, jnp.bool_(False)),
                         "valid=False vs identity")


def test_snapshot_roundtrip_and_spec_pin(tmp_path):
    spec = _spec()
    rng = np.random.default_rng(5)
    pool = DeviceFramePool(spec, seed=2)
    for _ in range(3):
        pool.add(_chunk(rng), jnp.asarray(rng.random(8), jnp.float32))
    pool.sample(8, 0.4)
    path = os.path.join(tmp_path, "pool.msgpack")
    pool.snapshot(path)

    other = DeviceFramePool(spec, seed=99)       # different chain on disk
    other.restore(path)
    _assert_states_equal(pool.state, other.state, "after restore")
    assert other.ingested == pool.ingested
    # the restored chain continues identically
    b1, w1, i1 = pool.sample(8, 0.4)
    b2, w2, i2 = other.sample(8, 0.4)
    assert np.array_equal(np.asarray(i1), np.asarray(i2))
    assert np.array_equal(np.asarray(b1["obs"]), np.asarray(b2["obs"]))

    # a shape-shifting restore refuses loudly
    with pytest.raises(ValueError, match="different pool spec"):
        DeviceFramePool(_spec(capacity=32, frame_capacity=64)).restore(
            path)


# -- the fused step --------------------------------------------------------

def _cfg(warmup=32, capacity=512, n_envs=2, send=8):
    return ApexConfig(
        env=EnvConfig(env_id="ApexCatchSmall-v0", frame_stack=2,
                      clip_rewards=False, episodic_life=False),
        replay=ReplayConfig(capacity=capacity, warmup=warmup,
                            beta_anneal=2000),
        learner=LearnerConfig(batch_size=16, compute_dtype="float32",
                              target_update_interval=50,
                              publish_interval=5),
        actor=ActorConfig(n_actors=1, n_envs_per_actor=n_envs,
                          send_interval=send))


def _run_fused(steps_per_dispatch, dispatches):
    t = FusedApexTrainer(_cfg(), steps_per_dispatch=steps_per_dispatch,
                         rollout_len=8)
    for _ in range(dispatches):
        t.train_state, t.replay_state, t.key, info = t.fused.dispatch(
            t.train_state, t.replay_state, t.key)
    return t


def test_fused_vs_serial_train_state_parity():
    """steps_per_dispatch=3 x 2 dispatches == steps_per_dispatch=1 x 6
    dispatches: bit-identical params/opt/step, replay state, and both
    key chains — the scan composition is pure latency amortization."""
    a = _run_fused(3, 2)
    b = _run_fused(1, 6)
    pa = jax.tree.leaves(jax.device_get(
        (a.train_state.params, a.train_state.opt_state)))
    pb = jax.tree.leaves(jax.device_get(
        (b.train_state.params, b.train_state.opt_state)))
    assert all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(pa, pb))
    assert int(a.train_state.step) == int(b.train_state.step) > 0
    _assert_states_equal(a.replay_state, b.replay_state,
                         "fused vs serial")
    assert np.array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))
    assert np.array_equal(
        np.asarray(jax.random.key_data(a.fused.engine.key)),
        np.asarray(jax.random.key_data(b.fused.engine.key)))
    assert int(a.fused.ingested_dev) == int(b.fused.ingested_dev)
    assert a.fused.train_steps == b.fused.train_steps > 0
    assert a.fused.prio_writebacks == b.fused.prio_writebacks > 0


def test_acting_priorities_match_host_epilogue_within_one_ulp():
    """The device priorities follow the numpy epilogue formula; XLA's
    FMA contraction rounds the multiply-add once where numpy rounds
    twice, so the envelope is <= 1 ulp (module-docstring contract:
    self-consistency inside the fused plane, not host bit-parity)."""
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.anakin import make_anakin_engine
    from apex_tpu.training.apex import dqn_env_specs
    from apex_tpu.training.state import create_train_state

    cfg = _cfg(n_envs=3)
    spec, fs, fd, stack = dqn_env_specs(cfg)
    model = DuelingDQN(**spec)
    stacked = fs[:-1] + (stack * fs[-1],)
    ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                            np.zeros((1,) + stacked, fd))
    eng = make_anakin_engine(cfg, rollout_len=16)
    eng.key, k = jax.random.split(eng.key)
    _, _, out = eng._jit(ts.params, eng.epsilons, eng.carry,
                         eng.carry_frames, k)
    dev = np.asarray(jax.device_get(jax.jit(acting_priorities)(out)))
    got = jax.device_get(out)
    q_taken = np.take_along_axis(got["q0"], got["action"][..., None],
                                 -1)[..., 0]
    target = got["reward"] + got["discount"] * got["qn"].max(-1)
    host = (np.abs(target - q_taken).astype(np.float32)
            + np.float32(1e-6))
    assert np.allclose(dev, host, rtol=2e-7, atol=0), \
        np.abs(dev - host).max()


def test_fused_trainer_trains_and_reports():
    t = FusedApexTrainer(_cfg(), steps_per_dispatch=2, rollout_len=8)
    t.train(total_steps=4, max_seconds=120.0)
    assert t.steps_rate.total >= 4
    summary = t.fleet_summary()
    ond = summary["metrics"]["ondevice"]
    assert ond["dispatches"] > 0 and ond["chunks"] > 0
    assert ond["train_steps"] > 0 and ond["prio_writebacks"] >= 1
    assert ond["transitions"] > 0
    # the fused plane beat into the registry
    idents = {p["identity"] for p in summary["peers"]}
    assert "fused-0" in idents


def test_fused_checkpoint_roundtrip(tmp_path):
    """The on-device replay state host-spills through the PR 8
    checkpoint machinery: restore imposes the donated pool bit-exactly
    and re-seeds the device warm/anneal counter."""
    t = FusedApexTrainer(_cfg(), steps_per_dispatch=2, rollout_len=8,
                         checkpoint_dir=str(tmp_path))
    for _ in range(3):
        t.train_state, t.replay_state, t.key, _ = t.fused.dispatch(
            t.train_state, t.replay_state, t.key)
    t.ingested = t.fused.transitions
    path = t.save_checkpoint()
    assert os.path.exists(path)

    t2 = FusedApexTrainer(_cfg(), steps_per_dispatch=2, rollout_len=8,
                          checkpoint_dir=str(tmp_path))
    t2.restore()
    _assert_states_equal(t.replay_state, t2.replay_state,
                         "after checkpoint restore")
    assert int(t2.fused.ingested_dev) == min(
        t.ingested, int(t.fused._ing_cap))
    assert np.array_equal(np.asarray(jax.random.key_data(t.key)),
                          np.asarray(jax.random.key_data(t2.key)))
    # the restored trainer keeps dispatching
    t2.train_state, t2.replay_state, t2.key, info = t2.fused.dispatch(
        t2.train_state, t2.replay_state, t2.key)
    assert info["transitions"] > 0


def test_fused_refusals_name_their_knobs():
    cfg = _cfg()
    # dp>1 is no longer refused wholesale (PR 17) — the honest capability
    # errors left are divisibility, naming BOTH knobs each
    with pytest.raises(ValueError) as ei:
        FusedApexTrainer(cfg.replace(
            learner=dataclasses.replace(cfg.learner, mesh_shape=(2,)),
            actor=dataclasses.replace(cfg.actor, n_envs_per_actor=3)))
    assert "--n-envs-per-actor" in str(ei.value)
    assert "--mesh-dp" in str(ei.value)
    with pytest.raises(ValueError) as ei:
        FusedApexTrainer(cfg.replace(learner=dataclasses.replace(
            cfg.learner, mesh_shape=(4,), batch_size=18)))
    assert "batch_size" in str(ei.value)
    assert "mesh" in str(ei.value)
    # a mesh wider than the host still refuses with the device count
    with pytest.raises(ValueError, match="devices"):
        FusedApexTrainer(cfg.replace(learner=dataclasses.replace(
            cfg.learner, mesh_shape=(1024,))))
    # non-jittable env ids refuse in make_jax_env before any pool spawn
    with pytest.raises(ValueError, match="ApexCartPole"):
        FusedApexTrainer(cfg.replace(env=dataclasses.replace(
            cfg.env, env_id="ApexCartPole-v0", frame_stack=1)))
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        FusedApexTrainer(cfg, steps_per_dispatch=0)


def test_cli_env_twins(monkeypatch):
    from apex_tpu.runtime.cli import build_parser
    monkeypatch.setenv("APEX_ROLLOUT", "fused")
    monkeypatch.setenv("APEX_STEPS_PER_DISPATCH", "7")
    args = build_parser().parse_args([])
    assert args.rollout == "fused"
    assert args.steps_per_dispatch == 7


def test_fused_bench_lane_direction_classes():
    """The part-1f ondevice_fused lane's leaves classify higher-better
    in the obs.slo --check differ (the regression gate direction)."""
    from apex_tpu.obs.slo import _direction
    assert _direction("ondevice_fused.toy.frames_per_sec") > 0
    assert _direction("ondevice_fused.toy.train_steps_per_sec") > 0
    assert _direction("ondevice_fused.pixel.transitions_per_sec") > 0
    # the PR 17 fused_dp lane's leaves ride the same classifier
    assert _direction("fused_dp.dp1.frames_per_sec") > 0
    assert _direction("fused_dp.dpN.frames_per_sec") > 0
    assert _direction("fused_dp.dpN.train_steps_per_sec") > 0
