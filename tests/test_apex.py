"""Ape-X driver: actor pool mechanics + end-to-end learning on CartPole."""

import numpy as np
import pytest

from apex_tpu.actors.pool import actor_epsilons
from apex_tpu.config import small_test_config
from apex_tpu.training.apex import ApexTrainer


def test_actor_epsilon_ladder_matches_reference_schedule():
    """batchrecorder.py:121: eps_i = 0.4^(1 + i/(N-1)*7)."""
    eps = actor_epsilons(8)
    np.testing.assert_allclose(eps[0], 0.4)
    np.testing.assert_allclose(eps[-1], 0.4 ** 8.0)
    assert (np.diff(eps) < 0).all()
    np.testing.assert_allclose(actor_epsilons(1), [0.4])


def test_apex_pipeline_mechanics():
    """Chunks flow from workers, the learner warms up, trains, publishes
    versioned params, collects episode stats, and shuts down cleanly."""
    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    trainer.train(total_steps=40, max_seconds=120)

    assert trainer.steps_rate.total >= 40
    assert trainer.ingested >= cfg.replay.warmup
    assert trainer.param_version >= 2          # initial + >=1 republish
    rewards = trainer.log.history.get("learner/episode_reward")
    assert rewards, "no episode stats arrived from workers"
    assert all(not p.is_alive() for p in trainer.pool.procs)
    # eval path shares the policy/jit machinery
    score = trainer.evaluate(episodes=1, max_steps=200)
    assert np.isfinite(score)


def test_trainer_rejects_replay_over_hbm_budget():
    """Mis-sized replay configs must fail at construction with an
    actionable error, not an opaque XLA OOM mid-run."""
    import dataclasses

    cfg = small_test_config()
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 hbm_budget_gb=1e-6))
    with pytest.raises(ValueError, match="HBM"):
        ApexTrainer(cfg)


def test_apex_learns_cartpole():
    """The concurrent pipeline must actually learn: greedy eval clearly
    beats random play (~22/episode) within a small budget.  No retries —
    learning must be robust to actor/learner interleaving (epsilon anneal
    keeps early near-greedy actors exploring; the replay-ratio band keeps
    data and compute in step whatever the host's core count)."""
    import dataclasses

    cfg = small_test_config(capacity=8192, batch_size=64, n_actors=3)
    # The reference ladder (eps_alpha=7, batchrecorder.py:121) is tuned for
    # ~200-actor fleets; with 3 actors it leaves two of them near-greedy
    # from step 0, which reliably collapses learning (verified both ways).
    # Small fleets get a gentler ladder + an exploration anneal.
    cfg = cfg.replace(actor=dataclasses.replace(
        cfg.actor, eps_anneal_steps=1500, eps_alpha=3.0))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05,
                          train_ratio=8.0, min_train_ratio=1.0)
    # generous wall-clock ceiling: under CPU contention the step budget —
    # not the clock — must decide when training is done
    trainer.train(total_steps=6000, max_seconds=900)
    score = trainer.evaluate(episodes=5, epsilon=0.0, max_steps=500)
    assert score > 40.0, f"eval reward {score} <= 40: pipeline not learning"
