"""Ape-X driver: actor pool mechanics + end-to-end learning on CartPole."""

import numpy as np
import pytest

from apex_tpu.actors.pool import actor_epsilons
from apex_tpu.config import small_test_config
from apex_tpu.training.apex import ApexTrainer


def test_actor_epsilon_ladder_matches_reference_schedule():
    """batchrecorder.py:121: eps_i = 0.4^(1 + i/(N-1)*7)."""
    eps = actor_epsilons(8)
    np.testing.assert_allclose(eps[0], 0.4)
    np.testing.assert_allclose(eps[-1], 0.4 ** 8.0)
    assert (np.diff(eps) < 0).all()
    np.testing.assert_allclose(actor_epsilons(1), [0.4])


@pytest.mark.slow
def test_apex_pipeline_mechanics():
    """Chunks flow from workers, the learner warms up, trains, publishes
    versioned params, collects episode stats, and shuts down cleanly."""
    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    trainer.train(total_steps=40, max_seconds=120)

    assert trainer.steps_rate.total >= 40
    assert trainer.ingested >= cfg.replay.warmup
    assert trainer.param_version >= 2          # initial + >=1 republish
    rewards = trainer.log.history.get("learner/episode_reward")
    assert rewards, "no episode stats arrived from workers"
    assert all(not p.is_alive() for p in trainer.pool.procs)
    # eval path shares the policy/jit machinery
    score = trainer.evaluate(episodes=1, max_steps=200)
    assert np.isfinite(score)


@pytest.mark.slow
def test_apex_scan_dispatch_mechanics():
    """config.scan_steps > 1: when chunks back up, the trainer drains K at
    a time through ONE lax.scan dispatch (bit-parity with sequential steps
    is pinned in test_learner/test_frame_pool; this proves the concurrent
    wiring — counters, cadences, shutdown — survives K-step jumps)."""
    import dataclasses

    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, scan_steps=2, publish_interval=3, save_interval=10))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    assert trainer._multi is not None
    trainer.train(total_steps=40, max_seconds=120)

    assert trainer.steps_rate.total >= 40
    assert trainer.scan_dispatches > 0, "scan path never fired"
    assert trainer.param_version >= 2
    assert all(not p.is_alive() for p in trainer.pool.procs)


def test_trainer_rejects_replay_over_hbm_budget():
    """Mis-sized replay configs must fail at construction with an
    actionable error, not an opaque XLA OOM mid-run."""
    import dataclasses

    cfg = small_test_config()
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 hbm_budget_gb=1e-6))
    with pytest.raises(ValueError, match="HBM"):
        ApexTrainer(cfg)


@pytest.mark.slow
def test_apex_mechanics_atari_shapes():
    """The FLAGSHIP shapes end to end: 84x84x1 uint8 frames, stack 4 —
    the exact Nature-DQN geometry bench.py and the Pong target use.  This
    exercises the tile-padded frame ring (7056 -> 7168 rows), the conv
    trunk, and chunked actor ingest at real frame sizes; a few training
    steps prove shape plumbing, not learning."""
    import dataclasses

    cfg = small_test_config(capacity=512, batch_size=16, n_actors=2,
                            env_id="ApexCatch-v0")
    cfg = cfg.replace(env=dataclasses.replace(cfg.env, frame_stack=4))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    assert trainer.replay.row_dim == 7168          # padded for the kernel
    assert trainer.replay.ring_shape == (1024, 8, 896)
    trainer.train(total_steps=10, max_seconds=300)
    assert trainer.steps_rate.total >= 10
    assert trainer.ingested >= cfg.replay.warmup
    assert all(not p.is_alive() for p in trainer.pool.procs)


@pytest.mark.slow
def test_apex_learns_catch(tmp_path):
    """The PIXEL path must learn end-to-end: conv trunk, device-side frame
    stacking from the frame-pool ring, chunked actor ingest.  CatchSmall
    max score is +3 (3 balls); an untrained greedy policy scores ~1.0 and
    random play ~-0.4; a learned catcher exceeds 2.  Scored over retained
    checkpoints (see test_apex_learns_cartpole for why)."""
    import dataclasses

    from apex_tpu.training.checkpoint import evaluate_checkpoint

    cfg = small_test_config(capacity=8192, batch_size=32, n_actors=3,
                            env_id="ApexCatchSmall-v0")
    cfg = cfg.replace(
        env=dataclasses.replace(cfg.env, frame_stack=2),
        actor=dataclasses.replace(cfg.actor, eps_anneal_steps=1500,
                                  eps_alpha=3.0),
        learner=dataclasses.replace(cfg.learner, gamma=0.97,
                                    target_update_interval=100,
                                    save_interval=500))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0,
                          min_train_ratio=1.0,
                          checkpoint_dir=str(tmp_path / "ck"))
    trainer.checkpointer.keep = 20
    trainer.train(total_steps=8000, max_seconds=900)

    scores = [trainer.evaluate(episodes=5, epsilon=0.0, max_steps=100)]
    for name in trainer.checkpointer._all():
        scores.append(evaluate_checkpoint(str(tmp_path / "ck" / name),
                                          episodes=5, max_steps=100))
    best = max(scores)
    assert best > 2.0, (f"best pixel policy scored {best} <= 2 over "
                        f"{len(scores)} eval points: conv path not "
                        f"learning (all: {[round(s, 1) for s in scores]})")


@pytest.mark.slow
def test_apex_learns_cartpole(tmp_path):
    """The concurrent pipeline must actually learn: some policy it produces
    clearly beats random play (~22/episode).  No retries — learning must be
    robust to actor/learner interleaving.

    Verified stabilizers (each failure mode reproduced without it):
    * gentler epsilon ladder + exploration anneal — the reference ladder
      (eps_alpha=7, batchrecorder.py:121) is tuned for ~200-actor fleets;
      with 3 actors two are near-greedy from step 0 and learning collapses;
    * gamma=0.97 — at 0.99 CartPole's Q ceiling (1/(1-gamma) = 100)
      saturates under extended training, erasing the action gap;
    * best-checkpoint scoring — end-point eval on CartPole DQN oscillates;
      the certificate is the best policy the run PRODUCED (scored through
      the framework's own checkpoint/enjoy path), which is also what the
      continuous evaluator role measures in deployment.
    """
    import dataclasses

    from apex_tpu.training.checkpoint import evaluate_checkpoint

    cfg = small_test_config(capacity=8192, batch_size=64, n_actors=3)
    cfg = cfg.replace(
        actor=dataclasses.replace(cfg.actor, eps_anneal_steps=1500,
                                  eps_alpha=3.0),
        learner=dataclasses.replace(cfg.learner, gamma=0.97,
                                    save_interval=500))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05,
                          train_ratio=8.0, min_train_ratio=1.0,
                          checkpoint_dir=str(tmp_path / "ck"))
    trainer.checkpointer.keep = 20
    # generous wall-clock ceiling: under CPU contention the step budget —
    # not the clock — must decide when training is done
    trainer.train(total_steps=8000, max_seconds=900)

    scores = [trainer.evaluate(episodes=3, epsilon=0.0, max_steps=500)]
    for name in trainer.checkpointer._all():
        path = str(tmp_path / "ck" / name)
        scores.append(evaluate_checkpoint(path, episodes=3, max_steps=500))
    best = max(scores)
    assert best > 60.0, (f"best policy over {len(scores)} eval points "
                         f"scored {best} <= 60: pipeline not learning "
                         f"(all: {[round(s, 1) for s in scores]})")


@pytest.mark.slow
def test_apex_learns_catch_medium(tmp_path):
    """Harder pixel certificate (ALE compensation, ROUND4_NOTES.md): the
    11x11 Catch at 44x44 has a 10-step credit horizon — ~2x CatchSmall's.
    Random play scores ~-1.8 (catch prob ~3/11 over 4 balls); a learned
    tracker clearly exceeds 0 (more catches than misses).  Scored over
    retained checkpoints like the other learning certificates."""
    import dataclasses

    from apex_tpu.training.checkpoint import evaluate_checkpoint

    cfg = small_test_config(capacity=8192, batch_size=32, n_actors=3,
                            env_id="ApexCatchMedium-v0")
    cfg = cfg.replace(
        env=dataclasses.replace(cfg.env, frame_stack=2),
        actor=dataclasses.replace(cfg.actor, eps_anneal_steps=2000,
                                  eps_alpha=3.0),
        learner=dataclasses.replace(cfg.learner, gamma=0.98,
                                    target_update_interval=150,
                                    save_interval=600))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0,
                          min_train_ratio=1.0,
                          checkpoint_dir=str(tmp_path / "ck"))
    trainer.checkpointer.keep = 20
    trainer.train(total_steps=9000, max_seconds=1200)

    scores = [trainer.evaluate(episodes=5, epsilon=0.0, max_steps=150)]
    for name in trainer.checkpointer._all():
        scores.append(evaluate_checkpoint(str(tmp_path / "ck" / name),
                                          episodes=5, max_steps=150))
    best = max(scores)
    assert best > 0.0, (f"best medium-Catch policy scored {best} <= 0 "
                        f"(random ~-1.8): 10-step pixel credit assignment "
                        f"not learned")
