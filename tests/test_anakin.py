"""On-device Anakin rollouts (training/anakin.py).

The load-bearing pin is CHUNK BIT-COMPATIBILITY: the fused scan's sealed
chunks must be byte-identical to what the host
:class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder` emits for the same
trajectory — same chunk boundaries, frame carry, refs, padding, priorities
— and must ingest into :class:`~apex_tpu.replay.frame_pool.FramePoolReplay`
to the same state.  The host side replays the engine's exact key chain
through the numpy builder (the jax envs stepped eagerly), so any drift in
the scan port's state machine shows up as an array mismatch.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from apex_tpu.actors.pool import (EpisodeStat,  # noqa: E402
                                  drain_builder_chunks)
from apex_tpu.config import (ActorConfig, ApexConfig,  # noqa: E402
                             EnvConfig, LearnerConfig, ReplayConfig)
from apex_tpu.envs.registry import make_jax_env  # noqa: E402
from apex_tpu.models.dueling import (DuelingDQN,  # noqa: E402
                                     make_policy_fn)
from apex_tpu.ops.losses import make_optimizer  # noqa: E402
from apex_tpu.replay.frame_chunks import FrameChunkBuilder  # noqa: E402
from apex_tpu.training import anakin  # noqa: E402
from apex_tpu.training.anakin import (AnakinPool,  # noqa: E402
                                      make_anakin_engine)
from apex_tpu.training.apex import ApexTrainer, dqn_env_specs  # noqa: E402
from apex_tpu.training.state import create_train_state  # noqa: E402

CHUNK_KEYS = ("frames", "n_frames", "n_trans", "action", "reward",
              "discount", "obs_ref", "next_ref")


def _cfg(env_id="ApexCatchSmall-v0", stack=2, n_envs=3, send=16):
    return ApexConfig(
        env=EnvConfig(env_id=env_id, frame_stack=stack,
                      clip_rewards=False, episodic_life=False),
        replay=ReplayConfig(capacity=1024, warmup=128),
        learner=LearnerConfig(batch_size=32, ingest_chunk=32,
                              compute_dtype="float32",
                              target_update_interval=100),
        actor=ActorConfig(n_actors=1, n_envs_per_actor=n_envs,
                          send_interval=send))


def _params(cfg):
    model_spec, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    model = DuelingDQN(**model_spec)
    stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
    ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                            np.zeros((1,) + stacked, frame_dtype))
    return model, model_spec, frame_shape, frame_dtype, ts.params


def _host_replay(cfg, engine, params, model, dispatches):
    """Replay the engine's exact key chain through the numpy builder:
    eager jax env steps + the standalone jitted policy feeding per-slot
    FrameChunkBuilders — the ground truth the scan port must match."""
    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    env = make_jax_env(cfg.env.env_id, cfg.env)
    policy = jax.jit(make_policy_fn(model))
    B, T = engine.B, engine.T
    builders = [FrameChunkBuilder(
        engine.n, cfg.learner.gamma, engine.S, frame_shape,
        chunk_transitions=engine.K, frame_dtype=frame_dtype)
        for _ in range(B)]
    # the engine consumed key(seed) -> (chain, init) at construction
    chain, init_key = jax.random.split(
        jax.random.key(cfg.env.seed + 1000))
    states, obs0 = jax.vmap(env.reset)(engine.reset_keys(init_key))
    obs0 = np.asarray(obs0)
    for b in range(B):
        builders[b].begin_episode(obs0[b])
    vstep = jax.jit(jax.vmap(lambda s, a, k: env.step(s, a, k)))
    eps = engine.epsilons
    per_dispatch, stats = [], []
    for _d in range(dispatches):
        chain, kd = jax.random.split(chain)
        for sk in jax.random.split(kd, T):
            stack = np.stack([bl.current_stack() for bl in builders])
            a, q = policy(params, stack, eps,
                          jax.random.fold_in(sk, anakin.T_POLICY))
            # apexlint: disable=J008 -- parity replay harness, not a hot loop: eager materialization keeps the ground-truth trace obvious
            a, q = np.asarray(a), np.asarray(q)
            # apexlint: disable=J004 -- replaying the engine's documented tag discipline: T_POLICY vs T_ENV folds are disjoint
            keys = jax.vmap(jax.random.fold_in, (None, 0))(
                jax.random.fold_in(sk, anakin.T_ENV),
                np.arange(B, dtype=np.uint32))
            states, obs, rew, done, ff = vstep(states, jnp.asarray(a),
                                               keys)
            obs, rew, done, ff = map(np.asarray, (obs, rew, done, ff))
            for b in range(B):
                builders[b].add_step(int(a[b]), float(rew[b]), q[b],
                                     ff[b], bool(done[b]), False)
                if done[b]:
                    stats.append((b, float(rew[b])))
                    builders[b].begin_episode(obs[b])
        host = []
        for b in range(B):
            host.extend(drain_builder_chunks(builders[b]))
        per_dispatch.append(host)
    return per_dispatch, stats


def test_chunk_bit_compat_with_host_builder():
    """Three dispatches (carry state survives dispatch boundaries): every
    sealed chunk byte-equals the host builder's, priorities included."""
    cfg = _cfg()
    model, _spec, _shape, _dtype, params = _params(cfg)
    engine = make_anakin_engine(cfg, rollout_len=40)
    host_stream, _ = _host_replay(cfg, engine, params, model,
                                  dispatches=3)
    compared = 0
    for host in host_stream:
        msgs, _stats = engine.rollout(params)
        assert len(host) == len(msgs)
        for h, e in zip(host, msgs):
            np.testing.assert_array_equal(h["priorities"],
                                          e["priorities"])
            assert h["n_trans"] == e["n_trans"]
            for k in CHUNK_KEYS:
                np.testing.assert_array_equal(
                    np.asarray(h["payload"][k]),
                    np.asarray(e["payload"][k]), err_msg=k)
            compared += 1
    assert compared >= 8       # several chunks incl. cross-dispatch carry


def test_chunk_ingest_parity_into_frame_pool():
    """The replay-path pin: on-device chunks ingested into FramePoolReplay
    produce the SAME state (frames ring, id tables, trees, cursors) as the
    host-built chunks — they flow into the existing path unchanged."""
    from apex_tpu.replay.frame_pool import FramePoolReplay

    cfg = _cfg(n_envs=2, send=16)
    model, _spec, frame_shape, frame_dtype, params = _params(cfg)
    engine = make_anakin_engine(cfg, rollout_len=48)
    host_stream, _ = _host_replay(cfg, engine, params, model,
                                  dispatches=1)
    msgs, _ = engine.rollout(params)
    host = host_stream[0]
    pool = FramePoolReplay(capacity=256, frame_shape=frame_shape,
                           frame_stack=engine.S,
                           frame_dtype=np.dtype(frame_dtype).name)
    add = jax.jit(pool.add)

    def ingest(stream):
        state = pool.init()
        for m in stream:
            state = add(state, jax.tree.map(jnp.asarray, m["payload"]),
                        jnp.asarray(m["priorities"]))
        return state

    sa, sb = ingest(host), ingest(msgs)
    for field in ("frames", "action", "reward", "discount", "obs_ids",
                  "next_ids", "frame_epoch", "sum_tree", "min_tree",
                  "pos", "f_epoch", "size", "max_priority"):
        np.testing.assert_array_equal(
            np.asarray(getattr(sa, field)), np.asarray(getattr(sb, field)),
            err_msg=field)


def test_engine_episode_stats_match_env():
    cfg = _cfg(n_envs=2)
    model, _spec, _shape, _dtype, params = _params(cfg)
    engine = make_anakin_engine(cfg, rollout_len=60)
    _, host_stats = _host_replay(cfg, engine, params, model, dispatches=1)
    _msgs, stats = engine.rollout(params)
    assert len(stats) == len(host_stats) and len(stats) >= 2
    assert all(isinstance(s, EpisodeStat) for s in stats)
    # CatchSmall: 3 balls of +-1 -> integer returns in [-3, 3], 18 steps
    assert all(abs(s.reward) <= 3 and s.length == 18 for s in stats)


def test_rally_engine_runs():
    cfg = _cfg(env_id="ApexRallySmall-v0", stack=2, n_envs=2)
    _model, _spec, _shape, _dtype, params = _params(cfg)
    engine = make_anakin_engine(cfg, rollout_len=32)
    msgs, _ = engine.rollout(params)
    msgs2, _ = engine.rollout(params)
    total = sum(m["n_trans"] for m in msgs + msgs2)
    assert total >= engine.B * 32        # every step eventually emits


def test_anakin_pool_trains_apex_trainer():
    """The co-located training mode end to end: AnakinPool as the
    trainer's chunk source — steps taken, transitions ingested, on-device
    counters live in fleet_summary, heartbeat peer visible."""
    cfg = _cfg(n_envs=4, send=32)
    pool = AnakinPool(cfg, make_anakin_engine(cfg))
    trainer = ApexTrainer(cfg, pool=pool, publish_min_seconds=0.2,
                          train_ratio=0.5)
    trainer.train(total_steps=6, max_seconds=90, log_every=10 ** 9)
    assert trainer.steps_rate.total >= 6
    assert trainer.ingested >= cfg.replay.warmup
    summary = trainer.fleet_summary()
    ond = summary["metrics"]["ondevice"]
    assert ond["chunks"] > 0 and ond["frames"] > 0
    assert ond["dispatches"] > 0 and ond["transitions"] > 0
    peers = {p["identity"]: p["role"] for p in summary["peers"]}
    assert peers.get("ondevice-0") == "rollout"


def test_anakin_pool_device_params_and_backpressure():
    cfg = _cfg(n_envs=2)
    pool = AnakinPool(cfg, make_anakin_engine(cfg, rollout_len=16))
    assert pool.accepts_device_params
    # no params yet: polling produces nothing (no dispatch without a
    # policy), so the replay-ratio gate pauses collection for free
    assert pool.poll_chunks(4) == []
    _model, _spec, _shape, _dtype, params = _params(cfg)
    pool.publish_params(1, params)
    got = pool.poll_chunks(1)
    assert len(got) == 1 and "payload" in got[0]
    # the dispatch produced one chunk per env slot: the second drains the
    # pending buffer WITHOUT a fresh dispatch
    d0 = pool.engine.dispatches
    rest = pool.poll_chunks(1)
    assert len(rest) == 1 and pool.engine.dispatches == d0
    stats = pool.poll_stats()
    assert any(getattr(s, "role", "") == "rollout" for s in stats)


def test_make_anakin_engine_guards():
    cfg = _cfg(env_id="ApexCartPole-v0", stack=1)
    with pytest.raises(ValueError, match="ApexCartPole-v0"):
        make_anakin_engine(cfg)


def test_loadgen_slot_bands_match_worker_slots():
    """A loadgen process's ladder band equals the host vector worker's for
    the same actor id — the fleet exploration spectrum is topology-
    independent."""
    from apex_tpu.actors.vector import worker_slots

    cfg = ApexConfig(
        env=EnvConfig(env_id="ApexCatchSmall-v0", frame_stack=2,
                      clip_rewards=False, episodic_life=False),
        actor=ActorConfig(n_actors=3, n_envs_per_actor=4))
    for band in range(3):
        eng = make_anakin_engine(cfg, n_envs=4, slot_band=band,
                                 total_slots=12)
        slot_ids, _seeds, eps = worker_slots(cfg, band)
        assert eng.slot_ids == slot_ids
        np.testing.assert_allclose(eng.epsilons,
                                   np.asarray(eps, np.float32))


def test_run_loadgen_ships_chunks_through_sender(monkeypatch):
    """Loadgen plumbing with the transport faked out: params arrive, the
    engine dispatches, chunks + heartbeats ship through the sender."""
    import threading

    from apex_tpu.config import RoleIdentity
    from apex_tpu.runtime import roles, transport

    cfg = _cfg(n_envs=2, send=16)
    _model, _spec, _shape, _dtype, params = _params(cfg)
    host_params = jax.device_get(params)

    class FakeSub:
        def __init__(self, comms):
            pass

        def wait_first(self, stop_event):
            return (1, host_params)

        def poll(self, ms):
            return None

        def close(self):
            pass

    sent = {"chunks": [], "stats": []}

    class FakeSender:
        chunks_sent = 0
        acks_received = 0

        def __init__(self, comms, name):
            pass

        def send_chunk(self, msg, stop_event, **kw):
            sent["chunks"].append(msg)
            return True

        def send_stat(self, stat):
            sent["stats"].append(stat)

        def close(self):
            pass

    monkeypatch.setattr(transport, "ParamSubscriber", FakeSub)
    monkeypatch.setattr(transport, "ChunkSender", FakeSender)
    stop = threading.Event()
    out = roles.run_loadgen(cfg, RoleIdentity(role="loadgen", actor_id=0,
                                              n_actors=1),
                            stop_event=stop, max_seconds=8.0,
                            rollout_len=24)
    assert out["dispatches"] >= 1 and out["chunks"] >= 1
    assert sent["chunks"] and all("payload" in m for m in sent["chunks"])
    assert out["frames"] == out["dispatches"] * 24 * 2


def test_outbox_overflow_bound_documented():
    """M sizing: transitions per dispatch <= leftover window + T + n, so
    seals can never exceed the sealed-slot budget for the toy envs; the
    host-side check would fire loudly rather than corrupt."""
    cfg = _cfg(n_envs=2, send=16)
    engine = make_anakin_engine(cfg, rollout_len=64)
    assert engine.M >= (64 + engine.n + engine.K - 1) // engine.K + 3 - 1
    _model, _spec, _shape, _dtype, params = _params(cfg)
    for _ in range(3):
        msgs, _ = engine.rollout(params)     # would raise on overflow
        assert all(m["n_trans"] >= 1 for m in msgs)
