"""Failure detection: actor death is noticed and the slot respawned.

The reference has NO death handling anywhere (SURVEY.md §5.3): a crashed
actor silently shrinks the fleet for the rest of the run.  Here the pool
reports dead workers and rebuilds them on the same ladder slot, and the
concurrent trainer does this continuously during training.
"""

import time

import numpy as np
import pytest

from apex_tpu.config import small_test_config
from apex_tpu.training.apex import ApexTrainer, dqn_model_spec


@pytest.mark.slow
def test_pool_detects_and_respawns_dead_worker():
    from apex_tpu.actors.pool import ActorPool

    cfg = small_test_config(capacity=512, batch_size=16, n_actors=2)
    pool = ActorPool(cfg, dqn_model_spec(cfg), chunk_transitions=16)
    pool.start()
    try:
        assert pool.dead_workers() == []
        pool.publish_params(1, _params(cfg))
        deadline = time.monotonic() + 60
        while not pool.poll_chunks(1) and time.monotonic() < deadline:
            time.sleep(0.05)

        victim = pool.procs[0]
        victim.terminate()
        victim.join(timeout=10)
        deadline = time.monotonic() + 10
        while pool.dead_workers() != [0] and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pool.dead_workers() == [0]

        pool.respawn_worker(0)
        assert pool.worker_deaths == 1
        assert pool.procs[0].is_alive()
        assert pool.procs[0] is not victim
        # the respawned slot produces data again (it got the re-queued
        # params immediately, no need to wait for the next publish)
        got = []
        deadline = time.monotonic() + 60
        while len(got) < 3 and time.monotonic() < deadline:
            got += pool.poll_chunks(4, timeout=0.2)
        assert len(got) >= 3, "fleet stopped producing after respawn"
        assert pool.dead_workers() == []
    finally:
        pool.cleanup()


def _crashing_worker(actor_id, cfg, model_spec, chunk_queue, param_queue,
                     stat_queue, stop_event, epsilon, chunk_transitions):
    raise RuntimeError("boom")      # deterministic startup crash


@pytest.mark.slow
def test_respawn_budget_stops_crash_loops():
    """A worker that dies on every start exhausts its respawn budget and
    drops out of dead_workers() — no infinite 5-second crash loop."""
    from apex_tpu.actors.pool import ActorPool

    cfg = small_test_config(n_actors=1)
    pool = ActorPool(cfg, {"num_actions": 2, "obs_is_image": False},
                     chunk_transitions=16, worker_fn=_crashing_worker)
    pool.max_respawns_per_slot = 2
    pool.start()
    try:
        respawns = 0
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            dead = pool.dead_workers()
            if not dead and not pool.procs[0].is_alive():
                break               # aged out of the respawn set
            for i in dead:
                assert pool.respawn_worker(i)
                respawns += 1
            time.sleep(0.1)
        assert respawns == 2
        assert pool.worker_deaths == 2
        assert pool.dead_workers() == []          # budget exhausted
        assert not pool.respawn_worker(0)         # and refuses directly
        # the budget is a RATE: surviving a full window restores it, so
        # sporadic crashes over a long run never permanently retire a slot
        pool.respawn_window_s = 0.05
        time.sleep(0.1)
        assert pool.dead_workers() == [0]
        assert pool.respawn_worker(0)
    finally:
        pool.cleanup(grace_seconds=1)


def _params(cfg):
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.apex import dqn_env_specs
    from apex_tpu.training.state import create_train_state

    model_spec, frame_shape, frame_dtype, _ = dqn_env_specs(cfg)
    ts = create_train_state(
        DuelingDQN(**model_spec), make_optimizer(), jax.random.key(0),
        np.zeros((1,) + frame_shape, frame_dtype))
    return jax.device_get(ts.params)


@pytest.mark.slow
def test_trainer_survives_worker_death():
    """Kill a worker mid-training: the trainer logs the respawn and the
    run completes its step budget with a full fleet."""
    import threading

    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=2)
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)

    def assassin():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if trainer.ingested > 0 and trainer.pool.procs[1].is_alive():
                trainer.pool.procs[1].terminate()
                return
            time.sleep(0.2)

    killer = threading.Thread(target=assassin, daemon=True)
    killer.start()
    trainer.train(total_steps=60, max_seconds=240)
    killer.join(timeout=1)

    assert trainer.steps_rate.total >= 60
    assert trainer.pool.worker_deaths >= 1, "death never detected"
    assert trainer.log.history.get("learner/worker_respawn"), \
        "respawn not logged"
