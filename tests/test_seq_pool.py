"""Frame-dedup SEQUENCE replay (apex_tpu/replay/seq_pool.py): stacked-vs-
pooled bit parity, the capacity win, padding/staleness invariants, and the
pooled pixel R2D2 driver mechanics."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.actors.r2d2 import (drain_grouped, pooled_sequence_message,
                                  sequence_message)
from apex_tpu.config import small_test_config
from apex_tpu.replay.device import DeviceReplay
from apex_tpu.replay.seq_pool import SequenceFramePoolReplay
from apex_tpu.training.r2d2 import SequenceBuilder

BURN, UNROLL, NSTEP = 2, 4, 1
T_TOTAL = BURN + UNROLL + NSTEP
H = 8           # lstm features
SHAPE = (6, 6, 1)


def _feed_episodes(builder: SequenceBuilder, rng, n_eps=5,
                   lengths=(9, 4, 15, 7, 12)):
    """Identical synthetic episodes into any builder."""
    for e in range(n_eps):
        n = lengths[e % len(lengths)]
        for t in range(n):
            builder.add_step(
                rng.integers(0, 255, SHAPE).astype(np.uint8),
                int(rng.integers(0, 3)), float(rng.normal()),
                terminated=(t == n - 1),
                carry_c=rng.normal(size=H).astype(np.float32),
                carry_h=rng.normal(size=H).astype(np.float32),
                q_values=rng.normal(size=3).astype(np.float32))
        builder.end_episode()


def _builders_pair(seed=0):
    """Two builders fed the SAME episode stream (same rng seed)."""
    out = []
    for pooled in (False, True):
        b = SequenceBuilder(BURN, UNROLL, NSTEP, gamma=0.9, stride=3,
                            pooled=pooled)
        _feed_episodes(b, np.random.default_rng(seed))
        out.append(b)
    return out


def test_pooled_message_parity_with_stacked():
    """The pooled message carries EXACTLY the stacked message's content:
    gathering frames[obs_ref] reproduces the stacked obs windows, all
    other leaves and the priorities/n_trans accounting are identical."""
    stacked_b, pooled_b = _builders_pair()
    group = 4
    stacked_msgs = drain_grouped(stacked_b.drain(), group)
    pooled_msgs = drain_grouped(pooled_b.drain(), group,
                                pooled_sequence_message)
    assert len(stacked_msgs) == len(pooled_msgs) > 0
    d = int(np.prod(SHAPE))
    for sm, pm in zip(stacked_msgs, pooled_msgs):
        np.testing.assert_array_equal(sm["priorities"], pm["priorities"])
        assert sm["n_trans"] == pm["n_trans"]
        sp, pp = sm["payload"], pm["payload"]
        for k in ("action", "reward", "discount", "mask",
                  "state_c", "state_h"):
            np.testing.assert_array_equal(sp[k], pp[k])
        # frame refs reconstruct the stacked windows bit-for-bit
        rebuilt = pp["frames"][pp["obs_ref"].reshape(-1)].reshape(
            group, T_TOTAL, *SHAPE)
        np.testing.assert_array_equal(rebuilt, sp["obs"])
        # row 0 is the shared zero pad frame; pad rows stay zero
        assert not pp["frames"][0].any()
        assert not pp["frames"][int(pp["n_frames"]):].any()


def test_pooled_message_dedups_overlap():
    """Overlapping windows (stride < t_total) share rows: the message
    ships FEWER frame rows than the stacked windows' total."""
    _, pooled_b = _builders_pair()
    msgs = drain_grouped(pooled_b.drain(), 4, pooled_sequence_message)
    for m in msgs:
        assert int(m["payload"]["n_frames"]) < 4 * T_TOTAL + 1


def _specs_pair(capacity=16):
    stacked = DeviceReplay(capacity=capacity)
    pooled = SequenceFramePoolReplay(
        capacity=capacity, t_total=T_TOTAL, lstm_features=H,
        frame_shape=SHAPE, frame_capacity=8 * capacity)
    example = dict(
        obs=jnp.zeros((T_TOTAL,) + SHAPE, jnp.uint8),
        action=jnp.zeros(T_TOTAL, jnp.int32),
        reward=jnp.zeros(T_TOTAL, jnp.float32),
        discount=jnp.zeros(T_TOTAL, jnp.float32),
        mask=jnp.zeros(T_TOTAL, jnp.float32),
        state_c=jnp.zeros(H, jnp.float32),
        state_h=jnp.zeros(H, jnp.float32))
    return stacked, stacked.init(example), pooled, pooled.init()


def test_pooled_sample_parity_with_stacked():
    """Same episode stream, same ingest order, same sampling key: the
    pooled layout returns the stacked layout's exact batch (obs included)
    and identical IS weights."""
    stacked_b, pooled_b = _builders_pair()
    group = 4
    s_spec, s_state, p_spec, p_state = _specs_pair()
    s_msgs = drain_grouped(stacked_b.drain(), group)
    p_msgs = drain_grouped(pooled_b.drain(), group,
                           pooled_sequence_message)
    for sm, pm in zip(s_msgs, p_msgs):
        s_state = s_spec.add(
            s_state, {k: jnp.asarray(v) for k, v in sm["payload"].items()},
            jnp.asarray(sm["priorities"]))
        p_state = p_spec.add(
            p_state, {k: jnp.asarray(v) for k, v in pm["payload"].items()},
            jnp.asarray(pm["priorities"]))

    key = jax.random.key(3)
    sb, sw, si = s_spec.sample(s_state, key, 8, 0.5)
    # apexlint: disable=J004 -- parity test: both layouts must sample with the identical key
    pb, pw, pi = p_spec.sample(p_state, key, 8, 0.5)
    np.testing.assert_array_equal(np.asarray(si), np.asarray(pi))
    for k in sb:
        np.testing.assert_array_equal(
            np.asarray(sb[k]), np.asarray(pb[k]), err_msg=k)
    np.testing.assert_allclose(np.asarray(sw), np.asarray(pw), rtol=1e-6)

    # priority write-back keeps the trees in lockstep too
    new_p = jnp.abs(jax.random.normal(jax.random.key(4), (8,))) + 0.1
    s_state = s_spec.update_priorities(s_state, si, new_p)
    p_state = p_spec.update_priorities(p_state, pi, new_p)
    np.testing.assert_allclose(np.asarray(s_state.sum_tree),
                               np.asarray(p_state.sum_tree), rtol=1e-6)


def test_padded_tail_gathers_zero_frames():
    """A short episode's padded positions (mask 0) must sample as all-zero
    frames — exactly what the stacked layout stores there."""
    b = SequenceBuilder(BURN, UNROLL, NSTEP, gamma=0.9, stride=3,
                        pooled=True)
    rng = np.random.default_rng(7)
    for t in range(BURN + 2):                 # shorter than t_total
        b.add_step(rng.integers(1, 255, SHAPE).astype(np.uint8),
                   0, 0.0, terminated=(t == BURN + 1),
                   carry_c=np.zeros(H, np.float32),
                   carry_h=np.zeros(H, np.float32))
    b.end_episode()
    seqs = b.drain()
    assert len(seqs) == 1
    msg = pooled_sequence_message(seqs)
    p_spec = SequenceFramePoolReplay(capacity=4, t_total=T_TOTAL,
                                     lstm_features=H, frame_shape=SHAPE,
                                     frame_capacity=64)
    state = p_spec.add(p_spec.init(),
                       {k: jnp.asarray(v)
                        for k, v in msg["payload"].items()},
                       jnp.asarray(msg["priorities"]))
    batch, _, _ = p_spec.sample(state, jax.random.key(0), 4, 0.4)
    obs = np.asarray(batch["obs"])
    mask = np.asarray(batch["mask"])
    n_real = BURN + 2
    assert (obs[:, :n_real] > 0).any()
    assert not obs[:, n_real:].any(), "padded positions must be zero"
    assert not mask[:, n_real:].any()


def test_frame_ring_wrap_and_staleness_redirect():
    """Ingesting far past frame_capacity: old sequences whose frames aged
    out redirect to the newest slot at sample time (graceful, never
    corrupt), and fresh sequences still reconstruct exactly."""
    p_spec = SequenceFramePoolReplay(capacity=8, t_total=T_TOTAL,
                                     lstm_features=H, frame_shape=SHAPE,
                                     frame_capacity=2 * T_TOTAL + 3)
    state = p_spec.init()
    rng = np.random.default_rng(1)
    b = SequenceBuilder(BURN, UNROLL, NSTEP, gamma=0.9, stride=3,
                        pooled=True)
    last_payload = None
    for e in range(6):
        for t in range(T_TOTAL):
            b.add_step(rng.integers(0, 255, SHAPE).astype(np.uint8),
                       0, 0.0, terminated=(t == T_TOTAL - 1),
                       carry_c=np.zeros(H, np.float32),
                       carry_h=np.zeros(H, np.float32))
        b.end_episode()
        for msg in drain_grouped(b.drain(), 2, pooled_sequence_message):
            last_payload = msg["payload"]
            state = p_spec.add(
                state,
                {k: jnp.asarray(v) for k, v in last_payload.items()},
                jnp.asarray(msg["priorities"]))
    batch, _, idx = p_spec.sample(state, jax.random.key(2), 16, 0.4)
    obs = np.asarray(batch["obs"])
    assert np.isfinite(np.asarray(batch["reward"])).all()
    # the newest slot's first real frame must appear verbatim for any
    # redirected row; every row decodes without corruption
    newest = int((state.pos - 1) % p_spec.capacity)
    ref = last_payload["frames"][last_payload["obs_ref"][-1, 0]]
    got = obs[np.asarray(idx) == newest]
    if got.size:
        np.testing.assert_array_equal(
            got[0, 0].reshape(-1), ref)


def test_capacity_win_vs_stacked():
    """The point of the layout: at a realistic R2D2 geometry the pooled
    spec stores the same number of live sequences in a fraction of the
    stacked HBM (the stacked layout repeats every frame ~t_total/stride
    times across overlapping windows)."""
    cap, t_total, lstm = 1024, 125, 512     # R2D2-paper-scale sequences
    stride, group = 40, 16
    per_seq = stride + -(-(t_total - stride + 1) // group)
    pooled = SequenceFramePoolReplay(
        capacity=cap, t_total=t_total, lstm_features=lstm,
        frame_shape=(84, 84, 1),
        frame_capacity=int(1.5 * cap * per_seq))
    stacked = DeviceReplay(capacity=cap)
    example = dict(
        obs=jnp.zeros((t_total, 84, 84, 1), jnp.uint8),
        action=jnp.zeros(t_total, jnp.int32),
        reward=jnp.zeros(t_total, jnp.float32),
        discount=jnp.zeros(t_total, jnp.float32),
        mask=jnp.zeros(t_total, jnp.float32),
        state_c=jnp.zeros(lstm, jnp.float32),
        state_h=jnp.zeros(lstm, jnp.float32))
    ratio = stacked.hbm_bytes(example) / pooled.hbm_bytes()
    assert ratio > 1.6, f"expected a >1.6x capacity win, got {ratio:.2f}x"


@pytest.mark.slow
def test_r2d2_pixel_pooled_driver_mechanics():
    """The pooled layout end to end in the single-process pixel driver:
    cfg.replay.frame_pool=True routes the recurrent family onto
    SequenceFramePoolReplay (builder, messages, fused ingest, sampling,
    eval) — a few training steps prove the plumbing."""
    from apex_tpu.training.r2d2 import R2D2Trainer

    cfg = small_test_config(capacity=256, batch_size=8,
                            env_id="ApexCatchSmall-v0")
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 frame_pool=True))
    t = R2D2Trainer(cfg)
    assert t.pooled and isinstance(t.replay, SequenceFramePoolReplay)
    t.train(total_frames=700, log_every=10 ** 9, warmup_sequences=8)
    assert t.steps_rate.total > 0
    assert t.sequences >= 8
    assert np.isfinite(t.evaluate(episodes=1, max_steps=30))


@pytest.mark.slow
def test_r2d2_pooled_checkpoint_roundtrip(tmp_path):
    """Full-bundle checkpoints cover the pooled state too: params, ring,
    id tables, trees, cursors and the transition counter all restore
    bit-exactly, and the restored trainer keeps training."""
    from apex_tpu.training.r2d2 import R2D2Trainer

    cfg = small_test_config(capacity=256, batch_size=8,
                            env_id="ApexCatchSmall-v0")
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 frame_pool=True))
    t = R2D2Trainer(cfg, checkpoint_dir=str(tmp_path))
    t.train(total_frames=500, log_every=10 ** 9, warmup_sequences=8)
    t.save_checkpoint()

    t2 = R2D2Trainer(cfg, checkpoint_dir=str(tmp_path))
    t2.restore()
    assert t2.pooled
    assert t2.steps_rate.total == t.steps_rate.total
    assert t2.transitions == t.transitions
    for a, b in zip(jax.tree.leaves(t.replay_state),
                    jax.tree.leaves(t2.replay_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t2.train(total_frames=120, log_every=10 ** 9, warmup_sequences=8)
    assert t2.frames_rate.total == t.frames_rate.total + 120


@pytest.mark.slow
def test_r2d2_apex_pooled_concurrent_mechanics():
    """Concurrent pooled R2D2: worker processes build POOLED sequence
    messages (the shared frame-pool predicate picks the layout on both
    sides) and the learner ingests them through the fused step."""
    from apex_tpu.training.r2d2 import R2D2ApexTrainer

    cfg = small_test_config(capacity=512, batch_size=8, n_actors=1,
                            env_id="ApexCatchSmall-v0")
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 frame_pool=True))
    t = R2D2ApexTrainer(cfg, publish_min_seconds=0.05)
    assert isinstance(t.replay, SequenceFramePoolReplay)
    t.train(total_steps=10, max_seconds=240)
    assert t.steps_rate.total >= 10
    assert all(not p.is_alive() for p in t.pool.procs)
