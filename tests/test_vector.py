"""Vectorized actors: B envs per process behind one batched policy call."""

import dataclasses

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import actor_epsilons
from apex_tpu.actors.vector import VectorDQNWorkerFamily
from apex_tpu.config import small_test_config
from apex_tpu.training.apex import ApexTrainer, dqn_env_specs


def _family(n_envs=3, chunk_transitions=16, env_id="ApexCartPole-v0"):
    cfg = small_test_config(env_id=env_id)
    model_spec, *_ = dqn_env_specs(cfg)
    ladder = actor_epsilons(n_envs)
    fam = VectorDQNWorkerFamily(
        cfg, model_spec, seeds=[100 + i for i in range(n_envs)],
        slot_ids=list(range(n_envs)), epsilons=ladder,
        chunk_transitions=chunk_transitions)
    return cfg, fam


def test_vector_family_contract():
    """B slots step under one batched forward; chunks keep the frame-chunk
    schema, transition counts add up across slots, episode stats carry the
    global slot id."""
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.training.state import create_train_state
    from apex_tpu.ops.losses import make_optimizer

    cfg, fam = _family(n_envs=3, chunk_transitions=16)
    model_spec, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    model = DuelingDQN(**model_spec)
    ts = create_train_state(
        model, make_optimizer(), jax.random.key(0),
        np.zeros((1,) + frame_shape, frame_dtype))

    fam.reset_all()
    key = jax.random.key(1)
    stats, msgs = [], []
    n_steps = 120
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        stats.extend(fam.step_all(ts.params, k))
        msgs.extend(fam.poll_msgs())
    msgs.extend(m for b in fam.builders
                for m in ({"payload": c, "priorities": c.pop("priorities"),
                           "n_trans": int(c["n_trans"])}
                          for c in b.force_flush()))
    fam.close()

    # every env step becomes exactly one transition once windows flush
    # (CartPole episodes end fast at high epsilon, flushing the tails); the
    # only transitions still unaccounted sit in <=B open n-step windows
    total_trans = sum(m["n_trans"] for m in msgs)
    pending = n_steps * fam.n_envs - total_trans
    assert 0 <= pending <= fam.n_envs * cfg.learner.n_steps

    for m in msgs:
        p = m["payload"]
        k = p["action"].shape[0]
        assert p["obs_ref"].shape == (k, frame_stack)
        assert p["frames"].dtype == np.dtype(frame_dtype)
        assert m["priorities"].shape == (k,)
        assert (m["priorities"][:m["n_trans"]] > 0).all()

    assert stats, "no episodes finished in 120 steps x 3 high-eps slots"
    assert {s.actor_id for s in stats} <= {0, 1, 2}


def test_vector_epsilons_span_global_ladder():
    """8 processes x 32 envs must reproduce the exploration spectrum of 256
    scalar actors: worker i owns ladder slots [i*B, (i+1)*B), with the
    Ape-X formula eps_base^(1 + slot/(N-1) * eps_alpha) evaluated on the
    GLOBAL slot index (batchrecorder.py:121), and the scalar workers'
    per-slot seeds."""
    from apex_tpu.actors.vector import worker_slots

    cfg = small_test_config()
    cfg = cfg.replace(actor=dataclasses.replace(
        cfg.actor, n_actors=8, n_envs_per_actor=32))
    all_slots, all_eps = [], []
    for worker in range(8):
        slot_ids, seeds, eps = worker_slots(cfg, worker)
        assert slot_ids == list(range(worker * 32, (worker + 1) * 32))
        assert seeds == [cfg.env.seed + 1000 * (s + 1) for s in slot_ids]
        # independent formula, not the actor_epsilons implementation
        want = [0.4 ** (1 + s / 255 * 7.0) for s in slot_ids]
        np.testing.assert_allclose(eps, want, rtol=1e-12)
        all_slots += slot_ids
        all_eps += list(eps)
    assert all_slots == list(range(256))
    assert (np.diff(all_eps) < 0).all()   # monotone across the whole fleet


@pytest.mark.slow
def test_apex_trainer_with_vector_actors():
    """End-to-end: ApexTrainer drives vector workers (1 process x 4 envs)
    through the same queues, warms up, trains, and shuts down cleanly."""
    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=1)
    cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                n_envs_per_actor=4))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    trainer.train(total_steps=40, max_seconds=180)

    assert trainer.steps_rate.total >= 40
    assert trainer.ingested >= cfg.replay.warmup
    slot_ids = [v for _, v in trainer.log.history.get("learner/actor_id", [])]
    assert slot_ids, "no episode stats from vector workers"
    assert max(slot_ids) > 0, "stats never arrived from slots beyond 0"
    assert all(not p.is_alive() for p in trainer.pool.procs)
