"""Vectorized actors: B envs per process behind one batched policy call."""

import dataclasses

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import actor_epsilons
from apex_tpu.actors.vector import VectorDQNWorkerFamily
from apex_tpu.config import small_test_config
from apex_tpu.training.apex import ApexTrainer, dqn_env_specs


def _family(n_envs=3, chunk_transitions=16, env_id="ApexCartPole-v0"):
    cfg = small_test_config(env_id=env_id)
    model_spec, *_ = dqn_env_specs(cfg)
    ladder = actor_epsilons(n_envs)
    fam = VectorDQNWorkerFamily(
        cfg, model_spec, seeds=[100 + i for i in range(n_envs)],
        slot_ids=list(range(n_envs)), epsilons=ladder,
        chunk_transitions=chunk_transitions)
    return cfg, fam


def test_vector_family_contract():
    """B slots step under one batched forward; chunks keep the frame-chunk
    schema, transition counts add up across slots, episode stats carry the
    global slot id."""
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.training.state import create_train_state
    from apex_tpu.ops.losses import make_optimizer

    cfg, fam = _family(n_envs=3, chunk_transitions=16)
    model_spec, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    model = DuelingDQN(**model_spec)
    ts = create_train_state(
        model, make_optimizer(), jax.random.key(0),
        np.zeros((1,) + frame_shape, frame_dtype))

    fam.reset_all()
    key = jax.random.key(1)
    stats, msgs = [], []
    n_steps = 120
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        stats.extend(fam.step_all(ts.params, k))
        msgs.extend(fam.poll_msgs())
    msgs.extend(m for b in fam.builders
                for m in ({"payload": c, "priorities": c.pop("priorities"),
                           "n_trans": int(c["n_trans"])}
                          for c in b.force_flush()))
    fam.close()

    # every env step becomes exactly one transition once windows flush
    # (CartPole episodes end fast at high epsilon, flushing the tails); the
    # only transitions still unaccounted sit in <=B open n-step windows
    total_trans = sum(m["n_trans"] for m in msgs)
    pending = n_steps * fam.n_envs - total_trans
    assert 0 <= pending <= fam.n_envs * cfg.learner.n_steps

    for m in msgs:
        p = m["payload"]
        k = p["action"].shape[0]
        assert p["obs_ref"].shape == (k, frame_stack)
        assert p["frames"].dtype == np.dtype(frame_dtype)
        assert m["priorities"].shape == (k,)
        assert (m["priorities"][:m["n_trans"]] > 0).all()

    assert stats, "no episodes finished in 120 steps x 3 high-eps slots"
    assert {s.actor_id for s in stats} <= {0, 1, 2}


def test_vector_epsilons_span_global_ladder():
    """8 processes x 32 envs must reproduce the exploration spectrum of 256
    scalar actors: worker i owns ladder slots [i*B, (i+1)*B), with the
    Ape-X formula eps_base^(1 + slot/(N-1) * eps_alpha) evaluated on the
    GLOBAL slot index (batchrecorder.py:121), and the scalar workers'
    per-slot seeds."""
    from apex_tpu.actors.vector import worker_slots

    cfg = small_test_config()
    cfg = cfg.replace(actor=dataclasses.replace(
        cfg.actor, n_actors=8, n_envs_per_actor=32))
    all_slots, all_eps = [], []
    for worker in range(8):
        slot_ids, seeds, eps = worker_slots(cfg, worker)
        assert slot_ids == list(range(worker * 32, (worker + 1) * 32))
        assert seeds == [cfg.env.seed + 1000 * (s + 1) for s in slot_ids]
        # independent formula, not the actor_epsilons implementation
        want = [0.4 ** (1 + s / 255 * 7.0) for s in slot_ids]
        np.testing.assert_allclose(eps, want, rtol=1e-12)
        all_slots += slot_ids
        all_eps += list(eps)
    assert all_slots == list(range(256))
    assert (np.diff(all_eps) < 0).all()   # monotone across the whole fleet


def _chunk_msgs_equal(a: list[dict], b: list[dict]) -> None:
    assert len(a) == len(b)
    for ma, mb in zip(a, b):
        assert ma["n_trans"] == mb["n_trans"]
        np.testing.assert_array_equal(ma["priorities"], mb["priorities"])
        pa, pb = ma["payload"], mb["payload"]
        assert set(pa) == set(pb)
        for k in pa:
            np.testing.assert_array_equal(np.asarray(pa[k]),
                                          np.asarray(pb[k]),
                                          err_msg=f"payload[{k}] diverged")


def _drive(fam, params, n_steps, seed=1):
    """Fixed key chain through n_steps vector steps; returns
    (stats, chunk messages incl. flush)."""
    fam.reset_all()
    key = jax.random.key(seed)
    stats, msgs = [], []
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        stats.extend(fam.step_all(params, k))
        msgs.extend(fam.poll_msgs())
    msgs.extend(m for b in fam.builders
                for m in ({"payload": c, "priorities": c.pop("priorities"),
                           "n_trans": int(c["n_trans"])}
                          for c in b.force_flush()))
    fam.close()
    return stats, msgs


@pytest.mark.parametrize("n_envs", [2, 5])
def test_double_buffer_bit_parity_with_serial(n_envs):
    """The tentpole acceptance pin: double-buffered and serial vector
    acting are BIT-IDENTICAL per slot — same actions, same chunks, same
    priorities — because both modes run the policy per half-group with
    fold_in(step_key, group) subkeys; only the dispatch/step interleaving
    differs.  Odd n_envs exercises uneven groups."""
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.state import create_train_state

    runs = {}
    for db in (True, False):
        cfg = small_test_config()
        cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                    double_buffer=db))
        model_spec, frame_shape, frame_dtype, _ = dqn_env_specs(cfg)
        ladder = actor_epsilons(n_envs)
        fam = VectorDQNWorkerFamily(
            cfg, model_spec, seeds=[100 + i for i in range(n_envs)],
            slot_ids=list(range(n_envs)), epsilons=ladder,
            chunk_transitions=16)
        assert fam.double_buffer == db
        assert len(fam.groups) == 2
        model = DuelingDQN(**model_spec)
        ts = create_train_state(
            model, make_optimizer(), jax.random.key(0),
            np.zeros((1,) + frame_shape, frame_dtype))
        runs[db] = _drive(fam, ts.params, 120)

    stats_db, msgs_db = runs[True]
    stats_serial, msgs_serial = runs[False]
    assert [(s.actor_id, s.reward, s.length) for s in stats_db] \
        == [(s.actor_id, s.reward, s.length) for s in stats_serial]
    assert stats_db, "no episodes ended: the pin never exercised resets"
    _chunk_msgs_equal(msgs_db, msgs_serial)


def test_scalar_fleet_and_vector_worker_slot_parity():
    """The worker_slots contract: a fleet of scalar workers on the same
    global slots and one vector worker produce IDENTICAL per-slot epsilon
    ladders and identical chunk-message shapes through
    drain_builder_chunks (same schema, same K/ref/frame geometry)."""
    from apex_tpu.actors.pool import DQNWorkerFamily, drain_builder_chunks
    from apex_tpu.actors.vector import worker_slots

    b = 3
    cfg = small_test_config()
    cfg = cfg.replace(actor=dataclasses.replace(
        cfg.actor, n_actors=2, n_envs_per_actor=b))
    model_spec, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    slot_ids, seeds, eps = worker_slots(cfg, actor_id=0)

    # identical epsilon ladder: the vector worker's slots ARE the scalar
    # fleet's global ladder entries (and scalar seeds match slot seeds)
    total = cfg.actor.n_actors * b
    ladder = actor_epsilons(total, cfg.actor.eps_base, cfg.actor.eps_alpha)
    np.testing.assert_array_equal(eps, ladder[slot_ids])
    assert seeds == [cfg.env.seed + 1000 * (s + 1) for s in slot_ids]

    vec = VectorDQNWorkerFamily(cfg, model_spec, seeds=seeds,
                                slot_ids=slot_ids, epsilons=eps,
                                chunk_transitions=16)
    scalars = [DQNWorkerFamily(cfg, model_spec, seed=seeds[i],
                               chunk_transitions=16) for i in range(b)]

    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.state import create_train_state
    model = DuelingDQN(**model_spec)
    ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                            np.zeros((1,) + frame_shape, frame_dtype))

    _, vec_msgs = _drive(vec, ts.params, 100)

    scalar_msgs = []
    for i, fam in enumerate(scalars):
        key = jax.random.key(1000 + i)
        obs, _ = fam.env.reset(seed=fam.seed)
        fam.begin_episode(obs)
        for _ in range(100):
            key, k = jax.random.split(key)
            obs, _r, term, trunc = fam.step(ts.params, obs,
                                            float(eps[i]), k)
            scalar_msgs.extend(fam.poll_msgs())
            if term or trunc:
                obs, _ = fam.env.reset()
                fam.begin_episode(obs)
        scalar_msgs.extend(
            {"payload": c, "priorities": c.pop("priorities"),
             "n_trans": int(c["n_trans"])}
            for c in fam.builder.force_flush())
        fam.env.close()

    assert vec_msgs and scalar_msgs
    ref = scalar_msgs[0]["payload"]
    for msg in vec_msgs + scalar_msgs:
        p = msg["payload"]
        assert set(p) == set(ref), "chunk-message schema diverged"
        for k in ref:
            assert p[k].shape == ref[k].shape, f"{k} shape diverged"
            assert p[k].dtype == ref[k].dtype, f"{k} dtype diverged"
        assert msg["priorities"].shape == (16,)


def test_vector_slot_arity_value_error():
    """The slot-arity guard survives `python -O` and names the config
    knobs that derive the three lists."""
    cfg = small_test_config()
    model_spec, *_ = dqn_env_specs(cfg)
    with pytest.raises(ValueError, match="n_envs_per_actor"):
        VectorDQNWorkerFamily(cfg, model_spec, seeds=[1, 2, 3],
                              slot_ids=[0, 1], epsilons=[0.4, 0.3, 0.2],
                              chunk_transitions=16)


def test_vector_worker_loop_counts_dropped_stats_and_emits_timing():
    """A full stat queue no longer loses episode stats SILENTLY: the next
    successful put carries the number dropped since the last success.  The
    loop also emits a periodic ActorTimingStat with the policy-wait /
    env-step / drain split."""
    import queue
    import threading

    from apex_tpu.actors.pool import ActorTimingStat, EpisodeStat
    from apex_tpu.actors.vector import vector_worker_loop
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.state import create_train_state

    cfg = small_test_config()
    cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                timing_interval=8))
    model_spec, frame_shape, frame_dtype, _ = dqn_env_specs(cfg)
    n_envs = 3
    fam = VectorDQNWorkerFamily(
        cfg, model_spec, seeds=[100 + i for i in range(n_envs)],
        slot_ids=list(range(n_envs)), epsilons=actor_epsilons(n_envs),
        chunk_transitions=16)
    model = DuelingDQN(**model_spec)
    ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                            np.zeros((1,) + frame_shape, frame_dtype))

    chunk_queue: queue.Queue = queue.Queue()
    param_queue: queue.Queue = queue.Queue()
    stat_queue: queue.Queue = queue.Queue(maxsize=1)   # force drops
    stop = threading.Event()
    param_queue.put((1, ts.params))
    t = threading.Thread(target=vector_worker_loop,
                         args=(0, cfg, fam, chunk_queue, param_queue,
                               stat_queue, stop), daemon=True)
    t.start()

    import time
    stats = []
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        try:
            stats.append(stat_queue.get(timeout=0.5))
        except queue.Empty:
            continue
        if (any(s.dropped_stats > 0 for s in stats)
                and any(isinstance(s, ActorTimingStat) for s in stats)):
            break
    stop.set()
    t.join(timeout=30)
    assert not t.is_alive()

    assert any(isinstance(s, EpisodeStat) and s.dropped_stats > 0
               for s in stats), "drops never surfaced on a carried stat"
    timing = [s for s in stats if isinstance(s, ActorTimingStat)]
    assert timing, "no periodic ActorTimingStat arrived"
    ts0 = timing[0]
    assert ts0.vector_steps == 8 and ts0.frames_per_sec > 0
    assert ts0.double_buffer
    for frac in (ts0.policy_wait_frac, ts0.env_step_frac, ts0.drain_frac):
        assert 0.0 <= frac <= 1.0
    assert ts0.policy_wait_frac + ts0.env_step_frac + ts0.drain_frac <= 1.0


def test_trainer_drains_actor_timing_stats_and_aggregates():
    """The learner's stats drain dispatches on type: ActorTimingStat lands
    in trainer.actor_timing (+ scalar logs), EpisodeStat keeps its episode
    semantics, and both contribute their carried drop counts; actor_plane()
    aggregates across workers for the e2e bench."""
    from apex_tpu.actors.pool import ActorTimingStat, EpisodeStat
    from apex_tpu.training.apex import ApexTrainer

    class OneShotPool:
        procs: list = []

        def __init__(self, stats):
            self._stats = list(stats)

        def start(self):
            pass

        def cleanup(self):
            pass

        def publish_params(self, version, params):
            pass

        def poll_chunks(self, max_chunks, timeout=0.0):
            return []

        def poll_stats(self):
            out, self._stats = self._stats, []
            return out

    stats = [
        ActorTimingStat(actor_id=0, frames_per_sec=100.0,
                        policy_wait_frac=0.5, env_step_frac=0.3,
                        drain_frac=0.1, dispatch_gap_ms_p50=2.5,
                        vector_steps=256, double_buffer=True,
                        dropped_stats=3),
        ActorTimingStat(actor_id=1, frames_per_sec=50.0,
                        policy_wait_frac=0.3, env_step_frac=0.5,
                        drain_frac=0.1, dispatch_gap_ms_p50=1.5,
                        vector_steps=256, double_buffer=True),
        EpisodeStat(2, 1.0, 5, dropped_stats=2),
    ]
    trainer = ApexTrainer(small_test_config(), pool=OneShotPool(stats),
                          respawn_workers=False)
    assert trainer.actor_plane() is None     # nothing reported yet
    trainer.train(total_steps=1, max_seconds=1.0, log_every=10 ** 9)

    assert set(trainer.actor_timing) == {0, 1}
    assert trainer.stat_drops == 5
    ap = trainer.actor_plane()
    assert ap["workers_reporting"] == 2
    assert ap["double_buffer"] is True
    assert ap["frames_per_sec_sum"] == 150.0
    assert ap["policy_wait_frac"] == pytest.approx(0.4)
    assert ap["stat_drops"] == 5
    # episode stats kept their channel
    rewards = [v for _, v in trainer.log.history.get(
        "learner/episode_reward", [])]
    assert rewards == [1.0]


@pytest.mark.slow
def test_apex_trainer_with_vector_actors():
    """End-to-end: ApexTrainer drives vector workers (1 process x 4 envs)
    through the same queues, warms up, trains, and shuts down cleanly."""
    cfg = small_test_config(capacity=1024, batch_size=32, n_actors=1)
    cfg = cfg.replace(actor=dataclasses.replace(cfg.actor,
                                                n_envs_per_actor=4))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.05)
    trainer.train(total_steps=40, max_seconds=180)

    assert trainer.steps_rate.total >= 40
    assert trainer.ingested >= cfg.replay.warmup
    slot_ids = [v for _, v in trainer.log.history.get("learner/actor_id", [])]
    assert slot_ids, "no episode stats from vector workers"
    assert max(slot_ids) > 0, "stats never arrived from slots beyond 0"
    assert all(not p.is_alive() for p in trainer.pool.procs)
